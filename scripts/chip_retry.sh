#!/bin/bash
# Retry loop around scripts/chip_session.py: the shared chip's claim can
# stay blocked for hours with brief free windows, so keep knocking until
# the round's chip-bound artifacts are complete (the session script is
# stage- and round-resumable, so partial windows still bank progress).
#
# Completeness is delegated to `chip_session.py --check`, which applies
# the session's OWN definition (current candidate sets, row-validity
# rules, retired lane sizes) without importing jax — so the loop cannot
# terminate on a stale artifact or spin on a permanently-failing size.
#
# Usage: chip_retry.sh [max_attempts] [attempt_timeout_s] [sleep_s]
set -u
cd "$(dirname "$0")/.."
MAX=${1:-60}
BUDGET=${2:-900}
NAP=${3:-300}

for i in $(seq 1 "$MAX"); do
  if python scripts/chip_session.py --check; then
    echo "[chip_retry] artifacts complete after $((i - 1)) attempts"
    exit 0
  fi
  echo "[chip_retry] attempt $i/$MAX (budget ${BUDGET}s)"
  timeout "$BUDGET" python scripts/chip_session.py
  echo "[chip_retry] attempt $i exited rc=$?"
  sleep "$NAP"
done
if python scripts/chip_session.py --check; then
  echo "[chip_retry] artifacts complete"
  exit 0
fi
echo "[chip_retry] gave up after $MAX attempts"
exit 1
