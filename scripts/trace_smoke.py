#!/usr/bin/env python
"""CI trace smoke: run a short emu-backend allreduce with ACCL_TRACE
on, assert the dumped Perfetto JSON parses and contains >= 1 span per
rank with the required trace_event keys, and land the dump_metrics
JSON next to it as a build artifact (see .github/workflows/
build-and-test.yml perf-gate job).

Usage: python scripts/trace_smoke.py [--ranks N] [--trace PATH]
       [--metrics PATH]
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=4)
    ap.add_argument("--trace", default="trace_smoke.json")
    ap.add_argument("--metrics", default="metrics_smoke.json")
    ap.add_argument("--count", type=int, default=256)
    args = ap.parse_args()

    # arm tracing exactly as a user would (env var), before any accl
    # use; the engine telemetry sampler rides along so the metrics
    # artifact carries the engine/* families perf_doctor renders (r14)
    os.environ["ACCL_TRACE"] = args.trace
    os.environ.setdefault("ACCL_TELEMETRY_INTERVAL_MS", "100")

    import numpy as np

    from accl_tpu import ReduceFunction
    from accl_tpu.backends.emu import EmuWorld
    from accl_tpu.observability import metrics as obs_metrics
    from accl_tpu.observability import trace as obs_trace

    assert obs_trace.enabled(), "ACCL_TRACE did not enable tracing"

    with EmuWorld(args.ranks) as world:
        def body(accl, rank):
            send = accl.create_buffer_like(
                np.arange(args.count, dtype=np.float32) + rank)
            recv = accl.create_buffer(args.count, np.float32)
            accl.allreduce(send, recv, args.count, ReduceFunction.SUM)
            return recv.host.copy()

        outs = world.run(body)
        if world.telemetry is not None:
            world.telemetry.sample()  # land one engine/* snapshot
    expected = np.sum([np.arange(args.count, dtype=np.float32) + r
                       for r in range(args.ranks)], axis=0)
    for got in outs:
        np.testing.assert_allclose(got, expected)

    path = obs_trace.collector().dump(args.trace)
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    slices = [ev for ev in events if ev.get("ph") == "X"]
    for ev in events:
        missing = [k for k in ("ph", "ts", "pid", "tid") if k not in ev]
        if missing:
            print(f"FAIL: event missing keys {missing}: {ev}")
            return 1
    per_rank = {r: sum(1 for ev in slices if ev["pid"] == r)
                for r in range(args.ranks)}
    if any(n < 1 for n in per_rank.values()):
        print(f"FAIL: ranks without spans: {per_rank}")
        return 1
    gangs = {(ev.get("args") or {}).get("gang_id") for ev in slices}
    gangs.discard(None)
    if not gangs:
        print("FAIL: no gang ids in trace")
        return 1

    with open(args.metrics, "w") as f:
        f.write(obs_metrics.dump_metrics(as_json=True))
    snap = obs_metrics.default_registry().snapshot()
    if not any(v["collective"] == "allreduce" and v["calls"] >= args.ranks
               for v in snap["calls"].values()):
        print(f"FAIL: metrics registry missing the allreduce rows: "
              f"{list(snap['calls'])}")
        return 1

    print(f"OK: {len(slices)} slices over {args.ranks} ranks "
          f"({per_rank}), {len(gangs)} gang(s); trace={path} "
          f"metrics={args.metrics}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
