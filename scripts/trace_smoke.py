#!/usr/bin/env python
"""CI trace smoke: run a short emu-backend allreduce with ACCL_TRACE
on, assert the dumped Perfetto JSON parses and contains >= 1 span per
rank with the required trace_event keys (and NO duplicated
thread_name/process_name metadata per (pid, tid) — the r15 merge-dedup
contract), and land the dump_metrics JSON next to it as a build
artifact (see .github/workflows/build-and-test.yml perf-gate job).

With ``ACCL_DEVICE_TRACE`` set (the CI perf-gate passes 1) the smoke
additionally runs a 4-virtual-rank ring allreduce through the Pallas
kernels on the tpu-interpret rung and schema-validates the per-rank
``device:*`` stamp tracks in the same Perfetto doc.  On a jax too old
to interpret remote DMAs the device rung self-skips with a note (the
same skew that parks the pallas test files locally).

Usage: python scripts/trace_smoke.py [--ranks N] [--trace PATH]
       [--metrics PATH]
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def check_no_duplicate_metadata(events) -> list:
    """The r15 schema rule: one thread_name/process_name declaration
    per (event, pid, tid) — duplicates are exactly what the
    merge_trace_files dedup exists to prevent."""
    seen = set()
    dups = []
    for ev in events:
        if ev.get("ph") != "M":
            continue
        key = (ev.get("name"), ev.get("pid"), ev.get("tid"))
        if key in seen:
            dups.append(key)
        seen.add(key)
    return dups


def run_device_trace_rung(ranks: int) -> bool:
    """The tpu-interpret device rung: a segmented ring allreduce whose
    kernels carry the ACCL_DEVICE_TRACE stamp rows.  Returns True when
    the rung ran (False = jax-skew self-skip)."""
    import numpy as np

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    import accl_tpu.ops.ring as ring
    from accl_tpu.parallel import make_mesh

    if len(jax.devices()) < ranks:
        print(f"note: device rung needs {ranks} devices (set XLA_FLAGS="
              f"--xla_force_host_platform_device_count={ranks}); skipped")
        return False
    mesh = make_mesh(dp=ranks)

    def body(xb):
        return ring.ring_all_reduce_segmented(
            xb[0], "dp", seg_elems=64, interpret=True)[None]

    try:
        f = shard_map(body, mesh=mesh, in_specs=P("dp", None),
                      out_specs=P("dp", None), check_vma=False)
    except TypeError:  # older shard_map spells the flag check_rep
        f = shard_map(body, mesh=mesh, in_specs=P("dp", None),
                      out_specs=P("dp", None), check_rep=False)
    x = np.stack([np.arange(256, dtype=np.float32) + r
                  for r in range(ranks)])
    xs = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
    try:
        out = np.asarray(jax.jit(f)(xs))
    except NotImplementedError as e:
        print(f"note: tpu-interpret rung self-skipped (jax-skew: {e})")
        return False
    np.testing.assert_allclose(out[0], x.sum(axis=0))
    return True


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=4)
    ap.add_argument("--trace", default="trace_smoke.json")
    ap.add_argument("--metrics", default="metrics_smoke.json")
    ap.add_argument("--count", type=int, default=256)
    args = ap.parse_args()

    # arm tracing exactly as a user would (env var), before any accl
    # use; the engine telemetry sampler rides along so the metrics
    # artifact carries the engine/* + link/* families perf_doctor
    # renders (r14/r15)
    os.environ["ACCL_TRACE"] = args.trace
    os.environ.setdefault("ACCL_TELEMETRY_INTERVAL_MS", "100")
    devtrace = os.environ.get("ACCL_DEVICE_TRACE", "0") not in ("", "0")

    import numpy as np

    from accl_tpu import ReduceFunction
    from accl_tpu.backends.emu import EmuWorld
    from accl_tpu.observability import metrics as obs_metrics
    from accl_tpu.observability import trace as obs_trace

    assert obs_trace.enabled(), "ACCL_TRACE did not enable tracing"

    with EmuWorld(args.ranks) as world:
        def body(accl, rank):
            send = accl.create_buffer_like(
                np.arange(args.count, dtype=np.float32) + rank)
            recv = accl.create_buffer(args.count, np.float32)
            accl.allreduce(send, recv, args.count, ReduceFunction.SUM)
            return recv.host.copy()

        outs = world.run(body)
        if world.telemetry is not None:
            world.telemetry.sample()  # land one engine/link snapshot
    expected = np.sum([np.arange(args.count, dtype=np.float32) + r
                       for r in range(args.ranks)], axis=0)
    for got in outs:
        np.testing.assert_allclose(got, expected)

    # device rung (r15): stamp buffers land in the same collector and
    # export as device:* tracks in the same Perfetto doc
    device_ran = devtrace and run_device_trace_rung(args.ranks)

    path = obs_trace.collector().dump(args.trace)
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    slices = [ev for ev in events if ev.get("ph") == "X"]
    for ev in events:
        missing = [k for k in ("ph", "ts", "pid", "tid") if k not in ev]
        if missing:
            print(f"FAIL: event missing keys {missing}: {ev}")
            return 1
    dups = check_no_duplicate_metadata(events)
    if dups:
        print(f"FAIL: duplicated track metadata (merge-dedup "
              f"violation): {dups}")
        return 1
    per_rank = {r: sum(1 for ev in slices if ev["pid"] == r)
                for r in range(args.ranks)}
    if any(n < 1 for n in per_rank.values()):
        print(f"FAIL: ranks without spans: {per_rank}")
        return 1
    gangs = {(ev.get("args") or {}).get("gang_id") for ev in slices}
    gangs.discard(None)
    if not gangs:
        print("FAIL: no gang ids in trace")
        return 1
    if device_ran:
        dev_tracks = {(ev["pid"], (ev.get("args") or {}).get("name"))
                      for ev in events if ev.get("ph") == "M"
                      and str((ev.get("args") or {}).get(
                          "name", "")).startswith("device:")}
        dev_ranks = {pid for pid, _n in dev_tracks}
        if dev_ranks != set(range(args.ranks)):
            print(f"FAIL: device tracks missing ranks: have "
                  f"{sorted(dev_ranks)}, want 0..{args.ranks - 1}")
            return 1
        dev_slices = [ev for ev in slices
                      if (ev.get("args") or {}).get("device_track")]
        if not dev_slices:
            print("FAIL: ACCL_DEVICE_TRACE on but no device slices")
            return 1
        bad = [ev for ev in dev_slices
               if not {"step", "device_track"} <=
               set((ev.get("args") or {}))]
        if bad:
            print(f"FAIL: device slices missing schema keys: {bad[:3]}")
            return 1

    with open(args.metrics, "w") as f:
        f.write(obs_metrics.dump_metrics(as_json=True))
    snap = obs_metrics.default_registry().snapshot()
    if not any(v["collective"] == "allreduce" and v["calls"] >= args.ranks
               for v in snap["calls"].values()):
        print(f"FAIL: metrics registry missing the allreduce rows: "
              f"{list(snap['calls'])}")
        return 1
    # link plane (r15): the sampler must have published the P×P cells
    link_cells = [k for k in snap["counters"]
                  if k.startswith("link/tx_bytes/")]
    if not link_cells:
        print(f"FAIL: no link/tx_bytes/* cells in the metrics snapshot "
              f"(link sampler never landed): "
              f"{sorted(snap['counters'])[:10]}")
        return 1

    print(f"OK: {len(slices)} slices over {args.ranks} ranks "
          f"({per_rank}), {len(gangs)} gang(s), "
          f"{len(link_cells)} link cell(s), device rung "
          f"{'ran' if device_ran else 'off/skipped'}; trace={path} "
          f"metrics={args.metrics}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
