#!/usr/bin/env python
"""Run the collective benchmark sweep to CSV.

Equivalent of the reference bench binary + parse_bench_results.py
(test/host/xrt/src/bench.cpp): sweep 2^4..2^19 elements over every
collective against the chosen backend.

Usage:
  python scripts/run_sweep.py --design emu-inproc --nranks 4 --out sweep.csv
  python scripts/run_sweep.py --design tpu --nranks 4 --pows 4 19
"""
from __future__ import annotations

import argparse
import sys


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--design", default="emu-inproc",
                    choices=["emu-inproc", "tpu"])
    ap.add_argument("--nranks", type=int, default=4)
    ap.add_argument("--pows", type=int, nargs=2, default=(4, 19),
                    metavar=("LO", "HI"))
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--collectives", nargs="*", default=None)
    ap.add_argument("--out", default="-")
    ap.add_argument("--quantized", action="store_true",
                    help="run the r17 compression-lane sweep "
                         "(bandwidth vs exactness per wire lane) "
                         "instead of the plain collective sweep")
    ap.add_argument("--fused-overlap", action="store_true",
                    help="run the r18 fused-overlap A/B lane (fused "
                         "chunked collective under matmul vs the "
                         "sequential schedule; TPU backend only)")
    ap.add_argument("--ef-convergence", action="store_true",
                    help="run the r19 error-feedback convergence "
                         "lane: train the flagship LM under dp with "
                         "fp32 / int8 / int8+EF gradient sync on "
                         "identical data and record the loss "
                         "trajectories (jax-level; no accl world)")
    ap.add_argument("--steps", type=int, default=40,
                    help="SGD steps for --ef-convergence")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    if args.ef_convergence:
        import os

        # virtual host devices for the dp mesh — must land before the
        # first jax import anywhere in the process
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags +
                f" --xla_force_host_platform_device_count={args.nranks}")
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        sys.path.insert(0, ".")
        from accl_tpu.bench.ef_convergence import (run_ef_convergence,
                                                   write_summary_md)

        out = sys.stdout if args.out == "-" else open(args.out, "w")
        try:
            summary = run_ef_convergence(
                out, steps=args.steps, dp=args.nranks, seed=args.seed,
                log=lambda s: print(s, file=sys.stderr))
        finally:
            if out is not sys.stdout:
                out.close()
        if args.out != "-":
            md = args.out.rsplit(".", 1)[0] + ".md"
            write_summary_md(md, summary,
                             csv_name=args.out.rsplit("/", 1)[-1])
            print(f"[ef] summary: {md}", file=sys.stderr)
        from accl_tpu.bench.ef_convergence import TRACK_TOL

        bad = {k: v for k, v in summary.items()
               if k.endswith("_mean_abs_dev") and v > TRACK_TOL}
        if bad:
            print(f"[ef] FAIL: quantized lane(s) diverged from the "
                  f"fp32 trajectory past {TRACK_TOL:g}: {bad}",
                  file=sys.stderr)
            return 1
        return 0

    if args.design == "tpu":
        import jax  # noqa: F401  (leave platform to the environment)

    sys.path.insert(0, ".")
    from accl_tpu.bench import SweepConfig, run_sweep
    from accl_tpu.utils.bringup import Design, initialize_world

    cfg = SweepConfig(
        count_pows=range(args.pows[0], args.pows[1] + 1),
        repetitions=args.reps,
        collectives=tuple(args.collectives) if args.collectives else
        SweepConfig.collectives,
    )
    out = sys.stdout if args.out == "-" else open(args.out, "w")
    design = Design.EMU_INPROC if args.design == "emu-inproc" else Design.TPU
    world = initialize_world(design, args.nranks,
                             max_eager_size=32 * 1024,
                             egr_rx_buf_size=16 * 1024,
                             # lift the rendezvous size cap above the
                             # largest swept message (2^19 fp32 = 2 MB)
                             max_rendezvous_size=1 << 30) \
        if args.design == "emu-inproc" else initialize_world(design,
                                                             args.nranks)
    try:
        if args.fused_overlap:
            from accl_tpu.bench.sweep import run_fused_overlap_sweep

            if args.design != "tpu":
                print("--fused-overlap requires --design tpu (the "
                      "fused lane is a TPU-backend dispatch lane)",
                      file=sys.stderr)
                return 2
            run_fused_overlap_sweep(
                world,
                collectives=tuple(args.collectives)
                if args.collectives else ("allreduce",
                                          "reduce_scatter"),
                count_pows=range(args.pows[0], args.pows[1] + 1),
                repetitions=args.reps, writer=out,
                log=lambda s: print(s, file=sys.stderr))
        elif args.quantized:
            from accl_tpu.bench.sweep import run_compression_sweep

            run_compression_sweep(
                world,
                collectives=tuple(args.collectives)
                if args.collectives else ("allreduce", "reduce_scatter"),
                count_pows=range(args.pows[0], args.pows[1] + 1),
                repetitions=args.reps, writer=out,
                log=lambda s: print(s, file=sys.stderr))
        else:
            run_sweep(world, cfg, writer=out)
    finally:
        world.close()
        if out is not sys.stdout:
            out.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
