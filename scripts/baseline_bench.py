#!/usr/bin/env python
"""The five benchmark configs of record from BASELINE.json.

Each config reproduces one of the reference-derived benchmark setups
(BASELINE.md "Benchmark configs to reproduce"):

  1. 2-rank fp32 all-reduce, 1KB-1MB, emulator mode (CPU baseline)
  2. 8-rank ring all-reduce fp32 sweep, nccl-tests style (1KB-1GB with
     --full; capped at 16MB by default so it runs on small hosts)
  3. 8-rank all-gather + reduce-scatter, fp16/bf16 on-path reduction
  4. 16-rank broadcast/scatter/gather tree-topology latency sweep
  5. Streaming compute + all-reduce fusion (reference vadd_put ->
     fused matmul+psum, accl_tpu/ops/fused.py)

Configs 2-3 run on the TPU backend (real chips, or the virtual CPU mesh
when JAX_PLATFORMS=cpu); 1 and 4 run on the native emulator; 5 measures
the jitted fused path on whatever mesh is available.

Usage:
  python scripts/baseline_bench.py --config 1 --out cfg1.csv
  python scripts/baseline_bench.py --config all --outdir bench_out
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python scripts/baseline_bench.py --config 2
"""
from __future__ import annotations

import argparse
import csv
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _open_out(path):
    return sys.stdout if path in (None, "-") else open(path, "w")


def _apply_platform_env() -> None:
    """jax may have been imported by the interpreter's sitecustomize with
    a hardware platform already selected; re-apply JAX_PLATFORMS from the
    environment so `JAX_PLATFORMS=cpu XLA_FLAGS=...device_count=8` works
    for the virtual-mesh configs (same trick as tests/conftest.py)."""
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)


def config1(out, full: bool = False, reps: int = 3):
    """2-rank fp32 all-reduce 1KB-1MB on the emulator (CPU baseline)."""
    from accl_tpu.bench import SweepConfig, run_sweep
    from accl_tpu.backends.emu import EmuWorld

    pows = range(8, 19)  # 2^8..2^18 fp32 elements = 1KB..1MB
    with EmuWorld(2, egr_rx_buf_size=16 * 1024,
                  max_eager_size=32 * 1024,
                  max_rendezvous_size=1 << 30) as world:
        return run_sweep(world, SweepConfig(collectives=("allreduce",),
                                            count_pows=pows,
                                            repetitions=reps), writer=out)


def config2(out, full: bool = False, reps: int = 3):
    """8-rank ring all-reduce fp32 sweep (nccl-tests style)."""
    from accl_tpu.bench import SweepConfig, run_sweep
    from accl_tpu.backends.tpu import TpuWorld

    hi = 28 if full else 22  # 2^28 fp32 = 1GB; default caps at 16MB
    with TpuWorld(8) as world:
        return run_sweep(world, SweepConfig(collectives=("allreduce",),
                                            count_pows=range(8, hi + 1, 2),
                                            repetitions=reps), writer=out)


def config3(out, full: bool = False, reps: int = 3):
    """8-rank all-gather + reduce-scatter with fp16/bf16 reduction."""
    from accl_tpu.bench import SweepConfig, run_sweep
    from accl_tpu.backends.tpu import TpuWorld

    hi = 22 if full else 16
    rows = []
    for dtype in ("float16", "bfloat16"):
        with TpuWorld(8) as world:
            rows += run_sweep(
                world,
                SweepConfig(collectives=("allgather", "reduce_scatter"),
                            count_pows=range(8, hi + 1, 2), dtype=dtype,
                            repetitions=reps), writer=out)
    return rows


def config4(out, full: bool = False, reps: int = 3):
    """16-rank broadcast/scatter/gather tree-topology latency sweep.

    Small messages stay eager; counts past the eager threshold cross
    into the rendezvous tree schedules (binomial bcast, windowed-fan-in
    gather), so the sweep covers both topologies."""
    from accl_tpu.bench import SweepConfig, run_sweep
    from accl_tpu.backends.emu import EmuWorld

    hi = 13 if full else 11
    with EmuWorld(16, egr_rx_buf_size=1024,
                  max_rendezvous_size=1 << 26) as world:
        return run_sweep(world,
                         SweepConfig(collectives=("bcast", "scatter",
                                                  "gather"),
                                     count_pows=range(4, hi + 1),
                                     repetitions=reps), writer=out)


def config5(out, full: bool = False, reps: int = 5):
    """Streaming compute + all-reduce fusion (vadd_put -> fused
    matmul+psum): fused kernel vs unfused matmul-then-psum."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P
    from accl_tpu.utils.compat import shard_map

    from accl_tpu.ops.fused import fused_matmul_allreduce
    from accl_tpu.utils.profiling import time_fn

    n_dev = len(jax.devices())
    mesh = Mesh(np.array(jax.devices()), ("tp",))
    m = 1024 if full else 256
    k_per = 512 if full else 128
    n = 1024 if full else 256
    dtype = jnp.bfloat16
    x = jnp.ones((m, k_per * n_dev), dtype)
    w = jnp.ones((k_per * n_dev, n), dtype)

    use_pallas = jax.default_backend() == "tpu"

    @jax.jit
    def fused(x, w):
        return shard_map(
            lambda xs, ws: fused_matmul_allreduce(xs, ws, axis="tp",
                                                  use_pallas=use_pallas),
            mesh=mesh, in_specs=(P(None, "tp"), P("tp", None)),
            out_specs=P(None, None))(x, w)

    @jax.jit
    def unfused(x, w):
        return shard_map(
            lambda xs, ws: jax.lax.psum(
                jnp.dot(xs, ws, preferred_element_type=jnp.float32
                        ).astype(xs.dtype), "tp"),
            mesh=mesh, in_specs=(P(None, "tp"), P("tp", None)),
            out_specs=P(None, None))(x, w)

    np.testing.assert_allclose(np.asarray(fused(x, w), np.float32),
                               np.asarray(unfused(x, w), np.float32),
                               rtol=2e-2)
    t_fused = time_fn(fused, x, w, iters=reps)
    t_unfused = time_fn(unfused, x, w, iters=reps)
    flops = 2.0 * m * k_per * n_dev * n
    rows = [
        {"variant": "fused", "seconds": t_fused,
         "tflops": flops / t_fused / 1e12},
        {"variant": "unfused", "seconds": t_unfused,
         "tflops": flops / t_unfused / 1e12},
        {"variant": "speedup", "seconds": t_unfused / t_fused, "tflops": 0.0},
    ]
    w_csv = csv.DictWriter(out, fieldnames=["variant", "seconds", "tflops"])
    w_csv.writeheader()
    for r in rows:
        w_csv.writerow(r)
    return rows


CONFIGS = {1: config1, 2: config2, 3: config3, 4: config4, 5: config5}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="all",
                    help="1-5 or 'all'")
    ap.add_argument("--full", action="store_true",
                    help="full reference sizes (needs big host / real TPUs)")
    ap.add_argument("--reps", type=int, default=None)
    ap.add_argument("--out", default="-", help="CSV path (single config)")
    ap.add_argument("--outdir", default=None, help="directory (all configs)")
    args = ap.parse_args()

    _apply_platform_env()
    ids = list(CONFIGS) if args.config == "all" else [int(args.config)]
    for cid in ids:
        fn = CONFIGS[cid]
        kwargs = {"full": args.full}
        if args.reps:
            kwargs["reps"] = args.reps
        if args.outdir:
            os.makedirs(args.outdir, exist_ok=True)
            path = os.path.join(args.outdir, f"baseline_cfg{cid}.csv")
        else:
            path = args.out if len(ids) == 1 else "-"
        out = _open_out(path)
        t0 = time.time()
        try:
            fn(out, **kwargs)
        finally:
            if out is not sys.stdout:
                out.close()
        print(f"config {cid} done in {time.time() - t0:.1f}s"
              + (f" -> {path}" if path != "-" else ""), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
