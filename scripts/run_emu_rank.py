#!/usr/bin/env python
"""One emulator rank as its own OS process over the TCP transport.

Equivalent of the reference emulator launcher (test/model/emulator/
run.py:45-77 starts one `cclo_emu` process per rank; the MPI test
binaries attach one driver each).  Launch N of these with rank ids
0..N-1 and the same base port; each runs a self-checking collective
workload and exits non-zero on any mismatch.

Usage:
  python scripts/run_emu_rank.py --rank R --nranks N --port 19000 \
      [--count 1024] [--workload allreduce|ring|all]
"""
from __future__ import annotations

import argparse
import sys


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--nranks", type=int, required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--count", type=int, default=1024)
    ap.add_argument("--workload", default="all",
                    choices=["allreduce", "ring", "bcast", "all"])
    args = ap.parse_args()

    import numpy as np

    sys.path.insert(0, ".")
    from accl_tpu import ReduceFunction
    from accl_tpu.backends.emu import EmuRankTcp

    r, P, n = args.rank, args.nranks, args.count

    def data(rank, salt=0):
        rng = np.random.default_rng(900 + rank + salt * 100)
        return rng.standard_normal(n).astype(np.float32)

    # Timeout layering: the engine's receive budget must be the FIRST to
    # fire — host-side call waits sit above it so a stall surfaces as the
    # engine's RECEIVE_TIMEOUT_ERROR diagnosis, not an opaque host-side
    # DMA_TIMEOUT_ERROR.
    with EmuRankTcp(r, P, args.port, call_timeout_s=540.0) as node:
        accl = node.accl
        # Startup-skew absorber: peer PROCESSES can lag by minutes on an
        # oversubscribed CI host (python+numpy import under load), and
        # that wait belongs to bring-up, not to any collective's budget.
        # Barrier under a long budget first, then tighten for the
        # workload proper.
        accl.set_timeout(480_000_000)
        accl.barrier()
        # workload proper: engine 120s < driver sync wait 180s < the
        # device waiter thread (540s) and the pytest harness ceiling
        accl.set_timeout(120_000_000)
        accl.call_timeout_s = 180.0

        if args.workload in ("allreduce", "all"):
            send = accl.create_buffer_like(data(r))
            recv = accl.create_buffer(n, np.float32)
            accl.allreduce(send, recv, n, ReduceFunction.SUM)
            exp = np.sum([data(i) for i in range(P)], axis=0)
            np.testing.assert_allclose(recv.host, exp, rtol=1e-5)

        if args.workload in ("ring", "all"):
            src = accl.create_buffer_like(data(r, salt=1))
            dst = accl.create_buffer(n, np.float32)
            nxt, prv = (r + 1) % P, (r - 1) % P
            sreq = accl.send(src, n, nxt, tag=3, run_async=True)
            accl.recv(dst, n, prv, tag=3)
            assert sreq.wait(timeout=120)
            sreq.check()
            np.testing.assert_array_equal(dst.host, data(prv, salt=1))

        if args.workload in ("bcast", "all"):
            buf = accl.create_buffer_like(data(r, salt=2))
            accl.bcast(buf, n, root=0)
            np.testing.assert_array_equal(buf.host, data(0, salt=2))

    print(f"rank {r}/{P}: OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
