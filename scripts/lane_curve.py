"""Single-chip on-path-reduction-lane curve: effective reduction
bandwidth vs message size, 1 KB - 1 GB (BASELINE.md metric of record's
single-chip leg; reference role: the CCLO's 64 B/cycle reduction
datapath, kernels/plugins/reduce_ops.cpp, whose ceiling is flat at
16 GB/s — here the curve shows the latency floor at small sizes and
the HBM roofline at large ones).

Measures accl_tpu.ops.reduce_ops.pallas_add (3 HBM streams per element)
with the chained in-jit methodology of bench.py, A/B-interleaved with
the plain XLA add as the same-window roofline reference.

Writes bench/results/lane_curve_r{N}.csv.  Run on the real chip:
  python scripts/lane_curve.py --round 4
"""
from __future__ import annotations

import argparse
import csv
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--round", type=int, default=4)
    ap.add_argument("--outdir", default=os.path.join("bench", "results"))
    ap.add_argument("--max-bytes", type=int, default=1 << 30)
    ap.add_argument("--platform", default="",
                    help="pin jax platform at runtime (cpu for a smoke "
                         "run; empty = whatever the site claims)")
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import jax.numpy as jnp

    backend = jax.default_backend()
    print(f"[lane_curve] backend={backend}", file=sys.stderr)

    from accl_tpu.bench.timing import make_harness
    from accl_tpu.ops.reduce_ops import pallas_add

    _probe, timed_chain, timed_chain_ab, sync_s = make_harness(jax, jnp)
    interpret = backend == "cpu"

    os.makedirs(args.outdir, exist_ok=True)
    path = os.path.join(args.outdir, f"lane_curve_r{args.round:02d}.csv")
    rows = []
    nbytes = 1 << 10
    while nbytes <= args.max_bytes:
        n = nbytes // 4
        rows_n = max(1, n // 128)
        a = jax.random.normal(jax.random.PRNGKey(0), (rows_n, 128),
                              jnp.float32)
        b = jax.random.normal(jax.random.PRNGKey(1), (rows_n, 128),
                              jnp.float32)
        streams = 3 * a.size * 4  # read a, read b, write out
        # enough chained iterations that device time dwarfs RTT jitter,
        # bounded so huge sizes don't take minutes
        est_ns = streams / 660e9 * 1e9 + 3000
        iters = int(min(2048, max(8, 15e6 / est_ns)))
        fns = {
            "pallas": lambda x, bb: pallas_add(x, bb, interpret=interpret,
                                               donate=True),
            "xla": lambda x, bb: x + bb,
        }
        dts = timed_chain_ab(fns, a, iters, trials=4, consts=(b,))
        row = {
            "bytes": a.size * 4,
            "iters": iters,
            "lane_GBps": round(streams / dts["pallas"] / 1e9, 3),
            "xla_GBps": round(streams / dts["xla"] / 1e9, 3),
            "lane_us": round(dts["pallas"] * 1e6, 3),
            "roofline_frac": round(dts["xla"] / dts["pallas"], 4),
        }
        rows.append(row)
        print(f"[lane_curve] {row}", file=sys.stderr)
        nbytes *= 4

    with open(path, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=list(rows[0].keys()))
        w.writeheader()
        w.writerows(rows)
    print(f"wrote {path} ({len(rows)} sizes, platform={backend})")


if __name__ == "__main__":
    t0 = time.perf_counter()
    main()
    print(f"total {time.perf_counter() - t0:.0f}s", file=sys.stderr)
