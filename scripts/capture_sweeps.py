"""Capture the busbw sweep artifacts of record into bench/results/.

Produces the CSV shapes BASELINE.md names as the metric of record
(busbw-vs-size tables, nccl conventions — reference bench harness
test/host/xrt/src/bench.cpp:25-61 + parse_bench_results.py):

  sweep_emu_r{N}.csv       driver busbw over the native engine (4 ranks,
                           inproc transport)
  sweep_dgram_r{N}.csv     same matrix over the adversarial datagram rung
  sweep_rdma_r{N}.csv      same matrix over the queue-pair RDMA rung
  sweep_tpu8_r{N}.csv      driver busbw over the TPU backend gang
                           scheduler on the 8-virtual-device CPU mesh
  pipeline_ab_r{N}.csv     eager egress pipelining A/B (depth 1 vs 3)
                           across message sizes on the emulator

CPU-rung absolute numbers are NOT hardware numbers — they are recorded
so the busbw-vs-size SHAPE and the pipelining delta are inspectable and
regressions show in review diffs.

Usage: python scripts/capture_sweeps.py [--round 3]
"""
from __future__ import annotations

import argparse
import csv
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--round", type=int, default=3)
    ap.add_argument("--outdir", default=os.path.join("bench", "results"))
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np  # noqa: F401

    from accl_tpu.backends.emu import EmuWorld
    from accl_tpu.bench.sweep import SweepConfig, run_sweep

    os.makedirs(args.outdir, exist_ok=True)
    tag = f"r{args.round:02d}"

    # 1. emulator rung (counts kept moderate: 1 core drives 4 engines)
    def raise_timeouts(w):
        # 1 core drives every engine; rendezvous retries under load need
        # far more than the 1s default receive budget
        for a in w.accls:
            a.set_timeout(60_000_000)
            a.call_timeout_s = 180.0
        return w

    cfg = SweepConfig(count_pows=tuple(range(4, 15)), repetitions=3)
    path = os.path.join(args.outdir, f"sweep_emu_{tag}.csv")
    # rx pool provisioned for the worst eager case: (P-1) peers x 16
    # segments in flight for alltoall at the 16 KB eager ceiling (the
    # reference bench sizes its spare-buffer pool the same way and its
    # tests SKIP when under-provisioned, test.cpp:279)
    with EmuWorld(4, n_egr_rx_bufs=64, max_eager_size=16384,
                  max_rendezvous_size=1 << 22) as w, \
            open(path, "w", newline="") as f:
        run_sweep(raise_timeouts(w), cfg, writer=f)
    print(f"wrote {path}")

    # 2. datagram rung (fragmentation + reorder on every transfer)
    path = os.path.join(args.outdir, f"sweep_dgram_{tag}.csv")
    with EmuWorld(4, transport="dgram", mtu=512, reorder_window=8,
                  n_egr_rx_bufs=64, max_eager_size=16384,
                  max_rendezvous_size=1 << 22) as w, \
            open(path, "w", newline="") as f:
        run_sweep(raise_timeouts(w), cfg, writer=f)
    print(f"wrote {path}")

    # 2b. RDMA rung (queue pairs; one-sided memory plane for rendezvous)
    path = os.path.join(args.outdir, f"sweep_rdma_{tag}.csv")
    with EmuWorld(4, transport="rdma", n_egr_rx_bufs=64,
                  max_eager_size=16384, max_rendezvous_size=1 << 22) as w, \
            open(path, "w", newline="") as f:
        run_sweep(raise_timeouts(w), cfg, writer=f)
    print(f"wrote {path}")

    # 3. TPU backend gang scheduler on the virtual 8-device mesh
    from accl_tpu.backends.tpu import TpuWorld

    path = os.path.join(args.outdir, f"sweep_tpu8_{tag}.csv")
    with TpuWorld(8) as w, open(path, "w", newline="") as f:
        run_sweep(w, SweepConfig(count_pows=tuple(range(4, 15)),
                                 repetitions=3), writer=f)
    print(f"wrote {path}")

    # 4. egress pipelining A/B: depth 1 (strictly serial, the round-2
    #    engine's behavior) vs depth 3 (reference discipline) across
    #    multi-segment message sizes
    path = os.path.join(args.outdir, f"pipeline_ab_{tag}.csv")
    with open(path, "w", newline="") as f:
        wcsv = csv.DictWriter(f, fieldnames=[
            "count", "bytes", "depth", "mean_us", "best_us", "reps"])
        wcsv.writeheader()
        for depth in (1, 3):
            with EmuWorld(2, max_eager_size=1 << 20,
                          max_rendezvous_size=1 << 22) as w:
                def fn(accl, rank, count, depth=depth):
                    import numpy as np
                    accl.set_tuning(3, depth)  # EGRESS_PIPELINE_DEPTH
                    nxt, prv = (rank + 1) % 2, (rank - 1) % 2
                    src = accl.create_buffer(count, np.float32)
                    dst = accl.create_buffer(count, np.float32)
                    src.host[:] = rank
                    durs = []
                    for rep in range(7):
                        t0 = time.perf_counter()
                        req = accl.send(src, count, nxt, tag=rep,
                                        run_async=True)
                        accl.recv(dst, count, prv, tag=rep)
                        req.wait(60)
                        durs.append(time.perf_counter() - t0)
                    return durs[2:]  # drop warmup reps

                for pw in range(8, 17):
                    count = 1 << pw
                    per_rank = w.run(fn, count)
                    durs = [d for ds in per_rank for d in ds]
                    wcsv.writerow({
                        "count": count,
                        "bytes": count * 4,
                        "depth": depth,
                        "mean_us": round(statistics.mean(durs) * 1e6, 1),
                        "best_us": round(min(durs) * 1e6, 1),
                        "reps": len(durs),
                    })
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
