"""Capture the busbw sweep artifacts of record into bench/results/.

Produces the CSV shapes BASELINE.md names as the metric of record
(busbw-vs-size tables, nccl conventions — reference bench harness
test/host/xrt/src/bench.cpp:25-61 + parse_bench_results.py):

  sweep_emu_r{N}.csv       driver busbw over the native engine (4 ranks,
                           inproc transport)
  sweep_dgram_r{N}.csv     same matrix over the adversarial datagram rung
  sweep_rdma_r{N}.csv      same matrix over the queue-pair RDMA rung
  sweep_tpu8_r{N}.csv      driver busbw over the TPU backend gang
                           scheduler on the 8-virtual-device CPU mesh
  driver_vs_raw_r{N}.csv   allreduce latency through the FULL driver
                           stack vs a bare jitted shard_map psum on the
                           same mesh (the Coyote harness's ACCL-vs-MPI
                           comparison role, plot.py:10-44)
  sweep_{emu,dgram,rdma,tpu8}_f16_r{N}.csv  fp16 allreduce sweep on
                           every rung (the metric of record names
                           fp32/fp16) through the f16 arithmetic lanes
  pipeline_ab_r{N}.csv     eager egress pipelining A/B (depth 1 vs 3)
                           across message sizes on the emulator

CPU-rung absolute numbers are NOT hardware numbers — they are recorded
so the busbw-vs-size SHAPE and the pipelining delta are inspectable and
regressions show in review diffs.

Usage: python scripts/capture_sweeps.py [--round 3]
"""
from __future__ import annotations

import argparse
import csv
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--round", type=int, default=4)
    ap.add_argument("--stages",
                    default="emu,dgram,rdma,tpu8,f16,f16all,vsraw,pipeline",
                    help="comma list of stages to run")
    ap.add_argument("--maxpow", type=int, default=19,
                    help="largest 2^k element count (BASELINE metric of "
                         "record: 2^4..2^19, reference bench.cpp:25-61)")
    ap.add_argument("--outdir", default=os.path.join("bench", "results"))
    args = ap.parse_args()

    from accl_tpu.utils.platform import ensure_host_device_count

    ensure_host_device_count(8)

    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np  # noqa: F401

    from accl_tpu.backends.emu import EmuWorld
    from accl_tpu.bench.sweep import SweepConfig, run_sweep

    os.makedirs(args.outdir, exist_ok=True)
    tag = f"r{args.round:02d}"
    stages = set(args.stages.split(","))

    # 1. emulator rung (counts kept moderate: 1 core drives 4 engines)
    def raise_timeouts(w):
        # 1 core drives every engine; rendezvous retries under load need
        # far more than the 1s default receive budget
        for a in w.accls:
            a.set_timeout(60_000_000)
            a.call_timeout_s = 180.0
        return w

    def prep_tpu_world(w):
        # the full-range virtual sweeps ride the XLA collective path:
        # the interpreted Pallas ring at multi-MB payloads measures the
        # interpreter, not the driver (ring correctness at 8 ranks is
        # certified by dryrun_multichip with the threshold forced to 0)
        w.engine.ring_threshold_bytes = 1 << 60
        for a in w.accls:
            a.call_timeout_s = 180.0  # 1 core drives all 8 gang members
        return w

    def make_emu_world(**extra):
        # ONE provisioning for every emulator-rung sweep: rx pool sized
        # for the worst eager case ((P-1) peers x 16 segments at the
        # 16 KB ceiling, the reference bench's sizing), 256MB devicemem
        # + 64MB rendezvous cap for the 2^19 large-message regime
        return EmuWorld(4, devmem_bytes=256 << 20, n_egr_rx_bufs=64,
                        max_eager_size=16384,
                        max_rendezvous_size=64 << 20, **extra)

    cfg = SweepConfig(count_pows=tuple(range(4, args.maxpow + 1)),
                      repetitions=3)
    if "emu" in stages:
        path = os.path.join(args.outdir, f"sweep_emu_{tag}.csv")
        with make_emu_world() as w, open(path, "w", newline="") as f:
            run_sweep(raise_timeouts(w), cfg, writer=f)
        print(f"wrote {path}")

    # 2. datagram rung (fragmentation + reorder on every transfer)
    if "dgram" in stages:
        path = os.path.join(args.outdir, f"sweep_dgram_{tag}.csv")
        with make_emu_world(transport="dgram", mtu=512,
                            reorder_window=8) as w, \
                open(path, "w", newline="") as f:
            run_sweep(raise_timeouts(w), cfg, writer=f)
        print(f"wrote {path}")

    # 2b. RDMA rung (queue pairs; one-sided memory plane for rendezvous)
    if "rdma" in stages:
        path = os.path.join(args.outdir, f"sweep_rdma_{tag}.csv")
        with make_emu_world(transport="rdma") as w, \
                open(path, "w", newline="") as f:
            run_sweep(raise_timeouts(w), cfg, writer=f)
        print(f"wrote {path}")

    # 3. TPU backend gang scheduler on the virtual 8-device mesh
    from accl_tpu.backends.tpu import TpuWorld

    if "tpu8" in stages:
        path = os.path.join(args.outdir, f"sweep_tpu8_{tag}.csv")
        with TpuWorld(8) as w, open(path, "w", newline="") as f:
            run_sweep(prep_tpu_world(w), SweepConfig(
                count_pows=tuple(range(4, args.maxpow + 1)),
                repetitions=3), writer=f)
        print(f"wrote {path}")

    # 3c. fp16 allreduce sweep (BASELINE metric of record names
    #     "fp32/fp16"): the f16 arithmetic lanes end to end on the
    #     emulator rung + the TPU-backend gang
    if "f16" in stages:
        cfg16 = SweepConfig(collectives=("allreduce",),
                            count_pows=tuple(range(4, args.maxpow + 1)),
                            dtype="float16", repetitions=3)
        path = os.path.join(args.outdir, f"sweep_emu_f16_{tag}.csv")
        with make_emu_world() as w, open(path, "w", newline="") as f:
            run_sweep(raise_timeouts(w), cfg16, writer=f)
        print(f"wrote {path}")
        path = os.path.join(args.outdir, f"sweep_tpu8_f16_{tag}.csv")
        with TpuWorld(8) as w, open(path, "w", newline="") as f:
            run_sweep(prep_tpu_world(w), cfg16, writer=f)
        print(f"wrote {path}")

    # 3d. f16 on the lossy/datagram and RDMA rungs too ("f16all"),
    # completing the fp32+fp16 matrix across every transport rung
    if "f16all" in stages:
        cfg16 = SweepConfig(collectives=("allreduce",),
                            count_pows=tuple(range(4, args.maxpow + 1)),
                            dtype="float16", repetitions=3)
        for rung, kw in (("dgram", dict(transport="dgram", mtu=512,
                                        reorder_window=8)),
                         ("rdma", dict(transport="rdma"))):
            path = os.path.join(args.outdir,
                                f"sweep_{rung}_f16_{tag}.csv")
            with make_emu_world(**kw) as w, \
                    open(path, "w", newline="") as f:
                run_sweep(raise_timeouts(w), cfg16, writer=f)
            print(f"wrote {path}")

    # 3b + 4: the remaining stages self-select below
    if "vsraw" in stages:
        _vsraw_stage(args, tag, TpuWorld)
    _pipeline_stage(args, tag, stages, EmuWorld)


def _vsraw_stage(args, tag, TpuWorld) -> None:
    # driver path vs raw XLA collective across the sweep — the Coyote
    # harness's ACCL-vs-MPI comparison role (reference
    # test/host/Coyote/run_scripts/plot.py:10-44): same mesh, same
    # payload, allreduce through the full driver stack vs a bare jitted
    # shard_map psum.  The ratio column is the driver's end-to-end
    # overhead at each size.
    import jax as _jax
    from accl_tpu.utils.compat import install as _compat_install
    _compat_install(_jax)  # old-jax: alias jax.shard_map to the shim
    import jax.numpy as _jnp
    import numpy as _np
    from jax.sharding import Mesh as _Mesh, NamedSharding as _NS, PartitionSpec as _P

    path = os.path.join(args.outdir, f"driver_vs_raw_{tag}.csv")
    with TpuWorld(8) as w, open(path, "w", newline="") as f:
        w.engine.ring_threshold_bytes = 1 << 60
        for a in w.accls:
            a.call_timeout_s = 180.0
        wcsv = csv.DictWriter(f, fieldnames=[
            "count", "bytes", "driver_us", "raw_us", "overhead_x"])
        wcsv.writeheader()

        devs = _jax.devices()[:8]
        mesh = _Mesh(_np.array(devs), ("rank",))

        def driver_best(count, reps=5):
            def body(accl, rank):
                import numpy as np
                s = accl.create_buffer(count, np.float32)
                r = accl.create_buffer(count, np.float32)
                s.host[:] = rank
                from accl_tpu import ReduceFunction
                accl.allreduce(s, r, count, ReduceFunction.SUM)  # warm
                best = 1e30
                for _ in range(reps):
                    t0 = time.perf_counter()
                    accl.allreduce(s, r, count, ReduceFunction.SUM,
                                   from_fpga=True, to_fpga=True)
                    best = min(best, time.perf_counter() - t0)
                for b in (s, r):
                    free = getattr(b, "free", None)
                    if free:
                        free()
                return best
            return max(w.run(body))

        def raw_best(count, reps=5):
            x = _jax.device_put(
                _jnp.zeros((8 * count,), _jnp.float32),
                _NS(mesh, _P("rank")))
            fn = _jax.jit(_jax.shard_map(
                lambda v: _jax.lax.psum(v, "rank"), mesh=mesh,
                in_specs=_P("rank"), out_specs=_P("rank")))
            _jax.block_until_ready(fn(x))
            best = 1e30
            for _ in range(reps):
                t0 = time.perf_counter()
                _jax.block_until_ready(fn(x))
                best = min(best, time.perf_counter() - t0)
            return best

        for pw in range(4, args.maxpow + 1):
            count = 1 << pw
            d_us = driver_best(count) * 1e6
            r_us = raw_best(count) * 1e6
            wcsv.writerow({
                "count": count,
                "bytes": count * 4,
                "driver_us": round(d_us, 1),
                "raw_us": round(r_us, 1),
                "overhead_x": round(d_us / max(r_us, 1e-9), 2),
            })
    print(f"wrote {path}")


def _pipeline_stage(args, tag, stages, EmuWorld) -> None:
    # 4. egress pipelining A/B: depth 1 (strictly serial, the round-2
    #    engine's behavior) vs depth 3 (reference discipline) across
    #    multi-segment message sizes
    if "pipeline" not in stages:
        return
    path = os.path.join(args.outdir, f"pipeline_ab_{tag}.csv")
    with open(path, "w", newline="") as f:
        wcsv = csv.DictWriter(f, fieldnames=[
            "count", "bytes", "depth", "mean_us", "best_us", "reps"])
        wcsv.writeheader()
        for depth in (1, 3):
            with EmuWorld(2, max_eager_size=1 << 20,
                          max_rendezvous_size=64 << 20) as w:
                def fn(accl, rank, count, depth=depth):
                    import numpy as np
                    accl.set_tuning(3, depth)  # EGRESS_PIPELINE_DEPTH
                    nxt, prv = (rank + 1) % 2, (rank - 1) % 2
                    src = accl.create_buffer(count, np.float32)
                    dst = accl.create_buffer(count, np.float32)
                    src.host[:] = rank
                    durs = []
                    for rep in range(7):
                        t0 = time.perf_counter()
                        req = accl.send(src, count, nxt, tag=rep,
                                        run_async=True)
                        accl.recv(dst, count, prv, tag=rep)
                        req.wait(60)
                        durs.append(time.perf_counter() - t0)
                    return durs[2:]  # drop warmup reps

                for pw in range(8, 17):
                    count = 1 << pw
                    per_rank = w.run(fn, count)
                    durs = [d for ds in per_rank for d in ds]
                    wcsv.writerow({
                        "count": count,
                        "bytes": count * 4,
                        "depth": depth,
                        "mean_us": round(statistics.mean(durs) * 1e6, 1),
                        "best_us": round(min(durs) * 1e6, 1),
                        "reps": len(durs),
                    })
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
