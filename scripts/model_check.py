#!/usr/bin/env python3
"""Deterministic schedule-exploration model checker for the native engine.

Orchestrates the ``ACCL_DETSCHED`` harness (``native/test/test_detsched``,
scheduler in ``native/src/detsched.hpp``): builds the instrumented
binaries, explores drill interleavings (DPOR-pruned, bounded-preemption
DFS over schedule prefixes), and — on a finding — writes a replayable
failing-schedule artifact (drill + minimal hex schedule prefix + seed,
mirroring fuzz_wire.py's failing-frame artifact).  Reproduce with::

    python scripts/model_check.py --replay model_check_failure.json

Modes
-----
``--drill NAME [--runs N]``
    explore one drill (see ``--list``) on the fixed build.
``--ci``
    the CI gate: >= ``--runs`` (default 3000) schedules on EACH of the
    four engine drills with zero findings, PLUS the sensitivity proof —
    the ``ACCL_FAULT_DETACH_RACE`` build (which reverts the r13
    InprocHub::detach drain) must REDISCOVER the detach race.  A
    checker that cannot re-find a known race proves nothing; this run
    proves sensitivity on every CI invocation.
``--replay ARTIFACT``
    re-run one recorded schedule; exits 0 iff the artifact's verdict
    (failing schedule) reproduces.

Exit codes: 0 clean/as-expected, 1 findings (or sensitivity loss),
2 usage/build errors.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native")
BIN = os.path.join(NATIVE, "test", "test_detsched")
BIN_FAULT = os.path.join(NATIVE, "test", "test_detsched_fault")

ENGINE_DRILLS = (
    "replay_vs_invalidate",
    "abort_vs_traffic",
    "join_vs_traffic",
    "shutdown_vs_waiters",
    # r17: the ROADMAP item 2 KNOWN-ISSUE shape (concurrent sub-comm
    # allgathers over one rx pool) at its 4-rank exhaustive scale; the
    # full 8-rank repro is `--drill subcomm_allgather8` with an
    # explicit budget (heavier per schedule)
    "subcomm_allgather",
)
SENSITIVITY_DRILL = "detach_race"


def build(verbose: bool) -> None:
    cmd = ["make", "-C", NATIVE, "detsched"]
    proc = subprocess.run(cmd, capture_output=not verbose, text=True)
    if proc.returncode != 0:
        if proc.stdout:
            sys.stderr.write(proc.stdout)
        if proc.stderr:
            sys.stderr.write(proc.stderr)
        raise SystemExit(2)


def run_harness(binary: str, args: list[str], timeout_s: float) -> dict:
    try:
        proc = subprocess.run(
            [binary, *args], capture_output=True, text=True, timeout=timeout_s
        )
    except subprocess.TimeoutExpired as exc:
        # a wedged harness is itself a finding, not an orchestrator
        # crash: report it like a failed run so artifacts still land
        return {
            "findings": 1,
            "runs": 0,
            "what": f"harness timeout after {timeout_s:.0f}s "
                    f"(possible scheduler hang): {exc}",
            "exit_code": -1,
        }
    line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else "{}"
    try:
        out = json.loads(line)
    except json.JSONDecodeError:
        out = {"parse_error": line}
    out["exit_code"] = proc.returncode
    if proc.stderr.strip():
        out["stderr_tail"] = proc.stderr.strip().splitlines()[-5:]
    return out


def write_artifact(path: str, drill: str, result: dict, fault_build: bool) -> None:
    art = {
        "drill": drill,
        "schedule_hex": result.get("prefix_hex", ""),
        "full_trace_hex": result.get("trace_hex", ""),
        "seed": result.get("seed", 1),
        "what": result.get("what", ""),
        "fail_step": result.get("fail_step", 0),
        "pbound": result.get("pbound", 3),
        "max_steps": result.get("max_steps", 200000),
        "fault_build": fault_build,
        "replay": (
            f"python scripts/model_check.py --replay {os.path.basename(path)}"
        ),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(art, f, indent=2)
    print(f"[model_check] failing-schedule artifact -> {path}")


def explore_drill(
    drill: str,
    runs: int,
    seed: int,
    pbound: int,
    max_steps: int,
    budget_s: float,
    artifact: str,
    fault_build: bool = False,
    expect_finding: bool = False,
) -> tuple[bool, dict]:
    """Returns (ok, result)."""
    binary = BIN_FAULT if fault_build else BIN
    args = [
        "--drill", drill,
        "--explore", str(runs),
        "--seed", str(seed),
        "--pbound", str(pbound),
        "--max-steps", str(max_steps),
        "--budget-s", str(budget_s),
    ]
    if expect_finding:
        args.append("--expect-finding")
    res = run_harness(binary, args, timeout_s=budget_s + 120)
    findings = int(res.get("findings", 0))
    label = "fault" if fault_build else "fixed"
    print(
        f"[model_check] {drill} ({label}): {res.get('runs', '?')} schedules, "
        f"{res.get('unique_traces', '?')} unique, {findings} finding(s)"
    )
    if findings and not expect_finding:
        print(f"[model_check]   FINDING: {res.get('what', '')!r} "
              f"(step {res.get('fail_step')})")
        write_artifact(artifact, drill, res, fault_build)
        return False, res
    if expect_finding and not findings:
        print(
            f"[model_check]   SENSITIVITY LOSS: the {label} build's seeded "
            f"race was NOT rediscovered"
        )
        return False, res
    if expect_finding and findings:
        print(f"[model_check]   rediscovered: {res.get('what', '')!r} "
              f"(minimal prefix {res.get('prefix_hex', '')!r})")
    return True, res


def replay(path: str) -> int:
    with open(path, encoding="utf-8") as f:
        art = json.load(f)
    binary = BIN_FAULT if art.get("fault_build") else BIN
    args = [
        "--drill", art["drill"],
        "--schedule", art["schedule_hex"],
        "--seed", str(art.get("seed", 1)),
        "--max-steps", str(art.get("max_steps", 200000)),
        "--expect-finding",
    ]
    res = run_harness(binary, args, timeout_s=120)
    ok = res.get("exit_code") == 0 and res.get("failed") is True
    print(
        f"[model_check] replay {art['drill']} schedule "
        f"{art['schedule_hex']!r}: "
        + (f"reproduced ({res.get('what', '')!r})" if ok else "did NOT reproduce")
    )
    return 0 if ok else 1


def main() -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--drill", help="explore one drill on the fixed build")
    ap.add_argument("--list", action="store_true", help="list drills")
    ap.add_argument("--ci", action="store_true",
                    help="CI gate: all four drills + sensitivity proof")
    ap.add_argument("--runs", type=int, default=3000,
                    help="schedules per drill (default 3000)")
    ap.add_argument("--min-interleavings", type=int, default=10000,
                    help="--ci fails below this explored total (the "
                         "acceptance floor; no silent coverage caps)")
    ap.add_argument("--seed", type=int, default=3)
    ap.add_argument("--pbound", type=int, default=3,
                    help="preemption bound per schedule")
    ap.add_argument("--max-steps", type=int, default=200000,
                    help="scheduling-step budget per run (livelock guard)")
    ap.add_argument("--budget-s", type=float, default=240.0,
                    help="wall-clock budget per drill sweep")
    ap.add_argument("--artifact", default="model_check_failure.json",
                    help="failing-schedule artifact path")
    ap.add_argument("--replay", default="",
                    help="replay a failure artifact instead of exploring")
    ap.add_argument("--fault-build", action="store_true",
                    help="run --drill against the ACCL_FAULT_DETACH_RACE build")
    ap.add_argument("--expect-finding", action="store_true",
                    help="with --drill: exit 0 iff a finding IS discovered")
    ap.add_argument("--no-build", action="store_true",
                    help="assume the harness binaries are current")
    ap.add_argument("--verbose", action="store_true")
    opts = ap.parse_args()

    if not opts.no_build:
        build(opts.verbose)

    if opts.list:
        subprocess.run([BIN, "--list"])
        return 0

    if opts.replay:
        return replay(opts.replay)

    if opts.drill:
        ok, _ = explore_drill(
            opts.drill, opts.runs, opts.seed, opts.pbound, opts.max_steps,
            opts.budget_s, opts.artifact, fault_build=opts.fault_build,
            expect_finding=opts.expect_finding,
        )
        return 0 if ok else 1

    if opts.ci:
        total = 0
        all_ok = True
        for drill in ENGINE_DRILLS:
            ok, res = explore_drill(
                drill, opts.runs, opts.seed, opts.pbound, opts.max_steps,
                opts.budget_s, opts.artifact,
            )
            total += int(res.get("runs", 0))
            all_ok = all_ok and ok
            if not ok:
                break
        if all_ok:
            # sensitivity: the seeded detach race must be rediscovered
            ok, _ = explore_drill(
                SENSITIVITY_DRILL, max(opts.runs, 500), opts.seed,
                opts.pbound, opts.max_steps, opts.budget_s, opts.artifact,
                fault_build=True, expect_finding=True,
            )
            all_ok = all_ok and ok
            # and the FIXED hub must hold the same invariant clean
            ok, res = explore_drill(
                SENSITIVITY_DRILL, max(opts.runs, 500), opts.seed,
                opts.pbound, opts.max_steps, opts.budget_s, opts.artifact,
            )
            total += int(res.get("runs", 0))
            all_ok = all_ok and ok
        if all_ok and total < opts.min_interleavings:
            # the acceptance floor is a guarantee, not a report: a
            # budget-truncated sweep must fail loudly, never pass green
            print(
                f"[model_check] CI sweep EXPLORED TOO LITTLE: {total} < "
                f"{opts.min_interleavings} interleavings (budget/runs too "
                f"low for this box)"
            )
            all_ok = False
        print(
            f"[model_check] CI sweep: {total} interleavings across the "
            f"engine drills, "
            + ("sensitivity proven, zero findings" if all_ok else "FAILED")
        )
        return 0 if all_ok else 1

    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
