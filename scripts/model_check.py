#!/usr/bin/env python3
"""Deterministic schedule-exploration model checker for the native engine.

Orchestrates the ``ACCL_DETSCHED`` harness (``native/test/test_detsched``,
scheduler in ``native/src/detsched.hpp``): builds the instrumented
binaries, explores drill interleavings (DPOR-pruned, bounded-preemption
DFS over schedule prefixes, with first-class timeout injection and
rx-pool pressure modeling), and — on a finding — writes a replayable
failing-schedule artifact (drill + minimal hex schedule prefix + seed +
injection bound, mirroring fuzz_wire.py's failing-frame artifact).
Reproduce with::

    python scripts/model_check.py --replay model_check_failure.json

Modes
-----
``--drill NAME [--runs N]``
    explore one drill (see ``--list``) on the fixed build.
``--ci``
    the CI gate: >= ``--runs`` (default 3000) schedules on EACH engine
    drill with zero findings, PLUS the sensitivity proofs — the fault
    build (``ACCL_FAULT_DETACH_RACE`` + ``ACCL_FAULT_SUBCOMM_WEDGE``,
    reverting the r13 InprocHub::detach drain AND the staged-segment
    rescue) must REDISCOVER both seeded failures, and the seeded
    ``liveness_leak`` drill must fire the stuck-progress invariant on
    the fixed build.  A checker that cannot re-find a known race
    proves nothing; this run proves sensitivity on every CI
    invocation.  Ends with a per-drill schedule/time table.
    ``--deep`` lifts the per-drill run caps for the nightly lane.
``--replay ARTIFACT``
    re-run one recorded schedule; exits 0 iff the artifact's verdict
    (failing schedule) reproduces.
``--guide ARTIFACT`` (with ``--drill``)
    trace-guided exploration: replay the artifact's recorded trace as a
    verbatim prefix and explore only the suffix decision space.

Budgets: ``ACCL_DETSCHED_BUDGET`` (seconds) overrides the default
per-drill wall budget — the nightly deep-exploration lane sets it high
and raises ``--runs``; the in-PR gate keeps the fast defaults.

Exit codes: 0 clean/as-expected, 1 findings (or sensitivity loss),
2 usage/build errors (unknown drill names list the registry and exit 2).
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native")
BIN = os.path.join(NATIVE, "test", "test_detsched")
BIN_FAULT = os.path.join(NATIVE, "test", "test_detsched_fault")

ENGINE_DRILLS = (
    "replay_vs_invalidate",
    "abort_vs_traffic",
    "join_vs_traffic",
    "shutdown_vs_waiters",
    # r17: the ROADMAP item 2 KNOWN-ISSUE shape (concurrent sub-comm
    # allgathers over one rx pool) at its 4-rank exhaustive scale; the
    # full 8-rank repro is `--drill subcomm_allgather8` with an
    # explicit budget (heavier per schedule)
    "subcomm_allgather",
    "subcomm_allgather8",
)
SENSITIVITY_DRILL = "detach_race"
WEDGE_DRILL = "subcomm_allgather8"
# the seeded liveness leak: a live token never handed back — the
# stuck-progress invariant must fire on the FIXED build (the checker
# machinery itself is under test, not an engine bug)
LIVENESS_DRILL = "liveness_leak"

# Timeout-injection budget per drill.  The sub-comm drills NEED
# injections (the wedge requires a budget slice expiring while the rx
# pool is pinned); the abort/shutdown drills assert "no call fails",
# which a legitimately injected RECEIVE_TIMEOUT would false-positive,
# so they explore the pure happens-before space (ibound 0 is also
# bit-identical to the pre-injection explorer: same schedules, same
# trace hashes).
DRILL_IBOUND = {
    "subcomm_allgather": 1,
    "subcomm_allgather8": 1,
}

# Per-drill CI run caps: the 8-rank drill costs ~10x a 4-rank schedule,
# and its wedge lives shallow (fault build finds it in <100 schedules),
# so a bounded sweep keeps the gate fast without hiding coverage — the
# nightly deep lane (--deep + ACCL_DETSCHED_BUDGET) runs it uncapped.
CI_RUN_CAPS = {
    "subcomm_allgather8": 400,
}


def build(verbose: bool) -> None:
    cmd = ["make", "-C", NATIVE, "detsched"]
    proc = subprocess.run(cmd, capture_output=not verbose, text=True)
    if proc.returncode != 0:
        if proc.stdout:
            sys.stderr.write(proc.stdout)
        if proc.stderr:
            sys.stderr.write(proc.stderr)
        raise SystemExit(2)


def known_drills() -> list[str]:
    try:
        proc = subprocess.run(
            [BIN, "--list"], capture_output=True, text=True, timeout=30
        )
    except (OSError, subprocess.TimeoutExpired):
        return []
    return [ln.strip() for ln in proc.stdout.splitlines() if ln.strip()]


def reject_unknown_drill(name: str) -> None:
    """Unknown drill names are usage errors: list the registry, exit 2."""
    drills = known_drills()
    if drills and name not in drills:
        print(f"[model_check] unknown drill {name!r}; available drills:")
        for d in drills:
            print(f"  {d}")
        raise SystemExit(2)


def run_harness(binary: str, args: list[str], timeout_s: float) -> dict:
    try:
        proc = subprocess.run(
            [binary, *args], capture_output=True, text=True, timeout=timeout_s
        )
    except subprocess.TimeoutExpired as exc:
        # a wedged harness is itself a finding, not an orchestrator
        # crash: report it like a failed run so artifacts still land
        return {
            "findings": 1,
            "runs": 0,
            "what": f"harness timeout after {timeout_s:.0f}s "
                    f"(possible scheduler hang): {exc}",
            "exit_code": -1,
        }
    line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else "{}"
    try:
        out = json.loads(line)
    except json.JSONDecodeError:
        out = {"parse_error": line}
    out["exit_code"] = proc.returncode
    if proc.stderr.strip():
        out["stderr_tail"] = proc.stderr.strip().splitlines()[-5:]
    return out


def write_artifact(path: str, drill: str, result: dict, fault_build: bool) -> None:
    art = {
        "drill": drill,
        "schedule_hex": result.get("prefix_hex", ""),
        "full_trace_hex": result.get("trace_hex", ""),
        "seed": result.get("seed", 1),
        "what": result.get("what", ""),
        "fail_step": result.get("fail_step", 0),
        "pbound": result.get("pbound", 3),
        # replay MUST present the same injection bound: choices are
        # reduced modulo (enabled + injectable), so a different ibound
        # misaligns every decision after the first armed window
        "ibound": result.get("ibound", 0),
        "max_steps": result.get("max_steps", 200000),
        "fault_build": fault_build,
        "replay": (
            f"python scripts/model_check.py --replay {os.path.basename(path)}"
        ),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(art, f, indent=2)
    print(f"[model_check] failing-schedule artifact -> {path}")


def explore_drill(
    drill: str,
    runs: int,
    seed: int,
    pbound: int,
    max_steps: int,
    budget_s: float,
    artifact: str,
    fault_build: bool = False,
    expect_finding: bool = False,
    ibound: int | None = None,
    guide_hex: str = "",
) -> tuple[bool, dict]:
    """Returns (ok, result); result carries ``elapsed_s``."""
    binary = BIN_FAULT if fault_build else BIN
    if ibound is None:
        ibound = DRILL_IBOUND.get(drill, 0)
    args = [
        "--drill", drill,
        "--explore", str(runs),
        "--seed", str(seed),
        "--pbound", str(pbound),
        "--ibound", str(ibound),
        "--max-steps", str(max_steps),
        "--budget-s", str(budget_s),
    ]
    if guide_hex:
        args += ["--explore-from", guide_hex]
    if expect_finding:
        args.append("--expect-finding")
    t0 = time.monotonic()
    res = run_harness(binary, args, timeout_s=budget_s + 120)
    res["elapsed_s"] = time.monotonic() - t0
    findings = int(res.get("findings", 0))
    label = "fault" if fault_build else "fixed"
    print(
        f"[model_check] {drill} ({label}, ibound={ibound}): "
        f"{res.get('runs', '?')} schedules, "
        f"{res.get('unique_traces', '?')} unique, "
        f"{res.get('injected_runs', 0)} injected, {findings} finding(s) "
        f"[{res['elapsed_s']:.1f}s]"
    )
    if findings and not expect_finding:
        print(f"[model_check]   FINDING: {res.get('what', '')!r} "
              f"(step {res.get('fail_step')})")
        write_artifact(artifact, drill, res, fault_build)
        return False, res
    if expect_finding and not findings:
        print(
            f"[model_check]   SENSITIVITY LOSS: the {label} build's seeded "
            f"failure was NOT rediscovered"
        )
        return False, res
    if expect_finding and findings:
        prefix = res.get("prefix_hex", "")
        shown = prefix if len(prefix) <= 64 else prefix[:64] + "..."
        print(f"[model_check]   rediscovered: {res.get('what', '')!r} "
              f"(minimal prefix {len(prefix) // 2}B {shown!r})")
        # expected findings still land an artifact: the nightly deep
        # lane uploads the minimal replayable schedule as its proof
        write_artifact(artifact, drill, res, fault_build)
    return True, res


def replay(path: str) -> int:
    with open(path, encoding="utf-8") as f:
        art = json.load(f)
    reject_unknown_drill(art["drill"])
    binary = BIN_FAULT if art.get("fault_build") else BIN
    args = [
        "--drill", art["drill"],
        "--schedule", art["schedule_hex"],
        "--seed", str(art.get("seed", 1)),
        "--pbound", str(art.get("pbound", 3)),
        "--ibound", str(art.get("ibound", 0)),
        "--max-steps", str(art.get("max_steps", 200000)),
        "--expect-finding",
    ]
    res = run_harness(binary, args, timeout_s=120)
    ok = res.get("exit_code") == 0 and res.get("failed") is True
    sched = art["schedule_hex"]
    shown = sched if len(sched) <= 64 else sched[:64] + "..."
    print(
        f"[model_check] replay {art['drill']} schedule {shown!r} "
        f"(ibound={art.get('ibound', 0)}): "
        + (f"reproduced ({res.get('what', '')!r})" if ok else "did NOT reproduce")
    )
    return 0 if ok else 1


def print_ci_table(rows: list[tuple[str, str, dict]]) -> None:
    """Per-drill schedule/time table closing every --ci sweep."""
    print("[model_check] --- CI sweep table ---")
    header = (
        f"{'drill':<24} {'build':<6} {'schedules':>9} {'unique':>7} "
        f"{'injected':>8} {'findings':>8} {'time':>7}"
    )
    print(f"[model_check] {header}")
    for drill, label, res in rows:
        print(
            "[model_check] "
            f"{drill:<24} {label:<6} {res.get('runs', 0):>9} "
            f"{res.get('unique_traces', 0):>7} "
            f"{res.get('injected_runs', 0):>8} "
            f"{res.get('findings', 0):>8} "
            f"{res.get('elapsed_s', 0.0):>6.1f}s"
        )


def main() -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--drill", help="explore one drill on the fixed build")
    ap.add_argument("--list", action="store_true", help="list drills")
    ap.add_argument("--ci", action="store_true",
                    help="CI gate: engine drills + sensitivity proofs")
    ap.add_argument("--runs", type=int, default=3000,
                    help="schedules per drill (default 3000)")
    ap.add_argument("--min-interleavings", type=int, default=10000,
                    help="--ci fails below this explored total (the "
                         "acceptance floor; no silent coverage caps)")
    ap.add_argument("--deep", action="store_true",
                    help="nightly lane: lift the per-drill CI run caps — "
                         "the wall budget (ACCL_DETSCHED_BUDGET / "
                         "--budget-s) becomes the only bound")
    ap.add_argument("--seed", type=int, default=3)
    ap.add_argument("--pbound", type=int, default=3,
                    help="preemption bound per schedule")
    ap.add_argument("--ibound", type=int, default=None,
                    help="timeout injections per run (default: per-drill "
                         "policy — sub-comm drills 1, others 0)")
    ap.add_argument("--max-steps", type=int, default=200000,
                    help="scheduling-step budget per run (livelock guard)")
    ap.add_argument("--budget-s", type=float,
                    default=float(os.environ.get("ACCL_DETSCHED_BUDGET", 240)),
                    help="wall-clock budget per drill sweep (default 240, "
                         "or the ACCL_DETSCHED_BUDGET env — the nightly "
                         "deep lane's knob)")
    ap.add_argument("--artifact", default="model_check_failure.json",
                    help="failing-schedule artifact path")
    ap.add_argument("--replay", default="",
                    help="replay a failure artifact instead of exploring")
    ap.add_argument("--guide", default="",
                    help="with --drill: artifact whose recorded trace seeds "
                         "the DFS (replay the prefix, explore the suffix)")
    ap.add_argument("--fault-build", action="store_true",
                    help="run --drill against the seeded-fault build")
    ap.add_argument("--expect-finding", action="store_true",
                    help="with --drill: exit 0 iff a finding IS discovered")
    ap.add_argument("--no-build", action="store_true",
                    help="assume the harness binaries are current")
    ap.add_argument("--verbose", action="store_true")
    opts = ap.parse_args()

    if not opts.no_build:
        build(opts.verbose)

    if opts.list:
        subprocess.run([BIN, "--list"])
        return 0

    if opts.replay:
        return replay(opts.replay)

    if opts.drill:
        reject_unknown_drill(opts.drill)
        guide_hex = ""
        if opts.guide:
            with open(opts.guide, encoding="utf-8") as f:
                art = json.load(f)
            guide_hex = art.get("full_trace_hex") or art.get("schedule_hex", "")
        ok, _ = explore_drill(
            opts.drill, opts.runs, opts.seed, opts.pbound, opts.max_steps,
            opts.budget_s, opts.artifact, fault_build=opts.fault_build,
            expect_finding=opts.expect_finding, ibound=opts.ibound,
            guide_hex=guide_hex,
        )
        return 0 if ok else 1

    if opts.ci:
        total = 0
        all_ok = True
        rows: list[tuple[str, str, dict]] = []
        for drill in ENGINE_DRILLS:
            runs = (opts.runs if opts.deep
                    else min(opts.runs, CI_RUN_CAPS.get(drill, opts.runs)))
            ok, res = explore_drill(
                drill, runs, opts.seed, opts.pbound, opts.max_steps,
                opts.budget_s, opts.artifact, ibound=opts.ibound,
            )
            total += int(res.get("runs", 0))
            rows.append((drill, "fixed", res))
            all_ok = all_ok and ok
            if not ok:
                break
        if all_ok:
            # sensitivity, part 1: the seeded detach race must be
            # rediscovered by the fault build and hold clean on the fixed
            ok, res = explore_drill(
                SENSITIVITY_DRILL, max(opts.runs, 500), opts.seed,
                opts.pbound, opts.max_steps, opts.budget_s, opts.artifact,
                fault_build=True, expect_finding=True,
            )
            rows.append((SENSITIVITY_DRILL, "fault", res))
            all_ok = all_ok and ok
            ok, res = explore_drill(
                SENSITIVITY_DRILL, max(opts.runs, 500), opts.seed,
                opts.pbound, opts.max_steps, opts.budget_s, opts.artifact,
            )
            total += int(res.get("runs", 0))
            rows.append((SENSITIVITY_DRILL, "fixed", res))
            all_ok = all_ok and ok
        if all_ok:
            # sensitivity, part 2: the liveness invariant itself must be
            # able to fire — the seeded leak drill (a live token never
            # handed back) must end with the stuck-progress finding on
            # the FIXED build.  Cheap: the leak is schedule-independent,
            # so stop_on_first lands it on run one.
            ok, res = explore_drill(
                LIVENESS_DRILL, 50, opts.seed, opts.pbound, opts.max_steps,
                opts.budget_s, opts.artifact, expect_finding=True,
            )
            rows.append((LIVENESS_DRILL, "fixed", res))
            all_ok = all_ok and ok
        if all_ok:
            # sensitivity, part 3 (LAST, so its minimal schedule owns the
            # artifact path the deep lane uploads): the 8-rank sub-comm
            # wedge (the staged-segment rescue revert) must be
            # rediscovered under timeout injection — the timeout/resource
            # machinery itself is under test here, not just the hub drain
            ok, res = explore_drill(
                WEDGE_DRILL,
                opts.runs if opts.deep else CI_RUN_CAPS.get(WEDGE_DRILL, 400),
                opts.seed, opts.pbound, opts.max_steps, opts.budget_s,
                opts.artifact, fault_build=True, expect_finding=True,
            )
            rows.append((WEDGE_DRILL, "fault", res))
            all_ok = all_ok and ok
        if all_ok and total < opts.min_interleavings:
            # the acceptance floor is a guarantee, not a report: a
            # budget-truncated sweep must fail loudly, never pass green
            print(
                f"[model_check] CI sweep EXPLORED TOO LITTLE: {total} < "
                f"{opts.min_interleavings} interleavings (budget/runs too "
                f"low for this box)"
            )
            all_ok = False
        print_ci_table(rows)
        print(
            f"[model_check] CI sweep: {total} interleavings across the "
            f"engine drills, "
            + ("sensitivity proven, zero findings" if all_ok else "FAILED")
        )
        return 0 if all_ok else 1

    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
