#!/usr/bin/env python
"""Summarize / plot sweep CSVs.

Equivalent of the reference post-processing pair — parse_bench_results.py
(cycle-count CSVs) and Coyote run_scripts/plot.py (throughput/busbw
curves vs a baseline).

Usage:
  python scripts/parse_bench_results.py sweep.csv
  python scripts/parse_bench_results.py sweep.csv --collective allreduce
  python scripts/parse_bench_results.py sweep.csv --baseline other.csv
  python scripts/parse_bench_results.py sweep.csv --plot sweep.png
"""
from __future__ import annotations

import argparse
import csv
import statistics
import sys
from collections import defaultdict


def load(path: str) -> dict:
    """-> {(collective, count): {"bytes", "dur_us", "algbw", "busbw"}}
    with medians over repetitions."""
    acc = defaultdict(lambda: defaultdict(list))
    with open(path, newline="") as f:
        for row in csv.DictReader(f):
            key = (row["collective"], int(row["count"]))
            acc[key]["bytes"].append(int(row["bytes"]))
            acc[key]["dur_us"].append(float(row["duration_us"]))
            acc[key]["algbw"].append(float(row["algbw_GBps"]))
            acc[key]["busbw"].append(float(row["busbw_GBps"]))
    return {
        k: {
            "bytes": v["bytes"][0],
            "dur_us": statistics.median(v["dur_us"]),
            "algbw": statistics.median(v["algbw"]),
            "busbw": statistics.median(v["busbw"]),
        }
        for k, v in sorted(acc.items())
    }


def _fmt_bytes(n: int) -> str:
    for unit, div in (("GB", 1 << 30), ("MB", 1 << 20), ("KB", 1 << 10)):
        if n >= div:
            return f"{n / div:g}{unit}"
    return f"{n}B"


def report(data: dict, baseline: dict | None = None,
           collective: str | None = None, out=sys.stdout) -> None:
    colls = sorted({c for c, _ in data})
    if collective:
        colls = [c for c in colls if c == collective]
    for coll in colls:
        rows = [(cnt, st) for (c, cnt), st in data.items() if c == coll]
        print(f"\n== {coll} ==", file=out)
        hdr = f"{'size':>8} {'time(us)':>12} {'algbw GB/s':>12} {'busbw GB/s':>12}"
        if baseline:
            hdr += f" {'vs baseline':>12}"
        print(hdr, file=out)
        for cnt, st in rows:
            line = (f"{_fmt_bytes(st['bytes']):>8} {st['dur_us']:>12.2f} "
                    f"{st['algbw']:>12.3f} {st['busbw']:>12.3f}")
            if baseline:
                b = baseline.get((coll, cnt))
                line += (f" {st['busbw'] / b['busbw']:>11.2f}x"
                         if b and b["busbw"] > 0 else f" {'-':>12}")
            print(line, file=out)
        peak = max((st["busbw"] for _, st in rows), default=0.0)
        print(f"peak busbw: {peak:.3f} GB/s", file=out)


def plot(data: dict, path: str, baseline: dict | None = None) -> None:
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    colls = sorted({c for c, _ in data})
    fig, ax = plt.subplots(figsize=(8, 5))
    for coll in colls:
        pts = sorted((st["bytes"], st["busbw"])
                     for (c, _), st in data.items() if c == coll)
        line, = ax.plot([p[0] for p in pts], [p[1] for p in pts],
                        marker="o", ms=3, label=coll)
        if baseline:
            bpts = sorted((st["bytes"], st["busbw"])
                          for (c, _), st in baseline.items() if c == coll)
            if bpts:
                # baseline dashed in the same color as its collective
                ax.plot([p[0] for p in bpts], [p[1] for p in bpts],
                        ls="--", lw=1, alpha=0.5, color=line.get_color(),
                        label=f"{coll} (baseline)")
    ax.set_xscale("log", base=2)
    ax.set_yscale("log")
    ax.set_xlabel("message size (bytes)")
    ax.set_ylabel("bus bandwidth (GB/s)")
    ax.legend(fontsize=8)
    ax.grid(True, which="both", alpha=0.3)
    fig.tight_layout()
    fig.savefig(path, dpi=120)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("csv")
    ap.add_argument("--collective")
    ap.add_argument("--baseline", help="second CSV to compare busbw against")
    ap.add_argument("--plot", help="write a busbw-vs-size PNG")
    args = ap.parse_args()

    data = load(args.csv)
    base = load(args.baseline) if args.baseline else None
    report(data, base, args.collective)
    if args.plot:
        plot(data, args.plot, base)
        print(f"\nwrote {args.plot}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
