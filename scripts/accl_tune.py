#!/usr/bin/env python
"""accl_tune: measure, persist, and verify a collective selection table.

The r16 autotuner CLI (accl_tpu/tuning): sweeps (collective, dtype,
size-bucket, algorithm) lanes through the bench sweep harness on an emu
or TPU world, writes the versioned JSON selection table
``ACCL.initialize`` consumes via ``ACCL_TUNE_TABLE``, and (--record)
re-measures static-vs-tuned per cell — interleaved, best-of, with
unreproducible selections pruned back to static — emitting the
``sweep_rNN_tuned_vs_static`` CSV/MD record the perf gate validates.

Usage:
  python scripts/accl_tune.py --ranks 4 --shape 2x2 --out tune_table.json
  python scripts/accl_tune.py --backend tpu --ranks 4 \\
      --out tune_table.json --record bench/results/sweep_r16_tuned_vs_static

The TPU rung claims the chip through the r16 fail-fast
(ACCL_TPU_CLAIM_TIMEOUT_S, default 60 s) and falls back to the CPU
rung, recording whichever succeeds.
"""
import argparse
import csv
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=4)
    ap.add_argument("--backend", choices=("emu", "tpu"), default="emu")
    ap.add_argument("--shape", default="",
                    help="fabric axis layout, e.g. 2x2 (default: "
                         "ACCL_FABRIC env / near-square factorization)")
    ap.add_argument("--collectives", default="",
                    help="comma list (default: the composable set + "
                         "reduce)")
    ap.add_argument("--pows", default="",
                    help="comma list of log2 element counts "
                         "(default 6,8,10,12,14,16)")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--dtypes", default="",
                    help="comma list of dtypes to sweep into ONE merged "
                         "per-dtype table, e.g. float32,bfloat16,float16 "
                         "(default: just --dtype; unswept dtypes are "
                         "served the float32 row at dispatch)")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--out", default="tune_table.json",
                    help="selection-table JSON path")
    ap.add_argument("--record", default="",
                    help="path PREFIX for the tuned-vs-static "
                         "verification record (.csv + .md written)")
    ap.add_argument("--no-demotion", action="store_true",
                    help="skip measured link-matrix axis demotion")
    args = ap.parse_args()

    # loaded/1-core boxes stall ranks past the reference 1 s receive
    # budget on big many-rank cells — widen the default like
    # tests/conftest.py (explicit env still wins)
    os.environ.setdefault("ACCL_DEFAULT_TIMEOUT", "30000000")

    # claim before anything imports jax (the fail-fast contract)
    from accl_tpu.bench.sweep import claim_platform

    if args.backend == "tpu":
        claimed = claim_platform("tpu")
        if claimed != "tpu":
            args.backend = "emu"
            print("[accl_tune] recording the emu/CPU rung instead",
                  file=sys.stderr)

    from accl_tpu.tuning import TuneConfig, autotune
    from accl_tpu.utils.topology import parse_shape

    shape = parse_shape(args.shape) if args.shape else None
    kwargs = {}
    if args.collectives:
        kwargs["collectives"] = tuple(args.collectives.split(","))
    pows = (tuple(int(p) for p in args.pows.split(","))
            if args.pows else (6, 8, 10, 12, 14, 16))
    dtypes = (tuple(d.strip() for d in args.dtypes.split(",") if d.strip())
              if args.dtypes else (args.dtype,))
    cfg = TuneConfig(count_pows=pows, dtype=dtypes[0],
                     repetitions=args.reps, shape=shape,
                     measured_demotion=not args.no_demotion, **kwargs)

    if args.backend == "tpu":
        # the probe in claim_platform released the chip; the REAL
        # claim below gets the same fail-fast watchdog (another
        # process can wedge the chip in the probe->claim window)
        from accl_tpu.bench.sweep import claim_watchdog

        guard = claim_watchdog(
            "accl_tune", advice="re-run with --backend emu for the "
            "CPU rung")
        from accl_tpu.backends.tpu import TpuWorld

        world = TpuWorld(args.ranks)
        if guard is not None:
            guard.cancel()
    else:
        from accl_tpu.backends.emu import EmuWorld

        world = EmuWorld(args.ranks, devmem_bytes=256 << 20,
                         n_egr_rx_bufs=64, max_eager_size=16384,
                         max_rendezvous_size=64 << 20)

    t0 = time.perf_counter()
    try:
        print(f"[accl_tune] tuning {args.ranks} ranks on "
              f"{args.backend} ({len(pows)} sizes x "
              f"{len(cfg.collectives)} collectives x "
              f"{len(dtypes)} dtypes)")
        table = None
        from dataclasses import replace
        for d in dtypes:
            cfg_d = replace(cfg, dtype=d)
            if len(dtypes) > 1:
                print(f"[accl_tune] dtype lane: {d}")
            t = autotune.tune(world, cfg_d, log=print)
            if table is None:
                table = t
            else:
                # merged per-dtype table: one artifact, one cell per
                # (collective, dtype, bucket) — dispatch falls back to
                # the float32 row for dtypes never swept here
                table.entries.update(t.entries)
                table._dtypes = None
        table.world["dtypes"] = list(dtypes)
        rows = []
        if args.record:
            print("[accl_tune] verifying tuned vs static (interleaved, "
                  "pruning unreproducible selections)")
            for d in dtypes:
                rows.extend(autotune.compare(
                    world, table, replace(cfg, dtype=d), log=print))
    finally:
        world.close()

    table.save(args.out)
    print(f"[accl_tune] table: {args.out} ({len(table.entries)} cells, "
          f"{time.perf_counter() - t0:.0f}s)")

    if args.record:
        csv_path = f"{args.record}.csv"
        with open(csv_path, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=[
                "collective", "dtype", "size_bucket", "count", "bytes",
                "algorithm", "static_busbw_GBps", "tuned_busbw_GBps",
                "ratio"])
            w.writeheader()
            w.writerows(rows)
        wins = sum(1 for r in rows if r["ratio"] >= 1.15)
        slow = [r for r in rows if r["ratio"] < 1.0 / 1.05]
        tuned_cells = sum(1 for r in rows if r["algorithm"] != "static")
        with open(f"{args.record}.md", "w") as f:
            f.write(
                f"# Tuned vs static sweep record\n\n"
                f"- world: {args.ranks} ranks, {args.backend} backend, "
                f"fabric {table.world.get('shape')}, dtypes "
                f"{','.join(dtypes)}\n"
                f"- table: {os.path.basename(args.out)} "
                f"({len(table.entries)} cells, "
                f"{tuned_cells} non-static selections after "
                f"verification pruning)\n"
                f"- wins >= 1.15x busbw vs static: {wins} cells\n"
                f"- cells > 1.05x slower than static: {len(slow)} "
                f"(gate: must be 0)\n\n"
                f"| collective | dtype | bucket | algorithm | "
                f"static GB/s | tuned GB/s | ratio |\n"
                f"|---|---|---|---|---|---|---|\n")
            for r in rows:
                f.write(f"| {r['collective']} | {r['dtype']} | "
                        f"{r['size_bucket']} | "
                        f"{r['algorithm']} | {r['static_busbw_GBps']} "
                        f"| {r['tuned_busbw_GBps']} | {r['ratio']}x "
                        f"|\n")
        print(f"[accl_tune] record: {csv_path} ({wins} wins >= 1.15x, "
              f"{len(slow)} cells slower than 1/1.05)")
        if slow:
            print("[accl_tune] FAIL: the verified record still has "
                  "slower-than-static cells", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
