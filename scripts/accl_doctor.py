#!/usr/bin/env python
"""accl_doctor: merge per-rank flight-recorder dumps and diagnose
cross-rank failure modes — the offline half of the hang/desync
watchdog (accl_tpu/observability/flight.py merge_flight_dumps).

Feed it per-rank dump files (ACCL.dump_flight_recorder(path),
SIGUSR1's ACCL_FLIGHT_DUMP, one per process of a multihost run) or an
already-merged watchdog dump; it prints a human report of

- HANGS    — stuck gang instances: which ranks arrived, which are
             missing, and the head-of-queue call each missing rank is
             actually blocked on;
- DESYNCS  — the first seq position where ranks issued different
             collectives on one communicator (order/shape/dtype
             mismatch);
- STRAGGLERS — ranks whose completed-gang progress trails the lead.

Usage: python scripts/accl_doctor.py dump_rank*.json [--out merged.json]
       [--fail-on-findings]

Exit code: 0 on a clean bill of health (or findings with the default
flags), 1 with --fail-on-findings when any hang/desync was found.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from accl_tpu.observability.flight import merge_flight_dumps  # noqa: E402


def fmt_record(rec) -> str:
    if rec is None:
        return "idle (no in-flight call)"
    return (f"seq={rec['seq']} {rec['collective']} comm={rec['comm']} "
            f"count={rec['count']} {rec['dtype']} "
            f"state={rec['state']} lane={rec['lane']} "
            f"age={rec['age_us'] / 1e3:.1f}ms")


def report(doc: dict, out=sys.stdout) -> bool:
    """Print the human report; returns True when findings exist."""
    an = doc["analysis"]
    w = out.write
    w(f"accl_doctor: {doc['nranks']} rank(s), "
      f"{sum(len(r['records']) for r in doc['ranks'])} record(s)\n")
    for r in doc["ranks"]:
        inflight = [x for x in r["records"]
                    if x["state"] not in ("complete", "failed")]
        w(f"  rank {r['rank']}: last_completed_seq="
          f"{r['last_completed_seq']}, {len(inflight)} in flight\n")

    for h in an["hangs"]:
        w(f"\nHANG: {h['collective']} (comm {h['comm']}, tag {h['tag']}, "
          f"count {h['count']}, {h['dtype']}) — stuck "
          f"{h['oldest_age_us'] / 1e6:.1f}s\n")
        w(f"  arrived ranks: {h['arrived']}\n")
        w(f"  MISSING ranks: {h['missing']}\n")
        for r, rec in h["missing_blocked_on"].items():
            w(f"    rank {r} blocked on: {fmt_record(rec)}\n")
        w(f"  last completed seq per rank: {h['last_completed_seq']}\n")

    for d in an["desyncs"]:
        w(f"\nDESYNC on comm {d['comm']} at gang index {d['index']} — "
          f"ranks disagree on the collective issued:\n")
        for r, s in sorted(d["per_rank"].items(), key=lambda kv: int(kv[0])):
            if s is None:
                w(f"    rank {r}: <no call at this position>\n")
            else:
                w(f"    rank {r}: seq={s['seq']} {s['collective']} "
                  f"tag={s['tag']} count={s['count']} {s['dtype']}\n")

    for s in an["stragglers"]:
        w(f"\nSTRAGGLER(s) on comm {s['comm']}: lead rank completed "
          f"{s['completed_lead']} gang call(s); behind: {s['behind']}\n")

    for comm in an.get("truncated_comms", []):
        w(f"\nnote: order analysis skipped on comm {comm} — a rank's "
          f"flight ring wrapped (uneven eviction would fake desyncs; "
          f"raise ACCL_FLIGHT_CAP for full-history analysis)\n")

    if an["ok"] and not an["stragglers"]:
        w("\nno hangs, desyncs or stragglers — all ranks in sync\n")
    return not an["ok"]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("dumps", nargs="+",
                    help="per-rank flight dump JSON files (or one "
                         "merged/watchdog dump)")
    ap.add_argument("--out", default="",
                    help="also write the merged+analyzed JSON here")
    ap.add_argument("--fail-on-findings", action="store_true",
                    help="exit 1 when any hang or desync is detected "
                         "(CI / alerting mode)")
    args = ap.parse_args()

    doc = merge_flight_dumps(args.dumps, out_path=args.out or None)
    findings = report(doc)
    if args.out:
        print(f"merged dump written to {args.out}")
    return 1 if (findings and args.fail_on_findings) else 0


if __name__ == "__main__":
    sys.exit(main())
