#!/usr/bin/env python
"""accl_doctor: merge per-rank flight-recorder dumps and diagnose
cross-rank failure modes — the offline half of the hang/desync
watchdog (accl_tpu/observability/flight.py merge_flight_dumps).

Feed it per-rank dump files (ACCL.dump_flight_recorder(path),
SIGUSR1's ACCL_FLIGHT_DUMP, one per process of a multihost run) or an
already-merged watchdog dump; it prints a human report of

- HANGS    — stuck gang instances: which ranks arrived, which are
             missing, and the head-of-queue call each missing rank is
             actually blocked on;
- DESYNCS  — the first seq position where ranks issued different
             collectives on one communicator (order/shape/dtype
             mismatch);
- STRAGGLERS — ranks whose completed-gang progress trails the lead.

Live mode (``--live host:port``) scrapes a RUNNING world's r8
exporter endpoints (``/metrics``, ``/healthz``, ``/flight`` on
``ACCL_METRICS_PORT``) and prints the same merged report plus the
health/membership summary — no SIGUSR1, no dump-file collection:

    python scripts/accl_doctor.py --live 127.0.0.1:9100

Usage: python scripts/accl_doctor.py dump_rank*.json [--out merged.json]
       [--fail-on-findings]
       python scripts/accl_doctor.py --live host:port [--out merged.json]

Exit code: 0 on a clean bill of health (or findings with the default
flags), 1 with --fail-on-findings when any hang/desync was found.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from accl_tpu.observability.flight import merge_flight_dumps  # noqa: E402


def fmt_record(rec) -> str:
    if rec is None:
        return "idle (no in-flight call)"
    return (f"seq={rec['seq']} {rec['collective']} comm={rec['comm']} "
            f"count={rec['count']} {rec['dtype']} "
            f"state={rec['state']} lane={rec['lane']} "
            f"age={rec['age_us'] / 1e3:.1f}ms")


def report(doc: dict, out=sys.stdout) -> bool:
    """Print the human report; returns True when findings exist."""
    an = doc["analysis"]
    w = out.write
    w(f"accl_doctor: {doc['nranks']} rank(s), "
      f"{sum(len(r['records']) for r in doc['ranks'])} record(s)\n")
    for r in doc["ranks"]:
        inflight = [x for x in r["records"]
                    if x["state"] not in ("complete", "failed")]
        w(f"  rank {r['rank']}: last_completed_seq="
          f"{r['last_completed_seq']}, {len(inflight)} in flight\n")

    for h in an["hangs"]:
        w(f"\nHANG: {h['collective']} (comm {h['comm']}, tag {h['tag']}, "
          f"count {h['count']}, {h['dtype']}) — stuck "
          f"{h['oldest_age_us'] / 1e6:.1f}s\n")
        w(f"  arrived ranks: {h['arrived']}\n")
        w(f"  MISSING ranks: {h['missing']}\n")
        for r, rec in h["missing_blocked_on"].items():
            w(f"    rank {r} blocked on: {fmt_record(rec)}\n")
        w(f"  last completed seq per rank: {h['last_completed_seq']}\n")

    for d in an["desyncs"]:
        w(f"\nDESYNC on comm {d['comm']} at gang index {d['index']} — "
          f"ranks disagree on the collective issued:\n")
        for r, s in sorted(d["per_rank"].items(), key=lambda kv: int(kv[0])):
            if s is None:
                w(f"    rank {r}: <no call at this position>\n")
            else:
                w(f"    rank {r}: seq={s['seq']} {s['collective']} "
                  f"tag={s['tag']} count={s['count']} {s['dtype']}\n")

    for s in an["stragglers"]:
        w(f"\nSTRAGGLER(s) on comm {s['comm']}: lead rank completed "
          f"{s['completed_lead']} gang call(s); behind: {s['behind']}\n")

    for comm in an.get("truncated_comms", []):
        w(f"\nnote: order analysis skipped on comm {comm} — a rank's "
          f"flight ring wrapped (uneven eviction would fake desyncs; "
          f"raise ACCL_FLIGHT_CAP for full-history analysis)\n")

    # r13: happens-before lifecycle suite (fence-stale replays,
    # completions after teardown, cross-rank lock-order inversions)
    from accl_tpu.analysis.checks import check_flight_lifecycle

    lifecycle = check_flight_lifecycle(doc)
    for f in lifecycle:
        w(f"\nLIFECYCLE {f.render()}\n")

    if an["ok"] and not an["stragglers"] and not lifecycle:
        w("\nno hangs, desyncs, stragglers or lifecycle violations — "
          "all ranks in sync\n")
    return (not an["ok"]) or any(f.severity == "error" for f in lifecycle)


def scrape_live(target: str, timeout_s: float = 10.0) -> dict:
    """Fetch /flight, /healthz and /metrics from a running world's
    exporter (observability/health.py start_exporter).  Returns
    {"flight": merged-dump-doc, "healthz": dict, "metrics": text}."""
    import urllib.request

    if "://" not in target:
        target = f"http://{target}"
    target = target.rstrip("/")
    out = {}
    for path in ("flight", "healthz", "metrics"):
        try:
            with urllib.request.urlopen(f"{target}/{path}",
                                        timeout=timeout_s) as resp:
                body = resp.read()
        except OSError as e:
            raise SystemExit(
                f"accl_doctor: cannot scrape {target}/{path}: {e} — is "
                f"the world running with ACCL_METRICS_PORT set?")
        out[path] = (body.decode() if path == "metrics"
                     else json.loads(body))
    # /slo (r20) is NON-FATAL: a pre-r20 world has no such route, and
    # this doctor must still produce its report against it
    try:
        with urllib.request.urlopen(f"{target}/slo",
                                    timeout=timeout_s) as resp:
            out["slo"] = json.loads(resp.read())
    except (OSError, ValueError):
        out["slo"] = None
    return out


def report_live(scraped: dict, out=sys.stdout) -> bool:
    """Health + membership + engine-telemetry summary in front of the
    merged report."""
    from accl_tpu.observability.metrics import metric_help_for

    w = out.write
    hz = scraped["healthz"]
    w(f"live world health: {hz.get('health', '?')} "
      f"(accl_health={hz.get('accl_health', '?')}, watchdog fires="
      f"{hz.get('watchdog_fires', 0)}, checks="
      f"{hz.get('watchdog_checks', 0)})\n")
    # surface the membership/recovery counter families from /metrics
    interesting = ("accl_membership_", "accl_recovery_",
                   "accl_join_wait_us_count", "accl_health ",
                   "accl_sentinel_")
    lines = [ln for ln in scraped["metrics"].splitlines()
             if ln and not ln.startswith("#")
             and any(ln.startswith(p) for p in interesting)]
    if lines:
        w("membership / recovery metrics:\n")
        for ln in lines:
            w(f"  {ln}\n")
    # engine telemetry families (r14 sampler: ACCL_TELEMETRY_INTERVAL_MS
    # > 0 on the scraped world).  A family this doctor build does not
    # know — a NEWER world exporting fields past our schema — renders as
    # unrecognized instead of crashing the report.
    engine_lines = [ln for ln in scraped["metrics"].splitlines()
                    if ln and not ln.startswith("#")
                    and ln.startswith("accl_engine_")]
    if engine_lines:
        w("engine telemetry (native stats sampler):\n")
        for ln in engine_lines:
            name = ln.split("{")[0].split(" ")[0]
            family = name
            for suffix in ("_total", "_bucket", "_sum", "_count"):
                if family.endswith(suffix):
                    family = family[: -len(suffix)]
                    break
            known = metric_help_for(family) or metric_help_for(name)
            tag = "" if known else "  [unrecognized (newer world?)]"
            w(f"  {ln}{tag}\n")
    else:
        w("engine telemetry: none exported (set "
          "ACCL_TELEMETRY_INTERVAL_MS>0 on the world to sample the "
          "native engine stats plane)\n")
    # per-tenant SLO plane (r20): the /slo body when the scraped world
    # has a tracker armed, plus the tenant/* metric families.  Same
    # forward-compatibility stance as the engine block: a family this
    # doctor build does not know renders as unrecognized, never fatal.
    slo = scraped.get("slo")
    if slo and slo.get("tenants"):
        w(f"per-tenant SLO ({len(slo.get('specs', []))} spec(s), "
          f"{slo.get('checks', 0)} check sweep(s)):\n")
        for tenant in sorted(slo["tenants"]):
            t = slo["tenants"][tenant]
            w(f"  tenant {tenant}: "
              f"{str(t.get('verdict', '?')).upper()} — budget "
              f"remaining {t.get('budget_remaining', 1.0) * 100:.1f}%"
              f" over {len(t.get('objectives', []))} objective(s)\n")
    tenant_lines = [ln for ln in scraped["metrics"].splitlines()
                    if ln and not ln.startswith("#")
                    and (ln.startswith("accl_tenant_")
                         or ln.startswith("accl_slo_")
                         or ln.startswith("accl_health{tenant="))]
    if tenant_lines:
        w("per-tenant metric families:\n")
        for ln in tenant_lines:
            name = ln.split("{")[0].split(" ")[0]
            family = name
            for suffix in ("_total", "_bucket", "_sum", "_count"):
                if family.endswith(suffix):
                    family = family[: -len(suffix)]
                    break
            known = metric_help_for(family) or metric_help_for(name)
            tag = "" if known else "  [unrecognized (newer world?)]"
            w(f"  {ln}{tag}\n")
    w("\n")
    return report(scraped["flight"], out)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("dumps", nargs="*",
                    help="per-rank flight dump JSON files (or one "
                         "merged/watchdog dump)")
    ap.add_argument("--live", default="",
                    help="scrape a running world's exporter instead of "
                         "reading dump files (host:port of "
                         "ACCL_METRICS_PORT)")
    ap.add_argument("--out", default="",
                    help="also write the merged+analyzed JSON here")
    ap.add_argument("--fail-on-findings", action="store_true",
                    help="exit 1 when any hang or desync is detected "
                         "(CI / alerting mode)")
    args = ap.parse_args()

    if bool(args.dumps) == bool(args.live):
        ap.error("pass either dump files or --live host:port")
    if args.live:
        scraped = scrape_live(args.live)
        if args.out:
            with open(args.out, "w") as f:
                json.dump(scraped["flight"], f, indent=1)
        findings = report_live(scraped)
    else:
        doc = merge_flight_dumps(args.dumps, out_path=args.out or None)
        findings = report(doc)
    if args.out:
        print(f"merged dump written to {args.out}")
    return 1 if (findings and args.fail_on_findings) else 0


if __name__ == "__main__":
    sys.exit(main())
