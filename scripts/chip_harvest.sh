#!/bin/bash
# Round-long chip harvester: alternate the BENCH stage ladder and the
# chip-session sweep/lane artifacts against a blocked chip claim.  Both
# knockers are stage-resumable (bench.py via ACCL_BENCH_RUN_ID-pinned
# ledger; chip_session.py via its artifact files), so every brief claim
# window banks progress and the loop exits once everything is complete.
#
# Usage: chip_harvest.sh [max_cycles] [run_id]
set -u
cd "$(dirname "$0")/.."
MAX=${1:-30}
RUN_ID=${2:-r05-bank}
NAP=180

bench_complete() {
  python - <<EOF
import json, sys
from bench import ALL_STAGES, _ledger_path  # bench.py owns both
try:
    with open(_ledger_path("$RUN_ID")) as f:
        led = json.load(f)
    stages = set(led.get("stages", {}))
    ok = (led.get("run_id") == "$RUN_ID"
          and set(ALL_STAGES) <= stages)
except Exception:
    ok = False
sys.exit(0 if ok else 1)
EOF
}

for i in $(seq 1 "$MAX"); do
  B_DONE=1; S_DONE=1
  bench_complete || B_DONE=0
  python scripts/chip_session.py --check || S_DONE=0
  if [ "$B_DONE" = 1 ] && [ "$S_DONE" = 1 ]; then
    echo "[harvest] all chip artifacts complete after $((i - 1)) cycles"
    exit 0
  fi
  echo "[harvest] cycle $i/$MAX (bench=$B_DONE sweep=$S_DONE)"
  if [ "$B_DONE" = 0 ]; then
    ACCL_BENCH_RUN_ID="$RUN_ID" ACCL_BENCH_TPU_TIMEOUT_S=420 \
      timeout 900 python bench.py >/dev/null 2>>/tmp/harvest_bench.log
    echo "[harvest] bench pass rc=$?"
  fi
  if [ "$S_DONE" = 0 ]; then
    timeout 900 python scripts/chip_session.py 2>>/tmp/harvest_session.log
    echo "[harvest] session pass rc=$?"
  fi
  sleep "$NAP"
done
echo "[harvest] gave up after $MAX cycles"
exit 1
