#!/usr/bin/env python3
"""Clang Thread Safety Analysis lane for the native engine (`make tsa`).

Builds every native translation unit under clang with
``-Wthread-safety -Wthread-safety-beta`` and **fails on any
thread-safety diagnostic** — the ``-Werror=thread-safety`` wall the
annotation macros in ``native/src/common.hpp`` feed.  Two configs run
per TU: the plain build and the ``-DACCL_DETSCHED`` build (the model
checker's scheduler hooks change which code paths exist, so both must
hold the discipline).

Frontend selection, in order:

1. a real ``clang++`` (``$CLANGXX`` or PATH): compiled with
   ``-fsyntax-only -Werror=thread-safety``, the canonical CI path;
2. the ``libclang`` Python bindings (pip wheel): the same clang Sema —
   including the full thread-safety analysis — driven in-process, for
   boxes that carry the wheel but no clang driver.  GCC's builtin
   include directory substitutes for clang's resource dir.

Zero-waiver policy (the r13 sanitizer-suppression rule applied to
static analysis): ``ACCL_NO_TSA`` must not appear anywhere under
``native/src`` except its definition in common.hpp — this script greps
it banned before running the frontend, so the wall cannot be
quietly waived from inside the code it checks.

Exit codes: 0 clean, 1 thread-safety findings (or compile errors),
2 no usable clang frontend.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "native", "src")

# (translation unit, extra flags) — both lock-discipline configs
CONFIGS: list[tuple[str, tuple[str, ...]]] = [
    ("engine.cpp", ()),
    ("transport.cpp", ()),
    ("capi.cpp", ()),
    ("engine.cpp", ("-DACCL_DETSCHED",)),
    ("transport.cpp", ("-DACCL_DETSCHED",)),
    ("capi.cpp", ("-DACCL_DETSCHED",)),
]

BASE_FLAGS = [
    "-std=c++17",
    "-x",
    "c++",
    "-Wthread-safety",
    "-Wthread-safety-beta",
]


def check_no_waivers(src_dir: str) -> list[str]:
    """ACCL_NO_TSA is banned under accl:: — only its #define may exist."""
    offenders = []
    for name in sorted(os.listdir(src_dir)):
        if not name.endswith((".hpp", ".cpp")):
            continue
        path = os.path.join(src_dir, name)
        with open(path, encoding="utf-8") as f:
            for lineno, line in enumerate(f, 1):
                if "ACCL_NO_TSA" not in line:
                    continue
                stripped = line.strip()
                if stripped.startswith("//"):
                    continue  # prose mentioning the macro is not a waiver
                # the definition site lives in common.hpp
                if name == "common.hpp" and stripped.startswith(
                    "#define ACCL_NO_TSA"
                ):
                    continue
                offenders.append(f"{name}:{lineno}: {stripped}")
    return offenders


def gcc_builtin_include() -> str | None:
    gcc = shutil.which("gcc") or shutil.which("cc")
    if not gcc:
        return None
    try:
        out = subprocess.run(
            [gcc, "-print-file-name=include"],
            capture_output=True,
            text=True,
            check=True,
        ).stdout.strip()
    except (OSError, subprocess.CalledProcessError):
        return None
    return out if os.path.isdir(out) else None


def find_clangxx() -> str | None:
    env = os.environ.get("CLANGXX")
    if env and shutil.which(env):
        return env
    for cand in ("clang++", "clang++-18", "clang++-17", "clang++-16",
                 "clang++-15", "clang++-14"):
        if shutil.which(cand):
            return cand
    return None


def run_real_clang(clangxx: str, verbose: bool) -> int:
    findings = 0
    for tu, extra in CONFIGS:
        cmd = [
            clangxx,
            *BASE_FLAGS,
            "-Werror=thread-safety",
            "-fsyntax-only",
            *extra,
            os.path.join(SRC, tu),
        ]
        if verbose:
            print("+", " ".join(cmd))
        proc = subprocess.run(cmd, capture_output=True, text=True)
        label = f"{tu} {' '.join(extra) or '(plain)'}"
        if proc.returncode != 0:
            findings += 1
            print(f"[tsa] FAIL {label}")
            sys.stdout.write(proc.stderr)
        else:
            print(f"[tsa] ok   {label}")
    return findings


def run_libclang(verbose: bool) -> int:
    try:
        import clang.cindex as cindex
    except ImportError:
        return -1
    try:
        index = cindex.Index.create()
    except Exception as exc:  # pragma: no cover - env-specific
        print(f"[tsa] libclang unusable: {exc}", file=sys.stderr)
        return -1
    flags = list(BASE_FLAGS)
    builtin = gcc_builtin_include()
    if builtin:
        flags += ["-isystem", builtin]
    findings = 0
    for tu, extra in CONFIGS:
        args = flags + list(extra)
        label = f"{tu} {' '.join(extra) or '(plain)'}"
        if verbose:
            print("+ libclang", " ".join(args), tu)
        unit = index.parse(os.path.join(SRC, tu), args=args)
        bad = []
        for diag in unit.diagnostics:
            # severity 3+ = hard error; any -Wthread-safety* warning is
            # promoted to error (the -Werror=thread-safety contract)
            opt = diag.option or ""
            if diag.severity >= 3 or opt.startswith("-Wthread-safety"):
                bad.append(diag)
        if bad:
            findings += 1
            print(f"[tsa] FAIL {label}")
            for d in bad:
                loc = d.location
                where = (
                    f"{loc.file}:{loc.line}:{loc.column}" if loc.file else "?"
                )
                print(f"  {where}: {d.spelling} [{d.option or 'error'}]")
        else:
            print(f"[tsa] ok   {label}")
    return findings


def emit_compile_commands(path: str) -> None:
    """Mirror of the Makefile's compile_commands target, importable by
    clangd/clang-tidy and any external TSA driver."""
    entries = []
    for tu, extra in CONFIGS:
        if extra:
            continue  # one canonical entry per file
        entries.append(
            {
                "directory": os.path.join(REPO, "native"),
                "file": os.path.join(SRC, tu),
                "arguments": [
                    "clang++",
                    *BASE_FLAGS,
                    "-c",
                    os.path.join(SRC, tu),
                ],
            }
        )
    with open(path, "w", encoding="utf-8") as f:
        json.dump(entries, f, indent=2)
    print(f"[tsa] wrote {path}")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--verbose", action="store_true")
    ap.add_argument(
        "--emit-compile-commands",
        metavar="PATH",
        help="also write a compile_commands.json for the native TUs",
    )
    ap.add_argument(
        "--emit-only",
        metavar="PATH",
        help="write compile_commands.json and exit (no analysis)",
    )
    opts = ap.parse_args()

    if opts.emit_only:
        emit_compile_commands(opts.emit_only)
        return 0

    offenders = check_no_waivers(SRC)
    if offenders:
        print("[tsa] ACCL_NO_TSA waivers are banned under native/src:")
        for o in offenders:
            print("  " + o)
        return 1

    if opts.emit_compile_commands:
        emit_compile_commands(opts.emit_compile_commands)

    clangxx = find_clangxx()
    if clangxx:
        print(f"[tsa] frontend: {clangxx}")
        findings = run_real_clang(clangxx, opts.verbose)
    else:
        print("[tsa] frontend: libclang python bindings")
        findings = run_libclang(opts.verbose)
        if findings < 0:
            print(
                "[tsa] no clang++ on PATH and no usable libclang wheel — "
                "install either to run the thread-safety wall",
                file=sys.stderr,
            )
            return 2

    if findings:
        print(f"[tsa] {findings} translation-unit config(s) FAILED")
        return 1
    print("[tsa] clean: zero thread-safety findings, zero waivers")
    return 0


if __name__ == "__main__":
    sys.exit(main())
