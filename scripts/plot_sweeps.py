"""Render the busbw-vs-size comparison plots from the captured sweeps.

The reference ships MPI-comparison plots from its Coyote cluster bench
(test/host/Coyote notebooks + parse_bench_results.py); this renders the
equivalent artifacts from bench/results/*.csv:

  busbw_rungs_r{N}.svg    allreduce busbw vs size per transport rung
                          (emu inproc, datagram, RDMA queue pairs,
                          TPU-backend gang) with
                          the reference's CCLO datapath anchor line
  collectives_r{N}.svg    per-collective busbw vs size on the emulator
  pipeline_ab_r{N}.svg    egress pipelining depth 1 vs 3 latency

CPU-rung numbers are emulator numbers, clearly labeled — the plots show
SHAPE (linearity, protocol switchover) and deltas, not hardware rates.

Usage: python scripts/plot_sweeps.py [--round 3]
"""
from __future__ import annotations

import argparse
import csv
import os
from collections import defaultdict

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CCLO_ANCHOR_GBPS = 16.0  # reference CCLO datapath ceiling (BASELINE.md)


def load(path):
    rows = defaultdict(lambda: defaultdict(list))  # coll -> bytes -> busbw
    with open(path) as f:
        for row in csv.DictReader(f):
            rows[row["collective"]][int(row["bytes"])].append(
                float(row["busbw_GBps"]))
    return {
        coll: sorted((b, max(v)) for b, v in by_size.items())
        for coll, by_size in rows.items()
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--round", type=int, default=4)
    args = ap.parse_args()
    tag = f"r{args.round:02d}"
    outdir = os.path.join(ROOT, "bench", "results")

    rungs = {
        "emulator (inproc)": f"sweep_emu_{tag}.csv",
        "datagram rung (MTU 512 + reorder)": f"sweep_dgram_{tag}.csv",
        "RDMA rung (queue pairs)": f"sweep_rdma_{tag}.csv",
        "TPU backend gang (8 virtual devices)": f"sweep_tpu8_{tag}.csv",
    }
    f16_rungs = {
        "emulator fp16": f"sweep_emu_f16_{tag}.csv",
        "datagram rung fp16": f"sweep_dgram_f16_{tag}.csv",
        "RDMA rung fp16": f"sweep_rdma_f16_{tag}.csv",
        "TPU backend gang fp16": f"sweep_tpu8_f16_{tag}.csv",
    }

    # 1. allreduce busbw per rung (fp32 solid, fp16 dashed)
    fig, ax = plt.subplots(figsize=(7, 4.5))
    for rung_map, style in ((rungs, dict(marker="o", ms=3)),
                            (f16_rungs, dict(marker="x", ms=3, ls="--",
                                             lw=1))):
        for label, fname in rung_map.items():
            path = os.path.join(outdir, fname)
            if not os.path.exists(path):
                continue
            data = load(path).get("allreduce", [])
            if data:
                xs, ys = zip(*data)
                ax.plot(xs, ys, label=label, **style)
    ax.axhline(CCLO_ANCHOR_GBPS, ls="--", c="gray", lw=1,
               label="reference CCLO datapath (16 GB/s)")
    ax.set_xscale("log", base=2)
    ax.set_yscale("log")
    ax.set_xlabel("message size (bytes)")
    ax.set_ylabel("busbw (GB/s, nccl convention)")
    ax.set_title(f"allreduce busbw vs size per rung (round {args.round}; "
                 "CPU-rung numbers are emulator rates)")
    ax.grid(True, which="both", alpha=0.3)
    ax.legend(fontsize=8)
    fig.tight_layout()
    p = os.path.join(outdir, f"busbw_rungs_{tag}.svg")
    fig.savefig(p)
    print(f"wrote {p}")

    # 2. per-collective busbw on the emulator rung
    emu_path = os.path.join(outdir, f"sweep_emu_{tag}.csv")
    emu = load(emu_path) if os.path.exists(emu_path) else {}
    fig, ax = plt.subplots(figsize=(7, 4.5))
    for coll, data in sorted(emu.items()):
        xs, ys = zip(*data)
        ax.plot(xs, ys, marker="o", ms=2, lw=1, label=coll)
    ax.set_xscale("log", base=2)
    ax.set_yscale("log")
    ax.set_xlabel("message size (bytes)")
    ax.set_ylabel("busbw (GB/s)")
    ax.set_title(f"per-collective busbw, emulator rung (round {args.round})")
    ax.grid(True, which="both", alpha=0.3)
    ax.legend(fontsize=7, ncol=2)
    fig.tight_layout()
    p = os.path.join(outdir, f"collectives_{tag}.svg")
    fig.savefig(p)
    print(f"wrote {p}")

    # 3. pipelining A/B
    path = os.path.join(outdir, f"pipeline_ab_{tag}.csv")
    if os.path.exists(path):
        by_depth = defaultdict(list)
        with open(path) as f:
            for row in csv.DictReader(f):
                by_depth[row["depth"]].append(
                    (int(row["bytes"]), float(row["mean_us"])))
        fig, ax = plt.subplots(figsize=(7, 4))
        for depth, data in sorted(by_depth.items()):
            xs, ys = zip(*sorted(data))
            ax.plot(xs, ys, marker="o", ms=3,
                    label=f"egress window depth {depth}")
        ax.set_xscale("log", base=2)
        ax.set_yscale("log")
        ax.set_xlabel("message size (bytes)")
        ax.set_ylabel("sendrecv round latency (us, mean)")
        ax.set_title("eager egress pipelining A/B (emulator, 1 core)")
        ax.grid(True, which="both", alpha=0.3)
        ax.legend(fontsize=8)
        fig.tight_layout()
        p = os.path.join(outdir, f"pipeline_ab_{tag}.svg")
        fig.savefig(p)
        print(f"wrote {p}")

    # 4b. single-chip 1KB-1GB reduce-lane curve (metric-of-record proxy:
    #     on-path reduction busbw vs size with the XLA add as the
    #     per-size HBM roofline; BASELINE.md "busbw vs size, 1KB-1GB")
    path = os.path.join(outdir, f"lane_sweep_{tag}.csv")
    if os.path.exists(path):
        xs, p_gb, x_gb = [], [], []
        with open(path) as f:
            for row in csv.DictReader(f):
                xs.append(int(row["bytes"]))
                p_gb.append(float(row["pallas_GBps"]))
                x_gb.append(float(row["xla_GBps"]))
        order = sorted(range(len(xs)), key=lambda i: xs[i])
        xs = [xs[i] for i in order]
        p_gb = [p_gb[i] for i in order]
        x_gb = [x_gb[i] for i in order]
        fig, ax = plt.subplots(figsize=(7, 4))
        ax.plot(xs, p_gb, marker="o", ms=3,
                label="reduction lane (Pallas, real TPU)")
        ax.plot(xs, x_gb, marker="s", ms=3, ls="--", lw=1,
                label="XLA add (per-size HBM roofline)")
        ax.axhline(CCLO_ANCHOR_GBPS, ls="--", c="gray", lw=1,
                   label="reference CCLO datapath (16 GB/s)")
        ax.set_xscale("log", base=2)
        ax.set_yscale("log")
        ax.set_xlabel("operand size (bytes)")
        ax.set_ylabel("effective reduction bandwidth (GB/s)")
        ax.set_title("on-path reduction lane vs size, single TPU chip "
                     f"(round {args.round})")
        ax.grid(True, which="both", alpha=0.3)
        ax.legend(fontsize=8)
        fig.tight_layout()
        p = os.path.join(outdir, f"lane_sweep_{tag}.svg")
        fig.savefig(p)
        print(f"wrote {p}")

    # 4. driver path vs raw XLA collective (the Coyote harness's
    #    ACCL-vs-MPI comparison role, plot.py:10-44)
    path = os.path.join(outdir, f"driver_vs_raw_{tag}.csv")
    if os.path.exists(path):
        xs, d_us, r_us = [], [], []
        with open(path) as f:
            for row in csv.DictReader(f):
                xs.append(int(row["bytes"]))
                d_us.append(float(row["driver_us"]))
                r_us.append(float(row["raw_us"]))
        fig, ax = plt.subplots(figsize=(7, 4))
        ax.plot(xs, d_us, marker="o", ms=3,
                label="driver path (descriptor -> gang -> collective)")
        ax.plot(xs, r_us, marker="s", ms=3,
                label="raw jitted shard_map psum")
        ax.set_xscale("log", base=2)
        ax.set_yscale("log")
        ax.set_xlabel("message size (bytes)")
        ax.set_ylabel("allreduce latency (us, best)")
        ax.set_title("driver vs raw collective, 8-virtual-device mesh "
                     f"(round {args.round})")
        ax.grid(True, which="both", alpha=0.3)
        ax.legend(fontsize=8)
        fig.tight_layout()
        p = os.path.join(outdir, f"driver_vs_raw_{tag}.svg")
        fig.savefig(p)
        print(f"wrote {p}")


if __name__ == "__main__":
    main()
