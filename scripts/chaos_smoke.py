#!/usr/bin/env python
"""CI chaos smoke: prove the fault-tolerance stack end to end.

Three drills (the acceptance criteria of the resilience layer,
docs/fault_tolerance.md):

1. **Retransmission under seeded chaos** — a 4-rank emu allreduce loop
   under probabilistic drop/dup/delay (fixed seed, so a failure replays
   bit-for-bit) must produce results BITWISE IDENTICAL to the same
   loop on a clean world: every lost/duplicated/reordered segment is
   healed by the NACK lane inside the receive budget.  The engine's
   recovery counters must show the lane actually worked.

2. **Kill -> abort -> shrink -> finish** — mid-loop, one rank is
   killed.  Every survivor classifies the failure on its own clock,
   revokes the communicator (``ACCL.abort`` — the propagated abort
   wakes slower ranks immediately, no watchdog-timeout exit path),
   agrees on the surviving set (``shrink_communicator``), and finishes
   the loop on the 3-rank communicator with bitwise-correct results.

4. **Plan invalidation under chaos** (r12) — the loop runs through a
   PERSISTENT PLAN (``ACCL.capture_plan`` / ``plan.replay()``,
   accl_tpu/plans.py).  Mid-replay, one rank is killed: every
   survivor's replay fails classified, the abort FENCES the plan —
   the drill asserts a post-abort ``replay()`` RAISES (a stale plan
   must never silently run on the fenced epoch) and
   ``plan.invalidated`` is set — then survivors shrink, RE-CAPTURE on
   the healed communicator, agree on the restart iteration, and
   finish with results bitwise identical to the clean references.

3. **Elastic join drill** (r11) — mid-loop, rank 2 is killed; the
   per-rank RECOVERY SUPERVISORS (not this harness) drive every
   transition: abort -> probe -> shrink to 3 -> admit the replacement
   announced on the membership board (the ``join_rank`` chaos event)
   -> grow back to 4 ranks -> agree on the restart iteration ->
   resume.  The world must finish at its ORIGINAL size with results
   bitwise identical to a clean 4-rank world, the replacement fully
   participating, and the whole episode riding the abort clock.  The
   supervisors' state logs are written as a CI artifact.

Artifacts (uploaded by CI next to the hang smoke): the merged flight
dump after the kill drill (rank 3's records must show ``aborted``/
``failed`` terminal states, no in-flight stragglers), the per-rank
resilience counters, and the join drill's supervisor logs.

Usage: python scripts/chaos_smoke.py [--ranks N] [--count N]
       [--iters N] [--seed N] [--dump PATH] [--stats PATH]
       [--supervisor-log PATH]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _clean_reference(nranks, count, iters, make_data):
    """The same loop on a fault-free world: the bitwise oracle."""
    import numpy as np

    from accl_tpu import ReduceFunction
    from accl_tpu.backends.emu import EmuWorld

    with EmuWorld(nranks) as world:
        def fn(accl, rank):
            outs = []
            for it in range(iters):
                s = accl.create_buffer_like(make_data(rank, it))
                r = accl.create_buffer(count, np.float32)
                accl.allreduce(s, r, count, ReduceFunction.SUM)
                outs.append(r.host.copy())
            return outs

        return world.run(fn)[0]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=4)
    ap.add_argument("--count", type=int, default=256)
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--seed", type=int, default=2026)
    ap.add_argument("--dump", default="chaos_flight_dump.json")
    ap.add_argument("--stats", default="chaos_stats.json")
    ap.add_argument("--supervisor-log", default="chaos_supervisor_log.json")
    args = ap.parse_args()

    # generous engine budget: recovery must win long before a timeout
    os.environ.setdefault("ACCL_DEFAULT_TIMEOUT", "30000000")

    import numpy as np

    from accl_tpu import ACCLError, ErrorCode, ReduceFunction
    from accl_tpu.backends.emu import EmuWorld
    from accl_tpu.observability import flight as obs_flight

    def make_data(rank, it):
        rng = np.random.default_rng(1000 * rank + it)
        return rng.standard_normal(args.count).astype(np.float32)

    # ---- drill 1: seeded drop/dup/delay, bitwise via retransmission --
    plan = (f"seed={args.seed},drop=0.02,dup=0.02,delay=0.03,"
            f"delay_us=2000")
    reference = _clean_reference(args.ranks, args.count, args.iters,
                                 make_data)
    with EmuWorld(args.ranks, chaos=plan) as world:
        def loop(accl, rank):
            outs = []
            for it in range(args.iters):
                s = accl.create_buffer_like(make_data(rank, it))
                r = accl.create_buffer(args.count, np.float32)
                accl.allreduce(s, r, args.count, ReduceFunction.SUM)
                outs.append(r.host.copy())
            return outs

        chaos_outs = world.run(loop)
        stats1 = world.resilience_stats()

    for rank in range(args.ranks):
        for it in range(args.iters):
            if not np.array_equal(chaos_outs[rank][it], reference[it]):
                print(f"FAIL: drill 1 rank {rank} iter {it} diverged "
                      f"from the clean-world reference (not bitwise)")
                return 1
    recovered = sum(s["retrans_sent"] for s in stats1)
    nacks = sum(s["nacks_tx"] for s in stats1)
    if recovered < 1 or nacks < 1:
        print(f"FAIL: chaos plan {plan!r} never exercised the "
              f"retransmission lane (retrans={recovered}, nacks={nacks})")
        return 1
    print(f"drill 1 OK: {args.iters} allreduce iters x {args.ranks} "
          f"ranks bitwise-correct under {plan!r} "
          f"(retransmits={recovered}, nacks={nacks})")

    # ---- drill 2: mid-run kill -> abort -> shrink -> finish ----------
    # ULFM recovery, the real shape: survivors may be aborted at
    # DIFFERENT iterations (a lagging rank's in-flight call is revoked
    # too), so after the shrink they AGREE on the restart point — an
    # allreduce(MAX) of each survivor's negated first-incomplete
    # iteration on the fresh comm — discard anything at/after it, and
    # redo from there, keeping every gang aligned.
    kill_at = args.iters // 2
    victim = args.ranks - 1
    survivors = args.ranks - 1
    ref3 = _clean_reference(survivors, args.count, args.iters, make_data)
    with EmuWorld(args.ranks) as world:
        for a in world.accls:
            a.set_timeout(3_000_000)  # 3 s classification clock

        def loop2(accl, rank):
            comm_id = 0
            outs = {}
            restart = None
            it = 0
            while it < args.iters:
                if rank == victim and it == kill_at:
                    world.kill_rank(victim)  # the engine goes silent
                s = accl.create_buffer_like(make_data(rank, it))
                r = accl.create_buffer(args.count, np.float32)
                try:
                    accl.allreduce(s, r, args.count, ReduceFunction.SUM,
                                   comm_id=comm_id)
                    outs[it] = r.host.copy()
                    it += 1
                except ACCLError as e:
                    if rank == victim:
                        return ("dead", it, int(e.code))
                    # classify -> revoke -> shrink -> agree -> redo
                    assert restart is None, "second failure after shrink"
                    accl.abort(comm_id,
                               error=int(ErrorCode.RANK_FAILED))
                    comm_id = accl.shrink_communicator(comm_id,
                                                       window_s=2.0)
                    if accl.communicator(comm_id).size != survivors:
                        raise AssertionError(
                            f"shrink produced size "
                            f"{accl.communicator(comm_id).size}, "
                            f"wanted {survivors}")
                    sb = accl.create_buffer_like(
                        np.array([-it], np.float32))
                    rb = accl.create_buffer(1, np.float32)
                    accl.allreduce(sb, rb, 1, ReduceFunction.MAX,
                                   comm_id=comm_id)
                    restart = int(-rb.host[0])  # MIN over survivors
                    for k in range(restart, it):
                        outs.pop(k, None)
                    it = restart
            return ("alive", outs, restart, comm_id)

        t0 = time.time()
        results = world.run(loop2)
        drill2_s = time.time() - t0
        merged = obs_flight.merge_flight_dumps(
            [a.flight_recorder.dump() for a in world.accls],
            out_path=args.dump)
        stats2 = world.resilience_stats()

    # the victim died with a classified abort, not a silent hang
    dead = results[victim]
    if dead[0] != "dead" or not (dead[2] & int(ErrorCode.COMM_ABORTED)):
        print(f"FAIL: victim rank {victim} did not die aborted: {dead}")
        return 1
    # every survivor aborted, agreed on one restart point, and finished
    # ALL iterations; pre-restart results are bitwise vs the 4-rank
    # reference, the rest bitwise vs the 3-rank reference
    restarts = {results[r][2] for r in range(survivors)}
    comms = {results[r][3] for r in range(survivors)}
    if len(restarts) != 1 or None in restarts or len(comms) != 1:
        print(f"FAIL: survivors disagreed: restarts={restarts} "
              f"comms={comms}")
        return 1
    restart = restarts.pop()
    if restart > kill_at:
        print(f"FAIL: restart {restart} is past the kill at {kill_at}")
        return 1
    for rank in range(survivors):
        state, outs, _, _ = results[rank]
        if state != "alive" or sorted(outs) != list(range(args.iters)):
            print(f"FAIL: survivor {rank} state={state} iters="
                  f"{sorted(outs)}")
            return 1
        for it in range(args.iters):
            expected = (reference[it] if it < restart else ref3[it])
            if not np.array_equal(outs[it], expected):
                print(f"FAIL: drill 2 rank {rank} iter {it} not bitwise "
                      f"vs the {'4' if it < restart else '3'}-rank "
                      f"reference")
                return 1
    # no watchdog-timeout exit path: the whole drill rides the abort
    # clock (3 s classification + abort wake + shrink window), never a
    # watchdog or driver-wait expiry
    if drill2_s > 25.0:
        print(f"FAIL: drill 2 took {drill2_s:.1f}s — recovery leaned on "
              f"a timeout path, not the abort clock")
        return 1
    # the merged flight dump is the artifact: no in-flight stragglers
    hangs = merged["analysis"]["hangs"]
    if hangs:
        print(f"FAIL: flight analysis reports hangs after recovery: "
              f"{hangs}")
        return 1

    print(f"drill 2 OK: rank {victim} killed at iter {kill_at}; "
          f"survivors aborted (RANK_FAILED), shrank to {survivors} "
          f"ranks, finished bitwise in {drill2_s:.1f}s; "
          f"dump={args.dump}")

    # ---- drill 3: elastic join — kill -> shrink -> join -> grow ------
    # The supervisors drive EVERY transition; this harness only plays
    # the cluster manager (kills the victim's engine, spawns the
    # replacement process the join_rank chaos event names).  Data is
    # keyed by COMM-LOCAL rank so the 4-rank clean-world reference
    # stays the bitwise oracle across the membership change (the ring
    # schedule is local-rank-based: same locals, same arithmetic).
    import threading

    from accl_tpu.resilience.chaos import ChaosPlan
    from accl_tpu.resilience.supervisor import RecoveryPolicy

    jplan = ChaosPlan.parse(f"seed={args.seed},kill_rank=2,join_rank=2")
    j_victim = jplan.kills[0]
    assert jplan.joins == [j_victim], "join drill heals the killed rank"
    kill3_at = args.iters // 2
    sup_logs: dict = {}
    join_info: dict = {}

    def local_data(accl, comm_id, it):
        comm = accl.communicator(comm_id)
        return make_data(comm.local_rank, it), comm.size

    with EmuWorld(args.ranks) as world:
        for a in world.accls:
            a.set_timeout(3_000_000)  # 3 s classification clock
        policy_kw = dict(mode="grow", join_wait_s=10.0,
                         probe_window_s=1.5, max_rounds=2)

        def supervised(accl, rank):
            sup = accl.supervise(policy=RecoveryPolicy(**policy_kw),
                                 board=world.board)
            outs = {}

            def step(a, comm_id, it):
                if rank == j_victim and it == kill3_at:
                    world.kill_rank(j_victim)  # engine goes silent
                data, size = local_data(a, comm_id, it)
                s = a.create_buffer_like(data)
                r = a.create_buffer(args.count, np.float32)
                a.allreduce(s, r, args.count, ReduceFunction.SUM,
                            comm_id=comm_id)
                outs[it] = (size, r.host.copy())

            def on_restart(restart):
                for k in list(outs):
                    if k >= restart:
                        outs.pop(k)

            try:
                summary = sup.run_loop(step, args.iters, comm_id=0,
                                       on_restart=on_restart)
            except ACCLError as e:
                sup_logs[rank] = sup.state_log
                if rank == j_victim:
                    return ("dead", int(getattr(e, "code", 0)))
                raise
            sup_logs[rank] = summary["state_log"]
            return ("alive", outs, summary)

        def replacement():
            # the cluster manager notices the death and supplies a
            # replacement; everything after spawn is supervisor-driven
            time.sleep(1.0)
            j = world.spawn_replacement()
            comm_id = j.join(timeout_s=40.0)
            j.accl.set_timeout(40_000_000)  # cover survivor skew
            sup = j.accl.supervise(policy=RecoveryPolicy(**policy_kw),
                                   board=world.board)
            sup.comm_id = comm_id
            restart = sup.agree_restart(0, fresh=True)
            outs = {}

            def step(a, cid, it):
                data, size = local_data(a, cid, it)
                s = a.create_buffer_like(data)
                r = a.create_buffer(args.count, np.float32)
                a.allreduce(s, r, args.count, ReduceFunction.SUM,
                            comm_id=cid)
                outs[it] = (size, r.host.copy())

            summary = sup.run_loop(step, args.iters, comm_id=comm_id,
                                   start_iteration=restart)
            join_info.update(outs=outs, restart=restart,
                             summary=summary, rank=j.rank,
                             stats=j.device.join_stats())
            sup_logs[f"joiner:{j.rank}"] = summary["state_log"]

        t0 = time.time()
        jt = threading.Thread(target=replacement, daemon=True)
        jt.start()
        results3 = world.run(supervised)
        jt.join(timeout=60)
        drill3_s = time.time() - t0
        merged3 = obs_flight.merge_flight_dumps(
            [a.flight_recorder.dump() for a in world.accls]
            + [j.accl.flight_recorder.dump() for j in world.joiners])

    with open(args.supervisor_log, "w") as f:
        json.dump({str(k): [(round(t, 3), s, d) for t, s, d in v]
                   for k, v in sup_logs.items()}, f, indent=1)

    if jt.is_alive() or "outs" not in join_info:
        print("FAIL: drill 3 replacement never finished its loop")
        return 1
    dead3 = results3[j_victim]
    if dead3[0] != "dead":
        print(f"FAIL: drill 3 victim survived its own kill: {dead3}")
        return 1
    surv3 = [r for r in range(args.ranks) if r != j_victim]
    for rank in surv3:
        state, outs, summary = results3[rank]
        if state != "alive" or sorted(outs) != list(range(args.iters)):
            print(f"FAIL: drill 3 survivor {rank} state={state} "
                  f"iters={sorted(outs)}")
            return 1
        # the supervisor (not the harness) must have driven the episode
        states = [s for _t, s, _d in sup_logs[rank]]
        for needed in ("abort", "probe", "shrink", "grow", "resume"):
            if needed not in states:
                print(f"FAIL: drill 3 rank {rank} supervisor never "
                      f"entered {needed!r} (log: {states})")
                return 1
        # world restored to original size, replacement participating
        sizes = {outs[k][0] for k in outs}
        if sizes != {args.ranks}:
            print(f"FAIL: drill 3 rank {rank} ran iterations at sizes "
                  f"{sizes}, wanted all at {args.ranks}")
            return 1
        for it in range(args.iters):
            if not np.array_equal(outs[it][1], reference[it]):
                print(f"FAIL: drill 3 rank {rank} iter {it} not "
                      f"bitwise vs the clean 4-rank world")
                return 1
    outs = join_info["outs"]
    if {outs[k][0] for k in outs} != {args.ranks} or not outs:
        print("FAIL: drill 3 replacement ran at wrong world size")
        return 1
    for it, (_size, val) in outs.items():
        if not np.array_equal(val, reference[it]):
            print(f"FAIL: drill 3 replacement iter {it} not bitwise")
            return 1
    if join_info["stats"]["joined"] != 1:
        print(f"FAIL: drill 3 join counters {join_info['stats']}")
        return 1
    if drill3_s > 40.0:
        print(f"FAIL: drill 3 took {drill3_s:.1f}s — recovery leaned "
              f"on a timeout path, not the abort clock")
        return 1
    hangs3 = [h for h in merged3["analysis"]["hangs"]]
    if hangs3:
        print(f"FAIL: drill 3 flight analysis reports hangs after "
              f"recovery: {hangs3}")
        return 1

    # ---- drill 4: persistent plans under chaos — mid-replay kill ->
    # abort fences the plan (stale replay RAISES, never runs) ->
    # shrink -> re-capture on the healed comm -> bitwise finish -------
    kill4_at = args.iters // 2
    victim4 = args.ranks - 1
    with EmuWorld(args.ranks) as world:
        for a in world.accls:
            a.set_timeout(3_000_000)  # 3 s classification clock

        def loop4(accl, rank):
            comm_id = 0
            outs = {}
            restart = None
            s = accl.create_buffer(args.count, np.float32)
            r = accl.create_buffer(args.count, np.float32)

            def body(a, cid):
                a.allreduce(s, r, args.count, ReduceFunction.SUM,
                            comm_id=cid)

            s.host[:] = make_data(rank, 0)
            plan4 = accl.capture_plan(body, comm_id)
            outs[0] = r.host.copy()
            it = 1
            while it < args.iters:
                if rank == victim4 and it == kill4_at:
                    world.kill_rank(victim4)  # engine goes silent
                s.host[:] = make_data(rank, it)
                try:
                    plan4.replay()
                    outs[it] = r.host.copy()
                    it += 1
                except ACCLError as e:
                    if rank == victim4:
                        return ("dead", it, int(e.code))
                    assert restart is None, "second failure after shrink"
                    accl.abort(comm_id,
                               error=int(ErrorCode.RANK_FAILED))
                    # THE GATE: the fenced plan must refuse to replay
                    try:
                        plan4.replay()
                        return ("stale-replay-ran", rank, it)
                    except ACCLError:
                        pass
                    if not plan4.invalidated:
                        return ("not-invalidated", rank, it)
                    comm_id = accl.shrink_communicator(comm_id,
                                                       window_s=2.0)
                    sb = accl.create_buffer_like(
                        np.array([-it], np.float32))
                    rb = accl.create_buffer(1, np.float32)
                    accl.allreduce(sb, rb, 1, ReduceFunction.MAX,
                                   comm_id=comm_id)
                    restart = int(-rb.host[0])
                    for k in range(restart, it):
                        outs.pop(k, None)
                    it = restart
                    # re-capture on the healed communicator
                    s.host[:] = make_data(rank, it)
                    plan4 = accl.capture_plan(body, comm_id)
                    outs[it] = r.host.copy()
                    it += 1
            return ("alive", outs, restart, plan4.stats["replays"])

        t0 = time.time()
        results4 = world.run(loop4)
        drill4_s = time.time() - t0

    dead4 = results4[victim4]
    if dead4[0] != "dead" or not (dead4[2] & int(ErrorCode.COMM_ABORTED)):
        print(f"FAIL: drill 4 victim did not die aborted: {dead4}")
        return 1
    restarts4 = {results4[r][2] for r in range(args.ranks - 1)}
    if len(restarts4) != 1 or None in restarts4:
        print(f"FAIL: drill 4 survivors disagreed on restart: "
              f"{restarts4}")
        return 1
    restart4 = restarts4.pop()
    for rank in range(args.ranks - 1):
        state = results4[rank][0]
        if state != "alive":
            print(f"FAIL: drill 4 rank {rank} ended {results4[rank]} "
                  f"(stale-replay-ran = a fenced plan executed!)")
            return 1
        outs = results4[rank][1]
        if sorted(outs) != list(range(args.iters)):
            print(f"FAIL: drill 4 rank {rank} iters {sorted(outs)}")
            return 1
        for it in range(args.iters):
            expected = (reference[it] if it < restart4 else ref3[it])
            if not np.array_equal(outs[it], expected):
                print(f"FAIL: drill 4 rank {rank} iter {it} not "
                      f"bitwise vs the "
                      f"{'4' if it < restart4 else '3'}-rank reference")
                return 1
        if results4[rank][3] < 1:
            print(f"FAIL: drill 4 rank {rank} never replayed the "
                  f"re-captured plan")
            return 1
    if drill4_s > 25.0:
        print(f"FAIL: drill 4 took {drill4_s:.1f}s — recovery leaned "
              f"on a timeout path, not the abort clock")
        return 1
    print(f"drill 4 OK: rank {victim4} killed at iter {kill4_at} "
          f"mid-replay; fenced plan refused to run, survivors shrank, "
          f"re-captured, finished bitwise in {drill4_s:.1f}s")

    with open(args.stats, "w") as f:
        json.dump({"drill1": {"plan": plan, "per_rank": stats1,
                              "retransmits": recovered, "nacks": nacks},
                   "drill2": {"victim": victim, "kill_at_iter": kill_at,
                              "wall_s": round(drill2_s, 2),
                              "per_rank": stats2},
                   "drill4": {"victim": victim4,
                              "kill_at_iter": kill4_at,
                              "restart": restart4,
                              "wall_s": round(drill4_s, 2)},
                   "drill3": {"plan": jplan.spec(), "victim": j_victim,
                              "kill_at_iter": kill3_at,
                              "replacement_session": join_info["rank"],
                              "restart": join_info["restart"],
                              "wall_s": round(drill3_s, 2),
                              "join_stats": join_info["stats"]}},
                  f, indent=1)
    print(f"drill 3 OK: rank {j_victim} killed at iter {kill3_at}; "
          f"supervisors shrank to {args.ranks - 1}, admitted "
          f"replacement session {join_info['rank']}, grew back to "
          f"{args.ranks} ranks, agreed restart "
          f"{join_info['restart']}, finished bitwise in "
          f"{drill3_s:.1f}s; supervisor log={args.supervisor_log} "
          f"stats={args.stats}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
