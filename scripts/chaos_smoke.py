#!/usr/bin/env python
"""CI chaos smoke: prove the fault-tolerance stack end to end.

Two drills (the acceptance criteria of the resilience layer,
docs/fault_tolerance.md):

1. **Retransmission under seeded chaos** — a 4-rank emu allreduce loop
   under probabilistic drop/dup/delay (fixed seed, so a failure replays
   bit-for-bit) must produce results BITWISE IDENTICAL to the same
   loop on a clean world: every lost/duplicated/reordered segment is
   healed by the NACK lane inside the receive budget.  The engine's
   recovery counters must show the lane actually worked.

2. **Kill -> abort -> shrink -> finish** — mid-loop, one rank is
   killed.  Every survivor classifies the failure on its own clock,
   revokes the communicator (``ACCL.abort`` — the propagated abort
   wakes slower ranks immediately, no watchdog-timeout exit path),
   agrees on the surviving set (``shrink_communicator``), and finishes
   the loop on the 3-rank communicator with bitwise-correct results.

Artifacts (uploaded by CI next to the hang smoke): the merged flight
dump after the kill drill (rank 3's records must show ``aborted``/
``failed`` terminal states, no in-flight stragglers) and the per-rank
resilience counters.

Usage: python scripts/chaos_smoke.py [--ranks N] [--count N]
       [--iters N] [--seed N] [--dump PATH] [--stats PATH]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _clean_reference(nranks, count, iters, make_data):
    """The same loop on a fault-free world: the bitwise oracle."""
    import numpy as np

    from accl_tpu import ReduceFunction
    from accl_tpu.backends.emu import EmuWorld

    with EmuWorld(nranks) as world:
        def fn(accl, rank):
            outs = []
            for it in range(iters):
                s = accl.create_buffer_like(make_data(rank, it))
                r = accl.create_buffer(count, np.float32)
                accl.allreduce(s, r, count, ReduceFunction.SUM)
                outs.append(r.host.copy())
            return outs

        return world.run(fn)[0]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=4)
    ap.add_argument("--count", type=int, default=256)
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--seed", type=int, default=2026)
    ap.add_argument("--dump", default="chaos_flight_dump.json")
    ap.add_argument("--stats", default="chaos_stats.json")
    args = ap.parse_args()

    # generous engine budget: recovery must win long before a timeout
    os.environ.setdefault("ACCL_DEFAULT_TIMEOUT", "30000000")

    import numpy as np

    from accl_tpu import ACCLError, ErrorCode, ReduceFunction
    from accl_tpu.backends.emu import EmuWorld
    from accl_tpu.observability import flight as obs_flight

    def make_data(rank, it):
        rng = np.random.default_rng(1000 * rank + it)
        return rng.standard_normal(args.count).astype(np.float32)

    # ---- drill 1: seeded drop/dup/delay, bitwise via retransmission --
    plan = (f"seed={args.seed},drop=0.02,dup=0.02,delay=0.03,"
            f"delay_us=2000")
    reference = _clean_reference(args.ranks, args.count, args.iters,
                                 make_data)
    with EmuWorld(args.ranks, chaos=plan) as world:
        def loop(accl, rank):
            outs = []
            for it in range(args.iters):
                s = accl.create_buffer_like(make_data(rank, it))
                r = accl.create_buffer(args.count, np.float32)
                accl.allreduce(s, r, args.count, ReduceFunction.SUM)
                outs.append(r.host.copy())
            return outs

        chaos_outs = world.run(loop)
        stats1 = world.resilience_stats()

    for rank in range(args.ranks):
        for it in range(args.iters):
            if not np.array_equal(chaos_outs[rank][it], reference[it]):
                print(f"FAIL: drill 1 rank {rank} iter {it} diverged "
                      f"from the clean-world reference (not bitwise)")
                return 1
    recovered = sum(s["retrans_sent"] for s in stats1)
    nacks = sum(s["nacks_tx"] for s in stats1)
    if recovered < 1 or nacks < 1:
        print(f"FAIL: chaos plan {plan!r} never exercised the "
              f"retransmission lane (retrans={recovered}, nacks={nacks})")
        return 1
    print(f"drill 1 OK: {args.iters} allreduce iters x {args.ranks} "
          f"ranks bitwise-correct under {plan!r} "
          f"(retransmits={recovered}, nacks={nacks})")

    # ---- drill 2: mid-run kill -> abort -> shrink -> finish ----------
    # ULFM recovery, the real shape: survivors may be aborted at
    # DIFFERENT iterations (a lagging rank's in-flight call is revoked
    # too), so after the shrink they AGREE on the restart point — an
    # allreduce(MAX) of each survivor's negated first-incomplete
    # iteration on the fresh comm — discard anything at/after it, and
    # redo from there, keeping every gang aligned.
    kill_at = args.iters // 2
    victim = args.ranks - 1
    survivors = args.ranks - 1
    ref3 = _clean_reference(survivors, args.count, args.iters, make_data)
    with EmuWorld(args.ranks) as world:
        for a in world.accls:
            a.set_timeout(3_000_000)  # 3 s classification clock

        def loop2(accl, rank):
            comm_id = 0
            outs = {}
            restart = None
            it = 0
            while it < args.iters:
                if rank == victim and it == kill_at:
                    world.kill_rank(victim)  # the engine goes silent
                s = accl.create_buffer_like(make_data(rank, it))
                r = accl.create_buffer(args.count, np.float32)
                try:
                    accl.allreduce(s, r, args.count, ReduceFunction.SUM,
                                   comm_id=comm_id)
                    outs[it] = r.host.copy()
                    it += 1
                except ACCLError as e:
                    if rank == victim:
                        return ("dead", it, int(e.code))
                    # classify -> revoke -> shrink -> agree -> redo
                    assert restart is None, "second failure after shrink"
                    accl.abort(comm_id,
                               error=int(ErrorCode.RANK_FAILED))
                    comm_id = accl.shrink_communicator(comm_id,
                                                       window_s=2.0)
                    if accl.communicator(comm_id).size != survivors:
                        raise AssertionError(
                            f"shrink produced size "
                            f"{accl.communicator(comm_id).size}, "
                            f"wanted {survivors}")
                    sb = accl.create_buffer_like(
                        np.array([-it], np.float32))
                    rb = accl.create_buffer(1, np.float32)
                    accl.allreduce(sb, rb, 1, ReduceFunction.MAX,
                                   comm_id=comm_id)
                    restart = int(-rb.host[0])  # MIN over survivors
                    for k in range(restart, it):
                        outs.pop(k, None)
                    it = restart
            return ("alive", outs, restart, comm_id)

        t0 = time.time()
        results = world.run(loop2)
        drill2_s = time.time() - t0
        merged = obs_flight.merge_flight_dumps(
            [a.flight_recorder.dump() for a in world.accls],
            out_path=args.dump)
        stats2 = world.resilience_stats()

    # the victim died with a classified abort, not a silent hang
    dead = results[victim]
    if dead[0] != "dead" or not (dead[2] & int(ErrorCode.COMM_ABORTED)):
        print(f"FAIL: victim rank {victim} did not die aborted: {dead}")
        return 1
    # every survivor aborted, agreed on one restart point, and finished
    # ALL iterations; pre-restart results are bitwise vs the 4-rank
    # reference, the rest bitwise vs the 3-rank reference
    restarts = {results[r][2] for r in range(survivors)}
    comms = {results[r][3] for r in range(survivors)}
    if len(restarts) != 1 or None in restarts or len(comms) != 1:
        print(f"FAIL: survivors disagreed: restarts={restarts} "
              f"comms={comms}")
        return 1
    restart = restarts.pop()
    if restart > kill_at:
        print(f"FAIL: restart {restart} is past the kill at {kill_at}")
        return 1
    for rank in range(survivors):
        state, outs, _, _ = results[rank]
        if state != "alive" or sorted(outs) != list(range(args.iters)):
            print(f"FAIL: survivor {rank} state={state} iters="
                  f"{sorted(outs)}")
            return 1
        for it in range(args.iters):
            expected = (reference[it] if it < restart else ref3[it])
            if not np.array_equal(outs[it], expected):
                print(f"FAIL: drill 2 rank {rank} iter {it} not bitwise "
                      f"vs the {'4' if it < restart else '3'}-rank "
                      f"reference")
                return 1
    # no watchdog-timeout exit path: the whole drill rides the abort
    # clock (3 s classification + abort wake + shrink window), never a
    # watchdog or driver-wait expiry
    if drill2_s > 25.0:
        print(f"FAIL: drill 2 took {drill2_s:.1f}s — recovery leaned on "
              f"a timeout path, not the abort clock")
        return 1
    # the merged flight dump is the artifact: no in-flight stragglers
    hangs = merged["analysis"]["hangs"]
    if hangs:
        print(f"FAIL: flight analysis reports hangs after recovery: "
              f"{hangs}")
        return 1

    with open(args.stats, "w") as f:
        json.dump({"drill1": {"plan": plan, "per_rank": stats1,
                              "retransmits": recovered, "nacks": nacks},
                   "drill2": {"victim": victim, "kill_at_iter": kill_at,
                              "wall_s": round(drill2_s, 2),
                              "per_rank": stats2}}, f, indent=1)
    print(f"drill 2 OK: rank {victim} killed at iter {kill_at}; "
          f"survivors aborted (RANK_FAILED), shrank to {survivors} "
          f"ranks, finished bitwise in {drill2_s:.1f}s; "
          f"dump={args.dump} stats={args.stats}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
