#!/usr/bin/env python
"""accl_lint: static desync/deadlock/hazard linter for collective programs.

Runs a user script under N simulated ranks, captures every rank's
collective program (op, comm, root, counts, dtype pair, operand address
ranges, async-ness), and prints the severity-ranked findings of the
cross-rank checker suite (accl_tpu/analysis/checks.py): issue-order
desyncs, parameter mismatches, send/recv deadlock cycles, invalid
roots/peers, buffer overlap and use-after-free, leaked async requests.
Exits 1 when any ERROR survives (warnings too under ``--strict``).

Two capture modes (``--mode auto`` picks per script):

- **record** — the script exposes ``accl_main(accl, rank)``; it runs
  under a :class:`~accl_tpu.analysis.record.LintWorld` (the
  no-execution LintDevice backend): microsecond-fast, no backend
  needed, but buffers stay zero — don't assert on payloads.  An
  optional module-level ``LINT_RANKS`` overrides ``--ranks``.
- **shadow** — any other script runs UNMODIFIED as ``__main__`` on its
  real backend while a CaptureSession records the same facts (how CI
  lints ``examples/``, whose assertions need real data movement).

Exit codes: 0 = clean at the selected gate; 1 = findings at or above
``--fail-on`` (errors always fail; ``--fail-on warning`` — or its
alias ``--strict`` — fails on warnings too; info never fails).

Usage:
    python scripts/accl_lint.py program.py [--ranks N]
        [--mode auto|record|shadow] [--json out.json]
        [--fail-on error|warning] [--strict]
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import runpy
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_module(path: str):
    spec = importlib.util.spec_from_file_location("_accl_lint_target",
                                                  path)
    if spec is None or spec.loader is None:
        raise SystemExit(f"accl_lint: cannot import {path}")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def run_record(path: str, nranks: int):
    from accl_tpu.analysis.record import LintWorld

    mod = _load_module(path)
    entry = getattr(mod, "accl_main", None)
    if entry is None:
        raise SystemExit(
            f"accl_lint: {path} has no accl_main(accl, rank) — use "
            f"--mode shadow for scripts with their own __main__")
    nranks = getattr(mod, "LINT_RANKS", nranks)
    world = LintWorld(nranks)
    world.run(entry)
    meta = {"mode": "record", "ranks": nranks,
            "calls": {str(r): len(p.calls)
                      for r, p in world.programs.items()},
            "programs": {str(r): p.to_dict()
                         for r, p in world.programs.items()}}
    return world.check(), meta


def run_shadow(path: str):
    from accl_tpu.analysis.sanitizer import CaptureSession

    argv = sys.argv
    sys.argv = [path]
    try:
        with CaptureSession() as cap:
            runpy.run_path(path, run_name="__main__")
    finally:
        sys.argv = argv
    meta = {"mode": "shadow", "ranks": len(cap.programs),
            "calls": {str(r): len(p.calls)
                      for r, p in cap.programs.items()},
            "programs": {str(r): p.to_dict()
                         for r, p in cap.programs.items()}}
    return cap.check(), meta


def main() -> int:
    ap = argparse.ArgumentParser(
        prog="accl_lint",
        description="static desync/deadlock linter for ACCL collective "
                    "programs",
        epilog="exit codes: 0 = no finding at or above the --fail-on "
               "severity (info-level findings never fail); 1 = at "
               "least one ERROR (always), or at least one WARNING "
               "with --fail-on warning / --strict; 2 = usage error "
               "(argparse).  A crash while importing or running the "
               "target script propagates as a nonzero exit with the "
               "traceback — that is a broken script, not a lint "
               "verdict.")
    ap.add_argument("script", help="python file to lint")
    ap.add_argument("--ranks", type=int, default=2,
                    help="simulated world size for record mode "
                         "(module LINT_RANKS overrides; default 2)")
    ap.add_argument("--mode", choices=("auto", "record", "shadow"),
                    default="auto",
                    help="auto: record when the script defines "
                         "accl_main, else shadow (run under a real "
                         "backend with capture)")
    ap.add_argument("--json", default="",
                    help="write findings + captured programs as JSON")
    ap.add_argument("--fail-on", choices=("error", "warning"),
                    default="error",
                    help="lowest severity that fails the run: 'error' "
                         "(default) exits 1 only on errors; 'warning' "
                         "also fails on warnings (CI gate mode)")
    ap.add_argument("--strict", action="store_true",
                    help="alias for --fail-on warning (kept for "
                         "existing CI invocations)")
    ap.add_argument("--max-findings", type=int, default=50,
                    help="print at most N findings (default 50)")
    args = ap.parse_args()
    if args.strict:
        args.fail_on = "warning"

    mode = args.mode
    if mode == "auto":
        with open(args.script) as f:
            src = f.read()
        mode = "record" if "def accl_main" in src else "shadow"

    if mode == "record":
        findings, meta = run_record(args.script, args.ranks)
    else:
        findings, meta = run_shadow(args.script)

    from accl_tpu.analysis.findings import ERROR, WARNING

    n_err = sum(1 for f in findings if f.severity == ERROR)
    n_warn = sum(1 for f in findings if f.severity == WARNING)
    print(f"accl_lint: {args.script} — {meta['ranks']} rank(s), "
          f"mode={meta['mode']}, "
          f"{sum(int(n) for n in meta['calls'].values())} call(s)")
    for f in findings[:args.max_findings]:
        print(f.render())
    if len(findings) > args.max_findings:
        print(f"... {len(findings) - args.max_findings} more finding(s) "
              f"suppressed (--max-findings)")

    if args.json:
        with open(args.json, "w") as f:
            json.dump({"script": args.script, **meta,
                       "findings": [x.to_dict() for x in findings]},
                      f, indent=1)

    if not findings:
        print("accl_lint: clean — no findings")
    else:
        print(f"accl_lint: {n_err} error(s), {n_warn} warning(s)")
    if n_err or (args.fail_on == "warning" and n_warn):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
