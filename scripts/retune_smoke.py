#!/usr/bin/env python
"""retune_smoke: CI drill for the r19 online-retune control plane.

One command proves the live telemetry -> tuner loop end-to-end on a
4-rank emu world, deterministically (no timer threads — the drill
drives ``sentinel.check()`` and ``tuner.step()`` explicitly, so a
failing run replays bit-for-bit from ``--seed``):

1. healthy allreduce traffic; the registry snapshot becomes the
   sentinel baseline (the committed-baseline stand-in);
2. a SEEDED chaos plan (the ``ACCL_CHAOS`` grammar; default
   ``slow_rank``) degrades one rank's egress MID-RUN — the next
   ``sentinel.check()`` fires fresh findings into the subscribed
   :class:`~accl_tpu.tuning.online.OnlineTuner`;
3. the tuner turns one finding into one cell hypothesis, re-measures
   with the interleaved best-of A/B, and closes an episode —
   never-slower: only a verified winner installs, and the drill
   asserts the post-decision p50 did not regress;
4. artifacts (``retune_history.json`` — the exporter's ``/retunes``
   body — plus the metrics snapshot and a summary) are round-tripped
   through ``scripts/perf_doctor.py --ci --retunes`` in a subprocess:
   the doctor must schema-validate and render the exact bytes a live
   world would serve.

Usage:
  python scripts/retune_smoke.py --ranks 4 --seed 42 --out-dir .
"""
import argparse
import json
import os
import statistics
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=4)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--count", type=int, default=4096,
                    help="elements per allreduce (float32)")
    ap.add_argument("--warm", type=int, default=12,
                    help="healthy calls before the baseline snapshot")
    ap.add_argument("--degraded", type=int, default=16,
                    help="calls under chaos before the sentinel check")
    ap.add_argument("--chaos", default="",
                    help="ACCL_CHAOS-grammar plan injected mid-run "
                         "(default: seed=<seed>,slow_rank=1:1000)")
    ap.add_argument("--out-dir", default=".")
    args = ap.parse_args()
    chaos_spec = args.chaos or f"seed={args.seed},slow_rank=1:1000"

    # same receive-budget widening as tests/conftest.py: a loaded CI
    # core can stall a rank past the reference 1 s default
    os.environ.setdefault("ACCL_DEFAULT_TIMEOUT", "30000000")
    # single-axis fabric: this drill verifies the CONTROL PLANE
    # (finding -> hypothesis -> A/B -> install), so the challenger
    # shortlist stays on the register/compression lanes.  Retested in
    # r21 after the sub-comm rx-pool-pinning wedge fix: the composed
    # hierarchical lane under per-message slow_rank chaos still
    # deadlocks, and with a DIFFERENT signature — the interleaved A/B
    # arms' sub-comm flights sit in `dispatched` until the engine wait
    # budget expires (a cross-phase stall between the two composed
    # structures, not a RECEIVE_TIMEOUT with the segment staged), so
    # the r21 fix does not cover it.  ROADMAP item 4 residue; a
    # detsched drill pairing two interleaved HierarchicalComm
    # instances is the next finder.
    os.environ.setdefault("ACCL_FABRIC", str(args.ranks))

    import numpy as np

    from accl_tpu.backends.emu import EmuWorld
    from accl_tpu.bench import sweep as _sweep
    from accl_tpu.observability import metrics as _metrics
    from accl_tpu.observability.sentinel import Baseline, Sentinel
    from accl_tpu.resilience.chaos import ChaosPlan
    from accl_tpu.tuning.online import DECISIONS, OnlineTuner

    dtype = np.dtype(np.float32)
    registry = _metrics.default_registry()
    world = EmuWorld(args.ranks, devmem_bytes=256 << 20,
                     n_egr_rx_bufs=64, max_eager_size=16384,
                     max_rendezvous_size=64 << 20)

    def drive(n: int) -> float:
        """n timed allreduces; returns the p50 call duration in us."""
        durs = [_sweep._run_once(world, "allreduce", args.count, dtype, 0)
                for _ in range(n)]
        return statistics.median(durs) * 1e6

    summary: dict = {"seed": args.seed, "chaos": chaos_spec,
                     "count": args.count}
    try:
        # -- 1: healthy phase -> baseline -----------------------------
        p50_warm = drive(args.warm)
        summary["p50_warm_us"] = round(p50_warm, 1)
        baseline = Baseline.from_snapshot(
            registry.snapshot(), source=f"retune_smoke warm phase "
                                        f"(seed {args.seed})")
        assert baseline.entries, "warm traffic published no call metrics"
        sentinel = Sentinel(baseline, registry, p50_ratio=1.5,
                            p99_ratio=2.0, bw_ratio=0.6, min_calls=8)
        tuner = OnlineTuner(world, hysteresis=1.05, repetitions=2)
        tuner.attach_sentinel(sentinel)
        print(f"retune_smoke: warm p50 {p50_warm:.0f}us over "
              f"{args.warm} calls; baseline has "
              f"{len(baseline.entries)} entr(ies)")

        # -- 2: seeded chaos mid-run ----------------------------------
        plan = ChaosPlan.parse(chaos_spec)
        for r, d in enumerate(world.devices):
            plan.apply(d, r)
        p50_degraded = drive(args.degraded)
        summary["p50_degraded_us"] = round(p50_degraded, 1)
        print(f"retune_smoke: chaos [{chaos_spec}] -> degraded p50 "
              f"{p50_degraded:.0f}us ({p50_degraded / p50_warm:.2f}x "
              f"warm)")

        findings = sentinel.check()
        if not findings:
            print("retune_smoke: FAIL — sentinel saw no drift after "
                  f"the chaos phase (p50 {p50_degraded:.0f}us vs warm "
                  f"{p50_warm:.0f}us)", file=sys.stderr)
            return 1
        print(f"retune_smoke: sentinel fired {len(findings)} "
              f"finding(s); {tuner.pending()} queued to the tuner")

        # -- 3: drain the control plane -------------------------------
        episodes = []
        while tuner.pending():
            ep = tuner.step()
            if ep is not None:
                episodes.append(ep)
        if not episodes:
            print("retune_smoke: FAIL — findings queued but no episode "
                  "closed", file=sys.stderr)
            return 1
        for ep in episodes:
            assert ep["decision"] in DECISIONS, ep
            print(f"retune_smoke: episode #{ep['seq']} "
                  f"{ep.get('cell')}: {ep['decision']} "
                  f"({ep.get('reason', '')})")
        decisions = {ep["decision"] for ep in episodes}
        if not decisions & {"installed", "rejected"}:
            print(f"retune_smoke: FAIL — no episode reached a measured "
                  f"decision (got {sorted(decisions)})", file=sys.stderr)
            return 1

        # never-slower, measured: whatever the decisions were, the live
        # dispatch after the control plane ran must not be worse than
        # the degraded state it was reacting to (generous slack:
        # shared CI cores)
        p50_post = drive(args.warm)
        summary["p50_post_us"] = round(p50_post, 1)
        summary["recovery_ratio"] = round(p50_degraded / p50_post, 3) \
            if p50_post else 0.0
        print(f"retune_smoke: post-decision p50 {p50_post:.0f}us "
              f"({summary['recovery_ratio']}x recovery vs degraded)")
        if p50_post > p50_degraded * 1.5:
            print("retune_smoke: FAIL — dispatch after the retune is "
                  f"{p50_post / p50_degraded:.2f}x SLOWER than the "
                  f"degraded state (never-slower broken)",
                  file=sys.stderr)
            return 1

        # retune counter families must have moved (schema'd telemetry)
        counters = registry.snapshot()["counters"]
        retunes = {k: v for k, v in counters.items()
                   if k.startswith("tuning/retunes/")}
        assert retunes.get("tuning/retunes/proposed", 0) >= 1, retunes
        summary["retune_counters"] = retunes
        print(f"retune_smoke: counters {retunes}")

        # -- 4: artifacts + the perf_doctor round-trip ----------------
        os.makedirs(args.out_dir, exist_ok=True)
        hist_path = os.path.join(args.out_dir, "retune_history.json")
        with open(hist_path, "w") as f:
            json.dump(tuner.history.to_doc(), f, indent=1,
                      sort_keys=True)
        snap_path = os.path.join(args.out_dir, "retune_metrics.json")
        with open(snap_path, "w") as f:
            json.dump(registry.snapshot(), f, indent=1, sort_keys=True)
        summary["episodes"] = len(episodes)
        summary["decisions"] = sorted(decisions)
        with open(os.path.join(args.out_dir,
                               "retune_summary.json"), "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
    finally:
        world.close()

    report_path = os.path.join(args.out_dir, "retune_doctor_report.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "perf_doctor.py"),
         "--retunes", hist_path, "--metrics", snap_path,
         "--ci", "--out", report_path],
        capture_output=True, text=True)
    sys.stdout.write(proc.stdout)
    sys.stderr.write(proc.stderr)
    if proc.returncode != 0:
        print(f"retune_smoke: FAIL — perf_doctor --ci rejected the "
              f"retune artifacts (rc={proc.returncode})",
              file=sys.stderr)
        return 1
    with open(report_path) as f:
        report = json.load(f)
    assert "retunes" in report and not report["schema_errors"], report
    print("retune_smoke: OK — artifact round-trip through "
          "perf_doctor --ci validated")
    return 0


if __name__ == "__main__":
    sys.exit(main())
