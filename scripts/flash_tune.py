"""Flash-attention schedule tuner — runs on the live TPU chip.

Sweeps resident-schedule block shapes / chunking / cast-scratch on the
bench's D=128 shape and prints a TFLOPs table (matmul peak measured
interleaved so fractions are window-robust on the shared chip).

Usage: python scripts/flash_tune.py [rounds]
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from accl_tpu.bench.timing import make_harness
from accl_tpu.ops.flash import flash_attention_packed as fap

B, T, H, D = 4, 2048, 4, 128
ROUNDS = int(sys.argv[1]) if len(sys.argv) > 1 else 6


def main():
    print(f"backend={jax.default_backend()}", file=sys.stderr)
    _probe, timed_chain, _ab, _sync = make_harness(jax, jnp)

    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(k1, (B * H, T, D), jnp.float32)
    k = jax.random.normal(k2, (B * H, T, D), jnp.float32)
    v = jax.random.normal(k3, (B * H, T, D), jnp.float32)

    mm_n = 4096
    ka, kb = jax.random.split(jax.random.PRNGKey(7))
    ma = jax.random.normal(ka, (mm_n, mm_n), jnp.bfloat16)
    mb = jax.random.normal(kb, (mm_n, mm_n), jnp.bfloat16)
    mm = lambda x, y: (x @ y).astype(jnp.bfloat16)

    def variant(kernel, bq, bk, ck, cast, qt=1):
        def fn(x, kk, vv):
            return fap(x, kk, vv, causal=True, kernel=kernel,
                       block_q=bq, block_k=bk, chunk_k=ck,
                       kv_cast_scratch=cast, q_tiles=qt)
        return fn

    cands = {}
    for bq, bk in ((256, 512), (512, 512), (256, 256), (512, 256),
                   (1024, 512), (512, 1024), (256, 1024)):
        cands[f"res_bq{bq}_bk{bk}"] = variant("resident", bq, bk, None,
                                              False)
    for bq, bk, ck in ((256, 512, 256), (512, 512, 256), (512, 512, 128),
                       (256, 512, 128)):
        cands[f"res_bq{bq}_bk{bk}_ck{ck}"] = variant(
            "resident", bq, bk, ck, False)
    for bq, bk in ((256, 512), (512, 512)):
        cands[f"res_bq{bq}_bk{bk}_cast"] = variant("resident", bq, bk,
                                                   None, True)
    for bq, bk, ck, qt in ((256, 512, None, 2), (512, 512, None, 2),
                           (512, 512, None, 4), (256, 512, None, 4),
                           (512, 512, 256, 2), (256, 512, 256, 2),
                           (512, 1024, None, 2)):
        ckn = f"_ck{ck}" if ck else ""
        cands[f"res_bq{bq}_bk{bk}{ckn}_qt{qt}"] = variant(
            "resident", bq, bk, ck, False, qt)

    only = os.environ.get("FLASH_TUNE_ONLY")
    if only:
        keep = [s.strip() for s in only.split(",")]
        cands = {n: f for n, f in cands.items()
                 if any(s in n for s in keep)}

    import time as _time

    best = {n: None for n in cands}
    best_mm = None
    dead = set()
    for r in range(ROUNDS):
        d = timed_chain(mm, ma, iters=48, trials=1, consts=(mb,))
        best_mm = d if best_mm is None else min(best_mm, d)
        for name, fn in cands.items():
            if name in dead:
                continue
            t0 = _time.perf_counter()
            try:
                dv = timed_chain(fn, q, iters=64, trials=1, consts=(k, v))
            except Exception as e:  # noqa: BLE001
                dead.add(name)
                best[name] = f"{type(e).__name__}: {e}"
                print(f"  {name}: DEAD {e}", file=sys.stderr, flush=True)
                continue
            wall = _time.perf_counter() - t0
            print(f"  [r{r}] {name}: {dv * 1e3:.2f} ms "
                  f"(wall {wall:.0f}s)", file=sys.stderr, flush=True)
            prev = best[name]
            best[name] = dv if prev is None else min(prev, dv)
        print(f"[round {r}] done", file=sys.stderr, flush=True)

    flops = 4 * B * H * T * T * D / 2
    mm_tf = 2 * mm_n**3 / best_mm / 1e12
    print(f"matmul_bf16: {mm_tf:.1f} TFLOPs")
    rows = []
    for name, dt in best.items():
        if isinstance(dt, float):
            tf = flops / dt / 1e12
            rows.append((tf, name))
        else:
            rows.append((0.0, f"{name} [{dt}]"))
    for tf, name in sorted(rows, reverse=True):
        print(f"  {name:32s} {tf:7.2f} TF  frac={tf / mm_tf:.3f}")


if __name__ == "__main__":
    main()
