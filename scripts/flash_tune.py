"""Flash-attention schedule tuner — runs on the live TPU chip.

Sweeps resident-schedule block shapes / chunking / q-tile interleave /
fused-denominator on the bench's D=128 shape and prints a TFLOPs table
(matmul peak measured interleaved so fractions are window-robust on the
shared chip).  The sweep loop itself lives in
accl_tpu.bench.flash_sweep (shared with scripts/chip_session.py).

Usage: python scripts/flash_tune.py [rounds]
Env:   FLASH_TUNE_ONLY=substr1,substr2   filter candidates
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from accl_tpu.bench.flash_sweep import make_variant, report, run_sweep
from accl_tpu.utils.compile_cache import enable as _enable_cache

_enable_cache()

ROUNDS = int(sys.argv[1]) if len(sys.argv) > 1 else 6


def main():
    print(f"backend={jax.default_backend()}", file=sys.stderr)
    from accl_tpu.bench.timing import make_harness

    _probe, timed_chain, _ab, _sync = make_harness(jax, jnp)

    cands = {}
    for bq, bk in ((256, 512), (512, 512), (256, 256), (512, 256),
                   (1024, 512), (512, 1024), (256, 1024)):
        cands[f"res_bq{bq}_bk{bk}"] = make_variant(bq, bk)
    for bq, bk, ck in ((256, 512, 256), (512, 512, 256), (512, 512, 128),
                       (256, 512, 128)):
        cands[f"res_bq{bq}_bk{bk}_ck{ck}"] = make_variant(bq, bk, ck=ck)
    for bq, bk in ((256, 512), (512, 512)):
        cands[f"res_bq{bq}_bk{bk}_cast"] = make_variant(bq, bk, cast=True)
    for bq, bk, ck, qt in ((256, 512, None, 2), (512, 512, None, 2),
                           (512, 512, None, 4), (256, 512, None, 4),
                           (512, 512, 256, 2), (256, 512, 256, 2),
                           (512, 1024, None, 2)):
        ckn = f"_ck{ck}" if ck else ""
        cands[f"res_bq{bq}_bk{bk}{ckn}_qt{qt}"] = make_variant(
            bq, bk, ck=ck, qt=qt)
    for bq, bk, qt in ((256, 512, 1), (512, 512, 2), (256, 512, 2)):
        cands[f"res_bq{bq}_bk{bk}_qt{qt}_fd"] = make_variant(
            bq, bk, qt=qt, fd=True)

    only = os.environ.get("FLASH_TUNE_ONLY")
    if only:
        keep = [s.strip() for s in only.split(",")]
        cands = {n: f for n, f in cands.items()
                 if any(s in n for s in keep)}

    best, best_mm = run_sweep(jax, jnp, timed_chain, cands, rounds=ROUNDS)
    res = report(best, best_mm)
    print(f"matmul_bf16: {res['matmul_bf16_tflops']:.1f} TFLOPs")
    rows = sorted(res["schedules"].items(),
                  key=lambda kv: -kv[1].get("tflops", 0.0))
    for name, r in rows:
        if "tflops" in r:
            print(f"  {name:32s} {r['tflops']:7.2f} TF  "
                  f"frac={r['mxu_frac']:.3f}")
        else:
            print(f"  {name:32s} [{r['error']}]")


if __name__ == "__main__":
    main()
