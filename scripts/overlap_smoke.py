#!/usr/bin/env python
"""CI overlap smoke: the r18 fused compute/communication gate.

Two rungs, both on the CPU/interpret rung (4 virtual devices), both
under ``ACCL_DEVICE_TRACE=1``:

1. **Device timeline** — run the chunked ring allreduce at C=1 (the
   sequential 3-phase stamp clock) and C=4 (the overlapped clock),
   schema-validate every per-chunk stamp row (rank/step ordering, ring
   neighbor attribution, per-hop bytes, the exact clock for each
   chunking), and assert ``attribution.device_overlap`` reports the
   fused timeline's exposed-wire fraction strictly below the
   sequential one (which must sit at 1.0).

2. **Driver A/B** — one `bench.sweep.run_fused_overlap_sweep` cell
   per wire lane (>= 64 KiB allreduce, fp32 + int8) through the real
   TPU-backend gang dispatch: the fused arm's measured
   ``attribution.overlap`` exposed-wire fraction must come back
   strictly below the sequential arm's.

Artifacts: the Perfetto doc with the device stamp tracks and a JSON
report with the A/B rows + device_overlap accounting (uploaded by
.github/workflows/build-and-test.yml perf-gate).

Usage: python scripts/overlap_smoke.py [--ranks N] [--trace PATH]
       [--report PATH]
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_device_rung(ranks: int) -> dict:
    """Ops-level C=1 vs C=4 chunked allreduce under the stamp plane;
    returns the schema-validated device_overlap accounting."""
    import numpy as np

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    import accl_tpu.ops.fused as fused
    import accl_tpu.ops.ring as ring
    from accl_tpu.observability import attribution
    from accl_tpu.observability import trace as obs_trace
    from accl_tpu.parallel import make_mesh

    assert len(jax.devices()) >= ranks, (
        f"device rung needs {ranks} devices (set XLA_FLAGS="
        f"--xla_force_host_platform_device_count={ranks})")
    ring._reset_device_trace_cache()
    assert ring.device_trace_enabled(), "ACCL_DEVICE_TRACE not armed"
    obs_trace.collector().clear()
    mesh = make_mesh(dp=ranks)

    def runner(chunks, collective):
        def body(xb):
            return fused.chunked_ring_all_reduce(
                xb[0], "dp", chunks=chunks, collective=collective)[None]

        try:
            f = shard_map(body, mesh=mesh, in_specs=P("dp", None),
                          out_specs=P("dp", None), check_vma=False)
        except TypeError:  # older shard_map spells the flag check_rep
            f = shard_map(body, mesh=mesh, in_specs=P("dp", None),
                          out_specs=P("dp", None), check_rep=False)
        x = np.stack([np.arange(1024, dtype=np.float32) + r
                      for r in range(ranks)])
        xs = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
        out = np.asarray(jax.jit(f)(xs))
        np.testing.assert_allclose(out[0], x.sum(axis=0), rtol=1e-5)

    C = 4
    runner(1, "seq_allreduce")
    runner(C, "fused_allreduce")

    # schema validation: every stamp row, per collective
    fields = obs_trace.DEVICE_TRACE_FIELDS
    rows_by = {}
    for rec in obs_trace.collector().device_records():
        rows_by.setdefault(rec["collective"], []).extend(
            dict(zip(fields, r)) for r in rec["rows"])
    assert set(rows_by) == {"seq_allreduce", "fused_allreduce"}, \
        f"unexpected collectives: {sorted(rows_by)}"
    for coll, rows in rows_by.items():
        seen_ranks = set()
        for row in rows:
            seen_ranks.add(row["rank"])
            assert row["tx_peer"] == (row["rank"] + 1) % ranks, row
            assert row["rx_peer"] == (row["rank"] - 1) % ranks, row
            assert row["tx_bytes"] > 0 and row["rx_bytes"] > 0, row
            assert row["seq_send"] < row["seq_wait"] < row["seq_phase"]
            if coll == "seq_allreduce":  # sequential 3-phase clock
                assert row["seq_send"] == 3 * row["step"], row
                assert row["seq_wait"] == row["seq_send"] + 1, row
            else:  # overlapped clock: xfer(i+1) covers reduce(i)
                assert row["seq_send"] == 2 * row["step"], row
                assert row["seq_wait"] == row["seq_send"] + 2, row
                assert row["seq_phase"] == row["seq_send"] + 4, row
        assert seen_ranks == set(range(ranks)), (coll, seen_ranks)
    # RS + AG phases: (P-1)*C slots each, per rank
    assert len(rows_by["seq_allreduce"]) == ranks * 2 * (ranks - 1)
    assert len(rows_by["fused_allreduce"]) == ranks * 2 * (ranks - 1) * C

    dev = attribution.device_overlap(obs_trace.collector().to_perfetto())
    seq = dev["collectives"]["seq_allreduce"]
    fus = dev["collectives"]["fused_allreduce"]
    assert abs(seq["exposed_fraction"] - 1.0) < 1e-6, seq
    assert fus["exposed_fraction"] < seq["exposed_fraction"], (seq, fus)
    print(f"[overlap-smoke] device timeline: sequential exposed "
          f"{seq['exposed_fraction']:.3f}, fused exposed "
          f"{fus['exposed_fraction']:.3f} (recovered-MXU "
          f"{fus['recovered_mxu_fraction']:.1%})")
    return dev


def run_driver_rung(ranks: int) -> list:
    """One fused-overlap A/B cell per wire lane through the TPU-backend
    gang dispatch; asserts fused exposed < sequential exposed."""
    from accl_tpu.backends.tpu import TpuWorld
    from accl_tpu.bench.sweep import run_fused_overlap_sweep

    with TpuWorld(ranks) as world:
        rows = run_fused_overlap_sweep(
            world, collectives=("allreduce",), count_pows=(14,),
            repetitions=2,
            log=lambda s: print(f"[overlap-smoke]{s}"))
    cells = {}
    for r in rows:
        cells.setdefault((r["wire"], r["collective"], r["count"]),
                         {})[r["mode"]] = r
    assert cells, "A/B sweep produced no rows"
    for key, modes in cells.items():
        seq, fus = modes["sequential"], modes["fused"]
        assert seq["exposed_wire_fraction"] is not None, seq
        assert fus["exposed_wire_fraction"] is not None, fus
        assert (fus["exposed_wire_fraction"]
                < seq["exposed_wire_fraction"]), (key, seq, fus)
        print(f"[overlap-smoke] driver {key}: sequential exposed "
              f"{seq['exposed_wire_fraction']:.3f} -> fused "
              f"{fus['exposed_wire_fraction']:.3f}")
    return rows


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=4)
    ap.add_argument("--trace", default="overlap_timeline.json")
    ap.add_argument("--report", default="overlap_smoke_report.json")
    args = ap.parse_args()

    # arm the stamp plane + virtual devices BEFORE jax/accl import
    os.environ["ACCL_DEVICE_TRACE"] = "1"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count="
            f"{args.ranks}").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    dev = run_device_rung(args.ranks)

    from accl_tpu.observability import trace as obs_trace

    obs_trace.collector().dump(args.trace)

    ab_rows = run_driver_rung(args.ranks)

    with open(args.report, "w") as f:
        json.dump({"ranks": args.ranks, "device_overlap": dev,
                   "driver_ab": ab_rows}, f, indent=1)
    print(f"[overlap-smoke] OK — report {args.report}, "
          f"timeline {args.trace}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
