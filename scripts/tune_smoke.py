#!/usr/bin/env python
"""tune_smoke: CI gate for the r16 topology-aware autotuner.

One command proves the whole tuning pipeline on a 4-rank emu world:

1. mini-sweep the algorithm lanes (static/flat/tree/hierarchical) and
   build a selection table (accl_tpu/tuning/autotune.tune);
2. verify tuned-vs-static in the same session (interleaved best-of;
   unreproducible selections pruned) — HARD gate: no cell of the
   verified record may be > 1.05x slower than static;
3. persist the table, re-load it through the ACCL_TUNE_TABLE policy
   path in a FRESH world, and assert the policy actually armed (the
   tuning/selected metric family appears, registers were rewritten);
4. advisory comparison of the tuned lane against the committed
   ``sweep_gate_baseline_r12.csv`` durations (shared CI cores swing
   3x, so only a catastrophic ratio fails).

Artifacts: tune_table.json (the table, uploaded by CI) and
tune_compare.csv (the verification record).
"""
import argparse
import csv
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def baseline_durations() -> dict:
    """(collective, count) -> best duration_us of the newest committed
    sweep-gate baseline — parsing + newest-round selection shared with
    scripts/check_bench_delta.py (one schema, one rule)."""
    sys.path.insert(0, os.path.join(ROOT, "scripts"))
    from check_bench_delta import _round_of, _sweep_best

    paths = sorted(
        glob.glob(os.path.join(ROOT, "bench", "results",
                               "sweep_gate_baseline_r*.csv")),
        key=_round_of)
    return _sweep_best(paths[-1]) if paths else {}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=4)
    ap.add_argument("--table", default="tune_table.json")
    ap.add_argument("--compare-out", default="tune_compare.csv")
    ap.add_argument("--baseline-ratio", type=float, default=10.0,
                    help="fresh tuned duration vs committed baseline "
                         "fail ratio (generous: shared CI cores)")
    args = ap.parse_args()

    # same receive-budget widening as tests/conftest.py: a loaded CI
    # core can stall a rank past the reference 1 s default mid-sweep
    os.environ.setdefault("ACCL_DEFAULT_TIMEOUT", "30000000")

    from accl_tpu.backends.emu import EmuWorld
    from accl_tpu.observability import metrics as _metrics
    from accl_tpu.tuning import TuneConfig, autotune

    # allgather rejoined the sweep in r21: the 8-rank concurrent
    # sub-comm wedge that kept it out of the r16 corpus (hierarchical
    # allgather's row/col sub-comm traffic hit intermittent
    # RECEIVE_TIMEOUTs) was root-caused to cross-comm rx-pool pinning
    # and fixed in the engine — model_check.py's subcomm_allgather
    # drills hold the invariant in CI now
    cfg = TuneConfig(
        collectives=("allreduce", "allgather", "bcast", "gather", "reduce"),
        count_pows=(8, 12, 14), repetitions=2, shape=(2, 2),
        measured_demotion=False)

    def world():
        return EmuWorld(args.ranks, devmem_bytes=256 << 20,
                        n_egr_rx_bufs=64, max_eager_size=16384,
                        max_rendezvous_size=64 << 20)

    # -- 1+2: tune, verify, prune ------------------------------------
    w = world()
    try:
        table = autotune.tune(w, cfg, log=print)
        assert table.entries, "tuner produced an empty table"
        rows = autotune.compare(w, table, cfg, log=print)
    finally:
        w.close()
    slow = [r for r in rows if r["ratio"] < 1.0 / 1.05]
    if slow:
        print(f"tune_smoke: FAIL — {len(slow)} verified cells slower "
              f"than 1/1.05x static: {slow}", file=sys.stderr)
        return 1
    tuned_cells = [r for r in rows if r["algorithm"] != "static"]
    print(f"tune_smoke: verified {len(rows)} cells, "
          f"{len(tuned_cells)} non-static selections, "
          f"{sum(1 for r in rows if r['ratio'] >= 1.15)} wins >= 1.15x")
    # per-dtype table (r17): the float32 sweep must have MEASURED the
    # compression lanes — the argmax may or may not pick them on a
    # given box, but the lanes must be in the candidate set
    lanes_measured = autotune.algorithms_for(w, cfg.dtype)
    assert set(autotune.COMPRESSION_ALGS) <= set(lanes_measured), \
        f"compression lanes missing from the float32 sweep: " \
        f"{lanes_measured}"
    comp_cells = [e for e in table.entries.values()
                  if e["algorithm"] in autotune.COMPRESSION_ALGS]
    print(f"tune_smoke: compression lanes swept "
          f"({len(comp_cells)} cells selected a compressed wire)")

    table.save(args.table)
    with open(args.compare_out, "w", newline="") as f:
        cw = csv.DictWriter(f, fieldnames=list(rows[0]))
        cw.writeheader()
        cw.writerows(rows)

    # -- 3: the ACCL_TUNE_TABLE policy path in a fresh world ----------
    doc = json.load(open(args.table))
    assert doc["format"] == "accl-tune-table" and doc["version"] == 1, doc
    os.environ["ACCL_TUNE_TABLE"] = os.path.abspath(args.table)
    try:
        import numpy as np

        w = world()
        try:
            assert all(a._tune_policy is not None for a in w.accls), \
                "policy did not arm from ACCL_TUNE_TABLE"

            def body(accl, rank):
                s = accl.create_buffer_like(
                    np.arange(4096, dtype=np.float32))
                r = accl.create_buffer(4096, np.float32)
                accl.allreduce(s, r, 4096)
                return r.host.copy()

            outs = w.run(body)
            if any(a.compression_policy is not None for a in w.accls):
                # a table that armed a compressed wire is a LOSSY lane
                # by contract: ranks agree within relay requantization
                # ulp, not bitwise (docs/performance.md error model)
                exact = np.arange(4096, dtype=np.float32) * args.ranks
                # documented bound: ~P half-steps of the block absmax
                bound = args.ranks * float(exact.max()) / 127.0
                for o in outs:
                    np.testing.assert_allclose(o, exact, atol=bound)
                    np.testing.assert_allclose(o, outs[0], rtol=1e-5,
                                               atol=1e-2)
            else:
                assert all(np.array_equal(o, outs[0]) for o in outs)
            counters = _metrics.default_registry().snapshot()["counters"]
            selected = {k: v for k, v in counters.items()
                        if k.startswith("tuning/selected/")}
            assert selected, (
                "armed policy published no tuning/selected counters: "
                f"{sorted(counters)[:20]}")
            print(f"tune_smoke: policy armed, selections {selected}")
            # a table whose cells picked a compress_* lane must have
            # armed the driver's CompressionPolicy at install (r17)
            if comp_cells:
                assert all(a.compression_policy is not None
                           for a in w.accls), \
                    "compress_* table cells did not arm a " \
                    "CompressionPolicy"
                print("tune_smoke: compression policy armed from the "
                      f"table: {w.accls[0].compression_policy.spec()}")
        finally:
            w.close()
    finally:
        del os.environ["ACCL_TUNE_TABLE"]

    # -- 4: advisory gate vs the committed sweep baseline -------------
    base = baseline_durations()
    bad = []
    for r in rows:
        key = (r["collective"], r["count"])
        if key not in base or not base[key]:
            continue
        # reconstruct the tuned duration from the verified busbw
        from accl_tpu.observability.metrics import busbw_factor

        bw = r["tuned_busbw_GBps"]
        if not bw:
            continue
        dur_us = (r["bytes"] * busbw_factor(r["collective"], args.ranks)
                  / bw / 1e9) * 1e6
        ratio = dur_us / base[key]
        if ratio > args.baseline_ratio:
            bad.append((key, round(ratio, 1)))
        else:
            print(f"tune_smoke: {r['collective']} count={r['count']} "
                  f"tuned {dur_us:.0f}us vs baseline {base[key]:.0f}us "
                  f"({ratio:.1f}x)")
    if bad:
        print(f"tune_smoke: FAIL — tuned lane catastrophically slower "
              f"than the committed baseline: {bad}", file=sys.stderr)
        return 1
    print("tune_smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
