"""On-chip tuning sweep for the flash-attention and compression kernels.

Run manually on TPU hardware to pick kernel defaults:

    python scripts/kernel_tune.py flash
    python scripts/kernel_tune.py compress

Methodology matches bench.py: chained iterations (output feeds the next
call), completion forced by scalar readback, sync RTT subtracted, best
of interleaved trials (the chip is shared; the fastest window estimates
hardware capability).
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))


def _setup():
    """The shared chained-timing harness (accl_tpu.bench.timing) — the
    same methodology as bench.py by construction."""
    import jax
    import jax.numpy as jnp

    from accl_tpu.bench.timing import make_harness

    print(f"[tune] backend={jax.default_backend()}", file=sys.stderr)
    probe, timed_chain, _ab, _sync = make_harness(jax, jnp)
    return jax, jnp, probe, timed_chain


def tune_flash():
    jax, jnp, _probe, timed_chain = _setup()
    from accl_tpu.ops.flash import flash_attention

    B, T, H, D = 4, 2048, 8, 64
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(k1, (B, T, H, D), jnp.float32)
    k = jax.random.normal(k2, (B, T, H, D), jnp.float32)
    v = jax.random.normal(k3, (B, T, H, D), jnp.float32)
    flops = 4 * B * H * T * T * D / 2  # causal

    combos = []
    for kernel in ("resident", "grid"):
        for bq, bk in ((128, 512), (256, 256), (256, 512), (256, 1024),
                       (512, 512), (512, 1024), (1024, 512)):
            combos.append((kernel, bq, bk))

    results = {}
    fns = {}
    for kernel, bq, bk in combos:
        def fa(x, kk, vv, kernel=kernel, bq=bq, bk=bk):
            return flash_attention(x, kk, vv, causal=True, block_q=bq,
                                   block_k=bk, kernel=kernel)
        try:
            # viability probe at the TIMING iteration count so the
            # compiled chain is the one the timing rounds reuse
            timed_chain(fa, q, iters=64, trials=1, consts=(k, v))
            fns[(kernel, bq, bk)] = fa
        except Exception as e:
            print(f"[tune] {kernel} bq={bq} bk={bk}: {type(e).__name__}: "
                  f"{str(e)[:120]}", file=sys.stderr)

    # interleaved best-window: one trial of each per round
    for _ in range(6):
        for key, fa in fns.items():
            dt = timed_chain(fa, q, iters=64, trials=1, consts=(k, v))
            if key not in results or dt < results[key]:
                results[key] = dt

    for key in sorted(results, key=lambda kk: results[kk]):
        kernel, bq, bk = key
        print(f"{kernel:9s} bq={bq:5d} bk={bk:5d}  "
              f"{flops / results[key] / 1e12:7.2f} TFLOPs")


def tune_compress():
    jax, jnp, _probe, timed_chain = _setup()
    import functools

    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # 256 MB: larger than on-chip scratch (a smaller chained loop gets
    # pinned in S(1) memory and measures on-chip, not HBM, bandwidth).
    # 2D carry so chained iterations don't pay relayout copies.
    n = 64 << 20
    x = jax.random.normal(jax.random.PRNGKey(3), (n // 512, 512),
                          jnp.float32)

    @functools.partial(jax.jit, static_argnames=("dtype", "cols",
                                                 "block_rows"))
    def cast2d(v, dtype, cols, block_rows):
        v2 = v.reshape(-1, cols)
        rows = v2.shape[0]
        br = min(block_rows, rows)
        spec = pl.BlockSpec((br, cols), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
        out = pl.pallas_call(
            lambda x_ref, o_ref: o_ref.__setitem__(
                slice(None), x_ref[:].astype(dtype)),
            out_shape=jax.ShapeDtypeStruct(v2.shape, dtype),
            grid=(pl.cdiv(rows, br),),
            in_specs=[spec], out_specs=spec,
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel",)),
        )(v2)
        return out.reshape(v.shape)

    nbytes = n * 12  # 4+2 down, 2+4 up

    results = {}
    fns = {}
    for cols in (128, 512, 1024, 4096):
        for br in (256, 1024, 4096, 16384):
            if (n // cols) < br:
                continue

            def rt(v, cols=cols, br=br):
                return cast2d(cast2d(v, jnp.bfloat16, cols, br),
                              jnp.float32, cols, br)
            try:
                timed_chain(rt, x, iters=24, trials=1)  # compile + warm
                fns[(cols, br)] = rt
            except Exception as e:
                print(f"[tune] cols={cols} br={br}: {type(e).__name__}: "
                      f"{str(e)[:120]}", file=sys.stderr)

    # XLA ceiling, interleaved with the rest (both casts barriered so
    # the simplifier can't fold convert(convert(x)) across iterations)
    def xla_rt(v):
        h = lax.optimization_barrier(v.astype(jnp.bfloat16))
        return lax.optimization_barrier(h.astype(jnp.float32))

    fns[("xla", 0)] = xla_rt

    for _ in range(6):
        for key, fn in fns.items():
            dt = timed_chain(fn, x, iters=24, trials=1)
            if key not in results or dt < results[key]:
                results[key] = dt

    for key in sorted(results, key=lambda kk: results[kk]):
        cols, br = key
        print(f"cols={cols!s:>5} block_rows={br:6d}  "
              f"{nbytes / results[key] / 1e9:7.2f} GB/s")


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "flash"
    {"flash": tune_flash, "compress": tune_compress}[which]()
