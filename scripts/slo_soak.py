#!/usr/bin/env python
"""slo_soak: CI drill for the r20 per-tenant SLO observatory.

A 2-tenant world on one 4-rank emu fabric — ``decode`` (small
latency-critical allreduces on its own labeled communicator) and
``prefill`` (bulk allgather traffic) — driven through kill + join +
traffic-spike chaos with a :class:`~accl_tpu.observability.slo.
SLOTracker` enforcing per-tenant latency SLOs the whole way.  The
drill FAILS ON BUDGET EXHAUSTION, not just on wrong bits: correctness
drills (chaos_smoke) already pin bitwise recovery; this one pins that
recovery is fast enough to keep a latency-critical tenant inside its
error budget.

Deterministic shape (no timer threads — the harness drives
``tracker.check()`` explicitly, one sweep per traffic round):

1. **healthy phase** — warm traffic on both tenant communicators;
   the observed per-tenant histograms derive the SLO spec (ceilings
   two power-of-4 buckets above the healthy quantiles), written to
   ``slo_spec.json`` and round-tripped through
   :func:`~accl_tpu.observability.slo.load_specs` — the exact
   ``ACCL_SLO`` file format;
2. **traffic spike** — prefill multiplies its bulk volume while
   decode keeps its small calls: contention burns decode budget, the
   tracker's fast/slow windows watch;
3. **kill + shrink** — one rank dies mid-sweep; survivors classify,
   abort the tenant communicators, and remint decode on the survivor
   set (the latency-critical tenant stays on stable membership);
4. **join + grow** — a replacement announces on the membership board,
   survivors shrink the world comm and admit it
   (:func:`~accl_tpu.resilience.elastic.admit_pending`); the grown
   communicator becomes the prefill tenant's new lane, the joiner
   fully participating;
5. **the gate** — the healthy run must end with NO tenant's budget
   exhausted; then a DELIBERATELY-STARVED control tracker (a decode
   p99 ceiling below the first histogram bucket) replays real traffic
   and MUST exhaust — proving the gate actually fails when an SLO
   cannot be met, not only that it passes when one can;
6. artifacts (``slo_report.json`` — the exporter's ``/slo`` body with
   the per-tenant link-matrix slices merged in — plus the spec, the
   control report, the merged flight dump and a metrics snapshot) are
   round-tripped through ``scripts/perf_doctor.py --slo --ci`` in a
   subprocess: the doctor must schema-validate and render both
   tenants' matrices.

Usage: python scripts/slo_soak.py [--ranks 4] [--seed 7] [--out-dir .]
"""
import argparse
import json
import os
import subprocess
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bucket_ceiling(us: float, up: int = 2) -> float:
    """The smallest power-of-4 bucket bound >= ``us``, raised ``up``
    more buckets — histogram-native headroom (violation counting is
    per-bucket, so ceilings live on bucket bounds)."""
    from accl_tpu.observability.metrics import LATENCY_BUCKETS_US

    idx = len(LATENCY_BUCKETS_US) - 1
    for i, ub in enumerate(LATENCY_BUCKETS_US):
        if ub >= us:
            idx = i
            break
    return float(LATENCY_BUCKETS_US[min(idx + up,
                                        len(LATENCY_BUCKETS_US) - 1)])


def _tenant_hist(snap: dict, tenant: str, collective: str) -> list:
    from accl_tpu.observability.metrics import LATENCY_BUCKETS_US

    hist = [0] * (len(LATENCY_BUCKETS_US) + 1)
    for doc in snap.get("tenant_calls", {}).values():
        if doc["tenant"] == tenant and doc["collective"] == collective:
            for i, ub in enumerate(LATENCY_BUCKETS_US):
                hist[i] += doc["hist_us"][f"le_{ub}"]
            hist[-1] += doc["hist_us"]["inf"]
    return hist


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=4)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--decode-count", type=int, default=256,
                    help="elements per latency-critical allreduce")
    ap.add_argument("--prefill-count", type=int, default=8192,
                    help="elements per bulk allgather contribution")
    ap.add_argument("--warm", type=int, default=8,
                    help="healthy sweeps before the spec is derived")
    ap.add_argument("--spike", type=int, default=4,
                    help="traffic-spike sweeps (prefill volume x4)")
    ap.add_argument("--post", type=int, default=3,
                    help="sweeps after the join/grow")
    ap.add_argument("--out-dir", default=".")
    args = ap.parse_args()

    # same receive-budget widening as tests/conftest.py; the kill
    # phase rides the 3 s classification clock set below, never this
    os.environ.setdefault("ACCL_DEFAULT_TIMEOUT", "30000000")

    import numpy as np

    from accl_tpu import ACCLError, ErrorCode, ReduceFunction
    from accl_tpu.backends.emu import EmuWorld
    from accl_tpu.observability import flight as obs_flight
    from accl_tpu.observability import metrics as _metrics
    from accl_tpu.observability.slo import SLOTracker, load_specs
    from accl_tpu.resilience.elastic import admit_pending

    nranks = args.ranks
    victim = nranks - 1
    survivors = [r for r in range(nranks) if r != victim]
    registry = _metrics.default_registry()
    os.makedirs(args.out_dir, exist_ok=True)
    summary: dict = {"seed": args.seed, "ranks": nranks}

    world = EmuWorld(nranks, devmem_bytes=256 << 20, n_egr_rx_bufs=64,
                     max_eager_size=16384,
                     max_rendezvous_size=64 << 20)
    try:
        for a in world.accls:
            a.set_timeout(3_000_000)  # 3 s classification clock

        # -- tenant communicators over the shared fabric ---------------
        ids = world.run(lambda a, r: (
            a.create_communicator(list(range(nranks)), tenant="decode"),
            a.create_communicator(list(range(nranks)), tenant="prefill")))
        decode_id, prefill_id = ids[0]
        assert all(i == ids[0] for i in ids), ids

        def traffic(accl, rank, d_id, p_id, decode_calls=4,
                    prefill_calls=1, check_bits=False):
            d_size = accl.communicator(d_id).size
            for _ in range(decode_calls):
                s = accl.create_buffer(args.decode_count, np.float32)
                s.host[:] = float(rank + 1)
                r = accl.create_buffer(args.decode_count, np.float32)
                accl.allreduce(s, r, args.decode_count,
                               ReduceFunction.SUM, comm_id=d_id)
                if check_bits:
                    ranks = [rk.session for rk in
                             accl.communicator(d_id).ranks]
                    want = float(sum(x + 1 for x in ranks))
                    assert np.all(r.host == want), \
                        f"decode allreduce wrong bits on rank {rank}"
            if p_id is not None:
                p_size = accl.communicator(p_id).size
                for _ in range(prefill_calls):
                    s = accl.create_buffer(args.prefill_count,
                                           np.float32)
                    s.host[:] = float(rank)
                    r = accl.create_buffer(
                        args.prefill_count * p_size, np.float32)
                    accl.allgather(s, r, args.prefill_count,
                                   comm_id=p_id)
            return d_size

        # -- phase 1: healthy traffic -> derived SLO spec --------------
        for _ in range(args.warm):
            world.run(traffic, decode_id, prefill_id, 4, 1, True)
        snap = registry.snapshot()
        from accl_tpu.observability.sentinel import quantile_us

        d_hist = _tenant_hist(snap, "decode", "allreduce")
        assert sum(d_hist), "warm phase published no decode histograms"
        p50_ceil = _bucket_ceiling(quantile_us(d_hist, 0.5))
        p99_ceil = _bucket_ceiling(quantile_us(d_hist, 0.99))
        spec_doc = {
            "format": "accl-slo-spec", "version": 1,
            "slos": [
                # latency objectives see SUCCESSFUL calls only (r8
                # histogram semantics); track_errors makes the kill
                # phase's classified failures burn the availability
                # budget — visibly, without exhausting it: exhaustion
                # is reserved for recovery that is SLOW
                {"tenant": "decode", "collective": "allreduce",
                 "size_bucket": "*", "p50_us": p50_ceil,
                 "p99_us": p99_ceil,
                 "availability": 0.75, "track_errors": True},
                {"tenant": "prefill", "collective": "allgather",
                 "size_bucket": "*",
                 "p99_us": _bucket_ceiling(
                     quantile_us(_tenant_hist(snap, "prefill",
                                              "allgather"), 0.99)),
                 "availability": 0.75, "track_errors": True},
            ],
        }
        spec_path = os.path.join(args.out_dir, "slo_spec.json")
        with open(spec_path, "w") as f:
            json.dump(spec_doc, f, indent=1)
        specs = load_specs(spec_path)  # the ACCL_SLO file round-trip
        summary["spec"] = {"decode_p50_us": p50_ceil,
                           "decode_p99_us": p99_ceil}
        print(f"slo_soak: derived spec from {args.warm} healthy "
              f"sweeps — decode p50<={p50_ceil:.0f}us "
              f"p99<={p99_ceil:.0f}us")

        tracker = SLOTracker(specs, registry=registry, fast_window=2,
                             slow_window=8, fast_burn=8.0,
                             slow_burn=2.0, min_calls=8)
        tracker.check()  # absorb the pre-tracker cumulative history

        # -- phase 2: prefill traffic spike ----------------------------
        for _ in range(args.spike):
            world.run(traffic, decode_id, prefill_id, 4, 4)
            tracker.check()
        spike_doc = tracker.doc()
        summary["after_spike"] = {
            t: d["verdict"] for t, d in spike_doc["tenants"].items()}
        print(f"slo_soak: spike phase verdicts {summary['after_spike']}")

        # -- phase 3: kill -> classify -> abort -> remint decode -------
        state: dict = {}

        def kill_sweep(accl, rank):
            if rank == victim:
                world.kill_rank(victim)  # the engine goes silent
            try:
                traffic(accl, rank, decode_id, prefill_id, 4, 1)
                return ("clean", None)
            except ACCLError as e:
                if rank == victim:
                    return ("dead", int(getattr(e, "code", 0)))
                for cid in (decode_id, prefill_id, 0):
                    try:
                        accl.abort(cid,
                                   error=int(ErrorCode.RANK_FAILED))
                    except ACCLError:
                        pass
                new_decode = accl.create_communicator(
                    survivors, tenant="decode")
                # the latency-critical tenant is back: prove it inside
                # the same sweep
                traffic(accl, rank, new_decode, None, 4)
                return ("recovered", new_decode)

        results = world.run(kill_sweep)
        tracker.check()
        assert results[victim][0] == "dead", results[victim]
        new_decodes = {results[r][1] for r in survivors}
        assert len(new_decodes) == 1 and results[survivors[0]][0] == \
            "recovered", results
        decode_id = new_decodes.pop()
        print(f"slo_soak: rank {victim} killed; survivors reminted "
              f"decode as comm {decode_id}")

        # -- phase 4: join + grow; the grown comm is prefill's lane ----
        joiner = world.spawn_replacement()
        join_out: dict = {}

        def joined():
            cid = joiner.join(timeout_s=40.0)
            joiner.accl.set_timeout(40_000_000)
            joiner.accl.set_tenant(cid, "prefill")
            for _ in range(args.post):
                size = joiner.accl.communicator(cid).size
                s = joiner.accl.create_buffer(args.prefill_count,
                                              np.float32)
                s.host[:] = float(joiner.rank)
                r = joiner.accl.create_buffer(
                    args.prefill_count * size, np.float32)
                joiner.accl.allgather(s, r, args.prefill_count,
                                      comm_id=cid)
            join_out["comm"] = cid

        jt = threading.Thread(target=joined, daemon=True)
        jt.start()

        def grow_sweep(accl, rank):
            if rank == victim:
                return None
            shrunk = accl.shrink_communicator(0, window_s=2.0)
            grown, admitted = admit_pending(accl, shrunk, world.board,
                                            wait_s=15.0)
            assert admitted == 1, f"admitted {admitted} joiner(s)"
            accl.set_tenant(grown, "prefill")
            for _ in range(args.post):
                traffic(accl, rank, decode_id, grown, 4, 1)
            return grown

        grow_results = world.run(grow_sweep)
        jt.join(timeout=60)
        assert not jt.is_alive() and "comm" in join_out, \
            "replacement never finished its prefill loop"
        growns = {grow_results[r] for r in survivors}
        assert len(growns) == 1, grow_results
        tracker.check()
        tracker.check()  # idle sweep: burn decays on quiet windows
        print(f"slo_soak: replacement session {joiner.rank} joined; "
              f"prefill rides grown comm {growns.pop()}")

        # -- phase 5a: the healthy gate --------------------------------
        report = tracker.doc()
        matrices = {t: world.link_matrix(tenant=t)
                    for t in ("decode", "prefill")}
        for t, m in matrices.items():
            moved = sum(v for row in m["fields"]["tx_bytes"]
                        for v in row)
            assert moved > 0, f"tenant {t} link slice saw no traffic"
        report["link_matrices"] = matrices
        report_path = os.path.join(args.out_dir, "slo_report.json")
        with open(report_path, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
        verdicts = {t: d["verdict"]
                    for t, d in report["tenants"].items()}
        budgets = {t: d["budget_remaining"]
                   for t, d in report["tenants"].items()}
        summary["verdicts"] = verdicts
        summary["budgets"] = budgets
        print(f"slo_soak: healthy-run verdicts {verdicts}, budget "
              f"remaining {budgets}")
        if "exhausted" in verdicts.values():
            print(f"slo_soak: FAIL — a tenant exhausted its error "
                  f"budget during the soak: {verdicts} (recovery too "
                  f"slow for the declared SLO)", file=sys.stderr)
            return 1

        # -- phase 5b: starved control — the gate MUST fail ------------
        control = SLOTracker(
            [{"tenant": "decode", "collective": "allreduce",
              "size_bucket": "*", "p50_us": 4.0, "p99_us": 4.0,
              "availability": 0.99}],
            registry=registry, fast_window=2, slow_window=8,
            fast_burn=8.0, slow_burn=2.0, min_calls=8)
        control.check()  # absorb history; budget starts clean

        def control_sweep(accl, rank):
            if rank != victim:  # the dead rank has no decode comm
                traffic(accl, rank, decode_id, None, 4)

        for _ in range(3):
            world.run(control_sweep)
            control.check()
        control_doc = control.doc()
        control_path = os.path.join(args.out_dir,
                                    "slo_control_report.json")
        with open(control_path, "w") as f:
            json.dump(control_doc, f, indent=1, sort_keys=True)
        cv = control_doc["tenants"]["decode"]["verdict"]
        summary["control_verdict"] = cv
        if cv != "exhausted":
            print(f"slo_soak: FAIL — the deliberately-starved control "
                  f"run ended {cv!r}, not 'exhausted': the gate cannot "
                  f"be trusted to fail", file=sys.stderr)
            return 1
        print(f"slo_soak: control run exhausted its budget as "
              f"designed (budget_remaining "
              f"{control_doc['tenants']['decode']['budget_remaining']})")

        # -- artifacts -------------------------------------------------
        dump_path = os.path.join(args.out_dir, "slo_flight_dump.json")
        obs_flight.merge_flight_dumps(
            [a.flight_recorder.dump() for a in world.accls]
            + [j.accl.flight_recorder.dump() for j in world.joiners],
            out_path=dump_path)
        snap_path = os.path.join(args.out_dir, "slo_metrics.json")
        with open(snap_path, "w") as f:
            json.dump(registry.snapshot(), f, indent=1, sort_keys=True)
        with open(os.path.join(args.out_dir,
                               "slo_summary.json"), "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
    finally:
        world.close()

    # -- phase 6: the perf_doctor --slo --ci round-trip ----------------
    doctor_path = os.path.join(args.out_dir, "slo_doctor_report.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "perf_doctor.py"),
         "--slo", report_path, "--ci", "--out", doctor_path],
        capture_output=True, text=True)
    sys.stdout.write(proc.stdout)
    sys.stderr.write(proc.stderr)
    if proc.returncode != 0:
        print(f"slo_soak: FAIL — perf_doctor --slo --ci rejected the "
              f"report (rc={proc.returncode})", file=sys.stderr)
        return 1
    with open(doctor_path) as f:
        doctor = json.load(f)
    assert "slo" in doctor and not doctor["schema_errors"], doctor
    for t in ("decode", "prefill"):
        if f"tenant {t}" not in proc.stdout:
            print(f"slo_soak: FAIL — perf_doctor never rendered the "
                  f"{t} tenant's link-matrix slice", file=sys.stderr)
            return 1
    print("slo_soak: OK — 2-tenant soak survived kill + join + spike "
          "inside budget; starved control exhausted; doctor round-trip "
          "validated")
    return 0


if __name__ == "__main__":
    sys.exit(main())
