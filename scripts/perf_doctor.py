#!/usr/bin/env python
"""perf_doctor: offline collective-performance observatory report.

The offline twin of the live r14 machinery — one command turns dump
files into the same three-part report a running world exposes through
/metrics + the sentinel:

- **critical-path attribution** (observability/attribution.py): merged
  flight dumps (+ optionally a Perfetto trace) -> per-collective phase
  breakdown (queue / gang-wait / dispatch / wire / reduce), per-rank
  clock skew, and straggler attribution naming the rank that arrives
  last, how often, by how much;
- **engine telemetry**: the ``engine/*`` counter/gauge families from a
  metrics snapshot (``ACCL.metrics()`` JSON / trace_smoke's
  metrics_smoke.json), rendered next to the wire/membership counters;
- **regression sentinel** (observability/sentinel.py): the snapshot's
  latency histograms + bandwidth compared against committed
  ``bench/results`` baselines per (collective, dtype, size-bucket,
  lane) with the same thresholds as the live sentinel.

``--ci`` is the perf-gate mode: the REPORT SCHEMA is hard-validated
(a malformed dump or snapshot fails the job) but threshold findings
are advisory — shared CI cores swing 3x, so drift there is a warning
in the artifact, not a red build.  ``--fail-on-findings`` makes drift
fatal for local/dedicated-box use.

Usage:
  python scripts/perf_doctor.py --metrics metrics_smoke.json \\
      --flight hang_flight_dump.json [--trace trace_smoke.json] \\
      --baseline bench/results/callrate_r12_plan_on.json \\
      [--baseline bench/results/sweep_gate_baseline_r12.csv] \\
      [--out perf_doctor_report.json] [--ci | --fail-on-findings]
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from accl_tpu.observability import attribution  # noqa: E402
from accl_tpu.observability.flight import merge_flight_dumps  # noqa: E402
from accl_tpu.observability.sentinel import Baseline, Sentinel  # noqa: E402

SNAPSHOT_KEYS = ("counters", "gauges", "calls")


def load_snapshot(path: str) -> dict:
    with open(path) as f:
        snap = json.load(f)
    missing = [k for k in SNAPSHOT_KEYS if k not in snap]
    if missing:
        raise ValueError(
            f"{path} is not a metrics snapshot (missing {missing}; want "
            f"ACCL.dump_metrics(as_json=True) / metrics_smoke.json)")
    return snap


def engine_section(snap: dict) -> dict:
    """The engine/* + wire/* + membership counter families."""
    out = {"counters": {}, "gauges": {}}
    for k, v in sorted(snap.get("counters", {}).items()):
        if k.startswith(("engine/", "wire/", "membership/", "watchdog/",
                         "plans/", "recovery/", "sentinel/")):
            out["counters"][k] = v
    for k, v in sorted(snap.get("gauges", {}).items()):
        if k.startswith("engine/") or k == "accl_health":
            out["gauges"][k] = v
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--metrics", default="",
                    help="metrics snapshot JSON (dump_metrics as_json)")
    ap.add_argument("--flight", nargs="*", default=[],
                    help="flight dump file(s): per-rank, merged, or a "
                         "watchdog dump (torn crash dumps are salvaged)")
    ap.add_argument("--trace", default="",
                    help="Perfetto trace JSON to refine the wire/reduce "
                         "split from device windows")
    ap.add_argument("--baseline", action="append", default=[],
                    help="committed baseline (sentinel JSON, callrate "
                         "record, registry snapshot, or sweep CSV); "
                         "repeatable — later files fill gaps")
    ap.add_argument("--out", default="",
                    help="write the full JSON report here (CI artifact)")
    ap.add_argument("--ci", action="store_true",
                    help="perf-gate mode: schema failures are fatal, "
                         "threshold findings advisory")
    ap.add_argument("--fail-on-findings", action="store_true",
                    help="exit 1 on any straggler dominance or sentinel "
                         "drift finding (dedicated-box mode)")
    ap.add_argument("--timeline", action="store_true",
                    help="include the per-gang timeline in the report")
    args = ap.parse_args()
    if not args.metrics and not args.flight:
        ap.error("pass --metrics and/or --flight input files")

    report: dict = {"version": 1}
    schema_errors: list = []
    findings = 0

    # -- attribution over flight dumps ---------------------------------
    if args.flight:
        try:
            merged = merge_flight_dumps(list(args.flight))
            trace_doc = None
            if args.trace:
                with open(args.trace) as f:
                    trace_doc = json.load(f)
            attr = attribution.attribute(merged, trace_doc=trace_doc,
                                         timeline=args.timeline)
            report["attribution"] = attr
            attribution.render(attr, sys.stdout)
            for c in attr["collectives"].values():
                d = c["dominant_straggler"]
                if d is not None and d["share"] >= 0.5:
                    findings += 1
            torn = merged["analysis"].get("torn_dumps", [])
            if torn:
                print(f"note: {len(torn)} torn dump file(s) salvaged "
                      f"(crash-time truncation) — "
                      f"{[t['path'] for t in torn]}")
        except (OSError, ValueError, KeyError, json.JSONDecodeError) as e:
            schema_errors.append(f"flight/attribution: "
                                 f"{type(e).__name__}: {e}")

    # -- engine telemetry + sentinel over the metrics snapshot ---------
    if args.metrics:
        try:
            snap = load_snapshot(args.metrics)
            report["engine_telemetry"] = engine_section(snap)
            print("\nengine telemetry:")
            for k, v in report["engine_telemetry"]["counters"].items():
                print(f"  {k:<40} {v}")
            for k, v in report["engine_telemetry"]["gauges"].items():
                print(f"  {k:<40} {v}")
            if args.baseline:
                base = None
                for path in args.baseline:
                    b = Baseline.load(path)
                    base = b if base is None else base.merge(b)
                sen = Sentinel(base)
                drift = sen.compare_snapshot(snap)
                report["sentinel"] = {
                    "baselines": args.baseline,
                    "thresholds": {"p50_ratio": sen.p50_ratio,
                                   "p99_ratio": sen.p99_ratio,
                                   "bw_ratio": sen.bw_ratio,
                                   "min_calls": sen.min_calls},
                    "findings": drift,
                }
                findings += len(drift)
                print(f"\nregression sentinel: {len(drift)} drift "
                      f"finding(s) vs {len(base.entries)} baseline "
                      f"entr(ies)")
                for f in drift:
                    print(f"  {f['collective']} {f['dtype']} "
                          f"{f['size_bucket']} {f['axis']}: live "
                          f"{f['live']} vs baseline {f['baseline']} "
                          f"({f['ratio']}x, threshold "
                          f"{f['threshold']}x)")
        except (OSError, ValueError, json.JSONDecodeError) as e:
            schema_errors.append(f"metrics/sentinel: "
                                 f"{type(e).__name__}: {e}")

    report["schema_errors"] = schema_errors
    report["findings_total"] = findings
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
        print(f"\nreport written to {args.out}")

    if schema_errors:
        for e in schema_errors:
            print(f"SCHEMA ERROR: {e}", file=sys.stderr)
        return 2  # malformed inputs fail even (especially) in --ci
    if args.fail_on_findings and findings:
        return 1
    if args.ci and findings:
        print(f"\n--ci: {findings} finding(s) are ADVISORY on shared "
              f"cores (see the report artifact)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
