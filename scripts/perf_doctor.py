#!/usr/bin/env python
"""perf_doctor: offline collective-performance observatory report.

The offline twin of the live r14 machinery — one command turns dump
files into the same three-part report a running world exposes through
/metrics + the sentinel:

- **critical-path attribution** (observability/attribution.py): merged
  flight dumps (+ optionally a Perfetto trace) -> per-collective phase
  breakdown (queue / gang-wait / dispatch / wire / reduce), per-rank
  clock skew, and straggler attribution naming the rank that arrives
  last, how often, by how much;
- **engine telemetry**: the ``engine/*`` counter/gauge families from a
  metrics snapshot (``ACCL.metrics()`` JSON / trace_smoke's
  metrics_smoke.json), rendered next to the wire/membership counters;
- **regression sentinel** (observability/sentinel.py): the snapshot's
  latency histograms + bandwidth compared against committed
  ``bench/results`` baselines per (collective, dtype, size-bucket,
  lane) with the same thresholds as the live sentinel;
- **link matrix** (r15): the ``link/*`` families of the snapshot
  reassembled into the world-level P×P per-link traffic matrix,
  rendered against the topology axes of the SAME Fabric the r16
  autotuner builds (accl_tpu/tuning/topology.Fabric.for_world —
  ACCL_FABRIC / device coords / near-square default) with
  slowest-link and imbalance findings — the measured per-link model
  ``Fabric.from_link_matrix`` ingests for axis demotion;
- **overlap accounting** (r15, needs --trace + --flight): wire-exposed
  vs compute-overlapped time per collective — the recovered-compute
  precursor metric for device-initiated fusion (ROADMAP item 3);
- **retune history** (r19, ``--retunes``): the online tuner's audit
  ring (the ``/retunes`` exporter endpoint / retune_smoke artifact)
  rendered as finding -> hypothesis -> A/B -> decision chains, with a
  post-install cross-check against the sentinel section — an installed
  cell the sentinel still flags (and the tuner has not auto-reverted)
  is a finding;
- **per-tenant SLO report** (r20, ``--slo``): an ``accl-slo-report``
  document (the ``/slo`` exporter body / slo_soak artifact) rendered
  as budget-remaining + fast/slow burn rates per tenant objective,
  with any embedded per-tenant link-matrix slices rendered against the
  same fabric axes as the world matrix — a tenant whose verdict is not
  ``ok`` is a finding.

File-loaded sections go through ONE report-section registry
(:data:`SECTIONS`: loader -> schema validator -> renderer), so
``--ci`` schema validation covers every section uniformly — a section
added without a validator is a bug the registry makes structurally
impossible, not a silent gap.

``--ci`` is the perf-gate mode: the REPORT SCHEMA is hard-validated
(a malformed dump or snapshot fails the job) but threshold findings
are advisory — shared CI cores swing 3x, so drift there is a warning
in the artifact, not a red build.  ``--fail-on-findings`` makes drift
fatal for local/dedicated-box use.

Usage:
  python scripts/perf_doctor.py --metrics metrics_smoke.json \\
      --flight hang_flight_dump.json [--trace trace_smoke.json] \\
      --baseline bench/results/callrate_r12_plan_on.json \\
      [--baseline bench/results/sweep_gate_baseline_r12.csv] \\
      [--out perf_doctor_report.json] [--ci | --fail-on-findings]
"""
import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from accl_tpu.observability import attribution, telemetry  # noqa: E402
from accl_tpu.observability.flight import merge_flight_dumps  # noqa: E402
from accl_tpu.observability.sentinel import Baseline, Sentinel  # noqa: E402
from accl_tpu.tuning.topology import Fabric  # noqa: E402
from accl_tpu.utils.topology import link_axis as _ring_link_axis  # noqa: E402


_FABRIC_CACHE: dict = {}


def _world_fabric(P: int):
    """(fabric_or_None, link_axis_fn) for a P-rank snapshot — the
    SAME Fabric the r16 tuner builds, but a snapshot must still render
    when this analyst's ACCL_FABRIC / probed coords do not fit the
    snapshot's world: fall back to the r15 ring labels rather than
    aborting the whole report.  Memoized per P so the findings and
    the rendering always label a link identically (and the fallback
    note prints once)."""
    if P in _FABRIC_CACHE:
        return _FABRIC_CACHE[P]
    try:
        # probe=False: an OFFLINE report must never import jax /
        # touch jax.devices() — on a TPU host that claims (or wedges
        # on) the very chip this tool is diagnosing
        fab = Fabric.for_world(P, probe=False)
        out = (fab, fab.link_axis)
    except Exception as e:  # noqa: BLE001 — a report must still render
        print(f"note: no fabric for a {P}-rank snapshot ({e}); "
              f"falling back to ring link labels", file=sys.stderr)
        out = (None, (lambda s, d: _ring_link_axis(s, d, nranks=P)))
    _FABRIC_CACHE[P] = out
    return out

SNAPSHOT_KEYS = ("counters", "gauges", "calls")

#: link/<field>/r<src>->r<dst> — the per-cell counter names the
#: telemetry sampler publishes (observability/telemetry.py)
_LINK_CELL = re.compile(r"^link/([a-z_]+)/r(\d+)->r(\d+)$")

#: imbalance past this max/mean ratio over nonzero tx_bytes cells is
#: flagged (shared CI cores swing schedules, so stay conservative)
IMBALANCE_RATIO = 4.0


def load_snapshot(path: str) -> dict:
    with open(path) as f:
        snap = json.load(f)
    missing = [k for k in SNAPSHOT_KEYS if k not in snap]
    if missing:
        raise ValueError(
            f"{path} is not a metrics snapshot (missing {missing}; want "
            f"ACCL.dump_metrics(as_json=True) / metrics_smoke.json)")
    return snap


def engine_section(snap: dict) -> dict:
    """The engine/* + wire/* + membership counter families."""
    out = {"counters": {}, "gauges": {}}
    for k, v in sorted(snap.get("counters", {}).items()):
        if k.startswith(("engine/", "wire/", "membership/", "watchdog/",
                         "plans/", "recovery/", "sentinel/")):
            out["counters"][k] = v
    for k, v in sorted(snap.get("gauges", {}).items()):
        if k.startswith("engine/") or k == "accl_health":
            out["gauges"][k] = v
    return out


def link_matrix_section(snap: dict) -> dict:
    """Reassemble the snapshot's ``link/*`` cell counters into the
    world-level matrix document + findings.  Empty dict when the
    snapshot carries no link families (pre-r15 world, or the sampler
    never ran)."""
    cells: dict = {}
    nranks = 0
    for name, v in snap.get("counters", {}).items():
        m = _LINK_CELL.match(name)
        if not m:
            continue
        field, s, d = m.group(1), int(m.group(2)), int(m.group(3))
        cells[(field, s, d)] = int(v)
        nranks = max(nranks, s + 1, d + 1)
    if not cells:
        return {}
    fields = {f: [[0] * nranks for _ in range(nranks)]
              for f in telemetry.LINK_COUNTER_FIELDS}
    for (field, s, d), v in cells.items():
        if field in fields:
            fields[field][s][d] = v
    matrix = {"nranks": nranks, "comm": 0, "fields": fields}
    return {"matrix": matrix, "findings": link_findings(matrix)}


def link_findings(matrix: dict) -> dict:
    """Slowest-link + imbalance findings over one link_matrix doc —
    the shape the r16 topology autotuner (accl_tpu/tuning) consumes.
    Axis names come from the SAME Fabric the tuner builds
    (Fabric.for_world honors ACCL_FABRIC / device coords), so the
    report and the tuner can never disagree about which axis a link
    belongs to."""
    P = matrix["nranks"]
    _, link_axis = _world_fabric(P)
    out: dict = {}
    slow = telemetry.slowest_link(matrix, "seek_wait_ns")
    if slow is not None:
        s, d = slow
        out["slowest_link"] = {
            "observer": s, "peer": d,
            "axis": link_axis(s, d),
            "seek_wait_ms": round(
                matrix["fields"]["seek_wait_ns"][s][d] / 1e6, 3)}
    busiest = telemetry.slowest_link(matrix, "tx_bytes")
    if busiest is not None:
        s, d = busiest
        out["busiest_link"] = {
            "src": s, "dst": d, "axis": link_axis(s, d),
            "tx_bytes": matrix["fields"]["tx_bytes"][s][d]}
    ratio = telemetry.link_imbalance(matrix, "tx_bytes")
    out["tx_imbalance_ratio"] = round(ratio, 2)
    out["imbalanced"] = ratio > IMBALANCE_RATIO
    retrans = telemetry.slowest_link(matrix, "retrans_sent")
    if retrans is not None:
        s, d = retrans
        total = sum(v for row in matrix["fields"]["retrans_sent"]
                    for v in row)
        out["lossiest_link"] = {
            "src": s, "dst": d, "axis": link_axis(s, d),
            "retransmits": matrix["fields"]["retrans_sent"][s][d],
            "share": round(
                matrix["fields"]["retrans_sent"][s][d] / total, 3)
            if total else 0.0}
    return out


def validate_link_section(section: dict) -> list:
    """--ci schema gate for the link_matrix report section: square
    matrices over every counter field, integer cells."""
    errors = []
    matrix = section.get("matrix", {})
    P = matrix.get("nranks", 0)
    fields = matrix.get("fields", {})
    for f in telemetry.LINK_COUNTER_FIELDS:
        cells = fields.get(f)
        if cells is None:
            errors.append(f"link_matrix: missing field {f}")
            continue
        if len(cells) != P or any(len(row) != P for row in cells):
            errors.append(f"link_matrix: field {f} is not {P}x{P}")
        elif any(not isinstance(v, int) or v < 0
                 for row in cells for v in row):
            errors.append(f"link_matrix: field {f} has non-counter "
                          f"cells")
    if "findings" not in section:
        errors.append("link_matrix: missing findings")
    return errors


def render_link_matrix(section: dict, out) -> None:
    matrix = section["matrix"]
    P = matrix["nranks"]
    fabric, axis_fn = _world_fabric(P)
    f = section["findings"]
    spec = f", fabric {fabric.spec()}" if fabric is not None else ""
    scope = (f"tenant {matrix['tenant']}" if matrix.get("tenant")
             else f"comm {matrix.get('comm') or 0}")
    out.write(f"\nlink matrix ({P}x{P}, {scope}{spec}):\n")
    tx = matrix["fields"]["tx_bytes"]
    wait = matrix["fields"]["seek_wait_ns"]
    for s in range(P):
        for d in range(P):
            if tx[s][d] == 0 and wait[s][d] == 0:
                continue
            axis = axis_fn(s, d)
            out.write(
                f"  r{s}->r{d} [{axis:>7}] tx {tx[s][d]:>12} B  "
                f"wait {wait[s][d] / 1e6:9.3f} ms  "
                f"retrans {matrix['fields']['retrans_sent'][s][d]}  "
                f"nacks {matrix['fields']['nacks_tx'][s][d]}\n")
    if "slowest_link" in f:
        sl = f["slowest_link"]
        out.write(f"  SLOWEST link: r{sl['observer']} blocked on "
                  f"r{sl['peer']} [{sl['axis']}] for "
                  f"{sl['seek_wait_ms']:.3f} ms\n")
    out.write(f"  tx imbalance max/mean: {f['tx_imbalance_ratio']}x"
              f"{'  (IMBALANCED)' if f['imbalanced'] else ''}\n")


def load_retunes(path: str) -> dict:
    from accl_tpu.tuning import online as _online

    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) \
            or doc.get("format") != _online.HISTORY_FORMAT:
        raise ValueError(
            f"{path} is not a retune history (format="
            f"{doc.get('format') if isinstance(doc, dict) else doc!r}; "
            f"want {_online.HISTORY_FORMAT!r} — the exporter's /retunes "
            f"body or retune_smoke's artifact)")
    return doc


def validate_retune_section(doc: dict) -> list:
    """--ci schema gate for the retune-history section: versioned
    format, every episode a closed decision chain."""
    from accl_tpu.tuning import online as _online

    errors = []
    if doc.get("version") != _online.HISTORY_VERSION:
        errors.append(f"retunes: unsupported history version "
                      f"{doc.get('version')!r}")
    episodes = doc.get("episodes")
    if not isinstance(episodes, list):
        errors.append("retunes: 'episodes' is not a list")
        return errors
    for ep in episodes:
        seq = ep.get("seq") if isinstance(ep, dict) else None
        tag = f"retunes: episode {seq!r}"
        if not isinstance(ep, dict) or not isinstance(seq, int):
            errors.append(f"{tag}: not a sequenced episode dict")
            continue
        if ep.get("kind") not in ("cell", "axis"):
            errors.append(f"{tag}: kind {ep.get('kind')!r}")
        if ep.get("decision") not in _online.DECISIONS:
            errors.append(f"{tag}: decision {ep.get('decision')!r} not "
                          f"in {_online.DECISIONS}")
        trigger = ep.get("trigger")
        if not isinstance(trigger, dict) or "type" not in trigger:
            errors.append(f"{tag}: trigger is not a typed dict")
        if not isinstance(ep.get("opened_at"), (int, float)) \
                or not isinstance(ep.get("closed_at"), (int, float)):
            errors.append(f"{tag}: missing opened_at/closed_at stamps")
        if ep.get("kind") == "cell" \
                and ep.get("decision") in ("installed", "rejected",
                                           "reverted") \
                and not isinstance(ep.get("cell"), str):
            errors.append(f"{tag}: cell decision without a cell key")
    return errors


def retune_cross_check(doc: dict, sentinel_findings: list) -> list:
    """Installed cells the sentinel STILL flags: the tuner's own
    post-install watch auto-reverts these when it sees the finding, so
    one surviving in a report means the regression outlived the loop
    (or the loop is stopped) — surface it as a finding."""
    reverted = {ep.get("installed_episode")
                for ep in doc.get("episodes", [])
                if ep.get("decision") == "reverted"}
    live_installs = {}
    for ep in doc.get("episodes", []):
        if ep.get("decision") == "installed" \
                and ep.get("kind") == "cell" \
                and ep.get("seq") not in reverted:
            live_installs[ep["cell"]] = ep
    out = []
    for f in sentinel_findings:
        key = "|".join(str(f.get(k, "")) for k in
                       ("collective", "dtype", "size_bucket"))
        for cell, ep in live_installs.items():
            if cell.startswith(key + "|"):
                out.append({
                    "cell": cell, "episode": ep["seq"],
                    "installed":
                        (ep.get("installed") or {}).get("algorithm"),
                    "sentinel_ratio": f.get("ratio"),
                })
    return out


def render_retunes(doc: dict, cross: list, out) -> None:
    episodes = doc.get("episodes", [])
    out.write(f"\nretune history (r19): {len(episodes)} episode(s) "
              f"kept of {doc.get('total', len(episodes))} "
              f"({doc.get('dropped', 0)} dropped from the ring)\n")
    for ep in episodes:
        trig = ep.get("trigger", {})
        if ep.get("kind") == "axis":
            hyp = ep.get("hypothesis", {})
            chain = (f"link_matrix re-score -> axis_order "
                     f"{hyp.get('axis_order_from')} -> "
                     f"{hyp.get('axis_order_to')}")
        else:
            parts = [f"sentinel {trig.get('kind', 'drift')} "
                     f"{trig.get('ratio')}x on {ep.get('cell')}"]
            hyp = ep.get("hypothesis")
            if hyp:
                parts.append(f"challenger {hyp.get('challenger')} vs "
                             f"{hyp.get('incumbent')}")
            ab = ep.get("ab")
            if ab:
                parts.append(f"A/B {ab.get('ratio')}x")
            chain = " -> ".join(parts)
        out.write(f"  #{ep.get('seq'):<3} [{ep.get('kind')}] {chain} "
                  f"-> {str(ep.get('decision', '?')).upper()}"
                  f"{': ' + ep['reason'] if ep.get('reason') else ''}\n")
    for c in cross:
        out.write(f"  CROSS-CHECK: installed cell {c['cell']} "
                  f"(episode #{c['episode']}, {c['installed']}) is "
                  f"still flagged by the sentinel at "
                  f"{c['sentinel_ratio']}x and has NOT been "
                  f"auto-reverted\n")


def _retunes_section(doc: dict, report: dict, out) -> int:
    cross = retune_cross_check(
        doc, report.get("sentinel", {}).get("findings", []))
    report["retunes"] = {"history": doc, "cross_check": cross}
    render_retunes(doc, cross, out)
    return len(cross)


def load_slo(path: str) -> dict:
    from accl_tpu.observability import slo as _slo

    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) \
            or doc.get("format") != _slo.SLO_REPORT_FORMAT:
        raise ValueError(
            f"{path} is not an SLO report (format="
            f"{doc.get('format') if isinstance(doc, dict) else doc!r}; "
            f"want {_slo.SLO_REPORT_FORMAT!r} — the exporter's /slo "
            f"body or slo_soak's artifact)")
    return doc


def validate_slo_section(doc: dict) -> list:
    """--ci schema gate for the SLO report: versioned format, every
    objective row complete with a known verdict and a sane budget,
    every embedded per-tenant link-matrix slice square."""
    from accl_tpu.observability import slo as _slo

    errors = []
    if doc.get("version") != _slo.SLO_REPORT_VERSION:
        errors.append(f"slo: unsupported report version "
                      f"{doc.get('version')!r}")
    tenants = doc.get("tenants")
    if not isinstance(tenants, dict):
        errors.append("slo: 'tenants' is not a dict")
        return errors
    for tenant, t in tenants.items():
        tag = f"slo: tenant {tenant!r}"
        if not isinstance(t, dict):
            errors.append(f"{tag}: not a dict")
            continue
        if t.get("verdict") not in _slo.VERDICT_NAMES:
            errors.append(f"{tag}: verdict {t.get('verdict')!r} not in "
                          f"{_slo.VERDICT_NAMES}")
        br = t.get("budget_remaining")
        if not isinstance(br, (int, float)) or not 0.0 <= br <= 1.0:
            errors.append(f"{tag}: budget_remaining {br!r} not in "
                          f"[0, 1]")
        rows = t.get("objectives")
        if not isinstance(rows, list):
            errors.append(f"{tag}: 'objectives' is not a list")
            continue
        for row in rows:
            missing = [k for k in _slo.OBJECTIVE_SCHEMA_KEYS
                       if k not in row]
            if missing:
                errors.append(f"{tag}: objective row missing {missing}")
                continue
            if row["verdict"] not in _slo.VERDICT_NAMES:
                errors.append(f"{tag}: objective {row['objective']} "
                              f"verdict {row['verdict']!r}")
    for tenant, m in (doc.get("link_matrices") or {}).items():
        errors.extend(
            f"slo[{tenant}]: {e}" for e in
            validate_link_section({"matrix": m, "findings": {}}))
    return errors


def render_slo(doc: dict, out) -> int:
    """Render the per-tenant report; returns the finding count (one
    per not-ok tenant, plus imbalanced tenant link slices)."""
    findings = 0
    tenants = doc.get("tenants", {})
    out.write(f"\nSLO report (r20): {len(doc.get('specs', []))} "
              f"spec(s), {len(tenants)} tenant(s), "
              f"{doc.get('checks', 0)} check sweep(s), windows "
              f"fast={doc.get('fast_window')}/"
              f"slow={doc.get('slow_window')} sweeps\n")
    for tenant in sorted(tenants):
        t = tenants[tenant]
        verdict = t.get("verdict", "?")
        if verdict != "ok":
            findings += 1
        out.write(f"  tenant {tenant}: {str(verdict).upper()}  "
                  f"budget remaining "
                  f"{t.get('budget_remaining', 1.0) * 100:.1f}%\n")
        for row in t.get("objectives", []):
            budget = (f"budget {row['budget_remaining'] * 100:.1f}%"
                      if row.get("budget_remaining") is not None
                      else "no budget (floor)")
            out.write(
                f"    {row['collective']}/{row['size_bucket']} "
                f"{row['objective']:<12} target {row['target']:<10} "
                f"burn fast {row['burn_fast']:>7.2f} / slow "
                f"{row['burn_slow']:>7.2f}  {budget}  "
                f"-> {row['verdict']}\n")
    for tenant in sorted(doc.get("link_matrices", {}) or {}):
        matrix = doc["link_matrices"][tenant]
        section = {"matrix": matrix, "findings": link_findings(matrix)}
        render_link_matrix(section, out)
        if section["findings"].get("imbalanced"):
            findings += 1
    return findings


def _slo_section(doc: dict, report: dict, out) -> int:
    report["slo"] = doc
    return render_slo(doc, out)


#: the report-section registry (r20 satellite): every file-loaded
#: section declares loader -> --ci schema validator -> renderer in one
#: place, so schema validation is uniform across sections by
#: construction.  The renderer returns the section's finding count;
#: validators for sections assembled in-process (link_matrix) are
#: registered too so main() resolves EVERY validator through here.
SECTIONS = {
    "retunes": {"load": load_retunes, "validate": validate_retune_section,
                "render": _retunes_section},
    "slo": {"load": load_slo, "validate": validate_slo_section,
            "render": _slo_section},
    "link_matrix": {"load": None, "validate": validate_link_section,
                    "render": None},
}


def run_section(name: str, path: str, report: dict,
                schema_errors: list, out) -> int:
    """Load + validate + render one registered file-backed section;
    loader/validator failures become schema errors (fatal under --ci),
    never tracebacks."""
    sec = SECTIONS[name]
    try:
        doc = sec["load"](path)
        schema_errors.extend(sec["validate"](doc))
        return sec["render"](doc, report, out)
    except (OSError, ValueError, KeyError, json.JSONDecodeError) as e:
        schema_errors.append(f"{name}: {type(e).__name__}: {e}")
        return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--metrics", default="",
                    help="metrics snapshot JSON (dump_metrics as_json)")
    ap.add_argument("--flight", nargs="*", default=[],
                    help="flight dump file(s): per-rank, merged, or a "
                         "watchdog dump (torn crash dumps are salvaged)")
    ap.add_argument("--trace", default="",
                    help="Perfetto trace JSON to refine the wire/reduce "
                         "split from device windows")
    ap.add_argument("--baseline", action="append", default=[],
                    help="committed baseline (sentinel JSON, callrate "
                         "record, registry snapshot, or sweep CSV); "
                         "repeatable — later files fill gaps")
    ap.add_argument("--retunes", default="",
                    help="retune-history JSON (the exporter's /retunes "
                         "body / retune_smoke artifact) — rendered as "
                         "decision chains + sentinel cross-check")
    ap.add_argument("--slo", default="",
                    help="SLO report JSON (the exporter's /slo body / "
                         "slo_soak artifact) — rendered as per-tenant "
                         "budget-remaining + burn rates, with embedded "
                         "per-tenant link-matrix slices")
    ap.add_argument("--out", default="",
                    help="write the full JSON report here (CI artifact)")
    ap.add_argument("--ci", action="store_true",
                    help="perf-gate mode: schema failures are fatal, "
                         "threshold findings advisory")
    ap.add_argument("--fail-on-findings", action="store_true",
                    help="exit 1 on any straggler dominance or sentinel "
                         "drift finding (dedicated-box mode)")
    ap.add_argument("--timeline", action="store_true",
                    help="include the per-gang timeline in the report")
    args = ap.parse_args()
    if not args.metrics and not args.flight and not args.retunes \
            and not args.slo:
        ap.error("pass --metrics, --flight, --retunes and/or --slo "
                 "input files")

    report: dict = {"version": 1}
    schema_errors: list = []
    findings = 0

    # -- attribution over flight dumps ---------------------------------
    if args.flight:
        try:
            merged = merge_flight_dumps(list(args.flight))
            trace_doc = None
            if args.trace:
                with open(args.trace) as f:
                    trace_doc = json.load(f)
            attr = attribution.attribute(merged, trace_doc=trace_doc,
                                         timeline=args.timeline)
            report["attribution"] = attr
            attribution.render(attr, sys.stdout)
            # overlap accounting (r15): wire-exposed vs compute-
            # overlapped per collective (device windows from --trace)
            ovl = attribution.overlap(merged, trace_doc=trace_doc)
            report["overlap"] = ovl
            print(f"\noverlap accounting ({ovl['compute_windows']} "
                  f"compute window(s)):")
            for key, c in sorted(ovl["collectives"].items()):
                print(f"  {key}: wire {c['wire_us']:.1f}us, exposed "
                      f"{c['exposed_us']:.1f}us "
                      f"({c['exposed_fraction'] * 100:.1f}% of span), "
                      f"recovered-compute "
                      f"{c['recovered_compute_fraction'] * 100:.1f}%")
            # device overlap (r18): the stamp-clock timeline's own
            # xfer-vs-reduce accounting — the recovered-MXU fraction
            # the fused lanes exist to raise (1.0 recovered = every
            # wire hop hidden under the matmul accumulator)
            if trace_doc is not None:
                dev = attribution.device_overlap(trace_doc)
                report["device_overlap"] = dev
                if dev["collectives"]:
                    print(f"\ndevice overlap (r18, "
                          f"{dev['tracks']} stamp track(s)):")
                    for coll, c in sorted(dev["collectives"].items()):
                        print(f"  {coll}: xfer {c['xfer_us']:.1f}us "
                              f"over {c['ranks']} rank(s), exposed "
                              f"{c['exposed_fraction'] * 100:.1f}%, "
                              f"recovered-MXU "
                              f"{c['recovered_mxu_fraction'] * 100:.1f}%")
            for c in attr["collectives"].values():
                d = c["dominant_straggler"]
                if d is not None and d["share"] >= 0.5:
                    findings += 1
            torn = merged["analysis"].get("torn_dumps", [])
            if torn:
                print(f"note: {len(torn)} torn dump file(s) salvaged "
                      f"(crash-time truncation) — "
                      f"{[t['path'] for t in torn]}")
        except (OSError, ValueError, KeyError, json.JSONDecodeError) as e:
            schema_errors.append(f"flight/attribution: "
                                 f"{type(e).__name__}: {e}")

    # -- engine telemetry + sentinel over the metrics snapshot ---------
    if args.metrics:
        try:
            snap = load_snapshot(args.metrics)
            report["engine_telemetry"] = engine_section(snap)
            print("\nengine telemetry:")
            for k, v in report["engine_telemetry"]["counters"].items():
                print(f"  {k:<40} {v}")
            for k, v in report["engine_telemetry"]["gauges"].items():
                print(f"  {k:<40} {v}")
            # link matrix (r15): reassembled from the link/* families
            links = link_matrix_section(snap)
            if links:
                report["link_matrix"] = links
                schema_errors.extend(
                    SECTIONS["link_matrix"]["validate"](links))
                render_link_matrix(links, sys.stdout)
                # r18: the recovered-MXU fraction belongs next to the
                # link traffic it hides — how much of those bytes'
                # wire time the device timeline shows covered by MXU
                dev = report.get("device_overlap", {}).get(
                    "collectives", {})
                if dev:
                    mean_rec = sum(c["recovered_mxu_fraction"]
                                   for c in dev.values()) / len(dev)
                    print(f"  recovered-MXU (device stamp clock): "
                          f"mean {mean_rec * 100:.1f}% over "
                          f"{len(dev)} collective(s)")
                if links["findings"].get("imbalanced"):
                    findings += 1
            if args.baseline:
                base = None
                for path in args.baseline:
                    b = Baseline.load(path)
                    base = b if base is None else base.merge(b)
                sen = Sentinel(base)
                drift = sen.compare_snapshot(snap)
                report["sentinel"] = {
                    "baselines": args.baseline,
                    "thresholds": {"p50_ratio": sen.p50_ratio,
                                   "p99_ratio": sen.p99_ratio,
                                   "bw_ratio": sen.bw_ratio,
                                   "min_calls": sen.min_calls},
                    "findings": drift,
                }
                findings += len(drift)
                print(f"\nregression sentinel: {len(drift)} drift "
                      f"finding(s) vs {len(base.entries)} baseline "
                      f"entr(ies)")
                for f in drift:
                    print(f"  {f['collective']} {f['dtype']} "
                          f"{f['size_bucket']} {f['axis']}: live "
                          f"{f['live']} vs baseline {f['baseline']} "
                          f"({f['ratio']}x, threshold "
                          f"{f['threshold']}x)")
        except (OSError, ValueError, json.JSONDecodeError) as e:
            schema_errors.append(f"metrics/sentinel: "
                                 f"{type(e).__name__}: {e}")

    # -- registry-driven file sections: retunes (r19), slo (r20) -------
    for name, path in (("retunes", args.retunes), ("slo", args.slo)):
        if path:
            findings += run_section(name, path, report, schema_errors,
                                    sys.stdout)

    report["schema_errors"] = schema_errors
    report["findings_total"] = findings
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
        print(f"\nreport written to {args.out}")

    if schema_errors:
        for e in schema_errors:
            print(f"SCHEMA ERROR: {e}", file=sys.stderr)
        return 2  # malformed inputs fail even (especially) in --ci
    if args.fail_on_findings and findings:
        return 1
    if args.ci and findings:
        print(f"\n--ci: {findings} finding(s) are ADVISORY on shared "
              f"cores (see the report artifact)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
