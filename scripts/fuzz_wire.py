#!/usr/bin/env python
"""fuzz_wire: deterministic structure-aware wire-protocol fuzzer.

Feeds mutated wire frames through the native engine's REAL ingress
classification path (``accl_engine_ingest_bytes``) and asserts the
r13 ingress contract:

- the engine NEVER crashes (a native crash kills this process — CI red);
- every frame is either consumed or cleanly rejected (return code 0/1,
  rejections counted in the ``wire/rejected_frames`` counter);
- the world stays RECOVERABLE: after every batch a ``reset_errors``
  quiesce + a fresh world must run a bitwise-correct allreduce;
- under the ASan lane (``ACCL_SANITIZER=asan`` + LD_PRELOAD, see
  docs/static_analysis.md) the run must also be leak-clean at exit.

Seed corpus: REAL captured frames of every MsgType — the script drives
an eager allreduce (EgrMsg), a rendezvous exchange (RndzvsInit/Msg/
WrDone), a dropped-segment recovery (Nack), a liveness probe
(Heartbeat), a join handshake (Join/Welcome/StateSync) and an abort
fan-out (Abort) through a tap-enabled world and records the egress
frames.  Mutation is a seeded xorshift64* stream: byte flips, field
smashes, truncation/extension, type swaps, header/payload splices —
``--seed`` reproduces the exact run.

On a failure the offending frame is written as hex + seed + iteration
to ``--artifact`` so a red CI run is reproducible from the artifact
alone: ``python scripts/fuzz_wire.py --replay <artifact.json>``.

Usage:
    python scripts/fuzz_wire.py --iters 50000 --seed 7
    python scripts/fuzz_wire.py --replay fuzz_wire_failure.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from accl_tpu.backends.emu import EmuWorld  # noqa: E402
from accl_tpu.utils.wire import (  # noqa: E402
    HEADER_SIZE,
    MSG_TYPE_NAMES,
    MSG_TYPES,
    WireFrame,
)

#: header (offset, size) pairs for the field-smash mutator — kept in
#: sync with accl_tpu/utils/wire.py HEADER_FMT
_FIELDS = [(0, 4), (4, 4), (8, 4), (12, 4), (16, 4), (20, 2), (22, 1),
           (23, 1), (24, 8), (32, 4), (36, 4), (40, 4)]
_INTERESTING = [0, 1, 2, 7, 9, 63, 64, 255, 1024, 4096, 0xFFFF,
                1 << 20, 1 << 27, 1 << 31, 0xFFFFFFFF]


class XorShift:
    """xorshift64* — the same generator the engine's chaos plan uses,
    so one seed word reproduces the whole mutation schedule."""

    def __init__(self, seed: int):
        self.x = (seed or 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF

    def next(self) -> int:
        x = self.x
        x ^= (x >> 12)
        x ^= (x << 25) & 0xFFFFFFFFFFFFFFFF
        x ^= (x >> 27)
        self.x = x
        return (x * 0x2545F4914F6CDD1D) & 0xFFFFFFFFFFFFFFFF

    def below(self, n: int) -> int:
        return self.next() % max(n, 1)

    def choice(self, seq):
        return seq[self.below(len(seq))]


# ---------------------------------------------------------------------------
# seed-corpus capture: one real frame of every MsgType
# ---------------------------------------------------------------------------
def capture_corpus(verbose: bool = True) -> list:
    w = EmuWorld(2, retry_max=4, max_eager_size=1024,
                 max_rendezvous_size=1 << 20)
    try:
        for d in w.devices:
            d.frame_tap(True)

        def eager(accl, rank):
            src = accl.create_buffer(16, np.float32)
            src.host[:] = float(rank + 1)
            src.sync_to_device()
            dst = accl.create_buffer(16, np.float32)
            accl.allreduce(src, dst, 16)

        def eager_quantized(accl, rank):
            # int8 block-scaled wire lane (r17): captures EgrMsg frames
            # with hdr.compressed == 2 and the self-describing
            # [nblocks][block][scales][q] segment framing, so the
            # mutator exercises the block-frame validation path
            from accl_tpu.constants import DataType

            src = accl.create_buffer(512, np.float32)
            src.host[:] = float(rank + 1) * 0.5
            src.sync_to_device()
            dst = accl.create_buffer(512, np.float32)
            accl.allreduce(src, dst, 512, compress_dtype=DataType.int8)

        def rendezvous(accl, rank):
            # 2048 B payload > the 1024 B eager ceiling -> rendezvous
            n = 512
            if rank == 0:
                src = accl.create_buffer(n, np.float32)
                src.host[:] = 3.5
                src.sync_to_device()
                accl.send(src, n, dst=1, tag=11)
            else:
                dst = accl.create_buffer(n, np.float32)
                accl.recv(dst, n, src=0, tag=11)

        w.run(eager)
        w.run(eager_quantized)
        w.run(rendezvous)
        # dropped segment -> receiver NACKs -> sender retransmits
        w.devices[1].inject_fault(w.devices[1].FAULT_DROP)
        w.run(eager)
        # liveness probe -> Heartbeat ping/pong
        w.devices[0].probe_liveness(0, 2, window_s=0.5)
        # join handshake -> Join (joiner), Welcome + StateSync (sponsor)
        joiner = w.spawn_replacement(announce=False)
        joiner.device.frame_tap(True)
        joiner.device.join_sync(sponsor_session=0, timeout_s=10.0)
        # abort fan-out last (it fences comm 0)
        w.devices[0].abort_comm(0, 0)
        time.sleep(0.1)  # let the egress pipelines stage everything

        frames = []
        for d in w.devices + [j.device for j in w.joiners]:
            frames.extend(d.tap_frames())
    finally:
        w.close()

    by_type: dict = {}
    for f in frames:
        by_type.setdefault(WireFrame.unpack(f).msg_type, []).append(f)
    # RndzvsWrDone is an ingress-only ABI type: the landing completion
    # is surfaced locally by land_one_sided, so NO engine ever emits it
    # on the wire — synthesize the one frame capture cannot produce
    wrdone = MSG_TYPES["rndzvs_wrdone"]
    if wrdone not in by_type:
        by_type[wrdone] = [WireFrame(msg_type=wrdone, src=1, tag=11,
                                     comm_id=0, vaddr=0x2000).pack()]
    missing = sorted(set(MSG_TYPES.values()) - set(by_type))
    if verbose:
        cov = {MSG_TYPE_NAMES[t]: len(v) for t, v in sorted(by_type.items())}
        print(f"fuzz_wire: corpus {len(frames)} frames, coverage {cov}")
    if missing:
        raise SystemExit(
            f"fuzz_wire: seed corpus is missing MsgType(s) "
            f"{[MSG_TYPE_NAMES[m] for m in missing]} — capture drive "
            f"incomplete")
    # one representative per type first (determinism), then the rest
    corpus = [v[0] for _, v in sorted(by_type.items())]
    corpus += [f for t, v in sorted(by_type.items()) for f in v[1:9]]
    return corpus


# ---------------------------------------------------------------------------
# mutation
# ---------------------------------------------------------------------------
def mutate(rng: XorShift, corpus: list) -> bytes:
    frame = bytearray(rng.choice(corpus))
    for _ in range(1 + rng.below(3)):  # stack 1-3 mutations
        op = rng.below(8)
        if op == 0 and frame:  # byte flips
            for _ in range(1 + rng.below(8)):
                frame[rng.below(len(frame))] ^= 1 << rng.below(8)
        elif op == 1 and len(frame) >= HEADER_SIZE:  # field smash
            off, size = rng.choice(_FIELDS)
            val = rng.choice(_INTERESTING) if rng.below(2) else rng.next()
            frame[off:off + size] = int(val).to_bytes(
                8, "little")[:size]
        elif op == 2:  # truncate (often mid-header)
            cut = rng.below(len(frame) + 1)
            frame = frame[:cut]
        elif op == 3:  # extend payload with noise
            frame += bytes(rng.below(256) for _ in range(rng.below(300)))
        elif op == 4 and len(frame) >= HEADER_SIZE:  # type swap
            frame[22] = (rng.choice(list(MSG_TYPES.values()))
                         if rng.below(4) else rng.below(256))
        elif op == 5 and len(frame) >= HEADER_SIZE:  # epoch/comm smash
            frame[40:44] = int(rng.below(16)).to_bytes(4, "little")
            frame[32:36] = int(rng.choice(_INTERESTING)).to_bytes(
                8, "little")[:4]
        elif op == 6 and len(frame) >= HEADER_SIZE and corpus:  # splice
            other = rng.choice(corpus)
            frame = bytearray(frame[:HEADER_SIZE]) + bytearray(
                other[HEADER_SIZE:])
        elif op == 7 and len(frame) >= HEADER_SIZE + 8:
            # block-scale segment framing smash (r17): hit the payload's
            # [u32 nblocks][u32 block] header with boundary values —
            # truncated scale rows (huge nblocks), count/block mismatch
            # (off-by-one nblocks), oversized/zero blocks — and flip the
            # wire header's compressed marker so cast-lane payloads get
            # re-interpreted as block segments and vice versa
            which = rng.below(3)
            if which == 0:  # nblocks smash
                val = rng.choice([0, 1, 2, 3, 255, 0xFFFF, 0xFFFFFFFF])
                frame[HEADER_SIZE:HEADER_SIZE + 4] = int(val).to_bytes(
                    4, "little")
            elif which == 1:  # block smash
                val = rng.choice([0, 1, 255, 256, 257, 65536, 65537,
                                  0xFFFFFFFF])
                frame[HEADER_SIZE + 4:HEADER_SIZE + 8] = int(
                    val).to_bytes(4, "little")
            else:  # compressed-marker flip (offset 36: WireHeader)
                frame[36:40] = int(rng.choice([0, 1, 2, 3])).to_bytes(
                    4, "little")
    return bytes(frame)


# ---------------------------------------------------------------------------
# the fuzz loop
# ---------------------------------------------------------------------------
def requiesce(w: EmuWorld) -> None:
    """Drive the r10 recovery contract after a garbage batch: mutated
    Abort/epoch frames LEGALLY fence communicators (that is the abort
    protocol working), possibly with divergent per-rank epochs.  A real
    supervisor heals that by re-aborting — handle_abort adopts the
    highest epoch monotonically — until the world agrees, then runs the
    collective reset.  reset_errors alone must NOT resync epochs (dead-
    epoch stragglers stay fenced forever), so the harness does exactly
    what a recovery supervisor would."""
    for _ in range(10):
        epochs = [d.comm_epoch(0) for d in w.devices]
        if len(set(epochs)) == 1:
            # settle: an abort fan-out still in flight would re-fence a
            # rank AFTER reset_errors (seen under the ASan slowdown) —
            # wait a beat and re-check before declaring agreement
            time.sleep(0.05)
            if len({d.comm_epoch(0) for d in w.devices}) == 1:
                break
            continue
        leader = epochs.index(max(epochs))
        w.devices[leader].abort_comm(0, 0)
        time.sleep(0.05)
    w.reset_errors()


def liveness(w: EmuWorld) -> None:
    expect = float(sum(r + 1 for r in range(w.nranks)))

    def fn(accl, rank):
        src = accl.create_buffer(16, np.float32)
        src.host[:] = float(rank + 1)
        src.sync_to_device()
        dst = accl.create_buffer(16, np.float32)
        accl.allreduce(src, dst, 16)
        dst.sync_from_device()
        if not np.array_equal(dst.host, np.full(16, expect, np.float32)):
            raise AssertionError(
                f"liveness allreduce corrupted: {dst.host[:4]}...")

    w.run(fn)


def write_artifact(path: str, seed: int, iteration: int, frame: bytes,
                   error: str) -> None:
    with open(path, "w") as f:
        json.dump({"seed": seed, "iteration": iteration,
                   "frame_hex": frame.hex(), "error": error,
                   "replay": f"python scripts/fuzz_wire.py --replay {path}"},
                  f, indent=1)
    print(f"fuzz_wire: FAILING FRAME written to {path}", file=sys.stderr)


def run_fuzz(iters: int, seed: int, batch: int, ranks: int,
             artifact: str) -> int:
    corpus = capture_corpus()
    rng = XorShift(seed)
    consumed = rejected = 0
    it = 0
    t0 = time.time()
    while it < iters:
        w = EmuWorld(ranks, retry_max=0)
        try:
            end = min(it + batch, iters)
            while it < end:
                frame = mutate(rng, corpus)
                target = w.devices[rng.below(ranks)]
                try:
                    rc = target.ingest_bytes(frame)
                except BaseException as e:  # engine misbehaved
                    write_artifact(artifact, seed, it, frame, repr(e))
                    raise
                if rc == 0:
                    consumed += 1
                elif rc == 1:
                    rejected += 1
                else:
                    write_artifact(artifact, seed, it, frame,
                                   f"ingest returned {rc}")
                    return 1
                it += 1
            # recoverability gate: recover the way a supervisor would
            # (abort-resync + collective reset), then a bitwise-correct
            # collective on the SAME world the garbage was fed into.
            # One retry: a straggling abort fan-out racing the reset is
            # a recoverable re-fence, not a wedge — a SECOND recovery
            # round must always succeed.
            requiesce(w)
            try:
                liveness(w)
            except Exception:
                requiesce(w)
                try:
                    liveness(w)
                except BaseException as e:
                    write_artifact(artifact, seed, it, b"",
                                   f"liveness after batch failed: {e!r}")
                    raise
        finally:
            w.close()
        print(f"fuzz_wire: {it}/{iters} frames "
              f"({consumed} consumed / {rejected} rejected, "
              f"{time.time() - t0:.1f}s)")
    if rejected == 0:
        print("fuzz_wire: suspicious — no frame was ever rejected",
              file=sys.stderr)
        return 1
    print(f"fuzz_wire: PASS — {iters} frames, {consumed} consumed, "
          f"{rejected} rejected, 0 crashes, seed {seed}")
    return 0


def run_replay(path: str) -> int:
    with open(path) as f:
        doc = json.load(f)
    frame = bytes.fromhex(doc["frame_hex"])
    print(f"fuzz_wire: replaying iteration {doc['iteration']} "
          f"(seed {doc['seed']}): {len(frame)}-byte frame")
    w = EmuWorld(2, retry_max=0)
    try:
        rc = w.devices[0].ingest_bytes(frame)
        print(f"fuzz_wire: ingest rc={rc}")
        w.reset_errors()
        liveness(w)
        print("fuzz_wire: world stayed live — frame no longer reproduces")
    finally:
        w.close()
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(
        prog="fuzz_wire",
        description="deterministic structure-aware wire-protocol fuzzer "
                    "for the native engine ingress path")
    ap.add_argument("--iters", type=int, default=50000,
                    help="mutated frames to inject (default 50000)")
    ap.add_argument("--seed", type=int, default=7,
                    help="xorshift seed — reproduces the exact run")
    ap.add_argument("--batch", type=int, default=5000,
                    help="frames per world before the recoverability "
                         "gate (reset_errors + bitwise allreduce)")
    ap.add_argument("--ranks", type=int, default=2)
    ap.add_argument("--artifact", default="fuzz_wire_failure.json",
                    help="where to write the failing frame (hex + seed)")
    ap.add_argument("--replay", default="",
                    help="replay a failure artifact instead of fuzzing")
    args = ap.parse_args()
    if args.replay:
        return run_replay(args.replay)
    return run_fuzz(args.iters, args.seed, args.batch, args.ranks,
                    args.artifact)


if __name__ == "__main__":
    sys.exit(main())
