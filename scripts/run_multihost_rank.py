"""One HOST PROCESS of the real multi-host bring-up test.

Launched (twice) by tests/test_multiprocess.py::test_multihost_two_processes:
each process joins a 2-process jax.distributed cluster over a local
coordinator, contributes 4 virtual CPU devices (8 global), builds the
hybrid DCN x ICI mesh through the SAME entry points a pod user calls
(utils.bringup.initialize_multihost + parallel.make_hybrid_mesh), and
runs a hierarchical all-reduce end to end, checking numerics on its
addressable shards.

Reference role: the MPI-launched multi-node driver bring-up + QP
exchange (test/host/Coyote/test.cpp:351-397) — exercised for real, not
dry-run (r4 VERDICT item 7).

Env: ACCL_COORDINATOR, ACCL_NUM_PROCESSES, ACCL_PROCESS_ID (read by
initialize_multihost), plus the JAX_PLATFORMS=cpu /
xla_force_host_platform_device_count=4 the parent sets.
Prints MULTIHOST_OK on success; any failure exits non-zero.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> None:
    import jax
    from accl_tpu.utils.compat import install as _compat_install
    _compat_install(jax)  # old-jax: alias jax.shard_map to the shim

    # the axon sitecustomize pins a hardware platform at interpreter
    # start; this test is a CPU-cluster test (see tests/conftest.py)
    jax.config.update("jax_platforms", "cpu")

    from accl_tpu.utils.bringup import initialize_multihost

    kwargs = initialize_multihost()  # from ACCL_* env — the real path
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())
    assert len(jax.local_devices()) == 4

    import numpy as np

    from jax.sharding import NamedSharding, PartitionSpec as P

    from accl_tpu.parallel.collectives import hierarchical_all_reduce
    from accl_tpu.parallel.mesh import make_hybrid_mesh

    # DCN axis spans the two host processes, ICI axis the 4 local
    # devices — exactly the pod-slice layout make_hybrid_mesh targets
    mesh = make_hybrid_mesh(ici={"ici": 4}, dcn={"dcn": 2})
    assert mesh.shape == {"dcn": 2, "ici": 4}, mesh.shape

    n = 64
    sharding = NamedSharding(mesh, P(("dcn", "ici")))
    # per-device distinct data: global row r holds value r + 1
    glob = np.arange(1, 8 * n + 1, dtype=np.float32)

    def cb(index):
        return glob[index]

    x = jax.make_array_from_callback((8 * n,), sharding, cb)

    step = jax.jit(jax.shard_map(
        lambda v: hierarchical_all_reduce(v, ici_axis="ici",
                                          dcn_axis="dcn"),
        mesh=mesh, in_specs=P(("dcn", "ici")),
        out_specs=P(("dcn", "ici"))))
    y = step(x)

    # every member's reduced shard = sum over the 8 members' rows
    want = glob.reshape(8, n).sum(axis=0)
    for s in y.addressable_shards:
        got = np.asarray(s.data)
        np.testing.assert_allclose(got, want, rtol=1e-5)

    # a flat psum over both axes must agree (the hierarchical schedule
    # is an optimization, not a semantics change)
    flat = jax.jit(jax.shard_map(
        lambda v: jax.lax.psum(v, ("dcn", "ici")),
        mesh=mesh, in_specs=P(("dcn", "ici")),
        out_specs=P(("dcn", "ici"))))
    z = flat(x)
    for s, t in zip(y.addressable_shards, z.addressable_shards):
        np.testing.assert_allclose(np.asarray(s.data),
                                   np.asarray(t.data), rtol=1e-5)

    print(f"MULTIHOST_OK process={kwargs.get('process_id')}", flush=True)


if __name__ == "__main__":
    main()
