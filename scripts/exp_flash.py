"""Flash-attention tuning experiments (run on the real TPU chip).

Decomposes the gap between flash_d128_mxu_frac and the matmul roofline:
times the BTHD wrapper, the packed (no-transpose) entry with/without
the K/V cast scratch, bf16 operands with chunked sub-folds, the
grid_resident schedule, block_q=512 variants, and jax's bundled splash
kernel as an achievability calibration.

Usage: python scripts/exp_flash.py [variant ...]
Variants: base d64 packed bf16 splash mm
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def main() -> None:
    which = set(sys.argv[1:]) or {"base", "packed", "bf16", "splash", "mm"}
    from accl_tpu.bench.timing import make_harness
    _probe, timed_chain, _ab, sync_s = make_harness(jax, jnp)
    print(f"sync_s={sync_s*1e3:.2f}ms backend={jax.default_backend()}",
          file=sys.stderr)

    B, T, H, D = 4, 2048, 4, 128
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(k1, (B, T, H, D), jnp.float32)
    k = jax.random.normal(k2, (B, T, H, D), jnp.float32)
    v = jax.random.normal(k3, (B, T, H, D), jnp.float32)
    flops = 4 * B * H * T * T * D / 2  # causal

    results = {}

    def run(name, fn, x, consts, iters=64, rounds=6, fl=flops):
        best = None
        for _ in range(rounds):
            dt = timed_chain(fn, x, iters=iters, trials=1, consts=consts)
            best = dt if best is None else min(best, dt)
        tf = fl / best / 1e12
        results[name] = tf
        print(f"{name:24s} {best*1e6:9.1f} us  {tf:7.2f} TFLOPs", flush=True)

    if "mm" in which:
        mm_n = 4096
        ka, kb = jax.random.split(jax.random.PRNGKey(7))
        ma = jax.random.normal(ka, (mm_n, mm_n), jnp.bfloat16)
        mb = jax.random.normal(kb, (mm_n, mm_n), jnp.bfloat16)
        run("matmul_bf16", lambda x, y: (x @ y).astype(jnp.bfloat16),
            ma, (mb,), iters=48, fl=2 * mm_n**3)

    if "base" in which:
        from accl_tpu.ops.flash import flash_attention
        run("base_resident", lambda x, kk, vv: flash_attention(
            x, kk, vv, causal=True), q, (k, v))
        run("base_grid", lambda x, kk, vv: flash_attention(
            x, kk, vv, causal=True, kernel="grid"), q, (k, v))

    if "d64" in which:
        from accl_tpu.ops.flash import flash_attention
        H2, D2 = 8, 64
        q4 = jax.random.normal(k1, (B, T, H2, D2), jnp.float32)
        k4 = jax.random.normal(k2, (B, T, H2, D2), jnp.float32)
        v4 = jax.random.normal(k3, (B, T, H2, D2), jnp.float32)
        run("base_d64", lambda x, kk, vv: flash_attention(
            x, kk, vv, causal=True), q4, (k4, v4))

    if "packed" in which:
        # operands already in [B*H, T, D] — isolates the pack/unpack
        # transpose cost from the kernel itself
        from accl_tpu.ops.flash import flash_attention_packed as fap
        qp = q.transpose(0, 2, 1, 3).reshape(B * H, T, D)
        kp = k.transpose(0, 2, 1, 3).reshape(B * H, T, D)
        vp = v.transpose(0, 2, 1, 3).reshape(B * H, T, D)
        run("packed_f32", lambda x, kk, vv: fap(x, kk, vv, causal=True),
            qp, (kp, vp))
        run("packed_f32_scratch",
            lambda x, kk, vv: fap(x, kk, vv, causal=True,
                                  kv_cast_scratch=True),
            qp, (kp, vp))
        if "bf16" in which:
            qb, kb, vb = (qp.astype(jnp.bfloat16), kp.astype(jnp.bfloat16),
                          vp.astype(jnp.bfloat16))
            for ck in (None, 256):
                run(f"packed_bf16_ck{ck}",
                    lambda x, kk, vv, c=ck: fap(x, kk, vv, causal=True,
                                                chunk_k=c),
                    qb, (kb, vb))
            for ck in (None, 256):
                run(f"gridres_bf16_ck{ck}",
                    lambda x, kk, vv, c=ck: fap(x, kk, vv, causal=True,
                                                kernel="grid_resident",
                                                chunk_k=c),
                    qb, (kb, vb))
            run("gridres_bf16_bq512",
                lambda x, kk, vv: fap(x, kk, vv, causal=True,
                                      kernel="grid_resident", block_q=512),
                qb, (kb, vb))
            run("packed_bf16_bq512",
                lambda x, kk, vv: fap(x, kk, vv, causal=True, block_q=512),
                qb, (kb, vb))

    if "splash" in which:
        # calibration: jax's bundled splash kernel, [H, T, D] layout,
        # vmapped over batch
        try:
            from jax.experimental.pallas.ops.tpu.splash_attention import (
                splash_attention_kernel as sk,
                splash_attention_mask as sm,
            )
            mask = sm.MultiHeadMask(
                [sm.CausalMask((T, T)) for _ in range(H)])
            kernel = sk.make_splash_mha(
                mask, head_shards=1, q_seq_shards=1)
            qs = q.transpose(0, 2, 1, 3)  # [B, H, T, D]
            ks = k.transpose(0, 2, 1, 3)
            vs = v.transpose(0, 2, 1, 3)
            vk = jax.jit(jax.vmap(kernel))

            def splash_fn(x, kk, vv):
                return vk(x, kk, vv)

            run("splash_bhtd", splash_fn, qs, (ks, vs))
            run("splash_bf16", splash_fn, qs.astype(jnp.bfloat16),
                (ks.astype(jnp.bfloat16), vs.astype(jnp.bfloat16)))
        except Exception as e:  # noqa: BLE001
            print(f"splash failed: {type(e).__name__}: {e}", file=sys.stderr)

    if "mm" in which and "base" in which:
        mmtf = results.get("matmul_bf16")
        if mmtf:
            for n, tf in results.items():
                if n != "matmul_bf16":
                    print(f"frac {n:24s} {tf/mmtf:.3f}")


if __name__ == "__main__":
    t0 = time.perf_counter()
    main()
    print(f"total {time.perf_counter()-t0:.0f}s", file=sys.stderr)
