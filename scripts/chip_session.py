"""One TPU claim window, every chip-bound artifact — resumable.

The shared chip's claim can stay blocked for long stretches, so when a
window opens this script harvests everything the round needs from real
hardware, stage by stage, skipping stages whose artifact already
exists:

  1. flash-attention schedule sweep  -> bench/results/flash_tune_r04.json
  2. 1KB-1GB reduce-lane size curve  -> bench/results/lane_sweep_r04.csv
     (the single-chip busbw-vs-size metric-of-record proxy: the on-path
     reduction lane streamed over HBM, with the plain-XLA add as the
     per-size memory roofline; reference role test/host/xrt/src/bench.cpp
     sweep + BASELINE.md "All-reduce busbw vs message size, 1KB-1GB")

Run under `timeout` from a retry loop; stages persist incrementally.
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(ROOT, "bench", "results")
FLASH_JSON = os.path.join(OUT, "flash_tune_r04.json")
LANE_CSV = os.path.join(OUT, "lane_sweep_r04.csv")


def flash_stage(timed_chain):
    from accl_tpu.bench.flash_sweep import (make_variant, report,
                                            run_sweep)

    # resumable at sweep granularity: the d128 result persists before
    # the d64 sweep starts, so a window closing mid-stage never
    # discards a completed sweep
    res = {}
    if os.path.exists(FLASH_JSON):
        try:
            with open(FLASH_JSON) as f:
                res = json.load(f)
        except ValueError:
            res = {}  # partial write from a killed run — redo

    cands = {
        "bq256_bk512": make_variant(256, 512),
        "bq512_bk512": make_variant(512, 512),
        "bq512_bk256": make_variant(512, 256),
        "bq256_bk512_ck256": make_variant(256, 512, ck=256),
        "bq256_bk512_qt2": make_variant(256, 512, qt=2),
        "bq512_bk512_qt2": make_variant(512, 512, qt=2),
        "bq512_bk512_qt4": make_variant(512, 512, qt=4),
        "bq256_bk512_fd": make_variant(256, 512, fd=True),
        "bq256_bk512_qt2_fd": make_variant(256, 512, qt=2, fd=True),
        "bq512_bk512_qt2_fd": make_variant(512, 512, qt=2, fd=True),
        # one-shot K/V cast (kills the per-fold f32->bf16 VPU pass)
        # stacked with the interleaved chains
        "bq256_bk512_cast": make_variant(256, 512, cast=True),
        "bq256_bk512_qt2_cast": make_variant(256, 512, qt=2,
                                             cast=True),
        "bq512_bk512_qt2_cast": make_variant(512, 512, qt=2,
                                             cast=True),
    }
    # per-ROUND persistence: a brief claim window that only survives
    # one round still banks its minimums (raw seconds merge across
    # runs; `schedules` is recomputed from the merged raw each time).
    # An artifact from the pre-persistence format (has schedules but no
    # raw seconds) is COMPLETE — don't throw its banked minimums away.
    raw = res.get("raw_s", {})
    raw_mm = res.get("raw_mm_s")
    rounds_done = res.get("rounds_done",
                          3 if "schedules" in res else 0)
    dead_local: set = set()  # compile-failed THIS process: skip its
    # remaining rounds (transient claim errors get retried by the next
    # process invocation)
    for _ in range(rounds_done, 3):
        live = {n: f for n, f in cands.items() if n not in dead_local}
        best, best_mm = run_sweep(jax, jnp, timed_chain, live, rounds=1)
        raw_mm = best_mm if raw_mm is None else min(raw_mm, best_mm)
        for name, dt in best.items():
            prev = raw.get(name)
            if isinstance(dt, float):
                raw[name] = (dt if not isinstance(prev, float)
                             else min(prev, dt))
            else:
                dead_local.add(name)
                if prev is None:
                    raw[name] = dt  # error string; next process retries
        rep = report(raw, raw_mm)
        res.update(rep)
        res["raw_s"] = raw
        res["raw_mm_s"] = raw_mm
        rounds_done += 1
        res["rounds_done"] = rounds_done
        _write_json(FLASH_JSON, res)

    # error-marked candidates from earlier invocations get ONE retry
    # per process even after all rounds completed (a transient claim
    # error in the final round must not freeze an {"error": ...} into
    # the artifact forever)
    errs = [n for n in cands
            if n in raw and not isinstance(raw[n], float)
            and n not in dead_local]
    if errs:
        best, best_mm = run_sweep(
            jax, jnp, timed_chain, {n: cands[n] for n in errs}, rounds=1)
        raw_mm = best_mm if raw_mm is None else min(raw_mm, best_mm)
        for name, dt in best.items():
            if isinstance(dt, float):
                raw[name] = dt
        res.update(report(raw, raw_mm))
        res["raw_s"] = raw
        res["raw_mm_s"] = raw_mm
        _write_json(FLASH_JSON, res)

    if "d64" not in res:
        cands64 = {
            "d64_resident": make_variant(256, 512),
            "d64_resident_fd": make_variant(256, 512, fd=True),
            "d64_resident_qt2_fd": make_variant(256, 512, qt=2, fd=True),
        }
        best64, best_mm64 = run_sweep(jax, jnp, timed_chain, cands64,
                                      rounds=2, d=64)
        res["d64"] = report(best64, best_mm64)
        _write_json(FLASH_JSON, res)


def _write_json(path, obj):
    # atomic: a kill mid-rewrite must not corrupt the previous result
    # (the resume logic depends on it)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1)
    os.replace(tmp, path)
    print(f"wrote {path}", file=sys.stderr, flush=True)


def lane_stage(timed_chain_ab):
    """busbw-vs-size curve for the on-path reduction lane, 1KB-1GB."""
    from accl_tpu.ops.reduce_ops import pallas_add

    header = "bytes,pallas_GBps,xla_GBps,iters\n"
    done = set()
    if os.path.exists(LANE_CSV):
        # keep only fully-written rows; a row truncated by a timeout
        # kill is dropped (and re-measured) rather than trusted
        good = []
        with open(LANE_CSV) as f:
            next(f, None)
            for line in f:
                if not line.endswith("\n"):
                    continue  # truncated final row — drop, re-measure
                parts = line.strip().split(",")
                try:
                    nb = int(parts[0])
                    float(parts[1]); float(parts[2]); int(parts[3])
                except (ValueError, IndexError):
                    continue
                done.add(nb)
                good.append(line)
        tmp = LANE_CSV + ".tmp"
        with open(tmp, "w") as f:
            f.write(header)
            f.writelines(good)
        os.replace(tmp, LANE_CSV)
    else:
        with open(LANE_CSV, "w") as f:
            f.write(header)

    for p in range(10, 31, 2):  # 1 KB .. 1 GB per operand
        nbytes = 1 << p
        if nbytes in done:
            continue
        n = nbytes // 4
        rows = max(1, n // 128)
        a = jax.random.normal(jax.random.PRNGKey(0), (rows, 128),
                              jnp.float32)
        b = jax.random.normal(jax.random.PRNGKey(1), (rows, 128),
                              jnp.float32)
        # keep ~8-30 ms of device work per dispatch across sizes
        iters = max(20, min(20000, (160 << 20) // nbytes))
        br = min(2048, rows)
        run = lambda x, bb: pallas_add(x, bb, block_rows=br, donate=True)
        xla = lambda x, bb: x + bb
        try:
            dts = timed_chain_ab({"pallas": run, "xla": xla}, a, iters,
                                 consts=(b,))
        except Exception as e:  # noqa: BLE001
            print(f"  lane {nbytes}B: FAILED {e}", file=sys.stderr,
                  flush=True)
            continue
        stream = 3 * nbytes  # read a, read b, write out
        row = (nbytes, round(stream / dts["pallas"] / 1e9, 3),
               round(stream / dts["xla"] / 1e9, 3), iters)
        with open(LANE_CSV, "a") as f:
            f.write(",".join(str(x) for x in row) + "\n")
        print(f"  lane {nbytes}B: pallas {row[1]} GB/s xla {row[2]} GB/s",
              file=sys.stderr, flush=True)
    print(f"wrote {LANE_CSV}", file=sys.stderr, flush=True)


def main():
    print(f"backend={jax.default_backend()}", file=sys.stderr, flush=True)
    from accl_tpu.bench.timing import make_harness

    _p, timed_chain, timed_chain_ab, _s = make_harness(jax, jnp)
    flash_stage(timed_chain)
    lane_stage(timed_chain_ab)
    print("chip session complete", file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
