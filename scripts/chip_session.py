"""One TPU claim window, every chip-bound artifact — resumable.

The shared chip's claim can stay blocked for long stretches, so when a
window opens this script harvests everything the round needs from real
hardware, stage by stage, skipping stages whose artifact already
exists:

  1. flash-attention schedule sweep  -> bench/results/flash_tune_r05.json
  2. 1KB-1GB reduce-lane size curve  -> bench/results/lane_sweep_r05.csv
     (the single-chip busbw-vs-size metric-of-record proxy: the on-path
     reduction lane streamed over HBM, with the plain-XLA add as the
     per-size memory roofline; reference role test/host/xrt/src/bench.cpp
     sweep + BASELINE.md "All-reduce busbw vs message size, 1KB-1GB")

Run under `timeout` from a retry loop (scripts/chip_retry.sh); stages
persist incrementally.  `--check` exits 0 iff every artifact is
complete BY THIS SCRIPT'S OWN DEFINITION (same candidate sets, same
row-validity rules) — the retry loop's termination test.  --check never
imports jax (under the axon platform even `import jax` can block on the
chip claim).
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(ROOT, "bench", "results")
FLASH_JSON = os.path.join(OUT, "flash_tune_r05.json")
LANE_CSV = os.path.join(OUT, "lane_sweep_r05.csv")
# consecutive-failure counts per lane size: a size that fails this many
# sessions in a row (e.g. deterministic OOM) is retired so the retry
# loop can terminate instead of rerunning a forever-incomplete sweep
LANE_FAIL_JSON = os.path.join(OUT, "lane_sweep_r05_failures.json")
LANE_MAX_FAILS = 3
LANE_SIZES = [1 << p for p in range(10, 31, 2)]  # 1 KB .. 1 GB

# Candidate SPECS as plain data (closures are built inside flash_stage)
# so --check can compare the current sets against a banked artifact
# without importing jax.  The sets follow the honest-timing (min-RTT
# harness) r04 findings: plain chains and the bq512 q-tile interleave
# are the Pareto front; split folds, qt4, and D=128 fused-denominator
# are out (fd at D=128 also on physics: the ones-extended V pads
# 129 -> 256 lanes, doubling the PV matmul — it stays in the D=64 set,
# where 65 and 64 pad to the same 128-lane tile); the skew schedule
# and one qt2+ck256 composition ride along so the rejected families
# keep being re-measured per chip generation.  The `cast` variant adds
# the one-shot K/V cast scratch (kills the per-fold f32->bf16 pass).
D128_SPECS = {
    "bq256_bk512": dict(bq=256, bk=512),
    "bq512_bk512": dict(bq=512, bk=512),
    "bq512_bk512_qt2": dict(bq=512, bk=512, qt=2),
    "bq256_bk512_qt2": dict(bq=256, bk=512, qt=2),
    "bq512_bk1024": dict(bq=512, bk=1024),
    "bq512_bk1024_qt2": dict(bq=512, bk=1024, qt=2),
    "bq256_bk1024": dict(bq=256, bk=1024),
    "bq512_bk512_cast": dict(bq=512, bk=512, cast=True),
    "bq256_bk512_skew": dict(bq=256, bk=512, kernel="resident_skew"),
    "bq512_bk512_qt2_ck256": dict(bq=512, bk=512, ck=256, qt=2),
    # r5 static-max pin: the VPU-minimal fold (no max/alpha/clamp
    # passes) — the decomposition change, not another block shape
    "bq256_bk512_sm40": dict(bq=256, bk=512, sm=40.0),
    "bq512_bk512_sm40": dict(bq=512, bk=512, sm=40.0),
    "bq256_bk512_sm40_qt2": dict(bq=256, bk=512, sm=40.0, qt=2),
}
D64_SPECS = {
    "d64_resident": dict(bq=256, bk=512),
    "d64_resident_fd": dict(bq=256, bk=512, fd=True),
    "d64_bq512_fd": dict(bq=512, bk=512, fd=True),
    "d64_resident_qt2_fd": dict(bq=256, bk=512, qt=2, fd=True),
    # static pin + fused denom: no VPU reductions left in the fold
    "d64_resident_fd_sm40": dict(bq=256, bk=512, fd=True, sm=40.0),
}


def _build(make_variant, specs):
    return {name: make_variant(sp["bq"], sp["bk"], ck=sp.get("ck"),
                               qt=sp.get("qt", 1), fd=sp.get("fd", False),
                               cast=sp.get("cast", False),
                               kernel=sp.get("kernel", "resident"),
                               sm=sp.get("sm"))
            for name, sp in specs.items()}


def flash_stage(jax, jnp, timed_chain):
    from accl_tpu.bench.flash_sweep import make_variant, report, run_sweep

    # resumable at sweep granularity: the d128 result persists before
    # the d64 sweep starts, so a window closing mid-stage never
    # discards a completed sweep
    res = {}
    if os.path.exists(FLASH_JSON):
        try:
            with open(FLASH_JSON) as f:
                res = json.load(f)
        except ValueError:
            res = {}  # partial write from a killed run — redo

    cands = _build(make_variant, D128_SPECS)
    # per-ROUND persistence: a brief claim window that only survives
    # one round still banks its minimums (raw seconds merge across
    # runs; `schedules` is recomputed from the merged raw each time).
    raw = res.get("raw_s", {})
    raw_mm = res.get("raw_mm_s")
    rounds_done = res.get("rounds_done",
                          3 if "schedules" in res else 0)
    # rounds_done counts rounds of THE CURRENT candidate set: when the
    # set changes (candidates added/renamed between sessions), a banked
    # artifact must not let the new candidates skip their measurement
    # rounds.  Minimums for still-present names are kept.
    cand_set = sorted(cands)
    if res.get("cand_set") != cand_set:
        rounds_done = 0
        # prune retired/renamed names so report() emits only the
        # current set (stale minimums from other contention windows
        # must not compete with the live candidates)
        raw = {n: v for n, v in raw.items() if n in cands}
    res["cand_set"] = cand_set
    dead_local: set = set()  # compile-failed THIS process: skip its
    # remaining rounds (transient claim errors get retried by the next
    # process invocation)
    for _ in range(rounds_done, 3):
        live = {n: f for n, f in cands.items() if n not in dead_local}
        best, best_mm = run_sweep(jax, jnp, timed_chain, live, rounds=1)
        raw_mm = best_mm if raw_mm is None else min(raw_mm, best_mm)
        for name, dt in best.items():
            prev = raw.get(name)
            if isinstance(dt, float):
                raw[name] = (dt if not isinstance(prev, float)
                             else min(prev, dt))
            else:
                dead_local.add(name)
                if prev is None:
                    raw[name] = dt  # error string; next process retries
        rep = report(raw, raw_mm)
        res.update(rep)
        res["raw_s"] = raw
        res["raw_mm_s"] = raw_mm
        rounds_done += 1
        res["rounds_done"] = rounds_done
        _write_json(FLASH_JSON, res)

    # error-marked candidates from earlier invocations get ONE retry
    # per process even after all rounds completed (a transient claim
    # error in the final round must not freeze an {"error": ...} into
    # the artifact forever).  A candidate that keeps failing keeps its
    # error string — completeness does not require it to turn numeric.
    errs = [n for n in cands
            if n in raw and not isinstance(raw[n], float)
            and n not in dead_local]
    if errs:
        best, best_mm = run_sweep(
            jax, jnp, timed_chain, {n: cands[n] for n in errs}, rounds=1)
        raw_mm = best_mm if raw_mm is None else min(raw_mm, best_mm)
        for name, dt in best.items():
            if isinstance(dt, float):
                raw[name] = dt
        res.update(report(raw, raw_mm))
        res["raw_s"] = raw
        res["raw_mm_s"] = raw_mm
        _write_json(FLASH_JSON, res)

    # d64 sweep carries the same stale-set guard as the main set
    d64_set = sorted(D64_SPECS)
    if "d64" not in res or res.get("d64_cand_set") != d64_set:
        cands64 = _build(make_variant, D64_SPECS)
        best64, best_mm64 = run_sweep(jax, jnp, timed_chain, cands64,
                                      rounds=2, d=64)
        res["d64"] = report(best64, best_mm64)
        res["d64_cand_set"] = d64_set
        _write_json(FLASH_JSON, res)


def _write_json(path, obj):
    # atomic: a kill mid-rewrite must not corrupt the previous result
    # (the resume logic depends on it)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1)
    os.replace(tmp, path)
    print(f"wrote {path}", file=sys.stderr, flush=True)


def _lane_done() -> set:
    """Sizes with a fully-written CSV row (same validity rule the
    resume logic applies: trailing newline + parseable fields)."""
    done = set()
    if not os.path.exists(LANE_CSV):
        return done
    with open(LANE_CSV) as f:
        next(f, None)
        for line in f:
            if not line.endswith("\n"):
                continue
            parts = line.strip().split(",")
            try:
                nb = int(parts[0])
                float(parts[1]); float(parts[2]); int(parts[3])
            except (ValueError, IndexError):
                continue
            done.add(nb)
    return done


def _lane_fails() -> dict:
    try:
        with open(LANE_FAIL_JSON) as f:
            return {int(k): int(v) for k, v in json.load(f).items()}
    except Exception:  # noqa: BLE001 — absent/corrupt: start clean
        return {}


def lane_stage(jax, jnp, timed_chain_ab):
    """busbw-vs-size curve for the on-path reduction lane, 1KB-1GB."""
    from accl_tpu.ops.reduce_ops import pallas_add

    header = "bytes,pallas_GBps,xla_GBps,iters\n"
    done = _lane_done()
    if os.path.exists(LANE_CSV):
        # rewrite keeping only fully-written rows; a row truncated by a
        # timeout kill is dropped (and re-measured) rather than trusted
        good = []
        with open(LANE_CSV) as f:
            next(f, None)
            for line in f:
                if not line.endswith("\n"):
                    continue
                try:
                    nb = int(line.split(",", 1)[0])
                except ValueError:
                    continue
                if nb in done:
                    good.append(line)
        tmp = LANE_CSV + ".tmp"
        with open(tmp, "w") as f:
            f.write(header)
            f.writelines(good)
        os.replace(tmp, LANE_CSV)
    else:
        with open(LANE_CSV, "w") as f:
            f.write(header)

    fails = _lane_fails()
    for nbytes in LANE_SIZES:
        if nbytes in done:
            continue
        if fails.get(nbytes, 0) >= LANE_MAX_FAILS:
            print(f"  lane {nbytes}B: retired after "
                  f"{fails[nbytes]} failed sessions", file=sys.stderr,
                  flush=True)
            continue
        n = nbytes // 4
        rows = max(1, n // 128)
        # keep ~8-30 ms of device work per dispatch across sizes
        iters = max(20, min(20000, (160 << 20) // nbytes))
        br = min(2048, rows)
        def run(x, bb):
            return pallas_add(x, bb, block_rows=br, donate=True)

        def xla(x, bb):
            return x + bb
        try:
            # operand allocation INSIDE the try: a deterministic OOM at
            # the big sizes must count toward retirement too
            a = jax.random.normal(jax.random.PRNGKey(0), (rows, 128),
                                  jnp.float32)
            b = jax.random.normal(jax.random.PRNGKey(1), (rows, 128),
                                  jnp.float32)
            dts = timed_chain_ab({"pallas": run, "xla": xla}, a, iters,
                                 consts=(b,))
        except Exception as e:  # noqa: BLE001
            # distinguish a size-specific failure (OOM — count toward
            # retirement) from the chip claim dying under us (the
            # documented normal case the retry loop rides out — do NOT
            # count, end the session and let the next window resume)
            try:
                float(jnp.zeros((), jnp.float32) + 1.0)
            except Exception:  # noqa: BLE001 — chip gone
                print(f"  lane {nbytes}B: chip lost mid-measure ({e}); "
                      "ending session", file=sys.stderr, flush=True)
                return
            fails[nbytes] = fails.get(nbytes, 0) + 1
            _write_json(LANE_FAIL_JSON, {str(k): v
                                         for k, v in fails.items()})
            print(f"  lane {nbytes}B: FAILED "
                  f"({fails[nbytes]}/{LANE_MAX_FAILS}) {e}",
                  file=sys.stderr, flush=True)
            continue
        if nbytes in fails:
            del fails[nbytes]
            _write_json(LANE_FAIL_JSON, {str(k): v
                                         for k, v in fails.items()})
        stream = 3 * nbytes  # read a, read b, write out
        row = (nbytes, round(stream / dts["pallas"] / 1e9, 3),
               round(stream / dts["xla"] / 1e9, 3), iters)
        with open(LANE_CSV, "a") as f:
            f.write(",".join(str(x) for x in row) + "\n")
        print(f"  lane {nbytes}B: pallas {row[1]} GB/s xla {row[2]} GB/s",
              file=sys.stderr, flush=True)
    print(f"wrote {LANE_CSV}", file=sys.stderr, flush=True)


def check_complete() -> bool:
    """True iff every artifact is complete for the CURRENT candidate
    sets.  Error-string candidates count as complete (measured as
    failing, recorded); lane sizes count when measured OR retired."""
    try:
        with open(FLASH_JSON) as f:
            res = json.load(f)
    except Exception:  # noqa: BLE001
        return False
    if "schedules" not in res or res.get("rounds_done", 0) < 3:
        return False
    if res.get("cand_set") != sorted(D128_SPECS):
        return False
    if "d64" not in res or res.get("d64_cand_set") != sorted(D64_SPECS):
        return False
    raw = res.get("raw_s", {})
    if any(n not in raw for n in D128_SPECS):
        return False
    done, fails = _lane_done(), _lane_fails()
    return all(nb in done or fails.get(nb, 0) >= LANE_MAX_FAILS
               for nb in LANE_SIZES)


def main():
    if "--check" in sys.argv:
        ok = check_complete()
        print("complete" if ok else "incomplete", file=sys.stderr)
        sys.exit(0 if ok else 1)

    import jax
    import jax.numpy as jnp

    from accl_tpu.utils.compile_cache import enable as _enable_cache

    _enable_cache()  # retry attempts reuse the prior window's compiles
    print(f"backend={jax.default_backend()}", file=sys.stderr, flush=True)
    from accl_tpu.bench.timing import make_harness

    _p, timed_chain, timed_chain_ab, _s = make_harness(jax, jnp)
    flash_stage(jax, jnp, timed_chain)
    lane_stage(jax, jnp, timed_chain_ab)
    print("chip session complete", file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
