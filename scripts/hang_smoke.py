#!/usr/bin/env python
"""CI hang-injection smoke: prove the always-on black box end to end.

Scenario (the acceptance drill for the flight-recorder/watchdog layer):
a 4-rank emu world where ranks 1..N-1 issue an allreduce and rank 0
withholds its gang member past ACCL_WATCHDOG_TIMEOUT.  Asserts, in
order:

1. the watchdog fires within the timeout and its merged flight dump
   (a) matches the RECORD_SCHEMA_KEYS schema and (b) names the missing
   rank AND the blocked collective;
2. the OpenMetrics endpoint (ACCL_METRICS_PORT, here an ephemeral
   port) flips ``accl_health`` to hung (2) — the curl-able signal;
3. after the withheld rank finally joins, the collective completes
   with correct results and health returns to ok (0) — a watchdog fire
   is a diagnosis, not a failure;
4. scripts/accl_doctor.py reads the dump and reports the same hang.

Artifacts (uploaded by CI next to the trace smoke): the watchdog dump
and the per-rank flight dumps.

Usage: python scripts/hang_smoke.py [--ranks N] [--timeout S]
       [--dump PATH] [--report PATH]
"""
import argparse
import json
import os
import subprocess
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=4)
    ap.add_argument("--count", type=int, default=256)
    ap.add_argument("--timeout", type=float, default=1.0,
                    help="watchdog stuck-gang threshold (s)")
    ap.add_argument("--dump", default="hang_flight_dump.json",
                    help="watchdog dump artifact path")
    ap.add_argument("--report", default="hang_doctor_report.txt",
                    help="accl_doctor output artifact path")
    args = ap.parse_args()

    # arm everything exactly as a production user would: env, before
    # any accl import.  Engine receive budget far above the hang length
    # so the stall is diagnosed by the WATCHDOG, not an engine timeout.
    os.environ["ACCL_WATCHDOG_TIMEOUT"] = str(args.timeout)
    os.environ["ACCL_WATCHDOG_DUMP"] = args.dump
    os.environ.setdefault("ACCL_DEFAULT_TIMEOUT", "60000000")

    import numpy as np

    from accl_tpu import ReduceFunction
    from accl_tpu.backends.emu import EmuWorld
    from accl_tpu.observability import health as obs_health
    from accl_tpu.observability.flight import RECORD_SCHEMA_KEYS

    exporter = obs_health.start_exporter(port=0)  # the ACCL_METRICS_PORT path
    base = f"http://{exporter.host}:{exporter.port}"

    def scrape_health() -> int:
        body = urllib.request.urlopen(base + "/metrics", timeout=10
                                      ).read().decode()
        for line in body.splitlines():
            if line.startswith("accl_health "):
                return int(float(line.split()[1]))
        raise AssertionError("accl_health gauge missing from /metrics")

    with EmuWorld(args.ranks) as world:
        bufs = {}

        def setup(accl, rank):
            s = accl.create_buffer_like(
                np.arange(args.count, dtype=np.float32) + rank)
            r = accl.create_buffer(args.count, np.float32)
            bufs[rank] = (s, r)

        world.run(setup)

        # -- inject the hang: rank 0 withholds its gang member --------
        reqs = {}

        def issue(accl, rank):
            if rank == 0:
                return None  # the delayed rank
            s, r = bufs[rank]
            reqs[rank] = accl.allreduce(s, r, args.count,
                                        ReduceFunction.SUM, run_async=True)
            return True

        world.run(issue)

        deadline = time.time() + args.timeout * 10 + 10
        while world.watchdog.last_report is None:
            if time.time() > deadline:
                print("FAIL: watchdog never fired")
                return 1
            time.sleep(0.05)
        report = world.watchdog.last_report

        # -- 1a. dump schema ------------------------------------------
        for rd in report["ranks"]:
            for key in ("rank", "capacity", "last_completed_seq",
                        "records"):
                if key not in rd:
                    print(f"FAIL: rank dump missing {key!r}")
                    return 1
            for rec in rd["records"]:
                missing = [k for k in RECORD_SCHEMA_KEYS if k not in rec]
                if missing:
                    print(f"FAIL: record missing keys {missing}: {rec}")
                    return 1
        if not os.path.exists(args.dump):
            print(f"FAIL: watchdog did not write {args.dump}")
            return 1

        # -- 1b. the hang names the missing rank + collective ---------
        hangs = report["analysis"]["hangs"]
        if not hangs:
            print("FAIL: fired report carries no hang analysis")
            return 1
        h = hangs[0]
        if h["collective"] != "allreduce" or h["missing"] != [0] \
                or h["arrived"] != list(range(1, args.ranks)):
            print(f"FAIL: wrong diagnosis: {h}")
            return 1

        # -- 2. OpenMetrics endpoint shows hung -----------------------
        if scrape_health() != obs_health.HEALTH_HUNG:
            print("FAIL: accl_health gauge did not flip to hung")
            return 1
        hz = json.loads(urllib.request.urlopen(base + "/healthz",
                                               timeout=10).read())
        if hz["health"] != "hung" or hz["watchdog_fires"] < 1:
            print(f"FAIL: /healthz disagrees: {hz}")
            return 1

        # -- 3. the withheld rank joins; everything completes ---------
        def join(accl, rank):
            if rank != 0:
                return None
            s, r = bufs[rank]
            accl.allreduce(s, r, args.count, ReduceFunction.SUM)
            return r.host.copy()

        outs = world.run(join)
        for rank in range(1, args.ranks):
            assert reqs[rank].wait(60), f"rank {rank} never completed"
            reqs[rank].check()
            bufs[rank][1].slice(0, args.count).sync_from_device()
        expected = np.sum([np.arange(args.count, dtype=np.float32) + r
                           for r in range(args.ranks)], axis=0)
        np.testing.assert_allclose(outs[0], expected)
        for rank in range(1, args.ranks):
            np.testing.assert_allclose(bufs[rank][1].host, expected)

        deadline = time.time() + 20
        while scrape_health() != obs_health.HEALTH_OK:
            if time.time() > deadline:
                print("FAIL: health never recovered to ok")
                return 1
            time.sleep(0.1)

    # -- 4. accl_doctor reads the dump back -----------------------------
    doctor = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "accl_doctor.py"), args.dump],
        capture_output=True, text=True)
    with open(args.report, "w") as f:
        f.write(doctor.stdout + doctor.stderr)
    if doctor.returncode != 0 or "MISSING ranks: [0]" not in doctor.stdout:
        print(f"FAIL: accl_doctor did not report the hang:\n"
              f"{doctor.stdout}\n{doctor.stderr}")
        return 1

    obs_health.stop_exporter()
    print(f"OK: watchdog fired in <= {args.timeout}s, named missing "
          f"rank 0 on allreduce; accl_health flipped hung->ok; "
          f"dump={args.dump} doctor={args.report}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
