"""CI perf gate: run bench.py and assert it did not regress.

Compares the fresh bench.py JSON line against the last recorded round
artifact (BENCH_r*.json, written by the round driver).  Policy:

- same platform (tpu vs tpu): fail below (1 - tolerance) x recorded value;
- platform downgrade (recorded tpu, now cpu/numpy fallback): the gate is
  SKIPPED with a warning — CI runners have no TPU, and a fallback number
  is not comparable to a hardware number;
- no recorded artifact: record-only mode, always passes.

Usage: python scripts/check_bench_delta.py [--tolerance 0.5]
(the tolerance is deliberately loose: the bench chip is shared and the
best-of-trials methodology still moves run to run).

PLAN-REPLAY rung gate (--plan): runs a short callrate bench fresh and
compares its persistent-plan lanes against the newest committed
``bench/results/callrate_r*_plan_on.json``: the fresh plan_sync call
rate must stay above (1 - tolerance) x the committed rate.  The
overhead-vs-raw ratio is printed and WARNED past --plan-ratio but
does not fail the build on its own — on 1-2 shared CI cores the raw
lane's window swings 3x round-to-round, so a short run's same-round
ratio can read 2.5x while the absolute plan call rate BEATS the
committed record (observed); the absolute rate is the robust signal,
the committed record documents the <=1.15x acceptance ratio.  With no
committed plan record the gate passes in record-only mode.

SWEEP-RUNG gate (--sweep): per-collective regression check over the
committed tpu8 sweep CSVs.  The newest sweep_tpu8_rNN.csv is compared
entry-by-entry — (collective, count), best duration over repetitions —
against the committed gate baseline
(bench/results/sweep_gate_baseline_r*.csv); any entry slower than
--sweep-ratio (default 2.0) x baseline fails the build.  A round that
*explains* a slowdown re-baselines by committing a new
sweep_gate_baseline_rNN.csv — the gate forces that explanation to be a
deliberate, reviewed act instead of silent drift (VERDICT r5 weak #2 /
next-round #3).  With no sweep newer than the baseline the gate passes
in record-only mode.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _objects(raw: str):
    """Walk concatenated (possibly pretty-printed) JSON objects."""
    dec = json.JSONDecoder()
    idx = 0
    while idx < len(raw):
        while idx < len(raw) and raw[idx] not in "{[":
            idx += 1
        if idx >= len(raw):
            return
        try:
            obj, end = dec.raw_decode(raw, idx)
        except json.JSONDecodeError:
            idx += 1  # skip a corrupt/truncated object, keep scanning
            continue
        yield obj
        idx = end


def last_recorded() -> dict | None:
    paths = sorted(glob.glob(os.path.join(ROOT, "BENCH_r*.json")))
    for path in reversed(paths):
        # the driver may concatenate {...}{...} across attempts; take
        # the LAST object carrying a parsed value
        best = None
        for doc in _objects(open(path).read()):
            parsed = doc.get("parsed") if isinstance(doc, dict) else None
            if parsed and parsed.get("value"):
                best = parsed
        if best:
            best["_source"] = os.path.basename(path)
            return best
    return None


def _sweep_best(path: str) -> dict:
    """Per-(collective, count) best duration_us across repetitions."""
    import csv

    best: dict = {}
    with open(path) as f:
        for row in csv.DictReader(f):
            key = (row["collective"], int(row["count"]))
            v = float(row["duration_us"])
            if key not in best or v < best[key]:
                best[key] = v
    return best


def _round_of(path: str) -> int:
    import re

    m = re.search(r"_r(\d+)\.csv$", path)
    return int(m.group(1)) if m else -1


def sweep_gate(ratio: float) -> int:
    results = os.path.join(ROOT, "bench", "results")
    baselines = sorted(
        glob.glob(os.path.join(results, "sweep_gate_baseline_r*.csv")),
        key=_round_of)
    if not baselines:
        print("sweep gate: no committed baseline — record-only pass")
        return tuned_lane_gate()
    base_path = baselines[-1]
    base_round = _round_of(base_path)
    sweeps = [p for p in glob.glob(
        os.path.join(results, "sweep_tpu8_r*.csv"))
        if _round_of(p) > base_round]
    if not sweeps:
        print(f"sweep gate: no sweep newer than baseline r{base_round:02d}"
              " — record-only pass")
        return tuned_lane_gate()
    new_path = max(sweeps, key=_round_of)
    base = _sweep_best(base_path)
    new = _sweep_best(new_path)
    shared = sorted(set(base) & set(new))
    print(f"sweep gate: {os.path.basename(new_path)} vs baseline "
          f"{os.path.basename(base_path)} ({len(shared)} shared entries,"
          f" fail ratio {ratio}x)")
    bad = []
    for key in shared:
        r = new[key] / base[key]
        if r > ratio:
            bad.append((key, r))
    for (coll, count), r in bad:
        print(f"sweep gate: REGRESSION {coll} count={count}: "
              f"{new[(coll, count)]:.0f}us vs {base[(coll, count)]:.0f}us "
              f"({r:.1f}x)", file=sys.stderr)
    if bad:
        print(f"sweep gate: {len(bad)}/{len(shared)} entries regressed "
              f"> {ratio}x — root-cause or re-baseline with a new "
              "sweep_gate_baseline_rNN.csv + explanation",
              file=sys.stderr)
        return 1
    print("sweep gate: OK")
    return tuned_lane_gate()


def tuned_lane_gate(slow_ratio: float = 1.05,
                    win_ratio: float = 1.15) -> int:
    """The tuned lane of the sweep gate (r16): validate the committed
    ``sweep_r*_tuned_vs_static.csv`` record — the autotuned policy must
    never be more than ``slow_ratio`` slower than static on any cell,
    and the record's ``win_ratio`` wins are counted for the log.  A
    tree without a tuned record passes (the lane is optional until a
    tuner run commits one)."""
    import csv
    import re

    def _tuned_round(path: str) -> int:
        m = re.search(r"sweep_r(\d+)_tuned_vs_static\.csv$", path)
        return int(m.group(1)) if m else -1

    results = os.path.join(ROOT, "bench", "results")
    records = sorted(glob.glob(
        os.path.join(results, "sweep_r*_tuned_vs_static.csv")),
        key=_tuned_round)
    if not records:
        print("sweep gate: no tuned-vs-static record — tuned lane "
              "skipped")
        return 0
    path = records[-1]
    bad, wins, rows = [], 0, 0
    with open(path) as f:
        for row in csv.DictReader(f):
            rows += 1
            r = float(row["ratio"])
            if r < 1.0 / slow_ratio:
                bad.append((row["collective"], row["size_bucket"], r))
            if r >= win_ratio:
                wins += 1
    print(f"sweep gate (tuned lane): {os.path.basename(path)} — "
          f"{rows} cells, {wins} at >= {win_ratio}x busbw vs static")
    for coll, bucket, r in bad:
        print(f"sweep gate (tuned lane): {coll} {bucket} is {r}x "
              f"static (< {1.0 / slow_ratio:.3f}) — the committed "
              f"policy regresses this cell", file=sys.stderr)
    if bad:
        print("sweep gate (tuned lane): re-run scripts/accl_tune.py "
              "--record (compare() prunes unreproducible selections) "
              "before committing the table", file=sys.stderr)
        return 1
    print("sweep gate (tuned lane): OK")
    return 0


def quantized_gate(min_ratio: float = 1.5,
                   min_bytes: int = 64 * 1024) -> int:
    """Quantized wire-lane gate (r17): validate the newest committed
    ``sweep_r*_quantized_*.csv`` record — the int8 lane must beat the
    lossless lane's busbw by >= ``min_ratio`` on every allreduce row at
    or above ``min_bytes``, the lossless lane's max_ulp must stay in
    summation-order-noise territory, and the int8 error columns must be
    finite and bounded.  A tree without a quantized record passes (the
    lane is optional until a capture commits one)."""
    import csv
    import re

    def _q_round(path: str) -> int:
        m = re.search(r"sweep_r(\d+)_quantized", path)
        return int(m.group(1)) if m else -1

    results = os.path.join(ROOT, "bench", "results")
    records = sorted(glob.glob(
        os.path.join(results, "sweep_r*_quantized_*.csv")),
        key=_q_round)
    if not records:
        print("quantized gate: no committed quantized sweep record — "
              "skipped")
        return 0
    path = records[-1]
    cells: dict = {}
    with open(path) as f:
        for row in csv.DictReader(f):
            key = (row["collective"], int(row["count"]))
            cells.setdefault(key, {})[row["lane"]] = row
    bad = []
    checked = 0
    for (coll, count), lanes in sorted(cells.items()):
        lossless, int8 = lanes.get("lossless"), lanes.get("int8")
        if lossless is None or int8 is None:
            continue
        # lossless exactness: summation-order noise only (the bitwise
        # gate runs on integer-valued data in the test suite; random
        # f32 data legitimately differs from the f64 reference by a
        # relative handful of ULP, which scales with count)
        if float(lossless["max_abs_err"]) > 1e-4:
            bad.append(f"{coll}/{count}: lossless max_abs_err "
                       f"{lossless['max_abs_err']} — the lossless lane "
                       f"is no longer lossless")
        if not float(int8["max_abs_err"]) < 1.0:
            bad.append(f"{coll}/{count}: int8 max_abs_err "
                       f"{int8['max_abs_err']} outside the documented "
                       f"bound")
        if coll == "allreduce" and int(lossless["bytes"]) >= min_bytes:
            checked += 1
            r = (float(int8["busbw_GBps"])
                 / max(float(lossless["busbw_GBps"]), 1e-12))
            if r < min_ratio:
                bad.append(f"{coll}/{count}: int8 busbw only {r:.2f}x "
                           f"lossless (< {min_ratio}x) — the quantized "
                           f"lane no longer pays for itself")
    print(f"quantized gate: {os.path.basename(path)} — "
          f"{len(cells)} cells, {checked} allreduce rows >= "
          f"{min_bytes // 1024} KiB checked at >= {min_ratio}x")
    for b in bad:
        print(f"quantized gate: {b}", file=sys.stderr)
    if bad:
        print("quantized gate: re-capture the record "
              "(scripts/run_sweep.py --quantized) or root-cause the "
              "lane regression before committing", file=sys.stderr)
        return 1
    print("quantized gate: OK")
    return 0


def plan_gate(tolerance: float, ratio: float) -> int:
    """Plan-replay rung: fresh short callrate vs the committed
    callrate_r*_plan_on baseline (see module docstring)."""
    results = os.path.join(ROOT, "bench", "results")
    records = sorted(
        glob.glob(os.path.join(results, "callrate_r*_plan_on.json")),
        key=lambda p: os.path.basename(p))
    if not records:
        print("plan gate: no committed callrate_r*_plan_on.json — "
              "record-only pass")
        return 0
    base = json.load(open(records[-1]))
    base_lane = base.get("lanes", {}).get("driver_plan_sync")
    if base_lane is None:
        print("plan gate: baseline record has no driver_plan_sync lane",
              file=sys.stderr)
        return 1
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "accl_tpu.bench.callrate",
             "--ranks", "4", "--count", "1024", "--iters", "120",
             "--rounds", "3"],
            capture_output=True, text=True, timeout=1200, cwd=ROOT)
    except subprocess.TimeoutExpired:
        print("plan gate: callrate bench hung past 1200s",
              file=sys.stderr)
        return 1
    line = next((ln for ln in reversed(proc.stdout.strip().splitlines())
                 if ln.startswith("{")), None)
    if proc.returncode != 0 or line is None:
        print(f"plan gate: callrate bench failed rc={proc.returncode}\n"
              f"{proc.stderr[-2000:]}", file=sys.stderr)
        return 1
    now = json.loads(line)
    lane = now["lanes"]["driver_plan_sync"]
    print(f"plan gate: fresh plan_sync {lane['calls_per_s']} calls/s "
          f"({now['plan_sync_overhead_x']}x raw), async "
          f"{now['plan_async_overhead_x']}x raw; baseline "
          f"{base_lane['calls_per_s']} calls/s "
          f"({os.path.basename(records[-1])})")
    floor = base_lane["calls_per_s"] * (1.0 - tolerance)
    if lane["calls_per_s"] < floor:
        print(f"plan gate: REGRESSION — plan_sync {lane['calls_per_s']}"
              f" calls/s < floor {floor:.1f}", file=sys.stderr)
        return 1
    if now["plan_sync_overhead_x"] > ratio:
        # advisory only: the absolute call rate above is the robust
        # signal on shared runners (see module docstring)
        print(f"plan gate: WARNING — plan_sync overhead "
              f"{now['plan_sync_overhead_x']}x raw > {ratio}x in this "
              f"window (raw swings 3x on shared cores; call-rate "
              f"floor passed)", file=sys.stderr)
    print("plan gate: OK")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tolerance", type=float, default=0.5)
    ap.add_argument("--sweep", action="store_true",
                    help="run the per-collective sweep-rung gate "
                         "instead of the headline bench gate")
    ap.add_argument("--sweep-ratio", type=float, default=2.0)
    ap.add_argument("--plan", action="store_true",
                    help="run the plan-replay rung gate (fresh "
                         "callrate plan lanes vs the committed "
                         "callrate_r*_plan_on baseline)")
    ap.add_argument("--plan-ratio", type=float, default=1.5)
    ap.add_argument("--quantized", action="store_true",
                    help="validate the committed r17 quantized "
                         "wire-lane record (int8 >= 1.5x lossless "
                         "busbw for allreduce >= 64 KiB)")
    ap.add_argument("--quantized-ratio", type=float, default=1.5)
    args = ap.parse_args()

    if args.sweep:
        return sweep_gate(args.sweep_ratio)
    if args.plan:
        return plan_gate(args.tolerance, args.plan_ratio)
    if args.quantized:
        return quantized_gate(args.quantized_ratio)

    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "bench.py")],
            capture_output=True, text=True, timeout=1200)
    except subprocess.TimeoutExpired:
        print("perf gate: bench.py hung past 1200s (TPU claim on a "
              "runner without hardware access?) — failing with context",
              file=sys.stderr)
        return 1
    line = next((ln for ln in reversed(proc.stdout.strip().splitlines())
                 if ln.startswith("{")), None)
    if proc.returncode != 0 or line is None:
        print(f"perf gate: bench.py failed rc={proc.returncode}",
              file=sys.stderr)
        return 1
    now = json.loads(line)
    print(f"perf gate: fresh  {now['value']} {now['unit']} "
          f"({now.get('platform')})")

    ref = last_recorded()
    if ref is None:
        print("perf gate: no recorded BENCH_r*.json — record-only pass")
        return 0
    print(f"perf gate: recorded {ref['value']} {ref['unit']} "
          f"({ref.get('platform')}, {ref['_source']})")

    if now.get("platform") != ref.get("platform"):
        print("perf gate: platform differs (no TPU on this runner?) — "
              "SKIPPED", file=sys.stderr)
        return 0
    floor = ref["value"] * (1.0 - args.tolerance)
    if now["value"] < floor:
        print(f"perf gate: REGRESSION — {now['value']} < floor "
              f"{floor:.1f} ({args.tolerance:.0%} below recorded)",
              file=sys.stderr)
        return 1
    print("perf gate: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
