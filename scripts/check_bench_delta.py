"""CI perf gate: run bench.py and assert it did not regress.

Compares the fresh bench.py JSON line against the last recorded round
artifact (BENCH_r*.json, written by the round driver).  Policy:

- same platform (tpu vs tpu): fail below (1 - tolerance) x recorded value;
- platform downgrade (recorded tpu, now cpu/numpy fallback): the gate is
  SKIPPED with a warning — CI runners have no TPU, and a fallback number
  is not comparable to a hardware number;
- no recorded artifact: record-only mode, always passes.

Usage: python scripts/check_bench_delta.py [--tolerance 0.5]
(the tolerance is deliberately loose: the bench chip is shared and the
best-of-trials methodology still moves run to run).
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _objects(raw: str):
    """Walk concatenated (possibly pretty-printed) JSON objects."""
    dec = json.JSONDecoder()
    idx = 0
    while idx < len(raw):
        while idx < len(raw) and raw[idx] not in "{[":
            idx += 1
        if idx >= len(raw):
            return
        try:
            obj, end = dec.raw_decode(raw, idx)
        except json.JSONDecodeError:
            idx += 1  # skip a corrupt/truncated object, keep scanning
            continue
        yield obj
        idx = end


def last_recorded() -> dict | None:
    paths = sorted(glob.glob(os.path.join(ROOT, "BENCH_r*.json")))
    for path in reversed(paths):
        # the driver may concatenate {...}{...} across attempts; take
        # the LAST object carrying a parsed value
        best = None
        for doc in _objects(open(path).read()):
            parsed = doc.get("parsed") if isinstance(doc, dict) else None
            if parsed and parsed.get("value"):
                best = parsed
        if best:
            best["_source"] = os.path.basename(path)
            return best
    return None


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tolerance", type=float, default=0.5)
    args = ap.parse_args()

    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "bench.py")],
            capture_output=True, text=True, timeout=1200)
    except subprocess.TimeoutExpired:
        print("perf gate: bench.py hung past 1200s (TPU claim on a "
              "runner without hardware access?) — failing with context",
              file=sys.stderr)
        return 1
    line = next((ln for ln in reversed(proc.stdout.strip().splitlines())
                 if ln.startswith("{")), None)
    if proc.returncode != 0 or line is None:
        print(f"perf gate: bench.py failed rc={proc.returncode}",
              file=sys.stderr)
        return 1
    now = json.loads(line)
    print(f"perf gate: fresh  {now['value']} {now['unit']} "
          f"({now.get('platform')})")

    ref = last_recorded()
    if ref is None:
        print("perf gate: no recorded BENCH_r*.json — record-only pass")
        return 0
    print(f"perf gate: recorded {ref['value']} {ref['unit']} "
          f"({ref.get('platform')}, {ref['_source']})")

    if now.get("platform") != ref.get("platform"):
        print("perf gate: platform differs (no TPU on this runner?) — "
              "SKIPPED", file=sys.stderr)
        return 0
    floor = ref["value"] * (1.0 - args.tolerance)
    if now["value"] < floor:
        print(f"perf gate: REGRESSION — {now['value']} < floor "
              f"{floor:.1f} ({args.tolerance:.0%} below recorded)",
              file=sys.stderr)
        return 1
    print("perf gate: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
