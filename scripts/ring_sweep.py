"""Ring-kernel busbw sanity sweep (VERDICT r1 item 3).

Runs the segmented Pallas ring allreduce against the XLA psum path on
the same mesh across message sizes and prints a CSV of seconds and
effective busbw (nccl convention: 2*(P-1)/P * bytes / time).  On the
CPU rung the kernels execute under the Pallas TPU interpreter, so the
absolute numbers are meaningless — the sweep is a *sanity* check that
the segmented driver scales linearly and a harness that produces real
numbers the moment it runs on a TPU slice.

Usage:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 python scripts/ring_sweep.py [--ranks 8]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=4)
    ap.add_argument("--sizes", type=str, default="")  # elements per member
    ap.add_argument("--tpu", action="store_true",
                    help="run on the real TPU platform (the claim can "
                         "hang when no chip is free — default is the "
                         "virtual-CPU rung)")
    args = ap.parse_args()

    import jax
    from accl_tpu.utils.compat import install as _compat_install
    _compat_install(jax)  # old-jax: alias jax.shard_map to the shim

    if not args.tpu:
        # NEVER probe jax.default_backend() before pinning: the axon
        # platform claim can hang forever (see .claude/skills/verify)
        jax.config.update("jax_platforms", "cpu")
    if not args.sizes:
        # the interpreter is ~10^4 x slower than hardware: keep the CPU
        # rung's sweep tiny; the TPU sweep covers the BASELINE.md range
        args.sizes = ("4096,65536,1048576,16777216" if args.tpu
                      else "1024,4096,16384")
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from accl_tpu.ops.ring import ring_all_reduce_segmented
    from accl_tpu.parallel.mesh import make_mesh

    Pn = args.ranks
    interp = jax.default_backend() != "tpu"
    mesh = make_mesh(dp=Pn)

    print("impl,elements,bytes,seconds,busbw_GBps")
    for n in (int(s) for s in args.sizes.split(",")):
        x = jax.device_put(
            np.random.default_rng(0).standard_normal((Pn, n)).astype(np.float32),
            NamedSharding(mesh, P("dp", None)))

        ring = jax.jit(jax.shard_map(
            lambda xb: ring_all_reduce_segmented(
                xb[0], "dp", interpret=interp)[None],
            mesh=mesh, in_specs=P("dp", None), out_specs=P("dp", None),
            check_vma=False))
        xla = jax.jit(jax.shard_map(
            lambda xb: jax.lax.psum(xb, "dp"),
            mesh=mesh, in_specs=P("dp", None), out_specs=P("dp", None)))

        for name, fn in (("ring", ring), ("xla_psum", xla)):
            try:
                jax.block_until_ready(fn(x))  # compile
                t0 = time.perf_counter()
                iters = 3 if not interp else 1
                for _ in range(iters):
                    jax.block_until_ready(fn(x))
                dt = (time.perf_counter() - t0) / iters
            except Exception as e:  # pragma: no cover
                print(f"{name},{n},{n * 4},ERROR,{type(e).__name__}: {e}",
                      file=sys.stderr)
                continue
            busbw = 2 * (Pn - 1) / Pn * n * 4 / dt / 1e9
            print(f"{name},{n},{n * 4},{dt:.6f},{busbw:.3f}")


if __name__ == "__main__":
    main()
