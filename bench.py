"""Benchmark of record — runs on real TPU hardware (one chip).

Measures the sustained throughput of the on-path reduction arithmetic
lane (accl_tpu.ops.reduce_ops, the reference reduce_ops plugin's role)
on large fp32 buffers.  This is the directly comparable single-device
anchor in BASELINE.md: the reference CCLO's internal datapath moves
64 B/cycle @ 250 MHz = 16 GB/s through its reduction unit; the TPU lane
streams both operands + result through HBM, so the metric is effective
reduction bandwidth = 3 x bytes / time.

Robustness contract (this file's one job is to ALWAYS land a number):
- the TPU ("axon") backend claim can hang forever or die with
  UNAVAILABLE when no chip is free, and the sitecustomize re-pins the
  platform so ``import jax`` itself can block — therefore ALL
  measurement happens in worker subprocesses with hard timeouts;
- the TPU attempt is retried (claim contention is transient);
- on failure it falls back to a clearly-labeled CPU measurement, and
  if even jax-on-CPU is broken, to a numpy measurement — the process
  exits 0 with exactly one JSON line on stdout in every case;
- diagnostics go to stderr only.

Methodology notes (important on remote-tunneled devices, where
`block_until_ready` can return at enqueue-ack rather than completion):
- iterations are CHAINED (out feeds the next call) so no caching or
  cross-call elision is possible;
- completion is forced by a scalar device->host readback, which cannot
  resolve before the producing op finishes;
- the readback round-trip cost is measured separately and subtracted;
- the reported value is the median of several trials.

vs_baseline = throughput / 16 GB/s (reference CCLO datapath ceiling,
BASELINE.md "CCLO internal datapath").

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N, ...}
"""
from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import time

BASELINE_GBPS = 16.0  # reference CCLO datapath (BASELINE.md)

# Wall-clock budgets (seconds).  The TPU claim itself can eat minutes;
# two attempts bound the total below typical driver patience.
TPU_ATTEMPT_TIMEOUTS = (
    int(os.environ.get("ACCL_BENCH_TPU_TIMEOUT_S", "420")),
    180,
)
CPU_TIMEOUT_S = 420


# ---------------------------------------------------------------------------
# worker: the actual measurement, run inside a subprocess
# ---------------------------------------------------------------------------

def _measure(platform: str) -> dict:
    import jax

    if platform == "cpu":
        # the axon sitecustomize re-pins the platform at interpreter
        # start; the runtime config update is what actually frees us
        # from the TPU claim (see tests/conftest.py)
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    t0 = time.perf_counter()
    backend = jax.default_backend()
    print(f"[bench worker] backend={backend} init took "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)
    on_tpu = backend not in ("cpu",)

    # 64 Mi elements = 256 MB per operand on TPU; small on CPU fallback
    n = (64 << 20) if on_tpu else (1 << 20)

    from accl_tpu.ops.reduce_ops import pallas_add

    a = jax.random.normal(jax.random.PRNGKey(0), (n,), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(1), (n,), jnp.float32)

    interpret = not on_tpu

    def run(x):
        return pallas_add(x, b, interpret=interpret)

    probe = jax.jit(lambda x: x[-1])

    # warmup / compile (both the kernel and the sync probe)
    out = run(a)
    float(probe(out))

    # measure the sync round-trip alone so it can be subtracted
    syncs = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(probe(a))
        syncs.append(time.perf_counter() - t0)
    sync_s = statistics.median(syncs)

    iters = 30 if on_tpu else 3
    trials = 3
    vals = []
    for _ in range(trials):
        out = a
        t0 = time.perf_counter()
        for _ in range(iters):
            out = run(out)
        float(probe(out))  # true completion barrier
        elapsed = time.perf_counter() - t0
        # RTT jitter can push elapsed below the pre-measured sync median;
        # fall back to the unsubtracted time rather than go negative
        net = elapsed - sync_s if elapsed > sync_s else elapsed
        vals.append(net / iters)
    dt = statistics.median(vals)

    nbytes = 3 * n * 4  # read a, read b, write out
    gbps = nbytes / dt / 1e9

    result = {
        "metric": "on-path reduction lane sustained throughput (fp32 sum, "
                  + ("TPU" if on_tpu else "CPU-interpret fallback") + ")",
        "value": round(gbps, 2),
        "unit": "GB/s",
        "vs_baseline": round(gbps / BASELINE_GBPS, 2),
        "platform": backend,
    }
    if on_tpu:
        result["detail"] = _secondary_kernels(jax, jnp, probe)
    return result


def _secondary_kernels(jax, jnp, probe) -> dict:
    """Compiled-on-TPU runs of the flash-attention and compression
    kernels (the round-1 gap: Pallas kernels had only ever executed
    under the CPU interpreter).  Best-effort — failures are recorded,
    not fatal."""
    detail: dict = {}
    try:
        from accl_tpu.ops.flash import flash_attention
        B, T, H, D = 1, 1024, 4, 64
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(2), 3)
        q = jax.random.normal(k1, (B, T, H, D), jnp.float32)
        k = jax.random.normal(k2, (B, T, H, D), jnp.float32)
        v = jax.random.normal(k3, (B, T, H, D), jnp.float32)
        o = flash_attention(q, k, v, causal=True, block_q=128, block_k=128,
                            interpret=False)
        float(probe(o.reshape(-1)))
        t0 = time.perf_counter()
        o = flash_attention(q, k, v, causal=True, block_q=128, block_k=128,
                            interpret=False)
        float(probe(o.reshape(-1)))
        # causal: ~half the 4*B*H*T^2*D matmul flops
        flops = 2 * B * H * T * T * D * 2 / 2
        detail["flash_attention_tflops"] = round(
            flops / (time.perf_counter() - t0) / 1e12, 3)
    except Exception as e:  # noqa: BLE001 — best-effort detail metric
        detail["flash_attention_error"] = f"{type(e).__name__}: {e}"
    try:
        from accl_tpu.ops.compression import compress_cast
        x = jax.random.normal(jax.random.PRNGKey(3), (16 << 20,), jnp.float32)
        y = compress_cast(x, jnp.bfloat16, interpret=False)
        float(probe(y.astype(jnp.float32)))
        t0 = time.perf_counter()
        y = compress_cast(x, jnp.bfloat16, interpret=False)
        float(probe(y.astype(jnp.float32)))
        nbytes = x.size * 4 + x.size * 2
        detail["compression_gbps"] = round(
            nbytes / (time.perf_counter() - t0) / 1e9, 2)
    except Exception as e:  # noqa: BLE001 — best-effort detail metric
        detail["compression_error"] = f"{type(e).__name__}: {e}"
    return detail


def _numpy_last_resort() -> dict:
    """If jax itself is broken, still land a labeled number."""
    import numpy as np
    n = 1 << 22
    a = np.random.default_rng(0).standard_normal(n).astype(np.float32)
    b = np.random.default_rng(1).standard_normal(n).astype(np.float32)
    a + b  # warm caches / allocator
    t0 = time.perf_counter()
    iters = 10
    out = a
    for _ in range(iters):
        out = out + b
    dt = (time.perf_counter() - t0) / iters
    gbps = 3 * n * 4 / dt / 1e9
    return {
        "metric": "on-path reduction lane sustained throughput "
                  "(fp32 sum, numpy last-resort fallback — jax unavailable)",
        "value": round(gbps, 2),
        "unit": "GB/s",
        "vs_baseline": round(gbps / BASELINE_GBPS, 2),
        "platform": "numpy",
    }


# ---------------------------------------------------------------------------
# orchestrator: subprocess + timeout around every jax touch
# ---------------------------------------------------------------------------

def _run_worker(platform: str, timeout_s: int) -> dict | None:
    """Run `python bench.py --worker <platform>` and parse its last
    stdout line as JSON.  Returns None on timeout / crash / bad JSON."""
    cmd = [sys.executable, os.path.abspath(__file__), "--worker", platform]
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired:
        print(f"[bench] {platform} worker timed out after {timeout_s}s "
              "(TPU claim hung?)", file=sys.stderr)
        return None
    dt = time.perf_counter() - t0
    tail = "\n".join(proc.stderr.strip().splitlines()[-8:])
    if tail:
        print(f"[bench] {platform} worker stderr tail:\n{tail}",
              file=sys.stderr)
    if proc.returncode != 0:
        print(f"[bench] {platform} worker exited rc={proc.returncode} "
              f"after {dt:.0f}s", file=sys.stderr)
        return None
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    print(f"[bench] {platform} worker produced no JSON line; stdout was: "
          f"{proc.stdout[-500:]!r}", file=sys.stderr)
    return None


def main() -> None:
    if len(sys.argv) >= 3 and sys.argv[1] == "--worker":
        print(json.dumps(_measure(sys.argv[2])))
        return

    result = None
    for i, budget in enumerate(TPU_ATTEMPT_TIMEOUTS):
        print(f"[bench] TPU attempt {i + 1}/{len(TPU_ATTEMPT_TIMEOUTS)} "
              f"(budget {budget}s)", file=sys.stderr)
        result = _run_worker("tpu", budget)
        if result is not None:
            break
    if result is None:
        print("[bench] TPU unavailable — falling back to CPU "
              "(interpret-mode Pallas; NOT a hardware number)",
              file=sys.stderr)
        result = _run_worker("cpu", CPU_TIMEOUT_S)
    if result is None:
        print("[bench] jax CPU worker failed too — numpy last resort",
              file=sys.stderr)
        try:
            result = _numpy_last_resort()
        except Exception as e:  # noqa: BLE001 — must still print a line
            result = {
                "metric": "benchmark could not run (all fallbacks failed)",
                "value": 0.0,
                "unit": "GB/s",
                "vs_baseline": 0.0,
                "error": f"{type(e).__name__}: {e}",
            }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
