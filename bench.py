"""Benchmark of record — runs on real TPU hardware (one chip).

Measures the sustained throughput of the on-path reduction arithmetic
lane (accl_tpu.ops.reduce_ops, the reference reduce_ops plugin's role)
on large fp32 buffers.  This is the directly comparable single-device
anchor in BASELINE.md: the reference CCLO's internal datapath moves
64 B/cycle @ 250 MHz = 16 GB/s through its reduction unit; the TPU lane
streams both operands + result through HBM, so the metric is effective
reduction bandwidth = 3 x bytes / time.

Robustness contract (this file's one job is to ALWAYS land a number):
- the TPU ("axon") backend claim can hang forever or die with
  UNAVAILABLE when no chip is free, and the sitecustomize re-pins the
  platform so ``import jax`` itself can block — therefore ALL
  measurement happens in worker subprocesses with hard timeouts;
- the TPU attempt is retried (claim contention is transient);
- on failure it falls back to a clearly-labeled CPU measurement, and
  if even jax-on-CPU is broken, to a numpy measurement — the process
  exits 0 with exactly one JSON line on stdout in every case;
- diagnostics go to stderr only.

Methodology notes (important on remote-tunneled devices, where
`block_until_ready` can return at enqueue-ack rather than completion):
- iterations are CHAINED INSIDE ONE COMPILED PROGRAM (lax.fori_loop; the
  carry feeds forward so no elision is possible) — one dispatch per
  trial regardless of iteration count.  Host-side per-call chaining is
  wrong on a tunneled device in BOTH directions: with few iterations
  the device time is smaller than the RTT being subtracted and the
  residue is noise (observed: a 12 B/elem cast pair "measuring" 3x the
  chip's HBM roofline), with many the dispatch stream is the bottleneck
  and the kernel is underestimated (round 2's 0.007-TFLOPs flash);
- completion is forced by a scalar device->host readback, which cannot
  resolve before the producing loop finishes;
- the readback round-trip cost is measured separately and subtracted
  (with in-jit chaining the iteration count can be made large enough
  that device time dominates the RTT jitter);
- the reported value is the best of several interleaved trials (the chip
  is shared; the fastest window estimates hardware capability, and
  ratioed quantities are measured A/B-interleaved in shared windows).

vs_baseline = throughput / 16 GB/s (reference CCLO datapath ceiling,
BASELINE.md "CCLO internal datapath").

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N, ...}
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE_GBPS = 16.0  # reference CCLO datapath (BASELINE.md)

# last successful real-TPU measurement, persisted so a blocked chip
# claim at run time degrades to an honest, clearly-labeled stale TPU
# number instead of a meaningless CPU-interpret rate
LAST_TPU_JSON = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench", "results", "last_tpu_bench.json")

# per-STAGE ledgers: the worker banks each completed measurement stage
# as it lands (atomic rewrite), so a chip claim that hangs midway
# through a later stage still leaves this run's earlier stages fresh —
# r4 lost its whole record to exactly this (three timed-out attempts,
# stale replay).  The orchestrator assembles a partial-but-fresh result
# from the ledger when every full attempt dies, and a retry attempt in
# the same run skips stages the previous attempt already banked.
# ONE FILE PER RUN ID: a shared file would let any invocation with a
# different id (a stray `python bench.py` beside the harvest loop)
# wipe hours of banked hardware stages wholesale.
_LEDGER_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "bench", "results")


def _ledger_path(run_id: str) -> str:
    safe = "".join(c if (c.isalnum() or c in "-_") else "_"
                   for c in run_id) or "default"
    return os.path.join(_LEDGER_DIR, f"bench_stages.{safe}.json")


def _load_ledger(run_id: str) -> dict:
    try:
        with open(_ledger_path(run_id)) as f:
            led = json.load(f)
        if led.get("run_id") == run_id:
            return led
    except (OSError, ValueError):
        pass
    return {"run_id": run_id, "stages": {}}


def _bank_stage(led: dict, name: str, data: dict) -> None:
    led["stages"][name] = data
    led["banked_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    path = _ledger_path(led.get("run_id", ""))
    try:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(led, f)
        os.replace(tmp, path)
        print(f"[bench worker] banked stage {name!r}", file=sys.stderr,
              flush=True)
    except OSError as e:  # never sink a measurement over disk trouble
        print(f"[bench worker] could not bank stage {name!r}: {e}",
              file=sys.stderr)


#: stages every complete TPU record carries, in execution order —
#: headline first (it is the metric of record), then the detail lanes
ALL_STAGES = ("headline", "flash", "flash_variants", "compression",
              "selfring", "tpu_tests")

#: detail keys the round has formally RETRACTED in docs/performance.md
#: (the r4 fwd+bwd composite timed a DCE'd program — only the dq kernel
#: ran).  A stale replay predates the in-bench three-kernel consistency
#: gate, so these keys are stripped from it and listed under
#: "retracted": a fallback record must never re-assert a figure the
#: docs have withdrawn (fresh measurements are unaffected — the gate
#: already refuses to emit an unverified composite).
RETRACTED_DETAIL_KEYS = (
    "flash_d128_fwdbwd_tflops",
    "flash_d128_fwdbwd_mxu_frac",
    "flash_d128_bwdonly_mxu_frac",
)


def _scrub_retracted(result: dict) -> dict:
    """Strip retracted figures from a replayed record, marking what was
    stripped so consumers can tell silence from omission."""
    detail = result.get("detail")
    if not isinstance(detail, dict):
        return result
    hit = [k for k in RETRACTED_DETAIL_KEYS if k in detail]
    for k in hit:
        del detail[k]
    if hit:
        result["retracted"] = sorted(
            set(result.get("retracted", [])) | set(hit))
    return result


def _assemble(stages: dict) -> dict | None:
    """Build the result line from banked stage fragments.  Returns None
    without a headline stage (there is no metric to report)."""
    head = stages.get("headline")
    if not head:
        return None
    gbps = head["gbps"]
    result = {
        "metric": "on-path reduction lane sustained throughput (fp32 sum, "
                  "TPU)",
        "value": round(gbps, 2),
        "unit": "GB/s",
        "vs_baseline": round(gbps / BASELINE_GBPS, 2),
        "platform": head.get("platform", "tpu"),
    }
    detail = {k: v for k, v in head.items()
              if k not in ("gbps", "platform")}
    for name in ALL_STAGES[1:]:
        if name in stages:
            detail.update(stages[name])
    missing = [n for n in ALL_STAGES if n not in stages]
    if missing:
        result["stages_missing"] = missing
    result["detail"] = detail
    return result

# Wall-clock budgets (seconds).  The TPU claim itself can eat minutes
# and a cold remote-compile cache pays ~10 program compiles at 20-40 s
# each; the attempts bound the total below typical driver patience
# (compiles cached server-side survive into later attempts).  THREE
# attempts instead of two: the shared chip's claim can stay blocked for
# hours with brief free windows, and more, shorter retries catch a
# window the old two-attempt ladder missed.
TPU_ATTEMPT_TIMEOUTS = (
    int(os.environ.get("ACCL_BENCH_TPU_TIMEOUT_S", "480")),
    180,
    150,
)
CPU_TIMEOUT_S = 420


# ---------------------------------------------------------------------------
# worker: the actual measurement, run inside a subprocess
# ---------------------------------------------------------------------------

def _measure(platform: str) -> dict:
    # claim fail-fast (r16): libtpu metadata retries can wedge the
    # claim for the worker's WHOLE budget (the kill-at-60s ritual
    # ROADMAP documented); a watchdog aborts the claim after
    # ACCL_TPU_CLAIM_TIMEOUT_S (default 60) with a clear message so
    # the orchestrator retries / falls to the CPU rung immediately
    # instead of burning the full attempt timeout.
    claim_guard = None
    if platform == "tpu":
        from accl_tpu.bench.sweep import claim_watchdog

        claim_guard = claim_watchdog(
            "bench worker", advice="the orchestrator will retry and "
            "fall back to the CPU rung")

    import jax

    from accl_tpu.utils.compile_cache import enable as _enable_cache
    _enable_cache()  # chip windows go to measurement, not recompiles

    if platform == "cpu":
        # the axon sitecustomize re-pins the platform at interpreter
        # start; the runtime config update is what actually frees us
        # from the TPU claim (see tests/conftest.py)
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    t0 = time.perf_counter()
    backend = jax.default_backend()
    if claim_guard is not None:
        claim_guard.cancel()  # claim landed: measurement may run long
    print(f"[bench worker] backend={backend} init took "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)
    on_tpu = backend not in ("cpu",)

    # 64 Mi elements = 256 MB per operand on TPU; small on CPU fallback.
    # Operands are laid out 2D (rows, 128) — the kernels' native tile
    # shape — because a 1D loop carry has a different physical layout
    # (T(1024) vs T(8,128)) and XLA then inserts a full-array relayout
    # copy per chained iteration in front of the pallas call (observed:
    # +2 HBM streams, a phantom 0.6x on the pallas side of the A/B).
    n = (64 << 20) if on_tpu else (1 << 20)

    from accl_tpu.ops.reduce_ops import pallas_add

    a = jax.random.normal(jax.random.PRNGKey(0), (n // 128, 128),
                          jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(1), (n // 128, 128),
                          jnp.float32)

    interpret = not on_tpu

    # shared chained-timing harness (in-jit fori_loop chains, sync RTT
    # subtraction, best-of-interleaved-windows; see its module docstring
    # for the full methodology rationale)
    from accl_tpu.bench.timing import make_harness

    _probe, timed_chain, timed_chain_ab, _sync_s = make_harness(jax, jnp)

    if not on_tpu:
        # CPU fallback: headline only, no ledger (nothing hardware-fresh
        # to bank), interpret-mode kernels
        def run(x, bb):
            return pallas_add(x, bb, interpret=interpret,
                              block_rows=512, donate=True)
        dt = timed_chain(run, a, 3, trials=3, consts=(b,))
        gbps = 3 * n * 4 / dt / 1e9
        return {
            "metric": "on-path reduction lane sustained throughput "
                      "(fp32 sum, CPU-interpret fallback)",
            "value": round(gbps, 2),
            "unit": "GB/s",
            "vs_baseline": round(gbps / BASELINE_GBPS, 2),
            "platform": backend,
        }

    # a worker launched directly (no orchestrator env) must NOT resume
    # a previous run's ledger as if freshly measured — give it a unique
    # id so it always starts clean
    run_id = (os.environ.get("ACCL_BENCH_RUN_ID")
              or f"direct-{os.getpid()}-{int(time.time())}")
    led = _load_ledger(run_id)
    stages = led["stages"]

    if "headline" not in stages:
        # autotune the VMEM tile depth: dispatch-bound at small blocks,
        # pipeline-starved at huge ones; best of a short ladder
        best_dt, best_rows = None, 0
        for rows in (512, 2048):
            def fn(x, bb, r=rows):
                return pallas_add(x, bb, interpret=False,
                                  block_rows=r, donate=True)
            dt_r = timed_chain(fn, a, 8, trials=2, consts=(b,))
            if best_dt is None or dt_r < best_dt:
                best_dt, best_rows = dt_r, rows
        print(f"[bench worker] pallas_add autotune -> "
              f"block_rows={best_rows}", file=sys.stderr)
        def run(x, bb):
            return pallas_add(x, bb, interpret=False,
                              block_rows=best_rows, donate=True)
        nbytes = 3 * n * 4  # read a, read b, write out
        # headline + roofline measured interleaved: the same 3-stream
        # add through plain XLA is the practical HBM ceiling on this
        # chip, so the headline number carries its own context
        def xla_add(x, bb):
            return x + bb
        dts = timed_chain_ab({"pallas": run, "xla": xla_add}, a, 30,
                             consts=(b,))
        _bank_stage(led, "headline", {
            "gbps": 3 * n * 4 / dts["pallas"] / 1e9,
            "platform": backend,
            "xla_add_gbps": round(nbytes / dts["xla"] / 1e9, 2),
            "roofline_frac": round(dts["xla"] / dts["pallas"], 3),
            "pallas_block_rows": best_rows,
        })
        # provisional line after every stage: the orchestrator takes the
        # LAST JSON line, so a kill during any later stage still lands
        # everything banked so far
        print(json.dumps(_assemble(stages)), flush=True)

    if "flash" not in stages:
        _bank_stage(led, "flash",
                    _flash_stage(jax, jnp, timed_chain))
        print(json.dumps(_assemble(stages)), flush=True)

    if "flash_variants" not in stages:
        _bank_stage(led, "flash_variants",
                    _flash_variants_stage(jax, jnp, timed_chain))
        print(json.dumps(_assemble(stages)), flush=True)

    if "compression" not in stages:
        _bank_stage(led, "compression",
                    _compression_stage(jax, jnp, timed_chain_ab))
        print(json.dumps(_assemble(stages)), flush=True)

    if "selfring" not in stages:
        _bank_stage(led, "selfring", _selfring_stage(jax, jnp, timed_chain))
        print(json.dumps(_assemble(stages)), flush=True)

    if "tpu_tests" not in stages:
        _bank_stage(led, "tpu_tests",
                    {"tpu_only_tests": _run_tpu_only_tests()})

    return _assemble(stages)


def _run_tpu_only_tests() -> str:
    """Execute the single-device-runnable Pallas kernel tests COMPILED
    on the claimed chip: the TPU-gated ones (stochastic rounding needs
    the hardware PRNG) plus the reduce/compression/matmul lanes and the
    virtual self-ring collectives (real semaphore + remote-DMA code).
    The multi-device ring tests are excluded — they need a >=2-chip
    mesh.  ACCL_TEST_ON_TPU=1 makes conftest.py keep the live platform
    instead of pinning the virtual-CPU mesh.  Best-effort: the result
    string is recorded in the bench detail for the round record."""
    import os

    os.environ["ACCL_TEST_ON_TPU"] = "1"
    try:
        import pytest

        class _Count:
            passed = 0
            skipped = 0

            def pytest_runtest_logreport(self, report):
                if report.when == "call" and report.passed:
                    _Count.passed += 1
                if report.skipped:
                    _Count.skipped += 1

        rc = pytest.main([
            "tests/test_pallas_ops.py", "-q", "--no-header", "-p",
            "no:cacheprovider", "-k", "not test_ring",
        ], plugins=[_Count()])
        # "all skipped" must NOT read as success — the whole point is
        # that these tests execute somewhere
        if rc == 0 and _Count.passed > 0:
            return f"passed:{_Count.passed}"
        return (f"pytest_exit_{int(rc)} passed:{_Count.passed} "
                f"skipped:{_Count.skipped}")
    except Exception as e:  # noqa: BLE001 — never sink the bench
        return f"{type(e).__name__}: {e}"


def _flash_operands(jax, jnp):
    """Shared operand/context pack for the two flash stages (split so a
    short claim window can bank the core record before the variant
    sweep's extra compiles; each stage re-measures the matmul peak
    interleaved in its OWN windows — only same-window ratios mean
    anything on the shared chip)."""
    B, T, H, D = 4, 2048, 8, 64
    H2, D2 = 4, 128
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(k1, (B, T, H, D), jnp.float32)
    k = jax.random.normal(k2, (B, T, H, D), jnp.float32)
    v = jax.random.normal(k3, (B, T, H, D), jnp.float32)
    q2 = jax.random.normal(k1, (B, T, H2, D2), jnp.float32)
    k2_ = jax.random.normal(k2, (B, T, H2, D2), jnp.float32)
    v2 = jax.random.normal(k3, (B, T, H2, D2), jnp.float32)
    # head-packed operands (the zero-transpose entries; transposes
    # measured ~free on this chip, so numbers stay comparable)
    def pk(x, h, d):
        return x.transpose(0, 2, 1, 3).reshape(B * h, T, d)
    ops = {
        "B": B, "T": T, "H": H, "D": D, "H2": H2, "D2": D2,
        "q": q, "k": k, "v": v, "q2": q2, "k2": k2_, "v2": v2,
        "q2p": pk(q2, H2, D2), "k2p": pk(k2_, H2, D2),
        "v2p": pk(v2, H2, D2),
        "q1p": pk(q, H, D), "k1p": pk(k, H, D), "v1p": pk(v, H, D),
        # causal: ~half of the 4*B*H*T^2*D matmul flops
        "flops": 4 * B * H * T * T * D / 2,
        "mm_n": 4096,
    }
    ka, kb = jax.random.split(jax.random.PRNGKey(7))
    ops["ma"] = jax.random.normal(ka, (4096, 4096), jnp.bfloat16)
    ops["mb"] = jax.random.normal(kb, (4096, 4096), jnp.bfloat16)
    ops["mm"] = lambda x, y: (x @ y).astype(jnp.bfloat16)
    return ops


def _flash_stage(jax, jnp, timed_chain) -> dict:
    """CORE flash record: the historical BTHD entries (d64 + d128), the
    interleaved matmul peak, the verified fwd+bwd composite, and the
    splash-attention external anchor — measured with the SAME
    chained-iteration + sync-subtraction methodology as the headline
    metric (round 2 recorded single-call dispatch latencies here,
    which looked like evidence and wasn't).  The schedule-candidate
    sweep lives in _flash_variants_stage so a short window still banks
    this record.  Best-effort — failures are recorded, not fatal."""
    detail: dict = {}
    try:
        from accl_tpu.ops.flash import flash_attention

        o = _flash_operands(jax, jnp)
        B, T = o["B"], o["T"]
        flops, mm_n = o["flops"], o["mm_n"]

        def fa(x, kk, vv):  # chained: output feeds the next queries
            return flash_attention(x, kk, vv, causal=True, interpret=False)

        # EXTERNAL ANCHOR: JAX's own splash-attention kernel on the
        # same packed operands, same windows — the practical same-shape
        # ceiling this chip generation offers.  [B*H2, T, D2] is
        # exactly splash's single-device MHA layout (heads, seq, hd)
        # with a per-head causal mask.
        try:
            from jax.experimental.pallas.ops.tpu import splash_attention as _sp
            _mask = _sp.splash_attention_mask.MultiHeadMask(
                [_sp.splash_attention_mask.CausalMask((T, T))]
                * (B * o["H2"]))
            _splash = _sp.make_splash_mha_single_device(_mask)

            def splash_fwd(x, kk, vv):
                return _splash(x, kk, vv)

            def splash_bwd(x, kk, vv):
                g = jax.grad(lambda a, b, c: jnp.sum(
                    _splash(a, b, c)), argnums=(0, 1, 2))(x, kk, vv)
                return g[0] + g[1] + g[2]
        except Exception as ve:  # noqa: BLE001 — anchor is best-effort
            splash_fwd = splash_bwd = None
            detail["splash_anchor_error"] = type(ve).__name__

        # backward pass (the custom-VJP Pallas kernels): grad over ALL
        # THREE operands, with dq+dk+dv summed into the chain carry so
        # every output is live.  r4 timed argnums=(0,) and jaxpr-level
        # DCE deleted the dkv pallas call whose outputs were discarded —
        # the recorded 0.81 "composite" ran 5 of the 9 matmul-units it
        # credited.  The lowered program is now checked to contain all
        # three pallas calls (fwd rerun + dq + dkv) before the number
        # can be reported at all.
        from accl_tpu.ops.flash import flash_attention_packed as _fap

        def fa_bwd(x, kk, vv):
            g = jax.grad(lambda a, b, c: jnp.sum(
                _fap(a, b, c, causal=True, kernel="resident")
                .astype(jnp.float32)), argnums=(0, 1, 2))(x, kk, vv)
            return g[0] + g[1] + g[2]

        try:
            n_pallas = jax.jit(fa_bwd).lower(
                o["q2p"], o["k2p"], o["v2p"]).as_text().count(
                    "tpu_custom_call")
        except Exception:  # noqa: BLE001 — lowering text is best-effort
            n_pallas = -1
        detail["flash_fwdbwd_pallas_calls"] = n_pallas

        # forward reference for the fwd+bwd consistency gate: the SAME
        # packed resident entry fa_bwd re-runs (the BTHD wrapper would
        # measure a different program — transposes + auto schedule —
        # and skew the implied backward-only residual either way)
        from accl_tpu.bench.flash_sweep import make_variant

        fa_res = make_variant(256, 512)

        # interleaved best-of-rounds: contention windows on this shared
        # chip last MINUTES and can depress identical kernels
        # several-fold, so the best-window estimator needs enough
        # rounds to straddle a window boundary — 12 rounds of this
        # stage's 8 lanes keeps the stage's wall span comparable to the
        # pre-split loop even though the variant lanes moved out.
        # Iteration counts put >= ~10 ms of device work per dispatch so
        # RTT jitter amortizes away.
        best_fa = best_f2 = best_mm = best_bwd = best_res = None
        best_sp = best_sp_bwd = None
        dead: set = set()
        for _ in range(12):
            d1 = timed_chain(fa, o["q"], iters=64, trials=1,
                             consts=(o["k"], o["v"]))
            d2 = timed_chain(o["mm"], o["ma"], iters=48, trials=1,
                             consts=(o["mb"],))
            d3 = timed_chain(fa, o["q2"], iters=64, trials=1,
                             consts=(o["k2"], o["v2"]))
            best_fa = d1 if best_fa is None else min(best_fa, d1)
            best_mm = d2 if best_mm is None else min(best_mm, d2)
            best_f2 = d3 if best_f2 is None else min(best_f2, d3)
            if "res" not in dead:
                try:
                    dr = timed_chain(fa_res, o["q2p"], iters=64, trials=1,
                                     consts=(o["k2p"], o["v2p"]))
                    best_res = (dr if best_res is None
                                else min(best_res, dr))
                except Exception as ve:  # noqa: BLE001
                    dead.add("res")
                    best_res = None
                    detail["flash_d128_fwdref_error"] = type(ve).__name__
            if "bwd" not in dead:
                try:
                    dv = timed_chain(fa_bwd, o["q2p"], iters=24, trials=1,
                                     consts=(o["k2p"], o["v2p"]))
                    best_bwd = (dv if best_bwd is None
                                else min(best_bwd, dv))
                except Exception as ve:  # noqa: BLE001 — the error
                    # REPLACES the number (a half-measured best would
                    # read as trustworthy)
                    dead.add("bwd")
                    best_bwd = None
                    detail["flash_d128_fwdbwd_error"] = type(ve).__name__
            if splash_fwd is not None and "splash" not in dead:
                try:
                    dv = timed_chain(splash_fwd, o["q2p"], iters=64,
                                     trials=1, consts=(o["k2p"], o["v2p"]))
                    best_sp = dv if best_sp is None else min(best_sp, dv)
                except Exception as ve:  # noqa: BLE001
                    dead.add("splash")
                    best_sp = None
                    detail["splash_anchor_error"] = type(ve).__name__
            if (splash_bwd is not None and "splash" not in dead
                    and "splash_bwd" not in dead):
                # separate lane: a backward OOM must not erase the
                # already-valid forward ceiling number
                try:
                    db = timed_chain(splash_bwd, o["q2p"], iters=24,
                                     trials=1, consts=(o["k2p"], o["v2p"]))
                    best_sp_bwd = (db if best_sp_bwd is None
                                   else min(best_sp_bwd, db))
                except Exception as ve:  # noqa: BLE001
                    dead.add("splash_bwd")
                    best_sp_bwd = None
                    detail["splash_bwd_anchor_error"] = type(ve).__name__

        detail["flash_attention_tflops"] = round(flops / best_fa / 1e12, 3)
        mm_peak = 2 * mm_n**3 / best_mm
        detail["matmul_bf16_tflops"] = round(mm_peak / 1e12, 2)
        detail["flash_mxu_frac"] = round((flops / best_fa) / mm_peak, 3)
        # metric of record: the SAME BTHD entry as previous rounds
        # (VERDICT's bar is against the existing methodology)
        detail["flash_d128_tflops"] = round(flops / best_f2 / 1e12, 3)
        detail["flash_d128_mxu_frac"] = round(
            (flops / best_f2) / mm_peak, 3)
        if best_res is not None:
            # the gate's forward reference, reported for transparency
            detail["flash_d128_fwdref_tflops"] = round(
                flops / best_res / 1e12, 3)
        if best_bwd is not None:
            # the timed chain runs forward + backward per iteration
            # (jax.grad re-runs the custom-VJP forward): 2 fwd matmuls
            # + 7 bwd matmuls per causal cell (dq kernel: S-recompute,
            # dP, dQ; dkv kernel: S-recompute, dV, dP, dK) = 4.5x the
            # fwd flops.  Gated on the lowered program actually
            # containing all three pallas calls, and on physical
            # consistency with the same-window standalone forward: the
            # implied backward-only rate must not exceed the matmul
            # peak (r4's DCE'd number failed exactly this test).
            bwd_flops = 4.5 * flops
            composite_frac = (bwd_flops / best_bwd) / mm_peak
            if best_res is not None and best_bwd > best_res:
                implied_bwd_frac = ((3.5 * flops)
                                    / (best_bwd - best_res) / mm_peak)
            else:
                implied_bwd_frac = None
            # FAIL CLOSED: a lowering-text failure (n_pallas == -1)
            # means the three-kernel check could not run, and the docs
            # promise the composite is only ever reported verified
            consistent = (n_pallas >= 3 and composite_frac <= 1.0
                          and (implied_bwd_frac is None
                               or implied_bwd_frac <= 1.05))
            if consistent:
                detail["flash_d128_fwdbwd_tflops"] = round(
                    bwd_flops / best_bwd / 1e12, 3)
                detail["flash_d128_fwdbwd_mxu_frac"] = round(
                    composite_frac, 3)
                if implied_bwd_frac is not None:
                    detail["flash_d128_bwdonly_mxu_frac"] = round(
                        implied_bwd_frac, 3)
            else:
                detail["flash_d128_fwdbwd_inconsistent"] = {
                    "pallas_calls": n_pallas,
                    "composite_frac": round(composite_frac, 3),
                    "implied_bwd_frac": (round(implied_bwd_frac, 3)
                                         if implied_bwd_frac else None),
                }
        if best_sp is not None:
            # the anchor under the identical flop credit: either our
            # kernel matches/beats it, or its number IS the recorded
            # practical same-shape ceiling (r4 review item 3)
            detail["splash_anchor_tflops"] = round(
                flops / best_sp / 1e12, 3)
            detail["splash_anchor_mxu_frac"] = round(
                (flops / best_sp) / mm_peak, 3)
        if best_sp_bwd is not None:
            detail["splash_anchor_fwdbwd_tflops"] = round(
                4.5 * flops / best_sp_bwd / 1e12, 3)
            detail["splash_anchor_fwdbwd_mxu_frac"] = round(
                (4.5 * flops / best_sp_bwd) / mm_peak, 3)
    except Exception as e:  # noqa: BLE001 — best-effort detail metric
        detail["flash_attention_error"] = f"{type(e).__name__}: {e}"
    return detail


def _flash_variants_stage(jax, jnp, timed_chain) -> dict:
    """Schedule-candidate sweep on the live chip: the packed d128/d64
    families (incl. the r5 static-max pin) and the bf16-input lane,
    with their OWN interleaved matmul peak.  Candidate construction is
    shared with the live-chip tuner scripts so methodology fixes land
    once (flash_sweep docstring).  Candidate sets follow the
    honest-timing Pareto front of the r04 sweeps; rejected families
    (split folds, qt4, D=128 fused denominator, the skew schedule)
    stay in chip_session's larger sweep."""
    detail: dict = {}
    try:
        from accl_tpu.bench.flash_sweep import make_variant

        o = _flash_operands(jax, jnp)
        flops, mm_n = o["flops"], o["mm_n"]
        d128_variants = {
            "resident": make_variant(256, 512),
            "resident_bq512": make_variant(512, 512),
            "resident_bq512_qt2": make_variant(512, 512, qt=2),
            "resident_bq512_bk1024": make_variant(512, 1024),
            # r5 static-max pin: drops the max/alpha/clamp VPU passes
            # (the measured fold bottleneck) — a decomposition change,
            # not another block shape
            "resident_sm40": make_variant(256, 512, sm=40.0),
            "resident_bq512_sm40": make_variant(512, 512, sm=40.0),
        }
        d64_variants = {
            "resident": make_variant(256, 512),
            "resident_fd": make_variant(256, 512, fd=True),
            "resident_qt2_fd": make_variant(256, 512, qt=2, fd=True),
            # static pin + fused denom: no VPU reductions in the fold
            "resident_fd_sm40": make_variant(256, 512, fd=True, sm=40.0),
        }
        # bf16-input lane: the flagship TRAINS in bf16 activations
        # (models/transformer bf16 config), so the f32-input entries
        # pay a per-fold K/V cast and double HBM the real training
        # path never sees — this lane measures the kernel as the model
        # actually calls it (cast once, outside the timing)
        q2b, k2b, v2b = (x.astype(jnp.bfloat16)
                         for x in (o["q2p"], o["k2p"], o["v2p"]))
        fa_bf16 = make_variant(256, 512)

        best_mm = best_bf = None
        best_pk = {name: None for name in d128_variants}
        best_pk64 = {name: None for name in d64_variants}
        dead: set = set()
        for _ in range(10):
            d2 = timed_chain(o["mm"], o["ma"], iters=48, trials=1,
                             consts=(o["mb"],))
            best_mm = d2 if best_mm is None else min(best_mm, d2)
            if "bf16" not in dead:
                try:
                    db = timed_chain(fa_bf16, q2b, iters=64, trials=1,
                                     consts=(k2b, v2b))
                    best_bf = db if best_bf is None else min(best_bf, db)
                except Exception as ve:  # noqa: BLE001 — the error
                    # REPLACES the number
                    dead.add("bf16")
                    best_bf = None
                    detail["flash_d128_bf16_error"] = type(ve).__name__
            for name, vfn in d128_variants.items():
                if name in dead:
                    continue
                # a candidate schedule failing on this chip generation
                # must not take down the established metrics with it
                try:
                    dv = timed_chain(vfn, o["q2p"], iters=64, trials=1,
                                     consts=(o["k2p"], o["v2p"]))
                except Exception as ve:  # noqa: BLE001
                    dead.add(name)
                    best_pk[name] = f"{type(ve).__name__}"
                    continue
                prev = best_pk[name]
                best_pk[name] = dv if prev is None else min(prev, dv)
            for name, vfn in d64_variants.items():
                if ("d64:" + name) in dead:
                    continue
                try:
                    dv = timed_chain(vfn, o["q1p"], iters=64, trials=1,
                                     consts=(o["k1p"], o["v1p"]))
                except Exception as ve:  # noqa: BLE001
                    dead.add("d64:" + name)
                    best_pk64[name] = f"{type(ve).__name__}"
                    continue
                prev = best_pk64[name]
                best_pk64[name] = dv if prev is None else min(prev, dv)

        mm_peak = 2 * mm_n**3 / best_mm
        detail["variants_matmul_bf16_tflops"] = round(mm_peak / 1e12, 2)
        if best_bf is not None:
            detail["flash_d128_bf16_tflops"] = round(
                flops / best_bf / 1e12, 3)
            detail["flash_d128_bf16_mxu_frac"] = round(
                (flops / best_bf) / mm_peak, 3)
        live = {n: dt for n, dt in best_pk.items()
                if isinstance(dt, float)}
        if live:
            win = min(live, key=lambda n: live[n])
            detail["flash_d128_packed_tflops"] = round(
                flops / live[win] / 1e12, 3)
            detail["flash_d128_packed_mxu_frac"] = round(
                (flops / live[win]) / mm_peak, 3)
            detail["flash_d128_packed_schedule"] = win
        detail["flash_d128_packed_all"] = {
            n: (round(flops / dt / 1e12, 2) if isinstance(dt, float)
                else dt) for n, dt in best_pk.items()}
        live64 = {n: dt for n, dt in best_pk64.items()
                  if isinstance(dt, float)}
        if live64:
            win = min(live64, key=lambda n: live64[n])
            detail["flash_d64_packed_tflops"] = round(
                flops / live64[win] / 1e12, 3)
            detail["flash_d64_packed_mxu_frac"] = round(
                (flops / live64[win]) / mm_peak, 3)
            detail["flash_d64_packed_schedule"] = win
        detail["flash_d64_packed_all"] = {
            n: (round(flops / dt / 1e12, 2) if isinstance(dt, float)
                else dt) for n, dt in best_pk64.items()}
    except Exception as e:  # noqa: BLE001 — best-effort detail metric
        detail["flash_variants_error"] = f"{type(e).__name__}: {e}"
    return detail


def _compression_stage(jax, jnp, timed_chain_ab) -> dict:
    """Wire-compression roundtrip lane vs the same-window XLA cast pair
    (the practical ceiling for this access pattern)."""
    detail: dict = {}
    try:
        from accl_tpu.ops.compression import compress_cast
        # 256 MB fp32: larger than any on-chip scratch (observed: at
        # 64 MB XLA pins the whole chained cast loop in S(1) memory and
        # "measures" >100 TB/s — on-chip bandwidth, not the HBM-streaming
        # ceiling a wire-compression lane actually faces).  2D layout for
        # the same copy-free-carry reason as the headline operands.
        x = jax.random.normal(jax.random.PRNGKey(3), ((64 << 20) // 512, 512),
                              jnp.float32)

        from accl_tpu.ops.compression import decompress_cast

        import jax.lax as _lax

        def roundtrip(v):  # chained compress -> decompress
            return decompress_cast(compress_cast(v, jnp.bfloat16,
                                                 interpret=False),
                                   jnp.float32, interpret=False)

        # context measured INTERLEAVED: the same roundtrip as plain XLA
        # casts is the practical ceiling for this access pattern.
        # BOTH halves sit behind optimization_barriers: one barrier only
        # pins the bf16 intermediate, and across chained iterations the
        # simplifier then folds convert(convert(x)) to x, eliding every
        # roundtrip but the first (observed as an impossible 7.4 TB/s);
        # the second barrier pins the f32 output so each iteration's
        # traffic is real.
        def xla_rt(v):
            h = _lax.optimization_barrier(v.astype(jnp.bfloat16))
            return _lax.optimization_barrier(h.astype(jnp.float32))

        dts = timed_chain_ab({"pallas": roundtrip, "xla": xla_rt}, x,
                             iters=24, trials=8)
        # bytes per roundtrip: read 4B + write 2B + read 2B + write 4B
        nbytes = x.size * 12
        detail["compression_gbps"] = round(nbytes / dts["pallas"] / 1e9, 2)
        detail["compression_xla_gbps"] = round(nbytes / dts["xla"] / 1e9, 2)
    except Exception as e:  # noqa: BLE001 — best-effort detail metric
        detail["compression_error"] = f"{type(e).__name__}: {e}"
    return detail


def _selfring_stage(jax, jnp, timed_chain) -> dict:
    """Execute the Mosaic-COMPILED ring collectives on the chip as a
    virtual 8-rank self-ring: every hop is a real remote DMA
    (device_id = self) with the real semaphore handshakes and
    ACK-window flow control — no interpreter anywhere.  This is the
    reference's execute-the-synthesized-artifact rung
    (test/model/simulator/cclo_sim.cpp:57-559): until r5 the compiled
    semaphore/remote-DMA code had only ever been *compiled*, never run.
    Correctness is asserted against the self-ring closed forms (ag →
    x tiled V times; rs → op-fold of our own V chunks) before anything
    is timed."""
    detail: dict = {}
    try:
        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P

        from accl_tpu.ops.ring import (
            ring_all_gather_pallas,
            ring_all_reduce_pallas,
            ring_reduce_scatter_pallas,
        )

        V = 8
        rows = 4096                      # 4096 x 128 f32 = 2 MB chunk
        mesh = Mesh(np.array(jax.devices()[:1]), ("r",))
        spec = P()                       # 1-member axis: full array local

        def smap(f):
            return jax.jit(jax.shard_map(f, mesh=mesh, in_specs=spec,
                                         out_specs=spec,
                                         check_vma=False))

        x = jax.random.normal(jax.random.PRNGKey(11), (rows, 128),
                              jnp.float32)
        xs = jax.random.normal(jax.random.PRNGKey(12), (V, rows, 128),
                               jnp.float32)

        # correctness first: the compiled kernels must produce the
        # self-ring closed forms or no bandwidth number is reported
        ag = smap(lambda v: ring_all_gather_pallas(v, "r", ring_size=V))
        got = np.asarray(ag(x))
        want = np.broadcast_to(np.asarray(x), (V, rows, 128))
        assert np.array_equal(got, want), "self-ring allgather mismatch"

        rs = smap(lambda v: ring_reduce_scatter_pallas(v, "r",
                                                       ring_size=V))
        got = np.asarray(rs(xs))
        want = np.asarray(xs).astype(np.float64).sum(axis=0)
        err = np.max(np.abs(got - want) / (np.abs(want) + 1e-6))
        assert err < 1e-3, f"self-ring reduce-scatter mismatch {err}"
        detail["ring_compiled_selfring_ok"] = True

        # bandwidth of the remote-DMA path: (V-1) hops x chunk bytes
        # per kernel; chained via the [0] row (== x for the self-ring)
        ag_chain = smap(
            lambda v: ring_all_gather_pallas(v, "r", ring_size=V)[0])
        dt = timed_chain(ag_chain, x, iters=48, trials=3)
        hop_bytes = (V - 1) * rows * 128 * 4
        detail["ring_selfring_ag_gbps"] = round(hop_bytes / dt / 1e9, 2)

        # allreduce self-ring: rs + ag composition, value renormalized
        # by V so the chain carry stays bounded (self-ring sum tiles
        # the chunk-fold; /V makes iteration a bounded fixed point)
        arx = jax.random.normal(jax.random.PRNGKey(13), (V * rows, 128),
                                jnp.float32)
        ar_chain = smap(
            lambda v: ring_all_reduce_pallas(v, "r", ring_size=V) / V)
        dt = timed_chain(ar_chain, arx, iters=32, trials=3)
        # rs phase: (V-1) hops x chunk; ag phase: (V-1) hops x chunk
        ar_bytes = 2 * (V - 1) * rows * 128 * 4
        detail["ring_selfring_ar_gbps"] = round(ar_bytes / dt / 1e9, 2)
    except Exception as e:  # noqa: BLE001 — best-effort detail metric
        detail["ring_selfring_error"] = f"{type(e).__name__}: {e}"
    return detail


def _numpy_last_resort() -> dict:
    """If jax itself is broken, still land a labeled number."""
    import numpy as np
    n = 1 << 22
    a = np.random.default_rng(0).standard_normal(n).astype(np.float32)
    b = np.random.default_rng(1).standard_normal(n).astype(np.float32)
    a + b  # warm caches / allocator
    t0 = time.perf_counter()
    iters = 10
    out = a
    for _ in range(iters):
        out = out + b
    dt = (time.perf_counter() - t0) / iters
    gbps = 3 * n * 4 / dt / 1e9
    return {
        "metric": "on-path reduction lane sustained throughput "
                  "(fp32 sum, numpy last-resort fallback — jax unavailable)",
        "value": round(gbps, 2),
        "unit": "GB/s",
        "vs_baseline": round(gbps / BASELINE_GBPS, 2),
        "platform": "numpy",
    }


# ---------------------------------------------------------------------------
# orchestrator: subprocess + timeout around every jax touch
# ---------------------------------------------------------------------------

def _run_worker(platform: str, timeout_s: int,
                run_id: str = "") -> dict | None:
    """Run `python bench.py --worker <platform>` and parse its last
    stdout line as JSON.  Returns None on timeout / crash / bad JSON.
    `run_id` keys the per-stage ledger: a retry attempt in the same run
    resumes after the last banked stage instead of starting over."""
    cmd = [sys.executable, os.path.abspath(__file__), "--worker", platform]
    env = dict(os.environ, ACCL_BENCH_RUN_ID=run_id)
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout_s,
            env=env, cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired:
        print(f"[bench] {platform} worker timed out after {timeout_s}s "
              "(TPU claim hung?)", file=sys.stderr)
        return None
    dt = time.perf_counter() - t0
    tail = "\n".join(proc.stderr.strip().splitlines()[-8:])
    if tail:
        print(f"[bench] {platform} worker stderr tail:\n{tail}",
              file=sys.stderr)
    if proc.returncode != 0:
        print(f"[bench] {platform} worker exited rc={proc.returncode} "
              f"after {dt:.0f}s", file=sys.stderr)
        return None
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    print(f"[bench] {platform} worker produced no JSON line; stdout was: "
          f"{proc.stdout[-500:]!r}", file=sys.stderr)
    return None


def main() -> None:
    if len(sys.argv) >= 3 and sys.argv[1] == "--worker":
        print(json.dumps(_measure(sys.argv[2])))
        return

    result = None
    # ACCL_BENCH_RUN_ID pins the stage ledger across bench invocations:
    # a retry loop knocking on a blocked chip accumulates stages over
    # hours instead of restarting per invocation (each invocation's
    # attempts already share the ledger via this id)
    run_id = (os.environ.get("ACCL_BENCH_RUN_ID")
              or f"run-{os.getpid()}-{int(time.time())}")
    for i, budget in enumerate(TPU_ATTEMPT_TIMEOUTS):
        print(f"[bench] TPU attempt {i + 1}/{len(TPU_ATTEMPT_TIMEOUTS)} "
              f"(budget {budget}s)", file=sys.stderr)
        result = _run_worker("tpu", budget, run_id=run_id)
        if result is not None:
            break
    if result is None:
        # every attempt died mid-run — but any stage a worker banked
        # before its claim hung is still a FRESH hardware measurement;
        # a partial fresh record beats a complete stale one (r4 lost
        # its whole round record to an all-or-nothing worker)
        led = _load_ledger(run_id)
        result = _assemble(led["stages"])
        if result is not None:
            print("[bench] assembling PARTIAL result from "
                  f"{sorted(led['stages'])} stages banked before the "
                  "attempts timed out", file=sys.stderr)
    if result is None:
        # no stages under OUR run id — but a harvest loop (another
        # invocation with its own pinned id, scripts/chip_harvest.sh)
        # may have banked recent fresh stages in its own ledger file;
        # those are real hardware measurements and still beat a stale
        # replay.  Recency-gated: a ledger from a previous round's
        # filesystem must not masquerade as this run's.
        try:
            import calendar
            import glob as _glob

            cands = []
            for p in _glob.glob(os.path.join(_LEDGER_DIR,
                                             "bench_stages.*.json")):
                try:
                    with open(p) as f:
                        cands.append(json.load(f))
                except (OSError, ValueError):
                    continue
            foreign = max(cands, key=lambda d: d.get("banked_at", ""),
                          default={})
            banked = foreign.get("banked_at", "")
            # the timestamp is UTC: timegm, not mktime (which would
            # skew the age by the host's UTC offset)
            age_s = (time.time() - calendar.timegm(time.strptime(
                banked, "%Y-%m-%dT%H:%M:%SZ"))) if banked else 1e18
            if age_s < 24 * 3600:
                result = _assemble(foreign.get("stages", {}))
                if result is not None:
                    result["partial_from_run"] = foreign.get("run_id")
                    result["measured_at"] = banked
                    print("[bench] assembling PARTIAL result from the "
                          f"harvest ledger (run {foreign.get('run_id')!r}"
                          f", banked {banked})", file=sys.stderr)
        except (OSError, ValueError, OverflowError):
            pass
    if (result is not None
            and result.get("platform") not in (None, "cpu", "numpy")
            and not result.get("stages_missing")):
        # bank the fresh COMPLETE hardware measurement for future
        # blocked windows (a partial must not overwrite a complete
        # record's detail lanes; partials live in the stage ledger)
        try:
            tmp = LAST_TPU_JSON + ".tmp"
            with open(tmp, "w") as f:
                json.dump(dict(result, measured_at=time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime())), f)
            os.replace(tmp, LAST_TPU_JSON)
        except OSError as e:
            print(f"[bench] could not persist TPU result: {e}",
                  file=sys.stderr)
    if result is not None and result.get("platform") in ("cpu", "numpy"):
        # a "tpu" worker that quietly initialized a CPU backend (no
        # axon sitecustomize on this box) measured nothing the metric
        # cares about — treat it like a failed attempt so the stale
        # hardware number below can take precedence
        print("[bench] tpu worker landed on platform="
              f"{result['platform']} — discarding", file=sys.stderr)
        result = None
    if result is None and os.path.exists(LAST_TPU_JSON):
        # a blocked chip claim is transient; the last REAL hardware
        # number, clearly marked stale, beats a CPU-interpret rate that
        # measures nothing the metric cares about
        try:
            with open(LAST_TPU_JSON) as f:
                result = json.load(f)
            result["stale"] = True
            result["note"] = ("chip claim unavailable at run time; "
                              "last persisted real-TPU measurement")
            _scrub_retracted(result)
            print("[bench] TPU unavailable — reporting last persisted "
                  f"TPU result ({result.get('measured_at')}) marked "
                  "stale", file=sys.stderr)
        except (OSError, ValueError):
            result = None
    if result is None:
        print("[bench] TPU unavailable — falling back to CPU "
              "(interpret-mode Pallas; NOT a hardware number)",
              file=sys.stderr)
        result = _run_worker("cpu", CPU_TIMEOUT_S)
    if result is None:
        print("[bench] jax CPU worker failed too — numpy last resort",
              file=sys.stderr)
        try:
            result = _numpy_last_resort()
        except Exception as e:  # noqa: BLE001 — must still print a line
            result = {
                "metric": "benchmark could not run (all fallbacks failed)",
                "value": 0.0,
                "unit": "GB/s",
                "vs_baseline": 0.0,
                "error": f"{type(e).__name__}: {e}",
            }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
