"""Benchmark of record — runs on real TPU hardware (one chip).

Measures the sustained throughput of the on-path reduction arithmetic
lane (accl_tpu.ops.reduce_ops, the reference reduce_ops plugin's role)
on large fp32 buffers.  This is the directly comparable single-device
anchor in BASELINE.md: the reference CCLO's internal datapath moves
64 B/cycle @ 250 MHz = 16 GB/s through its reduction unit; the TPU lane
streams both operands + result through HBM, so the metric is effective
reduction bandwidth = 3 x bytes / time.

Methodology notes (important on remote-tunneled devices, where
`block_until_ready` can return at enqueue-ack rather than completion):
- iterations are CHAINED (out feeds the next call) so no caching or
  cross-call elision is possible;
- completion is forced by a scalar device->host readback, which cannot
  resolve before the producing op finishes;
- the readback round-trip cost is measured separately and subtracted;
- the reported value is the median of several trials.

vs_baseline = throughput / 16 GB/s (reference CCLO datapath ceiling,
BASELINE.md "CCLO internal datapath").

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N}
"""
from __future__ import annotations

import json
import statistics
import time


def main() -> None:
    import jax
    import jax.numpy as jnp

    on_tpu = jax.default_backend() == "tpu"
    # 64 Mi elements = 256 MB per operand on TPU; small on CPU fallback
    n = (64 << 20) if on_tpu else (1 << 20)

    from accl_tpu.ops.reduce_ops import pallas_add

    a = jax.random.normal(jax.random.PRNGKey(0), (n,), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(1), (n,), jnp.float32)

    interpret = not on_tpu

    def run(x):
        return pallas_add(x, b, interpret=interpret)

    probe = jax.jit(lambda x: x[-1])

    # warmup / compile (both the kernel and the sync probe)
    out = run(a)
    float(probe(out))

    # measure the sync round-trip alone so it can be subtracted
    syncs = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(probe(a))
        syncs.append(time.perf_counter() - t0)
    sync_s = statistics.median(syncs)

    iters = 30 if on_tpu else 3
    trials = 3
    vals = []
    for _ in range(trials):
        out = a
        t0 = time.perf_counter()
        for _ in range(iters):
            out = run(out)
        float(probe(out))  # true completion barrier
        elapsed = time.perf_counter() - t0
        # RTT jitter can push elapsed below the pre-measured sync median;
        # fall back to the unsubtracted time rather than go negative
        net = elapsed - sync_s if elapsed > sync_s else elapsed
        vals.append(net / iters)
    dt = statistics.median(vals)

    nbytes = 3 * n * 4  # read a, read b, write out
    gbps = nbytes / dt / 1e9
    baseline_gbps = 16.0  # reference CCLO datapath (BASELINE.md)
    print(json.dumps({
        "metric": "on-path reduction lane sustained throughput (fp32 sum, "
                  f"{'TPU' if on_tpu else 'CPU-interpret fallback'})",
        "value": round(gbps, 2),
        "unit": "GB/s",
        "vs_baseline": round(gbps / baseline_gbps, 2),
    }))


if __name__ == "__main__":
    main()
