"""Benchmark of record — runs on real TPU hardware (one chip).

Measures the sustained throughput of the on-path reduction arithmetic
lane (accl_tpu.ops.reduce_ops, the reference reduce_ops plugin's role)
on large fp32 buffers.  This is the directly comparable single-device
anchor in BASELINE.md: the reference CCLO's internal datapath moves
64 B/cycle @ 250 MHz = 16 GB/s through its reduction unit; the TPU lane
streams both operands + result through HBM, so the metric is effective
reduction bandwidth = 3 x bytes / time.

vs_baseline = throughput / 16 GB/s (reference CCLO datapath ceiling,
BASELINE.md "CCLO internal datapath").

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N}
"""
from __future__ import annotations

import json
import time


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    on_tpu = jax.default_backend() == "tpu"
    # 64 Mi elements = 256 MB per operand on TPU; small on CPU fallback
    n = (64 << 20) if on_tpu else (1 << 20)

    from accl_tpu.ops.reduce_ops import pallas_add

    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n,), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(1), (n,), jnp.float32)
    jax.block_until_ready((a, b))

    interpret = not on_tpu

    def run():
        return pallas_add(a, b, interpret=interpret)

    # warmup / compile
    out = run()
    jax.block_until_ready(out)

    iters = 20 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        out = run()
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters

    nbytes = 3 * n * 4  # read a, read b, write out
    gbps = nbytes / dt / 1e9
    baseline_gbps = 16.0  # reference CCLO datapath (BASELINE.md)
    print(json.dumps({
        "metric": "on-path reduction lane sustained throughput (fp32 sum, "
                  f"{'TPU' if on_tpu else 'CPU-interpret fallback'})",
        "value": round(gbps, 2),
        "unit": "GB/s",
        "vs_baseline": round(gbps / baseline_gbps, 2),
    }))


if __name__ == "__main__":
    main()
