"""Int8 block-scaled quantized collectives (ops/quantized.py): the wire
compression algebra extended below the reference's fp16 lane set.

Error contract under test: one quantization rounds within scale/2 =
block-absmax/254 per element; the ring reduce-scatter requantizes per
hop so allreduce error grows linearly in P.  Tolerances below derive
from those bounds, not from hand-tuning.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from accl_tpu.ops.quantized import (
    DEFAULT_BLOCK,
    dequantize_blockwise,
    quantize_blockwise,
    quantized_all_reduce,
    quantized_ring_all_gather,
    quantized_ring_reduce_scatter,
)
from accl_tpu.parallel.mesh import make_mesh

NR = 4


def _shard_map(fn, mesh, nin=1):
    spec = P("dp")
    return jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=(spec,) * nin,
                                 out_specs=spec))


def _mesh():
    return make_mesh(dp=NR)


def _rand(n, seed=0, scale=1.0):
    return (np.random.default_rng(seed).standard_normal(n) * scale
            ).astype(np.float32)


# ---------------------------------------------------------------------------
# quantize/dequantize roundtrip
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("n", [256, 1000, 4096 + 17])
def test_quantize_roundtrip_error_bound(n):
    x = jnp.asarray(_rand(n, seed=n))
    q, sc, m = quantize_blockwise(x)
    assert m == n and q.dtype == jnp.int8
    y = dequantize_blockwise(q, sc, n)
    # per-element bound: half a quantization step of its block
    bound = np.repeat(np.asarray(sc)[:, 0], DEFAULT_BLOCK)[:n] * 0.5 + 1e-7
    assert np.all(np.abs(np.asarray(y) - np.asarray(x)) <= bound)


def test_quantize_zero_block_exact():
    x = jnp.zeros(512, jnp.float32)
    q, sc, n = quantize_blockwise(x)
    np.testing.assert_array_equal(np.asarray(dequantize_blockwise(q, sc, n)),
                                  np.zeros(512, np.float32))


def test_quantize_wire_width():
    # the point of the lane: 4:1 payload vs f32, + one f32 scale per block
    x = jnp.asarray(_rand(1 << 16))
    q, sc, _ = quantize_blockwise(x)
    assert q.size == x.size and q.dtype.itemsize == 1
    assert sc.size == x.size // DEFAULT_BLOCK


# ---------------------------------------------------------------------------
# collectives vs exact references
# ---------------------------------------------------------------------------
def test_quantized_ring_reduce_scatter_matches_psum_scatter():
    n = 512  # per-rank chunk
    mesh = _mesh()
    xs = np.stack([_rand(NR * n, seed=r) for r in range(NR)])

    out = _shard_map(
        lambda x: quantized_ring_reduce_scatter(x[0], axis="dp")[None],
        mesh)(jnp.asarray(xs))  # [NR, NR*n], one row per member
    got = np.asarray(out).reshape(NR, n)
    exact = xs.sum(axis=0).reshape(NR, n)
    # error: one requantization per hop (P-1 hops), values ~N(0, sqrt(P))
    # with block absmax <~ 5 sigma -> step <~ 5*sqrt(P)/127; allow 2 steps
    tol = 2 * 5 * np.sqrt(NR) / 127
    np.testing.assert_allclose(got, exact, atol=NR * tol)
    # and it must actually be close in a relative sense
    assert np.mean(np.abs(got - exact)) < 0.05 * np.std(exact)


def test_quantized_ring_all_gather_matches_all_gather():
    n = 700  # ragged vs block
    mesh = _mesh()
    xs = np.stack([_rand(n, seed=10 + r) for r in range(NR)])

    out = _shard_map(
        lambda x: quantized_ring_all_gather(x.reshape(-1), axis="dp")
        .reshape(1, -1), mesh)(jnp.asarray(xs))  # [NR, n]
    got = np.asarray(out).reshape(NR, NR * n)
    exact = xs.reshape(-1)
    for r in range(NR):
        # single quantization round-trip per contribution
        err = np.abs(got[r] - exact)
        assert err.max() <= (np.abs(xs).max() / 127) * 0.5 + 1e-6


@pytest.mark.parametrize("nranks", [2, 4, 8])
def test_quantized_all_reduce_matches_psum(nranks):
    n = 256
    mesh = make_mesh(dp=nranks)
    xs = np.stack([_rand(nranks * n, seed=20 + r) for r in range(nranks)])

    out = _shard_map(
        lambda x: quantized_all_reduce(x.reshape(-1), axis="dp")
        .reshape(1, -1), mesh)(jnp.asarray(xs).reshape(nranks, nranks * n))
    got = np.asarray(out)
    exact = xs.sum(axis=0)
    for r in range(nranks):
        # per-hop requantization error: P-1 hops, each within half a
        # quantization step of a partial whose magnitude grows ~sqrt(P)
        # (values ~N(0,1), block absmax <~ 5 sigma) — same bound as the
        # reduce-scatter test
        atol = nranks * (2 * 5 * np.sqrt(nranks) / 127)
        np.testing.assert_allclose(got[r], exact, atol=atol)
        assert np.mean(np.abs(got[r] - exact)) < 0.05 * np.std(exact)
    # all members agree bit-exactly (same wire data relayed)
    for r in range(1, nranks):
        np.testing.assert_array_equal(got[r], got[0])


def test_quantized_all_reduce_error_feedback_within_bound():
    """The EF lane (EQuARX): per-hop error is carried, not dropped —
    result stays inside the documented bound and the lane is genuinely
    distinct from plain requantization."""
    n, nranks = 256, 4
    mesh = make_mesh(dp=nranks)
    xs = np.stack([_rand(nranks * n, seed=60 + r) for r in range(nranks)])

    def run(ef):
        out = _shard_map(
            lambda x: quantized_all_reduce(
                x.reshape(-1), axis="dp", error_feedback=ef)
            .reshape(1, -1), mesh)(
                jnp.asarray(xs).reshape(nranks, nranks * n))
        return np.asarray(out)

    exact = xs.sum(axis=0)
    got_ef, got_plain = run(True), run(False)
    atol = nranks * (2 * 5 * np.sqrt(nranks) / 127)
    for r in range(nranks):
        np.testing.assert_allclose(got_ef[r], exact, atol=atol)
    # the residual fold changes the hop-k+1 quantization input, so the
    # two lanes cannot be byte-identical on random data
    assert not np.array_equal(got_ef, got_plain)


def test_quantize_blockwise_stochastic_rounding():
    """PRNG-key rounding: each element lands within one full step (the
    floor(r + u) contract) and different keys draw different roundings
    — the PRNG is live, decorrelating ring hops."""
    import jax

    x = jnp.asarray(_rand(512, seed=9))
    q, sc, n = quantize_blockwise(x, key=jax.random.PRNGKey(0))
    y = np.asarray(dequantize_blockwise(q, sc, n))
    step = float(np.asarray(sc).max())
    assert np.all(np.abs(y - np.asarray(x)) <= step + 1e-6)
    q2, _, _ = quantize_blockwise(x, key=jax.random.PRNGKey(1))
    assert not np.array_equal(np.asarray(q), np.asarray(q2))


def test_sync_gradients_int8():
    from accl_tpu.parallel.strategies import sync_gradients

    mesh = _mesh()
    tree = {
        "w": np.stack([_rand((8, 33), seed=30 + r).reshape(8, 33)
                       for r in range(NR)]),
        "b": np.stack([_rand(5, seed=40 + r) for r in range(NR)]),
    }

    def body(w, b):
        out = sync_gradients({"w": w[0], "b": b[0]}, axis="dp",
                             compress="int8")
        return out["w"][None], out["b"][None]

    spec4 = P("dp", None, None)
    spec2 = P("dp", None)
    fn = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(spec4, spec2),
        out_specs=(spec4, spec2)))
    w_out, b_out = fn(jnp.asarray(tree["w"]), jnp.asarray(tree["b"]))
    exp_w = tree["w"].mean(axis=0)
    exp_b = tree["b"].mean(axis=0)
    for r in range(NR):
        np.testing.assert_allclose(np.asarray(w_out)[r], exp_w, atol=0.2)
        np.testing.assert_allclose(np.asarray(b_out)[r], exp_b, atol=0.2)
