"""Protocol-level emulator tests: wire compression, kernel streams,
TCP socket transport (reference: test.cpp compressed variants :381-1002,
stream tests :315-380, multi-process emulator run over ZMQ)."""
import threading

import numpy as np
import pytest

from accl_tpu import DataType, ReduceFunction
from accl_tpu.backends.emu import EmuRankTcp, EmuWorld

NRANKS = 4
COUNT = 300
F16RTOL, F16ATOL = 5e-3, 5e-3  # reference FLOAT16RTOL/ATOL (utility.hpp)


@pytest.fixture(scope="module")
def world():
    with EmuWorld(NRANKS) as w:
        yield w


def _data(count, rank, salt=0):
    rng = np.random.default_rng(77 + rank + salt * 100)
    return rng.standard_normal(count).astype(np.float32)


# ---------------------------------------------------------------------------
# fp16 on-the-wire compression (reference: test_sendrcv_compressed :381,
# allreduce/bcast/reduce compressed variants; tolerance per utility.hpp)
# ---------------------------------------------------------------------------
def test_sendrecv_compressed(world):
    def fn(accl, rank):
        if rank == 0:
            src = accl.create_buffer_like(_data(COUNT, 0))
            accl.send(src, COUNT, 1, tag=5, compress_dtype=DataType.float16)
        elif rank == 1:
            dst = accl.create_buffer(COUNT, np.float32)
            accl.recv(dst, COUNT, 0, tag=5, compress_dtype=DataType.float16)
            np.testing.assert_allclose(dst.host, _data(COUNT, 0),
                                       rtol=F16RTOL, atol=F16ATOL)

    world.run(fn)


@pytest.mark.parametrize("root", [0, 2])
def test_bcast_compressed(world, root):
    def fn(accl, rank):
        buf = accl.create_buffer_like(_data(COUNT, rank, salt=root))
        accl.bcast(buf, COUNT, root, compress_dtype=DataType.float16)
        np.testing.assert_allclose(buf.host, _data(COUNT, root, salt=root),
                                   rtol=F16RTOL, atol=F16ATOL)

    world.run(fn)


def test_allreduce_compressed(world):
    def fn(accl, rank):
        send = accl.create_buffer_like(_data(COUNT, rank))
        recv = accl.create_buffer(COUNT, np.float32)
        accl.allreduce(send, recv, COUNT, ReduceFunction.SUM,
                       compress_dtype=DataType.float16)
        exp = np.sum([_data(COUNT, r) for r in range(NRANKS)], axis=0)
        # errors accumulate over ring steps; loosen vs single-hop tolerance
        np.testing.assert_allclose(recv.host, exp, rtol=5e-2, atol=5e-2)

    world.run(fn)


# ---------------------------------------------------------------------------
# kernel streams (reference: test_stream_put :315-380, vadd_put flow —
# a compute kernel pushes operands into the engine and pulls results from
# a stream id >= 9)
# ---------------------------------------------------------------------------
def test_stream_put(world):
    count, strm = 64, 9

    def fn(accl, rank):
        if rank == 0:
            src = accl.create_buffer_like(_data(count, 0))
            accl.stream_put(src, count, dst=1, stream_id=strm)
        elif rank == 1:
            raw = accl.device.pop_stream(strm, count * 4, timeout_s=20)
            assert raw is not None
            got = np.frombuffer(raw, dtype=np.float32)
            np.testing.assert_array_equal(got, _data(count, 0))

    world.run(fn)


def test_send_from_kernel_stream(world):
    # OP0_STREAM: operand bytes come from the local compute-kernel input
    # (the vadd_put kernel's data_to_cclo port)
    from accl_tpu.constants import StreamFlags
    count = 32

    def fn(accl, rank):
        if rank == 0:
            data = _data(count, 9)
            accl.device.push_krnl(data)
            dummy = accl.create_buffer(count, np.float32)
            accl.send(dummy, count, 1, tag=11, from_fpga=True,
                      stream_flags=StreamFlags.OP0_STREAM)
        elif rank == 1:
            dst = accl.create_buffer(count, np.float32)
            accl.recv(dst, count, 0, tag=11)
            np.testing.assert_array_equal(dst.host, _data(count, 9))

    world.run(fn)


# ---------------------------------------------------------------------------
# TCP socket transport: one engine per "process" (threads here), real
# sockets in between — the multi-node rung of the test ladder
# ---------------------------------------------------------------------------
def test_tcp_transport_allreduce():
    # port picked per-process to dodge TIME_WAIT from earlier runs; the
    # engine receive timeout is raised because rank startup is staggered
    # by real connect/accept latency (slow under a loaded single core)
    import os
    nranks, count = 2, 128
    base_port = 18650 + (os.getpid() % 2000)
    results = {}
    errors = []

    def rank_main(r):
        try:
            with EmuRankTcp(r, nranks, base_port) as node:
                node.accl.set_timeout(60_000_000)
                send = node.accl.create_buffer_like(_data(count, r))
                recv = node.accl.create_buffer(count, np.float32)
                node.accl.allreduce(send, recv, count, ReduceFunction.SUM)
                results[r] = recv.host.copy()
        except Exception as e:  # pragma: no cover
            errors.append((r, e))

    threads = [threading.Thread(target=rank_main, args=(r,))
               for r in range(nranks)]
    for t in threads:
        t.start()
    # generous join budget: under full-suite load on a single core the
    # connect/accept + allreduce round can take well over a minute
    for t in threads:
        t.join(timeout=180)
    alive = [i for i, t in enumerate(threads) if t.is_alive()]
    assert not alive, f"rank threads still running after join: {alive}"
    assert not errors, errors
    exp = _data(count, 0) + _data(count, 1)
    for r in range(nranks):
        np.testing.assert_allclose(results[r], exp, rtol=1e-6)


# ---------------------------------------------------------------------------
# mem<->stream reduce variants (reference: test.cpp:813-910 — reduce with
# a streamed operand and/or a streamed result)
# ---------------------------------------------------------------------------
def test_reduce_from_stream(world):
    from accl_tpu import StreamFlags

    root = 1

    def fn(accl, rank):
        accl.device.push_krnl(_data(COUNT, rank, salt=3))
        recv = accl.create_buffer(COUNT, np.float32) if rank == root else None
        accl.reduce(None, recv, COUNT, root,
                    stream_flags=StreamFlags.OP0_STREAM)
        if rank == root:
            expect = sum(_data(COUNT, r, salt=3) for r in range(NRANKS))
            np.testing.assert_allclose(recv.host, expect, rtol=1e-4,
                                       atol=1e-4)

    world.run(fn)


def test_reduce_to_stream(world):
    from accl_tpu import StreamFlags

    root, strm = 0, 10

    def fn(accl, rank):
        send = accl.create_buffer_like(_data(COUNT, rank, salt=4))
        accl.reduce(send, None, COUNT, root,
                    stream_flags=StreamFlags.RES_STREAM, stream_id=strm)
        if rank == root:
            raw = accl.device.pop_stream(strm, COUNT * 4, timeout_s=20)
            assert raw is not None
            expect = sum(_data(COUNT, r, salt=4) for r in range(NRANKS))
            np.testing.assert_allclose(np.frombuffer(raw, np.float32),
                                       expect, rtol=1e-4, atol=1e-4)

    world.run(fn)


def test_reduce_stream_to_stream(world):
    from accl_tpu import StreamFlags

    root, strm = 2, 11

    def fn(accl, rank):
        accl.device.push_krnl(_data(COUNT, rank, salt=5))
        accl.reduce(None, None, COUNT, root,
                    stream_flags=StreamFlags.OP0_STREAM
                    | StreamFlags.RES_STREAM, stream_id=strm)
        if rank == root:
            raw = accl.device.pop_stream(strm, COUNT * 4, timeout_s=20)
            assert raw is not None
            expect = sum(_data(COUNT, r, salt=5) for r in range(NRANKS))
            np.testing.assert_allclose(np.frombuffer(raw, np.float32),
                                       expect, rtol=1e-4, atol=1e-4)

    world.run(fn)


# ---------------------------------------------------------------------------
# the rendezvous max-size register is enforced as a hard cap: transfers
# that fit neither protocol fail fast with DMA_SIZE_ERROR instead of
# wedging (the reference validates but never enforces, fw :2442-2448)
# ---------------------------------------------------------------------------
def test_rendezvous_size_cap():
    from accl_tpu import ACCLError
    from accl_tpu.backends.emu import EmuWorld as _World

    n = 16384  # 64 KB fp32 > default 32 KB rendezvous cap

    def fn(accl, rank):
        src = accl.create_buffer(n, np.float32)
        dst = accl.create_buffer(n, np.float32)
        with pytest.raises(ACCLError, match="DMA_SIZE"):
            if rank == 0:
                accl.send(src, n, 1, tag=99)
            else:
                accl.recv(dst, n, 0, tag=99)
        # raising the register re-enables the transfer
        accl.set_max_rendezvous_msg_size(1 << 20)
        src.host[:] = float(rank + 1)
        src.sync_to_device()
        if rank == 0:
            accl.send(src, n, 1, tag=100)
        else:
            accl.recv(dst, n, 0, tag=100)
            np.testing.assert_allclose(dst.host, 1.0)

    with _World(2) as w:
        w.run(fn)


# ---------------------------------------------------------------------------
# copy stream variants + p2p buffers (reference: test_copy_stream :46,
# test_copy_p2p :63) and the segmentation boundary matrix (reference:
# test_sendrcv_segmentation :265 — counts at segment_size multiples +/-1)
# ---------------------------------------------------------------------------
def test_copy_stream(world):
    # copy_to_stream pushes the buffer out the kernel port; the test plays
    # the loopback kernel (the emulator's --loopback wiring) by feeding the
    # payload back into the kernel input; copy_from_stream lands it in mem
    count = 64

    def fn(accl, rank):
        if rank != 0:
            return
        src = accl.create_buffer_like(_data(count, 0, salt=21))
        dst = accl.create_buffer(count, np.float32)
        accl.copy_to_stream(src, count, stream_id=11)
        raw = accl.device.pop_stream(11, count * 4)
        assert raw is not None
        accl.device.push_krnl(np.frombuffer(raw, np.float32))
        accl.copy_from_stream(dst, count)
        np.testing.assert_array_equal(dst.host, _data(count, 0, salt=21))

    world.run(fn)


def test_copy_p2p(world):
    count = 64

    def fn(accl, rank):
        if rank != 0:
            return
        src = accl.create_buffer_like(_data(count, 0, salt=22))
        p2p = accl.create_buffer_p2p(count, np.float32)
        accl.copy(src, p2p, count)
        np.testing.assert_array_equal(p2p.host, _data(count, 0, salt=22))

    world.run(fn)


@pytest.mark.parametrize("multiplier", [1, 2])
@pytest.mark.parametrize("offset", [-1, 0, 1])
def test_sendrecv_segmentation(world, multiplier, offset):
    # default eager rx buffer = 1KB -> 256 fp32 per segment; sweep counts
    # at segment multiples +/-1 element, echoing both directions like the
    # reference (send next, recv prev, send back, recv back)
    seg_elems = 256
    count = seg_elems * multiplier + offset

    def fn(accl, rank):
        nxt, prv = (rank + 1) % NRANKS, (rank - 1) % NRANKS
        src = accl.create_buffer_like(_data(count, rank, salt=30 + offset))
        mid = accl.create_buffer(count, np.float32)
        res = accl.create_buffer(count, np.float32)
        s0 = accl.send(src, count, nxt, tag=0, run_async=True)
        accl.recv(mid, count, prv, tag=0)
        assert s0.wait(timeout=30); s0.check()
        # echo what we received back to its sender
        s1 = accl.send(mid, count, prv, tag=1, run_async=True)
        accl.recv(res, count, nxt, tag=1)
        assert s1.wait(timeout=30); s1.check()
        np.testing.assert_array_equal(res.host, _data(count, rank, salt=30 + offset))

    world.run(fn)


# ---------------------------------------------------------------------------
# eager egress pipelining (reference: the firmware keeps 2-3 moves in
# flight per send and applies end_move() backpressure beyond that,
# ccl_offload_control.c:628-649, :1981-1986; here TuningKey 3 =
# EGRESS_PIPELINE_DEPTH bounds the outstanding-segment window)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("depth", [1, 2, 4])
def test_egress_pipeline_depths(depth):
    count = 2000  # ~8 segments of 1 KB per message
    with EmuWorld(2, max_eager_size=16384) as w:
        def fn(accl, rank):
            accl.set_tuning(3, depth)  # EGRESS_PIPELINE_DEPTH
            nxt, prv = (rank + 1) % 2, (rank - 1) % 2
            for round_ in range(3):
                src = accl.create_buffer_like(_data(count, rank, salt=round_))
                dst = accl.create_buffer(count, np.float32)
                req = accl.send(src, count, nxt, tag=round_, run_async=True)
                accl.recv(dst, count, prv, tag=round_)
                assert req.wait(timeout=30.0)
                req.check()
                # FIFO order + integrity across the window
                np.testing.assert_array_equal(
                    dst.host, _data(count, prv, salt=round_))

        w.run(fn)
