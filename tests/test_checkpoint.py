"""Checkpoint/restore subsystem tests (SURVEY §5: the reference has no
checkpointing — this is the model-layer snapshot/resume the framework
adds, including distributed sharded checkpoints via orbax)."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accl_tpu.models.transformer import ModelConfig, init_params, make_train_step, shard_params
from accl_tpu.parallel.mesh import make_mesh
from accl_tpu.utils.checkpoint import load_pytree, load_sharded, save_pytree, save_sharded

CFG = ModelConfig(vocab=64, d_model=32, n_layers=1, n_heads=4, d_head=8,
                  d_ff=64)


def test_pytree_roundtrip(tmp_path):
    params = init_params(np.random.default_rng(0), CFG)
    path = str(tmp_path / "ckpt")
    save_pytree(path, params)
    restored = load_pytree(path, params)
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pytree_shape_validation(tmp_path):
    params = init_params(np.random.default_rng(0), CFG)
    path = str(tmp_path / "ckpt")
    save_pytree(path, params)
    from dataclasses import replace
    other = init_params(np.random.default_rng(1), replace(CFG, d_ff=128))
    with pytest.raises(ValueError):
        load_pytree(path, other)


def test_sharded_roundtrip_preserves_shardings(tmp_path):
    mesh = make_mesh(tp=4)
    params = shard_params(init_params(np.random.default_rng(0), CFG), mesh,
                          CFG)
    path = os.path.join(str(tmp_path), "sharded")
    save_sharded(path, params)
    restored = load_sharded(path, params)
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(restored)):
        assert a.sharding == b.sharding
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_resume_training_matches_uninterrupted(tmp_path):
    # save at step 1, restore, continue -> identical to never stopping
    mesh = make_mesh(dp=2, tp=2)
    params = shard_params(init_params(np.random.default_rng(0), CFG), mesh,
                          CFG)
    step, (_, tok_spec) = make_train_step(mesh, CFG)
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, CFG.vocab, (4, 16)))

    p1, _ = step(params, tokens)
    path = os.path.join(str(tmp_path), "resume")
    save_sharded(path, p1)
    p2_direct, loss_direct = step(p1, tokens)

    p1_restored = load_sharded(path, p1)
    p2_resumed, loss_resumed = step(p1_restored, tokens)
    assert float(loss_direct) == float(loss_resumed)
    for a, b in zip(jax.tree_util.tree_leaves(p2_direct),
                    jax.tree_util.tree_leaves(p2_resumed)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sharded_refuses_overwrite_and_relative(tmp_path):
    mesh = make_mesh(tp=4)
    params = shard_params(init_params(np.random.default_rng(0), CFG), mesh,
                          CFG)
    path = os.path.join(str(tmp_path), "step_0")
    save_sharded(path, params)
    with pytest.raises(ValueError):
        save_sharded(path, params)      # existing path = recovery point
    with pytest.raises(ValueError):
        save_sharded("relative/ckpt", params)


def test_sharded_scalar_leaves(tmp_path):
    mesh = make_mesh(tp=4)
    state = {
        "params": shard_params(init_params(np.random.default_rng(0), CFG),
                               mesh, CFG),
        "step": 7,
    }
    path = os.path.join(str(tmp_path), "with_step")
    save_sharded(path, state)
    restored = load_sharded(path, state)
    assert int(restored["step"]) == 7
