"""ACCL_DEVICE_TRACE (r15): the in-kernel Pallas phase-stamp plane.

Pins the two halves of the overhead contract: with the gate OFF the
built kernels are bit-identical to the uninstrumented baseline (same
jaxpr — no extra output, no callback; the env is read ONCE at first
kernel build), and with the gate ON the kernels emit per-step stamp
rows whose neighbor/byte attribution matches the ring schedule.

Kernel EXECUTION needs a jax whose Pallas interpreter implements
remote DMA signals; on older jax those tests self-skip exactly like
the pallas test files do (tracing alone works everywhere).
"""
import jax
import numpy as np
import pytest

try:
    from jax import shard_map
except ImportError:  # older jax spells it experimental
    from jax.experimental.shard_map import shard_map

from jax.sharding import NamedSharding, PartitionSpec as P

import accl_tpu.ops.ring as ring
from accl_tpu.observability import trace as obs_trace
from accl_tpu.parallel import make_mesh

NR = 4


@pytest.fixture
def devtrace_env(monkeypatch):
    """Restore the module gate (and collector) around each test."""
    yield monkeypatch
    ring._reset_device_trace_cache()
    obs_trace.collector().clear()


def _mesh():
    if len(jax.devices()) < NR:
        pytest.skip("needs a 4-device mesh")
    return make_mesh(dp=NR)


def _smap(mesh, fn, in_spec, out_spec):
    try:
        return shard_map(fn, mesh=mesh, in_specs=in_spec,
                         out_specs=out_spec, check_vma=False)
    except TypeError:  # older shard_map spells the flag check_rep
        return shard_map(fn, mesh=mesh, in_specs=in_spec,
                         out_specs=out_spec, check_rep=False)


def _allreduce_fn(mesh):
    def body(xb):
        return ring.ring_all_reduce_segmented(
            xb[0], "dp", seg_elems=32, interpret=True)[None]

    return _smap(mesh, body, P("dp", None), P("dp", None))


def _run(mesh):
    x = np.stack([np.arange(64, dtype=np.float32) + r
                  for r in range(NR)])
    xs = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
    try:
        out = np.asarray(jax.jit(_allreduce_fn(mesh))(xs))
    except NotImplementedError as e:  # jax-skew: no remote DMA interp
        pytest.skip(f"pallas interpreter lacks remote DMA: {e}")
    np.testing.assert_allclose(out[0], x.sum(axis=0))
    return out


# ---------------------------------------------------------------------------
# the off path: structurally zero
# ---------------------------------------------------------------------------
def test_off_path_jaxpr_unchanged(devtrace_env):
    """With ACCL_DEVICE_TRACE unset the compiled kernels are the
    baseline: no stamp output, no host callback, and the build is
    identical to one with the gate explicitly forced off — the env
    gate only ever routes between the two builders."""
    devtrace_env.delenv("ACCL_DEVICE_TRACE", raising=False)
    ring._reset_device_trace_cache()
    mesh = _mesh()
    x = np.zeros((NR, 64), np.float32)
    j_off = str(jax.make_jaxpr(_allreduce_fn(mesh))(x))
    assert "debug_callback" not in j_off
    assert j_off.count("pallas_call") > 0
    # deterministic: a rebuild traces to the identical program
    assert str(jax.make_jaxpr(_allreduce_fn(mesh))(x)) == j_off
    # forcing the cached gate off produces the same build even with
    # the env now set — proving the off path has no trace artifacts
    devtrace_env.setenv("ACCL_DEVICE_TRACE", "1")
    ring._DEVICE_TRACE = False
    assert str(jax.make_jaxpr(_allreduce_fn(mesh))(x)) == j_off


def test_env_gate_read_once_at_build(devtrace_env):
    """The gate is cached at FIRST kernel build: flipping the env
    afterwards must not change later builds (the structurally-zero
    off-path contract — no per-call env reads)."""
    devtrace_env.delenv("ACCL_DEVICE_TRACE", raising=False)
    ring._reset_device_trace_cache()
    assert ring.device_trace_enabled() is False
    devtrace_env.setenv("ACCL_DEVICE_TRACE", "1")
    assert ring.device_trace_enabled() is False  # cached
    ring._reset_device_trace_cache()
    assert ring.device_trace_enabled() is True


def test_on_path_jaxpr_gains_stamp_plane(devtrace_env):
    devtrace_env.setenv("ACCL_DEVICE_TRACE", "1")
    ring._reset_device_trace_cache()
    mesh = _mesh()
    j_on = str(jax.make_jaxpr(_allreduce_fn(mesh))(
        np.zeros((NR, 64), np.float32)))
    assert "debug_callback" in j_on


# ---------------------------------------------------------------------------
# the on path: stamp rows with true neighbor attribution
# ---------------------------------------------------------------------------
def test_device_trace_stamps_ring_neighbors(devtrace_env):
    devtrace_env.setenv("ACCL_DEVICE_TRACE", "1")
    ring._reset_device_trace_cache()
    obs_trace.collector().clear()
    mesh = _mesh()
    _run(mesh)
    recs = obs_trace.collector().device_records()
    assert recs, "traced kernels emitted no stamp buffers"
    assert {r["collective"] for r in recs} == \
        {"all_gather", "reduce_scatter"}
    fields = obs_trace.DEVICE_TRACE_FIELDS
    seen_ranks = set()
    for rec in recs:
        for raw in rec["rows"]:
            row = dict(zip(fields, raw))
            seen_ranks.add(row["rank"])
            # ring neighbor attribution: tx to (rank+1) % NR, rx from
            # (rank-1) % NR — the per-neighbor byte counts the link
            # matrix's device half is built from
            assert row["tx_peer"] == (row["rank"] + 1) % NR
            assert row["rx_peer"] == (row["rank"] - 1) % NR
            assert row["tx_bytes"] > 0 and row["rx_bytes"] > 0
            # logical stamps are ordered per step
            assert row["seq_send"] < row["seq_wait"] < row["seq_phase"]
            assert row["seq_send"] == 3 * row["step"]
    assert seen_ranks == set(range(NR))
    # the device half of the link matrix: every rank's bytes attribute
    # to its right ring neighbor
    link = obs_trace.collector().device_link_bytes()
    for r in range(NR):
        assert link.get((r, (r + 1) % NR), 0) > 0
    # and the Perfetto doc grows per-rank device tracks
    doc = obs_trace.collector().to_perfetto()
    tracks = {(ev["pid"], ev["args"]["name"])
              for ev in doc["traceEvents"] if ev.get("ph") == "M"
              and str((ev.get("args") or {}).get("name", "")
                      ).startswith("device:")}
    assert {pid for pid, _n in tracks} == set(range(NR))


def test_device_trace_off_emits_nothing(devtrace_env):
    devtrace_env.delenv("ACCL_DEVICE_TRACE", raising=False)
    ring._reset_device_trace_cache()
    obs_trace.collector().clear()
    mesh = _mesh()
    _run(mesh)
    assert obs_trace.collector().device_records() == []


def test_on_off_results_bitwise_identical(devtrace_env):
    devtrace_env.delenv("ACCL_DEVICE_TRACE", raising=False)
    ring._reset_device_trace_cache()
    mesh = _mesh()
    out_off = _run(mesh)
    devtrace_env.setenv("ACCL_DEVICE_TRACE", "1")
    ring._reset_device_trace_cache()
    out_on = _run(mesh)
    np.testing.assert_array_equal(out_off, out_on)
