"""MoE model family tests: dense-vs-EP routing equivalence and an
expert-parallel train step over a dp x ep mesh (EP = the reference's
alltoall enablement, SURVEY §2.8)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from accl_tpu.models.moe import (
    MoEConfig,
    forward,
    init_params,
    loss_fn,
    make_train_step,
    shard_params,
)
from accl_tpu.parallel.mesh import make_mesh


CFG = MoEConfig(vocab=64, d_model=32, n_layers=1, n_heads=2, d_head=16,
                d_ff=64, n_experts=4, capacity_factor=4.0)


def _tokens(b, t, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).integers(0, CFG.vocab, (b, t)))


def test_dense_forward_shapes():
    params = init_params(np.random.default_rng(0), CFG)
    logits, aux = forward(params, _tokens(2, 16), CFG)
    assert logits.shape == (2, 16, CFG.vocab)
    assert np.isfinite(float(aux))


def test_ep_matches_dense():
    # the ep-sharded routed FFN (alltoall dispatch/combine) must agree
    # with the run-every-expert dense reference, given enough capacity
    params = init_params(np.random.default_rng(0), CFG)
    tokens = _tokens(4, 16, seed=2)
    dense_logits, dense_aux = forward(params, tokens, CFG)

    mesh = make_mesh(ep=4)
    sharded = shard_params(params, mesh, CFG)

    def body(p, t):
        logits, _aux = forward(p, t, CFG, ep_axis="ep")
        return logits

    from accl_tpu.models.moe import param_specs
    fn = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(param_specs(CFG, "ep"), P("ep")),
        out_specs=P("ep")))
    ep_logits = fn(sharded, tokens)
    np.testing.assert_allclose(np.asarray(ep_logits),
                               np.asarray(dense_logits), rtol=2e-4,
                               atol=2e-4)


def test_moe_train_step_loss_decreases():
    params = init_params(np.random.default_rng(0), CFG)
    mesh = make_mesh(dp=2, ep=4)
    params = shard_params(params, mesh, CFG)
    step, _ = make_train_step(mesh, CFG, lr=1e-2)
    tokens = _tokens(8, 16, seed=3)
    losses = []
    for _ in range(5):
        params, loss = step(params, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_moe_grad_matches_dense_full_batch():
    # regression: the distributed step's effective gradient must equal
    # the dense single-device full-batch gradient (NOT n_devices x it) —
    # mesh size must not change training dynamics
    lr = 1e-2
    params = init_params(np.random.default_rng(0), CFG)
    tokens = _tokens(8, 16, seed=4)

    # dense reference step on the full batch
    (ls, cnt), grads = jax.value_and_grad(
        lambda p: loss_fn(p, tokens, CFG, None), has_aux=True)(params)
    ref = jax.tree_util.tree_map(
        lambda p, g: p - (lr / cnt) * g, params, grads)

    mesh = make_mesh(dp=2, ep=4)
    sharded = shard_params(params, mesh, CFG)
    step, _ = make_train_step(mesh, CFG, lr=lr)
    new_params, _loss = step(sharded, tokens)

    for (path, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(new_params)[0],
            jax.tree_util.tree_flatten_with_path(ref)[0]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
            err_msg=str(path))


def test_moe_ep_size_mismatch_raises():
    mesh = make_mesh(ep=2)
    with pytest.raises(ValueError):
        make_train_step(mesh, CFG)
