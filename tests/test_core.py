"""Unit tests for the driver core: constants/ABI, arithconfig,
communicator, request layer (reference test analog: driver-level pieces of
test/host/xrt/src/test.cpp plus constants sanity)."""
import threading

import pytest

from accl_tpu import (
    ACCLError,
    CCLOCall,
    Communicator,
    CompressionFlags,
    DataType,
    Operation,
    Rank,
    ReduceFunction,
    Request,
)
from accl_tpu.arithconfig import DEFAULT_ARITH_CONFIG
from accl_tpu.communicator import _ip_decode, _ip_encode
from accl_tpu.constants import ErrorCode, error_code_to_str


def test_operation_codes_match_reference_abi():
    # scenario codes must stay bit-compatible with the reference
    # (constants.hpp:191-210)
    assert Operation.config == 0
    assert Operation.copy == 1
    assert Operation.combine == 2
    assert Operation.send == 3
    assert Operation.recv == 4
    assert Operation.bcast == 5
    assert Operation.scatter == 6
    assert Operation.gather == 7
    assert Operation.reduce == 8
    assert Operation.allgather == 9
    assert Operation.allreduce == 10
    assert Operation.reduce_scatter == 11
    assert Operation.barrier == 12
    assert Operation.alltoall == 13
    assert Operation.nop == 255


def test_call_descriptor_is_15_words():
    call = CCLOCall(
        scenario=Operation.allreduce,
        count=1024,
        comm=0,
        function=int(ReduceFunction.SUM),
        addr_0=0x1_0000_0040,
        addr_2=0xDEAD_BEEF_0000,
    )
    words = call.to_words()
    assert len(words) == 15
    assert words[0] == 10
    assert words[1] == 1024
    # 64-bit addresses split low/high
    assert words[9] == 0x0000_0040 and words[10] == 0x1
    assert (words[13] | words[14] << 32) == 0xDEAD_BEEF_0000


def test_error_code_decode():
    code = int(ErrorCode.DMA_TIMEOUT_ERROR | ErrorCode.ARITH_ERROR)
    s = error_code_to_str(code)
    assert "DMA_TIMEOUT_ERROR" in s and "ARITH_ERROR" in s
    assert error_code_to_str(0) == "COLLECTIVE_OP_SUCCESS"


def test_arithconfig_table_covers_reference_pairs():
    # identity pairs for the 5 reference dtypes + fp32-over-fp16
    # compression (arithconfig.hpp:106-119), plus the bf16 identity and
    # fp32-over-bf16 compressed pairs (TPU extensions)
    pairs = set(DEFAULT_ARITH_CONFIG)
    assert (DataType.float32, DataType.float32) in pairs
    assert (DataType.float32, DataType.float16) in pairs
    assert (DataType.bfloat16, DataType.bfloat16) in pairs
    assert (DataType.float32, DataType.bfloat16) in pairs
    assert len(pairs) == 8
    cfg = DEFAULT_ARITH_CONFIG[(DataType.float32, DataType.float16)]
    assert cfg.compression_ratio == 2
    words = cfg.to_words()
    assert words[0] == 32 and words[1] == 16


def test_communicator_table_and_split():
    ranks = [Rank(ip="10.1.212.%d" % i, port=5500 + i, session=i) for i in range(4)]
    comm = Communicator(ranks, local_rank=2)
    assert comm.size == 4 and comm.local_rank == 2
    words = comm.to_words()
    assert words[0] == 4 and words[1] == 2
    # split keeping ranks {0, 2}: local rank renumbers to 1
    sub = comm.split([0, 2], comm_id=1)
    assert sub.size == 2 and sub.local_rank == 1
    with pytest.raises(ValueError):
        comm.split([0, 1], comm_id=2)  # local rank 2 not a member
    assert "rank 2" in comm.dump()


def test_ip_encode_roundtrip():
    assert _ip_decode(_ip_encode("10.1.212.129")) == "10.1.212.129"


def test_request_wait_and_check():
    req = Request("test")
    assert not req.done

    def completer():
        req.complete(retcode=0, duration_ns=123.0)

    t = threading.Timer(0.05, completer)
    t.start()
    assert req.wait(timeout=5.0)
    assert req.duration_ns == 123.0
    req.check()  # no raise

    bad = Request("bad")
    bad.complete(retcode=int(ErrorCode.RECEIVE_TIMEOUT_ERROR))
    with pytest.raises(ACCLError) as ei:
        bad.check()
    assert "RECEIVE_TIMEOUT_ERROR" in str(ei.value)


def test_compression_flags_algebra():
    f = CompressionFlags.OP0_COMPRESSED | CompressionFlags.ETH_COMPRESSED
    assert int(f) == 9
    assert CompressionFlags.RES_COMPRESSED & f == 0


def test_native_host_driver_suite():
    # the C++ host-driver binary (native/test/test_native.cpp) — the
    # reference's gtest rung for its C++ driver — built and run via
    # `make -C native check`
    import fcntl
    import os
    import subprocess

    root = os.path.join(os.path.dirname(__file__), os.pardir)
    # serialize with the emu backend's auto-builder: both compile the
    # shared native objects (emu.py _build_lib_if_stale takes this lock)
    with open(os.path.join(root, "native", ".build.lock"), "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        proc = subprocess.run(["make", "-C", "native", "check"],
                              cwd=root, capture_output=True, text=True,
                              timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout


def test_call_memo_is_a_true_lru():
    """The descriptor memo must evict its COLDEST entry at capacity,
    not wholesale-clear: a workload cycling through more than cap
    distinct descriptors would otherwise re-derive every call each
    pass (r5 ADVICE, accl.py)."""
    from accl_tpu.accl import ACCL

    a = ACCL(device=object())  # _build never touches the device
    a._arith_ids = {(DataType.float32, DataType.float32): 0}
    a._call_memo_cap = 8

    calls = [a._build(Operation.nop, 0, 0, tag=i) for i in range(20)]
    assert len(a._call_memo) == 8  # bounded

    # hits return the memoized descriptor object (and refresh recency)
    assert a._build(Operation.nop, 0, 0, tag=19) is calls[19]
    assert a._build(Operation.nop, 0, 0, tag=12) is calls[12]

    # oldest resident (tag=13) evicts before the just-touched tag=12
    # when fresh keys push the memo past capacity
    for i in range(100, 106):
        a._build(Operation.nop, 0, 0, tag=i)
    assert a._build(Operation.nop, 0, 0, tag=12) is calls[12]
    assert a._build(Operation.nop, 0, 0, tag=13) is not calls[13]

    # evicted keys re-derive an equal descriptor (correctness is
    # unaffected by eviction)
    assert a._build(Operation.nop, 0, 0, tag=0).tag == calls[0].tag
