"""Per-operand + wire compression matrix over the emulator backend.

Port of the reference compressed test corpus (test/host/xrt/src/
test.cpp:381-1002: test_sendrcv_compressed, test_bcast_compressed,
test_scatter_compressed, test_gather_compressed, test_allgather_compressed,
test_reduce_compressed, ...) widened to the full flag algebra
(constants.hpp:320-325): every collective runs under each of the four
compression flag combinations —

  none   : homogeneous fp32 buffers, NO_COMPRESSION
  eth    : fp32 buffers + compress_dtype=f16  -> ETH_COMPRESSED
  op     : mixed f16 operand / f32 result     -> OP{0}/RES_COMPRESSED
  op_eth : mixed buffers + compress_dtype=f16 -> per-operand | ETH

— at three protocol points: single-segment eager, multi-segment eager
with a ragged tail (segmentation +-1), and rendezvous (the engine here
supports compressed rendezvous, which the reference firmware leaves as a
TODO, fw :589).  Tolerances follow the reference (FLOAT16RTOL/ATOL,
test.cpp:27-28) since fp16 wire hops and the mixed-precision accumulate
(arith_is_compressed, arithconfig.hpp:106-119) are lossy.
"""
import numpy as np
import pytest

from accl_tpu import DataType, ReduceFunction
from accl_tpu.backends.emu import EmuWorld

NRANKS = 4
RTOL, ATOL = 0.005, 0.05  # FLOAT16RTOL/FLOAT16ATOL (test.cpp:27-28)

# rx buffers are 1 KB; max_eager raised so multi-segment eager exists
# below the rendezvous switch (the reference tests pick counts against
# options.segment_size the same way, test.cpp:265-313)
RX_BUF = 1024
MAX_EAGER = 4096

#: count -> protocol rung.  The engine selects the protocol on WIRE
#: bytes, so compressed combos halve the byte count per element; 4096
#: elements exceed MAX_EAGER on the wire for every combo (8 KB raw f32 /
#: f16-compressed 8 KB at twice the elements) -> rendezvous everywhere.
SIZES = {
    "eager1": 64,     # single segment eager
    "eagerN": 513,    # multi-segment eager with ragged tail (+1)
    "rndzv": 4096,    # above MAX_EAGER in wire bytes for all combos
}

#: Symmetric collectives (every rank holds both operand and result):
#: combo -> (operand dtype, result dtype, compress_dtype).  The "op"
#: combo exercises pure per-operand flags (OP0_COMPRESSED, uncompressed
#: wire); "op_eth" layers ETH wire compression on top.
COMBOS = {
    "none": (np.float32, np.float32, None),
    "eth": (np.float32, np.float32, DataType.float16),
    "op": (np.float16, np.float32, None),
    "op_eth": (np.float16, np.float32, DataType.float16),
}

#: Rooted/directional collectives (source-side ranks never see the
#: result buffer and vice versa): mixed dtypes require compress_dtype so
#: every rank derives the same wire format — exactly the reference's
#: constraint, whose prepare_call only reconciles mixed operands through
#: a shared (uncompressed, compressed) arithcfg (accl.cpp:1338-1367).
#: combo -> (source-side dtype, sink-side dtype, compress_dtype).
ROOTED_COMBOS = {
    "none": (np.float32, np.float32, None),
    "eth": (np.float32, np.float32, DataType.float16),
    "op": (np.float16, np.float32, DataType.float16),
    "op_eth": (np.float32, np.float16, DataType.float16),
}

combo_ids = list(COMBOS)
size_ids = list(SIZES)


@pytest.fixture(scope="module")
def world():
    with EmuWorld(NRANKS, egr_rx_buf_size=RX_BUF,
                  max_eager_size=MAX_EAGER,
                  max_rendezvous_size=1 << 20) as w:
        yield w


def _data(count, rank, dtype, salt=0):
    rng = np.random.default_rng(77 + rank + salt * 131)
    # f16-held operands quantize on creation; expectations are computed
    # from the values actually stored (like the reference computing from
    # op_buf contents)
    return rng.standard_normal(count).astype(np.float32).astype(
        dtype).astype(np.float32)


def _check(got, want):
    np.testing.assert_allclose(np.asarray(got, np.float32), want,
                               rtol=RTOL, atol=ATOL)


def _params(metafunc_ids=None):
    return pytest.mark.parametrize(
        "combo,size",
        [(c, s) for c in combo_ids for s in size_ids],
        ids=[f"{c}-{s}" for c in combo_ids for s in size_ids])


@_params()
def test_sendrecv(world, combo, size):
    op_dt, res_dt, comp = ROOTED_COMBOS[combo]
    count = SIZES[size]

    def fn(accl, rank):
        nxt, prv = (rank + 1) % NRANKS, (rank - 1) % NRANKS
        src = accl.create_buffer_like(_data(count, rank, op_dt).astype(op_dt))
        dst = accl.create_buffer(count, res_dt)
        # async send + sync recv: a rendezvous send completes only once
        # the peer posts its landing address (fw rendezvous_get_addr)
        req = accl.send(src, count, nxt, tag=7, compress_dtype=comp,
                        run_async=True)
        accl.recv(dst, count, prv, tag=7, compress_dtype=comp)
        assert req.wait(timeout=30.0)
        req.check()
        _check(dst.host, _data(count, prv, op_dt))

    world.run(fn)


@_params()
def test_bcast(world, combo, size):
    op_dt, res_dt, comp = ROOTED_COMBOS[combo]
    count = SIZES[size]
    root = 1

    def fn(accl, rank):
        # root holds the operand dtype; leaves land in the result dtype
        # (per-operand algebra: OP0_COMPRESSED at root, RES at leaves)
        dt = op_dt if rank == root else res_dt
        if rank == root:
            buf = accl.create_buffer_like(_data(count, root, op_dt).astype(dt))
        else:
            buf = accl.create_buffer(count, dt)
        accl.bcast(buf, count, root, compress_dtype=comp)
        _check(buf.host, _data(count, root, op_dt))

    world.run(fn)


@_params()
def test_scatter(world, combo, size):
    op_dt, res_dt, comp = ROOTED_COMBOS[combo]
    count = SIZES[size]
    root = 2

    def fn(accl, rank):
        if rank == root:
            full = np.concatenate(
                [_data(count, r, op_dt) for r in range(NRANKS)])
            send = accl.create_buffer_like(full.astype(op_dt))
        else:
            send = accl.create_buffer(count * NRANKS, op_dt)
        recv = accl.create_buffer(count, res_dt)
        accl.scatter(send, recv, count, root, compress_dtype=comp)
        _check(recv.host, _data(count, rank, op_dt))

    world.run(fn)


@_params()
def test_gather(world, combo, size):
    op_dt, res_dt, comp = ROOTED_COMBOS[combo]
    count = SIZES[size]
    root = 0

    def fn(accl, rank):
        send = accl.create_buffer_like(_data(count, rank, op_dt).astype(op_dt))
        recv = accl.create_buffer(count * NRANKS, res_dt)
        accl.gather(send, recv, count, root, compress_dtype=comp)
        if rank == root:
            want = np.concatenate(
                [_data(count, r, op_dt) for r in range(NRANKS)])
            _check(recv.host, want)

    world.run(fn)


@_params()
def test_allgather(world, combo, size):
    op_dt, res_dt, comp = COMBOS[combo]
    count = SIZES[size]

    def fn(accl, rank):
        send = accl.create_buffer_like(_data(count, rank, op_dt).astype(op_dt))
        recv = accl.create_buffer(count * NRANKS, res_dt)
        accl.allgather(send, recv, count, compress_dtype=comp)
        want = np.concatenate([_data(count, r, op_dt) for r in range(NRANKS)])
        _check(recv.host, want)

    world.run(fn)


@_params()
def test_reduce(world, combo, size):
    op_dt, res_dt, comp = COMBOS[combo]
    count = SIZES[size]
    root = 3

    def fn(accl, rank):
        send = accl.create_buffer_like(_data(count, rank, op_dt).astype(op_dt))
        recv = accl.create_buffer(count, res_dt)
        accl.reduce(send, recv, count, root, ReduceFunction.SUM,
                    compress_dtype=comp)
        if rank == root:
            want = sum(_data(count, r, op_dt) for r in range(NRANKS))
            _check(recv.host, want)

    world.run(fn)


@_params()
def test_allreduce(world, combo, size):
    op_dt, res_dt, comp = COMBOS[combo]
    count = SIZES[size]

    def fn(accl, rank):
        send = accl.create_buffer_like(_data(count, rank, op_dt).astype(op_dt))
        recv = accl.create_buffer(count, res_dt)
        accl.allreduce(send, recv, count, ReduceFunction.SUM,
                       compress_dtype=comp)
        want = sum(_data(count, r, op_dt) for r in range(NRANKS))
        _check(recv.host, want)

    world.run(fn)


@_params()
def test_reduce_scatter(world, combo, size):
    op_dt, res_dt, comp = COMBOS[combo]
    count = SIZES[size]

    def fn(accl, rank):
        full = np.concatenate([_data(count, rank, op_dt, salt=k)
                               for k in range(NRANKS)])
        send = accl.create_buffer_like(full.astype(op_dt))
        recv = accl.create_buffer(count, res_dt)
        accl.reduce_scatter(send, recv, count, ReduceFunction.SUM,
                            compress_dtype=comp)
        want = sum(_data(count, r, op_dt, salt=rank) for r in range(NRANKS))
        _check(recv.host, want)

    world.run(fn)


@pytest.mark.parametrize("size", size_ids)
def test_alltoall_mixed_operands(world, size):
    # alltoall has no compress_dtype in the reference API; per-operand
    # compression still applies through mixed buffer dtypes
    count = SIZES[size]

    def fn(accl, rank):
        full = np.concatenate([_data(count, rank, np.float16, salt=k)
                               for k in range(NRANKS)])
        send = accl.create_buffer_like(full.astype(np.float16))
        recv = accl.create_buffer(count * NRANKS, np.float32)
        accl.alltoall(send, recv, count)
        want = np.concatenate(
            [_data(count, r, np.float16, salt=rank) for r in range(NRANKS)])
        _check(recv.host, want)

    world.run(fn)


# ---------------------------------------------------------------------------
# per-operand combine variants (reference per-operand flag derivation,
# accl.cpp:1310-1335: OP1_COMPRESSED and RES_COMPRESSED)
# ---------------------------------------------------------------------------
def test_combine_op1_compressed(world):
    def fn(accl, rank):
        a = accl.create_buffer_like(_data(64, rank, np.float32))
        b = accl.create_buffer_like(_data(64, rank, np.float16,
                                          salt=1).astype(np.float16))
        res = accl.create_buffer(64, np.float32)
        accl.combine(64, ReduceFunction.SUM, a, b, res)
        want = _data(64, rank, np.float32) + _data(64, rank, np.float16,
                                                   salt=1)
        _check(res.host, want)

    world.run(fn)


def test_combine_res_compressed(world):
    def fn(accl, rank):
        a = accl.create_buffer_like(_data(64, rank, np.float32))
        b = accl.create_buffer_like(_data(64, rank, np.float32, salt=1))
        res = accl.create_buffer(64, np.float16)
        accl.combine(64, ReduceFunction.MAX, a, b, res)
        want = np.maximum(_data(64, rank, np.float32),
                          _data(64, rank, np.float32, salt=1))
        _check(res.host.astype(np.float32), want)

    world.run(fn)


def test_copy_compress_decompress(world):
    # copy f32 -> f16 buffer exercises the compressor lane; the round
    # trip exercises the decompressor (dma_mover lane routing)
    def fn(accl, rank):
        src = accl.create_buffer_like(_data(64, rank, np.float32))
        mid = accl.create_buffer(64, np.float16)
        back = accl.create_buffer(64, np.float32)
        accl.copy(src, mid, 64)
        accl.copy(mid, back, 64)
        _check(back.host, _data(64, rank, np.float32))

    world.run(fn)


# ---------------------------------------------------------------------------
# mem<->stream compressed variants (reference: test_reduce_stream2mem /
# _mem2stream with compression dtype variants, test.cpp:813-910)
# ---------------------------------------------------------------------------
def test_reduce_stream2mem_compressed(world):
    from accl_tpu import StreamFlags

    count, root = 64, 1

    def fn(accl, rank):
        data = _data(count, rank, np.float32)
        accl.device.push_krnl(data.astype(np.float32))
        recv = accl.create_buffer(count, np.float32)
        accl.reduce(None, recv, count, root, ReduceFunction.SUM,
                    stream_flags=StreamFlags.OP0_STREAM,
                    compress_dtype=DataType.float16)
        if rank == root:
            want = sum(_data(count, r, np.float32) for r in range(NRANKS))
            _check(recv.host, want)

    world.run(fn)


def test_reduce_mem2stream_compressed(world):
    from accl_tpu import StreamFlags

    count, root, strm = 64, 2, 10

    def fn(accl, rank):
        send = accl.create_buffer_like(_data(count, rank, np.float32))
        accl.reduce(send, None, count, root, ReduceFunction.SUM,
                    stream_flags=StreamFlags.RES_STREAM, stream_id=strm,
                    compress_dtype=DataType.float16)
        if rank == root:
            raw = accl.device.pop_stream(strm, count * 4)
            assert raw is not None, "no stream payload delivered"
            got = np.frombuffer(raw, np.float32)
            want = sum(_data(count, r, np.float32) for r in range(NRANKS))
            _check(got, want)

    world.run(fn)


# ---------------------------------------------------------------------------
# bf16 wire pair (TPU-native extension lane)
# ---------------------------------------------------------------------------
def test_allreduce_bf16_wire(world):
    try:
        import ml_dtypes  # noqa: F401
    except ImportError:
        pytest.skip("ml_dtypes unavailable")
    count = 256

    def fn(accl, rank):
        send = accl.create_buffer_like(_data(count, rank, np.float32))
        recv = accl.create_buffer(count, np.float32)
        accl.allreduce(send, recv, count, ReduceFunction.SUM,
                       compress_dtype=DataType.bfloat16)
        want = sum(_data(count, r, np.float32) for r in range(NRANKS))
        # bf16 has ~3 decimal digits less mantissa than f16
        np.testing.assert_allclose(recv.host, want, rtol=0.05, atol=0.3)

    world.run(fn)


# ---------------------------------------------------------------------------
# TPU backend leg: the same flag combinations over the gang scheduler +
# XLA collectives (the compiled quantize/dequantize steps in
# backends/tpu.py _run_collective / _collective_fn)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def tpu_world():
    from accl_tpu.backends.tpu import TpuWorld

    with TpuWorld(NRANKS) as w:
        yield w


@pytest.mark.parametrize("combo", combo_ids)
def test_tpu_allreduce_combos(tpu_world, combo):
    op_dt, res_dt, comp = COMBOS[combo]
    count = 64

    def fn(accl, rank):
        send = accl.create_buffer_like(_data(count, rank, op_dt).astype(op_dt))
        recv = accl.create_buffer(count, res_dt)
        accl.allreduce(send, recv, count, ReduceFunction.SUM,
                       compress_dtype=comp)
        want = sum(_data(count, r, op_dt) for r in range(NRANKS))
        _check(recv.host, want)

    tpu_world.run(fn)


@pytest.mark.parametrize("combo", ["eth", "op", "op_eth"])
def test_tpu_bcast_gather_combos(tpu_world, combo):
    op_dt, res_dt, comp = ROOTED_COMBOS[combo]
    count = 64
    root = 1

    def fn(accl, rank):
        dt = op_dt if rank == root else res_dt
        if rank == root:
            buf = accl.create_buffer_like(_data(count, root, op_dt).astype(dt))
        else:
            buf = accl.create_buffer(count, dt)
        accl.bcast(buf, count, root, compress_dtype=comp)
        _check(buf.host, _data(count, root, op_dt))
        send = accl.create_buffer_like(
            _data(count, rank, op_dt, salt=3).astype(op_dt))
        recv = accl.create_buffer(count * NRANKS, res_dt)
        accl.gather(send, recv, count, root, compress_dtype=comp)
        if rank == root:
            want = np.concatenate(
                [_data(count, r, op_dt, salt=3) for r in range(NRANKS)])
            _check(recv.host, want)

    tpu_world.run(fn)


def test_tpu_sendrecv_mixed(tpu_world):
    count = 64

    def fn(accl, rank):
        nxt, prv = (rank + 1) % NRANKS, (rank - 1) % NRANKS
        src = accl.create_buffer_like(
            _data(count, rank, np.float16).astype(np.float16))
        dst = accl.create_buffer(count, np.float32)
        req = accl.send(src, count, nxt, tag=9,
                        compress_dtype=DataType.float16, run_async=True)
        accl.recv(dst, count, prv, tag=9, compress_dtype=DataType.float16)
        assert req.wait(timeout=30.0)
        req.check()
        _check(dst.host, _data(count, prv, np.float16))

    tpu_world.run(fn)


def test_tpu_allreduce_bf16_wire(tpu_world):
    # the bf16 pair must roundtrip through bfloat16 (range ~3e38), not
    # float16 (range 65504): large magnitudes survive the wire hop
    count = 64

    def fn(accl, rank):
        data = _data(count, rank, np.float32) * 1.0e6
        send = accl.create_buffer_like(data)
        recv = accl.create_buffer(count, np.float32)
        accl.allreduce(send, recv, count, ReduceFunction.SUM,
                       compress_dtype=DataType.bfloat16)
        want = sum(_data(count, r, np.float32) * 1.0e6 for r in range(NRANKS))
        assert np.all(np.isfinite(recv.host)), "f16 overflow on bf16 wire"
        np.testing.assert_allclose(recv.host, want, rtol=0.05,
                                   atol=0.3e6)

    tpu_world.run(fn)
