"""Tests for the parallelism strategies layer on the 8-device CPU mesh:
ring attention + Ulysses SP vs dense attention, DP gradient sync, ZeRO
shard/unshard, TP linears, GPipe pipeline, MoE dispatch/combine."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from accl_tpu.parallel import (
    column_parallel,
    expert_combine,
    expert_dispatch,
    make_mesh,
    pipeline_apply,
    ring_attention,
    row_parallel,
    sync_gradients,
    ulysses_attention,
    zero_shard_gradients,
    zero_unshard_params,
)
from accl_tpu.parallel.ring_attention import _dense_attention


def _rand(shape, seed=0):
    return np.random.default_rng(seed).standard_normal(shape).astype(np.float32)


# ---------------------------------------------------------------------------
# sequence parallelism
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    SP, B, T, H, D = 4, 2, 32, 4, 16
    mesh = make_mesh(sp=SP)
    q, k, v = (_rand((B, T, H, D), s) for s in (1, 2, 3))

    def shard_seq(x):
        # [B, T, H, D] -> [SP, B, T/SP, H, D] rank-major sequence shards
        return np.stack(np.split(x, SP, axis=1))

    def body(qb, kb, vb):
        return ring_attention(qb[0], kb[0], vb[0], axis="sp",
                              causal=causal)[None]

    f = shard_map(body, mesh=mesh, in_specs=(P("sp", None, None, None, None),) * 3,
                  out_specs=P("sp", None, None, None, None))
    out = np.asarray(jax.jit(f)(
        *(jnp.asarray(shard_seq(x)) for x in (q, k, v))))
    got = np.concatenate(list(out), axis=1)  # reassemble sequence
    exp = np.asarray(_dense_attention(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), causal=causal))
    np.testing.assert_allclose(got, exp, rtol=2e-4, atol=2e-4)


def test_ulysses_matches_dense():
    SP, B, T, H, D = 4, 2, 32, 8, 16
    mesh = make_mesh(sp=SP)
    q, k, v = (_rand((B, T, H, D), s) for s in (4, 5, 6))

    def shard_seq(x):
        return np.stack(np.split(x, SP, axis=1))

    def body(qb, kb, vb):
        return ulysses_attention(qb[0], kb[0], vb[0], axis="sp",
                                 causal=True)[None]

    f = shard_map(body, mesh=mesh,
                  in_specs=(P("sp", None, None, None, None),) * 3,
                  out_specs=P("sp", None, None, None, None))
    out = np.asarray(jax.jit(f)(
        *(jnp.asarray(shard_seq(x)) for x in (q, k, v))))
    got = np.concatenate(list(out), axis=1)
    exp = np.asarray(_dense_attention(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), causal=True))
    np.testing.assert_allclose(got, exp, rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# data parallel + ZeRO
# ---------------------------------------------------------------------------
def test_sync_gradients_and_compression():
    DP = 8
    mesh = make_mesh(dp=DP)
    g = _rand((DP, 40), 7)
    x = jax.device_put(jnp.asarray(g), NamedSharding(mesh, P("dp", None)))

    def body(gb):
        tree = {"w": gb[0]}
        out = sync_gradients(tree, "dp", mean=True)
        return out["w"][None]

    f = shard_map(body, mesh=mesh, in_specs=P("dp", None),
                  out_specs=P("dp", None))
    out = np.asarray(jax.jit(f)(x))
    np.testing.assert_allclose(out[0], g.mean(axis=0), rtol=1e-5, atol=1e-6)

    def body_c(gb):
        return sync_gradients({"w": gb[0]}, "dp", compress="bf16",
                              mean=True)["w"][None]

    fc = shard_map(body_c, mesh=mesh, in_specs=P("dp", None),
                   out_specs=P("dp", None))
    outc = np.asarray(jax.jit(fc)(x))
    np.testing.assert_allclose(outc[0], g.mean(axis=0), rtol=2e-2, atol=2e-2)


def test_zero_shard_roundtrip():
    DP = 4
    mesh = make_mesh(dp=DP)
    g = _rand((DP, 30), 8)  # 30 not divisible by 4 -> padding path
    x = jax.device_put(jnp.asarray(g), NamedSharding(mesh, P("dp", None)))

    def body(gb):
        tree = {"w": gb[0]}
        shards = zero_shard_gradients(tree, "dp")
        full = zero_unshard_params(shards, {"w": (30,)}, "dp")
        return full["w"][None]

    f = shard_map(body, mesh=mesh, in_specs=P("dp", None),
                  out_specs=P("dp", None))
    out = np.asarray(jax.jit(f)(x))
    np.testing.assert_allclose(out[0], g.sum(axis=0), rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# tensor parallel
# ---------------------------------------------------------------------------
def test_tp_column_then_row():
    TP, B, Din, Dmid, Dout = 4, 8, 32, 64, 16
    mesh = make_mesh(tp=TP)
    x = _rand((B, Din), 9)
    w1 = _rand((Din, Dmid), 10)
    w2 = _rand((Dmid, Dout), 11)
    w1s = np.stack(np.split(w1, TP, axis=1))  # column shards
    w2s = np.stack(np.split(w2, TP, axis=0))  # row shards

    def body(w1b, w2b):
        h = column_parallel(jnp.asarray(x), w1b[0], axis="tp")
        h = jax.nn.relu(h)
        y = row_parallel(h, w2b[0], axis="tp")
        return y[None]

    f = shard_map(body, mesh=mesh,
                  in_specs=(P("tp", None, None), P("tp", None, None)),
                  out_specs=P("tp", None, None))
    out = np.asarray(jax.jit(f)(jnp.asarray(w1s), jnp.asarray(w2s)))
    exp = np.maximum(x @ w1, 0) @ w2
    np.testing.assert_allclose(out[0], exp, rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# pipeline parallel
# ---------------------------------------------------------------------------
def test_pipeline_matches_sequential():
    PP, M, B, D = 4, 6, 4, 8
    mesh = make_mesh(pp=PP)
    ws = _rand((PP, D, D), 12) * 0.5
    xs = _rand((M, B, D), 13)

    def stage_fn(w, x):
        return jnp.tanh(x @ w)

    def body(wb):
        return pipeline_apply(stage_fn, wb[0], jnp.asarray(xs), axis="pp")[None]

    f = shard_map(body, mesh=mesh, in_specs=P("pp", None, None),
                  out_specs=P("pp", None, None, None))
    out = np.asarray(jax.jit(f)(jnp.asarray(ws)))
    # sequential reference
    exp = xs.astype(np.float32)
    for s in range(PP):
        exp = np.tanh(exp @ ws[s])
    np.testing.assert_allclose(out[PP - 1], exp, rtol=1e-4, atol=1e-4)
    assert np.all(out[0] == 0)  # non-final stages emit zeros


# ---------------------------------------------------------------------------
# expert parallel
# ---------------------------------------------------------------------------
def test_moe_dispatch_combine():
    EP, N, D = 4, 16, 8
    mesh = make_mesh(ep=EP)
    xs = _rand((EP, N, D), 14)
    rng = np.random.default_rng(15)
    assign = rng.integers(0, EP, size=(EP, N)).astype(np.int32)
    scales = np.arange(1, EP + 1, dtype=np.float32)  # expert e: x * (e+1)

    def body(xb, ab):
        ep_rank = jax.lax.axis_index("ep")
        inp, info = expert_dispatch(xb[0], ab[0], axis="ep", capacity=N)
        y = inp * (ep_rank + 1).astype(jnp.float32)  # this member's expert
        out = expert_combine(y, info, axis="ep")
        return out[None]

    f = shard_map(body, mesh=mesh,
                  in_specs=(P("ep", None, None), P("ep", None)),
                  out_specs=P("ep", None, None))
    out = np.asarray(jax.jit(f)(jnp.asarray(xs), jnp.asarray(assign)))
    exp = xs * scales[assign][..., None]
    np.testing.assert_allclose(out, exp, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_flash_impl_matches_dense(causal):
    # the flash-backed ring schedule (lse-weighted shard fold over the
    # Pallas kernel) must agree with the dense-ring reference; the CPU
    # rung needs check_vma=False for the Pallas HLO interpreter inside
    # shard_map (jax vma/dynamic_slice limitation)
    import jax

    from accl_tpu.parallel.mesh import make_mesh

    P_sp = 4
    mesh = make_mesh(sp=P_sp)
    B, Tl, H, D = 2, 16, 2, 16
    rng = np.random.default_rng(11)
    q, k, v = (jnp.asarray(rng.standard_normal((B, P_sp * Tl, H, D)),
                           jnp.float32) for _ in range(3))

    spec = P(None, "sp", None, None)
    fn = jax.jit(jax.shard_map(
        lambda a, b, c: ring_attention(a, b, c, axis="sp", causal=causal,
                                       impl="flash"),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
        check_vma=False))
    got = np.asarray(fn(q, k, v))
    want = np.asarray(_dense_attention(q, k, v, causal=causal))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_zigzag_indices_roundtrip():
    from accl_tpu.parallel.ring_attention import zigzag_indices, zigzag_indices_inverse

    T, Psp = 64, 4
    perm = np.asarray(zigzag_indices(T, Psp))
    inv = np.asarray(zigzag_indices_inverse(T, Psp))
    x = np.arange(T)
    np.testing.assert_array_equal(x[perm][inv], x)
    # rank i's shard holds chunks i and 2P-1-i
    C = T // (2 * Psp)
    for i in range(Psp):
        shard = perm[i * 2 * C:(i + 1) * 2 * C]
        np.testing.assert_array_equal(shard[:C], np.arange(i * C, (i + 1) * C))
        j = 2 * Psp - 1 - i
        np.testing.assert_array_equal(shard[C:], np.arange(j * C, (j + 1) * C))


@pytest.mark.parametrize("impl,P_sp", [("dense", 2), ("dense", 4),
                                       ("dense", 8), ("flash", 2),
                                       ("flash", 4), ("flash", 8)])
def test_ring_attention_zigzag_matches_dense(impl, P_sp):
    # the load-balanced causal schedule must be EXACTLY the same math
    # at every ring size (the chunk-liveness algebra is P-dependent):
    # permute the global sequence into zigzag order, run the zigzag
    # ring, un-permute, compare to global dense causal attention
    import jax

    from accl_tpu.parallel.mesh import make_mesh
    from accl_tpu.parallel.ring_attention import zigzag_indices, zigzag_indices_inverse

    mesh = make_mesh(sp=P_sp)
    B, Tl, H, D = 2, 16, 2, 16
    T = P_sp * Tl
    rng = np.random.default_rng(12)
    q, k, v = (jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
               for _ in range(3))
    perm = zigzag_indices(T, P_sp)
    inv = zigzag_indices_inverse(T, P_sp)

    spec = P(None, "sp", None, None)
    fn = jax.jit(jax.shard_map(
        lambda a, b, c: ring_attention(a, b, c, axis="sp", causal=True,
                                       impl=impl, schedule="zigzag"),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
        check_vma=False))
    got_z = fn(q[:, perm], k[:, perm], v[:, perm])
    got = np.asarray(got_z[:, inv])
    want = np.asarray(_dense_attention(q, k, v, causal=True))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_ring_attention_zigzag_rejects_non_causal():
    import jax

    from accl_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(sp=2)
    q = jnp.zeros((1, 8, 2, 8), jnp.float32)
    with pytest.raises(ValueError, match="causal"):
        jax.shard_map(
            lambda a: ring_attention(a, a, a, axis="sp", causal=False,
                                     schedule="zigzag"),
            mesh=mesh, in_specs=P(None, "sp", None, None),
            out_specs=P(None, "sp", None, None), check_vma=False)(q)


def test_ulysses_flash_attn_fn_matches_dense():
    # the flash kernel as ulysses' inner attention (the TPU default)
    # must match the dense inner attention; exercised explicitly on the
    # CPU rung via attn_fn with interpret mode
    import functools

    import jax

    from accl_tpu.ops.flash import flash_attention
    from accl_tpu.parallel.mesh import make_mesh
    from accl_tpu.parallel.ring_attention import ulysses_attention

    P_sp = 4
    mesh = make_mesh(sp=P_sp)
    B, Tl, H, D = 2, 16, 4, 16
    rng = np.random.default_rng(13)
    q, k, v = (jnp.asarray(rng.standard_normal((B, P_sp * Tl, H, D)),
                           jnp.float32) for _ in range(3))
    spec = P(None, "sp", None, None)

    def run(attn_fn):
        fn = jax.jit(jax.shard_map(
            lambda a, b, c: ulysses_attention(a, b, c, axis="sp",
                                              causal=True, attn_fn=attn_fn),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
            check_vma=False))
        return np.asarray(fn(q, k, v))

    flash_fn = functools.partial(flash_attention, causal=True,
                                 mxu_dtype=jnp.float32, interpret=True)
    got = run(flash_fn)
    # explicit dense baseline: run(None) would resolve to flash on a
    # TPU host and compare flash against itself
    want = run(functools.partial(_dense_attention, causal=True))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_ring_attention_flash_opts_passthrough():
    # flash_opts forwards the chip-tuned resident-schedule options
    # (q_tiles / fuse_denom) into every per-hop kernel call — results
    # must stay dense-exact through both ring schedules
    import jax

    from accl_tpu.parallel.mesh import make_mesh
    from accl_tpu.parallel.ring_attention import zigzag_indices, zigzag_indices_inverse

    P_sp = 4
    mesh = make_mesh(sp=P_sp)
    B, Tl, H, D = 1, 16, 2, 16
    T = P_sp * Tl
    rng = np.random.default_rng(31)
    q, k, v = (jnp.asarray(rng.standard_normal((B, T, H, D)),
                           jnp.float32) for _ in range(3))
    opts = {"q_tiles": 2, "fuse_denom": True}
    spec = P(None, "sp", None, None)

    fn = jax.jit(jax.shard_map(
        lambda a, b, c: ring_attention(a, b, c, axis="sp", causal=True,
                                       impl="flash", flash_opts=opts),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
        check_vma=False))
    got = np.asarray(fn(q, k, v))
    want = np.asarray(_dense_attention(q, k, v, causal=True))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    perm = zigzag_indices(T, P_sp)
    inv = zigzag_indices_inverse(T, P_sp)
    fz = jax.jit(jax.shard_map(
        lambda a, b, c: ring_attention(a, b, c, axis="sp", causal=True,
                                       impl="flash", schedule="zigzag",
                                       flash_opts=opts),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
        check_vma=False))
    gz = np.asarray(fz(q[:, perm], k[:, perm], v[:, perm])[:, inv])
    np.testing.assert_allclose(gz, want, rtol=2e-4, atol=2e-4)


def test_ring_attention_flash_trains():
    # on real TPU the SP train path defaults to impl="flash" — the
    # kernel's custom VJP must produce dense-exact gradients through
    # the lse-weighted ring merge (a non-differentiable kernel would
    # break training exactly where CPU CI can't see it)
    import jax

    from accl_tpu.parallel.mesh import make_mesh

    P_sp = 4
    mesh = make_mesh(sp=P_sp)
    B, Tl, H, D = 1, 32, 2, 16
    rng = np.random.default_rng(41)
    q, k, v = (jnp.asarray(rng.standard_normal((B, P_sp * Tl, H, D)),
                           jnp.float32) for _ in range(3))
    spec = P(None, "sp", None, None)

    def mkloss(impl):
        fn = jax.shard_map(
            lambda a, b, c: ring_attention(a, b, c, axis="sp",
                                           causal=True, impl=impl),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
            check_vma=False)
        return lambda a, b, c: jnp.sum(fn(a, b, c) ** 2)

    gf = jax.jit(jax.grad(mkloss("flash"), argnums=(0, 1, 2)))(q, k, v)
    gd = jax.jit(jax.grad(mkloss("dense"), argnums=(0, 1, 2)))(q, k, v)
    for name, a, b in zip("qkv", gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"d{name}")


def test_ring_attention_zigzag_flash_trains():
    # the zigzag schedule's flash path (lax.switch branches + fori_loop
    # hops + lse merges) must also be reverse-differentiable — this is
    # the exact program the load-balanced SP train step runs on real
    # TPU hardware
    import jax

    from accl_tpu.parallel.mesh import make_mesh
    from accl_tpu.parallel.ring_attention import zigzag_indices

    P_sp = 4
    mesh = make_mesh(sp=P_sp)
    B, Tl, H, D = 1, 32, 2, 16
    T = P_sp * Tl
    rng = np.random.default_rng(47)
    q, k, v = (jnp.asarray(rng.standard_normal((B, T, H, D)),
                           jnp.float32) for _ in range(3))
    perm = zigzag_indices(T, P_sp)
    spec = P(None, "sp", None, None)

    def mkloss(impl):
        fn = jax.shard_map(
            lambda a, b, c: ring_attention(a, b, c, axis="sp",
                                           causal=True, impl=impl,
                                           schedule="zigzag"),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
            check_vma=False)
        return lambda a, b, c: jnp.sum(
            fn(a[:, perm], b[:, perm], c[:, perm]) ** 2)

    gf = jax.jit(jax.grad(mkloss("flash"), argnums=(0, 1, 2)))(q, k, v)
    gd = jax.jit(jax.grad(mkloss("dense"), argnums=(0, 1, 2)))(q, k, v)
    for name, a, b in zip("qkv", gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"d{name}")


def test_ulysses_flash_attn_trains():
    # Ulysses SP with the flash kernel as attn_fn (the backend default
    # on real TPU): the custom VJP must give dense-exact gradients
    # through the alltoall reshards
    import functools

    import jax

    from accl_tpu.ops.flash import flash_attention
    from accl_tpu.parallel.mesh import make_mesh
    from accl_tpu.parallel.ring_attention import ulysses_attention

    P_sp = 4
    mesh = make_mesh(sp=P_sp)
    B, Tl, H, D = 1, 16, 4, 16
    rng = np.random.default_rng(53)
    q, k, v = (jnp.asarray(rng.standard_normal((B, P_sp * Tl, H, D)),
                           jnp.float32) for _ in range(3))
    spec = P(None, "sp", None, None)

    def mkloss(attn_fn):
        fn = jax.shard_map(
            lambda a, b, c: ulysses_attention(a, b, c, axis="sp",
                                              causal=True,
                                              attn_fn=attn_fn),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
            check_vma=False)
        return lambda a, b, c: jnp.sum(fn(a, b, c) ** 2)

    from accl_tpu.parallel.ring_attention import _dense_attention

    flash_fn = functools.partial(flash_attention, causal=True,
                                 mxu_dtype=jnp.float32, interpret=True)
    # explicit dense baseline — attn_fn=None would resolve to flash on
    # a TPU host and compare flash against itself
    dense_fn = functools.partial(_dense_attention, causal=True)
    gf = jax.jit(jax.grad(mkloss(flash_fn), argnums=(0, 1, 2)))(q, k, v)
    gd = jax.jit(jax.grad(mkloss(dense_fn), argnums=(0, 1, 2)))(q, k, v)
    for name, a, b in zip("qkv", gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"d{name}")


@pytest.mark.parametrize("impl", ["dense", "flash"])
@pytest.mark.parametrize("schedule", ["contiguous", "zigzag"])
def test_ring_attention_gqa_matches_expanded(schedule, impl):
    # grouped-query K/V through the ring: the flash hops consume the
    # grouped layout in place (the ring rotates H/G-times-smaller
    # shards); the dense rung expands internally.  Either way the
    # result must match the same ring fed explicitly expanded K/V.
    SP, B, T, H, G, D = 4, 1, 64, 4, 2, 16
    mesh = make_mesh(sp=SP)
    q = _rand((B, T, H, D), 11)
    k, v = (_rand((B, T, G, D), s) for s in (12, 13))
    rep = lambda x: np.repeat(x, H // G, axis=2)

    def shard_seq(x):
        return np.stack(np.split(x, SP, axis=1))

    def make(expanded):
        def body(qb, kb, vb):
            return ring_attention(
                qb[0], kb[0], vb[0], axis="sp", causal=True,
                impl=impl, schedule=schedule)[None]
        kk, vv = (rep(k), rep(v)) if expanded else (k, v)
        f = shard_map(body, mesh=mesh,
                      in_specs=(P("sp", None, None, None, None),) * 3,
                      out_specs=P("sp", None, None, None, None),
                      check_vma=impl != "flash")
        return np.asarray(jax.jit(f)(
            *(jnp.asarray(shard_seq(x)) for x in (q, kk, vv))))

    np.testing.assert_allclose(make(False), make(True),
                               rtol=1e-5, atol=1e-5)


def test_ulysses_gqa_expands_for_custom_attn_fn():
    # a caller-supplied attn_fn is assumed NOT GQA-aware: the grouped
    # head subset must arrive expanded (correctness beats the saving)
    SP, B, T, H, G, D = 4, 1, 32, 8, 4, 16
    mesh = make_mesh(sp=SP)
    q = _rand((B, T, H, D), 41)
    k, v = (_rand((B, T, G, D), s) for s in (42, 43))
    seen = []

    def probe_fn(qx, kx, vx):
        seen.append((qx.shape, kx.shape))
        return _dense_attention(qx, kx, vx, causal=True)

    def body(qb, kb, vb):
        return ulysses_attention(qb[0], kb[0], vb[0], axis="sp",
                                 causal=True, attn_fn=probe_fn)[None]

    f = shard_map(body, mesh=mesh,
                  in_specs=(P("sp", None, None, None, None),) * 3,
                  out_specs=P("sp", None, None, None, None))
    jax.jit(f)(*(jnp.asarray(np.stack(np.split(x, SP, axis=1)))
                 for x in (q, k, v)))
    qshape, kshape = seen[0]
    assert kshape[2] == qshape[2], (qshape, kshape)


def test_ulysses_gqa_matches_expanded():
    # Ulysses GQA: K/V reshard their own (smaller) head axis over the
    # ranks; the grouped full-sequence attention on each head subset
    # must match resharding explicitly expanded K/V
    SP, B, T, H, G, D = 4, 1, 64, 8, 4, 16
    mesh = make_mesh(sp=SP)
    q = _rand((B, T, H, D), 21)
    k, v = (_rand((B, T, G, D), s) for s in (22, 23))
    rep = lambda x: np.repeat(x, H // G, axis=2)

    def shard_seq(x):
        return np.stack(np.split(x, SP, axis=1))

    def make(expanded):
        def body(qb, kb, vb):
            return ulysses_attention(qb[0], kb[0], vb[0], axis="sp",
                                     causal=True)[None]
        kk, vv = (rep(k), rep(v)) if expanded else (k, v)
        f = shard_map(body, mesh=mesh,
                      in_specs=(P("sp", None, None, None, None),) * 3,
                      out_specs=P("sp", None, None, None, None))
        return np.asarray(jax.jit(f)(
            *(jnp.asarray(shard_seq(x)) for x in (q, kk, vv))))

    np.testing.assert_allclose(make(False), make(True),
                               rtol=1e-5, atol=1e-5)


def test_ring_gqa_permutes_grouped_shards():
    # the bandwidth claim, certified at the COMPILED level: with
    # grouped K/V the flash ring's collective-permutes carry the
    # G-head shards (half the bytes at G = H/2), not expanded ones
    import re

    SP, B, T, H, G, D = 4, 1, 64, 4, 2, 16
    mesh = make_mesh(sp=SP)

    def compiled_permute_shapes(g):
        def body(qb, kb, vb):
            return ring_attention(qb[0], kb[0], vb[0], axis="sp",
                                  causal=True, impl="flash")[None]
        f = shard_map(body, mesh=mesh,
                      in_specs=(P("sp", None, None, None, None),) * 3,
                      out_specs=P("sp", None, None, None, None),
                      check_vma=False)
        q = jnp.zeros((SP, B, T // SP, H, D), jnp.float32)
        kv = jnp.zeros((SP, B, T // SP, g, D), jnp.float32)
        hlo = jax.jit(f).lower(q, kv, kv).compile().as_text()
        return set(re.findall(
            r"(f32\[[^\]]+\])[^\n]*collective-permute", hlo))

    assert compiled_permute_shapes(H) == {f"f32[1,16,{H},16]"}
    assert compiled_permute_shapes(G) == {f"f32[1,16,{G},16]"}


def test_ulysses_gqa_aware_attn_fn_keeps_grouped_kv():
    """attn_fn_gqa_aware=True hands the caller's GQA-capable callable
    the GROUPED K/V layout (no expansion — the bandwidth saving), and
    the result still matches the expanded default path (ADVICE r4)."""
    import functools

    import jax

    from accl_tpu.ops.flash import flash_attention
    from accl_tpu.parallel.mesh import make_mesh
    from accl_tpu.parallel.ring_attention import ulysses_attention

    P_sp = 2
    mesh = make_mesh(sp=P_sp)
    B, Tl, H, G, D = 2, 16, 8, 4, 16
    rng = np.random.default_rng(23)
    q = jnp.asarray(rng.standard_normal((B, P_sp * Tl, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, P_sp * Tl, G, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, P_sp * Tl, G, D)), jnp.float32)
    spec = P(None, "sp", None, None)

    seen_kv_heads = []

    def gqa_aware(qq, kk, vv):
        seen_kv_heads.append(kk.shape[2])
        return flash_attention(qq, kk, vv, causal=True,
                               mxu_dtype=jnp.float32, interpret=True)

    def run(**kw):
        fn = jax.jit(jax.shard_map(
            lambda a, b, c: ulysses_attention(a, b, c, axis="sp",
                                              causal=True, **kw),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
            check_vma=False))
        return np.asarray(fn(q, k, v))

    flash_fn = functools.partial(flash_attention, causal=True,
                                 mxu_dtype=jnp.float32, interpret=True)
    want = run(attn_fn=flash_fn)              # default: expanded K/V
    got = run(attn_fn=gqa_aware, attn_fn_gqa_aware=True)
    # grouped layout reached the callable: G/P heads, not H/P
    assert seen_kv_heads and set(seen_kv_heads) == {G // P_sp}
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_ring_attention_flash_opts_static_max():
    """static_max rides flash_opts through the SP ring path (BTHD
    entries gained the option in r5) and matches the dynamic fold."""
    import jax

    from accl_tpu.parallel.mesh import make_mesh
    from accl_tpu.parallel.ring_attention import ring_attention

    mesh = make_mesh(sp=4)
    B, Tl, H, D = 1, 32, 2, 32
    rng = np.random.default_rng(61)
    mk = lambda: jnp.asarray(rng.standard_normal((B, 4 * Tl, H, D)),
                             jnp.float32)
    q, k, v = mk(), mk(), mk()
    spec = P(None, "sp", None, None)

    def run(opts):
        f = jax.jit(jax.shard_map(
            lambda a, b, c: ring_attention(a, b, c, axis="sp",
                                           causal=True, impl="flash",
                                           flash_opts=opts),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
            check_vma=False))
        return np.asarray(f(q, k, v))

    base = run(None)
    sm = run({"static_max": 40.0, "kernel": "resident"})
    np.testing.assert_allclose(sm, base, rtol=2e-4, atol=2e-5)


def _run_windowed_ring(q, k, v, P_sp, window, impl, mesh=None, **kw):
    import jax

    from accl_tpu.parallel.mesh import make_mesh
    from accl_tpu.parallel.ring_attention import ring_attention

    mesh = mesh or make_mesh(sp=P_sp)
    spec = P(None, "sp", None, None)
    f = jax.jit(jax.shard_map(
        lambda a, b, c: ring_attention(a, b, c, axis="sp", causal=True,
                                       impl=impl, window=window, **kw),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
        check_vma=False))
    return np.asarray(f(q, k, v))


@pytest.mark.parametrize("window", [1, 7, 16, 31, 32])
def test_windowed_ring_matches_banded_dense(window):
    """Sliding-window SP (local block + ONE neighbor hop) must equal
    the full-sequence banded dense reference for every window/shard
    phase — including w == T_local (band exactly spans the previous
    shard) and w = 1 (self-attention only)."""
    from accl_tpu.parallel.ring_attention import _dense_attention

    P_sp, B, Tl, H, D = 4, 2, 32, 2, 16
    rng = np.random.default_rng(71)
    mk = lambda: jnp.asarray(rng.standard_normal((B, P_sp * Tl, H, D)),
                             jnp.float32)
    q, k, v = mk(), mk(), mk()
    want = np.asarray(_dense_attention(q, k, v, causal=True,
                                       window=window))
    got = _run_windowed_ring(q, k, v, P_sp, window, "dense")
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
    got_fl = _run_windowed_ring(q, k, v, P_sp, window, "flash",
                                flash_opts={"interpret": True})
    np.testing.assert_allclose(got_fl, want, rtol=2e-4, atol=2e-4)


def test_windowed_ring_gqa_matches_banded_dense():
    from accl_tpu.parallel.ring_attention import _dense_attention, expand_gqa_kv

    P_sp, B, Tl, H, G, D = 4, 1, 32, 4, 2, 16
    rng = np.random.default_rng(72)
    q = jnp.asarray(rng.standard_normal((B, P_sp * Tl, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, P_sp * Tl, G, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, P_sp * Tl, G, D)), jnp.float32)
    ke, ve = expand_gqa_kv(k, v, H)
    want = np.asarray(_dense_attention(q, ke, ve, causal=True, window=9))
    got = _run_windowed_ring(q, k, v, P_sp, 9, "flash",
                             flash_opts={"interpret": True})
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_windowed_ring_grads_match_banded_dense():
    import jax

    from accl_tpu.parallel.mesh import make_mesh
    from accl_tpu.parallel.ring_attention import _dense_attention, ring_attention

    P_sp, B, Tl, H, D, window = 4, 1, 16, 2, 8, 11
    rng = np.random.default_rng(73)
    mk = lambda: jnp.asarray(rng.standard_normal((B, P_sp * Tl, H, D)),
                             jnp.float32)
    q, k, v = mk(), mk(), mk()
    mesh = make_mesh(sp=P_sp)
    spec = P(None, "sp", None, None)

    def loss_ring(q, k, v):
        f = jax.shard_map(
            lambda a, b, c: ring_attention(a, b, c, axis="sp",
                                           causal=True, impl="dense",
                                           window=window),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
            check_vma=False)
        return jnp.sum(f(q, k, v) ** 2)

    g1 = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g2 = jax.grad(lambda a, b, c: jnp.sum(_dense_attention(
        a, b, c, causal=True, window=window) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_windowed_ring_validation():
    from accl_tpu.parallel.ring_attention import ring_attention

    q = jnp.zeros((1, 8, 2, 4), jnp.float32)
    with pytest.raises(ValueError, match="causal"):
        ring_attention(q, q, q, causal=False, window=4, impl="dense")
    with pytest.raises(ValueError, match="contiguous"):
        ring_attention(q, q, q, causal=True, window=4, impl="dense",
                       schedule="zigzag")


def test_ulysses_windowed_attn_fn_matches_banded_dense():
    """Window + Ulysses SP: the full-sequence head-subset layout makes
    windows compose for free via attn_fn — each member runs the banded
    kernel over the whole sequence on its heads."""
    import functools

    import jax

    from accl_tpu.ops.flash import flash_attention
    from accl_tpu.parallel.mesh import make_mesh
    from accl_tpu.parallel.ring_attention import _dense_attention, ulysses_attention

    P_sp, B, Tl, H, D, W = 4, 1, 16, 4, 16, 9
    mesh = make_mesh(sp=P_sp)
    rng = np.random.default_rng(81)
    mk = lambda: jnp.asarray(rng.standard_normal((B, P_sp * Tl, H, D)),
                             jnp.float32)
    q, k, v = mk(), mk(), mk()
    want = np.asarray(_dense_attention(q, k, v, causal=True, window=W))
    spec = P(None, "sp", None, None)
    fn = functools.partial(flash_attention, causal=True, window=W,
                           mxu_dtype=jnp.float32, interpret=True)
    f = jax.jit(jax.shard_map(
        lambda a, b, c: ulysses_attention(a, b, c, axis="sp",
                                          causal=True, attn_fn=fn),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
        check_vma=False))
    got = np.asarray(f(q, k, v))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
