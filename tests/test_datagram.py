"""Protocol corpus over the datagram rung: fragmentation, out-of-order
delivery, interleaved reassembly, loss.

Reference analog: the UDP protocol stack — packetizer splitting segments
into MTU datagrams, depacketizer + rxbuf_session reassembling interleaved
per-session fragments into rx-pool buffers
(kernels/cclo/hls/eth_intf/udp_depacketizer.cpp:30-180,
rxbuf_offload/rxbuf_session.cpp:1-202).  The emulated rung
(native/src/dgram.hpp) is adversarial by construction: every delivery
batch (reorder_window datagrams) arrives REVERSED, so every multi-
fragment message exercises reassembly out of order and concurrent
messages interleave.  The engine-side protocol machinery — rx-pool seqn
discipline, stream resequencing, reassembly-table eviction — must make
all of it invisible.
"""
import numpy as np
import pytest

from accl_tpu import DataType, ReduceFunction, StreamFlags
from accl_tpu.accl import default_timeout
from accl_tpu.backends.emu import EmuWorld

NRANKS = 4
MTU = 256          # 4 fragments per 1 KB rx segment
RX_BUF = 1024
MAX_EAGER = 4096   # multi-segment eager exists below the rendezvous switch


@pytest.fixture(scope="module")
def world():
    with EmuWorld(NRANKS, transport="dgram", mtu=MTU, reorder_window=8,
                  egr_rx_buf_size=RX_BUF, max_eager_size=MAX_EAGER,
                  max_rendezvous_size=1 << 20) as w:
        yield w


def _data(count, rank, salt=0):
    rng = np.random.default_rng(55 + rank + salt * 131)
    return rng.standard_normal(count).astype(np.float32)


# ---------------------------------------------------------------------------
# reassembly under reorder: single- and multi-fragment, eager + rendezvous
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("count", [16, 255, 256, 257, 1023],
                         ids=["tiny", "seg-1", "seg", "seg+1", "multiseg"])
def test_sendrecv_fragmented(world, count):
    def fn(accl, rank):
        nxt, prv = (rank + 1) % NRANKS, (rank - 1) % NRANKS
        src = accl.create_buffer_like(_data(count, rank, count))
        dst = accl.create_buffer(count, np.float32)
        req = accl.send(src, count, nxt, tag=count, run_async=True)
        accl.recv(dst, count, prv, tag=count)
        assert req.wait(timeout=30.0)
        req.check()
        np.testing.assert_array_equal(dst.host, _data(count, prv, count))

    world.run(fn)


def test_sendrecv_rendezvous_fragmented(world):
    # > MAX_EAGER -> rendezvous one-sided write, fragmented into 17 MTUs
    count = 1088
    def fn(accl, rank):
        nxt, prv = (rank + 1) % NRANKS, (rank - 1) % NRANKS
        src = accl.create_buffer_like(_data(count, rank, 9))
        dst = accl.create_buffer(count, np.float32)
        req = accl.send(src, count, nxt, tag=5, run_async=True)
        accl.recv(dst, count, prv, tag=5)
        assert req.wait(timeout=30.0)
        req.check()
        np.testing.assert_array_equal(dst.host, _data(count, prv, 9))

    world.run(fn)


def test_interleaved_tags(world):
    # two concurrent multi-fragment sends on different tags: both are in
    # flight simultaneously, so their fragments interleave inside the
    # shared reorder window and the reassembler juggles both sessions
    # (recvs follow send order — the seqn contract, see
    # test_fault_injection.py::test_ahead_of_sequence_message_...)
    count = 400
    def fn(accl, rank):
        nxt, prv = (rank + 1) % NRANKS, (rank - 1) % NRANKS
        a = accl.create_buffer_like(_data(count, rank, 1))
        b = accl.create_buffer_like(_data(count, rank, 2))
        ra = accl.create_buffer(count, np.float32)
        rb = accl.create_buffer(count, np.float32)
        qa = accl.send(a, count, nxt, tag=101, run_async=True)
        qb = accl.send(b, count, nxt, tag=102, run_async=True)
        accl.recv(ra, count, prv, tag=101)
        accl.recv(rb, count, prv, tag=102)
        for q in (qa, qb):
            assert q.wait(timeout=30.0)
            q.check()
        np.testing.assert_array_equal(ra.host, _data(count, prv, 1))
        np.testing.assert_array_equal(rb.host, _data(count, prv, 2))

    world.run(fn)


# ---------------------------------------------------------------------------
# collectives over the datagram rung (the protocol matrix runs unchanged)
# ---------------------------------------------------------------------------
def test_allreduce_over_datagrams(world):
    count = 513  # ragged multi-segment, each segment multi-fragment
    def fn(accl, rank):
        s = accl.create_buffer_like(_data(count, rank, 3))
        r = accl.create_buffer(count, np.float32)
        accl.allreduce(s, r, count, ReduceFunction.SUM)
        want = sum(_data(count, k, 3) for k in range(NRANKS))
        np.testing.assert_allclose(r.host, want, rtol=1e-5, atol=1e-5)

    world.run(fn)


def test_allreduce_compressed_over_datagrams(world):
    count = 300
    def fn(accl, rank):
        s = accl.create_buffer_like(_data(count, rank, 4))
        r = accl.create_buffer(count, np.float32)
        accl.allreduce(s, r, count, ReduceFunction.SUM,
                       compress_dtype=DataType.float16)
        want = sum(_data(count, k, 4) for k in range(NRANKS))
        np.testing.assert_allclose(r.host, want, rtol=0.005, atol=0.2)

    world.run(fn)


def test_rooted_collectives_over_datagrams(world):
    count = 320
    def fn(accl, rank):
        buf = accl.create_buffer(count, np.float32)
        if rank == 2:
            buf.host[:] = _data(count, 2, 5)
        accl.bcast(buf, count, root=2)
        np.testing.assert_array_equal(buf.host, _data(count, 2, 5))

        send = accl.create_buffer_like(_data(count, rank, 6))
        recv = accl.create_buffer(count * NRANKS, np.float32)
        accl.gather(send, recv, count, root=1)
        if rank == 1:
            want = np.concatenate([_data(count, k, 6) for k in range(NRANKS)])
            np.testing.assert_array_equal(recv.host, want)

        accl.barrier()

    world.run(fn)


# ---------------------------------------------------------------------------
# stream resequencing: stream-destined messages have their own sequence
# space and ingress reorders them back to FIFO (the engine.cpp seqn
# exemption would silently scramble them on this rung otherwise)
# ---------------------------------------------------------------------------
def test_stream_put_order_survives_reorder(world):
    n, strm, rounds = 96, 11, 6  # each payload = 384 B = 2 fragments

    def fn(accl, rank):
        nxt, prv = (rank + 1) % NRANKS, (rank - 1) % NRANKS
        for i in range(rounds):
            buf = accl.create_buffer_like(
                np.full(n, float(i * 10 + rank), np.float32))
            accl.stream_put(buf, n, nxt, strm)
        # pop in FIFO order: payload i must carry value i*10+prv
        for i in range(rounds):
            raw = accl.device.pop_stream(strm, n * 4)
            assert raw is not None, f"stream payload {i} missing"
            got = np.frombuffer(raw, np.float32)
            assert got[0] == pytest.approx(i * 10 + prv), (
                f"stream payload {i} out of order: {got[0]}")

    world.run(fn)


# ---------------------------------------------------------------------------
# loss: a dropped fragment means the message never reassembles; the
# protocol layer reports a timeout and the world recovers afterwards
# ---------------------------------------------------------------------------
def test_fragment_loss_detected_and_recovered(world):
    count = 256  # 4 fragments

    def fn(accl, rank):
        if rank >= 2:
            return
        if rank == 0:
            world_ref.inject_dgram_fault(EmuWorld.DGRAM_DROP_NEXT)
            src = accl.create_buffer_like(_data(count, 0, 7))
            accl.send(src, count, 1, tag=77)
        else:
            dst = accl.create_buffer(count, np.float32)
            accl.set_timeout(200_000)  # 200 ms budget
            try:
                with pytest.raises(Exception):
                    accl.recv(dst, count, 0, tag=77)
            finally:
                accl.set_timeout(default_timeout())  # module-scoped world

    world_ref = world
    world.run(fn)

    # recovery: the failed recv reported an explicit error (at-most-once,
    # never silent substitution) AND advanced the route cursor past the
    # lost message's whole seqn window, evicting any stranded same-tag
    # tail segments — so the next message on the route matches directly.
    def again(accl, rank):
        if rank >= 2:
            return
        if rank == 0:
            src = accl.create_buffer_like(_data(count, 0, 8))
            accl.send(src, count, 1, tag=78)
        else:
            dst = accl.create_buffer(count, np.float32)
            accl.recv(dst, count, 0, tag=78)
            np.testing.assert_array_equal(dst.host, _data(count, 0, 8))

    world.run(again)


def test_duplicate_fragment_ignored(world):
    count = 256

    def fn(accl, rank):
        if rank >= 2:
            return
        if rank == 0:
            world_ref.inject_dgram_fault(EmuWorld.DGRAM_DUP_NEXT)
            src = accl.create_buffer_like(_data(count, 0, 9))
            accl.send(src, count, 1, tag=79)
        else:
            dst = accl.create_buffer(count, np.float32)
            accl.recv(dst, count, 0, tag=79)
            np.testing.assert_array_equal(dst.host, _data(count, 0, 9))

    world_ref = world
    world.run(fn)


def test_mem2stream_reduce_over_datagrams(world):
    # streamed-result reduce across the reordering rung
    count, root, strm = 128, 0, 13

    def fn(accl, rank):
        send = accl.create_buffer_like(_data(count, rank, 10))
        accl.reduce(send, None, count, root, ReduceFunction.SUM,
                    stream_flags=StreamFlags.RES_STREAM, stream_id=strm)
        if rank == root:
            raw = accl.device.pop_stream(strm, count * 4)
            assert raw is not None
            got = np.frombuffer(raw, np.float32)
            want = sum(_data(count, k, 10) for k in range(NRANKS))
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    world.run(fn)
