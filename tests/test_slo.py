"""Per-tenant SLO observatory (r20): burn-rate math pins for the
SLOTracker (fast-burn fires before slow-burn, cleared keys re-arm,
verdict precedence per tenant), the metrics-registry cardinality guard,
tenant-labeled OpenMetrics families, per-tenant link-matrix slices on
emu AND tpu-interpret, RECEIVE_TIMEOUT flight forensics, and the
perf_doctor --slo / exporter /slo round trips.
"""
import json
import os
import re
import subprocess
import sys
import types

import numpy as np
import pytest

from accl_tpu import ReduceFunction
from accl_tpu.constants import ACCLError
from accl_tpu.observability import health as obs_health
from accl_tpu.observability import metrics as obs_metrics
from accl_tpu.observability import sentinel as obs_sentinel
from accl_tpu.observability import slo as obs_slo

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spec(tenant="decode", collective="allreduce", **axes):
    """A normalized spec dict in load_specs' output shape."""
    s = {"tenant": tenant, "collective": collective, "size_bucket": "*",
         "availability": axes.pop("availability", 0.99)}
    s.update(axes)
    return s


def _tracker(specs, reg, **kw):
    kw.setdefault("fast_window", 2)
    kw.setdefault("slow_window", 8)
    kw.setdefault("fast_burn", 8.0)
    kw.setdefault("slow_burn", 2.0)
    kw.setdefault("min_calls", 4)
    return obs_slo.SLOTracker(specs, registry=reg, **kw)


def _sweep(reg, us, n=10, ok=True, tenant="decode", coll="allreduce",
           nbytes=4096):
    for _ in range(n):
        reg.observe_call(coll, "float32", nbytes, us * 1e3, 4, ok=ok,
                         tenant=tenant)


def _row(tracker, objective="p50_us", tenant="decode"):
    rows = [o for o in tracker.objectives
            if o["objective"] == objective and o["tenant"] == tenant]
    assert len(rows) == 1, tracker.objectives
    return rows[0]


# ---------------------------------------------------------------------------
# burn-rate math pins: the multi-window discipline on a synthetic stream
# ---------------------------------------------------------------------------
def test_fast_burn_fires_before_slow_burn_then_budget_exhausts():
    """p50 objective (budget 0.5, clamped thresholds fast=1.8 slow=1.0):
    a latency cliff pages via the FAST window two sweeps in, while the
    slow window is still below threshold; the cumulative budget then
    drains monotonically to exhaustion."""
    reg = obs_metrics.MetricsRegistry()
    tr = _tracker([_spec(p50_us=256.0)], reg)
    deliveries = []
    tr.subscribe(lambda fs: deliveries.append(fs))

    for _ in range(6):                    # healthy: 100us < 256us ceiling
        _sweep(reg, 100)
        tr.check()
        assert _row(tr)["verdict"] == "ok"
        assert _row(tr)["budget_remaining"] == 1.0

    remaining = []
    verdicts = []
    for _ in range(6):                    # cliff: 1000us > ceiling
        _sweep(reg, 1000)
        tr.check()
        row = _row(tr)
        verdicts.append(row["verdict"])
        remaining.append(row["budget_remaining"])

    # sweep 1 of the cliff: fast window is half healthy — no page yet
    assert verdicts[0] == "ok"
    # sweep 2: fast window all-bad -> burn 2.0 >= clamped 1.8 pages,
    # while the slow burn is still under ITS threshold (fast fired first)
    assert verdicts[1] == "fast_burn"
    # slow catches up later; budget exhausts by cliff sweep 6
    assert verdicts[-1] == "exhausted"
    assert remaining[-1] == 0.0
    assert remaining == sorted(remaining, reverse=True)  # monotonic drain
    assert remaining[0] == pytest.approx(0.7143, abs=1e-3)

    # delivery gating: one page at the fast_burn flip, one re-delivery
    # when the verdict worsened to exhausted — repeats suppressed
    assert len(deliveries) == 2
    assert all(f["kind"] == "slo" for batch in deliveries for f in batch)
    assert deliveries[0][0]["verdict"] == "fast_burn"
    assert deliveries[1][0]["verdict"] == "exhausted"
    snap = reg.snapshot()
    assert snap["counters"]["slo/checks"] == 12
    assert snap["counters"]["slo/findings"] == 2
    assert snap["gauges"]["tenant/decode/health"] == obs_slo.V_EXHAUSTED
    assert snap["gauges"]["tenant/decode/slo_budget_remaining"] == 0.0


def test_slow_burn_threshold_crosses_after_fast():
    """The slow window's burn crosses its (clamped) threshold only once
    half its sweeps are bad — sweeps after the fast page."""
    reg = obs_metrics.MetricsRegistry()
    tr = _tracker([_spec(p50_us=256.0)], reg)
    for _ in range(6):
        _sweep(reg, 100)
        tr.check()
    burns_slow = []
    for _ in range(4):
        _sweep(reg, 1000)
        tr.check()
        burns_slow.append(_row(tr)["burn_slow"])
    # 2 bad of 8 sweeps -> bad_frac 0.25 -> burn 0.5 < 1.0 threshold
    assert burns_slow[1] == pytest.approx(0.5, abs=1e-6)
    assert burns_slow[1] < 1.0
    # 4 bad of 8 -> burn exactly at the clamped slow threshold
    assert burns_slow[3] == pytest.approx(1.0, abs=1e-6)


def test_cleared_keys_rearm_and_redeliver():
    """A finding that clears (healthy sweeps drain both windows) drops
    from the delivered table, so the NEXT violation pages again instead
    of being worsening-gated against the stale severity."""
    reg = obs_metrics.MetricsRegistry()
    tr = _tracker([_spec(p50_us=256.0)], reg)
    deliveries = []
    tr.subscribe(lambda fs: deliveries.append(fs))
    fkey = ("decode", "allreduce", "*", "p50_us")

    for _ in range(6):
        _sweep(reg, 100)
        tr.check()
    for _ in range(2):                    # first violation: one page
        _sweep(reg, 1000)
        tr.check()
    assert len(deliveries) == 1
    assert fkey in tr._delivered

    for _ in range(8):                    # recovery drains both windows
        _sweep(reg, 100)
        tr.check()
    assert _row(tr)["verdict"] == "ok"
    assert fkey not in tr._delivered      # cleared key re-armed

    for _ in range(2):                    # second violation: pages AGAIN
        _sweep(reg, 1000)
        tr.check()
    assert len(deliveries) == 2
    assert deliveries[1][0]["verdict"] == "fast_burn"


def test_verdict_precedence_and_per_tenant_isolation():
    """Two tenants: one driven to exhaustion, one healthy — verdicts,
    gauges, and the labeled accl_health samples stay per-tenant; a
    spec'd tenant with no traffic still reports ok."""
    reg = obs_metrics.MetricsRegistry()
    tr = _tracker([_spec(tenant="a", collective="*", p50_us=4.0),
                   _spec(tenant="b", collective="*", p50_us=256.0),
                   _spec(tenant="ghost", collective="*", p50_us=256.0)],
                  reg)
    for _ in range(3):
        _sweep(reg, 1000, tenant="a")     # every call violates 4us
        _sweep(reg, 100, tenant="b")
        tr.check()
    doc = tr.doc()
    assert doc["tenants"]["a"]["verdict"] == "exhausted"
    assert doc["tenants"]["a"]["budget_remaining"] == 0.0
    assert doc["tenants"]["b"]["verdict"] == "ok"
    assert doc["tenants"]["b"]["budget_remaining"] == 1.0
    assert doc["tenants"]["ghost"]["verdict"] == "ok"   # no traffic
    assert doc["tenants"]["ghost"]["objectives"] == []
    snap = reg.snapshot()
    assert snap["gauges"]["tenant/a/health"] == obs_slo.V_EXHAUSTED
    assert snap["gauges"]["tenant/b/health"] == obs_slo.V_OK

    # every objective row carries the full --ci schema
    for t in doc["tenants"].values():
        for row in t["objectives"]:
            for k in obs_slo.OBJECTIVE_SCHEMA_KEYS:
                assert k in row, (k, row)

    body = reg.to_openmetrics()
    assert obs_metrics.validate_openmetrics(body) == []
    assert re.search(r'^accl_health\{tenant="a"\} 3(\.0)?$', body, re.M)
    assert re.search(r'^accl_health\{tenant="b"\} 0(\.0)?$', body, re.M)
    # the per-tenant health gauge rides accl_health, never its own family
    assert "accl_tenant_a_health" not in body


def test_availability_objective_burns_on_failures_not_latency():
    """ok=False calls never enter the latency histogram (the latency
    SLI is over successful calls) — they burn the AVAILABILITY budget
    instead, which track_errors declares."""
    reg = obs_metrics.MetricsRegistry()
    tr = _tracker([_spec(availability=0.75, p50_us=256.0,
                         track_errors=True)], reg)
    for _ in range(4):
        _sweep(reg, 100)
        tr.check()
    # failures with enormous durations: latency axis must stay blind
    _sweep(reg, 1_000_000, ok=False)
    tr.check()
    lat = _row(tr, "p50_us")
    assert lat["bad_fast"] == 0 and lat["verdict"] == "ok"
    avail = _row(tr, "availability")
    assert avail["bad_fast"] == 10          # the errors, counted
    assert avail["budget_remaining"] < 1.0  # and burning the budget
    _sweep(reg, 1_000_000, ok=False)
    tr.check()
    assert _row(tr, "availability")["verdict"] == "exhausted"
    assert _row(tr, "p50_us")["verdict"] == "ok"


def test_busbw_floor_objective():
    """busbw is a floor, not a ceiling: under floor/2 pages fast, under
    floor bleeds slow, above it is ok — no cumulative budget."""
    reg = obs_metrics.MetricsRegistry()
    # synthetic stream: 1 MiB in 100us -> ~10 GB/s algbw
    tr = _tracker([_spec(busbw_GBps=1000.0),
                   _spec(tenant="fine", busbw_GBps=0.001)], reg)
    for _ in range(2):
        _sweep(reg, 100, nbytes=1 << 20)
        _sweep(reg, 100, nbytes=1 << 20, tenant="fine")
        tr.check()
    row = _row(tr, "busbw_GBps")
    assert row["verdict"] == "fast_burn"     # way under floor/2
    assert row["budget_remaining"] is None   # floors carry no budget
    assert _row(tr, "busbw_GBps", "fine")["verdict"] == "ok"


def test_idle_tenant_burn_decays():
    """A tenant that stops sending still has its windows advance
    (idle_sweep), so a past violation decays instead of pinning the
    verdict forever."""
    reg = obs_metrics.MetricsRegistry()
    tr = _tracker([_spec(p50_us=256.0)], reg)
    for _ in range(6):
        _sweep(reg, 100)
        tr.check()
    for _ in range(2):
        _sweep(reg, 1000)
        tr.check()
    assert _row(tr)["verdict"] == "fast_burn"
    for _ in range(8):                      # silence: no observe_call
        tr.check()
    assert _row(tr)["verdict"] == "ok"
    assert _row(tr)["calls_fast"] == 0


def test_sentinel_subscribers_receive_slo_findings(monkeypatch):
    """One control plane: a live sentinel's subscribers get SLO pages
    too, without subscribing to the tracker themselves."""
    reg = obs_metrics.MetricsRegistry()
    tr = _tracker([_spec(p50_us=4.0)], reg)
    got = []
    monkeypatch.setattr(
        obs_sentinel, "_sentinel",
        types.SimpleNamespace(_subscribers=[lambda fs: got.append(fs)]))
    _sweep(reg, 1000)
    tr.check()
    assert got and got[0][0]["kind"] == "slo"
    assert got[0][0]["tenant"] == "decode"


# ---------------------------------------------------------------------------
# spec loading + env-driven lifecycle
# ---------------------------------------------------------------------------
def _write_spec(tmp_path, doc, name="slo.json"):
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


def _valid_spec_doc():
    return {"format": obs_slo.SLO_SPEC_FORMAT,
            "version": obs_slo.SLO_SPEC_VERSION,
            "slos": [{"tenant": "decode", "collective": "allreduce",
                      "p50_us": 256.0},
                     {"tenant": "prefill", "availability": 0.9,
                      "track_errors": True}]}


def test_load_specs_round_trip_and_defaults(tmp_path):
    specs = obs_slo.load_specs(_write_spec(tmp_path, _valid_spec_doc()))
    assert specs[0]["size_bucket"] == "*"        # default wildcard
    assert specs[0]["availability"] == 0.99      # default availability
    assert specs[1]["collective"] == "*"
    assert specs[1]["track_errors"] is True


@pytest.mark.parametrize("mutate,match", [
    (lambda d: d.update(format="nope"), "not an accl-slo-spec"),
    (lambda d: d.update(version=99), "version"),
    (lambda d: d.update(slos=[]), "non-empty"),
    (lambda d: d["slos"][0].pop("tenant"), "tenant"),
    (lambda d: d["slos"][0].update(availability=1.5), r"\(0, 1\)"),
    (lambda d: d["slos"][0].update(p50_us=-1.0), "must be > 0"),
    (lambda d: d["slos"][0].update({"p50_us": None}) or
     d["slos"][0].pop("p50_us"), "no objective"),
])
def test_load_specs_validation_errors(tmp_path, mutate, match):
    doc = _valid_spec_doc()
    doc["slos"] = doc["slos"][:1]
    mutate(doc)
    with pytest.raises(ValueError, match=match):
        obs_slo.load_specs(_write_spec(tmp_path, doc))


def test_ensure_slo_from_env(tmp_path, monkeypatch):
    obs_slo.stop_slo()
    try:
        monkeypatch.delenv("ACCL_SLO", raising=False)
        assert obs_slo.ensure_slo_from_env() is None   # unset = off
        monkeypatch.setenv("ACCL_SLO", "0")
        assert obs_slo.ensure_slo_from_env() is None   # explicit off
        # a bad spec disables with a warning — never raises at bring-up
        monkeypatch.setenv("ACCL_SLO", str(tmp_path / "missing.json"))
        assert obs_slo.ensure_slo_from_env() is None
        bad = dict(_valid_spec_doc(), format="nope")
        monkeypatch.setenv("ACCL_SLO", _write_spec(tmp_path, bad, "b.json"))
        assert obs_slo.ensure_slo_from_env() is None
        # a good spec arms the singleton, idempotently
        reg = obs_metrics.MetricsRegistry()
        monkeypatch.setenv("ACCL_SLO",
                           _write_spec(tmp_path, _valid_spec_doc()))
        tr = obs_slo.ensure_slo_from_env(reg)
        assert tr is not None and obs_slo.tracker() is tr
        assert obs_slo.ensure_slo_from_env(reg) is tr
        assert tr._thread is None       # ACCL_SLO_INTERVAL_MS=0: no timer
    finally:
        obs_slo.stop_slo()
    assert obs_slo.tracker() is None


# ---------------------------------------------------------------------------
# satellite: the registry's label-cardinality bound
# ---------------------------------------------------------------------------
def test_metrics_cardinality_guard_counts_drops():
    reg = obs_metrics.MetricsRegistry(max_series=8)
    for i in range(30):
        reg.inc(f"series/{i}")
    snap = reg.snapshot()
    admitted = [k for k in snap["counters"] if k.startswith("series/")]
    assert len(admitted) == 8
    assert snap["counters"]["metrics/dropped_series"] == 22
    # existing series keep updating at capacity
    reg.inc("series/0", 5)
    assert reg.snapshot()["counters"]["series/0"] == 6
    # the guard bounds tenant series minting too (hostile label flood)
    for i in range(20):
        reg.observe_call("allreduce", "float32", 64, 1e3, 2,
                         tenant=f"t{i}")
    snap = reg.snapshot()
    assert len(snap["tenant_calls"]) == 0   # registry already full
    assert snap["counters"]["metrics/dropped_series"] > 22


def test_metrics_max_series_env_knob(monkeypatch):
    monkeypatch.setenv("ACCL_METRICS_MAX_SERIES", "16")
    reg = obs_metrics.MetricsRegistry()
    for i in range(40):
        reg.inc(f"series/{i}")
    assert sum(1 for k in reg.snapshot()["counters"]
               if k.startswith("series/")) == 16
    monkeypatch.setenv("ACCL_METRICS_MAX_SERIES", "banana")
    with pytest.raises(ACCLError, match="ACCL_METRICS_MAX_SERIES"):
        obs_metrics.MetricsRegistry()


def test_tenant_families_validate_as_openmetrics():
    reg = obs_metrics.MetricsRegistry()
    _sweep(reg, 100, tenant="decode")
    tr = _tracker([_spec(p50_us=256.0)], reg)
    tr.check()
    body = reg.to_openmetrics()
    assert obs_metrics.validate_openmetrics(body) == []
    assert ('accl_tenant_collective_calls_total{tenant="decode",'
            'collective="allreduce"') in body
    assert "accl_tenant_decode_slo_budget_remaining" in body
    assert "accl_slo_checks_total" in body


# ---------------------------------------------------------------------------
# per-tenant link-matrix slices (emu + tpu-interpret)
# ---------------------------------------------------------------------------
def _tenant_traffic_body(nranks=4, count=64, iters=3):
    def body(accl, rank):
        d = accl.create_communicator(list(range(nranks)),
                                     tenant="decode")
        accl.create_communicator(list(range(nranks)),
                                 tenant="prefill")
        assert accl.tenant_comm_ids("decode") == [d]
        send = accl.create_buffer_like(
            np.arange(count, dtype=np.float32) + rank)
        recv = accl.create_buffer(count, np.float32)
        for _ in range(iters):
            accl.allreduce(send, recv, count, ReduceFunction.SUM,
                           comm_id=d, from_fpga=True, to_fpga=True)
    return body


def _assert_tenant_slices(world):
    md = world.link_matrix(tenant="decode")
    mp = world.link_matrix(tenant="prefill")
    m0 = world.link_matrix()                 # comm 0: saw no traffic
    assert md["tenant"] == "decode"
    total = sum(sum(row) for row in md["fields"]["tx_bytes"])
    assert total > 0, "decode slice must carry the comm's traffic"
    assert sum(sum(row) for row in mp["fields"]["tx_bytes"]) == 0
    assert sum(sum(row) for row in m0["fields"]["tx_bytes"]) == 0
    # the sub-comm spans ranks in identity order: ring traffic lands on
    # right-neighbor links exactly like the comm-0 matrices do
    tx = md["fields"]["tx_msgs"]
    P = md["nranks"]
    assert any(tx[r][(r + 1) % P] > 0 for r in range(P))


def test_tenant_link_matrix_slice_emu():
    from accl_tpu.backends.emu import EmuWorld

    world = EmuWorld(4)
    try:
        world.run(_tenant_traffic_body())
        _assert_tenant_slices(world)
    finally:
        world.close()


def test_tenant_link_matrix_slice_tpu_interpret():
    from accl_tpu.backends.tpu import TpuWorld

    with TpuWorld(4) as world:
        world.run(_tenant_traffic_body(count=32, iters=2))
        _assert_tenant_slices(world)


# ---------------------------------------------------------------------------
# satellite: RECEIVE_TIMEOUT forensics in the flight dump
# ---------------------------------------------------------------------------
def test_flight_timeout_forensics_snapshot():
    from accl_tpu.observability import flight as obs_flight

    rec = obs_flight.FlightRecorder(rank=0, capacity=32)
    rec.set_forensics_sources({
        "link_rows": lambda: [{"comm": 3, "peer": 1, "tx_msgs": 7}],
        "gang_assembly": lambda: (_ for _ in ()).throw(
            RuntimeError("engine gone")),
    })
    r = rec.new_record(7, "allreduce", 3, 0, "float32", 64, 256, 2,
                       True, 1_000, tenant="decode")
    r.finish(obs_flight._RECEIVE_TIMEOUT_BIT, 2_000)
    dump = rec.dump()
    assert dump["records"][0]["tenant"] == "decode"
    assert len(dump["timeout_forensics"]) == 1
    snap = dump["timeout_forensics"][0]
    assert snap["tenant"] == "decode" and snap["collective"] == "allreduce"
    assert snap["link_rows"] == [{"comm": 3, "peer": 1, "tx_msgs": 7}]
    # a dying provider degrades to a note, never breaks the dump
    assert snap["gang_assembly"].startswith("<capture failed")
    # wall-clock stamps alongside the monotonic one (detsched antidote)
    assert snap["wall_clock"] > 0
    assert re.match(r"\d{4}-\d{2}-\d{2}T", snap["wall_clock_iso"])

    # non-timeout failures do NOT snapshot
    r2 = rec.new_record(8, "allgather", 0, 0, "float32", 64, 256, 2,
                        True, 3_000)
    r2.finish(1, 4_000)
    assert len(rec.dump()["timeout_forensics"]) == 1


# ---------------------------------------------------------------------------
# exporter /slo endpoint + perf_doctor --slo round trips
# ---------------------------------------------------------------------------
def test_exporter_slo_endpoint(tmp_path, monkeypatch):
    import urllib.request

    obs_slo.stop_slo()
    obs_health.stop_exporter()
    reg = obs_metrics.MetricsRegistry()
    monkeypatch.setenv("ACCL_SLO", _write_spec(tmp_path, _valid_spec_doc()))
    try:
        tr = obs_slo.ensure_slo_from_env(reg)
        assert tr is not None
        _sweep(reg, 100, tenant="decode")
        exp = obs_health.start_exporter(port=0, registry=reg)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{exp.port}/slo", timeout=10) as resp:
            doc = json.loads(resp.read())
        assert doc["format"] == obs_slo.SLO_REPORT_FORMAT
        assert doc["version"] == obs_slo.SLO_REPORT_VERSION
        assert doc["checks"] >= 1          # the scrape drove a sweep
        assert doc["tenants"]["decode"]["verdict"] == "ok"
    finally:
        obs_health.stop_exporter()
        obs_slo.stop_slo()

    # with no tracker armed the endpoint serves the empty document
    try:
        exp = obs_health.start_exporter(port=0, registry=reg)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{exp.port}/slo", timeout=10) as resp:
            doc = json.loads(resp.read())
        assert doc["tenants"] == {} and doc["checks"] == 0
    finally:
        obs_health.stop_exporter()


def _mk_report():
    """A real tracker report with one violating and one healthy
    tenant (what slo_soak writes / the exporter serves)."""
    reg = obs_metrics.MetricsRegistry()
    tr = _tracker([_spec(p50_us=256.0),
                   _spec(tenant="prefill", collective="*",
                         p99_us=16384.0)], reg)
    for _ in range(4):
        _sweep(reg, 100)
        _sweep(reg, 100, tenant="prefill", coll="allgather")
        tr.check()
    for _ in range(2):
        _sweep(reg, 1000)
        tr.check()
    return tr.doc()


def test_perf_doctor_slo_ci_round_trip(tmp_path):
    report_path = tmp_path / "slo_report.json"
    report_path.write_text(json.dumps(_mk_report()))
    out = tmp_path / "report.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/perf_doctor.py"),
         "--slo", str(report_path), "--ci", "--out", str(out)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(out.read_text())
    assert report["schema_errors"] == []
    assert report["slo"]["tenants"]["decode"]["verdict"] == "fast_burn"
    assert "tenant decode" in proc.stdout
    assert "tenant prefill" in proc.stdout
    assert "burn fast" in proc.stdout


def test_perf_doctor_slo_ci_rejects_schema_drift(tmp_path):
    doc = _mk_report()
    doc["tenants"]["decode"]["verdict"] = "bogus"
    doc["tenants"]["prefill"]["budget_remaining"] = 7.5
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(doc))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/perf_doctor.py"),
         "--slo", str(bad), "--ci"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "verdict" in proc.stdout + proc.stderr
    # and a non-report file is a schema error, not a traceback
    notreport = tmp_path / "x.json"
    notreport.write_text("{}")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/perf_doctor.py"),
         "--slo", str(notreport), "--ci"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 2
    assert "Traceback" not in proc.stderr
