"""Deterministic schedule-exploration model checker (r14).

Pins the ``scripts/model_check.py`` / ``native/test/test_detsched``
contract end to end:

* a clean drill explores with ZERO findings and unique traces == runs
  (the DFS really visits distinct interleavings, not one schedule N
  times);
* the sensitivity proof — the ``ACCL_FAULT_DETACH_RACE`` build, which
  reverts the r13 ``InprocHub::detach`` drain, must REDISCOVER the
  race and the minimal failing schedule must replay bit-for-bit from
  the artifact alone (the same hex+seed contract as fuzz_wire.py);
* the artifact round-trip: explore -> artifact -> --replay reproduces
  the identical finding, and the same schedule on the FIXED build runs
  clean.

Builds are driven through the native Makefile once per session; the
whole module self-skips when no C++ toolchain is available.
"""
import json
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native")
BIN = os.path.join(NATIVE, "test", "test_detsched")
BIN_FAULT = os.path.join(NATIVE, "test", "test_detsched_fault")
MODEL_CHECK = os.path.join(REPO, "scripts", "model_check.py")

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None and shutil.which("c++") is None,
    reason="no C++ toolchain for the detsched harness",
)


@pytest.fixture(scope="module")
def harness():
    proc = subprocess.run(
        ["make", "-C", NATIVE, "detsched"], capture_output=True, text=True,
        timeout=600,
    )
    if proc.returncode != 0:
        pytest.skip(f"detsched build failed: {proc.stderr[-500:]}")
    return BIN


def run_json(binary, *args, timeout=180):
    proc = subprocess.run(
        [binary, *args], capture_output=True, text=True, timeout=timeout
    )
    line = proc.stdout.strip().splitlines()[-1]
    out = json.loads(line)
    out["exit_code"] = proc.returncode
    return out


def test_clean_drill_zero_findings(harness):
    # a correct engine explored across hundreds of schedules: no
    # finding, and every run is a DISTINCT interleaving (the explorer
    # is exploring, not re-running one schedule)
    res = run_json(
        harness, "--drill", "abort_vs_traffic", "--explore", "300",
        "--seed", "3",
    )
    assert res["exit_code"] == 0
    assert res["findings"] == 0
    assert res["runs"] >= 300
    assert res["unique_traces"] == res["runs"]


def test_fault_build_rediscovers_detach_race(harness):
    # sensitivity: the seeded r13 race must be found, with a non-empty
    # minimal failing prefix naming the invariant
    res = run_json(
        BIN_FAULT, "--drill", "detach_race", "--explore", "500",
        "--seed", "3", "--expect-finding",
    )
    assert res["exit_code"] == 0
    assert res["findings"] == 1
    assert "detached slot" in res["what"]
    assert res["prefix_hex"] != ""
    # minimality: the minimized prefix is no longer than the full trace
    assert len(res["prefix_hex"]) <= len(res["trace_hex"])


def test_minimal_schedule_replays_bit_for_bit(harness):
    # artifact round-trip: the minimal failing schedule reproduces the
    # identical finding on the fault build and runs CLEAN on the fixed
    # build (the fix, not schedule luck, is what holds the invariant)
    found = run_json(
        BIN_FAULT, "--drill", "detach_race", "--explore", "500",
        "--seed", "3", "--expect-finding",
    )
    prefix = found["prefix_hex"]
    replay = run_json(
        BIN_FAULT, "--drill", "detach_race", "--schedule", prefix,
        "--seed", "3", "--expect-finding",
    )
    assert replay["exit_code"] == 0
    assert replay["failed"] is True
    assert replay["what"] == found["what"]
    fixed = run_json(
        harness, "--drill", "detach_race", "--schedule", prefix,
        "--seed", "3",
    )
    assert fixed["exit_code"] == 0
    assert fixed["failed"] is False


def test_exploration_is_deterministic(harness):
    # same (drill, seed, budget) -> identical sweep, run for run
    a = run_json(harness, "--drill", "shutdown_vs_waiters", "--explore",
                 "200", "--seed", "11")
    b = run_json(harness, "--drill", "shutdown_vs_waiters", "--explore",
                 "200", "--seed", "11")
    assert (a["runs"], a["unique_traces"], a["findings"]) == (
        b["runs"], b["unique_traces"], b["findings"])


def test_model_check_cli_artifact_roundtrip(harness, tmp_path):
    # the orchestrator end to end: a fault-build exploration through
    # scripts/model_check.py writes an artifact... by running the drill
    # WITHOUT --expect-finding so the finding is treated as a failure
    artifact = tmp_path / "model_check_failure.json"
    proc = subprocess.run(
        [sys.executable, MODEL_CHECK, "--drill", "detach_race",
         "--fault-build", "--runs", "500", "--no-build",
         "--artifact", str(artifact)],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert artifact.exists()
    art = json.loads(artifact.read_text())
    assert art["drill"] == "detach_race"
    assert art["schedule_hex"]
    assert art["fault_build"] is True
    replay = subprocess.run(
        [sys.executable, MODEL_CHECK, "--replay", str(artifact),
         "--no-build"],
        capture_output=True, text=True, timeout=300,
    )
    assert replay.returncode == 0, replay.stdout + replay.stderr
    assert "reproduced" in replay.stdout
