"""Deterministic schedule-exploration model checker (r14).

Pins the ``scripts/model_check.py`` / ``native/test/test_detsched``
contract end to end:

* a clean drill explores with ZERO findings and unique traces == runs
  (the DFS really visits distinct interleavings, not one schedule N
  times);
* the sensitivity proof — the ``ACCL_FAULT_DETACH_RACE`` build, which
  reverts the r13 ``InprocHub::detach`` drain, must REDISCOVER the
  race and the minimal failing schedule must replay bit-for-bit from
  the artifact alone (the same hex+seed contract as fuzz_wire.py);
* the artifact round-trip: explore -> artifact -> --replay reproduces
  the identical finding, and the same schedule on the FIXED build runs
  clean;
* r19 — the timeout- and resource-aware upgrade: injection branching
  is deterministic and off-by-default-identical (``--ibound 0``),
  rx-pool occupancy is explored state (tightening the pool surfaces
  more pressure decision points), trace-guided exploration refinds a
  captured failure on schedule one, and the liveness invariant (every
  submitted call finalizes) fires on a seeded leak and stays quiet on
  clean engine drills.

Builds are driven through the native Makefile once per session; the
whole module self-skips when no C++ toolchain is available.
"""
import json
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native")
BIN = os.path.join(NATIVE, "test", "test_detsched")
BIN_FAULT = os.path.join(NATIVE, "test", "test_detsched_fault")
MODEL_CHECK = os.path.join(REPO, "scripts", "model_check.py")

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None and shutil.which("c++") is None,
    reason="no C++ toolchain for the detsched harness",
)


@pytest.fixture(scope="module")
def harness():
    proc = subprocess.run(
        ["make", "-C", NATIVE, "detsched"], capture_output=True, text=True,
        timeout=600,
    )
    if proc.returncode != 0:
        pytest.skip(f"detsched build failed: {proc.stderr[-500:]}")
    return BIN


def run_json(binary, *args, timeout=180, env=None):
    proc = subprocess.run(
        [binary, *args], capture_output=True, text=True, timeout=timeout,
        env=env,
    )
    line = proc.stdout.strip().splitlines()[-1]
    out = json.loads(line)
    out["exit_code"] = proc.returncode
    return out


def test_clean_drill_zero_findings(harness):
    # a correct engine explored across hundreds of schedules: no
    # finding, and every run is a DISTINCT interleaving (the explorer
    # is exploring, not re-running one schedule)
    res = run_json(
        harness, "--drill", "abort_vs_traffic", "--explore", "300",
        "--seed", "3",
    )
    assert res["exit_code"] == 0
    assert res["findings"] == 0
    assert res["runs"] >= 300
    assert res["unique_traces"] == res["runs"]


def test_fault_build_rediscovers_detach_race(harness):
    # sensitivity: the seeded r13 race must be found, with a non-empty
    # minimal failing prefix naming the invariant
    res = run_json(
        BIN_FAULT, "--drill", "detach_race", "--explore", "500",
        "--seed", "3", "--expect-finding",
    )
    assert res["exit_code"] == 0
    assert res["findings"] == 1
    assert "detached slot" in res["what"]
    assert res["prefix_hex"] != ""
    # minimality: the minimized prefix is no longer than the full trace
    assert len(res["prefix_hex"]) <= len(res["trace_hex"])


def test_minimal_schedule_replays_bit_for_bit(harness):
    # artifact round-trip: the minimal failing schedule reproduces the
    # identical finding on the fault build and runs CLEAN on the fixed
    # build (the fix, not schedule luck, is what holds the invariant)
    found = run_json(
        BIN_FAULT, "--drill", "detach_race", "--explore", "500",
        "--seed", "3", "--expect-finding",
    )
    prefix = found["prefix_hex"]
    replay = run_json(
        BIN_FAULT, "--drill", "detach_race", "--schedule", prefix,
        "--seed", "3", "--expect-finding",
    )
    assert replay["exit_code"] == 0
    assert replay["failed"] is True
    assert replay["what"] == found["what"]
    fixed = run_json(
        harness, "--drill", "detach_race", "--schedule", prefix,
        "--seed", "3",
    )
    assert fixed["exit_code"] == 0
    assert fixed["failed"] is False


def test_exploration_is_deterministic(harness):
    # same (drill, seed, budget) -> identical sweep, run for run
    a = run_json(harness, "--drill", "shutdown_vs_waiters", "--explore",
                 "200", "--seed", "11")
    b = run_json(harness, "--drill", "shutdown_vs_waiters", "--explore",
                 "200", "--seed", "11")
    assert (a["runs"], a["unique_traces"], a["findings"]) == (
        b["runs"], b["unique_traces"], b["findings"])


def test_model_check_cli_artifact_roundtrip(harness, tmp_path):
    # the orchestrator end to end: a fault-build exploration through
    # scripts/model_check.py writes an artifact... by running the drill
    # WITHOUT --expect-finding so the finding is treated as a failure
    artifact = tmp_path / "model_check_failure.json"
    proc = subprocess.run(
        [sys.executable, MODEL_CHECK, "--drill", "detach_race",
         "--fault-build", "--runs", "500", "--no-build",
         "--artifact", str(artifact)],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert artifact.exists()
    art = json.loads(artifact.read_text())
    assert art["drill"] == "detach_race"
    assert art["schedule_hex"]
    assert art["fault_build"] is True
    replay = subprocess.run(
        [sys.executable, MODEL_CHECK, "--replay", str(artifact),
         "--no-build"],
        capture_output=True, text=True, timeout=300,
    )
    assert replay.returncode == 0, replay.stdout + replay.stderr
    assert "reproduced" in replay.stdout


# ---- r19: timeout- and resource-aware exploration ------------------------


def test_timeout_branch_determinism(harness):
    # same (drill, seed, ibound) -> identical sweep including the
    # injection schedule; and ibound=0 keeps the legacy explorer
    # bit-identical (no injections ever, so pre-r19 artifacts replay)
    a = run_json(harness, "--drill", "subcomm_allgather", "--explore",
                 "60", "--seed", "7", "--ibound", "1")
    b = run_json(harness, "--drill", "subcomm_allgather", "--explore",
                 "60", "--seed", "7", "--ibound", "1")
    keys = ("runs", "unique_traces", "findings", "injected_runs",
            "pressure_events")
    assert [a[k] for k in keys] == [b[k] for k in keys]
    assert a["findings"] == 0
    assert a["injected_runs"] > 0  # the injector really branched
    legacy = run_json(harness, "--drill", "subcomm_allgather", "--explore",
                      "60", "--seed", "7", "--ibound", "0")
    assert legacy["findings"] == 0
    assert legacy["injected_runs"] == 0


def test_resource_bound_exploration(harness):
    # rx-pool occupancy is modeled state: halving the pool must surface
    # MORE exhaustion decision points (pressure events arm the timeout
    # injector exactly where pinning can starve a match), and the fixed
    # engine must stay clean under the extra injected expiries
    wide = run_json(harness, "--drill", "subcomm_allgather", "--explore",
                    "40", "--seed", "3", "--ibound", "1")
    tight_env = dict(os.environ, ACCL_DETSCHED_RX_BUFS="2")
    tight = run_json(harness, "--drill", "subcomm_allgather", "--explore",
                     "40", "--seed", "3", "--ibound", "1", env=tight_env)
    assert wide["pressure_events"] > 0
    assert tight["pressure_events"] > wide["pressure_events"]
    assert tight["findings"] == 0
    assert tight["exit_code"] == 0


def test_trace_guided_exploration_roundtrip(harness):
    # seed the DFS from a captured failing trace: the fault build
    # refinds the race on schedule ONE instead of searching; the fixed
    # build explores the same guided prefix clean (the fix, not
    # schedule luck, holds the invariant)
    found = run_json(BIN_FAULT, "--drill", "detach_race", "--explore",
                     "500", "--seed", "3", "--expect-finding")
    trace = found["trace_hex"]
    assert trace
    guided = run_json(BIN_FAULT, "--drill", "detach_race", "--explore",
                      "50", "--seed", "3", "--explore-from", trace,
                      "--expect-finding")
    assert guided["runs"] == 1
    assert guided["findings"] == 1
    assert guided["what"] == found["what"]
    fixed = run_json(harness, "--drill", "detach_race", "--explore", "50",
                     "--seed", "3", "--explore-from", trace)
    assert fixed["findings"] == 0
    assert fixed["exit_code"] == 0


def test_liveness_positive_and_negative(harness):
    # positive: the seeded leak drill (a live token never handed back)
    # ends with the stuck-progress finding on its very first schedule;
    # negative: a clean engine drill with blocked-then-finalized calls
    # returns every token through the finalize paths
    leak = run_json(harness, "--drill", "liveness_leak", "--explore",
                    "50", "--seed", "3", "--expect-finding")
    assert leak["exit_code"] == 0
    assert leak["findings"] >= 1
    assert "stuck-progress" in leak["what"]
    clean = run_json(harness, "--drill", "shutdown_vs_waiters",
                     "--explore", "150", "--seed", "3")
    assert clean["findings"] == 0
    assert clean["exit_code"] == 0


def test_unknown_drill_lists_registry(harness):
    # the harness refuses with exit 2 and points at --list; the
    # orchestrator does the listing itself so a typoed --drill/--replay
    # name shows the caller what IS runnable
    proc = subprocess.run(
        [harness, "--drill", "no_such_drill", "--explore", "1"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 2
    assert "unknown drill" in proc.stderr
    mc = subprocess.run(
        [sys.executable, MODEL_CHECK, "--drill", "no_such_drill",
         "--no-build"],
        capture_output=True, text=True, timeout=120,
    )
    assert mc.returncode == 2
    assert "subcomm_allgather8" in mc.stdout + mc.stderr
