"""KV-cache inference parity: teacher-forced decode must reproduce the
training forward position for position, for every config flavor —
the standard cache-correctness contract (a wrong cache write/mask
shows up as a drifting logit at some position)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accl_tpu.models import ModelConfig, forward, init_params
from accl_tpu.models.decode import (
    decode_step,
    generate,
    init_kv_cache,
    prefill,
)

B, T = 2, 16


def _setup(**kw):
    cfg = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4,
                      d_head=8, d_ff=64, **kw)
    params = init_params(np.random.default_rng(3), cfg)
    tokens = jnp.asarray(np.random.default_rng(4).integers(
        0, cfg.vocab, size=(B, T), dtype=np.int32))
    return cfg, params, tokens


CFGS = [
    {},
    {"n_kv_heads": 2},                      # GQA: grouped cache
    {"rope": True},                          # absolute positions
    {"mlp": "swiglu"},
    {"attn_window": 5},                      # sliding window
    {"n_kv_heads": 2, "rope": True, "mlp": "swiglu"},
]


@pytest.mark.parametrize("kw", CFGS)
def test_prefill_matches_forward(kw):
    cfg, params, tokens = _setup(**kw)
    want = np.asarray(forward(params, tokens, cfg))
    cache = init_kv_cache(cfg, B, T + 4)
    got, cache = jax.jit(prefill, static_argnames=("cfg",))(
        params, tokens, cache, cfg)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5,
                               atol=2e-5)
    assert int(cache["pos"]) == T


@pytest.mark.parametrize("kw", [{}, {"n_kv_heads": 2, "rope": True,
                                     "mlp": "swiglu"}])
def test_teacher_forced_decode_matches_forward(kw):
    cfg, params, tokens = _setup(**kw)
    want = np.asarray(forward(params, tokens, cfg))  # [B, T, vocab]
    cache = init_kv_cache(cfg, B, T)
    step = jax.jit(decode_step, static_argnames=("cfg",))
    for t in range(T):
        lg, cache = step(params, tokens[:, t], cache, cfg)
        np.testing.assert_allclose(np.asarray(lg), want[:, t],
                                   rtol=3e-5, atol=3e-5, err_msg=f"t={t}")


def test_prefill_then_decode_continues_exactly():
    # split the sequence: prefill the first half, decode the second —
    # every decoded position must match the full forward
    cfg, params, tokens = _setup(rope=True)
    want = np.asarray(forward(params, tokens, cfg))
    half = T // 2
    cache = init_kv_cache(cfg, B, T)
    lg, cache = prefill(params, tokens[:, :half], cache, cfg)
    np.testing.assert_allclose(np.asarray(lg), want[:, :half],
                               rtol=3e-5, atol=3e-5)
    step = jax.jit(decode_step, static_argnames=("cfg",))
    for t in range(half, T):
        lg, cache = step(params, tokens[:, t], cache, cfg)
        np.testing.assert_allclose(np.asarray(lg), want[:, t],
                                   rtol=3e-5, atol=3e-5, err_msg=f"t={t}")


def test_generate_greedy_matches_stepwise_argmax():
    cfg, params, tokens = _setup()
    prompt = tokens[:, :8]
    out = np.asarray(generate(params, prompt, cfg, max_new=5))
    assert out.shape == (B, 5)
    # reference: grow the sequence through the full forward each step
    seq = np.asarray(prompt)
    for i in range(5):
        lg = np.asarray(forward(params, jnp.asarray(seq), cfg))
        nxt = lg[:, -1].argmax(axis=-1).astype(np.int32)
        np.testing.assert_array_equal(out[:, i], nxt, err_msg=f"i={i}")
        seq = np.concatenate([seq, nxt[:, None]], axis=1)


def test_decode_tp_sharded_matches_local():
    # tp-sharded serving from the same shard_map mesh as training
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    from jax.sharding import PartitionSpec as P

    from accl_tpu.models.transformer import shard_params
    from accl_tpu.parallel.mesh import make_mesh

    cfg, params, tokens = _setup(n_kv_heads=2)
    mesh = make_mesh(tp=2)
    want = np.asarray(forward(params, tokens, cfg))

    sharded = shard_params(params, mesh, cfg, tp="tp")
    cache = init_kv_cache(cfg, B, T)

    def run(p, tok, c):
        lg, c2 = prefill(p, tok, c, cfg, tp_axis="tp")
        return lg, c2

    from accl_tpu.models.transformer import param_specs
    pspecs = param_specs(cfg, tp="tp")
    # the cache shards over K/V HEADS exactly like the projections:
    # each tp member banks and reads only its own head subset
    kv_spec = P(None, None, "tp", None)
    cache_specs = {"pos": P(),
                   "layers": [{"k": kv_spec, "v": kv_spec}
                              for _ in range(cfg.n_layers)]}
    f = jax.jit(jax.shard_map(
        run, mesh=mesh,
        in_specs=(pspecs, P(), cache_specs),
        out_specs=(P(), cache_specs),
        check_vma=False))
    got, _ = f(sharded, tokens, cache)
    np.testing.assert_allclose(np.asarray(got), want, rtol=3e-5,
                               atol=3e-5)


def test_generate_sampling_modes():
    cfg, params, tokens = _setup()
    prompt = tokens[:, :6]
    # greedy is deterministic regardless of key
    g1 = np.asarray(generate(params, prompt, cfg, max_new=4))
    g2 = np.asarray(generate(params, prompt, cfg, max_new=4,
                             key=jax.random.PRNGKey(7)))
    np.testing.assert_array_equal(g1, g2)
    # sampling is reproducible per key and within the vocab
    s1 = np.asarray(generate(params, prompt, cfg, max_new=4,
                             temperature=1.0, top_k=8,
                             key=jax.random.PRNGKey(5)))
    s2 = np.asarray(generate(params, prompt, cfg, max_new=4,
                             temperature=1.0, top_k=8,
                             key=jax.random.PRNGKey(5)))
    np.testing.assert_array_equal(s1, s2)
    assert s1.min() >= 0 and s1.max() < cfg.vocab
    # top_k=1 at any temperature degenerates to greedy
    t1 = np.asarray(generate(params, prompt, cfg, max_new=4,
                             temperature=2.5, top_k=1,
                             key=jax.random.PRNGKey(9)))
    np.testing.assert_array_equal(t1, g1)


def test_moe_teacher_forced_decode_matches_forward():
    """The MoE family's serving path: cached decode reproduces
    models.moe.forward position for position (router decisions
    included — a drifting gate shows up as a logit mismatch)."""
    from accl_tpu.models.moe import MoEConfig, forward as moe_forward
    from accl_tpu.models.moe import init_params as moe_init
    from accl_tpu.models import moe_decode

    cfg = MoEConfig(vocab=64, d_model=32, n_layers=2, n_heads=4,
                    d_head=8, d_ff=64, n_experts=4)
    params = moe_init(np.random.default_rng(11), cfg)
    tokens = jnp.asarray(np.random.default_rng(12).integers(
        0, cfg.vocab, size=(B, T), dtype=np.int32))
    want, _aux = moe_forward(params, tokens, cfg)
    want = np.asarray(want)

    cache = moe_decode.init_kv_cache(cfg, B, T)
    lg, _aux2, cache = jax.jit(
        moe_decode.prefill, static_argnames=("cfg",))(
            params, tokens[:, :8], cache, cfg)
    np.testing.assert_allclose(np.asarray(lg), want[:, :8], rtol=3e-5,
                               atol=3e-5)
    step = jax.jit(moe_decode.decode_step, static_argnames=("cfg",))
    for t in range(8, T):
        lg, cache = step(params, tokens[:, t], cache, cfg)
        np.testing.assert_allclose(np.asarray(lg), want[:, t],
                                   rtol=3e-5, atol=3e-5, err_msg=f"t={t}")


def test_moe_decode_expert_parallel_matches_dense():
    """EP serving: decode under expert parallelism (dispatch/combine
    over the ep axis) must match the DENSE reference exactly — the
    serving capacity override makes the dispatch drop-free, where the
    training-time capacity formula would zero out tokens at decode's
    tiny per-call counts (r5 review finding)."""
    if len(jax.devices()) < 4:
        pytest.skip("needs a 4-device mesh")
    from jax.sharding import PartitionSpec as P

    from accl_tpu.models import moe_decode
    from accl_tpu.models.moe import (
        MoEConfig,
        forward as moe_forward,
        init_params as moe_init,
        param_specs as moe_specs,
        shard_params as moe_shard,
    )
    from accl_tpu.parallel.mesh import make_mesh

    cfg = MoEConfig(vocab=64, d_model=32, n_layers=2, n_heads=4,
                    d_head=8, d_ff=64, n_experts=4)
    params = moe_init(np.random.default_rng(13), cfg)
    tokens = jnp.asarray(np.random.default_rng(14).integers(
        0, cfg.vocab, size=(B, 12), dtype=np.int32))
    want, _aux = moe_forward(params, tokens, cfg)  # dense reference
    want = np.asarray(want)

    mesh = make_mesh(ep=4)
    sharded = moe_shard(params, mesh, cfg, ep="ep")
    cache = moe_decode.init_kv_cache(cfg, B, 12)
    cache_specs = jax.tree.map(lambda _: P(), cache)
    pspecs = moe_specs(cfg, ep="ep")

    def pre(p, tok, c):
        lg, _a, c2 = moe_decode.prefill(p, tok, c, cfg, ep_axis="ep")
        return lg, c2

    fpre = jax.jit(jax.shard_map(
        pre, mesh=mesh, in_specs=(pspecs, P(), cache_specs),
        out_specs=(P(), cache_specs), check_vma=False))
    lg, cache = fpre(sharded, tokens[:, :6], cache)
    np.testing.assert_allclose(np.asarray(lg), want[:, :6], rtol=3e-5,
                               atol=3e-5)

    def stp(p, tok, c):
        return moe_decode.decode_step(p, tok, c, cfg, ep_axis="ep")

    fstep = jax.jit(jax.shard_map(
        stp, mesh=mesh, in_specs=(pspecs, P(), cache_specs),
        out_specs=(P(), cache_specs), check_vma=False))
    for t in range(6, 12):
        lg, cache = fstep(sharded, tokens[:, t], cache)
        np.testing.assert_allclose(np.asarray(lg), want[:, t],
                                   rtol=3e-5, atol=3e-5, err_msg=f"t={t}")


def test_decode_bf16_config_parity():
    """bf16 activations (the real-TPU serving dtype): teacher-forced
    decode tracks the training forward within bf16 tolerance."""
    cfg, params, tokens = _setup(dtype="bfloat16", n_kv_heads=2)
    want = np.asarray(forward(params, tokens, cfg), np.float32)
    cache = init_kv_cache(cfg, B, T)
    step = jax.jit(decode_step, static_argnames=("cfg",))
    for t in range(T):
        lg, cache = step(params, tokens[:, t], cache, cfg)
        np.testing.assert_allclose(np.asarray(lg, np.float32), want[:, t],
                                   rtol=3e-2, atol=3e-2, err_msg=f"t={t}")


def test_generate_invalid_top_k_raises():
    """Out-of-range top_k must raise eagerly: under jit the negative
    index into jnp.sort would be clamped and top-k truncation would
    silently degrade to plain temperature sampling (r5 ADVICE)."""
    cfg, params, tokens = _setup()
    prompt = tokens[:, :6]
    for bad in (0, -3, cfg.vocab + 1):
        with pytest.raises(ValueError, match="top_k"):
            generate(params, prompt, cfg, max_new=2, temperature=1.0,
                     top_k=bad, key=jax.random.PRNGKey(1))
    # boundary values are legal
    for ok in (1, cfg.vocab):
        out = np.asarray(generate(params, prompt, cfg, max_new=2,
                                  temperature=1.0, top_k=ok,
                                  key=jax.random.PRNGKey(1)))
        assert out.shape == (B, 2)
