"""The examples/ scripts must stay runnable — they are the user-facing
getting-started surface (the reference ships runnable demo apps under
test/host; a switching user expects the same here)."""
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, extra_env=None, timeout=420):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # scripts pin their own platform
    if extra_env:
        env.update(extra_env)
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "examples", script)],
        capture_output=True, text=True, timeout=timeout, cwd=ROOT,
        env=env)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    return proc.stdout


def test_collectives_emu_example():
    out = _run("collectives_emu.py")
    assert "OK" in out


def test_train_transformer_3d_example():
    out = _run("train_transformer_3d.py",
               extra_env={"ACCL_EXAMPLE_STEPS": "2"})
    assert "OK" in out


def test_device_vadd_put_example():
    out = _run("device_vadd_put.py")
    assert "OK" in out


def test_collectives_tpu_gang_example():
    out = _run("collectives_tpu_gang.py")
    assert "OK" in out


def test_generate_text_example():
    out = _run("generate_text.py", extra_env={"ACCL_EXAMPLE_STEPS": "2"})
    assert "decode parity OK" in out and "OK" in out
