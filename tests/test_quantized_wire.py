"""The r17 quantized wire lane end to end: int8 block-scaled
collectives through the real driver dispatch on both backends.

Gates (the ISSUE-15 acceptance matrix):
- bitwise gate for the lossless lanes: no policy / ACCL_COMPRESS=0 is
  bit-identical static dispatch, and lossless results stay exact;
- per-P error-bound gate for int8 with and without error feedback —
  one symmetric absmax quantization rounds within scale/2 per element
  and the ring requantizes per hop, so allreduce error is bounded by
  ~P half-steps of the partial's block absmax (documented in
  docs/performance.md "Quantized wire lanes");
- plan capture/replay carries the quantization config bitwise-stably,
  and a fenced (abort/reset) plan RAISES instead of replaying stale;
- policy on/off parity on emu AND tpu-interpret backends;
- the wire accounting families (engine stats v3 + per-link
  comp_tx_bytes) actually attribute the compressed traffic.
"""
import numpy as np
import pytest

from accl_tpu.arithconfig import CompressionPolicy
from accl_tpu.backends.emu import EmuWorld
from accl_tpu.constants import ACCLError, DataType, ErrorCode, TuningKey


def _rand(n, seed=0):
    return np.random.default_rng(seed).standard_normal(n).astype(
        np.float32)


def _err_bound(P: int, inputs) -> float:
    """Documented per-element bound for the int8 ring allreduce: each
    of the ~P requantizations rounds within half a step of its running
    partial, whose block absmax is at most the exact sum's absmax plus
    accumulated error — bounded loosely by P * max|partial| / 254 per
    hop, P hops."""
    amax = float(np.abs(np.sum(inputs, axis=0)).max()) + float(
        max(np.abs(x).max() for x in inputs))
    return P * amax / 254.0 * 2.0


@pytest.fixture
def emu4():
    w = EmuWorld(4, max_eager_size=8192, max_rendezvous_size=1 << 22)
    yield w
    w.close()


@pytest.fixture
def tpu4():
    from accl_tpu.backends.tpu import TpuWorld

    w = TpuWorld(4)
    yield w
    w.close()


def _allreduce_int8(accl, rank, n, seed_base=0, compress=DataType.int8,
                    reps=1):
    data = _rand(n, seed=seed_base + rank)
    src = accl.create_buffer_like(data)
    dst = accl.create_buffer(n, np.float32)
    outs = []
    for _ in range(reps):
        accl.allreduce(src, dst, n, compress_dtype=compress)
        dst.sync_from_device()
        outs.append(dst.host.copy())
    return data, outs


# ---------------------------------------------------------------------------
# emu backend: eager ring + rendezvous, error bounds, EF, accounting
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("n,lane", [(1024, "eager"), (8192, "rendezvous")])
def test_emu_int8_allreduce_error_bound(emu4, n, lane):
    out = emu4.run(lambda a, r: _allreduce_int8(a, r, n, seed_base=7))
    inputs = [d for d, _ in out]
    exact = np.sum(inputs, axis=0)
    bound = _err_bound(4, inputs)
    for _, (got,) in out:
        err = np.abs(got - exact)
        assert err.max() <= bound, (lane, err.max(), bound)
        assert err.max() > 0  # genuinely quantized, not lossless
    # the compressed traffic is attributed: engine stats v3 + per-link
    st = emu4.devices[0].engine_stats()
    assert st["version"] >= 3
    assert st["compressed_tx_bytes"] > 0
    # ~4:1 — the logical bytes must dominate the wire bytes
    assert st["compressed_tx_logical_bytes"] > 3 * st["compressed_tx_bytes"]
    rows = emu4.devices[0].link_stats()
    assert any(r["comp_tx_bytes"] > 0 for r in rows)


def test_emu_int8_reduce_scatter_and_lossless_bitwise(emu4):
    n = 512

    def body(accl, rank):
        data = _rand(n * 4, seed=30 + rank)
        src = accl.create_buffer_like(data)
        dst = accl.create_buffer(n, np.float32)
        accl.reduce_scatter(src, dst, n, compress_dtype=DataType.int8)
        dst.sync_from_device()
        q = dst.host.copy()
        # lossless lane stays bitwise on integer-valued data
        ones = accl.create_buffer_like(np.full(n, rank + 1, np.float32))
        out = accl.create_buffer(n, np.float32)
        accl.allreduce(ones, out, n)
        out.sync_from_device()
        return data, q, out.host.copy()

    out = emu4.run(body)
    exact = np.sum([d for d, _q, _l in out], axis=0).reshape(4, n)
    bound = _err_bound(4, [d for d, _q, _l in out])
    for rank, (_, q, lossless) in enumerate(out):
        assert np.abs(q - exact[rank]).max() <= bound
        assert np.array_equal(lossless, np.full(n, 10.0, np.float32))


def test_emu_error_feedback_policy_lane(emu4):
    """EF selects a distinct arithcfg (the engine-side residual fold);
    repeated allreduce stays inside the bound and the wire stays 4:1."""
    n = 2048
    pol = CompressionPolicy(dtype=DataType.int8, min_bytes=1024,
                            error_feedback=True)

    def body(accl, rank):
        accl.set_compression(pol)
        pair = (DataType.float32, DataType.int8)
        assert accl._arith_ids_ef[pair] != accl._arith_ids[pair]
        return _allreduce_int8(accl, rank, n, seed_base=50,
                               compress=None, reps=4)

    out = emu4.run(body)
    inputs = [d for d, _ in out]
    exact = np.sum(inputs, axis=0)
    bound = _err_bound(4, inputs)
    for _, outs in out:
        for got in outs:
            assert np.abs(got - exact).max() <= bound


def test_emu_policy_threshold_and_off_parity(emu4):
    """Below min_bytes the policy leaves the call lossless (bitwise);
    disarmed (None) the descriptors are bit-identical to never-armed."""
    def body(accl, rank):
        from accl_tpu.constants import Operation

        buf = accl.create_buffer(4096, np.float32)
        out = accl.create_buffer(4096, np.float32)

        def build(count):
            return accl._build(Operation.allreduce, count, 0,
                               op0=buf, res=out)

        baseline = build(4096)
        pol = CompressionPolicy(dtype=DataType.int8, min_bytes=4096)
        accl.set_compression(pol)
        small = build(64)
        big = build(4096)
        accl.set_compression(None)
        off = build(4096)
        return (baseline.arithcfg, baseline.compression_flags,
                small.compression_flags, big.compression_flags,
                big.arithcfg, off.arithcfg, off.compression_flags)

    for (b_cfg, b_fl, small_fl, big_fl, big_cfg, off_cfg,
         off_fl) in emu4.run(body):
        assert small_fl == 0  # below the floor: untouched
        assert big_fl == 8  # ETH_COMPRESSED
        assert big_cfg != b_cfg  # the int8 pair, not the identity cfg
        # disarmed == never armed, bit for bit
        assert (off_cfg, off_fl) == (b_cfg, b_fl)


def test_emu_int8_operand_guards(emu4):
    def body(accl, rank):
        src8 = accl.create_buffer(256, np.int8)
        dst = accl.create_buffer(256, np.float32)
        with pytest.raises(ACCLError, match="float32"):
            accl.allreduce(src8, dst, 256, compress_dtype=DataType.int8)
        src64 = accl.create_buffer(256, np.float64)
        dst64 = accl.create_buffer(256, np.float64)
        with pytest.raises(ACCLError):
            accl.allreduce(src64, dst64, 256,
                           compress_dtype=DataType.int8)
        return True

    assert all(emu4.run(body))


def test_emu_plan_captures_quantization_config(emu4):
    """Plan capture/replay: the quantization config rides the captured
    descriptors (zero re-selection on replay), replays are bitwise
    stable on the no-EF lane, and a fenced plan RAISES."""
    n = 1024

    def body(accl, rank):
        data = _rand(n, seed=80 + rank)
        src = accl.create_buffer_like(data)
        dst = accl.create_buffer(n, np.float32)

        def step(a):
            a.allreduce(src, dst, n, compress_dtype=DataType.int8)

        plan = accl.capture_plan(step)
        dst.sync_from_device()
        captured = dst.host.copy()
        results = []
        for _ in range(2):
            plan.replay()
            dst.sync_from_device()
            results.append(dst.host.copy())
        return data, captured, results, plan, accl, dst

    out = emu4.run(body)
    inputs = [d for d, *_ in out]
    exact = np.sum(inputs, axis=0)
    bound = _err_bound(4, inputs)
    for _, captured, results, _pl, _a, _d in out:
        # same descriptors, same engine lanes, same inputs -> replay
        # reproduces the capture iteration bit for bit (no EF state)
        for got in results:
            assert np.array_equal(got, captured)
        assert np.abs(captured - exact).max() <= bound

    # fence the world: a stale replay must raise, never run
    def fence(accl, rank):
        accl.reset_errors()
        return True

    assert all(emu4.run(fence))
    for _, _c, _r, plan, _a, _d in out:
        with pytest.raises(ACCLError) as ei:
            plan.replay()
        assert (int(getattr(ei.value, "code", 0))
                & int(ErrorCode.COMM_ABORTED)) or "invalid" in str(
                    ei.value).lower() or "fenc" in str(ei.value).lower()


def test_emu_compress_env_off_is_static(emu4, monkeypatch):
    monkeypatch.setenv("ACCL_COMPRESS", "0")
    from accl_tpu.arithconfig import compression_policy_from_env

    assert compression_policy_from_env() is None
    monkeypatch.setenv("ACCL_COMPRESS", "granite")
    with pytest.raises(ACCLError, match="ACCL_COMPRESS"):
        compression_policy_from_env()


# ---------------------------------------------------------------------------
# tpu-interpret backend: quantized ring + flat lanes, policy parity
# ---------------------------------------------------------------------------
def test_tpu_int8_ring_and_flat_error_bound(tpu4):
    n = 2048
    for thr, lane in ((0, "ring"), (1 << 30, "flat")):
        for a in tpu4.accls:
            a.set_tuning(int(TuningKey.RING_THRESHOLD_BYTES), thr)
        out = tpu4.run(lambda a, r: _allreduce_int8(a, r, n,
                                                    seed_base=90))
        inputs = [d for d, _ in out]
        exact = np.sum(inputs, axis=0)
        bound = _err_bound(4, inputs)
        for _, (got,) in out:
            err = np.abs(got - exact)
            assert 0 < err.max() <= bound, (lane, err.max(), bound)
    # accounting twin: compressed bytes attributed at gang dispatch
    st = tpu4.devices[0].engine_stats()
    assert st["version"] >= 3
    assert st["compressed_tx_bytes"] > 0
    rows = tpu4.devices[0].link_stats()
    assert any(r.get("comp_tx_bytes", 0) > 0 for r in rows)


def test_tpu_policy_on_off_parity(tpu4):
    n = 1024

    def body(accl, rank):
        from accl_tpu.constants import Operation

        buf = accl.create_buffer(n, np.float32)
        out = accl.create_buffer(n, np.float32)

        def build():
            return accl._build(Operation.allreduce, n, 0,
                               op0=buf, res=out)

        base = build()
        accl.set_compression(CompressionPolicy(dtype=DataType.int8,
                                               min_bytes=256))
        armed = build()
        accl.set_compression(None)
        off = build()
        return base.arithcfg, base.compression_flags, \
            armed.compression_flags, off.arithcfg, off.compression_flags

    for b_cfg, b_fl, armed_fl, off_cfg, off_fl in tpu4.run(body):
        assert armed_fl == 8
        assert (off_cfg, off_fl) == (b_cfg, b_fl)


def test_tpu_lossless_bitwise_with_lane_registered(tpu4):
    """Registering the int8 arithcfg must not perturb the lossless
    lanes: integer-valued allreduce stays exact."""
    n = 512

    def body(accl, rank):
        src = accl.create_buffer_like(np.full(n, rank + 1, np.float32))
        dst = accl.create_buffer(n, np.float32)
        accl.allreduce(src, dst, n)
        dst.sync_from_device()
        return dst.host.copy()

    for got in tpu4.run(body):
        assert np.array_equal(got, np.full(n, 10.0, np.float32))


def test_wire_saved_bytes_metric_families(emu4):
    """The sampler publishes wire/compressed_tx_bytes and the derived
    bytes-saved family from the engine's v3 counters."""
    from accl_tpu.observability import telemetry as obs_telemetry
    from accl_tpu.observability.metrics import MetricsRegistry

    emu4.run(lambda a, r: _allreduce_int8(a, r, 2048, seed_base=3))
    reg = MetricsRegistry()
    sampler = obs_telemetry.TelemetrySampler(
        [d.engine_stats for d in emu4.devices], registry=reg)
    sampler.sample()
    counters = reg.counters()
    assert counters.get("wire/compressed_tx_bytes", 0) > 0
    assert counters.get("wire/compressed_saved_bytes", 0) > 0
    assert counters["wire/compressed_saved_bytes"] > \
        2 * counters["wire/compressed_tx_bytes"]
