"""Multi-slice (ICI x DCN) hybrid mesh tests.

The reference scales past one machine by running its protocol offload
engines on the machine-room network (SURVEY §5 "distributed
communication backend"); here the equivalent is a hybrid mesh whose
outer axes span slices over DCN.  CI has one host, so these validate
the sharding/collective semantics on the 8-device virtual CPU platform
(2 "slices" x 4 "chips"); the driver's dryrun does the same for the
full training step.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from accl_tpu.parallel.collectives import hierarchical_all_reduce
from accl_tpu.parallel.mesh import make_hybrid_mesh


@pytest.fixture(scope="module")
def hybrid_mesh():
    return make_hybrid_mesh(ici={"ici": 4}, dcn={"dcn": 2})


def test_hybrid_mesh_axis_order(hybrid_mesh):
    # DCN axes must be outermost (slowest-varying) so ICI neighbors stay
    # contiguous — the scaling-book layout rule
    assert hybrid_mesh.axis_names == ("dcn", "ici")
    assert hybrid_mesh.devices.shape == (2, 4)


def test_hierarchical_all_reduce_matches_flat(hybrid_mesh):
    n = 8 * 16
    x = jnp.arange(n, dtype=jnp.float32).reshape(8, 16)

    def body(xs):
        v = xs.reshape(xs.shape[1:])  # [16] per device
        h = hierarchical_all_reduce(v, "ici", "dcn")
        from jax import lax
        flat = lax.psum(v, ("dcn", "ici"))
        return h[None], flat[None]

    fn = jax.shard_map(body, mesh=hybrid_mesh,
                       in_specs=P(("dcn", "ici")),
                       out_specs=(P(("dcn", "ici")), P(("dcn", "ici"))))
    h, flat = jax.jit(fn)(x)
    np.testing.assert_allclose(np.asarray(h), np.asarray(flat), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(h)[0], np.asarray(x).sum(0),
                               rtol=1e-6)


def test_hybrid_train_step_compiles_and_runs(hybrid_mesh):
    # dp across slices (DCN), tp within a slice (ICI) — gradients ride
    # the hierarchy exactly as a 2-slice deployment would
    from accl_tpu.models.transformer import ModelConfig, init_params, make_train_step, shard_params

    cfg = ModelConfig(vocab=64, d_model=64, n_heads=4, d_head=16,
                      n_layers=1, d_ff=128)
    params = init_params(np.random.default_rng(0), cfg)
    mesh = make_hybrid_mesh(ici={"tp": 4}, dcn={"dp": 2})
    params = shard_params(params, mesh, cfg)
    step, _specs = make_train_step(mesh, cfg)
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab, (4, 32)))
    params2, loss = step(params, tokens)
    assert np.isfinite(float(loss))
    params3, loss2 = step(params2, tokens)
    assert float(loss2) < float(loss)
