"""Rendezvous-protocol collective tests: payloads above the eager
threshold take the address-exchange/one-sided-write path, with flat or
binomial-tree schedules selected by the tuning registers
(reference: fw tree bcast :816-869, tree reduce :1603-1728, flat
variants :870-922/:1533-1602, reduce-then-bcast allreduce :1878-1887,
reduce-to-0-then-scatter reduce_scatter :1768-1781)."""
import numpy as np
import pytest

from accl_tpu import ACCL, ReduceFunction
from accl_tpu.backends.emu import EmuWorld

NRANKS = 4
COUNT = 2048  # 8 KB fp32 > 1 KB eager threshold -> rendezvous


@pytest.fixture(scope="module", params=["flat", "tree"])
def world(request):
    with EmuWorld(NRANKS) as w:
        if request.param == "tree":
            # force binomial trees by lowering the flat thresholds
            def tune(accl, rank):
                accl.set_tuning(ACCL.BCAST_FLAT_TREE_MAX_RANKS, 2)
                accl.set_tuning(ACCL.REDUCE_FLAT_TREE_MAX_RANKS, 2)
                accl.set_tuning(ACCL.GATHER_FLAT_TREE_MAX_FANIN, 2)
            w.run(tune)
        yield w


def _data(count, rank, salt=0):
    rng = np.random.default_rng(31 + rank + salt * 97)
    return rng.standard_normal(count).astype(np.float32)


@pytest.mark.parametrize("root", [0, 1, 3])
def test_bcast_rendezvous(world, root):
    def fn(accl, rank):
        buf = accl.create_buffer_like(_data(COUNT, rank, salt=root))
        accl.bcast(buf, COUNT, root)
        np.testing.assert_array_equal(buf.host,
                                      _data(COUNT, root, salt=root))

    world.run(fn)


@pytest.mark.parametrize("root", [0, 2])
@pytest.mark.parametrize("func", [ReduceFunction.SUM, ReduceFunction.MAX])
def test_reduce_rendezvous(world, root, func):
    def fn(accl, rank):
        send = accl.create_buffer_like(_data(COUNT, rank))
        recv = accl.create_buffer(COUNT, np.float32)
        accl.reduce(send, recv, COUNT, root, func)
        if rank == root:
            inputs = [_data(COUNT, r) for r in range(NRANKS)]
            exp = (np.sum(inputs, axis=0) if func == ReduceFunction.SUM
                   else np.max(inputs, axis=0))
            np.testing.assert_allclose(recv.host, exp, rtol=1e-5, atol=1e-4)

    world.run(fn)


def test_allreduce_rendezvous(world):
    def fn(accl, rank):
        send = accl.create_buffer_like(_data(COUNT, rank))
        recv = accl.create_buffer(COUNT, np.float32)
        accl.allreduce(send, recv, COUNT, ReduceFunction.SUM)
        exp = np.sum([_data(COUNT, r) for r in range(NRANKS)], axis=0)
        np.testing.assert_allclose(recv.host, exp, rtol=1e-5, atol=1e-4)

    world.run(fn)


def test_reduce_scatter_rendezvous(world):
    def fn(accl, rank):
        send = accl.create_buffer_like(_data(COUNT * NRANKS, rank))
        recv = accl.create_buffer(COUNT, np.float32)
        accl.reduce_scatter(send, recv, COUNT, ReduceFunction.SUM)
        inputs = [_data(COUNT * NRANKS, r) for r in range(NRANKS)]
        exp = np.sum(inputs, axis=0)[rank * COUNT:(rank + 1) * COUNT]
        np.testing.assert_allclose(recv.host, exp, rtol=1e-5, atol=1e-4)

    world.run(fn)


@pytest.mark.parametrize("root", [0, 2])
def test_gather_scatter_rendezvous(world, root):
    def fn(accl, rank):
        send = accl.create_buffer_like(_data(COUNT, rank))
        recv = accl.create_buffer(COUNT * NRANKS, np.float32)
        accl.gather(send, recv, COUNT, root)
        if rank == root:
            exp = np.concatenate([_data(COUNT, r) for r in range(NRANKS)])
            np.testing.assert_array_equal(recv.host, exp)
        # scatter it back out
        out = accl.create_buffer(COUNT, np.float32)
        accl.scatter(recv, out, COUNT, root)
        if rank == root:
            np.testing.assert_array_equal(out.host, _data(COUNT, root))

    world.run(fn)


def test_alltoall_rendezvous(world):
    def fn(accl, rank):
        send = accl.create_buffer_like(_data(COUNT * NRANKS, rank))
        recv = accl.create_buffer(COUNT * NRANKS, np.float32)
        accl.alltoall(send, recv, COUNT)
        exp = np.concatenate([
            _data(COUNT * NRANKS, r)[rank * COUNT:(rank + 1) * COUNT]
            for r in range(NRANKS)
        ])
        np.testing.assert_array_equal(recv.host, exp)

    world.run(fn)
