"""Cross-rank critical-path attribution (observability/attribution.py)
+ the r14 torn-dump tolerance satellites.

The acceptance drills from ISSUE 12: on a 4-rank world with one rank
artificially delayed the report must name that rank as the dominant
straggler with > 90% episode share, and on a clean world the per-phase
breakdown must sum to within 5% of the measured end-to-end span — on
BOTH the emu and the tpu-interpret backends.
"""
import json
import time

import numpy as np
import pytest

from accl_tpu import ReduceFunction
from accl_tpu.observability import attribution, flight, trace

NRANKS = 4
COUNT = 256
SLOW_RANK = 2
SLOW_S = 0.003


def _loop_body(iters, slow_rank=None, slow_s=SLOW_S):
    def body(accl, rank):
        send = accl.create_buffer_like(
            np.arange(COUNT, dtype=np.float32) + rank)
        recv = accl.create_buffer(COUNT, np.float32)
        for _ in range(iters):
            if rank == slow_rank:
                time.sleep(slow_s)  # the artificial compute-skew delay
            accl.allreduce(send, recv, COUNT, ReduceFunction.SUM,
                           from_fpga=True, to_fpga=True)
        return recv.host.copy()

    return body


def _emu_dump(iters, slow_rank=None):
    from accl_tpu.backends.emu import EmuWorld

    with EmuWorld(NRANKS) as world:
        world.run(_loop_body(iters, slow_rank))
        # THIS world's recorders only — dump_all() sweeps every live
        # recorder in the process, and closed worlds from earlier tests
        # survive until a gc cycle collects their reference cycles
        return flight.merge_flight_dumps(
            [a.flight_recorder.dump() for a in world.accls])


def _tpu_dump(iters, slow_rank=None):
    from accl_tpu.backends.tpu import TpuWorld

    with TpuWorld(NRANKS) as world:
        world.run(_loop_body(iters, slow_rank))
        return flight.merge_flight_dumps(
            [a.flight_recorder.dump() for a in world.accls])


# ---------------------------------------------------------------------------
# acceptance: straggler attribution names the delayed rank
# ---------------------------------------------------------------------------
def test_straggler_attribution_emu():
    report = attribution.attribute(_emu_dump(12, slow_rank=SLOW_RANK))
    rows = [c for c in report["collectives"].values()
            if c["collective"] == "allreduce"]
    assert rows, "no allreduce group attributed"
    c = rows[0]
    d = c["dominant_straggler"]
    assert d is not None, "delayed rank not detected as straggler"
    assert d["rank"] == SLOW_RANK
    assert d["share"] > 0.9, f"episode share {d['share']} <= 0.9"
    # the injected delay is 3 ms; mean lateness must be that order
    assert d["mean_late_us"] > SLOW_S * 1e6 * 0.3


def test_straggler_attribution_tpu_interpret():
    report = attribution.attribute(_tpu_dump(10, slow_rank=SLOW_RANK))
    rows = [c for c in report["collectives"].values()
            if c["collective"] == "allreduce"]
    assert rows
    d = rows[0]["dominant_straggler"]
    assert d is not None
    assert d["rank"] == SLOW_RANK
    assert d["share"] > 0.9


# ---------------------------------------------------------------------------
# acceptance: clean-world phase breakdown partitions the span (>= 95%)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("dump_fn", [_emu_dump, _tpu_dump],
                         ids=["emu", "tpu-interpret"])
def test_phase_breakdown_covers_span(dump_fn):
    report = attribution.attribute(dump_fn(10))
    assert report["gangs_analyzed"] >= 8
    for c in report["collectives"].values():
        cov = c["phase_coverage"]
        assert 0.95 <= cov <= 1.05, (
            f"{c['collective']}: phases sum to {cov * 100:.1f}% of the "
            f"end-to-end span (want within 5%) — {c['phases_us']}")
        assert c["span_us"] > 0
        # every phase is non-negative and present
        for p in attribution.PHASES:
            assert c["phases_us"].get(p, 0.0) >= 0.0


def test_clean_world_has_no_dominant_straggler():
    report = attribution.attribute(_emu_dump(10))
    for c in report["collectives"].values():
        d = c["dominant_straggler"]
        # scheduler noise may elect scattered stragglers, but no rank
        # may own >90% of episodes on a clean world with any material
        # lateness; allow small-sample blips below 1 ms
        if d is not None and d["share"] > 0.9:
            assert d["mean_late_us"] < 1000.0


# ---------------------------------------------------------------------------
# clock-skew estimation from gang-rendezvous anchors
# ---------------------------------------------------------------------------
def _synthetic_dump(skew_ns=0, nranks=2, gangs=6, late_rank=None,
                    late_ns=0):
    """Hand-built per-rank dumps: gang instance k completes at the same
    TRUE time on every rank; rank r's clock reads true + r*skew_ns.
    late_rank's arrival trails the others by late_ns (true time)."""
    base = 1_000_000_000
    ranks = []
    for r in range(nranks):
        recs = []
        for k in range(gangs):
            t0 = base + k * 1_000_000  # true submit
            arrive = t0 + (late_ns if r == late_rank else 0)
            complete = t0 + max(late_ns, 0) + 500_000  # shared point
            off = r * skew_ns
            recs.append({
                "seq": k, "req_id": k, "rank": r,
                "collective": "allreduce", "comm": 0, "tag": 0,
                "dtype": "float32", "count": COUNT,
                "nbytes": COUNT * 4, "nranks": nranks, "lane": "emu",
                "state": "complete", "gang": True, "retcode": 0,
                "age_us": 500.0,
                "t_submit": arrive + off, "t_queue": arrive + 1_000 + off,
                "t_gang_ready": 0, "t_dispatch": arrive + 2_000 + off,
                "t_complete": complete + off,
            })
        ranks.append({"rank": r, "capacity": 512,
                      "last_completed_seq": gangs - 1, "records": recs})
    return ranks


def test_clock_skew_estimated_from_gang_anchors():
    # rank 1's clock is 3 ms ahead; no real straggler exists.  Without
    # skew correction every arrival comparison would blame rank 1.
    dumps = _synthetic_dump(skew_ns=3_000_000)
    report = attribution.attribute(flight.merge_flight_dumps(dumps))
    skew = report["clock_skew_ns"]
    assert abs(skew["1"] - 3_000_000) < 1_000
    for c in report["collectives"].values():
        assert c["dominant_straggler"] is None, (
            "pure clock skew misattributed as a straggler")


def test_skewed_clock_still_catches_real_straggler():
    dumps = _synthetic_dump(skew_ns=3_000_000, late_rank=0,
                            late_ns=2_000_000)
    report = attribution.attribute(flight.merge_flight_dumps(dumps))
    c = next(iter(report["collectives"].values()))
    d = c["dominant_straggler"]
    assert d is not None and d["rank"] == 0
    assert 1_000 < d["mean_late_us"] < 3_000


def test_render_names_dominant_straggler():
    report = attribution.attribute(
        _synthetic_dump(late_rank=1, late_ns=2_000_000))
    text = attribution.render(report)
    assert "DOMINANT straggler: rank 1" in text
    assert "gang_wait" in text


# ---------------------------------------------------------------------------
# satellite: torn (crash-truncated) dumps are salvaged, not fatal
# ---------------------------------------------------------------------------
def test_merge_flight_dumps_tolerates_torn_tail(tmp_path):
    dumps = _synthetic_dump(gangs=8)
    p0 = tmp_path / "r0.json"
    p1 = tmp_path / "r1.json"
    p0.write_text(json.dumps(dumps[0], indent=1))
    text = json.dumps(dumps[1], indent=1)
    p1.write_text(text[: int(len(text) * 0.7)])  # tear mid-record
    doc = flight.merge_flight_dumps([str(p0), str(p1)])
    torn = doc["analysis"]["torn_dumps"]
    assert len(torn) == 1 and torn[0]["path"] == str(p1)
    assert torn[0]["tail_bytes_skipped"] > 0
    # the complete prefix was salvaged (not everything lost)
    assert 0 < torn[0]["records_recovered"] < 8
    # the torn rank's order analysis gates like a wrapped ring: no
    # fake desync from the missing tail
    assert doc["analysis"]["desyncs"] == []
    assert 0 in doc["analysis"]["truncated_comms"]


def test_merge_flight_dumps_tolerates_torn_merged_doc(tmp_path):
    # a MERGED doc (watchdog dump: {"ranks": [...]}) torn mid-write
    # must salvage whole per-rank entries — probing the nested
    # "records" arrays first would silently drop every rank but the
    # first (r14 review finding)
    ranks = _synthetic_dump(gangs=4, nranks=3)
    merged = flight.merge_flight_dumps(ranks)
    text = json.dumps(merged, indent=1)
    # tear inside rank 2's entry: ranks 0 and 1 are fully intact
    cut = text.rindex('"rank": 2')
    p = tmp_path / "watchdog.json"
    p.write_text(text[:cut])
    doc = flight.merge_flight_dumps([str(p)])
    assert doc["nranks"] == 2, "intact ranks were dropped in salvage"
    assert sorted(rd["rank"] for rd in doc["ranks"]) == [0, 1]
    assert all(len(rd["records"]) == 4 for rd in doc["ranks"])
    assert doc["analysis"]["torn_dumps"][0]["records_recovered"] == 8


def test_merge_trace_files_tolerates_torn_tail(tmp_path):
    coll = trace.TraceCollector()
    for k in range(6):
        span = trace.TraceSpan("allreduce", rank=0, count=16)
        span.t_submit = 1000 + k
        span.t_complete = 2000 + k
        span.gang_id = k
        coll.add(span)
    doc = coll.to_perfetto()
    p0 = tmp_path / "t0.json"
    p1 = tmp_path / "t1.json"
    p0.write_text(json.dumps(doc))
    text = json.dumps(doc)
    p1.write_text(text[: int(len(text) * 0.6)])
    merged = trace.merge_trace_files([str(p0), str(p1)])
    assert len(merged["torn_files"]) == 1
    assert merged["torn_files"][0]["tail_bytes_skipped"] > 0
    assert merged["torn_files"][0]["events_recovered"] > 0
    assert len(merged["traceEvents"]) > len(doc["traceEvents"])


def test_salvage_rejects_hopeless_text():
    with pytest.raises(ValueError):
        trace.salvage_torn_json('{"no_array_here": 1', "records")


# ---------------------------------------------------------------------------
# attribution over merged docs vs raw dump lists must agree
# ---------------------------------------------------------------------------
def test_attribute_accepts_merged_and_raw():
    dumps = _synthetic_dump(late_rank=1, late_ns=2_000_000)
    merged = flight.merge_flight_dumps(dumps)
    a = attribution.attribute(merged)
    b = attribution.attribute(dumps)
    assert a["collectives"] == b["collectives"]
    # timeline mode carries the per-gang rows
    t = attribution.attribute(merged, timeline=True)
    assert len(t["timeline"]) == t["gangs_analyzed"]
    assert all(row["last_rank"] == 1 for row in t["timeline"])


# ---------------------------------------------------------------------------
# r15: the overlap accountant (wire-exposed vs compute-overlapped)
# ---------------------------------------------------------------------------
def test_overlap_math_with_fabricated_windows():
    """Synthetic dump + trace doc: exact interval arithmetic.  Two
    ranks, one gang; rank 0's wire interval [2us, 10us) is half-covered
    by a device window [4us, 8us) -> 4us overlapped, 4us exposed."""
    def rec(rank):
        return {"seq": 1, "gang": True, "state": "complete",
                "comm": 0, "collective": "allreduce", "tag": 0,
                "count": 64, "dtype": "float32", "nbytes": 256,
                "t_submit": 1000, "t_queue": 1500, "t_dispatch": 2000,
                "t_complete": 10000}

    doc = {"ranks": [{"rank": 0, "records": [rec(0)]},
                     {"rank": 1, "records": [rec(1)]}]}
    trace_doc = {"traceEvents": [
        # device COMPUTE window on rank 0: ts/dur in us, 4us..8us (an
        # xfer-phase slice would be excluded — it IS the wire)
        {"ph": "X", "pid": 0, "tid": 5, "name": "s0:reduce",
         "ts": 4.0, "dur": 4.0,
         "args": {"device_track": True, "device_phase": "reduce"}},
        # an unrelated non-compute slice must be ignored
        {"ph": "X", "pid": 0, "tid": 1, "name": "allreduce",
         "ts": 0.0, "dur": 100.0, "args": {}},
    ]}
    report = attribution.overlap(doc, trace_doc=trace_doc)
    assert report["compute_windows"] == 1
    row = report["collectives"]["allreduce|comm0|<=256B"]
    # rank 0: wire 8us, overlap 4us; rank 1: wire 8us, overlap 0
    assert row["wire_us"] == pytest.approx(16.0)
    assert row["overlapped_us"] == pytest.approx(4.0)
    assert row["exposed_us"] == pytest.approx(12.0)
    assert row["recovered_compute_fraction"] == pytest.approx(0.25)
    # span total 9us + 9us -> exposed fraction 12/18 (report rounds
    # fractions to 4 decimals)
    assert row["exposed_fraction"] == pytest.approx(12.0 / 18.0,
                                                    abs=1e-4)
    # without the trace doc nothing is overlapped
    bare = attribution.overlap(doc)
    assert bare["collectives"]["allreduce|comm0|<=256B"][
        "overlapped_us"] == 0.0


def test_wire_exposed_fraction_drops_without_delay_emu():
    """Acceptance drill (emu): a chaos-slowed peer produces a nonzero
    wire-exposed fraction that DROPS when the delay is removed."""
    slow = attribution.overlap(_emu_dump(10, slow_rank=SLOW_RANK))
    clean = attribution.overlap(_emu_dump(10, slow_rank=None))
    s = [c for c in slow["collectives"].values()
         if c["collective"] == "allreduce"][0]
    c = [c for c in clean["collectives"].values()
         if c["collective"] == "allreduce"][0]
    assert s["exposed_fraction"] > 0
    assert c["exposed_fraction"] > 0
    # the 3ms/iteration artificial delay dominates the slow world's
    # spans; removing it must shrink the exposed wire share
    assert s["exposed_us"] > c["exposed_us"]
    assert s["exposed_fraction"] >= c["exposed_fraction"]


def test_wire_exposed_fraction_drops_without_delay_tpu_interpret():
    """Acceptance drill (tpu-interpret rung): same contract through
    the gang-scheduler backend."""
    slow = attribution.overlap(_tpu_dump(8, slow_rank=SLOW_RANK))
    clean = attribution.overlap(_tpu_dump(8, slow_rank=None))
    s = [c for c in slow["collectives"].values()
         if c["collective"] == "allreduce"][0]
    c = [c for c in clean["collectives"].values()
         if c["collective"] == "allreduce"][0]
    assert s["exposed_fraction"] > 0
    assert s["exposed_us"] > c["exposed_us"]


def test_overlap_counts_window_spans():
    """Host-marked window: spans (trace.traced_window) count as
    compute cover too — the pre-device-trace way to mark compute."""
    windows = attribution._compute_windows({"traceEvents": [
        {"ph": "X", "pid": 3, "tid": 0, "name": "window:ffn",
         "ts": 10.0, "dur": 5.0, "args": {}},
        {"ph": "X", "pid": 3, "tid": 0, "name": "window:moe",
         "ts": 30.0, "dur": 5.0, "args": {}},
    ]})
    assert windows == {3: [(10000.0, 15000.0), (30000.0, 35000.0)]}
    assert attribution._overlap_ns(12000.0, 32000.0, windows[3]) == \
        pytest.approx(5000.0)


def test_overlap_windows_merge_never_double_count():
    """Overlapping cover (a host window: span CONTAINING device stamp
    slices, the common shape) must merge to its union — summing the
    intersections per window would let recovered_compute exceed 1.0."""
    windows = attribution._compute_windows({"traceEvents": [
        {"ph": "X", "pid": 0, "tid": 0, "name": "window:step",
         "ts": 10.0, "dur": 20.0, "args": {}},
        {"ph": "X", "pid": 0, "tid": 5, "name": "s0:reduce",
         "ts": 12.0, "dur": 4.0,
         "args": {"device_track": True, "device_phase": "reduce"}},
        {"ph": "X", "pid": 0, "tid": 5, "name": "s1:reduce",
         "ts": 28.0, "dur": 6.0,
         "args": {"device_track": True, "device_phase": "reduce"}},
        # the collective's own transfer slice is NOT compute cover
        {"ph": "X", "pid": 0, "tid": 5, "name": "s1:xfer->r1",
         "ts": 40.0, "dur": 6.0,
         "args": {"device_track": True, "device_phase": "xfer"}},
    ]})
    # 10-30 + 12-16 (contained) + 28-34 (extends) -> one 10-34 window
    assert windows == {0: [(10000.0, 34000.0)]}
    # cover can never exceed the wire interval itself
    assert attribution._overlap_ns(0.0, 100000.0, windows[0]) == \
        pytest.approx(24000.0)
