"""Mosaic lowering rung for the Pallas kernels.

The reference test ladder has an RTL/XSI rung that exercises the
*synthesized* artifact without a cluster (test/model/simulator/
cclo_sim.cpp:57-559).  The analog here: lower the ring and flash
kernels through the REAL TPU lowering pipeline (Pallas -> Mosaic MLIR,
serialized into the tpu_custom_call) via cross-platform jax.export —
no TPU devices needed, so a Mosaic lowering regression (bad block
shapes, semaphore misuse, unsupported ops) fails in CI instead of
hiding behind interpret mode.  Machine-code generation still happens
on hardware (bench.py's worker compiles and runs these kernels on the
real chip); this rung pins the compiler-frontend contract.
"""
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import AbstractMesh, NamedSharding, PartitionSpec as P

RANKS = 8


def _export_sharded(body, n_elems, dtype=jnp.float32):
    mesh = AbstractMesh((RANKS,), ("rank",),
                        axis_types=(jax.sharding.AxisType.Explicit,))
    fn = jax.shard_map(body, mesh=mesh, in_specs=P("rank"),
                       out_specs=P("rank"), check_vma=False)
    x = jax.ShapeDtypeStruct((n_elems,), dtype,
                             sharding=NamedSharding(mesh, P("rank")))
    exp = jax.export.export(jax.jit(fn), platforms=["tpu"])(x)
    return exp.mlir_module()


def _assert_mosaic(text):
    # the serialized Mosaic kernel rides a tpu_custom_call; its absence
    # means the Pallas path silently fell back or was elided
    assert "tpu_custom_call" in text, text[:1500]


@pytest.mark.parametrize("kernel", ["allreduce", "allgather",
                                    "reduce_scatter"])
def test_ring_kernels_lower_through_mosaic(kernel):
    from accl_tpu.ops import ring as R

    body = {
        "allreduce": lambda v: R.ring_all_reduce_segmented(
            v, "rank", interpret=False),
        "allgather": lambda v: R.ring_all_gather_segmented(
            v, "rank", interpret=False),
        "reduce_scatter": lambda v: R.ring_reduce_scatter_segmented(
            v, "rank", op="sum", interpret=False),
    }[kernel]
    # the driver's exact shape regime: flat per-member shards over the
    # ring threshold, ragged against the segment size (bulk/tail path)
    _assert_mosaic(_export_sharded(body, RANKS * 4096 + RANKS * 8))


def test_ring_compressed_lowers_through_mosaic(phased=None):
    # the quantized (int8 block-scaled) ring variant has its own Pallas
    # usage via the wire-compression path
    from accl_tpu.ops import ring as R

    _assert_mosaic(_export_sharded(
        lambda v: R.ring_all_reduce_segmented(v, "rank", interpret=False),
        RANKS * 1024, dtype=jnp.bfloat16))


@pytest.mark.parametrize("kern,opts", [
    ("resident", {}),
    ("grid", {}),
    # the chip-tuned resident schedule options (bench candidates)
    ("resident", {"q_tiles": 2}),
    ("resident", {"fuse_denom": True}),
    ("resident", {"q_tiles": 2, "fuse_denom": True}),
    # the software-pipelined score-carry schedule (kept selectable;
    # see its docstring for the measured result)
    ("resident_skew", {"q_tiles": 1}),
])
def test_flash_kernels_lower_through_mosaic(kern, opts):
    from accl_tpu.ops.flash import flash_attention_packed

    N, T, D = 4, 2048, 128  # the bench shape (MXU-native head dim)
    args = tuple(jax.ShapeDtypeStruct((N, T, D), jnp.bfloat16)
                 for _ in range(3))
    exp = jax.export.export(
        jax.jit(lambda q, k, v: flash_attention_packed(
            q, k, v, causal=True, kernel=kern, **opts)),
        platforms=["tpu"])(*args)
    _assert_mosaic(exp.mlir_module())


def test_flash_sliding_window_lowers_through_mosaic():
    # windowed liveness/masks ride the grid schedule's predication —
    # the banded long-context path must lower for the real target
    from accl_tpu.ops.flash import flash_attention_packed

    N, T, D = 4, 4096, 128
    a = jax.ShapeDtypeStruct((N, T, D), jnp.bfloat16)
    exp = jax.export.export(
        jax.jit(lambda q, k, v: flash_attention_packed(
            q, k, v, causal=True, window=1024, kernel="grid")),
        platforms=["tpu"])(a, a, a)
    _assert_mosaic(exp.mlir_module())


@pytest.mark.parametrize("kern", ["resident", "grid"])
def test_flash_gqa_lowers_through_mosaic(kern):
    # GQA: the grouped K/V index maps (b // group) must lower — a map
    # regression would strand the Llama-family layout in interpret mode
    from accl_tpu.ops.flash import flash_attention_packed

    N, Nk, T, D = 8, 2, 2048, 128
    q = jax.ShapeDtypeStruct((N, T, D), jnp.bfloat16)
    kv = jax.ShapeDtypeStruct((Nk, T, D), jnp.bfloat16)
    exp = jax.export.export(
        jax.jit(lambda q, k, v: flash_attention_packed(
            q, k, v, causal=True, kernel=kern)),
        platforms=["tpu"])(q, kv, kv)
    _assert_mosaic(exp.mlir_module())


@pytest.mark.parametrize("opts", [
    # fused-denominator scratch build (f32 -> bf16 K cast + ones-V)
    {"q_tiles": 2, "fuse_denom": True},
    # the two-buffer one-shot K/V cast scratch branch (the _cast sweep
    # candidates) — distinct scratch path from fuse_denom
    {"kv_cast_scratch": True},
    {"kv_cast_scratch": True, "q_tiles": 2},
])
def test_flash_scratch_paths_lower_through_mosaic(opts):
    # f32 inputs + bf16 MXU dtype: every VMEM scratch branch of the
    # resident kernel must lower, or live-chip sweep candidates die
    # DEAD in a scarce claim window
    from accl_tpu.ops.flash import flash_attention_packed

    N, T, D = 4, 2048, 128
    args = tuple(jax.ShapeDtypeStruct((N, T, D), jnp.float32)
                 for _ in range(3))
    exp = jax.export.export(
        jax.jit(lambda q, k, v: flash_attention_packed(
            q, k, v, causal=True, kernel="resident", **opts)),
        platforms=["tpu"])(*args)
    _assert_mosaic(exp.mlir_module())


def test_reduce_lane_lowers_through_mosaic():
    from accl_tpu.ops.reduce_ops import pallas_add

    x = jax.ShapeDtypeStruct((1 << 16, 128), jnp.float32)
    exp = jax.export.export(
        jax.jit(lambda a, b: pallas_add(a, b, interpret=False)),
        platforms=["tpu"])(x, x)
    _assert_mosaic(exp.mlir_module())


def test_flash_backward_lowers_through_mosaic():
    # the custom-VJP backward (dq and dk/dv kernels) must lower for the
    # real TPU target too — training on hardware runs exactly this
    from accl_tpu.ops.flash import flash_attention_packed

    N, T, D = 4, 2048, 128
    arg = jax.ShapeDtypeStruct((N, T, D), jnp.bfloat16)

    def loss(q, k, v):
        return jnp.sum(flash_attention_packed(
            q, k, v, causal=True, kernel="resident").astype(jnp.float32))

    exp = jax.export.export(
        jax.jit(jax.grad(loss, argnums=(0, 1, 2))),
        platforms=["tpu"])(arg, arg, arg)
    text = exp.mlir_module()
    _assert_mosaic(text)


@pytest.mark.parametrize("which", ["allgather", "reduce_scatter",
                                   "allreduce"])
def test_selfring_lowers_through_mosaic(which):
    """The single-device VIRTUAL self-ring (ring_size override — the
    execute-the-artifact rung bench.py runs compiled on the chip) must
    lower through Mosaic on a 1-member axis: real remote-DMA ops with
    device_id = self, the extended V-step hop loop, and the ACK-window
    semaphores all survive the TPU pipeline."""
    from accl_tpu.ops import ring as R

    V = 8
    n = 512
    mesh = AbstractMesh((1,), ("r",),
                        axis_types=(jax.sharding.AxisType.Explicit,))
    body = {
        "allgather": lambda v: R.ring_all_gather_pallas(
            v, "r", ring_size=V),
        "reduce_scatter": lambda v: R.ring_reduce_scatter_pallas(
            v, "r", ring_size=V),
        "allreduce": lambda v: R.ring_all_reduce_pallas(
            v, "r", ring_size=V),
    }[which]
    shape = {"allgather": (n, 128), "reduce_scatter": (V, n, 128),
             "allreduce": (V * n, 128)}[which]
    fn = jax.shard_map(body, mesh=mesh, in_specs=P(),
                       out_specs=P(), check_vma=False)
    x = jax.ShapeDtypeStruct(shape, jnp.float32,
                             sharding=NamedSharding(mesh, P()))
    exp = jax.export.export(jax.jit(fn), platforms=["tpu"])(x)
    _assert_mosaic(exp.mlir_module())


def test_flash_gqa_backward_lowers_through_mosaic():
    """The r5 expansion-free GQA backward: grouped K/V via b//G index
    maps (dq) and the G-extended accumulation axis with divmod q
    row/block index maps (dkv) must survive the real TPU lowering."""
    from accl_tpu.ops.flash import flash_attention_packed

    N, G, T, D = 8, 2, 1024, 128
    q = jax.ShapeDtypeStruct((N, T, D), jnp.bfloat16)
    kv = jax.ShapeDtypeStruct((G, T, D), jnp.bfloat16)

    def loss(q, k, v):
        # GQA is shape-driven on the packed entry: k/v carry G rows
        return jnp.sum(flash_attention_packed(
            q, k, v, causal=True,
            kernel="resident").astype(jnp.float32))

    exp = jax.export.export(
        jax.jit(jax.grad(loss, argnums=(0, 1, 2))),
        platforms=["tpu"])(q, kv, kv)
    _assert_mosaic(exp.mlir_module())


def test_flash_static_max_lowers_through_mosaic():
    """The r5 static-max resident schedule (pinned softmax shift, no
    max/alpha VPU passes) must lower for the real TPU target."""
    from accl_tpu.ops.flash import flash_attention_packed

    arg = jax.ShapeDtypeStruct((4, 2048, 128), jnp.float32)
    exp = jax.export.export(
        jax.jit(lambda q, k, v: flash_attention_packed(
            q, k, v, causal=True, kernel="resident", static_max=40.0)),
        platforms=["tpu"])(arg, arg, arg)
    _assert_mosaic(exp.mlir_module())
