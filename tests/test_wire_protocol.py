"""Wire-protocol hardening (r13): malformed-frame rejection through the
real ingress classification path, the egress frame tap, and the
suite-exit teardown regressions.

The ingest hook (``accl_engine_ingest_bytes``) feeds raw frames to the
same validation + demux every transport delivery runs, so these tests
pin the ingress contract directly: a malformed frame increments the
rejection counter and changes NOTHING else — the engine stays live.

The teardown tests pin the r13 suite-exit segfault fix (rc=139 after
the pytest summary): each scenario runs in a subprocess and must exit
with the interpreter's rc, never a signal.  Root cause + fix ordering:
docs/debugging.md "The suite-exit segfault".
"""
import ctypes
import os
import subprocess
import sys

import numpy as np
import pytest

from accl_tpu.backends.emu import EmuWorld, _load_lib
from accl_tpu.utils.wire import HEADER_SIZE, MSG_TYPES, WireFrame

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def world():
    with EmuWorld(2) as w:
        yield w


def _alive(w):
    """The post-injection liveness probe: a real collective must still
    run end-to-end and produce correct data."""

    def fn(accl, rank):
        src = accl.create_buffer(16, np.float32)
        src.host[:] = rank + 1.0
        src.sync_to_device()
        dst = accl.create_buffer(16, np.float32)
        accl.allreduce(src, dst, 16)
        dst.sync_from_device()
        np.testing.assert_allclose(dst.host, 3.0)

    w.run(fn)


# ---------------------------------------------------------------------------
# malformed-frame rejection: one bad frame per message type
# ---------------------------------------------------------------------------
#: (name, frame-bytes builder) — every entry must be REJECTED
_MALFORMED = [
    ("truncated_header", lambda: b"\x00" * (HEADER_SIZE - 10)),
    ("unknown_msg_type", lambda: WireFrame(msg_type=77).pack()),
    ("egr_count_mismatch", lambda: WireFrame(
        msg_type=MSG_TYPES["egr"], src=1, count=100,
        payload=b"\x01" * 4).pack()),
    ("egr_oversized_segment", lambda: WireFrame(
        msg_type=MSG_TYPES["egr"], src=1, count=5000,
        payload=b"\x02" * 5000).pack()),  # > the 1024B rx buffer
    ("egr_comm_out_of_range", lambda: WireFrame(
        msg_type=MSG_TYPES["egr"], src=1, comm_id=1 << 20,
        count=4, payload=b"\x03" * 4).pack()),
    ("rndzvs_msg_count_mismatch", lambda: WireFrame(
        msg_type=MSG_TYPES["rndzvs_msg"], src=1, count=64,
        vaddr=0x2000, payload=b"\x04" * 8).pack()),
    ("rndzvs_init_comm_out_of_range", lambda: WireFrame(
        msg_type=MSG_TYPES["rndzvs_init"], src=1,
        comm_id=1 << 16, count=16).pack()),
    ("rndzvs_wrdone_comm_out_of_range", lambda: WireFrame(
        msg_type=MSG_TYPES["rndzvs_wrdone"], src=1,
        comm_id=1 << 16).pack()),
    ("nack_comm_out_of_range", lambda: WireFrame(
        msg_type=MSG_TYPES["nack"], src=1, comm_id=1 << 10).pack()),
    ("heartbeat_comm_out_of_range", lambda: WireFrame(
        msg_type=MSG_TYPES["heartbeat"], src=1, count=1,
        comm_id=1 << 10).pack()),
    ("abort_comm_out_of_range", lambda: WireFrame(
        msg_type=MSG_TYPES["abort"], src=1, comm_id=1 << 10,
        count=1 << 27).pack()),
    ("state_sync_count_mismatch", lambda: WireFrame(
        msg_type=MSG_TYPES["state_sync"], src=1, count=400,
        payload=b"\x05" * 12).pack()),
]


@pytest.mark.parametrize("name,build", _MALFORMED,
                         ids=[n for n, _ in _MALFORMED])
def test_malformed_frame_rejected_engine_stays_live(world, name, build):
    dev = world.devices[0]
    before = dev.frame_stats(publish=False)["rejected_frames"]
    rc = dev.ingest_bytes(build())
    assert rc == 1, f"{name}: malformed frame was not rejected"
    after = dev.frame_stats(publish=False)["rejected_frames"]
    assert after == before + 1, f"{name}: rejection counter did not move"
    _alive(world)


def test_stale_epoch_frame_fenced_not_rejected(world):
    """A well-formed frame on a dead epoch is a FENCE drop (the r10
    abort discipline), not a malformed-frame rejection — the two
    counters stay distinct diagnostics."""
    dev = world.devices[0]
    stale = WireFrame(msg_type=MSG_TYPES["egr"], src=1, comm_id=0,
                      epoch=7, count=4, payload=b"\x06" * 4).pack()
    before_rej = dev.frame_stats(publish=False)["rejected_frames"]
    before_fen = dev.resilience_stats()["fenced_drops"]
    assert dev.ingest_bytes(stale) == 0  # consumed (by the fence gate)
    assert dev.frame_stats(publish=False)["rejected_frames"] == before_rej
    assert dev.resilience_stats()["fenced_drops"] == before_fen + 1
    _alive(world)


def test_wellformed_control_frames_consumed(world):
    """Well-formed heartbeat/join/welcome frames pass validation (the
    join pair is session-addressed and legal pre-communicator)."""
    dev = world.devices[0]
    for f in (
        WireFrame(msg_type=MSG_TYPES["heartbeat"], src=1, count=0),
        WireFrame(msg_type=MSG_TYPES["join"], src=1, count=1),
        WireFrame(msg_type=MSG_TYPES["welcome"], src=1, count=2),
    ):
        assert dev.ingest_bytes(f.pack()) == 0, f.type_name
    _alive(world)


def test_rejection_counter_reaches_metrics_registry(world):
    from accl_tpu.observability import metrics as _metrics

    dev = world.devices[0]
    reg = _metrics.default_registry()
    before = reg.counter("wire/rejected_frames")
    dev.ingest_bytes(b"short")
    dev.frame_stats()  # publishes the delta
    assert reg.counter("wire/rejected_frames") >= before + 1


# ---------------------------------------------------------------------------
# frame tap: the fuzz seed-corpus capture
# ---------------------------------------------------------------------------
def test_frame_tap_captures_real_traffic(world):
    for d in world.devices:
        d.frame_tap(True)
    _alive(world)
    frames = [f for d in world.devices for f in d.tap_frames()]
    assert frames, "tap captured nothing"
    types = {WireFrame.unpack(f).msg_type for f in frames}
    assert MSG_TYPES["egr"] in types
    # every captured frame must round-trip the codec and re-ingest as
    # well-formed (the seed-corpus invariant the fuzzer relies on)
    for f in frames[:8]:
        wf = WireFrame.unpack(f)
        assert wf.pack() == f
    for d in world.devices:
        d.frame_tap(False)


# ---------------------------------------------------------------------------
# suite-exit teardown regressions (rc must be the interpreter's, not a
# signal — pre-fix these scenarios could die with rc=139)
# ---------------------------------------------------------------------------
def _run_sub(code: str) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=120)


def test_null_world_ffi_calls_are_safe():
    """ctypes None -> NULL world pointer: every capi entry must return
    an error, never dereference (the deterministic half of the
    suite-exit segfault: a late waiter thread after close())."""
    lib = _load_lib()
    ret = ctypes.c_uint32(0)
    dur = ctypes.c_double(0.0)
    assert lib.accl_wait_call(None, 0, 1, 5, ctypes.byref(ret),
                              ctypes.byref(dur)) == 0
    assert lib.accl_poll_call(None, 0, 1, ctypes.byref(ret),
                              ctypes.byref(dur)) == 0
    assert lib.accl_start_call(None, 0, _null_words()) == 0
    assert lib.accl_abort(None, 0, 0, 0) == -1
    assert lib.accl_plan_count(None, 0) == -1
    lib.accl_world_shutdown(None)
    lib.accl_world_destroy(None)


def _null_words():
    return (ctypes.c_uint32 * 15)()


def test_close_with_pending_call_exits_clean():
    """World closed while a call is pending and its waiter thread is
    inside accl_wait_call: shutdown must finalize the call, the waiter
    must be joined, and the process must exit 0 promptly."""
    rc = _run_sub(
        "import numpy as np, time\n"
        "from accl_tpu.backends.emu import EmuWorld\n"
        "w = EmuWorld(2)\n"
        "a = w.accls[0]\n"
        "buf = a.create_buffer(64, np.float32)\n"
        "req = a.recv(buf, 64, src=1, tag=5, run_async=True)\n"
        "time.sleep(0.05)\n"
        "w.close()\n"
        "time.sleep(0.2)\n")
    assert rc.returncode == 0, rc.stderr[-2000:]


def test_leaked_world_interpreter_exit_clean():
    """A world the test code never closed must not crash interpreter
    shutdown (engine threads vs static destructors): the atexit safety
    net closes it first."""
    rc = _run_sub(
        "import numpy as np\n"
        "from accl_tpu.backends.emu import EmuWorld\n"
        "w = EmuWorld(2)\n"
        "a = w.accls[0]\n"
        "buf = a.create_buffer(64, np.float32)\n"
        "req = a.recv(buf, 64, src=1, tag=5, run_async=True)\n"
        "# exit with the world leaked and the call pending\n")
    assert rc.returncode == 0, rc.stderr[-2000:]
