"""Extended emulator coverage mirroring the rest of the reference
corpus: multi-communicator incl. splits (test.cpp :621-753), compressed
rooted collectives (:381-1002), the rendezvous retry queue, and timeout
fault surfacing."""
import time

import numpy as np
import pytest

from accl_tpu import ACCLError, DataType, ReduceFunction
from accl_tpu.accl import default_timeout
from accl_tpu.backends.emu import EmuWorld

NRANKS = 4
COUNT = 128


@pytest.fixture(scope="module")
def world():
    with EmuWorld(NRANKS) as w:
        yield w


def _data(count, rank, salt=0):
    rng = np.random.default_rng(900 + rank + salt * 77)
    return rng.standard_normal(count).astype(np.float32)


# ---------------------------------------------------------------------------
# multi-communicator (reference: test_multicomm / split comms)
# ---------------------------------------------------------------------------
def test_split_communicator_collectives(world):
    members = [1, 2, 3]

    def fn(accl, rank):
        if rank not in members:
            return None
        cid = accl.create_communicator(members)
        # allreduce inside the sub-communicator
        send = accl.create_buffer_like(_data(COUNT, rank))
        recv = accl.create_buffer(COUNT, np.float32)
        accl.allreduce(send, recv, COUNT, ReduceFunction.SUM, comm_id=cid)
        exp = np.sum([_data(COUNT, m) for m in members], axis=0)
        np.testing.assert_allclose(recv.host, exp, rtol=1e-5, atol=1e-5)
        # bcast from sub-root 1 (= global rank 2)
        buf = accl.create_buffer_like(_data(COUNT, rank, salt=1))
        accl.bcast(buf, COUNT, 1, comm_id=cid)
        np.testing.assert_array_equal(buf.host, _data(COUNT, 2, salt=1))
        return cid

    cids = [c for c in world.run(fn) if c is not None]
    assert all(c == cids[0] for c in cids)


def test_two_disjoint_subcomms():
    # {0,1} and {2,3} operate concurrently without crosstalk.  Fresh
    # world: communicator creation is collective and order-sensitive —
    # ids must align across members exactly as the reference's
    # exchange-memory communicator addresses must (communicator.cpp:23).
    with EmuWorld(NRANKS) as w:
        _run_disjoint(w)


def _run_disjoint(world):
    def fn(accl, rank):
        group = [0, 1] if rank < 2 else [2, 3]
        cid = accl.create_communicator(group)
        send = accl.create_buffer_like(_data(COUNT, rank, salt=2))
        recv = accl.create_buffer(COUNT, np.float32)
        accl.allreduce(send, recv, COUNT, ReduceFunction.SUM, comm_id=cid)
        exp = np.sum([_data(COUNT, m, salt=2) for m in group], axis=0)
        np.testing.assert_allclose(recv.host, exp, rtol=1e-5, atol=1e-5)

    world.run(fn)


# ---------------------------------------------------------------------------
# compressed rooted collectives (fp16 wire; tolerance per reference
# FLOAT16RTOL/ATOL with slack for multi-hop accumulation)
# ---------------------------------------------------------------------------
TOL = dict(rtol=5e-2, atol=5e-2)


def test_scatter_gather_compressed(world):
    root = 1

    def fn(accl, rank):
        send = accl.create_buffer_like(_data(COUNT * NRANKS, rank, salt=3))
        recv = accl.create_buffer(COUNT, np.float32)
        accl.scatter(send, recv, COUNT, root,
                     compress_dtype=DataType.float16)
        exp = _data(COUNT * NRANKS, root, salt=3)
        np.testing.assert_allclose(
            recv.host, exp[rank * COUNT:(rank + 1) * COUNT], **TOL)
        back = accl.create_buffer(COUNT * NRANKS, np.float32)
        accl.gather(recv, back, COUNT, root,
                    compress_dtype=DataType.float16)
        if rank == root:
            np.testing.assert_allclose(back.host, exp, **TOL)

    world.run(fn)


@pytest.mark.parametrize("func", [ReduceFunction.SUM, ReduceFunction.MAX])
def test_reduce_compressed(world, func):
    root = 2

    def fn(accl, rank):
        send = accl.create_buffer_like(_data(COUNT, rank, salt=4))
        recv = accl.create_buffer(COUNT, np.float32)
        accl.reduce(send, recv, COUNT, root, func,
                    compress_dtype=DataType.float16)
        if rank == root:
            inputs = [_data(COUNT, r, salt=4) for r in range(NRANKS)]
            exp = (np.sum(inputs, axis=0) if func == ReduceFunction.SUM
                   else np.max(inputs, axis=0))
            np.testing.assert_allclose(recv.host, exp, **TOL)

    world.run(fn)


def test_allgather_reduce_scatter_compressed(world):
    def fn(accl, rank):
        send = accl.create_buffer_like(_data(COUNT, rank, salt=5))
        recv = accl.create_buffer(COUNT * NRANKS, np.float32)
        accl.allgather(send, recv, COUNT, compress_dtype=DataType.float16)
        exp = np.concatenate([_data(COUNT, r, salt=5) for r in range(NRANKS)])
        np.testing.assert_allclose(recv.host, exp, **TOL)

        send2 = accl.create_buffer_like(_data(COUNT * NRANKS, rank, salt=6))
        recv2 = accl.create_buffer(COUNT, np.float32)
        accl.reduce_scatter(send2, recv2, COUNT, ReduceFunction.SUM,
                            compress_dtype=DataType.float16)
        inputs = [_data(COUNT * NRANKS, r, salt=6) for r in range(NRANKS)]
        exp2 = np.sum(inputs, axis=0)[rank * COUNT:(rank + 1) * COUNT]
        np.testing.assert_allclose(recv2.host, exp2, **TOL)

    world.run(fn)


# ---------------------------------------------------------------------------
# retry queue: a rendezvous recv parked long before its sender arrives
# must resume from its saved step (fw NOT_READY re-queue :2460-2479)
# ---------------------------------------------------------------------------
def test_rendezvous_retry_queue(world):
    count = 4096  # > eager threshold

    def fn(accl, rank):
        if rank == 2:
            dst = accl.create_buffer(count, np.float32)
            req = accl.recv(dst, count, 3, tag=77, run_async=True)
            # engine parks the call; other work proceeds meanwhile
            probe = accl.create_buffer_like(_data(16, rank))
            out = accl.create_buffer(16, np.float32)
            accl.copy(probe, out, 16)  # engine still responsive
            assert req.wait(30)
            req.check()
            np.testing.assert_array_equal(dst.host, _data(count, 3, salt=9))
        elif rank == 3:
            time.sleep(0.5)  # force many NOT_READY retries on rank 2
            src = accl.create_buffer_like(_data(count, 3, salt=9))
            accl.send(src, count, 2, tag=77)

    world.run(fn)


# ---------------------------------------------------------------------------
# fault surfacing: engine timeout -> RECEIVE_TIMEOUT_ERROR retcode
# ---------------------------------------------------------------------------
def test_preconfig_delivery_survives_bringup():
    # Bring-up race (the historical TCP-rung flake): the transport and
    # ingress are live from engine construction, so a peer racing ahead
    # can deliver an eager message BEFORE the receiver's rx pool is
    # configured.  Those deposits stage against zero buffers and must be
    # installed when configure() runs — silent loss here deadlocks the
    # first collective on both sides.
    from accl_tpu.communicator import Rank

    with EmuWorld(2, initialize=False) as w:
        ranks = [Rank(ip="127.0.0.1", port=0, session=r,
                      max_segment_size=1024) for r in range(2)]
        w.accls[1].initialize(ranks, 1)
        data = np.arange(64, dtype=np.float32)
        src = w.accls[1].create_buffer_like(data)
        req = w.accls[1].send(src, 64, 0, tag=7, run_async=True)
        time.sleep(0.3)  # let the message land while rank 0 is unconfigured
        w.accls[0].initialize(ranks, 0)
        dst = w.accls[0].create_buffer(64, np.float32)
        w.accls[0].recv(dst, 64, 1, tag=7)
        assert req.wait(timeout=30)
        req.check()
        np.testing.assert_array_equal(dst.host, data)


def test_rendezvous_retry_deadline(world):
    # a rendezvous recv whose sender NEVER arrives must finalize with the
    # engine's own RECEIVE_TIMEOUT_ERROR once the receive budget expires
    # — the reference retries NOT_READY forever (fw :2460-2479), which
    # turns a dead peer into an opaque host-side hang
    def fn(accl, rank):
        if rank != 0:
            return
        accl.set_timeout(300_000)  # 300 ms budget
        try:
            dst = accl.create_buffer(4096, np.float32)  # > eager: rndzv
            t0 = time.time()
            with pytest.raises(ACCLError, match="RECEIVE_TIMEOUT_ERROR"):
                accl.recv(dst, 4096, 1, tag=54321)
            assert time.time() - t0 < 30, "retry loop failed to expire"
        finally:
            accl.set_timeout(default_timeout())  # module-scoped world

    world.run(fn)


def test_timeout_surfaces_as_error(world):
    def fn(accl, rank):
        if rank != 0:
            return
        accl.set_timeout(30_000)  # 30ms emulated
        try:
            dst = accl.create_buffer(8, np.float32)
            with pytest.raises(ACCLError, match="RECEIVE_TIMEOUT_ERROR"):
                accl.recv(dst, 8, 1, tag=12345)
        finally:
            accl.set_timeout(default_timeout())  # module-scoped world

    world.run(fn)


# ---------------------------------------------------------------------------
# bfloat16 on-path reduction (TPU-extension arithmetic lanes 10/11 — the
# reference reduce_ops set stops at fp16, reduce_ops.cpp:31-107)
# ---------------------------------------------------------------------------
def test_allreduce_bfloat16(world):
    import ml_dtypes

    bf16 = np.dtype(ml_dtypes.bfloat16)
    count = 256

    def fn(accl, rank):
        send = accl.create_buffer_like(np.full(count, rank + 1, bf16))
        recv = accl.create_buffer(count, bf16)
        accl.allreduce(send, recv, count)
        expect = sum(range(1, world.nranks + 1))
        np.testing.assert_allclose(recv.host.astype(np.float32),
                                   float(expect))

    world.run(fn)


def test_combine_max_bfloat16(world):
    import ml_dtypes
    from accl_tpu import ReduceFunction

    bf16 = np.dtype(ml_dtypes.bfloat16)
    count = 64

    def fn(accl, rank):
        a = accl.create_buffer_like(np.full(count, 2.5, bf16))
        b = accl.create_buffer_like(np.full(count, 7.5, bf16))
        r = accl.create_buffer(count, bf16)
        accl.combine(count, ReduceFunction.MAX, a, b, r)
        np.testing.assert_allclose(r.host.astype(np.float32), 7.5)

    world.run(fn)


# ---------------------------------------------------------------------------
# p2p buffers (reference: FPGABufferP2P + test_copy_p2p, test.cpp:63-85)
# ---------------------------------------------------------------------------
def test_p2p_buffer_zero_copy_and_wire_bypass(world):
    # A p2p buffer's host view IS the engine devicemem (bo.map analog):
    # data landed by a peer is visible with NO sync, and the rendezvous
    # one-sided write into it moves ZERO payload bytes over the
    # transport (direct peer-devicemem write, native engine rndzv_send
    # fast path) — only the small RNDZVS_INIT control message crosses.
    count = 4096  # 16 KB fp32: rendezvous protocol

    def fn(accl, rank):
        if rank == 0:
            src = accl.create_buffer_like(_data(count, 0, salt=61))
            _, pay0 = accl._device.tx_stats()
            accl.send(src, count, 1, tag=77)
            _, pay1 = accl._device.tx_stats()
            assert pay1 == pay0, (
                f"p2p rendezvous send moved {pay1 - pay0} payload bytes "
                "over the wire")
        elif rank == 1:
            dst = accl.create_buffer_p2p(count, np.float32)
            from accl_tpu.buffer import EmuBufferP2P
            assert isinstance(dst, EmuBufferP2P)
            accl.recv(dst, count, 0, tag=77)
            np.testing.assert_array_equal(dst.host,
                                          _data(count, 0, salt=61))

    world.run(fn)


def test_p2p_buffer_local_copy(world):
    # the reference test shape: copy a normal buffer into an own p2p
    # buffer; the result is visible through the mapping without sync
    def fn(accl, rank):
        data = _data(64, rank, salt=62)
        src = accl.create_buffer_like(data)
        p2p = accl.create_buffer_p2p(64, np.float32)
        accl.copy(src, p2p, 64)
        np.testing.assert_array_equal(p2p.host, data)

    world.run(fn)


def test_descriptor_memo_survives_address_reuse():
    """The driver's _build memo keys on (address, dtype, host-only) per
    buffer: the emulator's first-fit allocator REUSES freed addresses,
    so an address-only key could serve a stale fp32 arithcfg for a
    recycled address holding f16 data — silent wrong-lane reduction
    with retcode 0 (r5 review finding; this is the regression lock)."""
    with EmuWorld(nranks=2) as world:
        def worker(accl, rank):
            n = 256
            s32 = accl.create_buffer_like(
                np.full(n, float(rank + 1), np.float32))
            r32 = accl.create_buffer(n, np.float32)
            accl.allreduce(s32, r32, n, ReduceFunction.SUM)
            np.testing.assert_allclose(r32.host, 3.0)
            a_s, a_r = s32.address, r32.address
            s32.free(); r32.free()
            # the first-fit allocator hands the freed span back: the
            # new f16 operand lands at the OLD fp32 operand's address
            # (the half-size f16 result lands inside the span's
            # remainder — address reuse is the hazard, exact span
            # geometry is not)
            s16 = accl.create_buffer_like(
                np.full(n, float(rank + 1), np.float16))
            r16 = accl.create_buffer(n, np.float16)
            assert s16.address == a_s, \
                "allocator no longer reuses addresses; test needs a new way"
            accl.allreduce(s16, r16, n, ReduceFunction.SUM)
            np.testing.assert_allclose(r16.host.astype(np.float32), 3.0)
            return True

        assert all(world.run(worker))
