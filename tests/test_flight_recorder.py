"""Flight recorder, hang/desync watchdog and the health/OpenMetrics
surface (accl_tpu/observability/flight.py + health.py): always-on
record lifecycle on both backends, flight-embedded timeout errors,
watchdog hang diagnosis naming the missing rank, the cross-rank desync
analyzer, gang-assembly introspection, and the exporter endpoints."""
import json
import time
import urllib.request

import numpy as np
import pytest

from accl_tpu import ACCLError, ReduceFunction
from accl_tpu.observability import flight as obs_flight
from accl_tpu.observability import health as obs_health
from accl_tpu.observability import metrics as obs_metrics
from accl_tpu.observability.trace import now_ns

COUNT = 64
NRANKS = 4


def _allreduce_all(world, reps=1):
    def fn(accl, rank):
        s = accl.create_buffer_like(
            np.arange(COUNT, dtype=np.float32) + rank)
        r = accl.create_buffer(COUNT, np.float32)
        for _ in range(reps):
            accl.allreduce(s, r, COUNT, ReduceFunction.SUM)
        return r.host.copy()

    return world.run(fn)


# ---------------------------------------------------------------------------
# always-on record lifecycle
# ---------------------------------------------------------------------------
def test_flight_records_tpu_gang_lifecycle():
    from accl_tpu.backends.tpu import TpuWorld

    with TpuWorld(NRANKS) as w:
        _allreduce_all(w, reps=2)
        for accl in w.accls:
            rec_list = [r for r in accl.flight_recorder.records()
                        if r.collective == "allreduce"]
            assert len(rec_list) == 2
            for rec in rec_list:
                assert rec.gang and not rec.in_flight
                assert rec.state == obs_flight.S_COMPLETE
                assert rec.lane in ("leader", "executor", "batched")
                assert rec.dtype == "float32"
                assert rec.nbytes == COUNT * 4
                # full state-machine walk, stamped in order
                assert (rec.t_submit <= rec.t_queue <= rec.t_gang_ready
                        <= rec.t_dispatch <= rec.t_complete)
            # per-rank seq is monotonic and completion advanced the
            # recorder's high-water mark
            seqs = [r.seq for r in rec_list]
            assert seqs == sorted(seqs)
            assert accl.flight_recorder.last_completed_seq >= seqs[-1]


def test_flight_records_emu_lane():
    from accl_tpu.backends.emu import EmuWorld

    with EmuWorld(2) as w:
        _allreduce_all(w)
        for accl in w.accls:
            (rec,) = [r for r in accl.flight_recorder.records()
                      if r.collective == "allreduce"]
            assert rec.lane == "emu"
            assert rec.state == obs_flight.S_COMPLETE
            assert rec.t_submit <= rec.t_queue <= rec.t_dispatch \
                <= rec.t_complete


def test_flight_ring_is_bounded_and_disableable():
    rec = obs_flight.FlightRecorder(rank=0, capacity=4)
    for i in range(10):
        r = rec.new_record(i, "allreduce", 0, 0, "float32", 8, 32, 2,
                           True, now_ns())
        r.finish(0, now_ns())
    assert len(rec) == 4
    assert [r.seq for r in rec.records()] == [6, 7, 8, 9]
    assert rec.last_completed_seq == 9
    # the ACCL_FLIGHT=0 switch: no records attached while off
    obs_flight.set_enabled(False)
    try:
        assert not obs_flight.enabled()
        from accl_tpu.backends.tpu import TpuWorld

        with TpuWorld(2) as w:
            _allreduce_all(w)
            assert all(a.flight_recorder is None for a in w.accls)
    finally:
        obs_flight.set_enabled(True)
    assert obs_flight.enabled()


def test_dump_schema_and_dump_flight_recorder_api(tmp_path):
    from accl_tpu.backends.emu import EmuWorld

    with EmuWorld(2) as w:
        _allreduce_all(w)
        doc = w.accls[0].dump_flight_recorder(
            path=str(tmp_path / "r0.json"))
        assert doc["rank"] == 0
        for rec in doc["records"]:
            assert set(obs_flight.RECORD_SCHEMA_KEYS) <= set(rec)
        with open(tmp_path / "r0.json") as f:
            assert json.load(f)["rank"] == 0
        merged = w.accls[0].dump_flight_recorder(merged=True)
        assert merged["nranks"] >= 2
        assert merged["analysis"]["ok"]


# ---------------------------------------------------------------------------
# flight-embedded timeout errors + configurable wait default
# ---------------------------------------------------------------------------
def test_check_on_in_flight_request_embeds_flight_record():
    from accl_tpu.request import Request

    recr = obs_flight.FlightRecorder(rank=3)
    req = Request("allreduce(SUM)")
    req.flight = recr.new_record(req.id, "allreduce", 0, 0, "float32",
                                 64, 256, 4, True, now_ns())
    req.flight.lane = "emu"
    with pytest.raises(ACCLError) as ei:
        req.check()
    msg = str(ei.value)
    assert "seq=0" in msg and "state=submitted" in msg \
        and "lane=emu" in msg and "age=" in msg


def test_driver_timeout_error_embeds_flight_record():
    from accl_tpu.backends.emu import EmuWorld

    with EmuWorld(2) as w:
        def fn(accl, rank):
            buf = accl.create_buffer(COUNT, np.float32)
            if rank == 0:
                accl.call_timeout_s = 0.2  # driver budget fires first
                with pytest.raises(ACCLError) as ei:
                    accl.recv(buf, COUNT, src=1)
                accl.call_timeout_s = 60.0
                msg = str(ei.value)
                assert "timed out" in msg and "[flight:" in msg \
                    and "recv" in msg and "lane=emu" in msg
                return msg
            # unblock rank 0's pending engine recv before teardown
            time.sleep(0.5)
            src = accl.create_buffer_like(
                np.arange(COUNT, dtype=np.float32))
            accl.send(src, COUNT, dst=0)
            return None

        w.run(fn)


def test_wait_default_configurable_via_env(monkeypatch):
    from accl_tpu import request as request_mod

    monkeypatch.setenv("ACCL_DEFAULT_TIMEOUT", "2000000")  # 2 s engine
    assert request_mod.default_wait_timeout_s() == pytest.approx(61.0)
    monkeypatch.setenv("ACCL_DEFAULT_TIMEOUT", "3e7")
    assert request_mod.default_wait_timeout_s() == pytest.approx(89.0)
    # a bare wait() resolves the default (and still times out/false on
    # an incomplete request when given a tiny explicit budget)
    req = request_mod.Request("never")
    assert req.wait(timeout=0.01) is False


# ---------------------------------------------------------------------------
# watchdog: hang detection names the missing rank
# ---------------------------------------------------------------------------
def test_watchdog_fires_and_names_missing_rank(tmp_path):
    from accl_tpu.backends.emu import EmuWorld

    with EmuWorld(NRANKS) as w:
        wd = w.start_watchdog(timeout_s=0.3,
                              dump_path=str(tmp_path / "wd.json"))
        bufs = {}

        def setup(accl, rank):
            s = accl.create_buffer_like(
                np.arange(COUNT, dtype=np.float32) + rank)
            bufs[rank] = (s, accl.create_buffer(COUNT, np.float32))

        w.run(setup)
        reqs = {}

        def issue(accl, rank):
            if rank == 0:
                return None  # withheld gang member
            s, r = bufs[rank]
            reqs[rank] = accl.allreduce(s, r, COUNT, ReduceFunction.SUM,
                                        run_async=True)

        w.run(issue)
        deadline = time.time() + 15
        while wd.last_report is None and time.time() < deadline:
            time.sleep(0.02)
        assert wd.last_report is not None, "watchdog never fired"
        (hang,) = wd.last_report["analysis"]["hangs"]
        assert hang["collective"] == "allreduce"
        assert hang["arrived"] == [1, 2, 3]
        assert hang["missing"] == [0]
        assert hang["missing_blocked_on"]["0"] is None  # rank 0 idle
        assert (tmp_path / "wd.json").exists()  # automatic dump
        # the hung verdict is on the gauge the exporter serves
        snap = obs_metrics.default_registry().snapshot()
        assert snap["gauges"]["accl_health"] == obs_health.HEALTH_HUNG
        assert snap["counters"]["watchdog/fires"] >= 1

        # resolution: the missing rank joins, everything completes, and
        # the next watchdog sweep restores health
        def join(accl, rank):
            if rank != 0:
                return None
            s, r = bufs[rank]
            accl.allreduce(s, r, COUNT, ReduceFunction.SUM)

        w.run(join)
        for rank in (1, 2, 3):
            assert reqs[rank].wait(60)
            reqs[rank].check()
        deadline = time.time() + 10
        while time.time() < deadline:
            snap = obs_metrics.default_registry().snapshot()
            if snap["gauges"]["accl_health"] == obs_health.HEALTH_OK:
                break
            time.sleep(0.05)
        assert snap["gauges"]["accl_health"] == obs_health.HEALTH_OK


def test_watchdog_degraded_after_engine_error():
    recr = obs_flight.FlightRecorder(rank=0)
    rec = recr.new_record(0, "allreduce", 0, 0, "float32", 8, 32, 2,
                          True, now_ns())
    rec.finish(5, now_ns())  # non-zero retcode
    reg = obs_metrics.MetricsRegistry()
    wd = obs_health.Watchdog([recr], timeout_s=10, registry=reg,
                             dump_path="")
    assert wd.check() is None
    assert reg.snapshot()["gauges"]["accl_health"] \
        == obs_health.HEALTH_DEGRADED


def test_watchdog_direct_check_reports_stuck_record(tmp_path):
    recr = obs_flight.FlightRecorder(rank=1)
    recr.new_record(0, "bcast", 0, 5, "float32", 8, 32, 2, True,
                    now_ns() - int(1e9))  # submitted 1 s ago
    reg = obs_metrics.MetricsRegistry()
    wd = obs_health.Watchdog([recr], timeout_s=0.2, registry=reg,
                             dump_path=str(tmp_path / "d.json"))
    report = wd.check()
    assert report is not None
    assert report["watchdog"]["stuck_records"][0]["collective"] == "bcast"
    assert reg.snapshot()["gauges"]["accl_health"] \
        == obs_health.HEALTH_HUNG
    # one fire per hang episode: a second sweep does not re-fire
    assert wd.check() is None


def test_tpu_gang_assembly_introspection():
    from accl_tpu.backends.tpu import TpuWorld

    with TpuWorld(2) as w:
        s0 = w.accls[0].create_buffer_like(
            np.arange(COUNT, dtype=np.float32))
        r0 = w.accls[0].create_buffer(COUNT, np.float32)
        req0 = w.accls[0].allreduce(s0, r0, COUNT, ReduceFunction.SUM,
                                    run_async=True)
        deadline = time.time() + 10
        snap = []
        while time.time() < deadline:
            snap = [g for g in w.engine.gang_assembly_snapshot()
                    if g.get("kind") == "collective"]
            if snap:
                break
            time.sleep(0.01)
        assert snap, "partial gang never visible to introspection"
        assert snap[0]["collective"] == "allreduce"
        assert snap[0]["arrived"] == [0]
        assert snap[0]["missing"] == [1]
        # second member arrives: gang dispatches, assembly table drains
        s1 = w.accls[1].create_buffer_like(
            np.arange(COUNT, dtype=np.float32))
        r1 = w.accls[1].create_buffer(COUNT, np.float32)
        w.accls[1].allreduce(s1, r1, COUNT, ReduceFunction.SUM)
        assert req0.wait(60)
        req0.check()
        assert not [g for g in w.engine.gang_assembly_snapshot()
                    if g.get("kind") == "collective"]


# ---------------------------------------------------------------------------
# cross-rank desync analyzer (merge_flight_dumps / accl_doctor)
# ---------------------------------------------------------------------------
def _mk_recorder(rank, calls, inflight=()):
    """calls: (collective, comm, tag, count) completed in order;
    inflight: same shape, left in submitted state."""
    recr = obs_flight.FlightRecorder(rank=rank)
    for i, (coll, comm, tag, count) in enumerate(calls):
        rec = recr.new_record(i, coll, comm, tag, "float32", count,
                              count * 4, 2, True, now_ns())
        rec.finish(0, now_ns())
    for coll, comm, tag, count in inflight:
        recr.new_record(99, coll, comm, tag, "float32", count,
                        count * 4, 2, True, now_ns())
    return recr


def test_desync_analyzer_flags_first_divergent_seq():
    a = _mk_recorder(0, [("allreduce", 0, -1, 64), ("bcast", 0, -1, 64)])
    b = _mk_recorder(1, [("bcast", 0, -1, 64), ("allreduce", 0, -1, 64)])
    doc = obs_flight.merge_flight_dumps([a.dump(), b.dump()])
    (d,) = doc["analysis"]["desyncs"]
    assert d["comm"] == 0 and d["index"] == 0
    assert d["per_rank"]["0"]["collective"] == "allreduce"
    assert d["per_rank"]["1"]["collective"] == "bcast"
    assert not doc["analysis"]["ok"]


def test_desync_analyzer_flags_shape_mismatch_not_matching_prefix():
    a = _mk_recorder(0, [("allreduce", 0, -1, 64), ("allgather", 0, -1, 32)])
    b = _mk_recorder(1, [("allreduce", 0, -1, 64), ("allgather", 0, -1, 16)])
    doc = obs_flight.merge_flight_dumps([a.dump(), b.dump()])
    (d,) = doc["analysis"]["desyncs"]
    assert d["index"] == 1  # the matching allreduce prefix is NOT flagged
    assert d["per_rank"]["0"]["count"] == 32
    assert d["per_rank"]["1"]["count"] == 16


def test_analyzer_reports_hang_and_cross_blocked_rank():
    # ranks 1/2 stuck in allreduce; rank 0 is itself stuck in a
    # DIFFERENT collective (the desync-shaped hang): the hang entry
    # must name rank 0 missing and show what it is blocked on
    a = _mk_recorder(0, [], inflight=[("bcast", 0, -1, 64)])
    b = _mk_recorder(1, [], inflight=[("allreduce", 0, -1, 64)])
    c = _mk_recorder(2, [], inflight=[("allreduce", 0, -1, 64)])
    doc = obs_flight.merge_flight_dumps([a.dump(), b.dump(), c.dump()])
    hangs = {h["collective"]: h for h in doc["analysis"]["hangs"]}
    h = hangs["allreduce"]
    assert h["arrived"] == [1, 2] and 0 in h["missing"]
    assert h["missing_blocked_on"]["0"]["collective"] == "bcast"


def test_analyzer_skips_order_analysis_on_wrapped_rings():
    # rank 0's ring wrapped (evicted history): positional comparison
    # against rank 1's full history would fake a desync — the analyzer
    # must skip it and say so, while hang detection stays live
    a = obs_flight.FlightRecorder(rank=0, capacity=2)
    for i, coll in enumerate(("allreduce", "bcast", "allgather")):
        rec = a.new_record(i, coll, 0, -1, "float32", 64, 256, 2, True,
                           now_ns())
        rec.finish(0, now_ns())
    b = _mk_recorder(1, [("allreduce", 0, -1, 64), ("bcast", 0, -1, 64),
                         ("allgather", 0, -1, 64)])
    doc = obs_flight.merge_flight_dumps([a.dump(), b.dump()])
    assert doc["analysis"]["desyncs"] == []
    assert doc["analysis"]["stragglers"] == []
    assert doc["analysis"]["truncated_comms"] == [0]
    assert doc["analysis"]["ok"]


def test_analyzer_reports_stragglers():
    a = _mk_recorder(0, [("allreduce", 0, -1, 64)] * 3)
    b = _mk_recorder(1, [("allreduce", 0, -1, 64)] * 1)
    doc = obs_flight.merge_flight_dumps([a.dump(), b.dump()])
    (s,) = doc["analysis"]["stragglers"]
    assert s["completed_lead"] == 3 and s["behind"] == {"1": 1}


def test_merge_accepts_paths_and_merged_docs(tmp_path):
    a = _mk_recorder(0, [("allreduce", 0, -1, 64)])
    b = _mk_recorder(1, [("allreduce", 0, -1, 64)])
    pa = tmp_path / "a.json"
    with open(pa, "w") as f:
        json.dump(a.dump(), f)
    doc = obs_flight.merge_flight_dumps(
        [str(pa), b.dump()], out_path=str(tmp_path / "m.json"))
    assert doc["nranks"] == 2 and doc["analysis"]["ok"]
    # a previous merge re-ingests wholesale (the doctor's input mode)
    again = obs_flight.merge_flight_dumps([str(tmp_path / "m.json")])
    assert again["nranks"] == 2


# ---------------------------------------------------------------------------
# OpenMetrics rendering + HTTP health surface
# ---------------------------------------------------------------------------
def test_to_openmetrics_format():
    reg = obs_metrics.MetricsRegistry()
    reg.inc("watchdog/fires", 2)
    reg.set_gauge("accl_health", obs_health.HEALTH_OK)
    for _ in range(3):
        reg.observe_call("allreduce", "float32", 1024, 100e3, nranks=4)
    text = reg.to_openmetrics()
    assert text.endswith("# EOF\n")
    assert "# TYPE accl_watchdog_fires counter" in text
    assert "accl_watchdog_fires_total 2" in text
    assert "accl_health 0" in text          # not double-prefixed
    lbl = 'collective="allreduce",dtype="float32",size_bucket="<=1KiB"'
    assert f"accl_collective_calls_total{{{lbl}}} 3" in text
    # cumulative histogram: 100us sits in le_256; every bucket >= 256
    # carries the full count, +Inf closes at 3
    assert f'accl_collective_latency_us_bucket{{{lbl},le="64"}} 0' in text
    assert f'accl_collective_latency_us_bucket{{{lbl},le="256"}} 3' in text
    assert f'accl_collective_latency_us_bucket{{{lbl},le="+Inf"}} 3' in text
    assert f"accl_collective_latency_us_count{{{lbl}}} 3" in text
    assert f"accl_collective_latency_us_sum{{{lbl}}} 300.0" in text


def test_openmetrics_membership_schema():
    # r11 exporter-consumer contract: the accl_health gauge documents
    # its new recovering=4 value, the membership-event counters and the
    # recovery-latency histogram carry HELP text, and the value-
    # histogram family renders cumulative buckets + sum/count
    reg = obs_metrics.MetricsRegistry()
    reg.set_gauge("accl_health", obs_health.HEALTH_RECOVERING)
    reg.inc("membership/joins", 1)
    reg.inc("membership/shrinks", 2)
    reg.inc("membership/grows", 1)
    reg.inc("membership/rank_deaths", 1)
    reg.inc("recovery/rounds", 1)
    reg.observe_value("recovery/latency_us", 5_000_000.0)
    text = reg.to_openmetrics()
    assert "# HELP accl_health " in text and "4=recovering" in text
    assert "accl_health 4" in text
    for fam in ("accl_membership_joins", "accl_membership_shrinks",
                "accl_membership_grows", "accl_membership_rank_deaths",
                "accl_recovery_rounds"):
        assert f"# HELP {fam} " in text, fam
        assert f"# TYPE {fam} counter" in text, fam
    assert "accl_membership_joins_total 1" in text
    assert "accl_membership_shrinks_total 2" in text
    assert "# HELP accl_recovery_latency_us " in text
    assert "# TYPE accl_recovery_latency_us histogram" in text
    # 5 s lands in le=16777216 (power-of-4 µs buckets, cumulative)
    assert 'accl_recovery_latency_us_bucket{le="4194304"} 0' in text
    assert 'accl_recovery_latency_us_bucket{le="+Inf"} 1' in text
    assert "accl_recovery_latency_us_sum 5000000.0" in text
    assert "accl_recovery_latency_us_count 1" in text
    # the gauge's code list stays in lockstep with HEALTH_NAMES
    # (r14 added 5=slow — the regression sentinel's verdict)
    assert "5=slow" in text
    assert obs_health.HEALTH_NAMES == (
        "ok", "degraded", "hung", "aborted", "recovering", "slow")


def test_flight_record_recovering_state():
    # supervisor phase records: live in the `recovering` state (in
    # flight, but non-gang — invisible to the stuck-gang scan), retired
    # by finish() like any record
    rec_ring = obs_flight.FlightRecorder(0, capacity=8)
    rec = rec_ring.new_record(-1, "recovery/shrink", 0, 0, "none", 0, 0,
                              1, False, obs_flight.now_ns())
    rec.mark_recovering(obs_flight.now_ns())
    assert obs_flight.STATE_NAMES[rec.state] == "recovering"
    assert rec.in_flight and not rec.gang
    assert rec.lane == "supervisor"
    assert rec.to_dict()["state"] == "recovering"
    # a live recovering record never reads as a hang in the merge
    doc = obs_flight.merge_flight_dumps([rec_ring.dump()])
    assert doc["analysis"]["hangs"] == []
    rec.finish(0, obs_flight.now_ns())
    assert not rec.in_flight
    assert obs_flight.STATE_NAMES[rec.state] == "complete"
    assert "recovering" in obs_flight.STATE_NAMES


def test_metrics_exporter_endpoints():
    reg = obs_metrics.MetricsRegistry()
    reg.set_gauge("accl_health", obs_health.HEALTH_OK)
    reg.inc("watchdog/checks", 7)
    exp = obs_health.MetricsExporter(0, registry=reg)
    try:
        base = f"http://{exp.host}:{exp.port}"
        resp = urllib.request.urlopen(base + "/metrics", timeout=10)
        assert resp.headers["Content-Type"] \
            == obs_health.OPENMETRICS_CONTENT_TYPE
        body = resp.read().decode()
        assert "accl_health 0" in body and body.endswith("# EOF\n")
        hz = json.loads(urllib.request.urlopen(
            base + "/healthz", timeout=10).read())
        assert hz == {"health": "ok", "accl_health": 0,
                      "watchdog_fires": 0, "watchdog_checks": 7}
        fl = json.loads(urllib.request.urlopen(
            base + "/flight", timeout=10).read())
        assert "ranks" in fl and "analysis" in fl
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(base + "/nope", timeout=10)
    finally:
        exp.close()


def test_start_exporter_env_gating(monkeypatch):
    monkeypatch.delenv("ACCL_METRICS_PORT", raising=False)
    obs_health.stop_exporter()
    assert obs_health.start_exporter() is None  # unset -> no endpoint
    exp = obs_health.start_exporter(port=0)
    try:
        assert exp is obs_health.start_exporter(port=0)  # singleton
    finally:
        obs_health.stop_exporter()
