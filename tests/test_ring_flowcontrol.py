"""Flow-control property tests for the Pallas ring kernels.

The ring kernels' ack-semaphore windows (ops/ring.py ag_*/rs_* window
algebra) exist to stop a fast neighbor overrunning the double-buffered
communication slots — a race the CPU interpreter, which serializes
`rdma.start(); rdma.wait()`, can never provoke.  These tests replay the
EXACT schedule (driven by the same shared predicates the kernels
compile) in a discrete-event model under adversarial timing:

- remote writes land the instant they are issued (worst case for
  double-buffer overrun),
- devices are stepped in every relative order the scheduler allows
  (worst case for deadlock),

and assert three properties for P = 2..8:
  1. no landing slot is overwritten while its payload is still unread,
  2. every device completes (no deadlock),
  3. the ack-semaphore ledger balances (no counts leak across segments,
     which would poison the next collective reusing the semaphores).

An off-by-one in any window predicate fails here instead of deadlocking
or corrupting real hardware (the firmware's RAW-hazard discipline,
ccl_offload_control.c:1457-1460).  A soak over P x segments x ragged
tails through the real interpret-mode kernels complements the model.
"""
import itertools

import numpy as np
import pytest

from accl_tpu.ops.ring import (
    ag_signals_ack,
    ag_waits_ack,
    rs_signals_ack,
    rs_waits_ack,
)


class Device:
    """One ring member executing the kernel schedule as a coroutine of
    (op, args) steps; blocked ops return False and are retried."""

    def __init__(self, idx, P, program):
        self.idx = idx
        self.P = P
        self.pc = 0
        self.program = program  # list of (op, payload)
        self.done = False


def _run_schedule(P, make_program, n_slots):
    """Adversarial scheduler: eager delivery + every round-robin offset.

    State per device: slot payloads with read-counts, ack semaphore
    counts.  Returns the violation list (empty = pass).
    """
    violations = []
    for rotation in range(P):  # vary which device runs first each round
        # slots[d][s] = payload dict or None; a payload tracks the reads
        # it still owes before the slot may be overwritten
        slots = [[None] * n_slots for _ in range(P)]
        acks = [[0] * n_slots for _ in range(P)]
        devs = [Device(i, P, make_program(i, P)) for i in range(P)]

        def try_step(d):
            if d.pc >= len(d.program):
                d.done = True
                return False
            op, a = d.program[d.pc]
            if op == "wait_ack":
                if acks[d.idx][a["slot"]] < 1:
                    return False
                acks[d.idx][a["slot"]] -= 1
            elif op == "send":
                # eager delivery: the write lands NOW on the right
                # neighbor; overrun if the landing slot still owes reads
                dst = (d.idx + 1) % P
                tgt = slots[dst][a["slot"]]
                if tgt is not None and tgt["reads_left"] > 0:
                    violations.append(
                        f"P={P} rot={rotation}: dev {d.idx} step "
                        f"{a['step']} overran dev {dst} slot {a['slot']} "
                        f"(payload still owes {tgt['reads_left']} reads)")
                slots[dst][a["slot"]] = {
                    "reads_left": a["lands_reads"],
                    "from_step": a["step"],
                }
            elif op == "recv":
                # rdma.wait(): block until the incoming payload landed
                tgt = slots[d.idx][a["slot"]]
                if tgt is None or tgt["from_step"] != a["step"]:
                    return False
            elif op == "read":
                tgt = slots[d.idx][a["slot"]]
                if tgt is not None and tgt["reads_left"] > 0:
                    tgt["reads_left"] -= 1
            elif op == "signal_ack":
                left = (d.idx - 1) % P
                acks[left][a["slot"]] += 1
            d.pc += 1
            return True

        # round-robin from a rotated start until quiescent
        for _ in range(10_000):
            progressed = False
            for k in range(P):
                d = devs[(k + rotation) % P]
                while try_step(d):
                    progressed = True
            if all(dv.pc >= len(dv.program) for dv in devs):
                break
            if not progressed:
                stuck = [(d.idx, d.pc, d.program[d.pc][0])
                         for d in devs if d.pc < len(d.program)]
                violations.append(f"P={P} rot={rotation}: DEADLOCK at "
                                  f"{stuck}")
                return violations
        # ledger balance: leftover ack counts poison the next segment
        for d in range(P):
            for s in range(n_slots):
                if acks[d][s] != 0:
                    violations.append(
                        f"P={P} rot={rotation}: ack ledger leak at dev "
                        f"{d} slot {s}: {acks[d][s]}")
    return violations


def _ag_program(i, P):
    """The all-gather kernel's per-device schedule, driven by the SAME
    window predicates the kernel compiles (ops/ring.py).  The initial
    local fill of comm slot 0 needs no modeling: reads of an empty slot
    are no-ops and carry no hazard."""
    ops = []
    for step in range(P - 1):
        slot = step % 2
        nxt = (step + 1) % 2
        if ag_waits_ack(step, P):
            ops.append(("wait_ack", {"slot": nxt}))
        # send reads comm_buf[slot] once
        ops.append(("read", {"slot": slot}))
        # the payload landing at the right neighbor will be read by: the
        # put (1) + the forwarding send at the neighbor's next step
        # (1), except the neighbor's last landing which is only put
        lands_reads = 1 if step == P - 2 else 2
        ops.append(("send", {"slot": nxt, "step": step,
                             "lands_reads": lands_reads}))
        ops.append(("recv", {"slot": nxt, "step": step}))
        if ag_signals_ack(step, P):
            ops.append(("signal_ack", {"slot": slot}))
        # put: read the landed chunk into out
        ops.append(("read", {"slot": nxt}))
    return ops


def _rs_program(i, P):
    """The reduce-scatter kernel's per-device schedule: acc sends into
    the neighbor's double-buffered landing slots; the fold is the single
    read of a landed payload."""
    ops = []
    for step in range(P - 1):
        slot = step % 2
        if rs_waits_ack(step, P):
            ops.append(("wait_ack", {"slot": slot}))
        # send the acc; the landing payload is read exactly once (fold)
        ops.append(("send", {"slot": slot, "step": step, "lands_reads": 1}))
        ops.append(("recv", {"slot": slot, "step": step}))
        # fold consumes the landing
        ops.append(("read", {"slot": slot}))
        if rs_signals_ack(step, P):
            ops.append(("signal_ack", {"slot": slot}))
    return ops


@pytest.mark.parametrize("P", range(2, 9))
def test_allgather_window_properties(P):
    violations = _run_schedule(P, lambda i, p: _ag_program(i, p), n_slots=2)
    assert not violations, "\n".join(violations[:5])


@pytest.mark.parametrize("P", range(2, 9))
def test_reduce_scatter_window_properties(P):
    violations = _run_schedule(P, lambda i, p: _rs_program(i, p), n_slots=2)
    assert not violations, "\n".join(violations[:5])


@pytest.mark.parametrize("P,delta", itertools.product(
    (2, 4, 8), ("wait_late", "signal_extra")))
def test_window_mutations_are_caught(P, delta, monkeypatch):
    """Meta-test: a deliberately broken window must trip the model —
    otherwise the properties above prove nothing."""
    import accl_tpu.ops.ring as ring

    if delta == "wait_late":
        # never wait: a fast neighbor may overrun the double buffer
        monkeypatch.setattr(ring, "ag_waits_ack", lambda s, p: False)
    else:
        # signal one step too many: the ledger leaks a count
        monkeypatch.setattr(ring, "ag_signals_ack", lambda s, p: s <= p - 2)

    def prog(i, p):
        ops = []
        for step in range(p - 1):
            slot = step % 2
            nxt = (step + 1) % 2
            if ring.ag_waits_ack(step, p):
                ops.append(("wait_ack", {"slot": nxt}))
            ops.append(("read", {"slot": slot}))
            lands = 1 if step == p - 2 else 2
            ops.append(("send", {"slot": nxt, "step": step,
                                 "lands_reads": lands}))
            ops.append(("recv", {"slot": nxt, "step": step}))
            if ring.ag_signals_ack(step, p):
                ops.append(("signal_ack", {"slot": slot}))
            ops.append(("read", {"slot": nxt}))
        return ops

    violations = _run_schedule(P, prog, n_slots=2)
    if delta == "wait_late" and P <= 2:
        return  # 2-rank ring has no overrun window to violate
    assert violations, f"broken window {delta} went undetected at P={P}"


# ---------------------------------------------------------------------------
# soak: the real interpret-mode kernels across P x segments x ragged
# tails (numerical correctness through many segment/parity transitions)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("P", (2, 3, 5, 8))
def test_segmented_allreduce_soak(P):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as Pspec

    from accl_tpu.ops.ring import ring_all_reduce_segmented

    devs = jax.devices()[:P]
    if len(devs) < P:
        pytest.skip(f"need {P} devices")
    mesh = Mesh(np.array(devs), ("r",))
    # ragged: not a multiple of P, and seg_elems tiny so many segments
    # exercise the alternating collective_id parity
    N = 7 * P + 3
    xs = np.random.default_rng(P).standard_normal((P, N)).astype(np.float32)

    fn = jax.jit(jax.shard_map(
        lambda v: ring_all_reduce_segmented(
            v[0], "r", seg_elems=2 * P, interpret=True)[None],
        mesh=mesh, in_specs=Pspec("r"), out_specs=Pspec("r"),
        check_vma=False))
    out = np.asarray(fn(jnp.asarray(xs)))
    want = xs.sum(axis=0)
    for r in range(P):
        np.testing.assert_allclose(out[r], want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("P", (2, 4, 8))
def test_segmented_gather_scatter_soak(P):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as Pspec

    from accl_tpu.ops.ring import (
        ring_all_gather_segmented,
        ring_reduce_scatter_segmented,
    )

    devs = jax.devices()[:P]
    if len(devs) < P:
        pytest.skip(f"need {P} devices")
    mesh = Mesh(np.array(devs), ("r",))
    n = 11  # per-member elements, ragged vs seg_elems=4
    xs = np.random.default_rng(P + 50).standard_normal(
        (P, n)).astype(np.float32)

    ag = jax.jit(jax.shard_map(
        lambda v: ring_all_gather_segmented(
            v[0], "r", seg_elems=4, interpret=True)[None],
        mesh=mesh, in_specs=Pspec("r"), out_specs=Pspec("r"),
        check_vma=False))
    got = np.asarray(ag(jnp.asarray(xs)))
    want = xs.reshape(-1)
    for r in range(P):
        np.testing.assert_allclose(got[r], want, rtol=1e-6)

    xs2 = np.random.default_rng(P + 80).standard_normal(
        (P, P * n)).astype(np.float32)
    rs = jax.jit(jax.shard_map(
        lambda v: ring_reduce_scatter_segmented(
            v[0], "r", seg_elems=4, interpret=True)[None],
        mesh=mesh, in_specs=Pspec("r"), out_specs=Pspec("r"),
        check_vma=False))
    got2 = np.asarray(rs(jnp.asarray(xs2)))
    full = xs2.sum(axis=0).reshape(P, n)
    for r in range(P):
        np.testing.assert_allclose(got2[r], full[r], rtol=1e-5, atol=1e-5)
