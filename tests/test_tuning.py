"""Topology-aware hierarchical collectives + persistent autotuner
(accl_tpu/tuning, r16).

Pins the ISSUE-14 acceptance surface: hierarchical compositions
bitwise-exact vs the flat engine collectives for lossless lanes on
BOTH backends (including non-divisible counts and non-square fabrics),
the versioned selection-table round-trip with corrupt-table rejection,
``ACCL_TUNE=0`` parity, measured axis demotion from a (chaos-)slowed
link, the clear-error contract of the tuning registers, and a tuned
composition captured as an r12 plan — replaying bitwise and fenced by
abort/shrink like any plan.
"""
import json
import os

import numpy as np
import pytest

from accl_tpu import ACCLError
from accl_tpu.backends.emu import EmuWorld
from accl_tpu.backends.tpu import TpuWorld
from accl_tpu.constants import ReduceFunction, TuningKey
from accl_tpu.tuning import (
    Fabric,
    HierarchicalComm,
    SelectionTable,
    autotune,
)
from accl_tpu.utils.topology import grid_coords, link_axis, parse_shape

WORLDS = pytest.mark.parametrize("world_cls", [EmuWorld, TpuWorld],
                                 ids=["emu", "tpu-interpret"])


def _mk_world(world_cls, nranks):
    if world_cls is EmuWorld:
        return EmuWorld(nranks, devmem_bytes=128 << 20, n_egr_rx_bufs=32,
                        max_eager_size=16384,
                        max_rendezvous_size=16 << 20)
    return TpuWorld(nranks)


def _hier(world, shape):
    fab = Fabric.for_world(world.nranks, shape=shape)
    return [HierarchicalComm(a, fab) for a in world.accls]


# ---------------------------------------------------------------------------
# fabric / topology model
# ---------------------------------------------------------------------------

def test_fabric_shapes_groups_and_labels():
    fab = Fabric(8, shape=(4, 2))
    assert fab.groups(1) == [[0, 1], [2, 3], [4, 5], [6, 7]]
    assert fab.across_groups() == [[0, 2, 4, 6], [1, 3, 5, 7]]
    # one label source: Fabric delegates to utils.topology.link_axis
    assert fab.link_axis(0, 1) == "y"
    assert fab.link_axis(0, 2) == "x"
    assert fab.link_axis(0, 3) == "multi-axis"
    assert link_axis(0, 1, nranks=8, shape=(4, 2)) == "y"
    assert grid_coords(4, (2, 2)) == [(0, 0), (0, 1), (1, 0), (1, 1)]


def test_fabric_env_and_errors(monkeypatch):
    monkeypatch.setenv("ACCL_FABRIC", "2x2")
    assert Fabric.for_world(4).shape == (2, 2)
    monkeypatch.setenv("ACCL_FABRIC", "3x2")
    with pytest.raises(ACCLError, match="holds 6"):
        Fabric.for_world(4)
    monkeypatch.setenv("ACCL_FABRIC", "bogus")
    with pytest.raises(ACCLError, match="ACCL_FABRIC"):
        Fabric.for_world(4)
    monkeypatch.delenv("ACCL_FABRIC")
    assert Fabric.for_world(8).shape == (2, 4)  # near-square default
    assert Fabric.for_world(7).trivial          # prime -> single axis
    with pytest.raises(ValueError):
        parse_shape("4x-2")


def test_measured_demotion_flips_axis_order():
    """A slowed link along the default within axis demotes it: the
    fabric built from the measured matrix moves the healthy axis into
    the heavy-traffic role (and the composer swaps stages)."""
    P = 4
    fields = {f: [[0] * P for _ in range(P)]
              for f in ("seek_wait_ns", "retrans_sent", "tx_bytes")}
    fab0 = Fabric.for_world(P, shape=(2, 2))
    assert fab0.within_axis() == 1  # default: inner/contiguous axis
    # blocked time observed on the y links (0<->1, 2<->3)
    for s, d in ((0, 1), (1, 0), (2, 3), (3, 2)):
        fields["seek_wait_ns"][s][d] = 5_000_000
    matrix = {"nranks": P, "comm": 0, "fields": fields}
    fab = Fabric.from_link_matrix(matrix, shape=(2, 2))
    assert fab.within_axis() == 0, fab.axis_order
    assert fab.axis_order == (0, 1)
    # and a lossy link demotes the same way (retransmit penalty)
    fields["seek_wait_ns"] = [[0] * P for _ in range(P)]
    fields["retrans_sent"][0][2] = 50  # an x link
    fab2 = Fabric.from_link_matrix(
        {"nranks": P, "comm": 0, "fields": fields}, shape=(2, 2))
    assert fab2.within_axis() == 1


def test_chaos_slowed_link_demotes_measured_axis():
    """The real pipeline end-to-end: chaos-lossy eager traffic on the
    y-axis links lands retransmits + seek waits in
    ``world.link_matrix()``, and the fabric built from that measured
    snapshot demotes y out of the heavy-traffic within role (the
    default preference) — the tuner then composes within x."""
    with EmuWorld(4, chaos="seed=7,drop=0.08") as w:
        def body(accl, rank):
            # traffic ONLY along the y (inner) links of a 2x2 fabric:
            # pairs (0,1) and (2,3) — the faulty funnel makes exactly
            # those links lossy, x stays pristine
            peer = rank ^ 1
            s = accl.create_buffer_like(
                np.full(128, rank + 1, np.float32))
            r = accl.create_buffer(128, np.float32)
            for i in range(10):
                if rank < peer:
                    accl.send(s, 128, peer, tag=i)
                    accl.recv(r, 128, peer, tag=100 + i)
                else:
                    accl.recv(r, 128, peer, tag=i)
                    accl.send(s, 128, peer, tag=100 + i)

        w.run(body)
        matrix = w.link_matrix()
        measured = sum(v for row in matrix["fields"]["seek_wait_ns"]
                       for v in row) + 1e6 * sum(
            v for row in matrix["fields"]["retrans_sent"] for v in row)
        assert measured > 0, matrix["fields"]
        fab = Fabric.from_link_matrix(matrix, shape=(2, 2))
        assert fab.within_axis() == 0, (fab.axis_order, fab.axis_scores)
        assert fab.axis_scores["y"] > fab.axis_scores["x"]


# ---------------------------------------------------------------------------
# hierarchical composition: bitwise vs flat on both backends
# ---------------------------------------------------------------------------

@WORLDS
@pytest.mark.parametrize("nranks,shape", [(4, (2, 2)), (6, (3, 2))],
                         ids=["2x2", "3x2"])
@pytest.mark.parametrize("count", [64, 7], ids=["divisible", "ragged"])
def test_hier_allreduce_bitwise_vs_flat(world_cls, nranks, shape, count):
    if world_cls is TpuWorld and nranks > 4:
        nranks, shape = 4, (2, 2)  # 8 virtual devices; keep it light
    w = _mk_world(world_cls, nranks)
    try:
        hier = _hier(w, shape)

        def body(accl, rank):
            data = (np.arange(count) % 13 + rank).astype(np.int32)
            s = accl.create_buffer_like(data)
            h = accl.create_buffer(count, np.int32)
            f = accl.create_buffer(count, np.int32)
            hier[rank].allreduce(s, h, count)
            accl.allreduce(s, f, count)
            hm = accl.create_buffer(count, np.int32)
            fm = accl.create_buffer(count, np.int32)
            hier[rank].allreduce(s, hm, count, ReduceFunction.MAX)
            accl.allreduce(s, fm, count, ReduceFunction.MAX)
            return (h.host.copy(), f.host.copy(), hm.host.copy(),
                    fm.host.copy())

        for h, f, hm, fm in w.run(body):
            np.testing.assert_array_equal(h, f)
            np.testing.assert_array_equal(hm, fm)
    finally:
        w.close()


@WORLDS
def test_hier_reduce_scatter_bitwise_vs_flat(world_cls):
    w = _mk_world(world_cls, 4)
    try:
        hier = _hier(w, (2, 2))
        count = 5  # per-rank chunk; global input 20 (no padding by
        # construction — the composed slabs must still land flat)

        def body(accl, rank):
            data = (np.arange(count * 4) + rank * 100).astype(np.int32)
            s = accl.create_buffer_like(data)
            h = accl.create_buffer(count, np.int32)
            f = accl.create_buffer(count, np.int32)
            hier[rank].reduce_scatter(s, h, count)
            accl.reduce_scatter(s, f, count)
            return h.host.copy(), f.host.copy()

        for h, f in w.run(body):
            np.testing.assert_array_equal(h, f)
    finally:
        w.close()


@WORLDS
def test_hier_bcast_allgather_scatter_gather_bitwise(world_cls):
    w = _mk_world(world_cls, 4)
    try:
        hier = _hier(w, (2, 2))
        count, root = 9, 2

        def body(accl, rank):
            out = {}
            # bcast
            data = np.arange(count, dtype=np.float32) + \
                (1000 if rank == root else 0)
            b = accl.create_buffer_like(data)
            hier[rank].bcast(b, count, root)
            out["bcast"] = b.host.copy()
            # allgather
            s = accl.create_buffer_like(
                np.arange(count, dtype=np.float32) + rank * 10)
            g = accl.create_buffer(count * 4, np.float32)
            hier[rank].allgather(s, g, count)
            out["allgather"] = g.host.copy()
            # scatter (root holds 4*count)
            sd = accl.create_buffer_like(
                np.arange(count * 4, dtype=np.float32)
                * (1 if rank == root else 0))
            sr = accl.create_buffer(count, np.float32)
            hier[rank].scatter(sd, sr, count, root)
            out["scatter"] = sr.host.copy()
            # gather
            gs = accl.create_buffer_like(
                np.arange(count, dtype=np.float32) + rank * 10)
            gr = (accl.create_buffer(count * 4, np.float32)
                  if rank == root else None)
            hier[rank].gather(gs, gr, count, root)
            out["gather"] = gr.host.copy() if gr is not None else None
            return out

        res = w.run(body)
        bexp = np.arange(count, dtype=np.float32) + 1000
        agexp = np.concatenate(
            [np.arange(count, dtype=np.float32) + rk * 10
             for rk in range(4)])
        for rk in range(4):
            np.testing.assert_array_equal(res[rk]["bcast"], bexp)
            np.testing.assert_array_equal(res[rk]["allgather"], agexp)
            np.testing.assert_array_equal(
                res[rk]["scatter"],
                np.arange(count * 4,
                          dtype=np.float32)[rk * count:(rk + 1) * count])
        np.testing.assert_array_equal(res[root]["gather"], agexp)
    finally:
        w.close()


def test_hier_trivial_fabric_falls_back_flat():
    with EmuWorld(2) as w:
        fab = Fabric.for_world(2, shape=(1, 2))
        assert fab.trivial
        hier = [HierarchicalComm(a, fab) for a in w.accls]
        assert all(h.flat for h in hier)

        def body(accl, rank):
            s = accl.create_buffer_like(
                np.full(8, rank + 1.0, np.float32))
            r = accl.create_buffer(8, np.float32)
            hier[rank].allreduce(s, r, 8)
            return r.host.copy()

        for out in w.run(body):
            np.testing.assert_array_equal(out, np.full(8, 3.0))


# ---------------------------------------------------------------------------
# tuning registers: clear-error contract
# ---------------------------------------------------------------------------

def test_tuning_register_clear_errors():
    with EmuWorld(2) as w:
        a = w.accls[0]
        # driver-level: unknown key names the key and the known set
        with pytest.raises(ACCLError, match="42.*BCAST_FLAT_TREE"):
            a.set_tuning(42, 1)
        # emu backend: RING_THRESHOLD_BYTES is TPU-only
        with pytest.raises(ACCLError, match="RING_THRESHOLD_BYTES"):
            a.set_tuning(int(TuningKey.RING_THRESHOLD_BYTES), 0)
        # known keys still write (no raise)
        a.set_tuning(int(TuningKey.REDUCE_FLAT_TREE_MAX_COUNT), 4096)
        a.apply_static_tuning()


def test_tpu_tuning_register_twin():
    with TpuWorld(2) as w:
        a = w.accls[0]
        with pytest.raises(ACCLError, match="unknown tuning key 42"):
            a.set_tuning(42, 1)
        a.set_tuning(int(TuningKey.RING_THRESHOLD_BYTES), 777)
        assert w.engine.ring_threshold_bytes == 777
        a.set_tuning(int(TuningKey.BCAST_FLAT_TREE_MAX_RANKS), 5)
        assert w.engine.tuning_registers[
            int(TuningKey.BCAST_FLAT_TREE_MAX_RANKS)] == 5


# ---------------------------------------------------------------------------
# selection table + policy
# ---------------------------------------------------------------------------

def _toy_table(nranks=4):
    entries = {
        f"allreduce|float32|<=64KiB|{nranks}": {
            "algorithm": "ring", "busbw_GBps": 1.0,
            "static_busbw_GBps": 0.5, "bytes": 65536},
        f"reduce|float32|<=64KiB|{nranks}": {
            "algorithm": "tree", "busbw_GBps": 1.0,
            "static_busbw_GBps": 0.5, "bytes": 65536},
        f"reduce|float32|<=1KiB|{nranks}": {
            "algorithm": "flat", "busbw_GBps": 1.0,
            "static_busbw_GBps": 0.9, "bytes": 1024},
    }
    return SelectionTable(entries, {"nranks": nranks, "backend": "emu",
                                    "dtype": "float32"})


def test_selection_table_round_trip(tmp_path):
    path = str(tmp_path / "t.json")
    table = _toy_table()
    table.save(path)
    loaded = SelectionTable.load(path)
    assert loaded.entries == table.entries
    assert loaded.lookup("allreduce", "float32", 40000, 4)[
        "algorithm"] == "ring"
    assert loaded.lookup("allreduce", "float32", 40000, 8) is None


def test_selection_table_rejects_corruption(tmp_path):
    path = str(tmp_path / "bad.json")
    with open(path, "w") as f:
        f.write("{ not json")
    with pytest.raises(ACCLError, match="corrupt"):
        SelectionTable.load(path)
    with pytest.raises(ACCLError, match="cannot read"):
        SelectionTable.load(str(tmp_path / "missing.json"))
    doc = _toy_table().to_doc()
    doc["version"] = 99
    with open(path, "w") as f:
        json.dump(doc, f)
    with pytest.raises(ACCLError, match="version 99"):
        SelectionTable.load(path)
    doc["version"] = 1
    doc["format"] = "something-else"
    with open(path, "w") as f:
        json.dump(doc, f)
    with pytest.raises(ACCLError, match="not a selection table"):
        SelectionTable.load(path)
    doc["format"] = "accl-tune-table"
    doc["entries"]["reduce|float32|<=1KiB|4"] = {"algorithm": "warp"}
    with open(path, "w") as f:
        json.dump(doc, f)
    with pytest.raises(ACCLError, match="corrupt selection-table entry"):
        SelectionTable.load(path)


def test_policy_armed_installs_and_records(tmp_path, monkeypatch):
    path = str(tmp_path / "t.json")
    _toy_table().save(path)
    monkeypatch.setenv("ACCL_TUNE_TABLE", path)
    with EmuWorld(4) as w:
        assert all(a._tune_policy is not None for a in w.accls)

        def body(accl, rank):
            s = accl.create_buffer_like(np.ones(256, np.float32))
            r = accl.create_buffer(256, np.float32)
            accl.reduce(s, r, 256, 0)
            return r.host[0] if rank == 0 else 0.0

        w.run(body)
        snap = w.accls[0].metrics()
        selected = {k: v for k, v in snap["counters"].items()
                    if k.startswith("tuning/selected/")}
        assert selected, snap["counters"].keys()


def test_policy_install_programs_tpu_ring_crossover(tmp_path,
                                                    monkeypatch):
    path = str(tmp_path / "t.json")
    _toy_table().save(path)
    monkeypatch.setenv("ACCL_TUNE_TABLE", path)
    with TpuWorld(4) as w:
        # the learned ring crossover replaced the env-default constant
        assert w.engine.ring_threshold_bytes == 65536


def test_policy_ring_crossover_deflates_allgather_bytes(tmp_path,
                                                        monkeypatch):
    """Table bytes carry the nccl-tests payload factor (P for
    allgather); the installed ring threshold must be in the gang
    planner's per-rank units, so an allgather cell deflates by P."""
    table = _toy_table()
    table.entries["allgather|float32|<=16KiB|4"] = {
        "algorithm": "ring", "busbw_GBps": 1.0,
        "static_busbw_GBps": 0.5, "bytes": 16384}  # per-rank 4096
    path = str(tmp_path / "t.json")
    table.save(path)
    monkeypatch.setenv("ACCL_TUNE_TABLE", path)
    with TpuWorld(4) as w:
        assert w.engine.ring_threshold_bytes == 4096


def test_fabric_for_world_survives_mismatched_probe():
    """A world smaller than the probed coord grid degrades to the
    factorization fallback instead of refusing a default fabric."""
    coords = [(0, 0), (0, 1), (1, 0), (1, 1)]
    fab = Fabric.from_coords(4, coords)
    assert fab.shape == (2, 2)
    # 3 ranks cannot fill that grid: for_world must not raise
    import accl_tpu.tuning.topology as topo_mod

    orig = Fabric._probe_coords
    try:
        Fabric._probe_coords = staticmethod(
            lambda nranks: coords[:nranks])
        fab3 = topo_mod.Fabric.for_world(3)
        assert fab3.nranks == 3
    finally:
        Fabric._probe_coords = orig


def test_compare_verifies_the_tuned_fabric(tmp_path):
    """compare() rebuilds the fabric from the table's persisted world
    meta — including a demoted axis order — so verification measures
    the SAME composition tune() selected."""
    table = _toy_table()
    table.world = {"nranks": 4, "shape": [2, 2], "axis_order": [0, 1],
                   "backend": "emu", "dtype": "float32"}
    fab = autotune.fabric_of_table(table, 4)
    assert fab.shape == (2, 2)
    assert fab.axis_order == (0, 1)  # the demoted order, not default
    assert fab.within_axis() == 0


def test_tune_zero_restores_static_bit_for_bit(tmp_path, monkeypatch):
    path = str(tmp_path / "t.json")
    _toy_table().save(path)
    monkeypatch.setenv("ACCL_TUNE_TABLE", path)
    monkeypatch.setenv("ACCL_TUNE", "0")
    with TpuWorld(2) as w:
        assert all(a._tune_policy is None for a in w.accls)
        # the env-default constant stands — no learned write happened
        assert w.engine.ring_threshold_bytes == int(
            os.environ.get("ACCL_RING_THRESHOLD", str(4 << 20)))
    monkeypatch.delenv("ACCL_TUNE")
    monkeypatch.delenv("ACCL_TUNE_TABLE")
    # no table present at all: same static state
    with TpuWorld(2) as w:
        assert all(a._tune_policy is None for a in w.accls)
        assert w.engine.ring_threshold_bytes == int(
            os.environ.get("ACCL_RING_THRESHOLD", str(4 << 20)))


def test_policy_table_naming_error_on_missing_file(monkeypatch):
    monkeypatch.setenv("ACCL_TUNE_TABLE", "/nonexistent/table.json")
    with pytest.raises(ACCLError, match="ACCL_TUNE_TABLE"):
        EmuWorld(2)


# ---------------------------------------------------------------------------
# autotuner pipeline (mini)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_tune_builds_table_and_compare_never_slower():
    w = EmuWorld(4, devmem_bytes=128 << 20, n_egr_rx_bufs=32,
                 max_eager_size=16384, max_rendezvous_size=16 << 20)
    try:
        cfg = autotune.TuneConfig(
            collectives=("allreduce", "reduce"), count_pows=(8, 12),
            repetitions=2, shape=(2, 2), measured_demotion=False)
        table = autotune.tune(w, cfg)
        assert table.entries
        for e in table.entries.values():
            assert e["algorithm"] in autotune.ALGORITHMS
        rows = autotune.compare(w, table, cfg)
        assert rows
        # pruning guarantees the verified table never regresses a cell
        assert all(r["ratio"] >= 1.0 / 1.05 for r in rows), rows
    finally:
        w.close()


# ---------------------------------------------------------------------------
# tuned composition as an r12 plan
# ---------------------------------------------------------------------------

def test_hier_composition_captured_as_plan_replays_bitwise():
    w = _mk_world(EmuWorld, 4)
    try:
        hier = _hier(w, (2, 2))
        count = 48
        plans = [None] * 4

        def captured(accl, rank):
            s = accl.create_buffer_like(
                (np.arange(count) + rank).astype(np.int32))
            r = accl.create_buffer(count, np.int32)
            plan = accl.capture_plan(
                lambda a: hier[rank].allreduce(s, r, count))
            plans[rank] = plan
            first = r.host.copy()
            r.host[:] = 0
            plan.replay()
            return first, r.host.copy()

        for first, replayed in w.run(captured):
            np.testing.assert_array_equal(first, replayed)
    finally:
        w.close()


def test_hier_plan_fenced_by_abort_and_reset():
    """A captured composition is an ordinary r12 plan: aborting the
    sub-communicator it runs on fences the replay (raises, never runs
    the dead epoch), and reset_errors invalidates every plan — the
    same contract shrink/grow apply through _invalidate_plans."""
    w = _mk_world(EmuWorld, 4)
    try:
        hier = _hier(w, (2, 2))
        count = 16
        plans = [None] * 4

        def cap(accl, rank):
            s = accl.create_buffer_like(
                np.full(count, rank + 1, np.int32))
            r = accl.create_buffer(count, np.int32)
            plans[rank] = accl.capture_plan(
                lambda a: hier[rank].allreduce(s, r, count),
                validate=False)

        w.run(cap)

        def abort_then_replay(accl, rank):
            # each rank aborts its own within-group communicator (the
            # composition's heavy stage) — the epoch fence must refuse
            # the replay on every member
            accl.abort(hier[rank]._inner_comm)
            with pytest.raises(ACCLError):
                plans[rank].replay()
            return True

        assert all(w.run(abort_then_replay))
        w.reset_errors()

        # re-capture on the recovered world, then reset_errors fences
        # again (the shrink/grow-equivalent all-plans invalidation)
        w.run(cap)
        w.reset_errors()

        def replay_after_reset(accl, rank):
            with pytest.raises(ACCLError):
                plans[rank].replay()
            return True

        assert all(w.run(replay_after_reset))
    finally:
        w.close()
