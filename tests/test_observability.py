"""Observability layer (accl_tpu/observability): span ordering
invariants, disabled-mode zero-allocation fast path, multi-rank gang-id
merge, Perfetto JSON schema validity, metrics registry content, and the
satellite fixes riding this PR (get_duration error paths, Timer/timed
unification, time_fn per-iteration sync)."""
import json
import os

import numpy as np
import pytest

from accl_tpu import ACCLError, ReduceFunction
from accl_tpu.observability import metrics as obs_metrics
from accl_tpu.observability import trace as obs_trace

COUNT = 64
NRANKS = 4


@pytest.fixture
def tracing():
    """Tracing ON with a fresh collector; restores disabled state."""
    col = obs_trace.enable()
    col.clear()
    try:
        yield col
    finally:
        obs_trace.disable()
        col.clear()


def _tpu_world(nranks=NRANKS):
    from accl_tpu.backends.tpu import TpuWorld

    return TpuWorld(nranks)


def _allreduce_all_ranks(world, reps=1):
    def fn(accl, rank):
        s = accl.create_buffer_like(
            np.arange(COUNT, dtype=np.float32) + rank)
        r = accl.create_buffer(COUNT, np.float32)
        for _ in range(reps):
            accl.allreduce(s, r, COUNT, ReduceFunction.SUM)
        return r.host.copy()

    return world.run(fn)


# ---------------------------------------------------------------------------
# span ordering + gang merge (TPU backend gang scheduler)
# ---------------------------------------------------------------------------
def test_span_ordering_invariants(tracing):
    with _tpu_world() as w:
        _allreduce_all_ranks(w, reps=2)
    spans = [s for s in tracing.spans() if s.name == "allreduce"]
    assert len(spans) == 2 * NRANKS
    for s in spans:
        ts = s.timestamps()
        # every stage stamped on the gang path
        for k in ("submit", "queue", "gang_ready", "dispatch",
                  "device_begin", "device_end", "complete"):
            assert ts[k] is not None, f"stage {k} missing on {s!r}"
        assert s.t_submit <= s.t_queue <= s.t_gang_ready
        assert s.t_gang_ready <= s.t_dispatch <= s.t_device_begin
        assert s.t_device_begin <= s.t_device_end <= s.t_complete
        assert s.lane in ("leader", "executor", "batched")
        assert s.dtype == "float32"
        assert s.nbytes == COUNT * 4


def test_multi_rank_gang_id_merge(tracing):
    with _tpu_world() as w:
        _allreduce_all_ranks(w, reps=3)
    spans = [s for s in tracing.spans() if s.name == "allreduce"]
    by_gang = {}
    for s in spans:
        by_gang.setdefault(s.gang_id, []).append(s)
    # 3 instances, each merging all four ranks under one gang id
    assert len(by_gang) == 3
    for gid, members in by_gang.items():
        assert gid is not None
        assert sorted(m.rank for m in members) == list(range(NRANKS))
        # a fused gang program has ONE device window, so member slices
        # are exactly aligned
        assert len({(m.t_device_begin, m.t_device_end)
                    for m in members}) == 1


def test_disabled_mode_zero_allocation(tracing):
    # flip OFF after the fixture armed a fresh collector: the driver
    # and backends must not allocate spans nor touch the ring buffer
    obs_trace.disable()
    with _tpu_world() as w:
        def fn(accl, rank):
            s = accl.create_buffer_like(
                np.arange(COUNT, dtype=np.float32))
            r = accl.create_buffer(COUNT, np.float32)
            req = accl.allreduce(s, r, COUNT, ReduceFunction.SUM)
            assert req.trace is None  # zero-allocation fast path
            return True

        w.run(fn)
    assert obs_trace.new_span("x") is None
    assert len(tracing) == 0


# ---------------------------------------------------------------------------
# Perfetto export schema
# ---------------------------------------------------------------------------
def test_perfetto_json_schema(tracing, tmp_path):
    with _tpu_world() as w:
        _allreduce_all_ranks(w)
    path = tracing.dump(str(tmp_path / "trace.json"))
    with open(path) as f:
        doc = json.loads(f.read())
    events = doc["traceEvents"]
    assert events
    for ev in events:
        for key in ("ph", "ts", "pid", "tid"):
            assert key in ev, f"{key} missing from {ev}"
        assert ev["ph"] in ("X", "M")
        if ev["ph"] == "X":
            assert ev["dur"] >= 0
    # per-rank process tracks with at least one slice each
    slice_pids = {ev["pid"] for ev in events if ev["ph"] == "X"}
    assert slice_pids == set(range(NRANKS))
    # lane track names registered via thread_name metadata
    names = {ev["args"]["name"] for ev in events if ev["ph"] == "M"
             and ev["name"] == "thread_name"}
    assert any(n.startswith("lane:") for n in names)
    assert "queue" in names and "call" in names


def test_emu_backend_spans_and_merge(tracing):
    from accl_tpu.backends.emu import EmuWorld

    with EmuWorld(NRANKS) as w:
        def fn(accl, rank):
            s = accl.create_buffer_like(
                np.arange(COUNT, dtype=np.float32) + rank)
            r = accl.create_buffer(COUNT, np.float32)
            accl.allreduce(s, r, COUNT, ReduceFunction.SUM)
            return r.host.copy()

        w.run(fn)
    spans = [s for s in tracing.spans() if s.name == "allreduce"]
    assert len(spans) == NRANKS
    assert len({s.gang_id for s in spans}) == 1  # one merged gang
    assert sorted(s.rank for s in spans) == list(range(NRANKS))
    for s in spans:
        assert s.lane == "emu"
        assert s.t_submit <= s.t_queue <= s.t_dispatch
        assert s.t_dispatch <= s.t_device_begin <= s.t_device_end
        assert s.t_device_end <= s.t_complete


def test_traced_window_and_merge_files(tracing, tmp_path):
    with obs_trace.traced_window("unit"):
        pass
    spans = [s for s in tracing.spans() if s.name == "window:unit"]
    assert len(spans) == 1 and spans[0].lane == "window"
    # merge: two single-file traces with a shared gang id align clocks
    p1, p2 = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    def mk(path, ts):
        ev = {"name": "g", "ph": "X", "ts": ts, "dur": 5.0, "pid": 0,
              "tid": 0, "args": {"gang_id": 7}}
        with open(path, "w") as f:
            json.dump({"traceEvents": [ev]}, f)
    mk(p1, 100.0)
    mk(p2, 900.0)
    doc = obs_trace.merge_trace_files([p1, p2])
    ts = [ev["ts"] for ev in doc["traceEvents"] if ev["ph"] == "X"]
    assert ts == [100.0, 100.0]  # second file shifted onto the first


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------
def test_metrics_registry_reports_calls_hist_and_bandwidth():
    reg = obs_metrics.MetricsRegistry()
    # 1 KiB allreduce over 4 ranks, 10 calls of 100 us each
    for _ in range(10):
        reg.observe_call("allreduce", "float32", 1024, 100e3, nranks=4)
    reg.observe_call("allreduce", "float32", 1024, 100e3, nranks=4,
                     ok=False)
    snap = reg.snapshot()
    (key,) = snap["calls"].keys()
    st = snap["calls"][key]
    assert st["calls"] == 11 and st["errors"] == 1
    assert st["latency_us"]["avg"] == pytest.approx(100.0)
    # 100 us lands in the le_256 bucket of the power-of-4 ladder
    assert st["hist_us"]["le_256"] == 10
    assert sum(st["hist_us"].values()) == 10  # errors not in the hist
    # algbw = bytes/ns: 1024 B / 100e3 ns; busbw = algbw * 2(P-1)/P
    # (snapshot rounds to 4 decimals)
    assert st["algbw_GBps"] == pytest.approx(1024 / 100e3, abs=1e-4)
    assert st["busbw_GBps"] == pytest.approx(
        1024 / 100e3 * 1.5, abs=1e-4)
    # text + JSON renderings both carry the row
    assert "allreduce" in reg.to_text()
    assert json.loads(reg.to_json())["calls"][key]["calls"] == 11


def test_driver_publishes_metrics_end_to_end():
    reg = obs_metrics.default_registry()
    reg.reset()
    with _tpu_world() as w:
        _allreduce_all_ranks(w, reps=2)
        accl = w.accls[0]
        snap = accl.metrics()
        text = accl.dump_metrics()
        js = json.loads(accl.dump_metrics(as_json=True))
    rows = [v for v in snap["calls"].values()
            if v["collective"] == "allreduce"]
    assert rows and rows[0]["calls"] == 2 * NRANKS
    assert rows[0]["dtype"] == "float32"
    assert rows[0]["nranks"] == NRANKS
    assert rows[0]["algbw_GBps"] > 0
    assert sum(rows[0]["hist_us"].values()) == 2 * NRANKS
    assert "allreduce" in text
    assert js["calls"]
    reg.reset()


def test_engine_stats_registry_view():
    with _tpu_world() as w:
        before = dict(w.engine.stats)
        assert set(before) >= {"leader_dispatches", "executor_dispatches",
                               "batches", "batched_gangs"}
        _allreduce_all_ranks(w)
        after = dict(w.engine.stats)
        assert (after["leader_dispatches"] + after["executor_dispatches"]
                + after["batched_gangs"]) > (
            before["leader_dispatches"] + before["executor_dispatches"]
            + before["batched_gangs"])


# ---------------------------------------------------------------------------
# satellites
# ---------------------------------------------------------------------------
def test_get_duration_unfinished_raises():
    from accl_tpu.accl import ACCL
    from accl_tpu.request import Request

    accl = ACCL(device=None)
    with pytest.raises(ACCLError, match="no request"):
        accl.get_duration()
    pending = Request("inflight")
    with pytest.raises(ACCLError, match="not completed"):
        accl.get_duration(pending)
    finished = Request("done")
    finished.complete(0, 123.0)
    assert accl.get_duration(finished) == 123.0


def test_get_duration_completed_path_end_to_end():
    with _tpu_world(2) as w:
        def fn(accl, rank):
            s = accl.create_buffer_like(
                np.arange(COUNT, dtype=np.float32))
            r = accl.create_buffer(COUNT, np.float32)
            req = accl.allreduce(s, r, COUNT, ReduceFunction.SUM,
                                 run_async=True)
            # in-flight request raises instead of returning 0.0
            if not req.done:
                with pytest.raises(ACCLError):
                    accl.get_duration(req)
            req.wait(60)
            return accl.get_duration(req)

        durs = w.run(fn)
    assert all(d > 0 for d in durs)


def test_timer_and_timed_unified():
    import time

    from accl_tpu.utils import profiling, timing

    # one implementation: profiling re-exports timing's
    assert profiling.timed is timing.timed
    assert profiling.Timer is timing.Timer
    t = timing.Timer()
    t.start()
    time.sleep(0.005)
    t.end()
    # ns and us agree (and the reference-shaped alias still works)
    assert t.duration_ns() == pytest.approx(t.duration_us() * 1e3)
    assert t.durationUs() == t.duration_us()
    results = {}
    with timing.timed("blk", results) as timer:
        time.sleep(0.002)
    assert isinstance(timer, timing.Timer)
    assert results["blk"][0] >= 1e6  # ns


def test_time_fn_blocks_each_iteration():
    import jax
    import jax.numpy as jnp

    from accl_tpu.utils.profiling import time_fn

    f = jax.jit(lambda x: x * 2 + 1)
    x = jnp.ones(256)
    per_call = time_fn(f, x, iters=3, warmup=1)
    overlapped = time_fn(f, x, iters=3, warmup=1, pipelined=True)
    assert per_call > 0 and overlapped > 0


def test_merge_dedupes_track_metadata(tmp_path):
    """r15 satellite: merging N per-process trace files must emit ONE
    thread_name/process_name declaration per (pid, tid), not one per
    input file — Perfetto renders duplicates as repeated track names."""
    paths = []
    for i in range(3):
        p = str(tmp_path / f"m{i}.json")
        events = [
            {"name": "process_name", "ph": "M", "ts": 0, "pid": 0,
             "tid": 0, "args": {"name": "rank 0"}},
            {"name": "thread_name", "ph": "M", "ts": 0, "pid": 0,
             "tid": 1, "args": {"name": "call"}},
            {"name": "g", "ph": "X", "ts": 10.0 + i, "dur": 2.0,
             "pid": 0, "tid": 1, "args": {"gang_id": 4}},
        ]
        with open(p, "w") as f:
            json.dump({"traceEvents": events}, f)
        paths.append(p)
    doc = obs_trace.merge_trace_files(paths)
    meta = [(ev["name"], ev["pid"], ev["tid"])
            for ev in doc["traceEvents"] if ev["ph"] == "M"]
    assert len(meta) == len(set(meta)), f"duplicated metadata: {meta}"
    assert ("process_name", 0, 0) in meta
    assert ("thread_name", 0, 1) in meta
    # slices all survive the dedup
    assert sum(1 for ev in doc["traceEvents"] if ev["ph"] == "X") == 3
    # and the smoke's schema checker agrees
    import importlib.util as _ilu
    spec = _ilu.spec_from_file_location(
        "trace_smoke", os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))),
            "scripts", "trace_smoke.py"))
    smoke = _ilu.module_from_spec(spec)
    spec.loader.exec_module(smoke)
    assert smoke.check_no_duplicate_metadata(doc["traceEvents"]) == []
    dup_doc = doc["traceEvents"] + [doc["traceEvents"][0]]
    assert smoke.check_no_duplicate_metadata(dup_doc)


def test_device_steps_render_as_perfetto_tracks(tracing):
    """r15: stamp buffers land as per-rank device:<collective> tracks
    whose slices carry the step/peer/bytes schema."""
    rows = [
        [0, 0, 0, 1, 2, 1, 3, 512, 512],
        [0, 1, 3, 4, 5, 1, 3, 512, 512],
        [1, 0, 0, 1, 2, 2, 0, 512, 512],
    ]
    obs_trace.record_device_steps("all_gather", np.array(rows, np.int32))
    assert len(tracing.device_records()) == 1
    assert tracing.device_link_bytes() == {(0, 1): 1024, (1, 2): 512}
    doc = tracing.to_perfetto()
    tracks = {(ev["pid"], ev["args"]["name"])
              for ev in doc["traceEvents"] if ev.get("ph") == "M"
              and str((ev.get("args") or {}).get("name", "")
                      ).startswith("device:")}
    assert (0, "device:all_gather") in tracks
    assert (1, "device:all_gather") in tracks
    dev = [ev for ev in doc["traceEvents"]
           if (ev.get("args") or {}).get("device_track")]
    # two slices (xfer + reduce) per stamp row
    assert len(dev) == 2 * len(rows)
    xfer = [ev for ev in dev if "xfer" in ev["name"]]
    assert all(ev["args"]["tx_bytes"] == 512 for ev in xfer)
    # clear() drops device records too
    tracing.clear()
    assert tracing.device_records() == []
