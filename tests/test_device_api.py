"""Device-side caller API tests.

Port of the reference PL-kernel test rung (test/host/hls/test.cpp:54-126:
user HLS kernels call collectives through accl_hls::ACCLCommand/ACCLData
against CCLO_BFM, no host driver on the data path) plus the in-jit
`DeviceCollectives` surface.
"""
import numpy as np
import pytest

from accl_tpu import ReduceFunction
from accl_tpu.backends.emu import EmuWorld
from accl_tpu.constants import DataType, Operation
from accl_tpu.device_api import ACCLCommand, ACCLData, DeviceCollectives

F32 = (DataType.float32, DataType.float32)

NRANKS = 2
COUNT = 32


@pytest.fixture(scope="module")
def world():
    with EmuWorld(NRANKS) as w:
        yield w


def _data(count, salt=0):
    rng = np.random.default_rng(555 + salt)
    return rng.standard_normal(count).astype(np.float32)


def test_vadd_put_kernel(world):
    # the vadd_put flow (kernels/plugins/vadd_put/vadd_put.cpp:23-86):
    # kernel computes x+1, streams it into the engine, issues stream_put;
    # the remote kernel pulls the payload from its output stream.
    def fn(accl, rank):
        cmd = ACCLCommand(accl.device, arithcfg=accl._arith_ids[F32])
        data = ACCLData(accl.device)
        if rank == 0:
            x = _data(COUNT)
            data.push(x + 1.0)          # the "vadd" compute
            cmd.stream_put(COUNT, stream_id=9, dst=1)
        elif rank == 1:
            got = data.pull(COUNT, np.float32, stream_id=9)
            np.testing.assert_allclose(got, _data(COUNT) + 1.0, rtol=1e-6)

    world.run(fn)


def test_kernel_initiated_allreduce(world):
    # a kernel issuing a rooted collective by raw device addresses —
    # the client_arbiter's second-client path (accl_hls.h allreduce :447)
    def fn(accl, rank):
        src = accl.create_buffer(COUNT, np.float32)
        dst = accl.create_buffer(COUNT, np.float32)
        src.host[:] = _data(COUNT, salt=rank)
        src.sync_to_device()

        cmd = ACCLCommand(accl.device, arithcfg=accl._arith_ids[F32])
        cmd.allreduce(COUNT, int(ReduceFunction.SUM),
                      src.address, dst.address)
        dst.sync_from_device()
        exp = sum(_data(COUNT, salt=r) for r in range(NRANKS))
        np.testing.assert_allclose(dst.host, exp, rtol=1e-5)

    world.run(fn)


def test_kernel_sendrecv_and_ack_ordering(world):
    def fn(accl, rank):
        cmd = ACCLCommand(accl.device, arithcfg=accl._arith_ids[F32])
        if rank == 0:
            buf = accl.create_buffer(COUNT, np.float32)
            buf.host[:] = _data(COUNT, salt=3)
            buf.sync_to_device()
            cmd.send(COUNT, tag=5, dst=1, src_addr=buf.address)
            # strict call/ack ordering: a second start before finalize
            # must be rejected (the reference command stream is ordered)
            cmd.start_call(Operation.nop, 0)
            with pytest.raises(RuntimeError):
                cmd.start_call(Operation.nop, 0)
            cmd.finalize_call()
        elif rank == 1:
            buf = accl.create_buffer(COUNT, np.float32)
            cmd.recv(COUNT, tag=5, src=0, dst_addr=buf.address)
            buf.sync_from_device()
            np.testing.assert_array_equal(buf.host, _data(COUNT, salt=3))

    world.run(fn)


def test_device_collectives_in_jit():
    # the in-jit surface: same helper names, XLA as the arbiter
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    shard_map = jax.shard_map

    devs = jax.devices()[:4]
    mesh = Mesh(np.array(devs), ("rank",))
    col = DeviceCollectives("rank")

    x = jnp.arange(4 * COUNT, dtype=jnp.float32).reshape(4, COUNT)

    def body(xs):
        v = xs[0]
        return (col.allreduce(v)[None],
                col.bcast(v, root=2)[None],
                col.allgather(v)[None])

    fn = shard_map(body, mesh=mesh, in_specs=P("rank"),
                   out_specs=(P("rank"), P("rank"), P("rank")))
    s, b, g = jax.jit(fn)(x)
    np.testing.assert_allclose(np.asarray(s)[0], np.asarray(x).sum(0), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(b)[0], np.asarray(x)[2], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g)[0], np.asarray(x).reshape(-1),
                               rtol=1e-6)
