"""Flagship model tests: the parallel (dp x tp x sp) train step must
match a single-device dense run — loss and updated parameters."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accl_tpu.models import (
    ModelConfig,
    forward,
    init_params,
    loss_fn,
    make_train_step,
)
from accl_tpu.models.transformer import shard_params
from accl_tpu.parallel import make_mesh

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_head=8,
                  d_ff=64)


def _tokens(b, t, seed=0):
    return np.random.default_rng(seed).integers(
        0, CFG.vocab, size=(b, t)).astype(np.int32)


def _single_device_step(params, tokens, lr=1e-3, cfg=None):
    cfg = cfg if cfg is not None else CFG

    def total_loss(p):
        s, c = loss_fn(p, tokens, cfg)
        return s, c

    (loss_sum, count), grads = jax.value_and_grad(total_loss,
                                                  has_aux=True)(params)
    scale = lr / count
    new_params = jax.tree_util.tree_map(lambda p, g: p - scale * g, params,
                                        grads)
    return new_params, loss_sum / count


@pytest.mark.parametrize("axes,schedule", [
    (dict(dp=2), "contiguous"), (dict(tp=2), "contiguous"),
    (dict(sp=2), "contiguous"), (dict(dp=2, tp=2, sp=2), "contiguous"),
    # zigzag is the SAME global computation on a permuted layout — the
    # labels' cross-shard successor fetch included
    (dict(sp=2), "zigzag"), (dict(sp=4), "zigzag"),
])
def test_parallel_train_step_matches_single(axes, schedule):
    import dataclasses

    from jax.sharding import NamedSharding

    from accl_tpu.parallel.ring_attention import zigzag_indices

    B, T = 4, 16
    mesh = make_mesh(**axes)
    cfg = dataclasses.replace(CFG, sp_schedule=schedule)
    rng = np.random.default_rng(1)
    params = init_params(rng, CFG)
    tokens = _tokens(B, T, seed=2)

    # reference: one dense step on one device (natural token order)
    ref_params, ref_loss = jax.jit(_single_device_step)(
        params, jnp.asarray(tokens))

    step, (specs, tok_spec) = make_train_step(mesh, cfg)
    p_sharded = shard_params(params, mesh, CFG)
    if schedule == "zigzag":
        perm = np.asarray(zigzag_indices(T, axes["sp"]))
        tokens = tokens[:, perm]
    tok_dev = jax.device_put(jnp.asarray(tokens),
                             NamedSharding(mesh, tok_spec))
    new_params, loss = step(p_sharded, tok_dev)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5,
                               atol=1e-6)
    flat_new = jax.tree_util.tree_leaves(new_params)
    flat_ref = jax.tree_util.tree_leaves(ref_params)
    for got, exp in zip(flat_new, flat_ref):
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   rtol=5e-4, atol=5e-5)


def test_zigzag_requires_sp_axis():
    import dataclasses

    with pytest.raises(ValueError, match="zigzag"):
        make_train_step(make_mesh(dp=2),
                        dataclasses.replace(CFG, sp_schedule="zigzag"))


def test_forward_shapes():
    params = init_params(np.random.default_rng(3), CFG)
    tokens = jnp.asarray(_tokens(2, 8, seed=4))
    logits = jax.jit(lambda p, t: forward(p, t, CFG))(params, tokens)
    assert logits.shape == (2, 8, CFG.vocab)


def test_loss_decreases():
    B, T = 4, 16
    mesh = make_mesh(dp=2, sp=2)
    params = shard_params(init_params(np.random.default_rng(5), CFG), mesh,
                          CFG)
    step, (specs, tok_spec) = make_train_step(mesh, CFG, lr=0.1)
    from jax.sharding import NamedSharding

    tokens = jax.device_put(jnp.asarray(_tokens(B, T, seed=6)),
                            NamedSharding(mesh, tok_spec))
    losses = []
    for _ in range(8):
        params, loss = step(params, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_remat_matches_exact():
    # remat recomputes each block on the backward pass — same math,
    # identical loss and gradients, at O(T) activation memory
    import dataclasses

    cfg = dataclasses.replace(CFG)
    cfg_r = dataclasses.replace(CFG, remat=True)
    params = init_params(np.random.default_rng(3), CFG)
    tokens = _tokens(2, 16, seed=4)

    def grads(c):
        return jax.jit(jax.grad(
            lambda p: loss_fn(p, jnp.asarray(tokens), c)[0]))(params)

    ga, gb = grads(cfg), grads(cfg_r)
    for a, b in zip(jax.tree_util.tree_leaves(ga),
                    jax.tree_util.tree_leaves(gb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_remat_parallel_train_step_matches_single():
    # remat composes with the SPMD train step (collectives inside the
    # checkpointed block re-execute on backward)
    import dataclasses

    from jax.sharding import NamedSharding

    B, T = 4, 16
    mesh = make_mesh(dp=2, sp=2)
    cfg = dataclasses.replace(CFG, remat=True)
    params = init_params(np.random.default_rng(1), CFG)
    tokens = _tokens(B, T, seed=2)

    ref_params, ref_loss = jax.jit(_single_device_step)(
        params, jnp.asarray(tokens))

    step, (specs, tok_spec) = make_train_step(mesh, cfg)
    p_sharded = shard_params(params, mesh, CFG)
    tok_dev = jax.device_put(jnp.asarray(tokens),
                             NamedSharding(mesh, tok_spec))
    new_params, loss = step(p_sharded, tok_dev)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5,
                               atol=1e-6)
    for got, exp in zip(jax.tree_util.tree_leaves(new_params),
                        jax.tree_util.tree_leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   rtol=5e-4, atol=5e-5)


def test_optax_train_step_matches_single_device():
    # optax path: optimizer states shard exactly like the parameters
    # they mirror (structure-based spec substitution); adamw over a
    # dp x tp mesh must reproduce the single-device update
    import optax

    from jax.sharding import NamedSharding

    opt = optax.adamw(1e-2)
    params = init_params(np.random.default_rng(0), CFG)
    tokens = _tokens(4, 16, seed=1)

    def single():
        st = opt.init(params)

        def stp(p, s, t):
            (ls, c), g = jax.value_and_grad(
                lambda pp: loss_fn(pp, t, CFG), has_aux=True)(p)
            gm = jax.tree_util.tree_map(
                lambda x: x / jnp.maximum(c, 1.0), g)
            up, s2 = opt.update(gm, s, p)
            return (optax.apply_updates(p, up), s2,
                    ls / jnp.maximum(c, 1.0))

        return jax.jit(stp)(params, st, jnp.asarray(tokens))

    ref_p, _ref_s, ref_loss = single()

    mesh = make_mesh(dp=2, tp=2)
    step, (specs, opt_specs, tok_spec), init_opt = make_train_step(
        mesh, CFG, optimizer=opt, params=params)
    ps = shard_params(params, mesh, CFG)
    st = init_opt(ps)
    tok = jax.device_put(jnp.asarray(tokens),
                         NamedSharding(mesh, tok_spec))
    new_p, new_s, loss = step(ps, st, tok)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(new_p),
                    jax.tree_util.tree_leaves(ref_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)
    # states thread (second step runs and the loss keeps moving)
    _p2, _s2, loss2 = step(new_p, new_s, tok)
    assert float(loss2) < float(loss)


def test_bf16_flash_remat_training_smoke():
    # the real-TPU training configuration (bf16 activations, flash
    # attention, per-block remat) on a dp x tp mesh: losses stay finite
    # and decrease (check_vma auto-disables on the CPU rung for the
    # flash interpreter inside shard_map; compiled TPU keeps it on).
    import dataclasses

    from jax.sharding import NamedSharding

    cfg = dataclasses.replace(CFG, dtype="bfloat16", attn="flash",
                              remat=True)
    params = init_params(np.random.default_rng(0), cfg)
    mesh = make_mesh(dp=2, tp=2)
    step, (specs, tok_spec) = make_train_step(mesh, cfg, lr=1e-2)
    p = shard_params(params, mesh, cfg)
    tok = jax.device_put(jnp.asarray(_tokens(4, 32, seed=1)),
                         NamedSharding(mesh, tok_spec))
    losses = []
    for _ in range(3):
        p, loss = step(p, tok)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses


@pytest.mark.parametrize("kvh", [2, 1])  # grouped (GQA) and MQA
def test_gqa_flash_matches_dense(kvh):
    # grouped-query attention config: the flash path reads the grouped
    # K/V in place (ops/flash.py GQA index maps) while the dense path
    # expands per q head — same math, so logits must agree to f32
    # kernel tolerance, and training must move
    import dataclasses

    cfg_d = dataclasses.replace(CFG, n_kv_heads=kvh, attn="dense")
    cfg_f = dataclasses.replace(CFG, n_kv_heads=kvh, attn="flash")
    params = init_params(np.random.default_rng(3), cfg_d)
    tok = jnp.asarray(_tokens(2, 64, seed=5))
    out_d = forward(params, tok, cfg_d)
    out_f = forward(params, tok, cfg_f)
    assert params["blocks"][0]["wk"].shape == (CFG.d_model, kvh,
                                              CFG.d_head)
    np.testing.assert_allclose(np.asarray(out_d), np.asarray(out_f),
                               rtol=2e-4, atol=2e-5)
    # gradients flow through the grouped projections
    g = jax.grad(lambda p: loss_fn(p, tok, cfg_f)[0] )(params)
    gk = np.asarray(g["blocks"][0]["wk"])
    assert gk.shape == (CFG.d_model, kvh, CFG.d_head)
    assert np.isfinite(gk).all() and np.abs(gk).max() > 0


def test_gqa_validates_divisibility():
    import dataclasses

    with pytest.raises(ValueError, match="n_kv_heads"):
        dataclasses.replace(CFG, n_kv_heads=3)


def test_rope_changes_output_and_matches_reference():
    # RoPE must actually rotate (different logits than rope=False) and
    # match a hand-rolled rotation applied around the dense attention
    import dataclasses

    cfg = dataclasses.replace(CFG, rope=True)
    params = init_params(np.random.default_rng(7), cfg)
    tok = jnp.asarray(_tokens(2, 16, seed=9))
    out = forward(params, tok, cfg)
    out_plain = forward(params, tok, CFG)
    assert np.abs(np.asarray(out) - np.asarray(out_plain)).max() > 1e-4

    # reference: the same rotation formula applied independently
    from accl_tpu.models.transformer import _rope
    Dh = CFG.d_head
    x = jnp.asarray(np.random.default_rng(11).standard_normal(
        (1, 8, 2, Dh)), jnp.float32)
    pos = jnp.arange(8)
    got = np.asarray(_rope(x, pos, 10000.0))
    half = Dh // 2
    freqs = 10000.0 ** (-np.arange(half, dtype=np.float64) / half)
    ang = np.arange(8)[:, None] * freqs[None, :]
    c, s_ = np.cos(ang), np.sin(ang)
    xn = np.asarray(x, np.float64)
    ref = np.concatenate(
        [xn[..., :half] * c[None, :, None] - xn[..., half:] * s_[None, :, None],
         xn[..., :half] * s_[None, :, None] + xn[..., half:] * c[None, :, None]],
        axis=-1)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("axes,schedule", [
    (dict(sp=2), "contiguous"),
    (dict(sp=2), "zigzag"),
    (dict(sp=4), "zigzag"),
    (dict(dp=2, tp=2, sp=2), "contiguous"),
])
def test_rope_parallel_train_step_matches_single(axes, schedule):
    # RoPE under sequence parallelism: each shard rotates by its own
    # GLOBAL positions (zigzag shards by their split chunk positions),
    # so the distributed step must reproduce the single-device run —
    # a wrong position base shows up here immediately
    import dataclasses

    from jax.sharding import NamedSharding

    from accl_tpu.parallel.ring_attention import zigzag_indices

    B, T = 4, 16
    mesh = make_mesh(**axes)
    cfg1 = dataclasses.replace(CFG, rope=True, n_kv_heads=2)
    cfg = dataclasses.replace(cfg1, sp_schedule=schedule)
    rng = np.random.default_rng(1)
    params = init_params(rng, cfg1)
    tokens = _tokens(B, T, seed=2)

    def single(p, tok, lr=1e-3):
        def total_loss(p):
            return loss_fn(p, tok, cfg1)

        (loss_sum, count), grads = jax.value_and_grad(
            total_loss, has_aux=True)(p)
        scale = lr / count
        return (jax.tree_util.tree_map(lambda a, g: a - scale * g, p,
                                       grads),
                loss_sum / count)

    ref_params, ref_loss = jax.jit(single)(params, jnp.asarray(tokens))

    step, (specs, tok_spec) = make_train_step(mesh, cfg)
    p_sharded = shard_params(params, mesh, cfg)
    if schedule == "zigzag":
        perm = np.asarray(zigzag_indices(T, axes["sp"]))
        tokens = tokens[:, perm]
    tok_dev = jax.device_put(jnp.asarray(tokens),
                             NamedSharding(mesh, tok_spec))
    new_params, loss = step(p_sharded, tok_dev)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5,
                               atol=1e-6)
    for got, exp in zip(jax.tree_util.tree_leaves(new_params),
                        jax.tree_util.tree_leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   rtol=5e-4, atol=5e-5)


@pytest.mark.parametrize("axes", [dict(tp=2), dict(dp=2, tp=2, sp=2)])
def test_swiglu_parallel_matches_single(axes):
    # the gated MLP (silu(x W1) * (x W3) W2): the gate projection
    # shards its hidden dim like w1, so the tp row-parallel psum
    # contract holds — distributed step == single-device step
    import dataclasses

    from jax.sharding import NamedSharding

    B, T = 4, 16
    mesh = make_mesh(**axes)
    cfg = dataclasses.replace(CFG, mlp="swiglu")
    params = init_params(np.random.default_rng(17), cfg)
    assert "w3" in params["blocks"][0]
    tokens = _tokens(B, T, seed=18)

    def single(p, tok, lr=1e-3):
        (loss_sum, count), grads = jax.value_and_grad(
            lambda p: loss_fn(p, tok, cfg), has_aux=True)(p)
        scale = lr / count
        return (jax.tree_util.tree_map(lambda a, g: a - scale * g, p,
                                       grads),
                loss_sum / count)

    ref_params, ref_loss = jax.jit(single)(params, jnp.asarray(tokens))
    step, (specs, tok_spec) = make_train_step(mesh, cfg)
    p_sharded = shard_params(params, mesh, cfg)
    tok_dev = jax.device_put(jnp.asarray(tokens),
                             NamedSharding(mesh, tok_spec))
    new_params, loss = step(p_sharded, tok_dev)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5,
                               atol=1e-6)
    for got, exp in zip(jax.tree_util.tree_leaves(new_params),
                        jax.tree_util.tree_leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   rtol=5e-4, atol=5e-5)


def test_swiglu_differs_from_gelu():
    import dataclasses

    cfg = dataclasses.replace(CFG, mlp="swiglu")
    p = init_params(np.random.default_rng(19), cfg)
    tok = jnp.asarray(_tokens(2, 16, seed=20))
    out_s = forward(p, tok, cfg)
    # same params minus the gate run the gelu MLP
    p_g = {**p, "blocks": [{k: v for k, v in b.items() if k != "w3"}
                           for b in p["blocks"]]}
    out_g = forward(p_g, tok, CFG)
    assert np.abs(np.asarray(out_s) - np.asarray(out_g)).max() > 1e-4


def test_sliding_window_flash_matches_dense():
    # attn_window: the flash grid schedule (dead blocks skipped) must
    # agree with the dense banded mask, and the window must change the
    # result vs full causal attention
    import dataclasses

    cfg_d = dataclasses.replace(CFG, attn_window=8, attn="dense")
    cfg_f = dataclasses.replace(CFG, attn_window=8, attn="flash")
    params = init_params(np.random.default_rng(23), cfg_d)
    tok = jnp.asarray(_tokens(2, 64, seed=24))
    out_d = forward(params, tok, cfg_d)
    out_f = forward(params, tok, cfg_f)
    np.testing.assert_allclose(np.asarray(out_d), np.asarray(out_f),
                               rtol=2e-4, atol=2e-5)
    out_full = forward(params, tok, CFG)
    assert np.abs(np.asarray(out_d) - np.asarray(out_full)).max() > 1e-4


def test_sliding_window_sp_composition_rules():
    """r5: window + sp COMPOSES on the contiguous schedule (covered by
    test_windowed_sp_train_step_matches_single); the zigzag layout's
    split chunks break the one-neighbor-hop bound and must raise, as
    must a window wider than the local shard."""
    import dataclasses

    from jax.sharding import NamedSharding

    cfg = dataclasses.replace(CFG, attn_window=8, sp_schedule="zigzag")
    mesh = make_mesh(sp=2)
    step, (specs, tok_spec) = make_train_step(mesh, cfg)
    p = shard_params(init_params(np.random.default_rng(1), cfg), mesh, cfg)
    tok = jax.device_put(jnp.asarray(_tokens(2, 16)),
                         NamedSharding(mesh, tok_spec))
    with pytest.raises(Exception, match="zigzag|contiguous"):
        step(p, tok)

    # window wider than the local shard: 16 tokens over sp=2 -> Tl=8 < 9
    cfg2 = dataclasses.replace(CFG, attn_window=9)
    step2, (_s2, tok_spec2) = make_train_step(mesh, cfg2)
    p2 = shard_params(init_params(np.random.default_rng(1), cfg2), mesh,
                      cfg2)
    tok2 = jax.device_put(jnp.asarray(_tokens(2, 16)),
                          NamedSharding(mesh, tok_spec2))
    with pytest.raises(Exception, match="window"):
        step2(p2, tok2)


def test_windowed_sp_train_step_matches_single():
    """attn_window + sequence parallelism (r5: local windowed block +
    one neighbor hop) — the full TRAIN STEP must reproduce the
    single-device banded run: loss and updated parameters."""
    import dataclasses

    from jax.sharding import NamedSharding

    B, T, W = 4, 32, 5   # T_local = 8 >= W (one-neighbor-hop bound)
    mesh = make_mesh(sp=4)
    cfg = dataclasses.replace(CFG, attn_window=W)
    rng = np.random.default_rng(1)
    params = init_params(rng, cfg)
    tokens = _tokens(B, T, seed=2)

    def single_step(params, tokens):
        return _single_device_step(params, tokens, cfg=cfg)

    ref_params, ref_loss = jax.jit(single_step)(params,
                                                jnp.asarray(tokens))
    step, (specs, tok_spec) = make_train_step(mesh, cfg)
    p_sharded = shard_params(params, mesh, cfg)
    tok_dev = jax.device_put(jnp.asarray(tokens),
                             NamedSharding(mesh, tok_spec))
    new_params, loss = step(p_sharded, tok_dev)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5,
                               atol=1e-6)
    for got, exp in zip(jax.tree_util.tree_leaves(new_params),
                        jax.tree_util.tree_leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   rtol=5e-4, atol=5e-5)
