"""Benchmark sweep harness + stress tests.

- sweep: reference ACCLSweepBenchmark (bench.cpp:25-61) — here a short
  range in CI; the full 2^4..2^19 sweep runs via scripts/run_sweep.py
- stress: the reference 2000-iteration ring send/recv
  (test/host/xrt/src/stress.cpp:24-34)
"""
import io

import numpy as np

from accl_tpu.backends.emu import EmuWorld
from accl_tpu.bench import SweepConfig, run_sweep
from accl_tpu.utils.bringup import Design, generate_ranks, initialize_world


def test_sweep_emulator():
    cfg = SweepConfig(count_pows=(4, 8), repetitions=1)
    out = io.StringIO()
    with EmuWorld(2) as world:
        rows = run_sweep(world, cfg, writer=out)
    assert len(rows) == len(cfg.collectives) * 2
    csv_text = out.getvalue()
    assert "allreduce" in csv_text and "busbw_GBps" in csv_text
    for r in rows:
        assert r["duration_us"] > 0


def test_sweep_tpu_backend():
    from accl_tpu.backends.tpu import TpuWorld

    cfg = SweepConfig(collectives=("allreduce", "allgather"),
                      count_pows=(6,), repetitions=1)
    with TpuWorld(4) as world:
        rows = run_sweep(world, cfg)
    assert len(rows) == 2


def test_stress_ring_sendrecv():
    # reference stress.cpp: 2000 iterations; trimmed for CI wall clock
    iters, count = 500, 32
    with EmuWorld(2) as world:
        def fn(accl, rank):
            nxt, prv = (rank + 1) % 2, (rank - 1) % 2
            src = accl.create_buffer_like(
                np.full(count, float(rank), np.float32))
            dst = accl.create_buffer(count, np.float32)
            for i in range(iters):
                sreq = accl.send(src, count, nxt, tag=i % 7, run_async=True)
                accl.recv(dst, count, prv, tag=i % 7)
                assert sreq.wait(30)
                sreq.check()
            np.testing.assert_array_equal(
                dst.host, np.full(count, float(prv), np.float32))

        world.run(fn)


def test_generate_ranks_and_bringup():
    ranks = generate_ranks(4, base_port=6000)
    assert len(ranks) == 4 and ranks[2].port == 6002
    with initialize_world(Design.EMU_INPROC, 2) as world:
        from accl_tpu import ReduceFunction

        def fn(accl, rank):
            a = accl.create_buffer_like(np.ones(8, np.float32))
            b = accl.create_buffer(8, np.float32)
            accl.allreduce(a, b, 8, ReduceFunction.SUM)
            return float(b.host[0])

        assert world.run(fn) == [2.0, 2.0]


def _import_baseline_bench():
    import importlib
    import os
    import sys

    scripts = os.path.join(os.path.dirname(__file__), os.pardir, "scripts")
    if scripts not in sys.path:
        sys.path.insert(0, scripts)
    return importlib.import_module("baseline_bench")


# ---------------------------------------------------------------------------
# the five benchmark configs of record (BASELINE.json / BASELINE.md) run
# end-to-end in miniature
# ---------------------------------------------------------------------------
def test_baseline_config1_cpu_baseline():
    import io
    baseline_bench = _import_baseline_bench()

    rows = baseline_bench.config1(io.StringIO(), reps=1)
    assert {r["collective"] for r in rows} == {"allreduce"}
    assert all(r["duration_us"] > 0 for r in rows)


def test_baseline_config3_bf16_fp16():
    import io
    baseline_bench = _import_baseline_bench()

    rows = baseline_bench.config3(io.StringIO(), reps=1)
    colls = {r["collective"] for r in rows}
    assert colls == {"allgather", "reduce_scatter"}


def test_baseline_config5_fusion():
    import io
    baseline_bench = _import_baseline_bench()

    rows = baseline_bench.config5(io.StringIO(), reps=1)
    by = {r["variant"]: r for r in rows}
    assert by["fused"]["seconds"] > 0 and by["unfused"]["seconds"] > 0


def test_parse_bench_results_roundtrip(tmp_path):
    # the postprocessing pair of the reference (parse_bench_results.py /
    # Coyote plot.py): sweep CSV -> median table + ratio vs a baseline
    import importlib.util
    import io as _io
    import os

    path = os.path.join(os.path.dirname(__file__), os.pardir, "scripts",
                        "parse_bench_results.py")
    spec = importlib.util.spec_from_file_location("parse_bench_results", path)
    parse = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(parse)

    csv_text = (
        "collective,count,bytes,duration_us,algbw_GBps,busbw_GBps,repetition\n"
        "allreduce,16,64,10.0,0.006,0.009,0\n"
        "allreduce,16,64,20.0,0.004,0.006,1\n"
        "allreduce,32,128,10.0,0.012,0.018,0\n")
    p = tmp_path / "sweep.csv"
    p.write_text(csv_text)
    data = parse.load(str(p))
    assert data[("allreduce", 16)]["dur_us"] == 15.0  # median of reps
    out = _io.StringIO()
    parse.report(data, baseline=data, out=out)
    text = out.getvalue()
    assert "allreduce" in text and "1.00x" in text and "peak busbw" in text


def _load_bench(name="bench_mod"):
    import importlib.util
    import os as _os

    spec = importlib.util.spec_from_file_location(
        name, _os.path.join(_os.path.dirname(_os.path.dirname(
            _os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


def test_bench_stage_ledger_roundtrip(tmp_path, monkeypatch):
    """bench.py's per-stage banking: stages persist atomically under a
    run id, a different run id starts clean, and _assemble builds the
    result line from whatever fragments landed (r4 lost its round
    record to an all-or-nothing worker; this is the regression lock)."""
    bench = _load_bench()
    monkeypatch.setattr(bench, "_LEDGER_DIR", str(tmp_path))

    led = bench._load_ledger("run-A")
    assert led["stages"] == {}
    bench._bank_stage(led, "headline", {"gbps": 640.0, "platform": "tpu",
                                        "xla_add_gbps": 650.0})
    bench._bank_stage(led, "flash", {"flash_d128_tflops": 64.0})

    # same run id resumes with both stages; another id starts clean
    led2 = bench._load_ledger("run-A")
    assert sorted(led2["stages"]) == ["flash", "headline"]
    assert bench._load_ledger("run-B")["stages"] == {}

    # partial assembly: headline + flash present, rest reported missing
    res = bench._assemble(led2["stages"])
    assert res["value"] == 640.0
    assert res["detail"]["flash_d128_tflops"] == 64.0
    assert res["detail"]["xla_add_gbps"] == 650.0
    assert set(res["stages_missing"]) == (
        set(bench.ALL_STAGES) - {"headline", "flash"})
    assert res["vs_baseline"] == round(640.0 / bench.BASELINE_GBPS, 2)

    # no headline -> nothing to report
    assert bench._assemble({"flash": {"x": 1}}) is None


def test_bench_stage_functions_smoke(monkeypatch):
    """Structurally execute every TPU bench stage's operand
    construction + reporting logic with a FAKE timing harness, so a
    NameError/typo in chip-only code fails in CI instead of wasting a
    scarce claim window (r4's bf16 lane was added after the last
    successful window and had never run when the round closed)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    bench = _load_bench("bench_mod2")

    def fake_chain(fn, x0, iters, trials=1, consts=()):
        return 1e-3  # plausible per-iteration seconds; never executes

    detail = bench._flash_stage(jax, jnp, fake_chain)
    # the reporting paths must have produced the headline flash keys
    assert "flash_d128_tflops" in detail, detail
    assert "flash_attention_tflops" in detail, detail
    # equal fake times -> composite frac > 1 -> the consistency gate
    # must fail CLOSED (no DCE-style inflated number can slip out)
    assert "flash_d128_fwdbwd_tflops" not in detail, detail
    assert ("flash_d128_fwdbwd_inconsistent" in detail
            or "flash_d128_fwdbwd_error" in detail), detail

    detail = bench._flash_variants_stage(jax, jnp, fake_chain)
    assert "flash_d128_packed_all" in detail, detail
    assert "flash_d64_packed_all" in detail, detail

    def fake_ab(fns, x0, iters, trials=1, consts=()):
        return {k: 1e-3 for k in fns}

    detail = bench._compression_stage(jax, jnp, fake_ab)
    assert ("compression_gbps" in detail
            or "compression_error" in detail), detail

    # selfring asserts correctness before timing: on the CPU backend
    # the compiled (non-interpret) kernels cannot run, so the stage
    # must degrade to its recorded-error path, never raise
    detail = bench._selfring_stage(jax, jnp, fake_chain)
    assert ("ring_selfring_error" in detail
            or "ring_compiled_selfring_ok" in detail), detail


def test_bench_stale_replay_strips_retracted_keys():
    """A stale fallback record must never re-assert a figure the docs
    have retracted (r5 VERDICT weak #1): the scrub strips the
    retracted detail keys and lists them under "retracted" so
    consumers can tell silence from omission."""
    bench = _load_bench("bench_mod3")
    record = {
        "value": 653.4, "platform": "tpu",
        "detail": {
            "flash_d128_tflops": 64.4,               # kept: not retracted
            "flash_d128_fwdbwd_tflops": 151.2,       # retracted (r4 DCE)
            "flash_d128_fwdbwd_mxu_frac": 0.811,     # retracted
        },
    }
    out = bench._scrub_retracted(record)
    assert out is record
    assert "flash_d128_fwdbwd_tflops" not in record["detail"]
    assert "flash_d128_fwdbwd_mxu_frac" not in record["detail"]
    assert record["detail"]["flash_d128_tflops"] == 64.4
    assert record["retracted"] == sorted(
        ["flash_d128_fwdbwd_mxu_frac", "flash_d128_fwdbwd_tflops"])

    # a record with nothing retracted passes through unmarked
    clean = {"detail": {"flash_d128_tflops": 64.4}}
    assert "retracted" not in bench._scrub_retracted(clean)
