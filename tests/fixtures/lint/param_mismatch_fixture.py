"""Seeded parameter mismatch: every rank allreduces — same op, same
order — but rank 1 passes a different count.  Each engine derives its
wire format and segmentation from its own descriptor, so this desyncs
the dataplane (or hangs the gang) at runtime.  accl_lint must flag it
(``param-mismatch``) and exit nonzero.
"""
import numpy as np

from accl_tpu import ReduceFunction

LINT_RANKS = 2
COUNT = 256


def accl_main(accl, rank):
    src = accl.create_buffer(COUNT, np.float32)
    dst = accl.create_buffer(COUNT, np.float32)
    count = COUNT if rank == 0 else COUNT // 2
    accl.allreduce(src, dst, count, ReduceFunction.SUM)
