"""A representative CLEAN collective program: every checker must come
back empty.  Exercises the full surface the linter reasons about —
gang collectives (uniform parameters), an async neighbor exchange
(properly waited, deadlock-free order), a sub-communicator, rooted
collectives with valid comm-local roots, disjoint buffers, and
buffer free only after the last use.
"""
import numpy as np

from accl_tpu import ReduceFunction

LINT_RANKS = 4
COUNT = 1024


def accl_main(accl, rank):
    nranks = accl.size
    src = accl.create_buffer(COUNT, np.float32)
    dst = accl.create_buffer(COUNT, np.float32)
    gathered = accl.create_buffer(COUNT * nranks, np.float32)

    # gang collectives with uniform parameters
    accl.allreduce(src, dst, COUNT, ReduceFunction.SUM)
    accl.allgather(src, gathered, COUNT)
    accl.bcast(src, COUNT, root=0)
    accl.barrier()

    # async ring exchange: send posted async, recv blocks, then drain
    peer = (rank + 1) % nranks
    frm = (rank - 1) % nranks
    req = accl.send(src, COUNT, dst=peer, tag=7, run_async=True)
    accl.recv(dst, COUNT, src=frm, tag=7)
    req.wait()
    req.check()

    # sub-communicator of the even ranks, comm-local root
    members = list(range(0, nranks, 2))
    if rank in members:
        cid = accl.create_communicator(members)
        sub = accl.create_buffer(COUNT, np.float32)
        accl.reduce(src, sub, COUNT, root=0, comm_id=cid)
        sub.free()

    gathered.free()
