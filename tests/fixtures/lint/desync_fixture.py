"""Seeded issue-order desync: rank 0 allreduces while rank 1
broadcasts on the same communicator — the classic mismatched-order bug
that hangs both engines until the watchdog fires.  accl_lint must flag
it (``desync-order``) and exit nonzero; CI asserts exactly that.
"""
import numpy as np

from accl_tpu import ReduceFunction

LINT_RANKS = 2


def accl_main(accl, rank):
    src = accl.create_buffer(256, np.float32)
    dst = accl.create_buffer(256, np.float32)
    if rank == 0:
        accl.allreduce(src, dst, 256, ReduceFunction.SUM)
    else:
        accl.bcast(src, 256, root=0)
