"""Seeded cross-communicator interleave hazard: a 2x2 grid where the
top row enters row-comm-then-col-comm but the bottom row enters
col-comm-then-row-comm.  Every per-comm stream agrees (same ops, same
params, same depth — ``desync-order`` stays quiet), but no global comm
order exists: the gang windows interlock, and the chunked/async
engines contend for the shared rx pool exactly like the 8-rank
sub-comm allgather wedge.  accl_lint must flag
``subcomm-interleave-hazard`` and exit nonzero.
"""
import numpy as np

LINT_RANKS = 4
COUNT = 256


def accl_main(accl, rank):
    row, col = divmod(rank, 2)
    # id discipline: every rank creates row comm then col comm, so id 1
    # is "my row" and id 2 is "my col" on every rank
    row_comm = accl.create_communicator([row * 2, row * 2 + 1])
    col_comm = accl.create_communicator([col, col + 2])

    src = accl.create_buffer(COUNT, np.float32)
    row_out = accl.create_buffer(COUNT * 2, np.float32)
    col_out = accl.create_buffer(COUNT * 2, np.float32)

    if row == 0:
        first, fout = row_comm, row_out
        second, sout = col_comm, col_out
    else:  # bottom row: opposite axis first — the seeded divergence
        first, fout = col_comm, col_out
        second, sout = row_comm, row_out

    ra = accl.allgather(src, fout, COUNT, comm_id=first, run_async=True)
    rb = accl.allgather(src, sout, COUNT, comm_id=second, run_async=True)
    ra.wait()
    ra.check()
    rb.wait()
    rb.check()
