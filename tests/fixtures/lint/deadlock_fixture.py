"""Seeded send/recv deadlock: a head-to-head exchange where both ranks
issue a blocking rendezvous-sized send before their recv.  Neither
send can complete until the peer posts its landing address — a
circular wait.  accl_lint must flag the cycle (``deadlock-cycle``)
and exit nonzero.
"""
import numpy as np

from accl_tpu.constants import TAG_ANY  # noqa: F401 — doc pointer

LINT_RANKS = 2

# 4096 fp32 = 16 KB: far above the 1 KB eager threshold, so the send
# rides RENDEZVOUS and genuinely blocks on the matching recv
COUNT = 4096


def accl_main(accl, rank):
    peer = 1 - rank
    src = accl.create_buffer(COUNT, np.float32)
    dst = accl.create_buffer(COUNT, np.float32)
    accl.send(src, COUNT, dst=peer, tag=3)
    accl.recv(dst, COUNT, src=peer, tag=3)
