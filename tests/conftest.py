"""Test configuration.

Forces an 8-device virtual CPU platform *before* jax initializes, so the
multi-chip sharding paths (mesh collectives, shard_map, pjit) run in CI
without TPU hardware — the TPU translation of the reference's
run-everything-against-the-CPU-emulator strategy (SURVEY §4).
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(42)
