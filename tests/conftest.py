"""Test configuration.

Forces an 8-device virtual CPU platform *before* jax initializes, so the
multi-chip sharding paths (mesh collectives, shard_map, pjit) run in CI
without TPU hardware — the TPU translation of the reference's
run-everything-against-the-CPU-emulator strategy (SURVEY §4).

Set ACCL_TEST_ON_TPU=1 to SKIP the CPU pin and run against whatever
platform jax claims — how bench.py's TPU worker executes the
TPU-marked tests (stochastic rounding et al.) on the real chip, so no
test is permanently skipped on every rung.
"""
import os

_ON_TPU = os.environ.get("ACCL_TEST_ON_TPU") == "1"

if not _ON_TPU:
    os.environ["JAX_PLATFORMS"] = "cpu"
# Loaded CI hosts can stall a rank long enough for the 1 s reference
# receive budget to fire spuriously; widen the *default* engine timeout
# for tests (tests exercising timeout behavior pass explicit values).
os.environ.setdefault("ACCL_DEFAULT_TIMEOUT", "30000000")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# jax may already have been imported by the environment's sitecustomize
# (with a hardware platform baked in); the runtime config update is what
# actually pins tests to the virtual CPU mesh.
import jax

if not _ON_TPU:
    jax.config.update("jax_platforms", "cpu")

# jax < 0.5 compatibility: the corpus is written against the current
# `jax.shard_map` spelling (check_vma kwarg); alias the library's shim
# so test modules keep the one spelling (library code imports it
# directly)
from accl_tpu.utils.compat import install as _compat_install

_compat_install(jax)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(42)
