"""Aux-subsystem utilities (SURVEY §5): timer, profiling hooks,
topology/capability probe (the hwid parse analog), debug logging."""
import os

import pytest


def test_timer_shape():
    import time

    from accl_tpu.utils.timing import Timer

    t = Timer()
    t.start()
    time.sleep(0.01)
    t.end()
    us = t.durationUs()
    assert 5_000 <= us <= 5_000_000
    assert abs(t.duration_ns() - us * 1000) < 1e3
    with Timer() as t2:
        time.sleep(0.002)
    assert t2.durationUs() >= 1_000


def test_profiling_timed_and_time_fn():
    import jax.numpy as jnp

    from accl_tpu.utils.profiling import time_fn, timed

    results = {}
    with timed("block", results):
        sum(range(1000))
    assert len(results["block"]) == 1 and results["block"][0] > 0

    import jax

    f = jax.jit(lambda x: x * 2 + 1)
    dt = time_fn(f, jnp.ones(128), iters=3, warmup=1)
    assert dt > 0


def test_topology_probe_and_hwid():
    from accl_tpu.utils.topology import dump, probe

    cap = probe()
    assert cap.num_devices == 8  # conftest's virtual CPU mesh
    word = cap.hwid()
    # bit layout: platform (cpu=0), arith bit 4, compression bit 5,
    # remote-dma bit 6, device count at bits 8+
    assert word & 0xF == 0
    assert (word >> 4) & 1 == 1
    assert (word >> 5) & 1 == 1
    assert (word >> 8) & 0xFFFF == 8
    text = dump()
    assert "platform=cpu" in text and "n=8" in text


def test_debug_logging_env(capsys, monkeypatch):
    import importlib
    import logging as stdlog

    from accl_tpu.utils import logging as alog

    monkeypatch.setenv("ACCL_DEBUG", "1")
    # reset the module's one-shot configuration so the env is honored
    importlib.reload(alog)
    stdlog.getLogger("accl_tpu").handlers.clear()
    log = alog.get_logger(rank=3)
    log.debug("hello-debug")
    err = capsys.readouterr().err
    # structured rank prefix: "[accl r3] D hello-debug"
    assert "hello-debug" in err and "[accl r3]" in err
    # restore: unconfigured module state for later tests
    monkeypatch.delenv("ACCL_DEBUG")
    stdlog.getLogger("accl_tpu").handlers.clear()
    importlib.reload(alog)


def test_accl_log_level_env(capsys, monkeypatch):
    import importlib
    import logging as stdlog

    from accl_tpu.utils import logging as alog

    monkeypatch.setenv("ACCL_LOG", "info")
    importlib.reload(alog)
    stdlog.getLogger("accl_tpu").handlers.clear()
    log = alog.get_logger(rank=1)
    log.info("at-info")
    log.debug("below-level")
    err = capsys.readouterr().err
    assert "[accl r1] I at-info" in err
    assert "below-level" not in err
    monkeypatch.delenv("ACCL_LOG")
    stdlog.getLogger("accl_tpu").handlers.clear()
    importlib.reload(alog)


def test_initialize_multihost_arg_assembly(monkeypatch):
    # dry_run resolves explicit args + ACCL_* env defaults without
    # touching jax (a second host doesn't exist on CI); explicit
    # arguments win over the environment
    from accl_tpu.utils.bringup import initialize_multihost

    monkeypatch.setenv("ACCL_COORDINATOR", "10.0.0.1:8476")
    monkeypatch.setenv("ACCL_NUM_PROCESSES", "4")
    monkeypatch.setenv("ACCL_PROCESS_ID", "2")
    kw = initialize_multihost(dry_run=True)
    assert kw == {"coordinator_address": "10.0.0.1:8476",
                  "num_processes": 4, "process_id": 2}

    kw = initialize_multihost(coordinator_address="h:1", process_id=0,
                              local_device_ids=[0, 1], dry_run=True)
    assert kw["coordinator_address"] == "h:1"
    assert kw["process_id"] == 0
    assert kw["local_device_ids"] == [0, 1]
    assert kw["num_processes"] == 4  # env still fills the gap

    monkeypatch.delenv("ACCL_COORDINATOR")
    monkeypatch.delenv("ACCL_NUM_PROCESSES")
    monkeypatch.delenv("ACCL_PROCESS_ID")
    assert initialize_multihost(dry_run=True) == {}  # pod auto-detect


@pytest.fixture
def _restore_jax_cache_config():
    # enable() mutates process-global jax config; leaking it would make
    # every later compile in the suite silently persist to a test dir
    import jax

    keys = ("jax_persistent_cache_min_compile_time_secs",
            "jax_compilation_cache_dir")
    prev = {k: getattr(jax.config, k) for k in keys}
    yield
    for k, v in prev.items():
        jax.config.update(k, v)


def test_compile_cache_enable(tmp_path, _restore_jax_cache_config):
    # the chip-facing tools call this before their first compile; it
    # must activate the persistent cache (compiles survive process
    # restarts) and report the directory it actually used
    import jax
    import jax.numpy as jnp

    from accl_tpu.utils.compile_cache import enable

    d = enable(str(tmp_path / "cache"))
    assert d == str(tmp_path / "cache")
    assert os.path.isdir(d)
    # a compile after enable() lands an artifact in the cache dir
    fn = jax.jit(lambda x: x * 2 + 1)
    fn(jnp.ones((8, 128))).block_until_ready()
    assert os.listdir(d), "no cache entry written for a fresh compile"


def test_compile_cache_env_override(tmp_path, monkeypatch,
                                    _restore_jax_cache_config):
    # $ACCL_COMPILE_CACHE wins over the per-user default when no
    # explicit path is passed
    from accl_tpu.utils.compile_cache import enable

    target = str(tmp_path / "envcache")
    monkeypatch.setenv("ACCL_COMPILE_CACHE", target)
    assert enable() == target


def test_compile_cache_default_dir_is_per_user():
    # a world-shared fixed path would be owned by whoever ran first on
    # a shared host; the default must be user-scoped
    import getpass

    from accl_tpu.utils import compile_cache

    d = compile_cache._default_dir()
    assert getpass.getuser() in os.path.basename(d)
