"""Fault injection against the failure-detection machinery.

SURVEY §5: the reference protects correctness with per-peer sequence
numbers checked at seek time, sticky error codes, and receive timeouts —
but ships no fault injector.  This harness injects one-shot egress
faults (drop / duplicate / seqn corruption) and asserts the detection
paths fire with the right error class.

The worlds here run with the retransmission lane OFF (``retry_max=0``):
these tests pin the DETECTION contract — which error class each fault
surfaces as.  With the lane on (the default), the same faults heal
transparently; that recovery matrix lives in tests/test_resilience.py.

The world is module-scoped and REUSED across tests: classified faults
no longer poison it permanently — ``reset_errors()`` resynchronizes the
sequence state after each test (the r10 recovery satellite), and
``test_world_reusable_after_classified_fault`` pins exactly that.
"""
import numpy as np
import pytest

from accl_tpu import ACCLError, ReduceFunction
from accl_tpu.backends.emu import EmuDevice, EmuWorld
from accl_tpu.constants import ErrorCode

NRANKS = 2
COUNT = 64


@pytest.fixture(scope="module")
def _world():
    # retransmission off: detection semantics (error classes), not
    # recovery, are under test here
    with EmuWorld(NRANKS, retry_max=0) as w:
        yield w


@pytest.fixture()
def world(_world):
    # module-world reuse: a classified fault skews seqn state, so every
    # test hands the world back resynchronized (ACCL.reset_errors —
    # zeroed seqn counters both directions, drained pools/stores)
    yield _world
    _world.reset_errors()


def _data(count, salt=0):
    rng = np.random.default_rng(4242 + salt)
    return rng.standard_normal(count).astype(np.float32)


def test_dropped_message_times_out(world):
    def fn(accl, rank):
        accl.set_timeout(1_000_000)  # 1s receive timeout
        if rank == 0:
            src = accl.create_buffer_like(_data(COUNT))
            accl.device.inject_fault(EmuDevice.FAULT_DROP)
            accl.send(src, COUNT, 1, tag=1)  # vanishes on the wire
        else:
            dst = accl.create_buffer(COUNT, np.float32)
            with pytest.raises(ACCLError) as e:
                accl.recv(dst, COUNT, 0, tag=1)
            assert e.value.code & int(ErrorCode.RECEIVE_TIMEOUT_ERROR)

    world.run(fn)


def test_corrupt_seqn_detected(world):
    def fn(accl, rank):
        accl.set_timeout(1_000_000)
        if rank == 0:
            src = accl.create_buffer_like(_data(COUNT))
            accl.device.inject_fault(EmuDevice.FAULT_CORRUPT_SEQ)
            accl.send(src, COUNT, 1, tag=2)
        else:
            dst = accl.create_buffer(COUNT, np.float32)
            with pytest.raises(ACCLError) as e:
                accl.recv(dst, COUNT, 0, tag=2)
            # the wrong-seqn segment is IN the pool: classified as a
            # sequence error, not a bare timeout
            assert e.value.code & int(ErrorCode.PACK_SEQ_NUMBER_ERROR)

    world.run(fn)


def test_duplicate_message_tolerated(world):
    # a duplicated segment must not corrupt the stream: the first copy
    # matches, the stale copy is ignored by seqn discipline, and later
    # traffic still matches its expected sequence numbers
    def fn(accl, rank):
        accl.set_timeout(5_000_000)
        if rank == 0:
            a = accl.create_buffer_like(_data(COUNT, salt=1))
            b = accl.create_buffer_like(_data(COUNT, salt=2))
            accl.device.inject_fault(EmuDevice.FAULT_DUPLICATE)
            accl.send(a, COUNT, 1, tag=3)
            accl.send(b, COUNT, 1, tag=4)
        else:
            da = accl.create_buffer(COUNT, np.float32)
            db = accl.create_buffer(COUNT, np.float32)
            accl.recv(da, COUNT, 0, tag=3)
            accl.recv(db, COUNT, 0, tag=4)
            np.testing.assert_array_equal(da.host, _data(COUNT, salt=1))
            np.testing.assert_array_equal(db.host, _data(COUNT, salt=2))

    world.run(fn)


def test_ahead_of_sequence_message_survives_misordered_recv(world):
    # the per-src seqn counter is shared across tags: a recv posted in a
    # different tag order than the sends must classify as a sequence
    # error BUT leave the still-valid future message queued, so the
    # correctly-ordered recvs afterwards succeed (no eviction of legal
    # ahead-of-sequence traffic)
    def fn(accl, rank):
        if rank == 0:
            a = accl.create_buffer_like(_data(COUNT, salt=11))
            b = accl.create_buffer_like(_data(COUNT, salt=12))
            accl.send(a, COUNT, 1, tag=21)  # seqn 0
            accl.send(b, COUNT, 1, tag=22)  # seqn 1
        else:
            accl.set_timeout(1_000_000)
            db = accl.create_buffer(COUNT, np.float32)
            with pytest.raises(ACCLError) as e:
                accl.recv(db, COUNT, 0, tag=22)  # expects seqn 0, has 1
            assert e.value.code & int(ErrorCode.PACK_SEQ_NUMBER_ERROR)
            da = accl.create_buffer(COUNT, np.float32)
            accl.recv(da, COUNT, 0, tag=21)  # seqn 0 still matches
            accl.recv(db, COUNT, 0, tag=22)  # seqn 1 now matches
            np.testing.assert_array_equal(da.host, _data(COUNT, salt=11))
            np.testing.assert_array_equal(db.host, _data(COUNT, salt=12))

    world.run(fn)


def test_seq_error_classified_and_other_routes_survive(world):
    # a corrupt-seqn segment is classified as a sequence error; while the
    # pool has spare capacity the offending (ahead) segment stays queued
    # (it could be a differently-ordered legal message), nothing is
    # parked in staging, and traffic on other routes is unaffected
    def fn(accl, rank):
        # rank 1 deliberately burns its 1s receive timeout on the broken
        # route; rank 0 must out-wait that before the reverse transfer
        accl.set_timeout(30_000_000 if rank == 0 else 1_000_000)
        if rank == 0:
            b = accl.create_buffer_like(_data(COUNT, salt=7))
            accl.device.inject_fault(EmuDevice.FAULT_CORRUPT_SEQ)
            accl.send(b, COUNT, 1, tag=5)
            # reverse direction still works after the fault
            d = accl.create_buffer(COUNT, np.float32)
            accl.recv(d, COUNT, 1, tag=6)
            np.testing.assert_array_equal(d.host, _data(COUNT, salt=8))
        else:
            d = accl.create_buffer(COUNT, np.float32)
            with pytest.raises(ACCLError):
                accl.recv(d, COUNT, 0, tag=5)
            assert "0 staged" in accl.dump_rx_buffers()  # nothing parked
            b = accl.create_buffer_like(_data(COUNT, salt=8))
            accl.send(b, COUNT, 0, tag=6)

    world.run(fn)


def test_world_reusable_after_classified_fault(world):
    # the r10 recovery satellite: a classified fault + reset_errors
    # leaves the world fully usable — the next collective succeeds with
    # bitwise-correct results (no permanent seqn poisoning, which is
    # what used to force function-scoped fixtures here)
    def poison(accl, rank):
        accl.set_timeout(1_000_000)
        if rank == 0:
            src = accl.create_buffer_like(_data(COUNT, salt=31))
            accl.device.inject_fault(EmuDevice.FAULT_DROP)
            accl.send(src, COUNT, 1, tag=41)  # vanishes; seqn burned
        else:
            dst = accl.create_buffer(COUNT, np.float32)
            with pytest.raises(ACCLError):
                accl.recv(dst, COUNT, 0, tag=41)

    world.run(poison)
    world.reset_errors()  # collective resync on the quiesced world

    def after(accl, rank):
        s = accl.create_buffer_like(_data(COUNT, salt=rank))
        r = accl.create_buffer(COUNT, np.float32)
        accl.allreduce(s, r, COUNT, ReduceFunction.SUM)
        return r.host.copy()

    outs = world.run(after)
    expected = _data(COUNT, salt=0) + _data(COUNT, salt=1)
    for out in outs:
        np.testing.assert_allclose(out, expected, rtol=1e-6)


def test_pool_exhaustion_reclaims_broken_route():
    # reclamation bound: when a corrupted stream's ahead-of-sequence
    # segments fill the whole pool, the sequence-error path must
    # force-evict the route so the pool cannot starve the world
    import time

    from accl_tpu.backends.emu import EmuWorld as W
    with W(NRANKS, n_egr_rx_bufs=4, retry_max=0) as world:
        def fn(accl, rank):
            if rank == 0:
                accl.device.inject_fault(EmuDevice.FAULT_CORRUPT_SEQ)
                for i in range(5):  # seqn 0 (corrupted), then 1..4
                    b = accl.create_buffer_like(_data(COUNT, salt=20 + i))
                    accl.send(b, COUNT, 1, tag=5)
            else:
                accl.set_timeout(1_000_000)
                time.sleep(0.5)  # let every segment land / fill the pool
                d = accl.create_buffer(COUNT, np.float32)
                with pytest.raises(ACCLError) as e:
                    accl.recv(d, COUNT, 0, tag=5)  # expects seqn 0
                assert e.value.code & int(ErrorCode.PACK_SEQ_NUMBER_ERROR)
                dump = accl.dump_rx_buffers()
                assert "RESERVED" not in dump  # route evicted, pool free
                assert "0 staged" in dump      # staging drained too

        world.run(fn)
