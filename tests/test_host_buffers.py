"""Host-resident buffers + count-based schedule thresholds.

Reference analogs: host-only buffers reached over the external_dma path
(OP0/OP1/RES_HOST move flags, ccl_offload_control.h:128-138;
kernels/plugins/external_dma) and the *_MAX_COUNT exchange-memory tuning
registers consulted by the gather/reduce schedules
(ccl_offload_control.h:86-90, fw :1163 and :1533, driver defaults
accl.cpp:1214-1224).
"""
import numpy as np
import pytest

from accl_tpu import ReduceFunction
from accl_tpu.backends.emu import EmuWorld
from accl_tpu.constants import HostFlags

NRANKS = 4


@pytest.fixture(scope="module")
def world():
    with EmuWorld(NRANKS, max_eager_size=4096,
                  max_rendezvous_size=1 << 20) as w:
        yield w


def _data(count, rank, salt=0):
    rng = np.random.default_rng(900 + rank + salt * 131)
    return rng.standard_normal(count).astype(np.float32)


def test_host_flags_marshalled(world):
    # the descriptor must carry OP0/RES_HOST for host-only operands
    # (prepare_call, accl.cpp:1259-1283)
    accl = world.accls[0]
    hb = accl.create_buffer(16, np.float32, host_only=True)
    db = accl.create_buffer(16, np.float32)
    assert hb.is_host_only and not db.is_host_only
    call = accl._build(  # noqa: SLF001 — marshaling contract test
        __import__("accl_tpu").constants.Operation.allreduce, 16, 0,
        op0=hb, res=db)
    assert call.host_flags == HostFlags.OP0_HOST
    call = accl._build(
        __import__("accl_tpu").constants.Operation.allreduce, 16, 0,
        op0=db, res=hb)
    assert call.host_flags == HostFlags.RES_HOST
    # slices inherit residency
    assert hb.slice(2, 8).is_host_only


@pytest.mark.parametrize("count", [64, 2048],
                         ids=["eager", "rendezvous"])
def test_host_resident_allreduce(world, count):
    def fn(accl, rank):
        send = accl.create_buffer(count, np.float32, host_only=True)
        recv = accl.create_buffer(count, np.float32, host_only=True)
        send.host[:] = _data(count, rank, 1)
        accl.allreduce(send, recv, count, ReduceFunction.SUM)
        want = sum(_data(count, r, 1) for r in range(NRANKS))
        np.testing.assert_allclose(recv.host, want, rtol=1e-5, atol=1e-5)

    world.run(fn)


def test_mixed_residency_sendrecv(world):
    count = 1500  # multi-segment eager

    def fn(accl, rank):
        nxt, prv = (rank + 1) % NRANKS, (rank - 1) % NRANKS
        src = accl.create_buffer(count, np.float32)  # device
        dst = accl.create_buffer(count, np.float32, host_only=True)
        src.host[:] = _data(count, rank, 2)
        req = accl.send(src, count, nxt, tag=3, run_async=True)
        accl.recv(dst, count, prv, tag=3)
        assert req.wait(timeout=30.0)
        req.check()
        np.testing.assert_array_equal(dst.host, _data(count, prv, 2))

    world.run(fn)


def test_reduce_count_threshold_boundary(world):
    # REDUCE_FLAT_TREE_MAX_COUNT (fw :1533): flat at/below the byte
    # threshold even when the world exceeds MAX_RANKS; binomial tree
    # above.  Results must agree on both sides of the boundary.
    count = 2048  # 8 KB rendezvous payload

    def fn(accl, rank):
        accl.set_tuning(accl.REDUCE_FLAT_TREE_MAX_RANKS, 1)
        for max_count, salt in ((0, 4), (1 << 30, 5)):
            accl.set_tuning(accl.REDUCE_FLAT_TREE_MAX_COUNT, max_count)
            send = accl.create_buffer(count, np.float32)
            recv = accl.create_buffer(count, np.float32)
            send.host[:] = _data(count, rank, salt)
            accl.reduce(send, recv, count, 0, ReduceFunction.SUM)
            if rank == 0:
                want = sum(_data(count, r, salt) for r in range(NRANKS))
                np.testing.assert_allclose(recv.host, want, rtol=1e-4,
                                           atol=1e-4)
            accl.barrier()
        # restore driver defaults for the module world
        accl.set_tuning(accl.REDUCE_FLAT_TREE_MAX_RANKS, 4)
        accl.set_tuning(accl.REDUCE_FLAT_TREE_MAX_COUNT, 32 * 1024)

    world.run(fn)


def test_gather_count_threshold_fanin(world):
    # GATHER_FLAT_TREE_MAX_COUNT (fw :1163): above the byte threshold the
    # root publishes landing addresses in fan-in-bounded windows
    count = 2048

    def fn(accl, rank):
        accl.set_tuning(accl.GATHER_FLAT_TREE_MAX_COUNT, 0)  # always cap
        accl.set_tuning(accl.GATHER_FLAT_TREE_MAX_FANIN, 1)  # serial
        send = accl.create_buffer(count, np.float32)
        recv = accl.create_buffer(count * NRANKS, np.float32)
        send.host[:] = _data(count, rank, 6)
        accl.gather(send, recv, count, 0)
        if rank == 0:
            want = np.concatenate(
                [_data(count, r, 6) for r in range(NRANKS)])
            np.testing.assert_array_equal(recv.host, want)
        accl.barrier()
        accl.set_tuning(accl.GATHER_FLAT_TREE_MAX_COUNT, 32 * 1024)
        accl.set_tuning(accl.GATHER_FLAT_TREE_MAX_FANIN, 2)

    world.run(fn)
