"""Explicit session lifecycle over the TCP rung (reference:
open_port/open_con/close_con driver entry points backed by the
tcp_session_handler plugin, accl.hpp:1069-1083).

Covers: explicit bring-up before any traffic, teardown + lazy re-open,
re-open idempotence, the distinct connect-failure error for a dead
peer, and the connectionless rungs' no-op success (like the reference
UDP/RDMA designs that ship without the session handler kernel)."""
import os
import threading

import numpy as np
import pytest

from accl_tpu import ACCLError
from accl_tpu.backends.emu import EmuRankTcp, EmuWorld


def _port(salt):
    return 23000 + (os.getpid() % 900) + salt


def _run_pair(base_port, fn):
    """Two TCP ranks as threads in this process; fn(rank_obj, rank)."""
    ranks = [None, None]
    errs = [None, None]

    def boot(r):
        try:
            ranks[r] = EmuRankTcp(r, 2, base_port)
            fn(ranks[r], r)
        except BaseException as e:  # noqa: BLE001 — surface per-rank
            errs[r] = e

    ts = [threading.Thread(target=boot, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    if any(t.is_alive() for t in ts):
        # a rank thread is stuck inside the native engine: closing the
        # world under it would be a segfault, not a test failure —
        # leak the worlds and fail loudly instead
        raise TimeoutError(
            "session-lifecycle rank thread hung (worlds leaked to avoid "
            "tearing down a native handle mid-call)")
    for r in ranks:
        if r is not None:
            r.close()
    for e in errs:
        if e is not None:
            raise e


def test_tcp_session_open_close_reopen():
    barrier = threading.Barrier(2, timeout=60)

    def fn(rk, rank):
        accl = rk.accl
        accl.open_port()
        barrier.wait()       # both listeners live before connecting
        accl.open_con()      # explicit bring-up of every peer session
        accl.open_con()      # idempotent: re-open of open sessions is ok

        data = np.arange(64, dtype=np.float32) + rank
        src = accl.create_buffer_like(data)
        dst = accl.create_buffer(64, np.float32)
        other = 1 - rank
        sreq = accl.send(src, 64, other, tag=5, run_async=True)
        accl.recv(dst, 64, other, tag=5)
        assert sreq.wait(60)
        sreq.check()
        np.testing.assert_array_equal(
            dst.host, np.arange(64, dtype=np.float32) + other)

        barrier.wait()       # quiesce before teardown
        accl.close_con()     # explicit teardown of the comm's sessions
        barrier.wait()
        # a later call lazily reconnects (the transport's normal path),
        # so traffic after close_con still works
        sreq = accl.send(src, 64, other, tag=6, run_async=True)
        accl.recv(dst, 64, other, tag=6)
        assert sreq.wait(60)
        sreq.check()
        # and an explicit re-open after teardown also succeeds
        accl.close_con()
        barrier.wait()
        accl.open_con()

    _run_pair(_port(0), fn)


def test_tcp_open_con_failure_is_distinct_error():
    # rank 1 never exists: explicit bring-up must surface a decodable
    # setup error naming the dead peer (NOT a mid-collective hang)
    rk = EmuRankTcp(0, 2, _port(10))
    try:
        rk.accl.open_port()  # own listener is fine
        with pytest.raises(ACCLError, match="open_con failed.*peer 1"):
            rk.accl.open_con()
    finally:
        rk.close()


def test_connectionless_rungs_are_noop_success():
    # inproc world: nothing to open — success no-ops, like the
    # reference designs without the session handler kernel
    with EmuWorld(2) as w:
        def fn(accl, rank):
            accl.open_port()
            accl.open_con()
            accl.close_con()

        w.run(fn)


def test_unknown_communicator_errors():
    with EmuWorld(2) as w:
        def fn(accl, rank):
            with pytest.raises(ACCLError, match="unknown communicator"):
                accl.open_con(comm_id=99)

        w.run(fn)
