"""Fault-tolerant collectives: retransmission, abort + epoch fencing,
ULFM-style shrink, and the seeded chaos harness (accl_tpu/resilience).

Complements tests/test_fault_injection.py: that file pins which error
class each fault is DETECTED as (retransmission off); this one pins
that the same faults are RECOVERED from (retransmission on — the
default), that an abort wakes every blocked waiter fast, and that a
dead rank is survivable via shrink + re-run.
"""
import threading
import time

import numpy as np
import pytest

from accl_tpu import ACCLError, ChaosPlan, ReduceFunction, RetryPolicy
from accl_tpu.backends.emu import EmuDevice, EmuWorld
from accl_tpu.constants import ErrorCode
from accl_tpu.observability import flight as obs_flight
from accl_tpu.observability import health as obs_health

COUNT = 32


def _data(count, salt=0):
    rng = np.random.default_rng(910 + salt)
    return rng.standard_normal(count).astype(np.float32)


# ---------------------------------------------------------------------------
# layer 1: NACK retransmission (one-shot faults heal transparently)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("fault", [
    EmuDevice.FAULT_DROP, EmuDevice.FAULT_DUPLICATE,
    EmuDevice.FAULT_CORRUPT_SEQ, EmuDevice.FAULT_DELAY,
], ids=["drop", "dup", "corrupt", "delay"])
def test_p2p_recovers_from_one_shot_fault(fault):
    with EmuWorld(2) as world:
        def fn(accl, rank):
            accl.set_timeout(10_000_000)
            if rank == 0:
                a = accl.create_buffer_like(_data(COUNT, salt=1))
                b = accl.create_buffer_like(_data(COUNT, salt=2))
                accl.device.inject_fault(fault)
                accl.send(a, COUNT, 1, tag=7)
                accl.send(b, COUNT, 1, tag=8)  # post-fault stream stays clean
            else:
                da = accl.create_buffer(COUNT, np.float32)
                db = accl.create_buffer(COUNT, np.float32)
                accl.recv(da, COUNT, 0, tag=7)
                accl.recv(db, COUNT, 0, tag=8)
                np.testing.assert_array_equal(da.host, _data(COUNT, salt=1))
                np.testing.assert_array_equal(db.host, _data(COUNT, salt=2))

        world.run(fn)
        # the recovery really went through the NACK lane (except dup,
        # which seqn-dedup absorbs without soliciting a resend)
        if fault in (EmuDevice.FAULT_DROP, EmuDevice.FAULT_CORRUPT_SEQ):
            stats = world.resilience_stats()
            assert sum(s["nacks_tx"] for s in stats) >= 1
            assert sum(s["retrans_sent"] for s in stats) >= 1


@pytest.mark.parametrize("fault", [
    EmuDevice.FAULT_DROP, EmuDevice.FAULT_DUPLICATE,
], ids=["drop", "dup"])
def test_allreduce_recovers_from_one_shot_fault(fault):
    with EmuWorld(2) as world:
        def fn(accl, rank):
            accl.set_timeout(10_000_000)
            s = accl.create_buffer_like(_data(COUNT, salt=rank))
            r = accl.create_buffer(COUNT, np.float32)
            if rank == 0:
                accl.device.inject_fault(fault)
            accl.allreduce(s, r, COUNT, ReduceFunction.SUM)
            return r.host.copy()

        outs = world.run(fn)
        expected = _data(COUNT, salt=0) + _data(COUNT, salt=1)
        for out in outs:
            np.testing.assert_allclose(out, expected, rtol=1e-6, atol=1e-5)


def test_wildcard_recv_recovers_dropped_tagged_send():
    # regression: a TAG_ANY recv's NACK is a wildcard solicitation —
    # it must resend the concretely-tagged segment it is waiting for
    # (tag-exact NACK matching stranded this exact pairing)
    with EmuWorld(2) as world:
        def fn(accl, rank):
            accl.set_timeout(10_000_000)
            if rank == 0:
                a = accl.create_buffer_like(_data(COUNT, salt=9))
                accl.device.inject_fault(EmuDevice.FAULT_DROP)
                accl.send(a, COUNT, 1, tag=5)  # concrete tag, dropped
            else:
                da = accl.create_buffer(COUNT, np.float32)
                accl.recv(da, COUNT, 0)  # wildcard TAG_ANY recv
                np.testing.assert_array_equal(da.host, _data(COUNT, salt=9))

        world.run(fn)
        assert sum(s["retrans_sent"]
                   for s in world.resilience_stats()) >= 1


def test_retry_disabled_restores_detection():
    # retry_max=0 is the pure detect-and-classify contract
    with EmuWorld(2, retry_max=0) as world:
        def fn(accl, rank):
            accl.set_timeout(1_000_000)
            if rank == 0:
                src = accl.create_buffer_like(_data(COUNT))
                accl.device.inject_fault(EmuDevice.FAULT_DROP)
                accl.send(src, COUNT, 1, tag=1)
            else:
                dst = accl.create_buffer(COUNT, np.float32)
                with pytest.raises(ACCLError) as e:
                    accl.recv(dst, COUNT, 0, tag=1)
                assert e.value.code & int(ErrorCode.RECEIVE_TIMEOUT_ERROR)

        world.run(fn)


def test_retry_policy_env(monkeypatch):
    monkeypatch.setenv("ACCL_RETRY_MAX", "7")
    monkeypatch.setenv("ACCL_RETRY_BASE_US", "333")
    pol = RetryPolicy.from_env()
    assert pol.max_retries == 7 and pol.base_us == 333 and pol.enabled
    # backoff: exponential envelope, deterministic jitter
    assert pol.backoff_us(3) >= 333 << 3
    assert pol.backoff_us(2, rank=1, seqn=5) == pol.backoff_us(2, rank=1,
                                                               seqn=5)
    monkeypatch.setenv("ACCL_RETRY_MAX", "0")
    assert not RetryPolicy.from_env().enabled


# ---------------------------------------------------------------------------
# seeded chaos matrix: all collectives under probabilistic drop/dup/delay
# ---------------------------------------------------------------------------
def _run_collective_matrix(world, nranks):
    """Every collective once, results asserted bitwise/allclose."""
    def fn(accl, rank):
        got = {}
        s = accl.create_buffer_like(_data(COUNT, salt=rank))
        r = accl.create_buffer(COUNT, np.float32)
        big_s = accl.create_buffer_like(
            np.concatenate([_data(COUNT, salt=100 * rank + i)
                            for i in range(nranks)]))
        big_r = accl.create_buffer(COUNT * nranks, np.float32)

        # p2p ring: rank -> rank+1
        nxt, prv = (rank + 1) % nranks, (rank - 1) % nranks
        if rank % 2 == 0:
            accl.send(s, COUNT, nxt, tag=50)
            accl.recv(r, COUNT, prv, tag=50)
        else:
            accl.recv(r, COUNT, prv, tag=50)
            accl.send(s, COUNT, nxt, tag=50)
        got["sendrecv"] = r.host.copy()

        accl.bcast(s if rank == 0 else r, COUNT, root=0)
        got["bcast"] = (s if rank == 0 else r).host.copy()

        accl.scatter(big_s, r, COUNT, root=0)
        got["scatter"] = r.host.copy()
        accl.gather(s, big_r, COUNT, root=0)
        got["gather"] = big_r.host.copy() if rank == 0 else None
        accl.allgather(s, big_r, COUNT)
        got["allgather"] = big_r.host.copy()
        accl.reduce(s, r, COUNT, root=0)
        got["reduce"] = r.host.copy() if rank == 0 else None
        accl.allreduce(s, r, COUNT, ReduceFunction.SUM)
        got["allreduce"] = r.host.copy()
        accl.reduce_scatter(big_s, r, COUNT, ReduceFunction.SUM)
        got["reduce_scatter"] = r.host.copy()
        accl.alltoall(big_s, big_r, COUNT)
        got["alltoall"] = big_r.host.copy()
        accl.barrier()
        return got

    outs = world.run(fn)
    ranks = range(nranks)
    srcs = [_data(COUNT, salt=r) for r in ranks]
    bigs = [np.concatenate([_data(COUNT, salt=100 * r + i)
                            for i in range(nranks)]) for r in ranks]
    total = np.sum(srcs, axis=0)
    for r in ranks:
        np.testing.assert_array_equal(outs[r]["sendrecv"],
                                      srcs[(r - 1) % nranks])
        np.testing.assert_array_equal(outs[r]["bcast"], srcs[0])
        np.testing.assert_array_equal(
            outs[r]["scatter"], bigs[0][r * COUNT:(r + 1) * COUNT])
        np.testing.assert_array_equal(outs[r]["allgather"],
                                      np.concatenate(srcs))
        np.testing.assert_allclose(outs[r]["allreduce"], total, rtol=1e-6, atol=1e-5)
        np.testing.assert_allclose(
            outs[r]["reduce_scatter"],
            np.sum([bigs[i][r * COUNT:(r + 1) * COUNT] for i in ranks],
                   axis=0), rtol=1e-6, atol=1e-5)
        np.testing.assert_array_equal(
            outs[r]["alltoall"],
            np.concatenate([bigs[i][r * COUNT:(r + 1) * COUNT]
                            for i in ranks]))
    np.testing.assert_array_equal(outs[0]["gather"], np.concatenate(srcs))
    np.testing.assert_allclose(outs[0]["reduce"], total, rtol=1e-6, atol=1e-5)


@pytest.mark.parametrize("plan", [
    "seed=11,drop=0.05", "seed=12,dup=0.05",
    "seed=13,delay=0.08,delay_us=3000",
    "seed=14,drop=0.03,dup=0.03,delay=0.03,delay_us=2000",
], ids=["drop", "dup", "delay", "mixed"])
def test_chaos_matrix_bitwise_correct(plan):
    # deterministic seeded chaos: every collective completes with
    # correct results via the retransmission lane (fixed seeds => the
    # fault schedule replays identically run after run)
    nranks = 3
    with EmuWorld(nranks, chaos=plan) as world:
        for a in world.accls:
            a.set_timeout(15_000_000)
        _run_collective_matrix(world, nranks)
        if "drop" in plan:
            stats = world.resilience_stats()
            assert sum(s["retrans_sent"] for s in stats) >= 1


def test_chaos_plan_grammar():
    plan = ChaosPlan.parse("seed=42,drop=0.01,dup=0.02,delay=0.03,"
                           "delay_us=500,corrupt=0.004,slow_rank=2:750,"
                           "kill_rank=3")
    assert plan.seed == 42 and plan.drop == 0.01 and plan.dup == 0.02
    assert plan.delay == 0.03 and plan.delay_us == 500
    assert plan.corrupt == 0.004
    assert plan.slow == {2: 750} and plan.kills == [3]
    assert plan.probabilistic
    # spec() round-trips through parse()
    again = ChaosPlan.parse(plan.spec())
    assert again == plan
    for bad in ("drop", "drop=2.0", "wat=1", "slow_rank=x"):
        with pytest.raises(ACCLError):
            ChaosPlan.parse(bad)
    assert ChaosPlan.from_env() is None  # unset => no plan


# ---------------------------------------------------------------------------
# layer 2: abort + epoch fencing
# ---------------------------------------------------------------------------
def test_abort_wakes_blocked_waiter_immediately():
    # the bare-wait satellite: a receiver blocked on a dead peer used to
    # exit only via the ACCL_DEFAULT_TIMEOUT budget; abort must wake it
    # now (engine finalization -> Request event), not at budget expiry
    with EmuWorld(2) as world:
        def fn(accl, rank):
            accl.set_timeout(60_000_000)  # 60 s receive budget
            if rank == 1:
                dst = accl.create_buffer(COUNT, np.float32)
                t0 = time.time()
                with pytest.raises(ACCLError) as e:
                    accl.recv(dst, COUNT, 0, tag=3)  # peer never sends
                assert time.time() - t0 < 10.0  # woke early, not at 60 s
                assert e.value.code & int(ErrorCode.COMM_ABORTED)
            else:
                time.sleep(0.5)
                accl.abort(0)

        world.run(fn)


def test_abort_wakes_bare_request_wait():
    # async flavor: a bare Request.wait() parked on the completion event
    # wakes the moment the engine finalizes the aborted call
    with EmuWorld(2) as world:
        reqs = {}

        def issue(accl, rank):
            if rank == 1:
                dst = accl.create_buffer(COUNT, np.float32)
                reqs[rank] = accl.recv(dst, COUNT, 0, tag=4,
                                       run_async=True)
            return None

        world.run(issue)
        waker = threading.Timer(
            0.5, lambda: world.accls[0].abort(
                0, error=int(ErrorCode.RANK_FAILED)))
        waker.start()
        t0 = time.time()
        assert reqs[1].wait(timeout=30.0)
        assert time.time() - t0 < 10.0
        assert reqs[1].aborted
        assert reqs[1].retcode & int(ErrorCode.RANK_FAILED)
        with pytest.raises(ACCLError):
            reqs[1].check()
        waker.join()


def test_aborted_comm_fails_fast_and_fenced_epoch_drops():
    with EmuWorld(2) as world:
        # a chaos delay holds rank 0's segment in flight across the
        # abort: when it finally releases it carries the DEAD epoch and
        # must be fenced at rank 1's ingress, not delivered
        world.devices[0].set_chaos(seed=1, drop_ppm=0, dup_ppm=0,
                                   delay_ppm=0, delay_us=700_000,
                                   corrupt_ppm=0, slow_us=0)

        def fn(accl, rank):
            accl.set_timeout(2_000_000)
            if rank == 0:
                src = accl.create_buffer_like(_data(COUNT))
                accl.device.inject_fault(EmuDevice.FAULT_DELAY)
                accl.send(src, COUNT, 1, tag=6)  # held for 0.7 s
                time.sleep(0.2)
                accl.abort(0)
                # driver-side fast fail: new calls on the aborted comm
                # never reach the engine
                with pytest.raises(ACCLError) as e:
                    accl.send(src, COUNT, 1, tag=7)
                assert e.value.code & int(ErrorCode.COMM_ABORTED)
            else:
                time.sleep(1.5)  # outlive the delayed release
            return None

        world.run(fn)
        stats = world.resilience_stats()
        assert stats[1]["fenced_drops"] >= 1  # the stale-epoch segment


def test_abort_flight_record_terminal_state_and_health():
    # flight records finalized by an abort retire as "aborted" — the
    # watchdog must see a recovery action, not a phantom hang — and the
    # accl_health gauge gains the aborted value
    with EmuWorld(2) as world:
        reqs = {}

        def issue(accl, rank):
            if rank == 1:
                dst = accl.create_buffer(COUNT, np.float32)
                reqs[rank] = accl.recv(dst, COUNT, 0, tag=5,
                                       run_async=True)
            return None

        world.run(issue)
        time.sleep(0.2)
        world.accls[0].abort(0)
        assert reqs[1].wait(30.0)
        rec = reqs[1].flight
        assert rec is not None
        assert obs_flight.STATE_NAMES[rec.state] == "aborted"
        assert not rec.in_flight
        # merged analysis: an aborted record is terminal, never a hang
        merged = obs_flight.merge_flight_dumps(
            [a.flight_recorder.dump() for a in world.accls])
        assert not any(
            h for h in merged["analysis"]["hangs"]
            if h["tag"] == 5), merged["analysis"]["hangs"]
        # health: the watchdog's next sweep reads aborted (3)
        wd = world.watchdog
        wd.check()
        assert wd._health == obs_health.HEALTH_ABORTED
        assert obs_health.HEALTH_NAMES[obs_health.HEALTH_ABORTED] == \
            "aborted"


def test_watchdog_action_abort_recovers_hang():
    # ACCL_WATCHDOG_ACTION=abort: the PR3 watchdog now triggers recovery
    # instead of only dumping — a withheld gang member turns into fast
    # COMM_ABORTED|RANK_FAILED failures on every arrived rank
    with EmuWorld(3) as world:
        world.start_watchdog(timeout_s=1.0, action="abort",
                             dump_path="")
        reqs = {}

        def issue(accl, rank):
            if rank == 0:
                return None  # withheld: never joins the gang
            s = accl.create_buffer_like(_data(COUNT, salt=rank))
            r = accl.create_buffer(COUNT, np.float32)
            reqs[rank] = accl.allreduce(s, r, COUNT, ReduceFunction.SUM,
                                        run_async=True)
            return None

        world.run(issue)
        deadline = time.time() + 30
        for rank in (1, 2):
            assert reqs[rank].wait(timeout=max(0.1, deadline - time.time()))
            assert reqs[rank].aborted
            assert reqs[rank].retcode & int(ErrorCode.RANK_FAILED)
        assert world.watchdog.last_report is not None


# ---------------------------------------------------------------------------
# layer 3: liveness + ULFM shrink
# ---------------------------------------------------------------------------
def test_probe_liveness_names_dead_rank():
    with EmuWorld(3) as world:
        world.kill_rank(2)

        def fn(accl, rank):
            if rank == 2:
                return None
            return accl.device.probe_liveness(0, 3, window_s=2.0)

        outs = world.run(fn)
        assert outs[0] == [True, True, False]
        assert outs[1] == [True, True, False]


def test_kill_abort_shrink_rerun():
    # the full recovery drill (the chaos_smoke acceptance path): a rank
    # dies mid-run; the failure is CLASSIFIED, the comm revoked, the
    # survivors agree on the surviving set and finish on the shrunk
    # world.
    #
    # Deflaked (r14): only rank 0 — whose ring predecessor IS the dead
    # rank — deterministically fails the first allreduce.  Ranks 1/2
    # sit downstream of live senders, and the eager ring keeps
    # forwarding after an upstream receive failure, so on some
    # interleavings they complete the schedule with relayed garbage and
    # retcode 0; asserting `raises` on EVERY survivor was the flake
    # (r12/r13 "passed this run" notes), and the rank that hit DID NOT
    # RAISE then skipped the shrink, starving the others into a
    # 6-second timeout.  This is exactly the ULFM contract: ONE rank
    # classifies and revokes; the propagated abort (or clean-looking
    # garbage) is what everyone else may legally observe.  The native
    # model checker documents the engine-level half of this contract
    # (scripts/model_check.py, drill abort_vs_traffic: a raced retcode
    # is either 0 or carries the fence bits).
    nranks = 4
    with EmuWorld(nranks) as world:
        world.kill_rank(3)

        def fn(accl, rank):
            if rank == 3:
                return "dead"
            accl.set_timeout(1_500_000)
            s = accl.create_buffer_like(_data(COUNT, salt=rank))
            r = accl.create_buffer(COUNT, np.float32)
            if rank == 0:
                # prev rank in the ring is dead: guaranteed classification
                with pytest.raises(ACCLError):
                    accl.allreduce(s, r, COUNT, ReduceFunction.SUM)
            else:
                # downstream of live senders: may fail fast via the
                # propagated abort OR complete with relayed garbage —
                # both are legal pre-revoke observations
                try:
                    accl.allreduce(s, r, COUNT, ReduceFunction.SUM)
                except ACCLError:
                    pass
            # ULFM pattern: whoever classifies a failure revokes; the
            # propagated abort wakes slower ranks' calls immediately
            accl.abort(0, error=int(ErrorCode.RANK_FAILED))
            new_comm = accl.shrink_communicator(0, window_s=2.0)
            assert accl.communicator(new_comm).size == nranks - 1
            # fresh clock for the rerun: the shrink agreement already
            # resynchronized the survivors, the budget only has to
            # cover the collective itself (not inherited skew)
            accl.set_timeout(5_000_000)
            accl.allreduce(s, r, COUNT, ReduceFunction.SUM,
                           comm_id=new_comm)
            return r.host.copy()

        outs = world.run(fn)
        expected = np.sum([_data(COUNT, salt=r) for r in range(3)], axis=0)
        for r in range(3):
            np.testing.assert_allclose(outs[r], expected, rtol=1e-6, atol=1e-5)


def test_shrink_without_deaths_is_a_fresh_comm():
    with EmuWorld(2) as world:
        def fn(accl, rank):
            nc = accl.shrink_communicator(0, window_s=1.0)
            assert accl.communicator(nc).size == 2
            s = accl.create_buffer_like(_data(8, salt=rank))
            r = accl.create_buffer(8, np.float32)
            accl.allreduce(s, r, 8, ReduceFunction.SUM, comm_id=nc)
            return r.host.copy()

        outs = world.run(fn)
        expected = _data(8, salt=0) + _data(8, salt=1)
        np.testing.assert_allclose(outs[0], expected, rtol=1e-6, atol=1e-5)
        np.testing.assert_allclose(outs[1], expected, rtol=1e-6, atol=1e-5)


# ---------------------------------------------------------------------------
# recovery edge races (r11 satellite): abort/shrink interleavings
# ---------------------------------------------------------------------------
def test_double_abort_is_idempotent():
    # two aborts of one comm (e.g. two survivors both classifying the
    # same failure, or a watchdog racing an application abort) must be
    # indistinguishable from one: epochs stay monotonic, waiters wake
    # once, the second abort neither raises nor resurrects the comm
    with EmuWorld(2) as world:
        def fn(accl, rank):
            if rank == 0:
                time.sleep(0.3)
                accl.abort(0)
                accl.abort(0)  # idempotent re-revoke
                with pytest.raises(ACCLError):
                    accl.barrier()  # still fenced after the second
            else:
                dst = accl.create_buffer(COUNT, np.float32)
                with pytest.raises(ACCLError) as e:
                    accl.recv(dst, COUNT, 0, tag=9)
                assert e.value.code & int(ErrorCode.COMM_ABORTED)
                accl.abort(0)  # cross-rank double abort, same contract

        world.run(fn)
        # both ranks re-aborting bumped epochs monotonically — no
        # wraparound/rollback (the handle_abort CAS adopts max only)
        assert world.devices[0].comm_epoch(0) >= 1
        assert world.devices[1].comm_epoch(0) >= 1


def test_shrink_concurrent_with_watchdog_abort():
    # a watchdog-triggered abort (action=abort) landing WHILE the
    # survivors are already inside shrink_communicator must not corrupt
    # the shrink: the probe runs on the control plane (epoch-agnostic)
    # and the fresh comm id is minted identically everywhere
    with EmuWorld(3) as world:
        world.start_watchdog(timeout_s=1.0, action="abort", dump_path="")

        def fn(accl, rank):
            accl.set_timeout(1_500_000)
            if rank == 0:
                # withheld from the gang: the watchdog will abort comm 0
                # while ranks 1-2 are mid-recovery
                time.sleep(2.0)
                accl.abort(0, error=int(ErrorCode.RANK_FAILED))
                nc = accl.shrink_communicator(0, window_s=2.0)
                return nc
            s = accl.create_buffer_like(_data(COUNT, salt=rank))
            r = accl.create_buffer(COUNT, np.float32)
            with pytest.raises(ACCLError):
                accl.allreduce(s, r, COUNT, ReduceFunction.SUM)
            accl.abort(0, error=int(ErrorCode.RANK_FAILED))
            nc = accl.shrink_communicator(0, window_s=2.0)
            return nc

        outs = world.run(fn)
        assert len(set(outs)) == 1, f"shrink ids diverged: {outs}"

        def verify(accl, rank, comm_id):
            s = accl.create_buffer_like(_data(8, salt=rank))
            r = accl.create_buffer(8, np.float32)
            accl.allreduce(s, r, 8, ReduceFunction.SUM, comm_id=comm_id)
            return r.host.copy()

        post = world.run(verify, outs[0])
        expected = np.sum([_data(8, salt=q) for q in range(3)], axis=0)
        for out in post:
            np.testing.assert_allclose(out, expected, rtol=1e-6,
                                       atol=1e-5)


def test_all_alive_shrink_mints_identical_ids_every_rank():
    # repeated all-alive shrinks are pure comm mints: every rank must
    # observe the SAME fresh id at every step (the create-order
    # discipline), and the last comm must still collectively work
    with EmuWorld(3) as world:
        def fn(accl, rank):
            ids = [accl.shrink_communicator(0, window_s=1.0)
                   for _ in range(3)]
            s = accl.create_buffer_like(_data(8, salt=rank))
            r = accl.create_buffer(8, np.float32)
            accl.allreduce(s, r, 8, ReduceFunction.SUM, comm_id=ids[-1])
            return ids, r.host.copy()

        outs = world.run(fn)
        ids = {tuple(o[0]) for o in outs}
        assert len(ids) == 1, f"per-rank shrink id sequences: {ids}"
        assert list(ids.pop()) == [1, 2, 3]
        expected = np.sum([_data(8, salt=q) for q in range(3)], axis=0)
        for _ids, out in outs:
            np.testing.assert_allclose(out, expected, rtol=1e-6,
                                       atol=1e-5)


# ---------------------------------------------------------------------------
# probe validation + env knob clear errors (r11 satellites)
# ---------------------------------------------------------------------------
def test_probe_alive_rejects_bad_window_and_overlong_result():
    from accl_tpu.resilience.membership import probe_alive

    with EmuWorld(2) as world:
        accl = world.accls[0]
        with pytest.raises(ACCLError, match=r"window_s.*> 0"):
            probe_alive(accl, 0, window_s=0.0)
        with pytest.raises(ACCLError, match="comm 0"):
            probe_alive(accl, 0, window_s=-1.0)

        # a backend handing back liveness for a DIFFERENT world must be
        # refused, not truncated (a shrink built from it could exclude
        # the wrong ranks); a short answer still pads with dead
        real = accl.device.probe_liveness
        try:
            accl.device.probe_liveness = \
                lambda c, n, w: [True, True, True, False]
            with pytest.raises(ACCLError, match="refusing to truncate"):
                probe_alive(accl, 0, window_s=0.5)
            accl.device.probe_liveness = lambda c, n, w: [True]
            assert probe_alive(accl, 0, window_s=0.5) == [True, False]
        finally:
            accl.device.probe_liveness = real


def test_env_knobs_raise_naming_errors(monkeypatch):
    from accl_tpu.observability.flight import FlightRecorder
    from accl_tpu.observability.health import watchdog_timeout_s

    monkeypatch.setenv("ACCL_RETRY_MAX", "lots")
    with pytest.raises(ACCLError, match="ACCL_RETRY_MAX"):
        RetryPolicy.from_env()
    monkeypatch.setenv("ACCL_RETRY_MAX", "-3")
    with pytest.raises(ACCLError, match="ACCL_RETRY_MAX"):
        RetryPolicy.from_env()
    monkeypatch.delenv("ACCL_RETRY_MAX")
    monkeypatch.setenv("ACCL_RETRY_BASE_US", "fast")
    with pytest.raises(ACCLError, match="ACCL_RETRY_BASE_US"):
        RetryPolicy.from_env()
    monkeypatch.delenv("ACCL_RETRY_BASE_US")
    monkeypatch.setenv("ACCL_WATCHDOG_TIMEOUT", "five minutes")
    with pytest.raises(ACCLError, match="ACCL_WATCHDOG_TIMEOUT"):
        watchdog_timeout_s()
    monkeypatch.delenv("ACCL_WATCHDOG_TIMEOUT")
    monkeypatch.setenv("ACCL_FLIGHT_CAP", "big")
    with pytest.raises(ACCLError, match="ACCL_FLIGHT_CAP"):
        FlightRecorder(0)
    monkeypatch.delenv("ACCL_FLIGHT_CAP")
    from accl_tpu.resilience.supervisor import RecoveryPolicy

    monkeypatch.setenv("ACCL_RECOVERY", "pray")
    with pytest.raises(ACCLError, match="ACCL_RECOVERY"):
        RecoveryPolicy()
    monkeypatch.setenv("ACCL_RECOVERY", "grow")
    monkeypatch.setenv("ACCL_JOIN_WAIT_S", "soon")
    with pytest.raises(ACCLError, match="ACCL_JOIN_WAIT_S"):
        RecoveryPolicy()


def test_chaos_plan_join_rank_grammar():
    plan = ChaosPlan.parse("seed=5,kill_rank=2,join_rank=2")
    assert plan.kills == [2] and plan.joins == [2]
    assert ChaosPlan.parse(plan.spec()) == plan  # round-trips
    with pytest.raises(ACCLError):
        ChaosPlan.parse("join_rank=x")


# ---------------------------------------------------------------------------
# elastic membership (r11 tentpole): join + grow + supervisor
# ---------------------------------------------------------------------------
def test_spawn_replacement_grow_healthy_world():
    # grow without any death: a 2-rank world admits a third live rank;
    # the grown comm works collectively and the old comm is untouched
    from accl_tpu.resilience.elastic import admit_pending

    with EmuWorld(2) as world:
        joiner = world.spawn_replacement()
        out = {}

        def joiner_thread():
            cid = out["comm"] = joiner.join(timeout_s=20.0)
            s = joiner.accl.create_buffer_like(np.full(8, 4.0,
                                                       np.float32))
            r = joiner.accl.create_buffer(8, np.float32)
            joiner.accl.allreduce(s, r, 8, ReduceFunction.SUM,
                                  comm_id=cid)
            out["result"] = r.host.copy()

        jt = threading.Thread(target=joiner_thread, daemon=True)
        jt.start()

        def fn(accl, rank):
            new_comm, n = admit_pending(accl, 0, world.board,
                                        wait_s=5.0, window_s=1.0)
            assert n == 1
            s = accl.create_buffer_like(
                np.full(8, float(rank + 1), np.float32))
            r = accl.create_buffer(8, np.float32)
            accl.allreduce(s, r, 8, ReduceFunction.SUM,
                           comm_id=new_comm)
            # the ORIGINAL comm still works: growing drained nothing
            accl.barrier(comm_id=0)
            return new_comm, r.host.copy()

        res = world.run(fn)
        jt.join(timeout=30)
        assert not jt.is_alive()
        assert res[0][0] == res[1][0] == out["comm"] == 1
        np.testing.assert_array_equal(res[0][1], np.full(8, 7.0,
                                                         np.float32))
        np.testing.assert_array_equal(out["result"],
                                      np.full(8, 7.0, np.float32))
        # engine-level join handshake really ran (Join/Welcome/
        # StateSync): the sponsor answered, the joiner completed
        stats = joiner.device.join_stats()
        assert stats["joined"] == 1
        assert sum(world.devices[r].join_stats()["sponsored"]
                   for r in range(2)) == 1


def test_placeholder_comms_fail_fast_on_joiner():
    # a joiner's padded id space: calls on a placeholder slot raise a
    # decodable error in the driver, and the engine fences strays
    from accl_tpu.resilience.elastic import admit_pending

    with EmuWorld(2) as world:
        joiner = world.spawn_replacement()
        out = {}

        def joiner_thread():
            # make the id space interesting: survivors mint one extra
            # comm before the admission, so the joiner pads TWO slots
            out["comm"] = joiner.join(timeout_s=20.0)

        jt = threading.Thread(target=joiner_thread, daemon=True)
        jt.start()

        def fn(accl, rank):
            accl.create_communicator([0, 1])  # id 1 (joiner never saw)
            new_comm, n = admit_pending(accl, 0, world.board,
                                        wait_s=5.0, window_s=1.0)
            return new_comm

        res = world.run(fn)
        jt.join(timeout=30)
        assert res[0] == out["comm"] == 2
        # comm 1 is a placeholder on the joiner: decodable fast-fail
        with pytest.raises(ACCLError, match="placeholder"):
            joiner.accl.communicator(1)
        s = joiner.accl.create_buffer_like(np.ones(4, np.float32))
        r = joiner.accl.create_buffer(4, np.float32)
        with pytest.raises(ACCLError, match="placeholder"):
            joiner.accl.allreduce(s, r, 4, ReduceFunction.SUM,
                                  comm_id=1)


def test_supervised_kill_shrink_join_grow_resume():
    # the tier-1 twin of the CI join drill (scripts/chaos_smoke.py
    # drill 3), smaller: the per-rank supervisors drive kill -> abort
    # -> probe -> shrink -> admit -> grow -> agree -> resume; the world
    # returns to full size and the replacement participates
    from accl_tpu.resilience.supervisor import RecoveryPolicy

    nranks, iters, count = 3, 4, 16
    victim = 1

    def local_data(accl, comm_id, it):
        comm = accl.communicator(comm_id)
        rng = np.random.default_rng(70 * comm.local_rank + it)
        return rng.standard_normal(count).astype(np.float32), comm.size

    with EmuWorld(nranks) as world:
        for a in world.accls:
            a.set_timeout(1_500_000)
        policy = dict(mode="grow", join_wait_s=8.0, probe_window_s=1.0,
                      max_rounds=2)
        join_out = {}

        def replacement():
            time.sleep(0.8)
            j = world.spawn_replacement()
            cid = j.join(timeout_s=30.0)
            j.accl.set_timeout(30_000_000)
            sup = j.accl.supervise(policy=RecoveryPolicy(**policy),
                                   board=world.board)
            sup.comm_id = cid
            restart = sup.agree_restart(0, fresh=True)
            outs = {}

            def step(a, c, it):
                data, size = local_data(a, c, it)
                s = a.create_buffer_like(data)
                r = a.create_buffer(count, np.float32)
                a.allreduce(s, r, count, ReduceFunction.SUM, comm_id=c)
                outs[it] = (size, r.host.copy())

            sup.run_loop(step, iters, comm_id=cid,
                         start_iteration=restart)
            join_out.update(outs=outs, restart=restart)

        jt = threading.Thread(target=replacement, daemon=True)
        jt.start()

        def supervised(accl, rank):
            from accl_tpu.resilience.supervisor import RecoveryPolicy

            sup = accl.supervise(policy=RecoveryPolicy(**policy),
                                 board=world.board)
            outs = {}

            def step(a, comm_id, it):
                if rank == victim and it == 1:
                    world.kill_rank(victim)
                data, size = local_data(a, comm_id, it)
                s = a.create_buffer_like(data)
                r = a.create_buffer(count, np.float32)
                a.allreduce(s, r, count, ReduceFunction.SUM,
                            comm_id=comm_id)
                outs[it] = (size, r.host.copy())

            try:
                summary = sup.run_loop(
                    step, iters, comm_id=0,
                    on_restart=lambda i: [outs.pop(k) for k in
                                          list(outs) if k >= i])
            except ACCLError as e:
                assert rank == victim, f"survivor {rank} died: {e}"
                # the victim halts ISOLATED, never shrinks to itself
                assert "isolated" in str(e)
                return ("dead", sup.state_log)
            return ("alive", outs, summary)

        res = world.run(supervised)
        jt.join(timeout=60)
        assert not jt.is_alive() and "outs" in join_out
        assert res[victim][0] == "dead"
        survivors = [r for r in range(nranks) if r != victim]
        for r in survivors:
            state, outs, summary = res[r]
            assert state == "alive"
            assert sorted(outs) == list(range(iters))
            # the supervisor drove the whole episode
            states = [s for _t, s, _d in summary["state_log"]]
            for needed in ("abort", "probe", "shrink", "grow",
                           "agree", "resume"):
                assert needed in states, (needed, states)
            # world back at original size for every post-recovery iter
            assert {outs[k][0] for k in outs} == {nranks}
        # replacement fully participated at full size
        assert {v[0] for v in join_out["outs"].values()} == {nranks}
        # every member agrees on the result values per iteration
        for it in range(iters):
            vals = [res[r][1][it][1] for r in survivors]
            if it in join_out["outs"]:
                vals.append(join_out["outs"][it][1])
            for v in vals[1:]:
                np.testing.assert_array_equal(v, vals[0])
        # observability: membership counters moved and the flight rings
        # carry retired recovery/<phase> records
        from accl_tpu.observability import metrics as obs_metrics

        snap = obs_metrics.default_registry().snapshot()
        assert snap["counters"].get("membership/joins", 0) >= 1
        assert snap["counters"].get("membership/shrinks", 0) >= 1
        assert snap["counters"].get("membership/grows", 0) >= 1
        assert snap["counters"].get("recovery/rounds", 0) >= 1
        assert snap["values"].get("recovery/latency_us",
                                  {}).get("count", 0) >= 1
        recs = [rec for a in world.accls
                for rec in a.flight_recorder.records()
                if rec.collective.startswith("recovery/")]
        assert recs, "no recovery phase records in the flight rings"
        assert all(not rec.in_flight for rec in recs)


def test_supervisor_shrink_policy_finishes_smaller():
    # default policy (shrink): a killed rank's world finishes at the
    # smaller size with no join machinery involved
    from accl_tpu.resilience.supervisor import RecoveryPolicy

    nranks, iters, count = 3, 3, 16
    with EmuWorld(nranks) as world:
        for a in world.accls:
            a.set_timeout(1_500_000)

        def supervised(accl, rank):
            sup = accl.supervise(
                policy=RecoveryPolicy(mode="shrink",
                                      probe_window_s=1.0),
                board=world.board)
            outs = {}

            def step(a, comm_id, it):
                if rank == 2 and it == 1:
                    world.kill_rank(2)
                comm = a.communicator(comm_id)
                s = a.create_buffer_like(
                    _data(count, salt=comm.local_rank + 7 * it))
                r = a.create_buffer(count, np.float32)
                a.allreduce(s, r, count, ReduceFunction.SUM,
                            comm_id=comm_id)
                outs[it] = (comm.size, r.host.copy())

            try:
                sup.run_loop(step, iters, comm_id=0,
                             on_restart=lambda i: [outs.pop(k) for k in
                                                   list(outs) if k >= i])
            except ACCLError:
                assert rank == 2
                return "dead"
            return outs

        res = world.run(supervised)
        assert res[2] == "dead"
        for r in (0, 1):
            outs = res[r]
            assert sorted(outs) == list(range(iters))
            # post-recovery iterations ran on the 2-rank survivor comm
            assert outs[iters - 1][0] == 2


def test_supervisor_health_gauge_recovering():
    from accl_tpu.observability import health as oh
    from accl_tpu.observability import metrics as om

    reg = om.MetricsRegistry()
    oh.note_recovering(reg, True)
    assert reg.snapshot()["gauges"]["accl_health"] == \
        oh.HEALTH_RECOVERING
    assert oh.HEALTH_NAMES[oh.HEALTH_RECOVERING] == "recovering"
    oh.note_recovering(reg, False)
    assert reg.snapshot()["gauges"]["accl_health"] == oh.HEALTH_OK


# ---------------------------------------------------------------------------
# soak (slow-marked: excluded from tier-1, run by the nightly lane)
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_chaos_soak_60s():
    # 60 s of mixed seeded chaos over a 3-rank allreduce/bcast loop:
    # every iteration must stay bitwise correct; any hang fails via the
    # receive budget
    nranks = 3
    plan = "seed=777,drop=0.02,dup=0.02,delay=0.03,delay_us=2000"
    with EmuWorld(nranks, chaos=plan) as world:
        for a in world.accls:
            a.set_timeout(20_000_000)
        deadline = time.time() + 60

        def fn(accl, rank):
            it = 0
            while time.time() < deadline:
                s = accl.create_buffer_like(_data(COUNT, salt=rank + it))
                r = accl.create_buffer(COUNT, np.float32)
                accl.allreduce(s, r, COUNT, ReduceFunction.SUM)
                expected = np.sum([_data(COUNT, salt=q + it)
                                   for q in range(nranks)], axis=0)
                np.testing.assert_allclose(r.host, expected, rtol=1e-6, atol=1e-5)
                accl.bcast(s if rank == 0 else r, COUNT, root=0)
                np.testing.assert_array_equal(
                    (s if rank == 0 else r).host, _data(COUNT, salt=it))
                it += 1
            return it

        iters = world.run(fn)
        assert min(iters) >= 3  # the loop really looped under chaos
