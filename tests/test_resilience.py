"""Fault-tolerant collectives: retransmission, abort + epoch fencing,
ULFM-style shrink, and the seeded chaos harness (accl_tpu/resilience).

Complements tests/test_fault_injection.py: that file pins which error
class each fault is DETECTED as (retransmission off); this one pins
that the same faults are RECOVERED from (retransmission on — the
default), that an abort wakes every blocked waiter fast, and that a
dead rank is survivable via shrink + re-run.
"""
import threading
import time

import numpy as np
import pytest

from accl_tpu import ACCLError, ChaosPlan, ReduceFunction, RetryPolicy
from accl_tpu.backends.emu import EmuDevice, EmuWorld
from accl_tpu.constants import ErrorCode
from accl_tpu.observability import flight as obs_flight
from accl_tpu.observability import health as obs_health

COUNT = 32


def _data(count, salt=0):
    rng = np.random.default_rng(910 + salt)
    return rng.standard_normal(count).astype(np.float32)


# ---------------------------------------------------------------------------
# layer 1: NACK retransmission (one-shot faults heal transparently)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("fault", [
    EmuDevice.FAULT_DROP, EmuDevice.FAULT_DUPLICATE,
    EmuDevice.FAULT_CORRUPT_SEQ, EmuDevice.FAULT_DELAY,
], ids=["drop", "dup", "corrupt", "delay"])
def test_p2p_recovers_from_one_shot_fault(fault):
    with EmuWorld(2) as world:
        def fn(accl, rank):
            accl.set_timeout(10_000_000)
            if rank == 0:
                a = accl.create_buffer_like(_data(COUNT, salt=1))
                b = accl.create_buffer_like(_data(COUNT, salt=2))
                accl.device.inject_fault(fault)
                accl.send(a, COUNT, 1, tag=7)
                accl.send(b, COUNT, 1, tag=8)  # post-fault stream stays clean
            else:
                da = accl.create_buffer(COUNT, np.float32)
                db = accl.create_buffer(COUNT, np.float32)
                accl.recv(da, COUNT, 0, tag=7)
                accl.recv(db, COUNT, 0, tag=8)
                np.testing.assert_array_equal(da.host, _data(COUNT, salt=1))
                np.testing.assert_array_equal(db.host, _data(COUNT, salt=2))

        world.run(fn)
        # the recovery really went through the NACK lane (except dup,
        # which seqn-dedup absorbs without soliciting a resend)
        if fault in (EmuDevice.FAULT_DROP, EmuDevice.FAULT_CORRUPT_SEQ):
            stats = world.resilience_stats()
            assert sum(s["nacks_tx"] for s in stats) >= 1
            assert sum(s["retrans_sent"] for s in stats) >= 1


@pytest.mark.parametrize("fault", [
    EmuDevice.FAULT_DROP, EmuDevice.FAULT_DUPLICATE,
], ids=["drop", "dup"])
def test_allreduce_recovers_from_one_shot_fault(fault):
    with EmuWorld(2) as world:
        def fn(accl, rank):
            accl.set_timeout(10_000_000)
            s = accl.create_buffer_like(_data(COUNT, salt=rank))
            r = accl.create_buffer(COUNT, np.float32)
            if rank == 0:
                accl.device.inject_fault(fault)
            accl.allreduce(s, r, COUNT, ReduceFunction.SUM)
            return r.host.copy()

        outs = world.run(fn)
        expected = _data(COUNT, salt=0) + _data(COUNT, salt=1)
        for out in outs:
            np.testing.assert_allclose(out, expected, rtol=1e-6, atol=1e-5)


def test_wildcard_recv_recovers_dropped_tagged_send():
    # regression: a TAG_ANY recv's NACK is a wildcard solicitation —
    # it must resend the concretely-tagged segment it is waiting for
    # (tag-exact NACK matching stranded this exact pairing)
    with EmuWorld(2) as world:
        def fn(accl, rank):
            accl.set_timeout(10_000_000)
            if rank == 0:
                a = accl.create_buffer_like(_data(COUNT, salt=9))
                accl.device.inject_fault(EmuDevice.FAULT_DROP)
                accl.send(a, COUNT, 1, tag=5)  # concrete tag, dropped
            else:
                da = accl.create_buffer(COUNT, np.float32)
                accl.recv(da, COUNT, 0)  # wildcard TAG_ANY recv
                np.testing.assert_array_equal(da.host, _data(COUNT, salt=9))

        world.run(fn)
        assert sum(s["retrans_sent"]
                   for s in world.resilience_stats()) >= 1


def test_retry_disabled_restores_detection():
    # retry_max=0 is the pure detect-and-classify contract
    with EmuWorld(2, retry_max=0) as world:
        def fn(accl, rank):
            accl.set_timeout(1_000_000)
            if rank == 0:
                src = accl.create_buffer_like(_data(COUNT))
                accl.device.inject_fault(EmuDevice.FAULT_DROP)
                accl.send(src, COUNT, 1, tag=1)
            else:
                dst = accl.create_buffer(COUNT, np.float32)
                with pytest.raises(ACCLError) as e:
                    accl.recv(dst, COUNT, 0, tag=1)
                assert e.value.code & int(ErrorCode.RECEIVE_TIMEOUT_ERROR)

        world.run(fn)


def test_retry_policy_env(monkeypatch):
    monkeypatch.setenv("ACCL_RETRY_MAX", "7")
    monkeypatch.setenv("ACCL_RETRY_BASE_US", "333")
    pol = RetryPolicy.from_env()
    assert pol.max_retries == 7 and pol.base_us == 333 and pol.enabled
    # backoff: exponential envelope, deterministic jitter
    assert pol.backoff_us(3) >= 333 << 3
    assert pol.backoff_us(2, rank=1, seqn=5) == pol.backoff_us(2, rank=1,
                                                               seqn=5)
    monkeypatch.setenv("ACCL_RETRY_MAX", "0")
    assert not RetryPolicy.from_env().enabled


# ---------------------------------------------------------------------------
# seeded chaos matrix: all collectives under probabilistic drop/dup/delay
# ---------------------------------------------------------------------------
def _run_collective_matrix(world, nranks):
    """Every collective once, results asserted bitwise/allclose."""
    def fn(accl, rank):
        got = {}
        s = accl.create_buffer_like(_data(COUNT, salt=rank))
        r = accl.create_buffer(COUNT, np.float32)
        big_s = accl.create_buffer_like(
            np.concatenate([_data(COUNT, salt=100 * rank + i)
                            for i in range(nranks)]))
        big_r = accl.create_buffer(COUNT * nranks, np.float32)

        # p2p ring: rank -> rank+1
        nxt, prv = (rank + 1) % nranks, (rank - 1) % nranks
        if rank % 2 == 0:
            accl.send(s, COUNT, nxt, tag=50)
            accl.recv(r, COUNT, prv, tag=50)
        else:
            accl.recv(r, COUNT, prv, tag=50)
            accl.send(s, COUNT, nxt, tag=50)
        got["sendrecv"] = r.host.copy()

        accl.bcast(s if rank == 0 else r, COUNT, root=0)
        got["bcast"] = (s if rank == 0 else r).host.copy()

        accl.scatter(big_s, r, COUNT, root=0)
        got["scatter"] = r.host.copy()
        accl.gather(s, big_r, COUNT, root=0)
        got["gather"] = big_r.host.copy() if rank == 0 else None
        accl.allgather(s, big_r, COUNT)
        got["allgather"] = big_r.host.copy()
        accl.reduce(s, r, COUNT, root=0)
        got["reduce"] = r.host.copy() if rank == 0 else None
        accl.allreduce(s, r, COUNT, ReduceFunction.SUM)
        got["allreduce"] = r.host.copy()
        accl.reduce_scatter(big_s, r, COUNT, ReduceFunction.SUM)
        got["reduce_scatter"] = r.host.copy()
        accl.alltoall(big_s, big_r, COUNT)
        got["alltoall"] = big_r.host.copy()
        accl.barrier()
        return got

    outs = world.run(fn)
    ranks = range(nranks)
    srcs = [_data(COUNT, salt=r) for r in ranks]
    bigs = [np.concatenate([_data(COUNT, salt=100 * r + i)
                            for i in range(nranks)]) for r in ranks]
    total = np.sum(srcs, axis=0)
    for r in ranks:
        np.testing.assert_array_equal(outs[r]["sendrecv"],
                                      srcs[(r - 1) % nranks])
        np.testing.assert_array_equal(outs[r]["bcast"], srcs[0])
        np.testing.assert_array_equal(
            outs[r]["scatter"], bigs[0][r * COUNT:(r + 1) * COUNT])
        np.testing.assert_array_equal(outs[r]["allgather"],
                                      np.concatenate(srcs))
        np.testing.assert_allclose(outs[r]["allreduce"], total, rtol=1e-6, atol=1e-5)
        np.testing.assert_allclose(
            outs[r]["reduce_scatter"],
            np.sum([bigs[i][r * COUNT:(r + 1) * COUNT] for i in ranks],
                   axis=0), rtol=1e-6, atol=1e-5)
        np.testing.assert_array_equal(
            outs[r]["alltoall"],
            np.concatenate([bigs[i][r * COUNT:(r + 1) * COUNT]
                            for i in ranks]))
    np.testing.assert_array_equal(outs[0]["gather"], np.concatenate(srcs))
    np.testing.assert_allclose(outs[0]["reduce"], total, rtol=1e-6, atol=1e-5)


@pytest.mark.parametrize("plan", [
    "seed=11,drop=0.05", "seed=12,dup=0.05",
    "seed=13,delay=0.08,delay_us=3000",
    "seed=14,drop=0.03,dup=0.03,delay=0.03,delay_us=2000",
], ids=["drop", "dup", "delay", "mixed"])
def test_chaos_matrix_bitwise_correct(plan):
    # deterministic seeded chaos: every collective completes with
    # correct results via the retransmission lane (fixed seeds => the
    # fault schedule replays identically run after run)
    nranks = 3
    with EmuWorld(nranks, chaos=plan) as world:
        for a in world.accls:
            a.set_timeout(15_000_000)
        _run_collective_matrix(world, nranks)
        if "drop" in plan:
            stats = world.resilience_stats()
            assert sum(s["retrans_sent"] for s in stats) >= 1


def test_chaos_plan_grammar():
    plan = ChaosPlan.parse("seed=42,drop=0.01,dup=0.02,delay=0.03,"
                           "delay_us=500,corrupt=0.004,slow_rank=2:750,"
                           "kill_rank=3")
    assert plan.seed == 42 and plan.drop == 0.01 and plan.dup == 0.02
    assert plan.delay == 0.03 and plan.delay_us == 500
    assert plan.corrupt == 0.004
    assert plan.slow == {2: 750} and plan.kills == [3]
    assert plan.probabilistic
    # spec() round-trips through parse()
    again = ChaosPlan.parse(plan.spec())
    assert again == plan
    for bad in ("drop", "drop=2.0", "wat=1", "slow_rank=x"):
        with pytest.raises(ACCLError):
            ChaosPlan.parse(bad)
    assert ChaosPlan.from_env() is None  # unset => no plan


# ---------------------------------------------------------------------------
# layer 2: abort + epoch fencing
# ---------------------------------------------------------------------------
def test_abort_wakes_blocked_waiter_immediately():
    # the bare-wait satellite: a receiver blocked on a dead peer used to
    # exit only via the ACCL_DEFAULT_TIMEOUT budget; abort must wake it
    # now (engine finalization -> Request event), not at budget expiry
    with EmuWorld(2) as world:
        def fn(accl, rank):
            accl.set_timeout(60_000_000)  # 60 s receive budget
            if rank == 1:
                dst = accl.create_buffer(COUNT, np.float32)
                t0 = time.time()
                with pytest.raises(ACCLError) as e:
                    accl.recv(dst, COUNT, 0, tag=3)  # peer never sends
                assert time.time() - t0 < 10.0  # woke early, not at 60 s
                assert e.value.code & int(ErrorCode.COMM_ABORTED)
            else:
                time.sleep(0.5)
                accl.abort(0)

        world.run(fn)


def test_abort_wakes_bare_request_wait():
    # async flavor: a bare Request.wait() parked on the completion event
    # wakes the moment the engine finalizes the aborted call
    with EmuWorld(2) as world:
        reqs = {}

        def issue(accl, rank):
            if rank == 1:
                dst = accl.create_buffer(COUNT, np.float32)
                reqs[rank] = accl.recv(dst, COUNT, 0, tag=4,
                                       run_async=True)
            return None

        world.run(issue)
        waker = threading.Timer(
            0.5, lambda: world.accls[0].abort(
                0, error=int(ErrorCode.RANK_FAILED)))
        waker.start()
        t0 = time.time()
        assert reqs[1].wait(timeout=30.0)
        assert time.time() - t0 < 10.0
        assert reqs[1].aborted
        assert reqs[1].retcode & int(ErrorCode.RANK_FAILED)
        with pytest.raises(ACCLError):
            reqs[1].check()
        waker.join()


def test_aborted_comm_fails_fast_and_fenced_epoch_drops():
    with EmuWorld(2) as world:
        # a chaos delay holds rank 0's segment in flight across the
        # abort: when it finally releases it carries the DEAD epoch and
        # must be fenced at rank 1's ingress, not delivered
        world.devices[0].set_chaos(seed=1, drop_ppm=0, dup_ppm=0,
                                   delay_ppm=0, delay_us=700_000,
                                   corrupt_ppm=0, slow_us=0)

        def fn(accl, rank):
            accl.set_timeout(2_000_000)
            if rank == 0:
                src = accl.create_buffer_like(_data(COUNT))
                accl.device.inject_fault(EmuDevice.FAULT_DELAY)
                accl.send(src, COUNT, 1, tag=6)  # held for 0.7 s
                time.sleep(0.2)
                accl.abort(0)
                # driver-side fast fail: new calls on the aborted comm
                # never reach the engine
                with pytest.raises(ACCLError) as e:
                    accl.send(src, COUNT, 1, tag=7)
                assert e.value.code & int(ErrorCode.COMM_ABORTED)
            else:
                time.sleep(1.5)  # outlive the delayed release
            return None

        world.run(fn)
        stats = world.resilience_stats()
        assert stats[1]["fenced_drops"] >= 1  # the stale-epoch segment


def test_abort_flight_record_terminal_state_and_health():
    # flight records finalized by an abort retire as "aborted" — the
    # watchdog must see a recovery action, not a phantom hang — and the
    # accl_health gauge gains the aborted value
    with EmuWorld(2) as world:
        reqs = {}

        def issue(accl, rank):
            if rank == 1:
                dst = accl.create_buffer(COUNT, np.float32)
                reqs[rank] = accl.recv(dst, COUNT, 0, tag=5,
                                       run_async=True)
            return None

        world.run(issue)
        time.sleep(0.2)
        world.accls[0].abort(0)
        assert reqs[1].wait(30.0)
        rec = reqs[1].flight
        assert rec is not None
        assert obs_flight.STATE_NAMES[rec.state] == "aborted"
        assert not rec.in_flight
        # merged analysis: an aborted record is terminal, never a hang
        merged = obs_flight.merge_flight_dumps(
            [a.flight_recorder.dump() for a in world.accls])
        assert not any(
            h for h in merged["analysis"]["hangs"]
            if h["tag"] == 5), merged["analysis"]["hangs"]
        # health: the watchdog's next sweep reads aborted (3)
        wd = world.watchdog
        wd.check()
        assert wd._health == obs_health.HEALTH_ABORTED
        assert obs_health.HEALTH_NAMES[obs_health.HEALTH_ABORTED] == \
            "aborted"


def test_watchdog_action_abort_recovers_hang():
    # ACCL_WATCHDOG_ACTION=abort: the PR3 watchdog now triggers recovery
    # instead of only dumping — a withheld gang member turns into fast
    # COMM_ABORTED|RANK_FAILED failures on every arrived rank
    with EmuWorld(3) as world:
        world.start_watchdog(timeout_s=1.0, action="abort",
                             dump_path="")
        reqs = {}

        def issue(accl, rank):
            if rank == 0:
                return None  # withheld: never joins the gang
            s = accl.create_buffer_like(_data(COUNT, salt=rank))
            r = accl.create_buffer(COUNT, np.float32)
            reqs[rank] = accl.allreduce(s, r, COUNT, ReduceFunction.SUM,
                                        run_async=True)
            return None

        world.run(issue)
        deadline = time.time() + 30
        for rank in (1, 2):
            assert reqs[rank].wait(timeout=max(0.1, deadline - time.time()))
            assert reqs[rank].aborted
            assert reqs[rank].retcode & int(ErrorCode.RANK_FAILED)
        assert world.watchdog.last_report is not None


# ---------------------------------------------------------------------------
# layer 3: liveness + ULFM shrink
# ---------------------------------------------------------------------------
def test_probe_liveness_names_dead_rank():
    with EmuWorld(3) as world:
        world.kill_rank(2)

        def fn(accl, rank):
            if rank == 2:
                return None
            return accl.device.probe_liveness(0, 3, window_s=2.0)

        outs = world.run(fn)
        assert outs[0] == [True, True, False]
        assert outs[1] == [True, True, False]


def test_kill_abort_shrink_rerun():
    # the full recovery drill (the chaos_smoke acceptance path): a rank
    # dies mid-run; survivors classify the failure, revoke the comm,
    # agree on the surviving set, and finish on the shrunk world
    nranks = 4
    with EmuWorld(nranks) as world:
        world.kill_rank(3)

        def fn(accl, rank):
            if rank == 3:
                return "dead"
            accl.set_timeout(1_500_000)
            s = accl.create_buffer_like(_data(COUNT, salt=rank))
            r = accl.create_buffer(COUNT, np.float32)
            with pytest.raises(ACCLError):
                accl.allreduce(s, r, COUNT, ReduceFunction.SUM)
            # ULFM pattern: whoever classifies a failure revokes; the
            # propagated abort wakes slower ranks' calls immediately
            accl.abort(0, error=int(ErrorCode.RANK_FAILED))
            new_comm = accl.shrink_communicator(0, window_s=2.0)
            assert accl.communicator(new_comm).size == nranks - 1
            accl.allreduce(s, r, COUNT, ReduceFunction.SUM,
                           comm_id=new_comm)
            return r.host.copy()

        outs = world.run(fn)
        expected = np.sum([_data(COUNT, salt=r) for r in range(3)], axis=0)
        for r in range(3):
            np.testing.assert_allclose(outs[r], expected, rtol=1e-6, atol=1e-5)


def test_shrink_without_deaths_is_a_fresh_comm():
    with EmuWorld(2) as world:
        def fn(accl, rank):
            nc = accl.shrink_communicator(0, window_s=1.0)
            assert accl.communicator(nc).size == 2
            s = accl.create_buffer_like(_data(8, salt=rank))
            r = accl.create_buffer(8, np.float32)
            accl.allreduce(s, r, 8, ReduceFunction.SUM, comm_id=nc)
            return r.host.copy()

        outs = world.run(fn)
        expected = _data(8, salt=0) + _data(8, salt=1)
        np.testing.assert_allclose(outs[0], expected, rtol=1e-6, atol=1e-5)
        np.testing.assert_allclose(outs[1], expected, rtol=1e-6, atol=1e-5)


# ---------------------------------------------------------------------------
# soak (slow-marked: excluded from tier-1, run by the nightly lane)
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_chaos_soak_60s():
    # 60 s of mixed seeded chaos over a 3-rank allreduce/bcast loop:
    # every iteration must stay bitwise correct; any hang fails via the
    # receive budget
    nranks = 3
    plan = "seed=777,drop=0.02,dup=0.02,delay=0.03,delay_us=2000"
    with EmuWorld(nranks, chaos=plan) as world:
        for a in world.accls:
            a.set_timeout(20_000_000)
        deadline = time.time() + 60

        def fn(accl, rank):
            it = 0
            while time.time() < deadline:
                s = accl.create_buffer_like(_data(COUNT, salt=rank + it))
                r = accl.create_buffer(COUNT, np.float32)
                accl.allreduce(s, r, COUNT, ReduceFunction.SUM)
                expected = np.sum([_data(COUNT, salt=q + it)
                                   for q in range(nranks)], axis=0)
                np.testing.assert_allclose(r.host, expected, rtol=1e-6, atol=1e-5)
                accl.bcast(s if rank == 0 else r, COUNT, root=0)
                np.testing.assert_array_equal(
                    (s if rank == 0 else r).host, _data(COUNT, salt=it))
                it += 1
            return it

        iters = world.run(fn)
        assert min(iters) >= 3  # the loop really looped under chaos
