"""Collective sanitizer tests: static checkers, record/shadow capture,
the accl_lint CLI, and the ACCL_SANITIZE runtime lane.

Layout mirrors the subsystem: LintWorld/record-mode programs feed the
static checker suite (each seeded bug class + a clean program must lint
exactly as specified), the CLI round-trips the committed fixtures, and
the runtime sanitizer turns would-hang emu programs into immediate
ACCLErrors.
"""
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from accl_tpu import ReduceFunction
from accl_tpu.analysis import LintWorld, check_programs
from accl_tpu.analysis import sanitizer
from accl_tpu.analysis.findings import ERROR, WARNING, has_errors
from accl_tpu.constants import ACCLError
from accl_tpu.observability.flight import first_divergence

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "lint")
LINT_CLI = os.path.join(REPO, "scripts", "accl_lint.py")


def codes(findings):
    return sorted({f.code for f in findings})


def lint(fn, nranks=2):
    world = LintWorld(nranks)
    world.run(fn)
    return world.check()


# ---------------------------------------------------------------------------
# static checkers: each seeded bug class
# ---------------------------------------------------------------------------
def test_clean_program_zero_findings():
    def fn(a, r):
        s = a.create_buffer(512, np.float32)
        d = a.create_buffer(512, np.float32)
        g = a.create_buffer(512 * a.size, np.float32)
        a.allreduce(s, d, 512, ReduceFunction.SUM)
        a.allgather(s, g, 512)
        a.bcast(s, 512, root=1)
        a.barrier()
        req = a.send(s, 512, dst=(r + 1) % a.size, tag=5, run_async=True)
        a.recv(d, 512, src=(r - 1) % a.size, tag=5)
        assert req.wait()
        req.check()
        g.free()  # free after last use: not a hazard

    assert lint(fn, nranks=4) == []


def test_order_desync_first_divergent_index():
    def fn(a, r):
        s = a.create_buffer(64, np.float32)
        d = a.create_buffer(64, np.float32)
        a.allreduce(s, d, 64, ReduceFunction.SUM)  # agreeing prefix
        if r == 0:
            a.allreduce(s, d, 64, ReduceFunction.SUM)
        else:
            a.bcast(s, 64, root=0)

    findings = lint(fn)
    assert [f.code for f in findings] == ["desync-order"]
    f = findings[0]
    assert f.severity == ERROR and f.index == 1 and f.comm == 0
    assert "allreduce" in f.message and "bcast" in f.message


def test_param_mismatch_count_dtype():
    def fn(a, r):
        s = a.create_buffer(128, np.float32)
        d = a.create_buffer(128, np.float32)
        a.allreduce(s, d, 128 if r == 0 else 96, ReduceFunction.SUM)

    findings = lint(fn)
    assert [f.code for f in findings] == ["param-mismatch"]
    assert "count=128" in findings[0].message
    assert "count=96" in findings[0].message

    def fn2(a, r):
        dt = np.float32 if r == 0 else np.float64
        s = a.create_buffer(64, dt)
        d = a.create_buffer(64, dt)
        a.allreduce(s, d, 64, ReduceFunction.SUM)

    findings = lint(fn2)
    assert [f.code for f in findings] == ["param-mismatch"]
    assert "float32" in findings[0].message
    assert "float64" in findings[0].message


def test_root_mismatch_is_param_mismatch():
    def fn(a, r):
        s = a.create_buffer(32, np.float32)
        a.bcast(s, 32, root=r)  # every rank names itself root

    assert codes(lint(fn)) == ["param-mismatch"]


def test_missing_call_imbalance():
    def fn(a, r):
        s = a.create_buffer(64, np.float32)
        d = a.create_buffer(64, np.float32)
        a.allreduce(s, d, 64, ReduceFunction.SUM)
        if r == 0:  # rank 1 returns early: its peers hang
            a.allreduce(s, d, 64, ReduceFunction.SUM)

    found = codes(lint(fn))
    assert "desync-missing-call" in found
    assert "gang-missing-member" in found  # the sim sees the hang too


def test_deadlock_cycle_head_to_head_sends():
    def fn(a, r):
        peer = 1 - r
        s = a.create_buffer(4096, np.float32)  # rendezvous-sized
        d = a.create_buffer(4096, np.float32)
        a.send(s, 4096, dst=peer, tag=0)
        a.recv(d, 4096, src=peer, tag=0)

    findings = lint(fn)
    assert [f.code for f in findings] == ["deadlock-cycle"]
    assert sorted(findings[0].ranks) == [0, 1]
    assert "send" in findings[0].message


def test_eager_send_before_recv_is_not_deadlock():
    # same head-to-head shape but the payload fits the 1 KB eager
    # threshold: the rx pool buffers it, both recvs drain — clean
    def fn(a, r):
        peer = 1 - r
        s = a.create_buffer(64, np.float32)  # 256 B: eager
        d = a.create_buffer(64, np.float32)
        a.send(s, 64, dst=peer, tag=0)
        a.recv(d, 64, src=peer, tag=0)

    assert lint(fn) == []


def test_cross_gang_p2p_deadlock():
    # rank 1 waits for a send rank 0 only issues AFTER its allreduce;
    # rank 0's allreduce waits for rank 1 — a mixed-edge cycle
    def fn(a, r):
        s = a.create_buffer(4096, np.float32)
        d = a.create_buffer(4096, np.float32)
        if r == 0:
            a.allreduce(s, d, 4096, ReduceFunction.SUM)
            a.send(s, 4096, dst=1, tag=1)
        else:
            a.recv(d, 4096, src=0, tag=1)
            a.allreduce(s, d, 4096, ReduceFunction.SUM)

    assert "deadlock-cycle" in codes(lint(fn))


def test_unmatched_send_and_recv():
    def fn(a, r):
        s = a.create_buffer(4096, np.float32)
        if r == 0:
            a.send(s, 4096, dst=1, tag=9)  # nobody ever receives

    findings = lint(fn)
    assert codes(findings) == ["p2p-unmatched"]

    def fn2(a, r):
        d = a.create_buffer(64, np.float32)
        if r == 1:
            a.recv(d, 64, src=0, tag=2)  # nobody ever sends

    findings = lint(fn2)
    assert codes(findings) == ["p2p-unmatched"]
    assert "no matching send" in findings[0].message


def test_root_and_peer_validity():
    def fn(a, r):
        s = a.create_buffer(16, np.float32)
        a.bcast(s, 16, root=7)

    assert "root-invalid" in codes(lint(fn))

    def fn2(a, r):
        s = a.create_buffer(4096, np.float32)
        if r == 0:
            a.send(s, 4096, dst=5, tag=0, run_async=True).wait()

    assert "peer-invalid" in codes(lint(fn2))


def test_sub_comm_root_is_comm_local():
    # root 2 is valid in the world but NOT in the 2-member sub-comm
    def fn(a, r):
        s = a.create_buffer(32, np.float32)
        members = [0, 2]
        if r in members:
            cid = a.create_communicator(members)
            a.bcast(s, 32, root=2, comm_id=cid)

    findings = lint(fn, nranks=4)
    assert "root-invalid" in codes(findings)
    bad = [f for f in findings if f.code == "root-invalid"]
    assert all(f.comm == 1 for f in bad)


def test_buffer_overlap_and_alias():
    def fn(a, r):
        s = a.create_buffer(128, np.float32)
        a.allreduce(s.slice(0, 64), s.slice(32, 96), 64,
                    ReduceFunction.SUM)

    findings = lint(fn)
    assert codes(findings) == ["buffer-overlap"]
    assert all(f.severity == ERROR for f in findings)

    def fn2(a, r):
        s = a.create_buffer(64, np.float32)
        a.allreduce(s, s, 64, ReduceFunction.SUM)  # exact alias

    findings = lint(fn2)
    assert codes(findings) == ["buffer-alias"]
    assert all(f.severity == WARNING for f in findings)


def test_use_after_free():
    def fn(a, r):
        s = a.create_buffer(64, np.float32)
        d = a.create_buffer(64, np.float32)
        s.free()
        a.allreduce(s, d, 64, ReduceFunction.SUM)

    findings = lint(fn)
    assert "use-after-free" in codes(findings)
    assert all(f.severity == ERROR for f in findings
               if f.code == "use-after-free")


def test_leaked_async_request():
    def fn(a, r):
        s = a.create_buffer(64, np.float32)
        d = a.create_buffer(64, np.float32)
        a.allreduce(s, d, 64, ReduceFunction.SUM, run_async=True)

    findings = lint(fn)
    assert codes(findings) == ["leaked-request"]
    assert all(f.severity == WARNING for f in findings)
    assert not has_errors(findings)

    def fn2(a, r):
        s = a.create_buffer(64, np.float32)
        d = a.create_buffer(64, np.float32)
        req = a.allreduce(s, d, 64, ReduceFunction.SUM, run_async=True)
        assert req.wait()

    assert lint(fn2) == []


def test_extent_scaling_catches_fan_overlap():
    # allgather result spans count*P elements: a result buffer placed
    # right after the source still collides through the fan-out
    def fn(a, r):
        big = a.create_buffer(64 + 64 * a.size, np.float32)
        src = big.slice(0, 64)
        res = big.slice(32, 32 + 64 * a.size)
        a.allgather(src, res, 64)

    assert "buffer-overlap" in codes(lint(fn))


def test_compressed_rooted_collective_is_not_a_mismatch():
    """Per-operand compression bits and stream flags are legitimately
    per-rank (only the ROOT of a compressed rooted collective marks its
    buffers): the documented ROOTED_COMBOS pattern must lint clean."""
    from accl_tpu.constants import DataType

    def fn(a, r):
        s = a.create_buffer(64, np.float32)
        d = a.create_buffer(64 * a.size, np.float32) if r == 0 else None
        a.gather(s, d, 64, root=0, compress_dtype=DataType.float16)

    assert lint(fn) == []


def test_missing_gang_member_is_not_a_deadlock_cycle():
    """Ranks co-blocked on the SAME gang instance wait together: the
    culprit is the member that never arrives, not each other."""
    def fn(a, r):
        if r != 2:
            a.barrier()

    findings = lint(fn, nranks=3)
    assert "deadlock-cycle" not in codes(findings)
    missing = [f for f in findings if f.code == "gang-missing-member"]
    assert missing and all("missing [2]" in f.message for f in missing)


def test_first_divergence_helper():
    seqs = {0: ["a", "b", "c"], 1: ["a", "x", "c"]}
    div = first_divergence(seqs, lambda s: s)
    assert div["index"] == 1 and div["per_rank"] == {0: "b", 1: "x"}
    assert first_divergence({0: ["a"], 1: ["a", "b"]}, lambda s: s) is None
    assert first_divergence({}, lambda s: s) is None


# ---------------------------------------------------------------------------
# driver satellites
# ---------------------------------------------------------------------------
def test_unknown_communicator_raises_acclerror():
    world = LintWorld(2)
    accl = world.accls[0]
    s = accl.create_buffer(8, np.float32)
    d = accl.create_buffer(8, np.float32)
    with pytest.raises(ACCLError, match="unknown communicator id 3"):
        accl.allreduce(s, d, 8, ReduceFunction.SUM, comm_id=3)
    with pytest.raises(ACCLError, match="unknown communicator id 3"):
        accl.communicator(3)
    with pytest.raises(ACCLError, match="unknown communicator"):
        accl.dump_communicator(9)


def test_create_communicator_validates_indices():
    world = LintWorld(2)
    with pytest.raises(ACCLError, match=r"\[5\]"):
        world.accls[0].create_communicator([0, 5])


def test_deinit_warns_about_pending_async(caplog):
    world = LintWorld(1)
    accl = world.accls[0]
    s = accl.create_buffer(8, np.float32)
    d = accl.create_buffer(8, np.float32)
    req = accl.allreduce(s, d, 8, ReduceFunction.SUM, run_async=True)
    # the record backend completes instantly; rewind the event so the
    # request is genuinely "still pending" at deinit
    req._done = threading.Event()
    with caplog.at_level("WARNING", logger="accl_tpu"):
        accl.deinit()
    text = caplog.text
    assert "pending" in text and "allreduce" in text
    assert "seq=" in text  # the flight record (seq/state) is listed


# ---------------------------------------------------------------------------
# cross-communicator interleave order
# ---------------------------------------------------------------------------
def _grid_program(row_first_ranks):
    """2x2 grid: row comm id 1, col comm id 2 on every rank; ranks in
    ``row_first_ranks`` enter row-then-col, the rest col-then-row."""
    def fn(a, rank):
        row, col = divmod(rank, 2)
        rc = a.create_communicator([row * 2, row * 2 + 1])
        cc = a.create_communicator([col, col + 2])
        s = a.create_buffer(64, np.float32)
        ro = a.create_buffer(128, np.float32)
        co = a.create_buffer(128, np.float32)
        order = [(rc, ro), (cc, co)]
        if rank not in row_first_ranks:
            order.reverse()
        reqs = [a.allgather(s, out, 64, comm_id=cid, run_async=True)
                for cid, out in order]
        for req in reqs:
            req.wait()
            req.check()
    return fn


def test_subcomm_interleave_divergent_pair_flagged():
    findings = lint(_grid_program(row_first_ranks={0, 1}), nranks=4)
    assert [f.code for f in findings] == ["subcomm-interleave-hazard"]
    f = findings[0]
    assert f.severity == ERROR
    assert f.ranks == [0, 2]  # one witness per direction
    assert "divergent order" in f.message


def test_subcomm_interleave_agreed_order_clean():
    # same grid, every rank row-then-col: one global order, no hazard
    assert lint(_grid_program(row_first_ranks={0, 1, 2, 3}),
                nranks=4) == []


def test_subcomm_interleave_long_cycle_flagged():
    # no pair is entered both ways, but the per-rank orders close a
    # 3-cycle in the comm-order graph: 1<2 (rank 0), 2<3 (rank 1),
    # 3<1 (rank 2) — no global order exists
    def fn(a, rank):
        members = [0, 1, 2]
        cids = [a.create_communicator(members) for _ in range(3)]
        s = a.create_buffer(64, np.float32)
        outs = [a.create_buffer(64 * 3, np.float32) for _ in range(3)]
        pair = (rank, (rank + 1) % 3)
        reqs = []
        for k in pair:
            reqs.append(a.allgather(s, outs[k], 64, comm_id=cids[k],
                                    run_async=True))
        for req in reqs:
            req.wait()
            req.check()

    findings = lint(fn, nranks=3)
    assert "subcomm-interleave-hazard" in codes(findings)
    cyc = [f for f in findings if f.code == "subcomm-interleave-hazard"]
    assert len(cyc) == 1 and "acquisition cycle" in cyc[0].message


# ---------------------------------------------------------------------------
# CLI round-trips over the committed fixtures
# ---------------------------------------------------------------------------
def run_cli(*args):
    return subprocess.run([sys.executable, LINT_CLI, *args],
                          capture_output=True, text=True, cwd=REPO)


def test_cli_clean_fixture_exits_zero():
    proc = run_cli(os.path.join(FIXTURES, "clean_fixture.py"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "no findings" in proc.stdout


def test_cli_desync_fixture_flagged(tmp_path):
    out = str(tmp_path / "lint.json")
    proc = run_cli(os.path.join(FIXTURES, "desync_fixture.py"),
                   "--json", out)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "desync-order" in proc.stdout
    doc = json.loads(open(out).read())
    assert doc["mode"] == "record" and doc["ranks"] == 2
    assert [f["code"] for f in doc["findings"]] == ["desync-order"]
    assert doc["programs"]["0"]["calls"][0]["op"] == "allreduce"
    assert doc["programs"]["1"]["calls"][0]["op"] == "bcast"


def test_cli_deadlock_fixture_flagged():
    proc = run_cli(os.path.join(FIXTURES, "deadlock_fixture.py"))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "deadlock-cycle" in proc.stdout


def test_cli_param_mismatch_fixture_flagged():
    proc = run_cli(os.path.join(FIXTURES, "param_mismatch_fixture.py"))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "param-mismatch" in proc.stdout
    assert "count=256" in proc.stdout and "count=128" in proc.stdout


def test_cli_subcomm_interleave_fixture_flagged():
    proc = run_cli(os.path.join(FIXTURES, "subcomm_interleave_fixture.py"))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "subcomm-interleave-hazard" in proc.stdout


def test_cli_strict_promotes_warnings(tmp_path):
    leaky = tmp_path / "leaky.py"
    leaky.write_text(
        "import numpy as np\n"
        "from accl_tpu import ReduceFunction\n"
        "def accl_main(a, r):\n"
        "    s = a.create_buffer(32, np.float32)\n"
        "    d = a.create_buffer(32, np.float32)\n"
        "    a.allreduce(s, d, 32, ReduceFunction.SUM, run_async=True)\n")
    assert run_cli(str(leaky)).returncode == 0
    assert run_cli(str(leaky), "--strict").returncode == 1


# ---------------------------------------------------------------------------
# runtime sanitizer lane (ACCL_SANITIZE)
# ---------------------------------------------------------------------------
@pytest.fixture
def sanitize():
    sanitizer.set_enabled(True)
    try:
        yield
    finally:
        sanitizer.set_enabled(False)
        sanitizer._reset_exchange()


@pytest.fixture
def emu_world():
    from accl_tpu.backends.emu import EmuWorld

    with EmuWorld(2) as world:
        yield world


def test_sanitize_off_by_default():
    assert not sanitizer.active()
    assert not sanitizer.enabled()


def test_sanitizer_clean_emu_program_unaffected(sanitize, emu_world):
    bufs = {}

    def fn(a, r):
        s = a.create_buffer_like(np.arange(64, dtype=np.float32) + r)
        d = a.create_buffer(64, np.float32)
        bufs[r] = (s, d)
        a.allreduce(s, d, 64, ReduceFunction.SUM)
        return d.host.copy()

    outs = emu_world.run(fn)
    expect = (np.arange(64, dtype=np.float32) * 2 + 1)
    np.testing.assert_allclose(outs[0], expect)
    np.testing.assert_allclose(outs[1], expect)


def test_sanitizer_turns_mismatch_into_error_on_both_ranks(
        sanitize, emu_world):
    """The acceptance drill: a would-hang mismatched emu program raises
    an immediate ACCLError naming BOTH divergent calls on EVERY rank —
    no watchdog timeout, no wedged gang."""
    def fn(a, r):
        s = a.create_buffer(64, np.float32)
        d = a.create_buffer(64, np.float32)
        with pytest.raises(ACCLError) as exc:
            a.allreduce(s, d, 64 if r == 0 else 32, ReduceFunction.SUM)
        msg = str(exc.value)
        assert "cross-rank call mismatch" in msg
        assert "count=64" in msg and "count=32" in msg
        assert "flight seq" in msg
        return msg

    emu_world.run(fn)


def test_sanitizer_order_desync_raises(sanitize, emu_world):
    def fn(a, r):
        s = a.create_buffer(64, np.float32)
        d = a.create_buffer(64, np.float32)
        with pytest.raises(ACCLError, match="cross-rank call mismatch"):
            if r == 0:
                a.allreduce(s, d, 64, ReduceFunction.SUM)
            else:
                a.bcast(s, 64, root=0)

    emu_world.run(fn)


def test_sanitizer_missing_member_times_out_with_names(
        sanitize, emu_world, monkeypatch):
    monkeypatch.setenv("ACCL_SANITIZE_TIMEOUT", "0.5")

    def fn(a, r):
        if r != 0:
            return None
        s = a.create_buffer(64, np.float32)
        d = a.create_buffer(64, np.float32)
        with pytest.raises(ACCLError, match=r"missing \[1\]"):
            a.allreduce(s, d, 64, ReduceFunction.SUM)
        return True

    assert emu_world.run(fn)[0] is True


def test_sanitizer_single_rank_checks(sanitize, emu_world):
    def fn(a, r):
        s = a.create_buffer(128, np.float32)
        with pytest.raises(ACCLError, match="root 9 is outside"):
            a.bcast(s, 128, root=9)
        with pytest.raises(ACCLError, match="partially overlaps"):
            a.allreduce(s.slice(0, 64), s.slice(32, 96), 64,
                        ReduceFunction.SUM)
        with pytest.raises(ACCLError, match="unknown communicator"):
            a.allreduce(s, s, 64, ReduceFunction.SUM, comm_id=4)

    emu_world.run(fn)


def test_sanitizer_abort_retires_flight_record(sanitize, emu_world):
    """An aborted call must leave the watchdog's in-flight scan: its
    flight record is finished with the dedicated sanitizer retcode,
    never reported as a hung gang."""
    def fn(a, r):
        s = a.create_buffer(64, np.float32)
        d = a.create_buffer(64, np.float32)
        with pytest.raises(ACCLError):
            a.allreduce(s, d, 64 if r == 0 else 32, ReduceFunction.SUM)
        recs = a.flight_recorder.records()
        assert recs, "no flight record for the aborted call"
        last = recs[-1]
        assert not last.in_flight
        from accl_tpu.constants import error_code_to_str

        assert "SANITIZER_ABORT_ERROR" in error_code_to_str(last.retcode)

    emu_world.run(fn)


def test_comm_abort_retires_flight_record_like_sanitizer(emu_world):
    """COMM_ABORTED is handled exactly like SANITIZER_ABORT_ERROR by
    the observability stack: an abort-finalized call's flight record is
    TERMINAL ("aborted"), leaves the watchdog's in-flight scan, and the
    merged cross-rank analysis reports no phantom hang while the abort
    propagates (the r10 abort/epoch satellite)."""
    import time

    from accl_tpu.constants import ErrorCode, error_code_to_str
    from accl_tpu.observability import flight as obs_flight

    reqs = {}

    def issue(a, r):
        if r == 1:
            d = a.create_buffer(64, np.float32)
            reqs[r] = a.recv(d, 64, 0, tag=77, run_async=True)
        return None

    emu_world.run(issue)
    time.sleep(0.1)
    emu_world.accls[0].abort(0)
    assert reqs[1].wait(30.0)
    rec = reqs[1].flight
    assert rec is not None and not rec.in_flight
    assert obs_flight.STATE_NAMES[rec.state] == "aborted"
    assert "COMM_ABORTED" in error_code_to_str(rec.retcode)
    # no phantom hang anywhere in the merged analysis during/after the
    # abort — aborted records are terminal for the hang scanner
    merged = obs_flight.merge_flight_dumps(
        [a.flight_recorder.dump() for a in emu_world.accls])
    assert merged["analysis"]["hangs"] == []
    # the world must stay usable for the remaining sanitizer tests
    # sharing this fixture (abort fencing cleared by reset_errors)
    for a in emu_world.accls:
        a.reset_errors()
    assert int(ErrorCode.COMM_ABORTED) != int(ErrorCode.RANK_FAILED)


def test_shadow_capture_session(emu_world):
    from accl_tpu.analysis.sanitizer import CaptureSession

    with CaptureSession() as cap:
        def fn(a, r):
            s = a.create_buffer(64, np.float32)
            d = a.create_buffer(64, np.float32)
            a.allreduce(s, d, 64, ReduceFunction.SUM)

        emu_world.run(fn)
    assert not sanitizer.active()  # uninstalled on exit
    assert sorted(cap.programs) == [0, 1]
    assert [c.op.name for c in cap.programs[0].calls] == ["allreduce"]
    assert cap.check() == []


def test_check_programs_empty_input():
    assert check_programs({}) == []


# ---------------------------------------------------------------------------
# r13: happens-before lifecycle checkers over merged flight dumps
# ---------------------------------------------------------------------------
def _rec(rank, seq, collective, comm=0, state="complete", retcode=0,
         gang=False, t_submit=0, t_complete=0, lane="emu"):
    """Minimal flight-record dict with the RECORD_SCHEMA_KEYS fields
    the lifecycle checkers consume."""
    return {"seq": seq, "req_id": seq, "rank": rank,
            "collective": collective, "comm": comm, "tag": 0,
            "dtype": "float32", "count": 16, "nbytes": 64, "nranks": 2,
            "lane": lane, "state": state, "gang": gang,
            "retcode": retcode, "age_us": 0.0, "t_submit": t_submit,
            "t_queue": 0, "t_gang_ready": 0, "t_dispatch": 0,
            "t_complete": t_complete}


def _dump(rank, records):
    return {"rank": rank, "capacity": 512, "last_completed_seq": -1,
            "records": records}


def test_fence_stale_replay_flagged():
    from accl_tpu.analysis.checks import check_fence_staleness

    recs = [
        _rec(0, 0, "plan_replay", state="complete", gang=True),
        _rec(0, 1, "abort", retcode=1 << 27, state="aborted",
             lane="fence"),
        # replay AFTER the fence with no re-capture: the violation
        _rec(0, 2, "plan_replay", state="complete", gang=True),
    ]
    findings = check_fence_staleness(_dump(0, recs))
    assert [f.code for f in findings] == ["fence-stale-replay"]
    assert findings[0].index == 2


def test_fence_then_recapture_then_replay_clean():
    from accl_tpu.analysis.checks import check_fence_staleness

    recs = [
        _rec(0, 0, "abort", retcode=1 << 27, state="aborted",
             lane="fence"),
        _rec(0, 1, "plan_capture", lane="plan"),
        _rec(0, 2, "plan_replay", state="complete", gang=True),
    ]
    assert check_fence_staleness(_dump(0, recs)) == []


def test_reset_errors_fences_every_existing_comm():
    from accl_tpu.analysis.checks import check_fence_staleness

    recs = [
        _rec(0, 0, "allreduce", comm=3, gang=True),
        _rec(0, 1, "reset_errors", comm=-1, lane="fence"),
        _rec(0, 2, "plan_replay", comm=3, state="complete", gang=True),
    ]
    findings = check_fence_staleness(_dump(0, recs))
    assert [f.code for f in findings] == ["fence-stale-replay"]


def test_failed_replay_after_fence_is_the_sanctioned_path():
    from accl_tpu.analysis.checks import check_fence_staleness

    recs = [
        _rec(0, 0, "abort", retcode=1 << 27, state="aborted",
             lane="fence"),
        # the fencing contract WORKING: replay raised COMM_ABORTED
        _rec(0, 1, "plan_replay", state="aborted", retcode=1 << 27,
             gang=True),
    ]
    assert check_fence_staleness(_dump(0, recs)) == []


def test_completion_after_teardown_flagged():
    from accl_tpu.analysis.checks import check_teardown_completions

    recs = [
        _rec(0, 0, "allreduce", gang=True, t_submit=10, t_complete=20),
        _rec(0, 1, "engine_teardown", comm=-1, t_submit=100,
             t_complete=100, lane="lifecycle"),
        # a success published after teardown: the segfault class
        _rec(0, 2, "allreduce", gang=True, t_submit=90, t_complete=150),
    ]
    findings = check_teardown_completions(_dump(0, recs))
    assert [f.code for f in findings] == ["completion-after-teardown"]
    assert findings[0].index == 2


def test_aborted_finalization_after_teardown_is_sanctioned():
    from accl_tpu.analysis.checks import check_teardown_completions

    recs = [
        _rec(0, 0, "engine_teardown", comm=-1, t_submit=100,
             t_complete=100, lane="lifecycle"),
        # shutdown's finalize sweep: COMM_ABORTED, state aborted — OK
        _rec(0, 1, "recv", state="aborted", retcode=(1 << 27) | (1 << 28),
             t_submit=90, t_complete=150),
    ]
    assert check_teardown_completions(_dump(0, recs)) == []


def test_lock_order_inversion_flagged_across_ranks():
    from accl_tpu.analysis.checks import check_lock_order

    # rank 0 nests comm 1 inside comm 0 (0 held while 1 submits);
    # rank 1 nests comm 0 inside comm 1 — ABBA
    r0 = [_rec(0, 0, "allreduce", comm=0, gang=True, t_submit=10,
               t_complete=0, state="dispatched"),
          _rec(0, 1, "allreduce", comm=1, gang=True, t_submit=20,
               t_complete=0, state="dispatched")]
    r1 = [_rec(1, 0, "allreduce", comm=1, gang=True, t_submit=10,
               t_complete=0, state="dispatched"),
          _rec(1, 1, "allreduce", comm=0, gang=True, t_submit=20,
               t_complete=0, state="dispatched")]
    merged = {"ranks": [_dump(0, r0), _dump(1, r1)]}
    findings = check_lock_order(merged)
    assert [f.code for f in findings] == ["lock-order-inversion"]
    assert findings[0].ranks == [0, 1]


def test_lock_order_sequential_acquisition_clean():
    from accl_tpu.analysis.checks import check_lock_order

    # both ranks run comm 0 to completion BEFORE touching comm 1 and
    # vice versa — no held-while-acquiring window, no finding
    r0 = [_rec(0, 0, "allreduce", comm=0, gang=True, t_submit=10,
               t_complete=15),
          _rec(0, 1, "allreduce", comm=1, gang=True, t_submit=20,
               t_complete=25)]
    r1 = [_rec(1, 0, "allreduce", comm=1, gang=True, t_submit=10,
               t_complete=15),
          _rec(1, 1, "allreduce", comm=0, gang=True, t_submit=20,
               t_complete=25)]
    merged = {"ranks": [_dump(0, r0), _dump(1, r1)]}
    assert check_lock_order(merged) == []


def test_stuck_progress_through_teardown_is_error():
    from accl_tpu.analysis.checks import check_stuck_progress

    recs = [
        _rec(0, 0, "allreduce", gang=True, t_complete=20),
        # a dispatched recv that never finalized, with the world torn
        # down around it: the liveness violation (the sub-comm wedge's
        # dump signature)
        _rec(0, 1, "recv", state="dispatched", comm=2, t_submit=30),
        _rec(0, 2, "engine_teardown", comm=-1, t_submit=100,
             t_complete=100, lane="lifecycle"),
    ]
    findings = check_stuck_progress(_dump(0, recs))
    assert [f.code for f in findings] == ["stuck-progress"]
    f = findings[0]
    assert f.severity == ERROR and f.index == 1 and f.comm == 2


def test_stuck_progress_midrun_snapshot_is_warning():
    from accl_tpu.analysis.checks import check_stuck_progress

    # no teardown anchor: the dump may be a live snapshot, so the
    # in-flight record downgrades to a warning
    recs = [_rec(0, 0, "allgather", gang=True, state="queued")]
    findings = check_stuck_progress(_dump(0, recs))
    assert [(f.code, f.severity) for f in findings] == \
        [("stuck-progress", WARNING)]


def test_stuck_progress_terminal_states_clean():
    from accl_tpu.analysis.checks import check_stuck_progress

    # complete, failed and ABORTED (teardown's finalize sweep) all
    # count as finalized — liveness holds
    recs = [
        _rec(0, 0, "allreduce", gang=True, t_complete=20),
        _rec(0, 1, "recv", state="failed", retcode=1 << 11,
             t_complete=30),
        _rec(0, 2, "send", state="aborted", retcode=1 << 27,
             t_complete=40),
        _rec(0, 3, "engine_teardown", comm=-1, t_submit=100,
             t_complete=100, lane="lifecycle"),
    ]
    assert check_stuck_progress(_dump(0, recs)) == []


def test_lifecycle_suite_end_to_end_on_real_world(tmp_path):
    """A real abort -> fenced replay -> re-capture -> replay flow must
    come out CLEAN, and the dump must carry the lifecycle anchors."""
    from accl_tpu.analysis.checks import check_flight_lifecycle
    from accl_tpu.backends.emu import EmuWorld
    from accl_tpu.observability.flight import merge_flight_dumps

    with EmuWorld(2) as w:

        def fn(accl, rank):
            src = accl.create_buffer(16, np.float32)
            src.host[:] = rank + 1.0
            src.sync_to_device()
            dst = accl.create_buffer(16, np.float32)
            accl.allreduce(src, dst, 16)

        w.run(fn)
        w.accls[0].abort(0)
        names = [r.collective for r in w.accls[0].flight_recorder.records()]
        assert "abort" in names
        doc = merge_flight_dumps(
            [a.flight_recorder.dump() for a in w.accls])
        findings = check_flight_lifecycle(doc)
        assert [f for f in findings if f.severity == ERROR] == []
        # round-trip through JSON like a production post-mortem would
        p = tmp_path / "dump.json"
        p.write_text(json.dumps(doc))
        assert [f for f in check_flight_lifecycle(str(p))
                if f.severity == ERROR] == []
