"""RDMA transport rung: queue pairs + out-of-band one-sided writes.

Reference analog: the Coyote RDMA backend (CoyoteDevice + cyt_adapter):
control traffic rides an ordered plane while rendezvous payloads move as
one-sided WRITEs with SQ/CQ accounting on a separate memory plane that
can overtake the ordered stream — the engine's out-of-order completion
matching (WR_DONE pop_match) is load-bearing on every transfer here.
"""
import numpy as np
import pytest

from accl_tpu import ReduceFunction
from accl_tpu.backends.emu import EmuWorld

NRANKS = 4


@pytest.fixture(scope="module")
def world():
    with EmuWorld(NRANKS, transport="rdma", max_eager_size=2048,
                  max_rendezvous_size=1 << 20) as w:
        yield w


def _data(count, rank, salt=0):
    rng = np.random.default_rng(640 + rank + salt * 131)
    return rng.standard_normal(count).astype(np.float32)


def test_rendezvous_collectives_over_rdma(world):
    # low eager ceiling: everything below rides control-plane eager,
    # everything above rides one-sided memory-plane writes
    count = 4096  # 16 KB -> rendezvous

    def fn(accl, rank):
        s = accl.create_buffer_like(_data(count, rank, 1))
        r = accl.create_buffer(count, np.float32)
        accl.allreduce(s, r, count, ReduceFunction.SUM)
        want = sum(_data(count, k, 1) for k in range(NRANKS))
        np.testing.assert_allclose(r.host, want, rtol=1e-4, atol=1e-4)

        buf = accl.create_buffer(count, np.float32)
        if rank == 1:
            buf.host[:] = _data(count, 1, 2)
        accl.bcast(buf, count, 1)
        np.testing.assert_array_equal(buf.host, _data(count, 1, 2))

        send = accl.create_buffer_like(_data(count, rank, 3))
        recv = accl.create_buffer(count * NRANKS, np.float32)
        accl.gather(send, recv, count, 0)
        if rank == 0:
            want = np.concatenate(
                [_data(count, k, 3) for k in range(NRANKS)])
            np.testing.assert_array_equal(recv.host, want)
        accl.barrier()

    world.run(fn)


def test_mixed_eager_and_onesided_interleave(world):
    # eager (ordered plane) and rendezvous (memory plane) traffic on the
    # same route concurrently: the memory plane may overtake the ordered
    # plane, so completion matching must be fully out-of-order-tolerant
    small, big = 128, 4096

    def fn(accl, rank):
        nxt, prv = (rank + 1) % NRANKS, (rank - 1) % NRANKS
        se = accl.create_buffer_like(_data(small, rank, 4))
        sb = accl.create_buffer_like(_data(big, rank, 5))
        re = accl.create_buffer(small, np.float32)
        rb = accl.create_buffer(big, np.float32)
        qe = accl.send(se, small, nxt, tag=50, run_async=True)
        qb = accl.send(sb, big, nxt, tag=51, run_async=True)
        accl.recv(re, small, prv, tag=50)
        accl.recv(rb, big, prv, tag=51)
        for q in (qe, qb):
            assert q.wait(timeout=30.0)
            q.check()
        np.testing.assert_array_equal(re.host, _data(small, prv, 4))
        np.testing.assert_array_equal(rb.host, _data(big, prv, 5))

    world.run(fn)


def test_queue_pair_accounting(world):
    count = 4096

    def fn(accl, rank):
        nxt, prv = (rank + 1) % NRANKS, (rank - 1) % NRANKS
        s = accl.create_buffer_like(_data(count, rank, 6))
        d = accl.create_buffer(count, np.float32)
        req = accl.send(s, count, nxt, tag=60, run_async=True)
        accl.recv(d, count, prv, tag=60)
        assert req.wait(timeout=30.0)
        req.check()

    world.run(fn)
    # every rank posted exactly one WRITE to its right neighbor on this
    # route, and SQ/CQ balance (no lost completions)
    for r in range(NRANKS):
        dump = world.dump_qps(r)
        assert f"queue pairs (rank {r})" in dump
        lines = [ln for ln in dump.splitlines() if "->" in ln]
        assert len(lines) == NRANKS
        for ln in lines:
            sq = int(ln.split("sq=")[1].split()[0])
            cq = int(ln.split("cq=")[1].split()[0])
            assert sq == cq, f"rank {r}: unbalanced SQ/CQ: {ln}"
        nxt_line = lines[(r + 1) % NRANKS]
        assert "bytes=" in nxt_line
        assert int(nxt_line.split("sq=")[1].split()[0]) >= 1
