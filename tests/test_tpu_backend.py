"""Driver-parity tests against the TPU backend (XLA collectives over a
mesh; virtual 8-device CPU platform in CI).

Same corpus shape as the emulator tests: the per-rank ACCL driver API is
identical, so user code moves between the emulator and the TPU backend
by swapping the world object (SURVEY §4: one suite, every rung)."""
import numpy as np
import pytest

from accl_tpu import DataType, ReduceFunction
from accl_tpu.backends.tpu import TpuWorld

NRANKS = 4
COUNT = 64


@pytest.fixture(scope="module")
def world():
    with TpuWorld(NRANKS) as w:
        yield w


def _data(count, rank, salt=0):
    rng = np.random.default_rng(500 + rank + salt * 131)
    return rng.standard_normal(count).astype(np.float32)


def test_copy_combine(world):
    def fn(accl, rank):
        src = accl.create_buffer_like(_data(COUNT, rank))
        dst = accl.create_buffer(COUNT, np.float32)
        accl.copy(src, dst, COUNT)
        np.testing.assert_array_equal(dst.host, _data(COUNT, rank))
        op1 = accl.create_buffer_like(_data(COUNT, rank, salt=1))
        res = accl.create_buffer(COUNT, np.float32)
        accl.combine(COUNT, ReduceFunction.SUM, src, op1, res)
        np.testing.assert_allclose(
            res.host, _data(COUNT, rank) + _data(COUNT, rank, salt=1),
            rtol=1e-6)

    world.run(fn)


def test_sendrecv(world):
    def fn(accl, rank):
        nxt, prv = (rank + 1) % NRANKS, (rank - 1) % NRANKS
        src = accl.create_buffer_like(_data(COUNT, rank))
        dst = accl.create_buffer(COUNT, np.float32)
        sreq = accl.send(src, COUNT, nxt, tag=3, run_async=True)
        accl.recv(dst, COUNT, prv, tag=3)
        assert sreq.wait(30)
        sreq.check()
        np.testing.assert_array_equal(dst.host, _data(COUNT, prv))

    world.run(fn)


def test_sendrecv_tag_any(world):
    # tagged send + wildcard recv must pair (rxpool seek semantics,
    # reference rxbuf_seek.cpp:19-78) — this used to deadlock on the
    # TPU backend because the gang key baked in the exact tag
    from accl_tpu.constants import TAG_ANY

    def fn(accl, rank):
        nxt, prv = (rank + 1) % NRANKS, (rank - 1) % NRANKS
        src = accl.create_buffer_like(_data(COUNT, rank, salt=11))
        dst = accl.create_buffer(COUNT, np.float32)
        sreq = accl.send(src, COUNT, nxt, tag=42, run_async=True)
        accl.recv(dst, COUNT, prv, tag=TAG_ANY)
        assert sreq.wait(30)
        sreq.check()
        np.testing.assert_array_equal(dst.host, _data(COUNT, prv, salt=11))

    world.run(fn)


def test_sendrecv_mixed_tag_ordering(world):
    # the per-src sequence counter is shared across tags (rxpool.hpp
    # seqn discipline; reference dma_mover.cpp:579-611): in-order tagged
    # recvs match their sends, and a wildcard drains whatever is oldest
    from accl_tpu.constants import TAG_ANY

    def fn(accl, rank):
        nxt, prv = (rank + 1) % NRANKS, (rank - 1) % NRANKS
        a = accl.create_buffer_like(_data(COUNT, rank, salt=21))
        b = accl.create_buffer_like(_data(COUNT, rank, salt=22))
        ra = accl.send(a, COUNT, nxt, tag=5, run_async=True)
        rb = accl.send(b, COUNT, nxt, tag=7, run_async=True)
        d5 = accl.create_buffer(COUNT, np.float32)
        dany = accl.create_buffer(COUNT, np.float32)
        accl.recv(d5, COUNT, prv, tag=5)
        accl.recv(dany, COUNT, prv, tag=TAG_ANY)  # drains the tag-7 send
        for r in (ra, rb):
            assert r.wait(30)
            r.check()
        np.testing.assert_array_equal(d5.host, _data(COUNT, prv, salt=21))
        np.testing.assert_array_equal(dany.host, _data(COUNT, prv, salt=22))

    world.run(fn)


def test_sendrecv_tag_mismatch_is_seq_error(world):
    # a recv whose tag does not match the head-of-stream send is a
    # sequence-discipline violation, SAME retcode as the emulator rung
    # classifies after its seek times out (PACK_SEQ_NUMBER_ERROR) — the
    # stream may not be reordered by tag
    from accl_tpu.constants import ACCLError, ErrorCode, TAG_ANY

    def fn(accl, rank):
        nxt, prv = (rank + 1) % NRANKS, (rank - 1) % NRANKS
        a = accl.create_buffer_like(_data(COUNT, rank, salt=31))
        ra = accl.send(a, COUNT, nxt, tag=5, run_async=True)
        bad = accl.create_buffer(COUNT, np.float32)
        with pytest.raises(ACCLError) as ei:
            accl.recv(bad, COUNT, prv, tag=9)
        assert ei.value.code & int(ErrorCode.PACK_SEQ_NUMBER_ERROR)
        # the mismatched send stays queued — a wildcard recv drains it
        dany = accl.create_buffer(COUNT, np.float32)
        accl.recv(dany, COUNT, prv, tag=TAG_ANY)
        assert ra.wait(30)
        ra.check()
        np.testing.assert_array_equal(dany.host, _data(COUNT, prv, salt=31))

    world.run(fn)


@pytest.mark.parametrize("root", [0, 2])
def test_bcast(world, root):
    def fn(accl, rank):
        buf = accl.create_buffer_like(_data(COUNT, rank, salt=root))
        accl.bcast(buf, COUNT, root)
        np.testing.assert_array_equal(buf.host, _data(COUNT, root, salt=root))

    world.run(fn)


def test_scatter_gather(world):
    root = 1

    def fn(accl, rank):
        send = accl.create_buffer_like(_data(COUNT * NRANKS, rank, salt=7))
        recv = accl.create_buffer(COUNT, np.float32)
        accl.scatter(send, recv, COUNT, root)
        exp = _data(COUNT * NRANKS, root, salt=7)
        np.testing.assert_array_equal(recv.host,
                                      exp[rank * COUNT:(rank + 1) * COUNT])
        back = accl.create_buffer(COUNT * NRANKS, np.float32)
        accl.gather(recv, back, COUNT, root)
        if rank == root:
            np.testing.assert_array_equal(back.host, exp)

    world.run(fn)


def test_allgather(world):
    def fn(accl, rank):
        send = accl.create_buffer_like(_data(COUNT, rank))
        recv = accl.create_buffer(COUNT * NRANKS, np.float32)
        accl.allgather(send, recv, COUNT)
        exp = np.concatenate([_data(COUNT, r) for r in range(NRANKS)])
        np.testing.assert_array_equal(recv.host, exp)

    world.run(fn)


@pytest.mark.parametrize("func", [ReduceFunction.SUM, ReduceFunction.MAX])
def test_reduce(world, func):
    root = 1

    def fn(accl, rank):
        send = accl.create_buffer_like(_data(COUNT, rank))
        recv = accl.create_buffer(COUNT, np.float32)
        accl.reduce(send, recv, COUNT, root, func)
        if rank == root:
            inputs = [_data(COUNT, r) for r in range(NRANKS)]
            exp = (np.sum(inputs, axis=0) if func == ReduceFunction.SUM
                   else np.max(inputs, axis=0))
            np.testing.assert_allclose(recv.host, exp, rtol=1e-5, atol=1e-5)

    world.run(fn)


def test_allreduce(world):
    def fn(accl, rank):
        send = accl.create_buffer_like(_data(COUNT, rank))
        recv = accl.create_buffer(COUNT, np.float32)
        accl.allreduce(send, recv, COUNT, ReduceFunction.SUM)
        exp = np.sum([_data(COUNT, r) for r in range(NRANKS)], axis=0)
        np.testing.assert_allclose(recv.host, exp, rtol=1e-5, atol=1e-5)

    world.run(fn)


def test_reduce_scatter(world):
    def fn(accl, rank):
        send = accl.create_buffer_like(_data(COUNT * NRANKS, rank))
        recv = accl.create_buffer(COUNT, np.float32)
        accl.reduce_scatter(send, recv, COUNT, ReduceFunction.SUM)
        inputs = [_data(COUNT * NRANKS, r) for r in range(NRANKS)]
        exp = np.sum(inputs, axis=0)[rank * COUNT:(rank + 1) * COUNT]
        np.testing.assert_allclose(recv.host, exp, rtol=1e-5, atol=1e-5)

    world.run(fn)


def test_alltoall(world):
    def fn(accl, rank):
        send = accl.create_buffer_like(_data(COUNT * NRANKS, rank))
        recv = accl.create_buffer(COUNT * NRANKS, np.float32)
        accl.alltoall(send, recv, COUNT)
        exp = np.concatenate([
            _data(COUNT * NRANKS, r)[rank * COUNT:(rank + 1) * COUNT]
            for r in range(NRANKS)
        ])
        np.testing.assert_array_equal(recv.host, exp)

    world.run(fn)


def test_barrier(world):
    def fn(accl, rank):
        accl.barrier()

    world.run(fn)


def test_allreduce_compressed(world):
    def fn(accl, rank):
        send = accl.create_buffer_like(_data(COUNT, rank))
        recv = accl.create_buffer(COUNT, np.float32)
        accl.allreduce(send, recv, COUNT, ReduceFunction.SUM,
                       compress_dtype=DataType.float16)
        exp = np.sum([_data(COUNT, r) for r in range(NRANKS)], axis=0)
        np.testing.assert_allclose(recv.host, exp, rtol=5e-2, atol=5e-2)

    world.run(fn)


def test_stream_put(world):
    strm = 9

    def fn(accl, rank):
        if rank == 0:
            src = accl.create_buffer_like(_data(COUNT, 0, salt=3))
            accl.stream_put(src, COUNT, dst=2, stream_id=strm)
        elif rank == 2:
            raw = accl.device.pop_stream(strm, COUNT * 4, timeout_s=30)
            assert raw is not None
            np.testing.assert_array_equal(
                np.frombuffer(raw, dtype=np.float32), _data(COUNT, 0, salt=3))

    world.run(fn)


def test_copy_to_and_from_stream(world):
    # local mem<->kernel-stream copies (reference copy_to_stream /
    # copy_from_stream, accl.cpp:310 family) — same semantics as the
    # emulator rung
    def fn(accl, rank):
        data = _data(COUNT, rank, salt=41)
        src = accl.create_buffer_like(data)
        accl.copy_to_stream(src, COUNT, stream_id=9)
        raw = accl.device.pop_stream(9, COUNT * 4, timeout_s=30)
        assert raw is not None
        np.testing.assert_array_equal(
            np.frombuffer(raw, dtype=np.float32), data)
        accl.device.push_krnl(data * 2)
        dst = accl.create_buffer(COUNT, np.float32)
        accl.copy_from_stream(dst, COUNT)
        np.testing.assert_array_equal(dst.host, data * 2)

    world.run(fn)


def test_reduce_mem_stream_variants(world):
    # rooted reduce with stream-side operand/result (reference mem<->
    # stream reduce tests, test.cpp:813-910) over the gang path
    root = 1

    def fn(accl, rank):
        from accl_tpu.constants import StreamFlags

        data = _data(COUNT, rank, salt=43)
        # stream -> mem: every member feeds its operand via the kernel
        # queue; the root's result lands in a buffer
        accl.device.push_krnl(data)
        recv = accl.create_buffer(COUNT, np.float32)
        accl.reduce(None, recv, COUNT, root, ReduceFunction.SUM,
                    stream_flags=StreamFlags.OP0_STREAM)
        want = sum(_data(COUNT, r, salt=43) for r in range(NRANKS))
        if rank == root:
            np.testing.assert_allclose(recv.host, want, rtol=1e-5)
        # mem -> stream: operands from buffers, root's result to its
        # local kernel stream
        send = accl.create_buffer_like(data)
        accl.reduce(send, None, COUNT, root, ReduceFunction.SUM,
                    stream_flags=StreamFlags.RES_STREAM, stream_id=11)
        if rank == root:
            raw = accl.device.pop_stream(11, COUNT * 4, timeout_s=30)
            assert raw is not None
            np.testing.assert_allclose(
                np.frombuffer(raw, dtype=np.float32), want, rtol=1e-5)

    world.run(fn)


def test_sub_communicator(world):
    # split {0, 2} and allreduce inside it (reference: test_multicomm)
    members = [0, 2]

    def fn(accl, rank):
        if rank not in members:
            return
        cid = accl.create_communicator(members)
        send = accl.create_buffer_like(_data(COUNT, rank, salt=9))
        recv = accl.create_buffer(COUNT, np.float32)
        accl.allreduce(send, recv, COUNT, ReduceFunction.SUM, comm_id=cid)
        exp = np.sum([_data(COUNT, m, salt=9) for m in members], axis=0)
        np.testing.assert_allclose(recv.host, exp, rtol=1e-5, atol=1e-5)

    world.run(fn)


@pytest.mark.parametrize("dtype", [np.int32])
def test_allreduce_dtypes(world, dtype):
    # dtype coverage on the XLA path (reference arith configs).  float64
    # is exercised on the emulator rung only: TPUs have no f64 units and
    # jax downcasts without the global x64 flag — the native engine's
    # arith lanes keep the reference's full f64 semantics
    # (tests/test_emu_collectives.py::test_allreduce_dtypes)
    def gen(rank):
        return np.random.default_rng(40 + rank).integers(
            -50, 50, COUNT).astype(dtype)

    def fn(accl, rank):
        send = accl.create_buffer_like(gen(rank))
        recv = accl.create_buffer(COUNT, dtype)
        accl.allreduce(send, recv, COUNT, ReduceFunction.SUM)
        return recv.host.copy()

    outs = world.run(fn)
    exp = np.sum([gen(r) for r in range(NRANKS)], axis=0)
    for got in outs:
        np.testing.assert_array_equal(got, exp)


def test_duration_counter(world):
    # per-call perf counter surfaces through the XLA backend too
    # (reference: test_perf_counter :1010)
    def fn(accl, rank):
        send = accl.create_buffer_like(_data(COUNT, rank, salt=13))
        recv = accl.create_buffer(COUNT, np.float32)
        req = accl.allreduce(send, recv, COUNT)
        assert accl.get_duration(req) > 0

    world.run(fn)


@pytest.mark.parametrize("nranks", [2, 3, 5, 6])
def test_tree_schedules_odd_world_sizes(nranks):
    # the binomial ppermute trees (bcast/gather) and the masked
    # psum_scatter (scatter) must be correct for non-power-of-2 worlds
    # and every root
    with TpuWorld(nranks) as w:
        def fn(accl, rank):
            for root in range(nranks):
                # bcast
                if rank == root:
                    b = accl.create_buffer_like(_data(COUNT, root, salt=31))
                else:
                    b = accl.create_buffer(COUNT, np.float32)
                accl.bcast(b, COUNT, root=root)
                np.testing.assert_allclose(
                    b.host, _data(COUNT, root, salt=31), rtol=1e-6)
                # scatter + gather round trip
                send = accl.create_buffer_like(
                    _data(COUNT * nranks, rank, salt=32))
                part = accl.create_buffer(COUNT, np.float32)
                accl.scatter(send, part, COUNT, root=root)
                exp = _data(COUNT * nranks, root, salt=32)
                np.testing.assert_allclose(
                    part.host, exp[rank * COUNT:(rank + 1) * COUNT],
                    rtol=1e-6)
                back = accl.create_buffer(COUNT * nranks, np.float32)
                accl.gather(part, back, COUNT, root=root)
                if rank == root:
                    np.testing.assert_allclose(back.host, exp, rtol=1e-6)

        w.run(fn)


def test_ring_path_forced_on_driver_corpus():
    # the rendezvous-analog large-message path: with the threshold at 0,
    # every eligible collective rides the segmented Pallas ring kernels
    # inside the gang program — results must match the XLA path exactly
    with TpuWorld(4) as w:
        w.engine.ring_threshold_bytes = 0

        def fn(accl, rank):
            n = 300  # odd size: exercises ragged segmentation too
            # allreduce (sum + max)
            send = accl.create_buffer_like(_data(n, rank, salt=41))
            recv = accl.create_buffer(n, np.float32)
            accl.allreduce(send, recv, n)
            exp = np.sum([_data(n, r, salt=41) for r in range(4)], axis=0)
            np.testing.assert_allclose(recv.host, exp, rtol=1e-4, atol=1e-5)
            accl.allreduce(send, recv, n, function=ReduceFunction.MAX)
            expm = np.max([_data(n, r, salt=41) for r in range(4)], axis=0)
            np.testing.assert_allclose(recv.host, expm, rtol=1e-4, atol=1e-5)
            # allgather
            ag = accl.create_buffer(n * 4, np.float32)
            accl.allgather(send, ag, n)
            expg = np.concatenate([_data(n, r, salt=41) for r in range(4)])
            np.testing.assert_allclose(ag.host, expg, rtol=1e-6)
            # reduce_scatter
            big = accl.create_buffer_like(_data(n * 4, rank, salt=42))
            part = accl.create_buffer(n, np.float32)
            accl.reduce_scatter(big, part, n)
            inputs = [_data(n * 4, r, salt=42) for r in range(4)]
            exps = np.sum(inputs, axis=0)[rank * n:(rank + 1) * n]
            np.testing.assert_allclose(part.host, exps, rtol=1e-4, atol=1e-5)

        w.run(fn)


def test_driver_allreduce_close_to_raw_psum():
    # the device-resident call path must not be orders of magnitude off
    # a bare jitted psum on the same mesh (VERDICT r1: no host
    # round-trips, compile-once).  The bound is loose because the gang
    # assembly is Python-threaded and this box has one CPU core; the
    # structural property it guards is "no per-call host staging or
    # retrace" (those blow the ratio to 50-100x).
    import time

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = 1 << 18  # 1 MiB fp32 per rank
    with TpuWorld(NRANKS) as w:
        mesh = w.engine._mesh_for(tuple(range(NRANKS)))

        raw = jax.jit(jax.shard_map(
            lambda x: jax.lax.psum(x, "rank"),
            mesh=mesh, in_specs=P("rank", None), out_specs=P("rank", None)))
        xs = jax.device_put(
            np.zeros((NRANKS, n), np.float32),
            NamedSharding(mesh, P("rank", None)))
        jax.block_until_ready(raw(xs))

        def measure_raw():
            # best-of: a capability estimator, like bench.py — a single
            # scheduler hiccup on this 1-core box must not fail the guard
            best = None
            for _ in range(5):
                t0 = time.perf_counter()
                jax.block_until_ready(raw(xs))
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            return best

        def fn(accl, rank):
            send = accl.create_buffer_like(np.zeros(n, np.float32))
            recv = accl.create_buffer(n, np.float32)
            send.sync_to_device()
            # zero-copy call path (reference accl.cpp:796-839): device-
            # resident operands, no host staging per call
            accl.allreduce(send, recv, n, from_fpga=True, to_fpga=True)
            best = None
            for _ in range(5):
                t0 = time.perf_counter()
                accl.allreduce(send, recv, n, from_fpga=True, to_fpga=True)
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            return best

        on_tpu = jax.default_backend() not in ("cpu",)
        # 2x is the hardware target (asserted when running on real TPU);
        # the CPU virtual-device rung gets single-digit headroom for the
        # Python gang scheduler sharing one core with the XLA runtime —
        # a reintroduced per-call host round-trip or retrace blows this
        # to 50-100x, which is the regression this guards
        bound = 2.0 if on_tpu else 10.0
        # best ratio across attempts: the guard targets a STRUCTURAL
        # regression (50-100x, fails every attempt); a starved thread on
        # a loaded 1-core CI box spoils single attempts ~30% of the time
        ratio, best_pair = None, (0.0, 0.0)
        for _attempt in range(3):
            raw_dt = measure_raw()
            drv_dt = max(w.run(fn))
            r = drv_dt / max(raw_dt, 1e-9)
            if ratio is None or r < ratio:
                ratio, best_pair = r, (drv_dt, raw_dt)
            if ratio < bound:
                break
    assert ratio < bound, \
        f"driver allreduce {best_pair[0]:.4f}s vs raw psum " \
        f"{best_pair[1]:.4f}s (best ratio {ratio:.1f}x, bound {bound}x)"


def test_async_window_batches_and_raw_guard():
    """The batched gang executor: (a) independent same-program gangs
    submitted through an async window actually FUSE into batched
    dispatches; (b) a data-DEPENDENT chain (gang N+1 reads gang N's
    result buffer) is never fused — the RAW guard must order it after
    the rebind; numerics prove it saw the reduced value, not the
    pre-state."""
    from collections import Counter

    from accl_tpu.backends.tpu import TpuEngine, TpuWorld

    sizes = Counter()
    orig_batch = TpuEngine._exec_gang_batch

    def spy(self, items):
        sizes[len(items)] += 1
        return orig_batch(self, items)

    TpuEngine._exec_gang_batch = spy
    try:
        with TpuWorld(4) as w:
            def worker(accl, rank):
                n = 128
                s = accl.create_buffer_like(
                    np.full(n, float(rank + 1), np.float32))
                # resident calls treat DEVICE data as authoritative
                # (reference from_fpga semantics) — stage it explicitly
                s.sync_to_device()
                r = accl.create_buffer(n, np.float32)
                t = accl.create_buffer(n, np.float32)
                # (b) dependent chain: r = sum(s); t = sum(r) — the
                # second reads the first's result buffer
                for _ in range(4):
                    q1 = accl.allreduce(s, r, n, ReduceFunction.SUM,
                                        from_fpga=True, to_fpga=True,
                                        run_async=True)
                    q2 = accl.allreduce(r, t, n, ReduceFunction.SUM,
                                        from_fpga=True, to_fpga=True,
                                        run_async=True)
                    q1.wait(); q2.wait()
                t.sync_from_device()
                # sum over ranks of s = 1+2+3+4 = 10; second hop: 4*10
                np.testing.assert_allclose(t.host, 40.0)
                # (a) independent window: same descriptor repeated —
                # operand s is never written, so every gang is fusable
                reqs = [accl.allreduce(s, r, n, ReduceFunction.SUM,
                                       from_fpga=True, to_fpga=True,
                                       run_async=True)
                        for _ in range(16)]
                for q in reqs:
                    q.wait()
                r.sync_from_device()
                np.testing.assert_allclose(r.host, 10.0)
                return True

            assert all(w.run(worker))
    finally:
        TpuEngine._exec_gang_batch = orig_batch
    # batches must have formed in the independent window phase
    assert sum(k * v for k, v in sizes.items()) > 0, sizes
    # and no batch may have fused the dependent chain: whenever a
    # fused batch ran, its members were the INDEPENDENT repeats whose
    # numerics above came out right — the chain assertions are the
    # real guard; this records that fusion engaged at all
    assert max(sizes) >= 2, sizes


def test_gang_executor_error_isolation():
    """A failing compiled collective must error-complete every request
    of ITS gang (retcode surfaces via ACCLError) without killing the
    executor thread — the next collective on the same world succeeds."""
    from accl_tpu.constants import ACCLError
    from accl_tpu.backends.tpu import TpuWorld

    with TpuWorld(2) as w:
        boom = {"armed": False}
        orig_run = type(w.engine)._run_collective

        def sabotaged(self, op, comm_id, gang):
            if boom["armed"]:
                boom["armed"] = False
                raise RuntimeError("injected dispatch failure")
            return orig_run(self, op, comm_id, gang)

        type(w.engine)._run_collective = sabotaged
        try:
            def worker(accl, rank):
                n = 64
                s = accl.create_buffer_like(np.ones(n, np.float32))
                r = accl.create_buffer(n, np.float32)
                if rank == 0:
                    boom["armed"] = True
                got_err = False
                try:
                    accl.allreduce(s, r, n, ReduceFunction.SUM)
                except ACCLError:
                    got_err = True
                # the engine must still be alive: a fresh call works
                accl.allreduce(s, r, n, ReduceFunction.SUM)
                np.testing.assert_allclose(r.host, 2.0)
                return got_err

            errs = w.run(worker)
            # the sabotaged gang completed as an error on every member
            assert all(errs), errs
        finally:
            type(w.engine)._run_collective = orig_run


def test_ring_path_gangs_never_batch():
    """Ring-path (Pallas) collectives must dispatch alone: fusing two
    instances into one compiled program would alias their fixed
    collective_ids (barrier/ACK semaphores) — r5 review finding."""
    from collections import Counter

    from accl_tpu.backends.tpu import TpuEngine, TpuWorld

    sizes = Counter()
    orig_batch = TpuEngine._exec_gang_batch

    def spy(self, items):
        for _op, _c, _g, plan in items:
            assert not plan["fn_args"][-1], "ring gang entered a batch"
        sizes[len(items)] += 1
        return orig_batch(self, items)

    TpuEngine._exec_gang_batch = spy
    try:
        # force EVERY payload onto the ring path
        import os
        prior = os.environ.get("ACCL_RING_THRESHOLD")
        os.environ["ACCL_RING_THRESHOLD"] = "0"
        try:
            with TpuWorld(4) as w:
                assert w.engine.ring_threshold_bytes == 0

                def worker(accl, rank):
                    n = 256
                    s = accl.create_buffer_like(
                        np.full(n, float(rank + 1), np.float32))
                    s.sync_to_device()
                    r = accl.create_buffer(n, np.float32)
                    reqs = [accl.allreduce(s, r, n, ReduceFunction.SUM,
                                           from_fpga=True, to_fpga=True,
                                           run_async=True)
                            for _ in range(6)]
                    for q in reqs:
                        assert q.wait(120)
                        q.check()
                    r.sync_from_device()
                    np.testing.assert_allclose(r.host, 10.0)
                    return True

                assert all(w.run(worker))
        finally:
            if prior is None:
                del os.environ["ACCL_RING_THRESHOLD"]
            else:
                os.environ["ACCL_RING_THRESHOLD"] = prior
    finally:
        TpuEngine._exec_gang_batch = orig_batch
    # every dispatch was singular (the spy asserts no ring in batches;
    # with only ring gangs in flight no batch may have formed at all)
    assert not sizes, sizes


def test_leader_dispatch_carries_the_sync_lane():
    """Blocking (sync-resident) gangs must take the leader-dispatch
    fast path: with no async traffic in flight the engine is idle at
    every gang completion, so the last-arriving rank executes inline —
    zero executor hand-offs.  Deterministic: the stats counters have
    exactly one writer per lane."""
    with TpuWorld(4) as w:
        def worker(accl, rank):
            n = 128
            s = accl.create_buffer_like(
                np.full(n, float(rank + 1), np.float32))
            s.sync_to_device()
            r = accl.create_buffer(n, np.float32)
            accl.allreduce(s, r, n, ReduceFunction.SUM,
                           from_fpga=True, to_fpga=True)  # warm plan
            return True

        assert all(w.run(worker))
        before = dict(w.engine.stats)

        M = 10
        bufs = {}

        def measured(accl, rank):
            n = 128
            s = accl.create_buffer_like(
                np.full(n, float(rank + 1), np.float32))
            s.sync_to_device()
            r = accl.create_buffer(n, np.float32)
            bufs[rank] = r
            for _ in range(M):
                accl.allreduce(s, r, n, ReduceFunction.SUM,
                               from_fpga=True, to_fpga=True)
            r.sync_from_device()
            np.testing.assert_allclose(r.host, 10.0)
            return True

        assert all(w.run(measured))
        after = dict(w.engine.stats)
    assert after["leader_dispatches"] - before["leader_dispatches"] == M
    assert after["executor_dispatches"] == before["executor_dispatches"]
    assert after["batches"] == before["batches"]


def test_leader_dispatch_mixed_sync_async_interleaving():
    """Correctness under mixed lanes: an async gang posted immediately
    before a blocking gang that READS its result buffer must still
    execute first (the blocking gang falls back to the executor queue
    whenever the engine is busy; inline execution only claims an IDLE
    engine, so the two lanes never reorder or overlap dispatches)."""
    with TpuWorld(4) as w:
        def worker(accl, rank):
            n = 128
            s = accl.create_buffer_like(
                np.full(n, float(rank + 1), np.float32))
            s.sync_to_device()
            r = accl.create_buffer(n, np.float32)
            t = accl.create_buffer(n, np.float32)
            for _ in range(6):
                # async hop writes r; the BLOCKING hop reads r — its
                # numerics prove it saw the reduced value, not pre-state
                q1 = accl.allreduce(s, r, n, ReduceFunction.SUM,
                                    from_fpga=True, to_fpga=True,
                                    run_async=True)
                accl.allreduce(r, t, n, ReduceFunction.SUM,
                               from_fpga=True, to_fpga=True)
                q1.wait(); q1.check()
                t.sync_from_device()
                np.testing.assert_allclose(t.host, 40.0)
            # drained engine: blocking calls now find it idle, so the
            # fast path re-engages the moment the async pressure stops
            for _ in range(2):
                accl.allreduce(s, r, n, ReduceFunction.SUM,
                               from_fpga=True, to_fpga=True)
            return True

        assert all(w.run(worker))
        stats = dict(w.engine.stats)
    # the mixed phase rode the executor (an async gang is pending at
    # every blocking completion, so inline never claims a busy engine);
    # the drained pure-sync tail took the leader lane
    assert stats["executor_dispatches"] > 0, stats
    assert stats["leader_dispatches"] > 0, stats


def test_raw_guard_keys_by_rank_and_address():
    """Symmetric per-rank allocators mint the SAME numeric addresses on
    every rank, so a raw-address RAW guard falsely aliases unrelated
    cross-rank buffers and terminates batches with no hazard (r5
    ADVICE).  The guard must key by (rank, address): only a same-rank
    overlap is a real read-after-write."""
    from collections import Counter

    from accl_tpu.backends.tpu import TpuEngine

    sizes = Counter()
    orig_batch = TpuEngine._exec_gang_batch

    def spy(self, items):
        sizes[len(items)] += 1
        return orig_batch(self, items)

    TpuEngine._exec_gang_batch = spy
    addrs: dict = {}
    try:
        with TpuWorld(2) as w:
            def worker(accl, rank):
                n = 64
                # allocation ORDER differs per rank, so rank0's res
                # address numerically equals rank1's operand address of
                # the OTHER chain (the false-alias premise)
                if rank == 0:
                    a, b, c, d = (accl.create_buffer(n, np.float32)
                                  for _ in range(4))
                else:
                    a, c, b, d = (accl.create_buffer(n, np.float32)
                                  for _ in range(4))
                a.host[:] = float(rank + 1)
                c.host[:] = float(rank + 1) * 10
                a.sync_to_device(); c.sync_to_device()
                addrs[(rank, "a")] = a.address
                addrs[(rank, "b")] = b.address
                addrs[(rank, "c")] = c.address
                addrs[(rank, "d")] = d.address
                for _ in range(8):
                    q1 = accl.allreduce(a, b, n, ReduceFunction.SUM,
                                        from_fpga=True, to_fpga=True,
                                        run_async=True)
                    q2 = accl.allreduce(c, d, n, ReduceFunction.SUM,
                                        from_fpga=True, to_fpga=True,
                                        run_async=True)
                    q1.wait(); q2.wait()
                b.sync_from_device(); d.sync_from_device()
                np.testing.assert_allclose(b.host, 3.0)
                np.testing.assert_allclose(d.host, 30.0)
                return True

            assert all(w.run(worker))

            plans = list(w.engine._gang_plans.values())
            assert len(plans) == 2
            p_ab = next(p for p in plans
                        if (0, addrs[(0, "a")]) in p["opnd_addrs"])
            p_cd = next(p for p in plans
                        if (0, addrs[(0, "c")]) in p["opnd_addrs"])
            # premise: the raw addresses DO alias across ranks ...
            raw_res = {ad for _g, ad in p_ab["res_addrs"]}
            raw_opnd = {ad for _g, ad in p_cd["opnd_addrs"]}
            assert raw_res & raw_opnd, (raw_res, raw_opnd)
            # ... but the (rank, address) guard sets are disjoint, so
            # the a->b / c->d chains stay batchable
            assert not (p_ab["res_addrs"] & p_cd["opnd_addrs"])
    finally:
        TpuEngine._exec_gang_batch = orig_batch
    # behavioral evidence on top of the structural check: fused batches
    # actually formed across the two falsely-aliasing chains
    assert max(sizes, default=1) >= 2, sizes


def test_profile_sync_disables_batching():
    """ACCL_PROFILE_SYNC=1 promises get_duration is THAT call's
    on-device perf-counter reading; a fused batch can only report an
    averaged share, so the exact mode must dispatch every gang alone
    (r5 ADVICE)."""
    import os

    from accl_tpu.backends.tpu import TpuEngine

    calls = []
    orig_batch = TpuEngine._exec_gang_batch

    def spy(self, items):
        calls.append(len(items))
        return orig_batch(self, items)

    TpuEngine._exec_gang_batch = spy
    os.environ["ACCL_PROFILE_SYNC"] = "1"
    try:
        with TpuWorld(4) as w:
            assert w.engine.profile_sync

            def worker(accl, rank):
                n = 128
                s = accl.create_buffer_like(
                    np.full(n, float(rank + 1), np.float32))
                s.sync_to_device()
                r = accl.create_buffer(n, np.float32)
                reqs = [accl.allreduce(s, r, n, ReduceFunction.SUM,
                                       from_fpga=True, to_fpga=True,
                                       run_async=True)
                        for _ in range(16)]
                for q in reqs:
                    assert q.wait(120)
                    q.check()
                    # blocking perf-counter mode: a real duration lands
                    assert q.duration_ns > 0.0
                r.sync_from_device()
                np.testing.assert_allclose(r.host, 10.0)
                return True

            assert all(w.run(worker))
            assert w.engine.stats["batches"] == 0
    finally:
        del os.environ["ACCL_PROFILE_SYNC"]
        TpuEngine._exec_gang_batch = orig_batch
    assert not calls, calls


def test_callrate_sync_lane_not_slower_than_async():
    """Leader dispatch must put the blocking lane's per-call overhead
    at (or below) the async lane's: the sync path saves the executor
    hop and the leader's own completion wakeup, while the async path
    amortizes via batching.  Loose margin — this is a smoke test of
    the MECHANISM on a shared CI box, the real numbers live in
    accl_tpu.bench.callrate; the structural stats assertion is the
    deterministic part."""
    import time

    with TpuWorld(4) as w:
        bufs = {}

        def setup(accl, rank):
            n = 256
            s = accl.create_buffer_like(
                np.full(n, float(rank + 1), np.float32))
            s.sync_to_device()
            r = accl.create_buffer(n, np.float32)
            bufs[rank] = (s, r)
            for _ in range(3):
                accl.allreduce(s, r, n, ReduceFunction.SUM,
                               from_fpga=True, to_fpga=True)
            return True

        assert all(w.run(setup))
        si = 30

        def sync_lane(accl, rank):
            s, r = bufs[rank]
            t0 = time.perf_counter()
            for _ in range(si):
                accl.allreduce(s, r, 256, ReduceFunction.SUM,
                               from_fpga=True, to_fpga=True)
            return time.perf_counter() - t0

        def async_lane(accl, rank):
            s, r = bufs[rank]
            window = []
            t0 = time.perf_counter()
            for _ in range(si):
                window.append(accl.allreduce(
                    s, r, 256, ReduceFunction.SUM, from_fpga=True,
                    to_fpga=True, run_async=True))
                if len(window) >= 8:
                    window.pop(0).wait()
            for q in window:
                q.wait()
            return time.perf_counter() - t0

        before = dict(w.engine.stats)
        rounds = 0
        ok = False
        best = (None, None)
        while rounds < 6 and not ok:
            # interleaved same-window pair per round; ANY round where
            # the sync lane lands within the margin proves the
            # mechanism (a loaded CI box can starve the 4 blocking
            # threads arbitrarily in individual rounds — the REGRESSION
            # this guards, the pre-leader 2.6x-of-async regime, fails
            # every round)
            rounds += 1
            dt_s = max(w.run(sync_lane))
            dt_a = max(w.run(async_lane))
            if best[0] is None or dt_s / dt_a < best[0] / best[1]:
                best = (dt_s, dt_a)
            ok = dt_s <= dt_a * 2.0 + 0.05
        after = dict(w.engine.stats)

    # deterministic: every blocking call of the sync slices ran inline
    assert (after["leader_dispatches"] - before["leader_dispatches"]
            == rounds * si)
    # smoke: in at least one same-window round the sync lane is in the
    # async lane's ballpark, not the old rendezvous regime
    assert ok, (f"sync never within 2x of async over {rounds} rounds; "
                f"best pair sync {best[0]:.4f}s vs async {best[1]:.4f}s")


def test_leader_dispatch_runs_outside_the_submission_lock():
    """The inline gang run is deferred to the leader's Request.wait:
    submit() holds the rank's RequestQueue lock, and executing the
    device program there would stall a concurrent submission on the
    same handle for the whole dispatch (posted-descriptor calls promise
    to return immediately).  During a leader dispatch every rank's
    submission lock must therefore be FREE."""
    from accl_tpu.backends.tpu import TpuEngine

    held: list = []
    orig_exec = TpuEngine._exec_gang
    accls: list = []

    def spy(self, scenario, comm_id, gang):
        for a in accls:
            got = a._queue._lock.acquire(blocking=False)
            if got:
                a._queue._lock.release()
            else:
                held.append(a.rank)
        return orig_exec(self, scenario, comm_id, gang)

    TpuEngine._exec_gang = spy
    try:
        with TpuWorld(2) as w:
            accls.extend(w.accls)

            def worker(accl, rank):
                n = 64
                s = accl.create_buffer_like(
                    np.full(n, float(rank + 1), np.float32))
                s.sync_to_device()
                r = accl.create_buffer(n, np.float32)
                for _ in range(4):
                    accl.allreduce(s, r, n, ReduceFunction.SUM,
                                   from_fpga=True, to_fpga=True)
                r.sync_from_device()
                np.testing.assert_allclose(r.host, 3.0)
                return True

            assert all(w.run(worker))
            assert w.engine.stats["leader_dispatches"] > 0
    finally:
        TpuEngine._exec_gang = orig_exec
    assert not held, f"submission lock held during dispatch by ranks {held}"


def test_elastic_state_sync_and_grow_rejoin():
    # r11 elastic membership on the TPU rung: sponsor-side state sync
    # (export_join_state), gang-table rebuild (partial gangs + cached
    # plans of a dead comm drained), and a grown communicator a
    # late-joining rank adopts after padding its comm-id space — the
    # same id-alignment discipline the emulator rung's wire protocol
    # enforces, collapsed to the in-process scheduler.
    import threading

    from accl_tpu import ACCLError
    from accl_tpu.communicator import Communicator, Rank
    from accl_tpu.constants import ErrorCode

    barrier = threading.Barrier(NRANKS, timeout=60)
    state = {}

    with TpuWorld(NRANKS) as world:
        def fn(accl, rank):
            # ranks 0-2 mint a sub-comm the late rank never saw
            if rank != 3:
                assert accl.create_communicator([0, 1, 2]) == 1
            barrier.wait()
            if rank == 1:
                # a PARTIAL gang on comm 1 (only this rank arrives)
                s = accl.create_buffer_like(_data(COUNT, rank))
                r = accl.create_buffer(COUNT, np.float32)
                state["partial"] = accl.allreduce(
                    s, r, COUNT, ReduceFunction.SUM, comm_id=1,
                    run_async=True)
            if rank == 2:
                # a PENDING p2p recv on comm 1 (nothing ever sent):
                # the rebuild must finalize its request too, not
                # silently evict it (the blocked waiter would
                # otherwise only wake at the driver budget)
                d = accl.create_buffer(COUNT, np.float32)
                state["precv"] = accl.recv(d, COUNT, 0, tag=77,
                                           comm_id=1, run_async=True)
            barrier.wait()
            if rank == 0:
                st = accl.device.export_join_state(1)
                assert st["comm_count"] == 2
                assert st["members"] == [0, 1, 2]
                # the rebuild drains the stale partial gang AND the
                # pending p2p recv
                assert accl.device.rebuild_gang_tables(1) >= 2
            barrier.wait()
            if rank == 1:
                req = state["partial"]
                assert req.wait(30)
                assert req.aborted
                with pytest.raises(ACCLError):
                    req.check()
            if rank == 2:
                req = state["precv"]
                assert req.wait(30)
                assert req.aborted
            if rank == 0:
                accl.abort(1, error=int(ErrorCode.RANK_FAILED))
                assert accl.device.export_join_state(1)["aborted"]
            barrier.wait()
            # grow comm 1 back to full size; rank 3 is the "joiner".
            # The joiner syncs + pads BEFORE any survivor's grow upload
            # bumps the shared scheduler's comm count — the same
            # sponsor-defers-until-synced ordering the emulator rung's
            # wire protocol enforces (here a barrier plays the ack).
            new_row = Rank(ip="127.0.0.1", port=0, session=3)
            if rank == 3:
                assert accl.device.join_sync(0) == 0
                assert accl.device.comm_count() == 2
                accl._pad_communicators(2)
                with pytest.raises(ACCLError, match="placeholder"):
                    accl.communicator(1)
            barrier.wait()
            if rank != 3:
                gid = accl.grow_communicator([new_row], comm_id=1,
                                             window_s=0.2)
            else:
                rows = [Rank(ip="127.0.0.1", port=0, session=i)
                        for i in range(3)] + [new_row]
                gid = accl._install_communicator(
                    Communicator(rows, 3, comm_id=2))
            assert gid == 2
            barrier.wait()
            s = accl.create_buffer_like(_data(COUNT, rank, salt=9))
            r = accl.create_buffer(COUNT, np.float32)
            accl.allreduce(s, r, COUNT, ReduceFunction.SUM, comm_id=gid)
            return r.host.copy()

        outs = world.run(fn)
        expected = np.sum([_data(COUNT, q, salt=9)
                           for q in range(NRANKS)], axis=0)
        for out in outs:
            np.testing.assert_allclose(out, expected, rtol=1e-5,
                                       atol=1e-5)
