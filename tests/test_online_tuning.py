"""Online tuner: the live telemetry -> tuner control plane (r19).

Pins the ISSUE-17 acceptance surface: ``ACCL_TUNE_ONLINE`` unset
constructs NOTHING (dispatch stays the r18 static/table behavior on
both backends), the armed loop closes finding -> hypothesis -> A/B ->
decision episodes against a live chaos-degraded world (never-slower by
construction), the post-install watch rejects stale same-batch
findings but auto-reverts a genuine post-install regression, per-cell
cooldown stops thrash, the sentinel's WORSEN_RATIO re-delivery feeds
the revert path without spamming persisting findings, the retune
counter families are schema'd, and the bounded audit ring round-trips
through the ``/retunes`` exporter endpoint.
"""
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from accl_tpu.backends.emu import EmuWorld
from accl_tpu.backends.tpu import TpuWorld
from accl_tpu.observability import health as obs_health
from accl_tpu.observability import metrics as _metrics
from accl_tpu.observability.sentinel import Baseline, Sentinel
from accl_tpu.resilience.chaos import ChaosPlan
from accl_tpu.tuning.autotune import SelectionTable, cell_key
from accl_tpu.tuning.online import (
    DECISIONS,
    HISTORY_FORMAT,
    HISTORY_VERSION,
    OnlineTuner,
    RetuneHistory,
    history_doc,
    online_enabled,
    online_tuner,
)


def _finding(coll="allreduce", dtype="float32", bucket="<=16KiB",
             axis="p50_us", ratio=2.0, kind="latency"):
    return {"collective": coll, "dtype": dtype, "size_bucket": bucket,
            "axis": axis, "ratio": ratio, "kind": kind,
            "live": 100.0, "baseline": 50.0, "threshold": 1.5,
            "baseline_source": "test"}


# ---------------------------------------------------------------------------
# the off switch: unset = nothing constructed, dispatch untouched
# ---------------------------------------------------------------------------

def test_online_enabled_parsing(monkeypatch):
    for off in (None, "", "0", " 0 "):
        if off is None:
            monkeypatch.delenv("ACCL_TUNE_ONLINE", raising=False)
        else:
            monkeypatch.setenv("ACCL_TUNE_ONLINE", off)
        assert not online_enabled()
    monkeypatch.setenv("ACCL_TUNE_ONLINE", "1")
    assert online_enabled()


@pytest.mark.parametrize("world_cls", [EmuWorld, TpuWorld],
                         ids=["emu", "tpu-interpret"])
def test_unset_env_constructs_nothing(monkeypatch, world_cls):
    """The bit-parity pin: without the env knob there is no tuner
    object, no loop thread, and no policy injected — the world is the
    r18 world."""
    monkeypatch.delenv("ACCL_TUNE_ONLINE", raising=False)
    with world_cls(2) as w:
        assert w.online_tuner is None
        assert online_tuner() is None
        assert all(getattr(a, "_tune_policy", None) is None
                   for a in w.accls)
        assert not any(t.name == "accl-online-tuner"
                       for t in threading.enumerate())
    doc = history_doc()
    assert doc == {"format": HISTORY_FORMAT, "version": HISTORY_VERSION,
                   "episodes": [], "dropped": 0, "total": 0}


def test_env_gate_arms_and_close_stops(monkeypatch):
    monkeypatch.setenv("ACCL_TUNE_ONLINE", "1")
    monkeypatch.setenv("ACCL_TUNE_ONLINE_INTERVAL_MS", "50")
    w = EmuWorld(2)
    try:
        tuner = w.online_tuner
        assert tuner is not None and online_tuner() is tuner
        assert any(t.name == "accl-online-tuner" and t.daemon
                   for t in threading.enumerate())
        # every driver serves the ONE shared table through its policy
        assert all(a._tune_policy.table is tuner.table
                   for a in w.accls)
    finally:
        w.close()
    assert online_tuner() is None
    time.sleep(0.2)
    assert not any(t.name == "accl-online-tuner"
                   for t in threading.enumerate())


# ---------------------------------------------------------------------------
# audit ring + counters schema
# ---------------------------------------------------------------------------

def test_history_ring_bounded_with_stable_seq():
    h = RetuneHistory(maxlen=3)
    for i in range(5):
        ep = h.append({"decision": "rejected", "i": i})
        assert ep["seq"] == i + 1
    doc = h.to_doc()
    assert doc["format"] == HISTORY_FORMAT
    assert doc["version"] == HISTORY_VERSION
    assert [e["i"] for e in doc["episodes"]] == [2, 3, 4]
    assert doc["dropped"] == 2 and doc["total"] == 5
    # seq survives the drop: the audit trail names evicted episodes
    assert [e["seq"] for e in doc["episodes"]] == [3, 4, 5]


def test_retune_counter_families_have_help():
    for fam in ("proposed", "verified", "installed", "rejected",
                "reverted"):
        assert f"accl_tuning_retunes_{fam}" in _metrics.METRIC_HELP


def test_retunes_endpoint_serves_history(monkeypatch):
    obs_health.stop_exporter()
    monkeypatch.setenv("ACCL_METRICS_PORT", "0")
    try:
        exporter = obs_health.ensure_exporter_from_env()
        assert exporter is not None
        with urllib.request.urlopen(
                f"http://127.0.0.1:{exporter.port}/retunes",
                timeout=10) as resp:
            doc = json.loads(resp.read())
        assert doc["format"] == HISTORY_FORMAT
        assert doc["version"] == HISTORY_VERSION
        assert isinstance(doc["episodes"], list)
    finally:
        obs_health.stop_exporter()


# ---------------------------------------------------------------------------
# sentinel re-delivery (the revert path's signal)
# ---------------------------------------------------------------------------

def test_sentinel_worsen_ratio_redelivery():
    """A persisting finding is delivered once; re-delivered only when
    its drift worsens past WORSEN_RATIO; a cleared finding re-arms."""
    reg = _metrics.MetricsRegistry()
    s = Sentinel(Baseline({}, "test"), registry=reg, min_calls=1)
    deliveries = []
    s.subscribe(lambda fresh: deliveries.append(list(fresh)))
    script = [
        ([_finding(ratio=2.0)], 1),   # new -> delivered
        ([_finding(ratio=2.2)], 1),   # 2.2 < 2.0*1.25 -> suppressed
        ([_finding(ratio=2.6)], 2),   # worsened past 2.5 -> delivered
        ([], 2),                      # cleared -> key re-arms
        ([_finding(ratio=2.0)], 3),   # back -> delivered again
        # bandwidth drifts DOWNWARD; the fold must still re-deliver
        ([_finding(axis="busbw_GBps", ratio=0.5, kind="bandwidth")], 4),
        ([_finding(axis="busbw_GBps", ratio=0.45, kind="bandwidth")], 4),
        ([_finding(axis="busbw_GBps", ratio=0.3, kind="bandwidth")], 5),
    ]
    for findings, want in script:
        s.compare_snapshot = lambda snap, f=findings: list(f)
        s.check()
        assert len(deliveries) == want, (findings, deliveries)


def test_sentinel_subscriber_fault_never_kills_the_check():
    reg = _metrics.MetricsRegistry()
    s = Sentinel(Baseline({}, "test"), registry=reg, min_calls=1)
    seen = []
    s.subscribe(lambda fresh: (_ for _ in ()).throw(RuntimeError("boom")))
    s.subscribe(lambda fresh: seen.extend(fresh))
    s.compare_snapshot = lambda snap: [_finding()]
    s.check()
    assert len(seen) == 1


# ---------------------------------------------------------------------------
# episode state machine: cooldown / stale rejection / revert
# ---------------------------------------------------------------------------

def test_cooldown_and_stale_then_genuine_revert():
    reg = _metrics.MetricsRegistry()
    with EmuWorld(2) as w:
        tuner = OnlineTuner(w, registry=reg, cooldown_s=60.0)
        key = cell_key("allreduce", "float32", "<=16KiB", 2)

        # (a) cell inside its cooldown window -> "cooldown" episode
        tuner._cooldown[key] = time.monotonic() + 60.0
        tuner.on_findings([_finding()])
        ep = tuner.step()
        assert ep["decision"] == "cooldown" and ep["cell"] == key
        assert reg.snapshot()["counters"].get(
            "tuning/retunes/rejected") == 1
        tuner._cooldown.pop(key)

        # (b) a finding queued BEFORE the install is the install
        # trigger's same-batch sibling, never its fallout -> rejected
        tuner.table.entries[key] = {"algorithm": "flat",
                                    "busbw_GBps": 1.0, "online": True}
        for a in w.accls:
            a._tune_policy._memo.clear()
        tuner.on_findings([_finding()])
        tuner._watch[key] = {"prev": None,
                             "installed_at": time.monotonic(),
                             "episode_seq": 7}
        ep = tuner.step()
        assert ep["decision"] == "rejected"
        assert "stale" in ep["reason"]
        assert key in tuner._watch  # the watch survives a stale hit

        # (c) a finding that arrives AFTER the install is the
        # install's fallout -> auto-revert to the pre-install entry
        tuner.on_findings([_finding()])
        ep = tuner.step()
        assert ep["decision"] == "reverted"
        assert ep["reverted_to"] == "static"
        assert ep["installed_episode"] == 7
        assert key not in tuner._watch
        assert key not in tuner.table.entries  # prev=None -> dropped
        assert tuner._cooldown[key] > time.monotonic()  # hard cooldown
        assert reg.snapshot()["counters"].get(
            "tuning/retunes/reverted") == 1


def test_tuner_adopts_armed_table_and_fabric_meta():
    """A tuner over a world armed with a tuned table serves THAT table
    (the incumbents) and composes over the table's recorded fabric."""
    with EmuWorld(4) as w:
        table = SelectionTable(
            {cell_key("allreduce", "float32", "<=1KiB", 4):
             {"algorithm": "flat", "busbw_GBps": 1.0}},
            {"nranks": 4, "backend": "emu", "dtype": "float32",
             "shape": [2, 2], "axis_order": [0, 1]})
        from accl_tpu.tuning.autotune import SelectionPolicy
        for a in w.accls:
            a._tune_policy = SelectionPolicy(table)
        tuner = OnlineTuner(w, registry=_metrics.MetricsRegistry())
        assert tuner.table is table
        assert tuple(tuner.fabric.shape) == (2, 2)
        assert not tuner.fabric.trivial


def test_dtype_fallback_serves_float32_row():
    """Per-dtype tables (r19): an unswept dtype borrows the float32
    row; a swept dtype's genuinely-untuned cell stays None."""
    key32 = cell_key("allreduce", "float32", "<=16KiB", 4)
    t = SelectionTable({key32: {"algorithm": "flat"}},
                       {"nranks": 4, "backend": "emu"})
    assert t.lookup("allreduce", "bfloat16", 16384, 4)["algorithm"] \
        == "flat"
    assert t.lookup("allreduce", "float32", 1 << 20, 4) is None
    t.entries[cell_key("allreduce", "bfloat16", "<=1KiB", 4)] = {
        "algorithm": "tree"}
    t._dtypes = None
    # bfloat16 is now a SWEPT dtype: no borrowing for its other cells
    assert t.lookup("allreduce", "bfloat16", 16384, 4) is None


# ---------------------------------------------------------------------------
# the drill: chaos -> finding -> hypothesis -> A/B -> decision
# ---------------------------------------------------------------------------

def test_retune_drill_end_to_end(monkeypatch):
    """The compressed scripts/retune_smoke.py drill: seeded chaos
    degrades a live world, the sentinel's findings drive the tuner
    through measured episodes, and the post-decision dispatch is
    never-slower than the degraded state it reacted to."""
    import statistics

    from accl_tpu.bench import sweep as _sweep

    # isolate the drill's call metrics from the rest of the suite
    reg = _metrics.MetricsRegistry()
    monkeypatch.setattr(_metrics, "_default", reg)
    monkeypatch.setenv("ACCL_DEFAULT_TIMEOUT", "30000000")
    # single-axis fabric: the drill verifies the control plane on the
    # register/compression lanes (see scripts/retune_smoke.py)
    monkeypatch.setenv("ACCL_FABRIC", "4")
    dtype = np.dtype(np.float32)
    count = 4096  # 16 KiB fp32: multiple eager segments per message

    w = EmuWorld(4, devmem_bytes=256 << 20, n_egr_rx_bufs=64,
                 max_eager_size=16384, max_rendezvous_size=64 << 20)
    try:
        def drive(n):
            durs = [_sweep._run_once(w, "allreduce", count, dtype, 0)
                    for _ in range(n)]
            return statistics.median(durs) * 1e6

        p50_warm = drive(8)
        baseline = Baseline.from_snapshot(reg.snapshot(), source="warm")
        sentinel = Sentinel(baseline, reg, p50_ratio=1.5, p99_ratio=2.0,
                            bw_ratio=0.6, min_calls=6)
        tuner = OnlineTuner(w, hysteresis=1.05, repetitions=2,
                            registry=reg)
        tuner.attach_sentinel(sentinel)

        plan = ChaosPlan.parse("seed=42,slow_rank=1:1000")
        for r, d in enumerate(w.devices):
            plan.apply(d, r)
        p50_degraded = drive(10)
        assert sentinel.check(), \
            f"no drift seen ({p50_warm:.0f} -> {p50_degraded:.0f}us)"
        assert tuner.pending() > 0

        episodes = []
        while tuner.pending():
            ep = tuner.step()
            if ep is not None:
                episodes.append(ep)
        assert episodes
        for ep in episodes:
            assert ep["decision"] in DECISIONS
            assert ep["trigger"]["type"] == "sentinel"
            assert isinstance(ep["opened_at"], float)
            assert isinstance(ep["closed_at"], float)
            assert ep["cell"].startswith("allreduce|float32|")
        decisions = {ep["decision"] for ep in episodes}
        assert decisions & {"installed", "rejected"}, episodes

        # never-slower: the dispatch the control plane left behind
        # must not be worse than the degraded state it reacted to
        p50_post = drive(8)
        assert p50_post <= p50_degraded * 1.5, \
            (p50_warm, p50_degraded, p50_post)

        counters = reg.snapshot()["counters"]
        assert counters.get("tuning/retunes/proposed", 0) >= 1
        if "installed" in decisions:
            assert counters.get("tuning/retunes/installed", 0) >= 1
            assert counters.get("tuning/retunes/verified", 0) >= 1
            # an install is fenced like abort: the flight ring carries
            # the anchor on every rank
            from accl_tpu.observability import flight as _flight
            for a in w.accls:
                kinds = [r.collective for r in
                         a.flight_recorder.records()]
                assert _flight.RETUNE_EVENT in kinds

        doc = tuner.history.to_doc()
        assert doc["format"] == HISTORY_FORMAT
        assert doc["version"] == HISTORY_VERSION
        assert len(doc["episodes"]) == len(episodes)
    finally:
        w.close()
