"""Flash attention kernel tests (Pallas interpret mode on CPU).

The tiled online-softmax kernel must match the dense reference exactly
(same math the ring layer applies across sequence shards).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accl_tpu.ops.flash import flash_attention
from accl_tpu.parallel.ring_attention import _dense_attention


def _qkv(B, T, H, D, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("kernel", ["resident", "grid"])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(causal, kernel):
    q, k, v = _qkv(2, 256, 2, 64)
    got = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64,
                          mxu_dtype=jnp.float32, kernel=kernel,
                          interpret=True)
    ref = _dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_flash_uneven_blocks():
    # bq != bk, and T equal to one block on the q side
    q, k, v = _qkv(1, 128, 1, 32, seed=1)
    got = flash_attention(q, k, v, causal=True, block_q=128, block_k=32,
                          mxu_dtype=jnp.float32, kernel="grid",
                          interpret=True)
    ref = _dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("kernel", ["resident", "grid"])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_packed_matches_bthd(causal, kernel):
    # the head-packed [N, T, D] entry is the same kernel minus the
    # layout transposes — identical numerics, including the one-shot
    # K/V cast scratch the resident schedule uses for non-MXU dtypes
    from accl_tpu.ops.flash import flash_attention_lse, flash_attention_packed_lse
    B, T, H, D = 2, 256, 2, 64
    q, k, v = _qkv(B, T, H, D, seed=3)
    pack = lambda x: x.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    got, lse_p = flash_attention_packed_lse(
        pack(q), pack(k), pack(v), causal=causal, block_q=64, block_k=64,
        mxu_dtype=jnp.float32, kernel=kernel, interpret=True)
    ref, lse = flash_attention_lse(
        q, k, v, causal=causal, block_q=64, block_k=64,
        mxu_dtype=jnp.float32, kernel=kernel, interpret=True)
    np.testing.assert_array_equal(
        np.asarray(got).reshape(B, H, T, D).transpose(0, 2, 1, 3),
        np.asarray(ref))
    np.testing.assert_array_equal(np.asarray(lse_p).reshape(B, H, T),
                                  np.asarray(lse))


@pytest.mark.parametrize("kernel", ["resident", "grid"])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_chunked_subfolds_match(causal, kernel):
    # chunk_k < block_k runs each block as an unrolled run of sub-folds
    # (the MXU/VPU pipelining path) — identical math to the unchunked
    # fold, including causal mask offsets inside a straddling block
    from accl_tpu.ops.flash import flash_attention_packed_lse
    N, T, D = 2, 256, 32
    rng = np.random.default_rng(13)
    mk = lambda: jnp.asarray(rng.standard_normal((N, T, D)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    kw = dict(causal=causal, block_q=64, block_k=128,
              mxu_dtype=jnp.float32, kernel=kernel, interpret=True)
    got, lse_c = flash_attention_packed_lse(q, k, v, chunk_k=32, **kw)
    ref, lse = flash_attention_packed_lse(q, k, v, **kw)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(lse_c), np.asarray(lse),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grid_resident_matches_grid(causal):
    # grid_resident = grid schedule (static predicated cells, scratch
    # carries) with the whole K/V row pinned via an unchanging block
    # index — must be bit-identical to the streaming grid schedule
    from accl_tpu.ops.flash import flash_attention_packed_lse
    N, T, D = 2, 256, 32
    rng = np.random.default_rng(15)
    mk = lambda: jnp.asarray(rng.standard_normal((N, T, D)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    kw = dict(causal=causal, block_q=64, block_k=64,
              mxu_dtype=jnp.float32, interpret=True)
    a, la = flash_attention_packed_lse(q, k, v, kernel="grid_resident",
                                       **kw)
    b, lb = flash_attention_packed_lse(q, k, v, kernel="grid", **kw)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_flash_chunk_snaps_to_divisor():
    # chunk snapping: 12 does not divide 64 -> largest divisor <= 12 and
    # >= 8 rows; must not decay below the tile floor (12->3->1 bug)
    from accl_tpu.ops.flash import flash_attention_packed
    N, T, D = 1, 64, 32
    rng = np.random.default_rng(14)
    mk = lambda: jnp.asarray(rng.standard_normal((N, T, D)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    out = flash_attention_packed(q, k, v, block_q=64, block_k=64,
                                 chunk_k=12, mxu_dtype=jnp.float32,
                                 kernel="resident", interpret=True)
    ref = flash_attention_packed(q, k, v, block_q=64, block_k=64,
                                 mxu_dtype=jnp.float32,
                                 kernel="resident", interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_resident_mixed_dtype_matches_grid(causal):
    # regression: with f32 inputs and bf16 mxu_dtype and NO cast
    # scratch, the resident kernel must still cast K/V per chunk like
    # the grid schedule — an earlier version read raw f32 blocks and
    # silently ignored mxu_dtype (resident vs grid diverged by ~3e-3)
    from accl_tpu.ops.flash import flash_attention_packed_lse
    N, T, D = 2, 256, 32
    rng = np.random.default_rng(17)
    mk = lambda: jnp.asarray(rng.standard_normal((N, T, D)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    # fuse_denom pinned off: the auto schedule turns it on for the
    # resident kernel at this lane-tile-free D, and its denominator
    # (bf16 p summed on the MXU) differs from grid's f32 jnp.sum in
    # the last bits — this test is about the cast path, bit-exactly
    kw = dict(causal=causal, block_q=64, block_k=64,
              mxu_dtype=jnp.bfloat16, interpret=True, fuse_denom=False)
    a, la = flash_attention_packed_lse(q, k, v, kernel="resident", **kw)
    b, lb = flash_attention_packed_lse(q, k, v, kernel="grid", **kw)
    np.testing.assert_array_equal(np.asarray(a, np.float32),
                                  np.asarray(b, np.float32))
    np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_resident_cast_scratch(causal):
    # exercises the resident kernel's needs_cast path: input dtype
    # (bf16) differs from mxu_dtype (f32), so K/V are cast ONCE into
    # VMEM scratch at iq==0 and all q-blocks read the scratch (grid
    # order made sequential via "arbitrary" semantics).  Must match the
    # same math applied per-fold without scratch (the grid kernel).
    from accl_tpu.ops.flash import flash_attention_packed_lse
    N, T, D = 2, 256, 32
    rng = np.random.default_rng(9)
    mk = lambda: jnp.asarray(rng.standard_normal((N, T, D)),
                             jnp.float32).astype(jnp.bfloat16)
    q, k, v = mk(), mk(), mk()
    got, lse_r = flash_attention_packed_lse(
        q, k, v, causal=causal, block_q=64, block_k=128,
        mxu_dtype=jnp.float32, kernel="resident", interpret=True,
        kv_cast_scratch=True)
    ref, lse_g = flash_attention_packed_lse(
        q, k, v, causal=causal, block_q=64, block_k=128,
        mxu_dtype=jnp.float32, kernel="grid", interpret=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(np.asarray(lse_r), np.asarray(lse_g),
                               rtol=1e-5, atol=1e-5)
    # and against the dense reference on the bf16-rounded operands
    from accl_tpu.parallel.ring_attention import _dense_attention
    dense = _dense_attention(
        q.astype(jnp.float32)[:, :, None, :],
        k.astype(jnp.float32)[:, :, None, :],
        v.astype(jnp.float32)[:, :, None, :], causal=causal)
    np.testing.assert_allclose(
        np.asarray(got, np.float32),
        np.asarray(dense)[:, :, 0, :], rtol=3e-2, atol=3e-2)


def test_flash_rejects_ragged():
    q, k, v = _qkv(1, 100, 1, 32)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)


def test_model_config_rejects_unknown_attn():
    from accl_tpu.models.transformer import ModelConfig
    with pytest.raises(ValueError):
        ModelConfig(attn="Flash")


def test_transformer_flash_matches_dense():
    from dataclasses import replace

    from accl_tpu.models.transformer import ModelConfig, forward, init_params

    cfg = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=2,
                      d_head=16, d_ff=64)
    params = init_params(np.random.default_rng(0), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab, (2, 64)))
    dense = forward(params, tokens, cfg)
    flash = forward(params, tokens, replace(cfg, attn="flash"))
    # the model derives the MXU input format from its activation dtype:
    # an f32 config keeps exact f32 matmuls, so the parity stays tight
    np.testing.assert_allclose(np.asarray(flash), np.asarray(dense),
                               rtol=2e-5, atol=2e-5)


def test_flash_bf16_default_accuracy():
    # the fast default (bf16 MXU inputs, f32 accumulate) must stay
    # within 16-bit-mantissa distance of the exact computation
    rng = np.random.default_rng(7)
    q, k, v = (jnp.asarray(rng.standard_normal((2, 128, 2, 32)),
                           jnp.float32) for _ in range(3))
    exact = flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                            mxu_dtype=jnp.float32, interpret=True)
    fast = flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                           interpret=True)
    np.testing.assert_allclose(np.asarray(fast), np.asarray(exact),
                               rtol=2e-2, atol=2e-2)


def test_flash_with_sp_rejected():
    # flash is the single-shard kernel; the ring layer owns attention
    # under sequence parallelism — the conflict must be loud
    from accl_tpu.models.transformer import ModelConfig, forward, init_params
    cfg = ModelConfig(vocab=64, d_model=32, n_layers=1, n_heads=2,
                      d_head=16, d_ff=64, attn="flash")
    params = init_params(np.random.default_rng(0), cfg)
    tokens = jnp.zeros((1, 64), jnp.int32)
    with pytest.raises(ValueError):
        forward(params, tokens, cfg, sp_axis="sp")


@pytest.mark.parametrize("kernel", ["resident", "grid"])
def test_flash_cross_length(kernel):
    # Tk != Tq (cross-attention shapes): used by lse-merge callers that
    # attend one query shard over differently-sized K/V segments; both
    # schedules have distinct cross-length index math, so both run
    rng = np.random.default_rng(21)
    q = jnp.asarray(rng.standard_normal((2, 128, 2, 16)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 64, 2, 16)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 64, 2, 16)), jnp.float32)
    got = flash_attention(q, k, v, mxu_dtype=jnp.float32, kernel=kernel,
                          interpret=True)
    ref = _dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, causal=True, interpret=True)


def test_flash_lse_merge_reconstructs_full():
    # splitting K/V and merging by lse must reproduce whole-row
    # attention (the ring fold's correctness contract)
    rng = np.random.default_rng(22)
    q = jnp.asarray(rng.standard_normal((1, 64, 2, 16)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 128, 2, 16)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 128, 2, 16)), jnp.float32)
    from accl_tpu.ops.flash import flash_attention_lse

    oA, lA = flash_attention_lse(q, k[:, :64], v[:, :64],
                                 mxu_dtype=jnp.float32, interpret=True)
    oB, lB = flash_attention_lse(q, k[:, 64:], v[:, 64:],
                                 mxu_dtype=jnp.float32, interpret=True)
    m = jnp.maximum(lA, lB)
    wA, wB = jnp.exp(lA - m), jnp.exp(lB - m)
    tot = wA + wB
    oM = (oA * jnp.transpose(wA / tot, (0, 2, 1))[..., None]
          + oB * jnp.transpose(wB / tot, (0, 2, 1))[..., None])
    full = flash_attention(q, k, v, mxu_dtype=jnp.float32, interpret=True)
    np.testing.assert_allclose(np.asarray(oM), np.asarray(full),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("q_tiles", [2, 4])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_q_tiles_match(causal, q_tiles):
    # q_tiles splits each q block into independent interleaved sub-tile
    # chains (MXU/VPU overlap) — per-row math is identical to a single
    # chain, so results must be bit-equal
    from accl_tpu.ops.flash import flash_attention_packed_lse
    N, T, D = 2, 256, 32
    rng = np.random.default_rng(23)
    mk = lambda: jnp.asarray(rng.standard_normal((N, T, D)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    kw = dict(causal=causal, block_q=64, block_k=64,
              mxu_dtype=jnp.float32, kernel="resident", interpret=True)
    a, la = flash_attention_packed_lse(q, k, v, q_tiles=q_tiles, **kw)
    b, lb = flash_attention_packed_lse(q, k, v, q_tiles=1, **kw)
    # per-row math is shape-independent, but the backend gemm may block
    # [32, D] and [64, D] differently — tight tolerance, not bit-equal
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_fuse_denom_matches(causal):
    # fused denominator: the softmax row-sum rides the PV matmul via a
    # ones-extended V column instead of a jnp.sum VPU pass.  Same
    # additions in a different evaluation order -> tight tolerance, and
    # the lse contract must hold exactly enough for ring merging
    from accl_tpu.ops.flash import flash_attention_packed_lse
    N, T, D = 2, 256, 32
    rng = np.random.default_rng(29)
    mk = lambda: jnp.asarray(rng.standard_normal((N, T, D)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    kw = dict(causal=causal, block_q=64, block_k=64,
              mxu_dtype=jnp.float32, kernel="resident", interpret=True)
    a, la = flash_attention_packed_lse(q, k, v, fuse_denom=True,
                                       q_tiles=1, **kw)
    # baseline pins fuse_denom=False: at this D the AUTO default now
    # resolves to the fused path, which would compare it to itself
    b, lb = flash_attention_packed_lse(q, k, v, fuse_denom=False,
                                       q_tiles=1, **kw)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                               rtol=1e-6, atol=1e-6)
    # combined with q_tiles (the two options compose) — out AND lse
    # (ring attention merges shards via lse, so the composed finalize
    # path's lse stores must hold too)
    c, lc = flash_attention_packed_lse(q, k, v, fuse_denom=True,
                                       q_tiles=2, **kw)
    np.testing.assert_allclose(np.asarray(c), np.asarray(b),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(lc), np.asarray(lb),
                               rtol=1e-6, atol=1e-6)
    # matching dtype: V-only scratch (no K copy) — same results
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    kwb = dict(kw, mxu_dtype=jnp.bfloat16)
    d, ld = flash_attention_packed_lse(qb, kb, vb, fuse_denom=True, **kwb)
    e, le = flash_attention_packed_lse(qb, kb, vb, fuse_denom=False, **kwb)
    np.testing.assert_allclose(np.asarray(d, np.float32),
                               np.asarray(e, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_flash_q_tiles_validation():
    from accl_tpu.ops.flash import flash_attention_packed
    q, k, v = (jnp.zeros((1, 64, 32), jnp.float32) for _ in range(3))
    # non-divisor / too-fine q_tiles snap DOWN to a valid split (the
    # same keep-working contract as block auto-shrink), so ring callers
    # can pass tuned opts without knowing the shard's shrunk block size
    flash_attention_packed(q, k, v, block_q=64, block_k=64,
                           q_tiles=3, interpret=True)
    flash_attention_packed(q, k, v, block_q=8, block_k=64,
                           q_tiles=2, interpret=True)
    with pytest.raises(ValueError):
        flash_attention_packed(q, k, v, block_q=64, block_k=64,
                               q_tiles=0, interpret=True)
    with pytest.raises(ValueError):
        flash_attention_packed(q, k, v, block_q=64, block_k=64,
                               fuse_denom=True, kernel="grid",
                               interpret=True)


@pytest.mark.parametrize("kernel", ["grid", "grid_resident"])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_grid_q_tiles_match(causal, kernel):
    # the grid schedules support the q-tile interleave too (the
    # long-context path auto lands on) — same per-row math as a single
    # chain
    from accl_tpu.ops.flash import flash_attention_packed_lse
    N, T, D = 2, 256, 32
    rng = np.random.default_rng(37)
    mk = lambda: jnp.asarray(rng.standard_normal((N, T, D)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    kw = dict(causal=causal, block_q=64, block_k=64,
              mxu_dtype=jnp.float32, kernel=kernel, interpret=True)
    a, la = flash_attention_packed_lse(q, k, v, q_tiles=2, **kw)
    b, lb = flash_attention_packed_lse(q, k, v, q_tiles=1, **kw)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                               rtol=1e-6, atol=1e-6)


def test_flash_opts_degrade_on_auto_grid():
    # under kernel="auto" the resident-only options are tuning HINTS:
    # when the K/V row exceeds the VMEM residency budget and auto lands
    # on the grid schedule, they drop instead of raising — distributed
    # callers forward tuned opts without knowing each shard's size.
    # (An EXPLICIT non-resident kernel still raises, tested above.)
    import accl_tpu.ops.flash as F
    q, k, v = (jnp.zeros((1, 256, 32), jnp.float32) for _ in range(3))
    orig = F._RESIDENT_KV_BYTES
    F._RESIDENT_KV_BYTES = 1  # force auto -> grid
    try:
        out = F.flash_attention_packed(
            q, k, v, block_q=64, block_k=64, q_tiles=2, fuse_denom=True,
            interpret=True)
        assert out.shape == q.shape
    finally:
        F._RESIDENT_KV_BYTES = orig


# ---------------------------------------------------------------------------
# backward pass (custom VJP)
# ---------------------------------------------------------------------------

def _dense_packed(q, k, v, causal):
    import jax
    D = q.shape[-1]
    T, Tk = q.shape[1], k.shape[1]
    s = jnp.einsum("ntd,nsd->nts", q, k) / np.sqrt(D)
    if causal:
        s = jnp.where(jnp.tril(jnp.ones((T, Tk), bool)), s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("nts,nsd->ntd", p, v)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    return out, lse


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_matches_dense(causal):
    # the custom VJP (Pallas dq and dk/dv kernels) against autodiff of
    # the dense reference, INCLUDING the lse output's cotangent — ring
    # attention differentiates through its lse-weighted shard merge
    from accl_tpu.ops.flash import flash_attention_packed_lse
    N, T, D = 2, 128, 32
    rng = np.random.default_rng(43)
    mk = lambda *s: jnp.asarray(rng.standard_normal(s), jnp.float32)
    q, k, v = mk(N, T, D), mk(N, T, D), mk(N, T, D)
    w_o, w_l = mk(N, T, D), mk(N, T)

    def loss_flash(q, k, v):
        o, l = flash_attention_packed_lse(
            q, k, v, causal=causal, block_q=32, block_k=64,
            mxu_dtype=jnp.float32, interpret=True)
        return jnp.sum(o * w_o) + jnp.sum(l * w_l)

    def loss_dense(q, k, v):
        o, l = _dense_packed(q, k, v, causal)
        return jnp.sum(o * w_o) + jnp.sum(l * w_l)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"d{name}")


def test_flash_backward_cross_length():
    # Tq != Tk exercises the distinct nq/nk accumulation bounds of the
    # two backward kernels
    from accl_tpu.ops.flash import flash_attention_packed_lse
    N, T, Tk, D = 1, 64, 128, 16
    rng = np.random.default_rng(44)
    mk = lambda *s: jnp.asarray(rng.standard_normal(s), jnp.float32)
    q, k, v = mk(N, T, D), mk(N, Tk, D), mk(N, Tk, D)

    def loss_flash(q, k, v):
        o, _ = flash_attention_packed_lse(
            q, k, v, block_q=32, block_k=32, mxu_dtype=jnp.float32,
            interpret=True)
        return jnp.sum(o ** 2)

    def loss_dense(q, k, v):
        o, _ = _dense_packed(q, k, v, False)
        return jnp.sum(o ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"d{name}")


def test_model_trains_with_flash_attention():
    # the flagship's attn="flash" path must be trainable end to end —
    # on real TPU hardware the ring/SP paths default to the flash
    # kernel, so a non-differentiable kernel would break training
    # exactly where CI can't see it
    from accl_tpu.models.transformer import ModelConfig, init_params, loss_fn
    cfg = ModelConfig(vocab=64, d_model=32, n_layers=1, n_heads=2,
                      d_head=16, d_ff=64, attn="flash")
    params = init_params(np.random.default_rng(0), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab, (2, 64)))
    g = jax.grad(lambda p: loss_fn(p, tokens, cfg)[0])(params)
    total = sum(float(jnp.sum(jnp.abs(x)))
                for x in jax.tree_util.tree_leaves(g))
    assert np.isfinite(total) and total > 0

    gd = jax.grad(lambda p: loss_fn(
        p, tokens, ModelConfig(vocab=64, d_model=32, n_layers=1,
                               n_heads=2, d_head=16, d_ff=64))[0])(params)
    for a, b in zip(jax.tree_util.tree_leaves(g),
                    jax.tree_util.tree_leaves(gd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_auto_schedule_matches_plain(causal):
    # q_tiles=None (the public default) resolves the tuned auto
    # schedule (interleaved sub-tile chains + split folds); per-row
    # math is identical to the explicit plain single-chain schedule
    from accl_tpu.ops.flash import flash_attention_packed_lse
    N, T, D = 2, 256, 32
    rng = np.random.default_rng(41)
    mk = lambda: jnp.asarray(rng.standard_normal((N, T, D)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    kw = dict(causal=causal, block_q=64, block_k=64,
              mxu_dtype=jnp.float32, kernel="resident", interpret=True)
    a, la = flash_attention_packed_lse(q, k, v, **kw)          # auto
    b, lb = flash_attention_packed_lse(q, k, v, q_tiles=1, **kw)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                               rtol=1e-6, atol=1e-6)
    # an explicit chunk_k is honored under the auto q_tiles too
    c, _ = flash_attention_packed_lse(q, k, v, chunk_k=32, **kw)
    np.testing.assert_allclose(np.asarray(c), np.asarray(b),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_chunked_matches_dense(causal):
    # chunk_k < block sizes runs the backward cells as unrolled
    # sub-chunk runs (dq chunks over k, dk/dv over q — the forward's
    # MXU/VPU pipelining lever); partial contributions are additive, so
    # gradients must match dense autodiff to accumulation-order
    # tolerance
    from accl_tpu.ops.flash import flash_attention_packed_lse
    N, T, D = 2, 256, 32
    rng = np.random.default_rng(47)
    mk = lambda *s: jnp.asarray(rng.standard_normal(s), jnp.float32)
    q, k, v = mk(N, T, D), mk(N, T, D), mk(N, T, D)
    w_o, w_l = mk(N, T, D), mk(N, T)

    def loss_flash(q, k, v):
        o, l = flash_attention_packed_lse(
            q, k, v, causal=causal, block_q=64, block_k=128,
            chunk_k=32, mxu_dtype=jnp.float32, interpret=True)
        return jnp.sum(o * w_o) + jnp.sum(l * w_l)

    def loss_dense(q, k, v):
        o, l = _dense_packed(q, k, v, causal)
        return jnp.sum(o * w_o) + jnp.sum(l * w_l)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"d{name}")


@pytest.mark.parametrize("causal", [False, True])
def test_flash_resident_skew_matches_plain(causal):
    # the software-pipelined schedule (QK^T of block j+1 issued before
    # block j's softmax/PV consume, score block carried through the
    # loop) must be bit-identical to the plain resident chain — same
    # _fold_consume, same fold order, only the issue order differs
    from accl_tpu.ops.flash import flash_attention_packed_lse
    N, T, D = 2, 256, 32
    rng = np.random.default_rng(23)
    mk = lambda: jnp.asarray(rng.standard_normal((N, T, D)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    kw = dict(causal=causal, block_q=64, block_k=64, interpret=True,
              mxu_dtype=jnp.bfloat16, q_tiles=1, fuse_denom=False)
    a, la = flash_attention_packed_lse(q, k, v, kernel="resident_skew",
                                       **kw)
    b, lb = flash_attention_packed_lse(q, k, v, kernel="resident", **kw)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_flash_resident_skew_rejects_inapplicable_options():
    # the module rule: silently ignoring an explicit schedule option
    # records fake sweep results — every inapplicable option raises
    from accl_tpu.ops.flash import flash_attention_packed
    N, T, D = 1, 128, 32
    x = jnp.zeros((N, T, D), jnp.float32)
    with pytest.raises(ValueError, match="single-chain"):
        flash_attention_packed(x, x, x, kernel="resident_skew",
                               q_tiles=2, interpret=True)
    with pytest.raises(ValueError, match="chunk_k"):
        flash_attention_packed(x, x, x, kernel="resident_skew",
                               chunk_k=64, interpret=True)
    with pytest.raises(ValueError, match="kv_cast_scratch"):
        flash_attention_packed(x, x, x, kernel="resident_skew",
                               kv_cast_scratch=True, interpret=True)


@pytest.mark.parametrize("kernel,opts", [
    ("resident", {}),
    ("grid", {}),
    # the separately-written pinned-row index map
    ("grid_resident", {}),
    ("resident_skew", {"q_tiles": 1, "fuse_denom": False}),
    # scratch paths: their @pl.when(iq == 0) builds must read the
    # GROUP's K/V rows, not the q-head index's
    ("resident", {"fuse_denom": True}),
    ("resident", {"kv_cast_scratch": True, "mxu_dtype": jnp.bfloat16}),
    ("resident", {"q_tiles": 2}),
])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_gqa_matches_expanded(causal, kernel, opts):
    # grouped-query attention: K/V with fewer heads than q — the
    # kernel's K/V index maps share each row across H/G consecutive q
    # heads, so the result must be BIT-identical to running the same
    # kernel on explicitly expanded (repeated) K/V.  B > 1 exercises
    # the packed-layout fold (b*H + h) // group == b*G + h // group.
    from accl_tpu.ops.flash import flash_attention_lse, flash_attention_packed_lse
    B, T, H, G, D = 2, 128, 4, 2, 32
    rng = np.random.default_rng(33)
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, T, G, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, T, G, D)), jnp.float32)
    rep = lambda x: jnp.repeat(x, H // G, axis=2)
    kw = dict(causal=causal, block_q=64, block_k=64, interpret=True,
              mxu_dtype=jnp.float32, kernel=kernel)
    kw.update(opts)
    if kernel in ("resident", "grid") and "kv_cast_scratch" not in opts:
        # BTHD wrapper path (no kv_cast_scratch arg there)
        a, la = flash_attention_lse(q, k, v, **kw)
        b, lb = flash_attention_lse(q, rep(k), rep(v), **kw)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    # packed entry covers every kernel and option
    pk = lambda x: x.transpose(0, 2, 1, 3).reshape(
        B * x.shape[2], T, D)
    a, la = flash_attention_packed_lse(pk(q), pk(k), pk(v), **kw)
    b, lb = flash_attention_packed_lse(pk(q), pk(rep(k)), pk(rep(v)),
                                       **kw)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_flash_gqa_grads_match_expansion():
    # the GQA backward expands K/V and group-sums dK/dV; that must
    # equal autodiff through an explicit repeat (whose transpose IS the
    # group sum).  B=2 exercises the batch-interleaved packed fold
    # (a wrong reshape order in the group-sum passes at B=1)
    from accl_tpu.ops.flash import flash_attention_lse
    B, T, H, G, D = 2, 128, 4, 2, 32
    rng = np.random.default_rng(35)
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, T, G, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, T, G, D)), jnp.float32)
    rep = lambda x: jnp.repeat(x, H // G, axis=2)

    def loss(q, k, v):
        o, lse = flash_attention_lse(q, k, v, causal=True, block_q=64,
                                     block_k=64, interpret=True,
                                     mxu_dtype=jnp.float32)
        return jnp.sum(o * o) + 0.1 * jnp.sum(lse)

    g1 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: loss(q, rep(k), rep(v)),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)


def test_flash_gqa_rejects_nondividing_heads():
    from accl_tpu.ops.flash import flash_attention
    q = jnp.zeros((1, 64, 4, 32), jnp.float32)
    kv = jnp.zeros((1, 64, 3, 32), jnp.float32)  # 3 does not divide 4
    with pytest.raises(ValueError, match="GQA"):
        flash_attention(q, kv, kv, interpret=True)


def _dense_windowed(q, k, v, window):
    # the shared banded reference (one implementation repo-wide)
    from accl_tpu.parallel.ring_attention import _dense_attention
    return _dense_attention(q, k, v, causal=True, window=window)


@pytest.mark.parametrize("kernel", ["grid", "grid_resident"])
@pytest.mark.parametrize("window", [1, 17, 64, 100, 1000])
def test_flash_sliding_window_matches_banded_dense(window, kernel):
    # sliding-window attention: blocks strictly before every row's
    # window are skipped, window-edge straddlers are masked — result
    # must equal the dense banded softmax for any window/block phase
    from accl_tpu.ops.flash import flash_attention_lse
    B, T, H, D = 1, 256, 2, 32
    rng = np.random.default_rng(41)
    mk = lambda: jnp.asarray(rng.standard_normal((B, T, H, D)),
                             jnp.float32)
    q, k, v = mk(), mk(), mk()
    o, _ = flash_attention_lse(q, k, v, causal=True, window=window,
                               block_q=64, block_k=64, interpret=True,
                               mxu_dtype=jnp.float32, kernel=kernel)
    np.testing.assert_allclose(np.asarray(o),
                               np.asarray(_dense_windowed(q, k, v, window)),
                               rtol=1e-5, atol=1e-5)


def test_flash_sliding_window_grads_match_banded_dense():
    # the backward kernels carry the same window liveness/mask split
    from accl_tpu.ops.flash import flash_attention_lse
    B, T, H, D, window = 1, 256, 2, 32, 48
    rng = np.random.default_rng(43)
    mk = lambda: jnp.asarray(rng.standard_normal((B, T, H, D)),
                             jnp.float32)
    q, k, v = mk(), mk(), mk()

    def loss_flash(q, k, v):
        o, _ = flash_attention_lse(q, k, v, causal=True, window=window,
                                   block_q=64, block_k=64, interpret=True,
                                   mxu_dtype=jnp.float32, kernel="grid")
        return jnp.sum(o * o)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: jnp.sum(
        _dense_windowed(q, k, v, window) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_flash_window_validation():
    from accl_tpu.ops.flash import flash_attention
    x = jnp.zeros((1, 128, 2, 32), jnp.float32)
    with pytest.raises(ValueError, match="causal"):
        flash_attention(x, x, x, causal=False, window=16, interpret=True)
    with pytest.raises(ValueError, match="grid-schedule"):
        flash_attention(x, x, x, causal=True, window=16,
                        kernel="resident_skew", q_tiles=1,
                        fuse_denom=False, interpret=True)
    # an EXPLICIT resident kernel with window raises too (the
    # explicit-option contract); only kernel="auto" moves to grid
    with pytest.raises(ValueError, match="grid-schedule"):
        flash_attention(x, x, x, causal=True, window=16, interpret=True,
                        kernel="resident")
    o = flash_attention(x, x, x, causal=True, window=16, interpret=True)
    assert o.shape == x.shape


def test_flash_gqa_window_grads_match_banded_dense():
    """GQA x sliding-window BACKWARD: the expansion-free grouped dkv
    accumulation (grid nq_eff*G, q row/block = divmod(j, nq)) composed
    with the window-bounded q span and phantom-cell guards — new index
    algebra in r5 with no other coverage (review finding)."""
    from accl_tpu.parallel.ring_attention import expand_gqa_kv
    from accl_tpu.ops.flash import flash_attention_lse
    B, T, H, G, D, window = 1, 256, 4, 2, 32, 48
    rng = np.random.default_rng(47)
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, T, G, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, T, G, D)), jnp.float32)

    def loss_flash(q, k, v):
        o, _ = flash_attention_lse(q, k, v, causal=True, window=window,
                                   block_q=64, block_k=64,
                                   interpret=True,
                                   mxu_dtype=jnp.float32)
        return jnp.sum(o * o)

    def loss_dense(q, k, v):
        ke, ve = expand_gqa_kv(k, v, H)
        return jnp.sum(_dense_windowed(q, ke, ve, window) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_flash_static_max_matches_dynamic():
    """static_max (pinned softmax shift, resident schedule) must be
    numerically interchangeable with the dynamic-max fold: same out,
    same lse, same gradients (the backward reconstructs p from the
    EXACT lse either way)."""
    from accl_tpu.ops.flash import flash_attention_packed_lse
    N, T, D = 2, 256, 32
    rng = np.random.default_rng(53)
    q, k, v = (jnp.asarray(rng.standard_normal((N, T, D)), jnp.float32)
               for _ in range(3))

    def run(**kw):
        return flash_attention_packed_lse(
            q, k, v, causal=True, block_q=64, block_k=64,
            interpret=True, mxu_dtype=jnp.float32, kernel="resident",
            **kw)

    o_dyn, lse_dyn = run()
    o_st, lse_st = run(static_max=40.0)
    np.testing.assert_allclose(np.asarray(o_st), np.asarray(o_dyn),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(lse_st), np.asarray(lse_dyn),
                               rtol=2e-5, atol=2e-5)

    def loss(fn_kw, q, k, v):
        o, _ = flash_attention_packed_lse(
            q, k, v, causal=True, block_q=64, block_k=64,
            interpret=True, mxu_dtype=jnp.float32, kernel="resident",
            **fn_kw)
        return jnp.sum(o * o)

    g_dyn = jax.grad(lambda *a: loss({}, *a), argnums=(0, 1, 2))(q, k, v)
    g_st = jax.grad(lambda *a: loss({"static_max": 40.0}, *a),
                    argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_st, g_dyn):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_flash_static_max_fused_denom_composes():
    """static_max + fuse_denom: the row-sum rides the PV matmul AND
    the max/alpha passes vanish — the minimal-VPU D=64 schedule."""
    from accl_tpu.ops.flash import flash_attention_packed
    N, T, D = 2, 256, 64
    rng = np.random.default_rng(54)
    q, k, v = (jnp.asarray(rng.standard_normal((N, T, D)), jnp.float32)
               for _ in range(3))
    o_dyn = flash_attention_packed(q, k, v, causal=True, block_q=64,
                                   block_k=64, interpret=True,
                                   mxu_dtype=jnp.float32,
                                   kernel="resident")
    o_st = flash_attention_packed(q, k, v, causal=True, block_q=64,
                                  block_k=64, interpret=True,
                                  mxu_dtype=jnp.float32,
                                  kernel="resident", fuse_denom=True,
                                  static_max=40.0)
    np.testing.assert_allclose(np.asarray(o_st), np.asarray(o_dyn),
                               rtol=2e-5, atol=2e-5)


def test_flash_static_max_grid_matches_and_skew_rejects():
    # grid (the long-context/window schedule) supports the pin too;
    # resident_skew's carried-score fold does not
    from accl_tpu.ops.flash import flash_attention_packed
    N, T, D = 2, 256, 32
    rng = np.random.default_rng(55)
    q, k, v = (jnp.asarray(rng.standard_normal((N, T, D)), jnp.float32)
               for _ in range(3))
    o_dyn = flash_attention_packed(q, k, v, causal=True, block_q=64,
                                   block_k=64, interpret=True,
                                   mxu_dtype=jnp.float32, kernel="grid")
    o_st = flash_attention_packed(q, k, v, causal=True, block_q=64,
                                  block_k=64, interpret=True,
                                  mxu_dtype=jnp.float32, kernel="grid",
                                  static_max=40.0)
    np.testing.assert_allclose(np.asarray(o_st), np.asarray(o_dyn),
                               rtol=2e-5, atol=2e-5)
    with pytest.raises(ValueError, match="static_max"):
        flash_attention_packed(q, k, v, causal=True,
                               kernel="resident_skew", interpret=True,
                               static_max=40.0)
