"""Tests for the functional SPMD collective layer (XLA lowerings and the
explicit ring schedules) over the virtual 8-device CPU mesh."""
import jax
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from accl_tpu.parallel import (
    all_gather,
    all_reduce,
    all_to_all,
    broadcast,
    make_mesh,
    reduce_scatter,
    ring_all_gather,
    ring_all_reduce,
    ring_reduce_scatter,
    scatter,
    send_recv,
)

NRANKS = 8
N = 16  # per-rank elements


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(dp=NRANKS)


def _global(mesh, data):
    return jax.device_put(data, NamedSharding(mesh, P("dp", None)))


def _run(mesh, body, x, out_specs=P("dp", None)):
    f = shard_map(body, mesh=mesh, in_specs=P("dp", None),
                  out_specs=out_specs)
    return np.asarray(jax.jit(f)(x))


def _data():
    rng = np.random.default_rng(7)
    return rng.standard_normal((NRANKS, N)).astype(np.float32)


def test_all_reduce(mesh):
    d = _data()
    x = _global(mesh, d)
    out = _run(mesh, lambda b: all_reduce(b, "dp")[None][0], x)
    exp = np.broadcast_to(d.sum(axis=0), (NRANKS, N))
    np.testing.assert_allclose(out, exp, rtol=1e-5, atol=1e-5)
    out = _run(mesh, lambda b: all_reduce(b, "dp", op="max"), x)
    np.testing.assert_allclose(out, np.broadcast_to(d.max(axis=0), (NRANKS, N)),
                               rtol=1e-6)


def test_all_gather_and_bcast(mesh):
    d = _data()
    x = _global(mesh, d)
    out = _run(mesh, lambda b: all_gather(b[0], "dp", tiled=True)[None],
               x, out_specs=P("dp", None))
    for r in range(NRANKS):
        np.testing.assert_array_equal(out[r], d.reshape(-1))
    out = _run(mesh, lambda b: broadcast(b[0], 3, "dp")[None], x)
    np.testing.assert_array_equal(out, np.broadcast_to(d[3], (NRANKS, N)))


def test_reduce_scatter(mesh):
    rng = np.random.default_rng(8)
    d = rng.standard_normal((NRANKS, NRANKS * N)).astype(np.float32)
    x = _global(mesh, d)
    out = _run(mesh, lambda b: reduce_scatter(b[0], "dp")[None], x)
    exp = d.sum(axis=0).reshape(NRANKS, N)
    np.testing.assert_allclose(out, exp, rtol=1e-5, atol=1e-5)


def test_all_to_all(mesh):
    rng = np.random.default_rng(9)
    d = rng.standard_normal((NRANKS, NRANKS * N)).astype(np.float32)
    x = _global(mesh, d)
    out = _run(mesh,
               lambda b: all_to_all(b[0].reshape(NRANKS, N), "dp",
                                    split_axis=0, concat_axis=0,
                                    tiled=False).reshape(1, -1), x)
    for r in range(NRANKS):
        exp = np.concatenate([d[s, r * N:(r + 1) * N] for s in range(NRANKS)])
        np.testing.assert_array_equal(out[r], exp)


def test_scatter_send_recv(mesh):
    rng = np.random.default_rng(10)
    d = rng.standard_normal((NRANKS, NRANKS * N)).astype(np.float32)
    x = _global(mesh, d)
    out = _run(mesh, lambda b: scatter(b[0].reshape(NRANKS, N), 2, "dp")[None],
               x, out_specs=P("dp", None))
    np.testing.assert_array_equal(out, d[2].reshape(NRANKS, N))

    d2 = _data()
    x2 = _global(mesh, d2)
    out = _run(mesh, lambda b: send_recv(b[0], 1, 5, "dp")[None], x2)
    np.testing.assert_array_equal(out[5], d2[1])
    np.testing.assert_array_equal(out[0], np.zeros(N, np.float32))


# ---------------------------------------------------------------------------
# explicit ring schedules must agree with the XLA lowerings
# ---------------------------------------------------------------------------
def test_ring_reduce_scatter_matches(mesh):
    rng = np.random.default_rng(11)
    d = rng.standard_normal((NRANKS, NRANKS * N)).astype(np.float32)
    x = _global(mesh, d)
    out = _run(mesh, lambda b: ring_reduce_scatter(b[0], "dp")[None], x)
    exp = d.sum(axis=0).reshape(NRANKS, N)
    np.testing.assert_allclose(out, exp, rtol=1e-4, atol=1e-4)


def test_ring_all_gather_matches(mesh):
    d = _data()
    x = _global(mesh, d)
    out = _run(mesh, lambda b: ring_all_gather(b[0], "dp")[None], x)
    for r in range(NRANKS):
        np.testing.assert_array_equal(out[r], d.reshape(-1))


def test_ring_all_reduce_matches(mesh):
    rng = np.random.default_rng(12)
    d = rng.standard_normal((NRANKS, NRANKS * N)).astype(np.float32)
    x = _global(mesh, d)
    out = _run(mesh, lambda b: ring_all_reduce(b[0], "dp")[None], x)
    exp = np.broadcast_to(d.sum(axis=0), (NRANKS, NRANKS * N))
    np.testing.assert_allclose(out, exp, rtol=1e-4, atol=1e-4)
