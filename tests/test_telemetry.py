"""Native-engine telemetry plane (observability/telemetry.py), the
regression sentinel (observability/sentinel.py), and the r14
observability satellites: ephemeral metrics port, OpenMetrics schema
completeness by construction, perf_doctor round-trip, doctor rendering
of unknown engine families.
"""
import io
import json
import os
import re
import subprocess
import sys

import numpy as np
import pytest

from accl_tpu import ReduceFunction
from accl_tpu.observability import health as obs_health
from accl_tpu.observability import metrics as obs_metrics
from accl_tpu.observability import sentinel as obs_sentinel
from accl_tpu.observability import telemetry as obs_telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_world(nranks=2, iters=4, count=64):
    from accl_tpu.backends.emu import EmuWorld

    world = EmuWorld(nranks)

    def body(accl, rank):
        send = accl.create_buffer_like(
            np.arange(count, dtype=np.float32) + rank)
        recv = accl.create_buffer(count, np.float32)
        for _ in range(iters):
            accl.allreduce(send, recv, count, ReduceFunction.SUM,
                           from_fpga=True, to_fpga=True)

    world.run(body)
    return world


# ---------------------------------------------------------------------------
# engine_stats: the versioned capi snapshot
# ---------------------------------------------------------------------------
def test_engine_stats_schema_and_traffic():
    world = _run_world()
    try:
        stats = world.engine_stats()
        assert len(stats) == world.nranks
        for st in stats:
            assert st["version"] == 1
            for field in obs_telemetry.ENGINE_STATS_FIELDS_V1:
                assert field in st, f"missing v1 field {field}"
            # no unknown fields from a same-version engine
            assert not any(k.startswith("unknown_field_") for k in st)
        # traffic really flowed through the counters
        assert all(st["tx_msgs"] > 0 for st in stats)
        assert all(st["seeks"] > 0 for st in stats)
        assert all(st["wire_accepted_frames"] > 0 for st in stats)
        # eager sends were captured into the retransmit store
        assert any(st["retrans_store_depth"] > 0 for st in stats)
        # the rx pool saw occupancy
        assert any(st["rx_occupancy_hwm"] > 0 for st in stats)
        # quiesced world: transient depths drained back to zero
        assert all(st["egress_depth"] == 0 for st in stats)
        assert all(st["seek_misses"] == 0 for st in stats)
    finally:
        world.close()


def test_engine_stats_closed_world_raises():
    from accl_tpu.constants import ACCLError

    world = _run_world(iters=1)
    dev = world.devices[0]
    world.close()
    with pytest.raises(ACCLError):
        dev.engine_stats()


def test_decode_keeps_newer_engine_fields():
    n = len(obs_telemetry.ENGINE_STATS_FIELDS_V1)
    values = list(range(n + 2))  # a newer engine returned 2 extra
    st = obs_telemetry.decode_engine_stats(values, total_fields=n + 2)
    assert st[obs_telemetry.ENGINE_STATS_FIELDS_V1[0]] == 0
    assert st[f"unknown_field_{n}"] == n
    assert st[f"unknown_field_{n + 1}"] == n + 1


# ---------------------------------------------------------------------------
# the sampler: engine/* families, counter-delta discipline, off switch
# ---------------------------------------------------------------------------
def test_sampler_publishes_engine_families():
    reg = obs_metrics.MetricsRegistry()
    world = _run_world()
    try:
        sampler = obs_telemetry.TelemetrySampler(
            [d.engine_stats for d in world.devices], registry=reg,
            interval_s=30.0)
        sampler.sample()
        snap = reg.snapshot()
        assert snap["counters"].get("engine/tx_msgs", 0) > 0
        assert snap["counters"].get("engine/seeks", 0) > 0
        assert "engine/rx_occupancy_hwm" in snap["gauges"]
        total_first = snap["counters"]["engine/tx_msgs"]
        # second sample without new traffic: counters must NOT double
        sampler.sample()
        assert reg.snapshot()["counters"]["engine/tx_msgs"] == total_first
        # counters aggregate as the SUM over ranks
        per_rank = sum(st["tx_msgs"] for st in world.engine_stats())
        assert total_first == per_rank
    finally:
        world.close()


def test_sampler_env_gate(monkeypatch):
    monkeypatch.delenv("ACCL_TELEMETRY_INTERVAL_MS", raising=False)
    assert obs_telemetry.sampler_from_env([lambda: {}]) is None
    monkeypatch.setenv("ACCL_TELEMETRY_INTERVAL_MS", "0")
    assert obs_telemetry.sampler_from_env([lambda: {}]) is None
    monkeypatch.setenv("ACCL_TELEMETRY_INTERVAL_MS", "50")
    reg = obs_metrics.MetricsRegistry()
    sampler = obs_telemetry.sampler_from_env(
        [lambda: {"tx_msgs": 3, "egress_depth": 1}], registry=reg)
    try:
        assert sampler is not None and sampler.interval_s == 0.05
        sampler.sample()
        assert reg.counter("engine/tx_msgs") == 3
        assert reg.snapshot()["gauges"]["engine/egress_depth"] == 1
    finally:
        sampler.stop()


def test_sampler_survives_dying_source():
    reg = obs_metrics.MetricsRegistry()

    def dead():
        raise RuntimeError("world closed mid-poll")

    sampler = obs_telemetry.TelemetrySampler(
        [dead, lambda: {"tx_msgs": 7}], registry=reg, interval_s=30.0)
    sampler.sample()
    assert reg.counter("engine/tx_msgs") == 7


def test_tpu_engine_stats_schema():
    from accl_tpu.backends.tpu import TpuWorld

    with TpuWorld(2) as world:
        def body(accl, rank):
            send = accl.create_buffer_like(
                np.arange(32, dtype=np.float32) + rank)
            recv = accl.create_buffer(32, np.float32)
            for _ in range(3):
                accl.allreduce(send, recv, 32, ReduceFunction.SUM,
                               from_fpga=True, to_fpga=True)

        world.run(body)
        st = world.devices[0].engine_stats()
        assert st["version"] == 1
        assert st["leader_dispatches"] + st["executor_dispatches"] > 0
        for k in ("plans_live", "plan_ring_refs",
                  "plan_ring_generation", "ready_depth"):
            assert k in st
        # every field classifies cleanly (counter or known gauge HELP)
        for k in st:
            if k == "version" or k in obs_telemetry.COUNTER_FIELDS:
                continue
            assert obs_metrics.metric_help_for(f"accl_engine_{k}"), k


# ---------------------------------------------------------------------------
# satellite: metrics schema completeness, by construction
# ---------------------------------------------------------------------------
def _sanitize(name: str) -> str:
    n = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    return n if n.startswith("accl_") else f"accl_{n}"


def test_every_registered_family_has_help():
    """Grep the library tree for every literal metric family minted via
    inc/set_gauge/observe_value and require each to resolve through
    METRIC_HELP (or a registered dynamic-name prefix) — the drift class
    'new family ships without HELP' fails here, not in review."""
    pattern = re.compile(
        r"\.(?:inc|set_gauge|observe_value)\(\s*(f?)\"([^\"]+)\"")
    families: dict = {}
    root = os.path.join(REPO, "accl_tpu")
    for dirpath, _dirs, files in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path) as f:
                text = f.read()
            for m in pattern.finditer(text):
                is_f, literal = m.group(1) == "f", m.group(2)
                prefix_only = is_f and "{" in literal
                name = literal.split("{")[0] if prefix_only else literal
                families[(name, prefix_only)] = path
    assert families, "grep found no metric registrations — pattern rot?"
    missing = []
    exact_keys = list(obs_metrics.METRIC_HELP)
    prefix_keys = list(obs_metrics.METRIC_HELP_PREFIXES)
    for (name, prefix_only), path in sorted(families.items()):
        s = _sanitize(name)
        if prefix_only:
            ok = any(k.startswith(s) for k in exact_keys) or \
                any(k.startswith(s) or s.startswith(k)
                    for k in prefix_keys)
        else:
            ok = obs_metrics.metric_help_for(s) is not None
        if not ok:
            missing.append(f"{name!r} ({path})")
    assert not missing, (
        "metric families without METRIC_HELP entries (add HELP text in "
        "observability/metrics.py): " + ", ".join(missing))


def test_exporter_body_validates_as_openmetrics():
    reg = obs_metrics.MetricsRegistry()
    reg.inc("watchdog/checks", 3)
    reg.inc("engine/tx_msgs", 9)
    reg.set_gauge("accl_health", 0)
    reg.set_gauge("engine/rx_occupancy_hwm", 4)
    reg.observe_value("recovery/latency_us", 1234.5)
    reg.observe_call("allreduce", "float32", 4096, 250_000.0, 4)
    reg.observe_call("allreduce", "float32", 4096, 90_000.0, 4)
    problems = obs_metrics.validate_openmetrics(reg.to_openmetrics())
    assert problems == []


def test_validator_catches_schema_breakage():
    reg = obs_metrics.MetricsRegistry()
    reg.inc("watchdog/checks")
    body = reg.to_openmetrics()
    assert obs_metrics.validate_openmetrics(body) == []
    # a family without HELP knowledge
    reg2 = obs_metrics.MetricsRegistry()
    reg2.inc("totally/unknown")
    probs = obs_metrics.validate_openmetrics(reg2.to_openmetrics())
    assert any("METRIC_HELP" in p for p in probs)
    # missing EOF
    assert any("EOF" in p for p in obs_metrics.validate_openmetrics(
        body.replace("# EOF", "")))
    # a sample without a TYPE declaration
    probs = obs_metrics.validate_openmetrics(
        "orphan_sample 1\n# EOF\n")
    assert any("TYPE" in p for p in probs)
    # non-cumulative histogram buckets
    bad = ("# TYPE accl_recovery_latency_us histogram\n"
           'accl_recovery_latency_us_bucket{le="1"} 5\n'
           'accl_recovery_latency_us_bucket{le="4"} 3\n'
           'accl_recovery_latency_us_bucket{le="+Inf"} 5\n'
           "accl_recovery_latency_us_sum 10\n"
           "accl_recovery_latency_us_count 5\n# EOF\n")
    assert any("cumulative" in p
               for p in obs_metrics.validate_openmetrics(bad))


# ---------------------------------------------------------------------------
# satellite: ACCL_METRICS_PORT=0 binds an ephemeral port
# ---------------------------------------------------------------------------
def test_metrics_port_zero_binds_ephemeral(monkeypatch):
    import urllib.request

    obs_health.stop_exporter()
    monkeypatch.setenv("ACCL_METRICS_PORT", "0")
    try:
        exporter = obs_health.ensure_exporter_from_env()
        assert exporter is not None, "port 0 must mean ephemeral, not off"
        port = obs_health.exporter_port()
        assert port == exporter.port and port > 0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as resp:
            assert json.loads(resp.read())["health"] in (
                "ok", "degraded", "hung", "aborted", "recovering",
                "slow")
    finally:
        obs_health.stop_exporter()
    assert obs_health.exporter_port() is None


def test_metrics_port_unset_means_off(monkeypatch):
    obs_health.stop_exporter()
    monkeypatch.delenv("ACCL_METRICS_PORT", raising=False)
    assert obs_health.ensure_exporter_from_env() is None
    monkeypatch.setenv("ACCL_METRICS_PORT", "")
    assert obs_health.ensure_exporter_from_env() is None


# ---------------------------------------------------------------------------
# regression sentinel: drift detection + the `slow` health verdict
# ---------------------------------------------------------------------------
def _observe(reg, us, n=30):
    for _ in range(n):
        reg.observe_call("allreduce", "float32", 4096, us * 1e3, 4)


def test_quantile_estimate_tracks_buckets():
    hist = [0] * (len(obs_metrics.LATENCY_BUCKETS_US) + 1)
    hist[5] = 100  # everything in the <=1024us bucket (4**5)
    p50 = obs_sentinel.quantile_us(hist, 0.5)
    assert 256 <= p50 <= 1024
    assert obs_sentinel.quantile_us([0] * len(hist), 0.5) == 0.0


def test_sentinel_flags_drift_and_degrades_health():
    reg = obs_metrics.MetricsRegistry()
    _observe(reg, us=200.0)
    baseline = obs_sentinel.Baseline.from_snapshot(reg.snapshot())
    assert baseline.entries, "baseline capture produced nothing"

    live = obs_metrics.MetricsRegistry()
    _observe(live, us=9000.0)  # ~45x the baseline p50
    sen = obs_sentinel.Sentinel(baseline, registry=live, p50_ratio=2.0,
                                p99_ratio=3.0, min_calls=10)
    findings = sen.check()
    assert findings, "45x latency drift not flagged"
    f = findings[0]
    assert f["collective"] == "allreduce" and f["axis"] in ("p50_us",
                                                           "p99_us")
    assert f["ratio"] > 2.0
    assert live.snapshot()["gauges"]["accl_health"] == \
        obs_health.HEALTH_SLOW
    assert live.counter("sentinel/findings") >= 1
    # recovery: a fresh registry state below threshold clears the verdict
    live.reset()
    _observe(live, us=200.0)
    assert sen.check() == []
    assert live.snapshot()["gauges"]["accl_health"] == \
        obs_health.HEALTH_OK


def test_sentinel_slow_never_masks_stronger_verdicts():
    reg = obs_metrics.MetricsRegistry()
    obs_health.note_slow(reg, True)
    assert reg.snapshot()["gauges"]["accl_health"] == \
        obs_health.HEALTH_SLOW
    # a recovery episode outranks slow
    obs_health.note_recovering(reg, True)
    assert reg.snapshot()["gauges"]["accl_health"] == \
        obs_health.HEALTH_RECOVERING
    obs_health.note_recovering(reg, False)
    obs_health.note_slow(reg, False)
    assert reg.snapshot()["gauges"]["accl_health"] == obs_health.HEALTH_OK


def test_sentinel_min_calls_guard():
    reg = obs_metrics.MetricsRegistry()
    _observe(reg, us=100.0)
    baseline = obs_sentinel.Baseline.from_snapshot(reg.snapshot())
    live = obs_metrics.MetricsRegistry()
    _observe(live, us=9000.0, n=3)  # below min_calls
    sen = obs_sentinel.Sentinel(baseline, registry=live, min_calls=10)
    assert sen.compare_snapshot(live.snapshot()) == []


def test_baseline_loads_committed_formats(tmp_path):
    # callrate record
    cb = obs_sentinel.Baseline.load(
        os.path.join(REPO, "bench/results/callrate_r12_plan_on.json"))
    assert any(k[0] == "allreduce" for k in cb.entries)
    assert any(k[3] == "*" for k in cb.entries)
    # sweep-gate CSV
    sb = obs_sentinel.Baseline.load(
        os.path.join(REPO, "bench/results/sweep_gate_baseline_r12.csv"))
    assert any(k[0] == "allreduce" for k in sb.entries)
    # native round-trip
    p = tmp_path / "base.json"
    cb.save(str(p))
    rb = obs_sentinel.Baseline.load(str(p))
    assert rb.entries == cb.entries
    # merge: self wins on conflicts, union otherwise
    merged = cb.merge(sb)
    assert len(merged.entries) >= max(len(cb.entries), len(sb.entries))


def test_sentinel_env_gate(monkeypatch, tmp_path):
    obs_sentinel.stop_sentinel()
    monkeypatch.delenv("ACCL_SENTINEL", raising=False)
    assert obs_sentinel.ensure_sentinel_from_env() is None
    monkeypatch.setenv("ACCL_SENTINEL", "/nonexistent/base.json")
    assert obs_sentinel.ensure_sentinel_from_env() is None  # never raises
    reg = obs_metrics.MetricsRegistry()
    _observe(reg, us=100.0)
    p = tmp_path / "base.json"
    obs_sentinel.Baseline.from_snapshot(reg.snapshot()).save(str(p))
    monkeypatch.setenv("ACCL_SENTINEL", str(p))
    monkeypatch.setenv("ACCL_SENTINEL_INTERVAL_MS", "60000")
    try:
        sen = obs_sentinel.ensure_sentinel_from_env()
        assert sen is not None
        assert obs_sentinel.ensure_sentinel_from_env() is sen  # idempotent
    finally:
        obs_sentinel.stop_sentinel()


# ---------------------------------------------------------------------------
# perf_doctor CLI round-trip (+ --ci schema gate)
# ---------------------------------------------------------------------------
def test_perf_doctor_cli_roundtrip(tmp_path):
    import time as _time

    from accl_tpu.backends.emu import EmuWorld
    from accl_tpu.observability import flight

    reg = obs_metrics.default_registry()
    with EmuWorld(2) as world:
        def body(accl, rank):
            send = accl.create_buffer_like(
                np.arange(64, dtype=np.float32) + rank)
            recv = accl.create_buffer(64, np.float32)
            for _ in range(6):
                if rank == 1:
                    _time.sleep(0.002)
                accl.allreduce(send, recv, 64, ReduceFunction.SUM,
                               from_fpga=True, to_fpga=True)

        world.run(body)
        fdump = tmp_path / "flight.json"
        # THIS world's recorders only: dump_all() sweeps every live
        # recorder in the process, and closed worlds from earlier tests
        # survive until a gc cycle collects their reference cycles
        doc = flight.merge_flight_dumps(
            [a.flight_recorder.dump() for a in world.accls])
        fdump.write_text(json.dumps(doc))
    mdump = tmp_path / "metrics.json"
    mdump.write_text(json.dumps(reg.snapshot()))
    out = tmp_path / "report.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/perf_doctor.py"),
         "--ci", "--metrics", str(mdump), "--flight", str(fdump),
         "--baseline",
         os.path.join(REPO, "bench/results/callrate_r12_plan_on.json"),
         "--out", str(out)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(out.read_text())
    assert report["schema_errors"] == []
    assert "attribution" in report and "sentinel" in report
    assert "engine_telemetry" in report
    d = next(iter(report["attribution"]["collectives"].values()))
    assert d["dominant_straggler"]["rank"] == 1
    assert "straggler" in proc.stdout


def test_perf_doctor_ci_fails_on_malformed_snapshot(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"not": "a snapshot"}))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/perf_doctor.py"),
         "--ci", "--metrics", str(bad)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 2
    assert "SCHEMA ERROR" in proc.stderr


# ---------------------------------------------------------------------------
# satellite: doctor --live renders unknown engine families gracefully
# ---------------------------------------------------------------------------
def test_doctor_live_renders_unknown_engine_family():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import accl_doctor
    finally:
        sys.path.pop(0)
    reg = obs_metrics.MetricsRegistry()
    reg.inc("engine/tx_msgs", 5)
    reg.set_gauge("engine/rx_occupancy_hwm", 2)
    metrics_text = reg.to_openmetrics() + (
        "# TYPE accl_engine_zz_future_field gauge\n"
        "accl_engine_zz_future_field 42\n# EOF\n")
    scraped = {
        "healthz": {"health": "ok", "accl_health": 0,
                    "watchdog_fires": 0, "watchdog_checks": 1},
        "metrics": metrics_text,
        "flight": {"generated_ns": 0, "nranks": 0, "ranks": [],
                   "analysis": {"desyncs": [], "hangs": [],
                                "stragglers": [], "truncated_comms": [],
                                "torn_dumps": [], "ok": True}},
    }
    out = io.StringIO()
    findings = accl_doctor.report_live(scraped, out)
    text = out.getvalue()
    assert not findings
    assert "engine telemetry" in text
    assert "accl_engine_tx_msgs_total 5" in text
    assert "unrecognized (newer world?)" in text
    # the known family is NOT tagged unrecognized
    known_line = [ln for ln in text.splitlines()
                  if "accl_engine_rx_occupancy_hwm" in ln][0]
    assert "unrecognized" not in known_line
